"""Fault-tolerance tests: the retry helper, the fault-injection harness,
and the session-level recovery contract (docs/robustness.md).

The contract under test: crash at any step, resume from the last good step
checkpoint, and the final weights are BITWISE identical to an uninterrupted
run — because chunked step dispatch applies the exact same per-batch updates
in the exact same order as whole-epoch dispatch, and a v2 snapshot captures
the full resumable state (params, optimizer state, step cursor).
"""

import numpy as np
import pytest

from shallowspeed_tpu import faults, retry
from shallowspeed_tpu.api import TrainingSession
from shallowspeed_tpu.checkpoint import (
    CheckpointError,
    list_step_checkpoints,
    step_checkpoint_path,
)
from shallowspeed_tpu.observability import JsonlMetrics, read_jsonl
from shallowspeed_tpu.observability.divergence import assert_models_equal
from shallowspeed_tpu.observability.health import HealthError

SIZES = (24, 20, 18, 16, 14, 12, 11, 10)
N, GBS = 256, 64  # 4 batches/epoch


@pytest.fixture()
def data_dir(tmp_path_factory):
    tmp_path = tmp_path_factory.mktemp("recovery_data")
    rng = np.random.RandomState(0)
    for suffix, n in (("train", N), ("val", 96)):
        x = rng.randn(n, SIZES[0]).astype(np.float32)
        y = np.eye(SIZES[-1], dtype=np.float32)[rng.randint(0, SIZES[-1], n)]
        np.save(tmp_path / f"x_{suffix}.npy", x)
        np.save(tmp_path / f"y_{suffix}.npy", y)
    return tmp_path


def _session(data_dir, **kw):
    kw.setdefault("sizes", SIZES)
    kw.setdefault("global_batch_size", GBS)
    kw.setdefault("lr", 0.01)
    return TrainingSession(data_dir=data_dir, **kw)


# ---------------------------------------------------------------------------
# retry: the one backoff policy
# ---------------------------------------------------------------------------


def test_backoff_schedule_grows_capped_and_deterministic():
    plain = retry.backoff_delays(8, base=1.0, factor=2.0, max_delay=60.0, jitter=0)
    assert plain == [1, 2, 4, 8, 16, 32, 60, 60]  # exponential, then the cap
    a = retry.backoff_delays(8, base=1.0, max_delay=60.0, jitter=0.2, seed=7)
    b = retry.backoff_delays(8, base=1.0, max_delay=60.0, jitter=0.2, seed=7)
    assert a == b  # deterministic per (seed, attempt)
    assert a != retry.backoff_delays(8, base=1.0, max_delay=60.0, jitter=0.2, seed=8)
    for got, want in zip(a, plain):
        assert want * 0.8 <= got <= want * 1.2  # jitter stays in its band
    with pytest.raises(ValueError):
        retry.backoff_delay(-1)
    with pytest.raises(ValueError):
        retry.backoff_delay(0, factor=0.5)
    with pytest.raises(ValueError):
        retry.backoff_delay(0, jitter=1.5)


def test_retry_call_bounded_budget_and_exception_filter():
    calls, sleeps, seen = [], [], []

    def flaky():
        calls.append(1)
        raise OSError("transient")

    with pytest.raises(OSError):
        retry.retry_call(
            flaky, attempts=4, jitter=0, base=1.0,
            on_retry=lambda i, e, d: seen.append((i, d)),
            sleep=sleeps.append,
        )
    assert len(calls) == 4  # the TOTAL budget — strictly bounded
    assert sleeps == [1.0, 2.0, 4.0]  # attempts - 1 sleeps
    assert [i for i, _ in seen] == [0, 1, 2]

    # non-retried exception types propagate on the first attempt
    calls.clear()

    def fatal():
        calls.append(1)
        raise RuntimeError("logic bug")

    with pytest.raises(RuntimeError):
        retry.retry_call(fatal, attempts=4, sleep=lambda s: None)
    assert len(calls) == 1

    # success after failures returns the value
    state = iter([OSError("x"), OSError("y"), "ok"])

    def eventually():
        v = next(state)
        if isinstance(v, Exception):
            raise v
        return v

    assert retry.retry_call(eventually, attempts=3, sleep=lambda s: None) == "ok"
    with pytest.raises(ValueError):
        retry.retry_call(lambda: None, attempts=0)


def test_retry_cli_prints_schedule(capsys):
    assert retry.main(["--attempts", "4", "--base", "2", "--jitter", "0"]) == 0
    out = capsys.readouterr().out.splitlines()
    assert [int(l) for l in out] == [2, 4, 8, 16]
    assert retry.main(["--attempts", "2", "--jitter", "2.0"]) == 1  # bad args


# ---------------------------------------------------------------------------
# faults: the injection harness
# ---------------------------------------------------------------------------


def test_fault_spec_grammar_round_trip():
    plan = faults.FaultPlan.parse("die@step=7:mode=sigkill, nan@step=3")
    assert [repr(f) for f in plan.faults] == [
        "die@step=7:mode=sigkill", "nan@step=3"
    ]
    assert bool(plan) and not bool(faults.FaultPlan.parse(""))
    assert not faults.FaultPlan.parse(None)
    for bad in (
        "die",               # no step
        "die@mode=exc",      # still no step
        "explode@step=3",    # unknown kind
        "die@step=-1",       # negative step
        "die@step=3:mode=soft",   # unknown die mode
        "nan@step=3:mode=exc",    # nan takes no mode
        "die@step=3:color=red",   # unknown field
    ):
        with pytest.raises(ValueError, match="fault"):
            faults.FaultPlan.parse(bad)


def test_fault_plan_env_and_boundaries(monkeypatch):
    monkeypatch.setenv(faults.ENV_VAR, "die@step=11")
    plan = faults.from_env()
    assert plan.faults[0].step == 11 and plan.faults[0].mode == "exc"
    monkeypatch.delenv(faults.ENV_VAR)
    assert not faults.from_env()
    # make_plan normalizes the API surface
    assert faults.make_plan(plan) is plan
    assert faults.make_plan("nan@step=2").faults[0].kind == "nan"

    # first_in: earliest un-fired fault inside [lo, hi)
    plan = faults.FaultPlan.parse("die@step=9,nan@step=5")
    assert plan.first_in(0, 4) is None
    assert plan.first_in(4, 12).step == 5
    plan.faults[1].fired = True
    assert plan.first_in(4, 12).step == 9
    assert plan.first_in(10, 12) is None

    # the soft kill raises (and marks itself fired)
    f = faults.Fault("die", 3)
    with pytest.raises(faults.InjectedFault, match="die@step=3"):
        faults.FaultPlan([f]).fire_die(f)
    assert f.fired


def test_poison_nan_touches_exactly_one_leaf():
    import jax.numpy as jnp

    tree = [[{"W": jnp.ones((3, 3)), "b": jnp.ones((1, 3))}]]
    out = faults.poison_nan(tree)
    w = np.asarray(out[0][0]["W"])
    assert np.isnan(w).sum() == 1  # one poisoned element
    assert not np.isnan(np.asarray(out[0][0]["b"])).any()
    with pytest.raises(ValueError, match="no array leaf"):
        faults.poison_nan([])


def test_corrupt_checkpoint_bytes_deterministic(tmp_path):
    p = tmp_path / "f.bin"
    p.write_bytes(bytes(range(256)) * 8)
    before = p.read_bytes()
    offs = faults.corrupt_checkpoint_bytes(p, nbytes=4, seed=5)
    after = p.read_bytes()
    assert [i for i in range(len(before)) if before[i] != after[i]] == offs
    assert all(o >= 64 for o in offs)
    q = tmp_path / "q.bin"
    q.write_bytes(bytes(range(256)) * 8)
    assert faults.corrupt_checkpoint_bytes(q, nbytes=4, seed=5) == offs
    empty = tmp_path / "e.bin"
    empty.touch()
    with pytest.raises(ValueError, match="empty"):
        faults.corrupt_checkpoint_bytes(empty)


# ---------------------------------------------------------------------------
# the session-level recovery contract
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "kw",
    [
        dict(),
        # the dp2pp2 leg rides the slow tier (1-core wall budget);
        # make recovery-smoke drives chunked dispatch on a mesh
        pytest.param(
            dict(dp=2, pp=2, schedule="gpipe"), marks=pytest.mark.slow
        ),
    ],
    ids=["seq", "dp2pp2"],
)
def test_train_steps_chunked_is_bitwise_identical_to_epochs(data_dir, kw):
    """The preemption-safe unit's correctness: dispatching an epoch in
    uneven step chunks applies the same updates in the same order as
    whole-epoch dispatch — identical final hash AND identical recombined
    epoch mean loss."""
    whole = _session(data_dir, **kw)
    whole_losses = [whole.train_epoch() for _ in range(2)]

    chunked = _session(data_dir, **kw)
    losses, sizes = [], [1, 3, 2, 1, 1]  # uneven on purpose; 4 steps/epoch
    while chunked.epoch < 2:
        n = sizes[(chunked.global_step + chunked.epoch) % len(sizes)]
        _, epoch_loss = chunked.train_steps(n)
        if epoch_loss is not None:
            losses.append(epoch_loss)
    # digest-backed comparator: a mismatch names the first divergent
    # (layer, tensor) with ULP evidence instead of a bare hash diff
    assert_models_equal(chunked.params(), whole.params(), "chunked", "whole")
    np.testing.assert_allclose(losses, whole_losses, rtol=1e-6)

    # a mid-flight epoch refuses the whole-epoch/fused entry points
    chunked.train_steps(1)
    with pytest.raises(ValueError, match="mid-flight"):
        chunked.train_epoch()
    with pytest.raises(ValueError, match="mid-flight"):
        chunked.train_run(1)
    with pytest.raises(ValueError):
        chunked.train_steps(0)


@pytest.mark.slow  # 1-core wall budget; make recovery-smoke drives this end to end
def test_kill_and_resume_bitwise_equals_uninterrupted(data_dir, tmp_path):
    """The headline contract, session level: inject a die at step 5 of 8,
    resume from the surviving snapshots, and the final hash is bitwise
    identical to the uninterrupted twin — with the v4 checkpoint/recovery
    records telling the story. Sequential + momentum keeps this about the
    record stream and the cursor; the mesh layouts (and their optimizer
    states) are the fuzz lattice's kill-and-resume dimension."""
    twin = _session(data_dir, optimizer="momentum")
    for _ in range(2):
        twin.train_epoch()

    ck = tmp_path / "ck"
    jsonl = tmp_path / "killed.jsonl"
    with JsonlMetrics(jsonl) as m:
        run = _session(
            data_dir, optimizer="momentum",
            checkpoint_dir=ck, faults="die@step=5", metrics=m,
        )
        assert run.faults_active
        with pytest.raises(faults.InjectedFault):
            while run.epoch < 2:
                run.train_steps(2)
                run.save_step_checkpoint()
    # the chunk containing step 5 was truncated at the fault boundary, so
    # the fault fired BEFORE step 5 trained: snapshots at 2, 4 and the
    # truncated-chunk boundary 5 (a MID-epoch cursor: epoch 1, step 1)
    steps = [gs for gs, _ in list_step_checkpoints(ck)]
    assert steps == [2, 4, 5]
    recs = read_jsonl(jsonl)
    cks = [r for r in recs if r["kind"] == "checkpoint"]
    assert [r["global_step"] for r in cks] == [2, 4, 5]
    assert all(r["bytes"] > 0 and r["name"] == "step" for r in cks)

    jsonl2 = tmp_path / "resumed.jsonl"
    with JsonlMetrics(jsonl2) as m:
        res = _session(
            data_dir, optimizer="momentum",
            checkpoint_dir=ck, resume="auto", metrics=m,
        )
        assert res.resumed_from == str(step_checkpoint_path(ck, 5))
        assert res.epoch == 1 and res.step_in_epoch == 1  # 4 steps/epoch
        while res.epoch < 2:
            res.train_steps(2)
    assert_models_equal(res.params(), twin.params(), "resumed", "twin")
    rec = [r for r in read_jsonl(jsonl2) if r["kind"] == "recovery"]
    assert len(rec) == 1 and rec[0]["name"] == "resumed"
    assert rec[0]["global_step"] == 5 and rec[0]["skipped"] == []
    # the completing epoch's record covers only the tail THIS process
    # trained (steps 1-3 of epoch 1): stamped steps_counted, loss is the
    # tail mean, samples/s claims 3 batches — not the full epoch's 4
    eps = [
        r for r in read_jsonl(jsonl2)
        if r["kind"] == "event" and r["name"] == "epoch"
    ]
    assert [r["epoch"] for r in eps] == [1]
    assert eps[0]["steps_counted"] == 3


@pytest.mark.parametrize(
    "killed_kw,resumed_kw",
    [
        # zero2-dp2 -> zero1-dp4: the grad/state shards re-deal over a
        # WIDER dp axis at a LOWER stage
        pytest.param(
            dict(dp=2, pp=2, schedule="gpipe", zero=2),
            dict(dp=4, pp=2, schedule="gpipe", zero=1),
            id="zero2dp2-to-zero1dp4", marks=pytest.mark.slow,
        ),
        # zero3-dp2 -> sequential: params sharded at rest rehydrate into
        # the no-mesh layout (slow tier: the 1-core tier-1 wall budget
        # is tight; test_zero23's z3-save -> plain-load leg keeps the
        # logical-snapshot contract in tier-1)
        pytest.param(
            dict(dp=2, pp=2, schedule="gpipe", zero=3),
            dict(),
            id="zero3dp2-to-seq", marks=pytest.mark.slow,
        ),
    ],
)
def test_kill_resume_elastic_resharding(data_dir, tmp_path, killed_kw,
                                        resumed_kw):
    """ZeRO snapshots are LOGICAL (the zero1 checkpoint substrate keeps
    nothing layout-shaped on disk), so a run killed under one (stage, dp)
    point resumes under ANOTHER — elastic re-sharding. Bitwise at
    restore: the re-sharded resume and a same-layout resume of the same
    snapshot agree on params (hash) and on every optimizer-state leaf,
    and the re-sharded session trains on from the cursor."""
    ck = tmp_path / "ck"
    run = _session(
        data_dir, optimizer="momentum", checkpoint_dir=ck,
        faults="die@step=5", **killed_kw,
    )
    with pytest.raises(faults.InjectedFault):
        while run.epoch < 2:
            run.train_steps(2)
            run.save_step_checkpoint()
    assert [gs for gs, _ in list_step_checkpoints(ck)][-1] == 5

    res = _session(
        data_dir, optimizer="momentum", checkpoint_dir=ck, resume="auto",
        **resumed_kw,
    )
    same = _session(
        data_dir, optimizer="momentum", checkpoint_dir=ck, resume="auto",
        **killed_kw,
    )
    assert res.resumed_from == same.resumed_from
    assert res.model_hash() == same.model_hash()
    a, b = res.opt_state_logical(), same.opt_state_logical()
    assert sorted(a["parts"]) == sorted(b["parts"])
    import jax

    la, lb = jax.tree.leaves(a["parts"]), jax.tree.leaves(b["parts"])
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    while res.epoch < 2:
        res.train_steps(2)
    assert res.epoch == 2 and np.isfinite(res.accuracy())


def test_resume_auto_skips_corrupt_newest(data_dir, tmp_path):
    """Acceptance criterion end-to-end: corrupt the NEWEST snapshot with
    the fault harness; resume auto detects it via the checksum, falls back
    to the previous good one, and records the skip with its cause."""
    ck = tmp_path / "ck"
    run = _session(data_dir, checkpoint_dir=ck)
    run.train_steps(2)
    run.save_step_checkpoint()
    run.train_steps(2)
    run.save_step_checkpoint()
    faults.corrupt_checkpoint_bytes(step_checkpoint_path(ck, 4), seed=2)

    res = _session(data_dir, checkpoint_dir=ck, resume="auto")
    assert res.resumed_from == str(step_checkpoint_path(ck, 2))
    assert res.global_step == 2
    assert res._recovery["skipped"] and "step-00000004" in (
        res._recovery["skipped"][0]["path"]
    )

    # when EVERY snapshot is corrupt, resume refuses loudly (train.py maps
    # this to the exit-4 "unrecoverable checkpoint state" contract)
    faults.corrupt_checkpoint_bytes(step_checkpoint_path(ck, 2), seed=2)
    with pytest.raises(CheckpointError, match="no snapshot verifies"):
        _session(data_dir, checkpoint_dir=ck, resume="auto")


def test_resume_auto_fresh_start_and_validation(data_dir, tmp_path):
    res = _session(data_dir, checkpoint_dir=tmp_path / "empty", resume="auto")
    assert res.resumed_from is None and res.epoch == 0
    assert res._recovery["verdict"] == "fresh_start"
    with pytest.raises(ValueError, match="checkpoint_dir"):
        _session(data_dir, resume="auto")
    with pytest.raises(ValueError, match="checkpoint_keep"):
        _session(data_dir, checkpoint_dir=tmp_path, checkpoint_keep=0)
    with pytest.raises(ValueError, match="no checkpoint_dir"):
        _session(data_dir).save_step_checkpoint()


def test_rotation_applied_by_session(data_dir, tmp_path):
    ck = tmp_path / "ck"
    run = _session(data_dir, checkpoint_dir=ck, checkpoint_keep=2)
    for _ in range(4):
        run.train_steps(1)
        run.save_step_checkpoint()
    assert [gs for gs, _ in list_step_checkpoints(ck)] == [3, 4]


def test_halt_flushes_resumable_snapshot(data_dir, tmp_path):
    """The health-halt contract: a NaN injected at step 3 halts the run,
    the halt path flushes a snapshot of the blown-up state (all_finite:
    false), and resume discovery SKIPS it — landing on the last healthy
    snapshot so the run is resumable from before the finding."""
    twin = _session(data_dir)
    for _ in range(2):
        twin.train_epoch()

    ck = tmp_path / "ck"
    run = _session(
        data_dir, checkpoint_dir=ck, health="halt", faults="nan@step=3",
    )
    with pytest.raises(HealthError):
        while run.epoch < 2:
            run.train_steps(2)
            run.save_step_checkpoint()
    # healthy snapshots at 2 and (the truncated chunk boundary) 3, plus
    # the halt flush at 4 — taken AFTER the poisoned step, so non-finite
    steps = [gs for gs, _ in list_step_checkpoints(ck)]
    assert steps == [2, 3, 4]

    res = _session(data_dir, checkpoint_dir=ck, resume="auto")
    assert res.resumed_from == str(step_checkpoint_path(ck, 3))
    skipped = res._recovery["skipped"]
    assert skipped and "non-finite" in skipped[0]["cause"]
    # the resumed run replays step 3 WITHOUT the poison and finishes on
    # the exact bits of the uninterrupted twin
    while res.epoch < 2:
        res.train_steps(2)
    assert_models_equal(res.params(), twin.params(), "resumed", "twin")


def test_multihost_explicit_join_retries_the_coordinator_race(monkeypatch):
    """Distributed init with an EXPLICIT coordinator retries through the
    shared backoff — a worker dialing a not-yet-listening coordinator waits
    out the race instead of crashing the fleet. The fake is STATEFUL the
    way jax really is (a failed connect leaves the client assigned, and a
    second initialize refuses with 'should only be called once'), so this
    pins the between-attempts state teardown, not just the retry loop.
    The no-coordinator path keeps its single-attempt fallback contract."""
    import jax

    from shallowspeed_tpu.parallel import multihost

    monkeypatch.setattr(multihost, "_distributed_is_initialized", lambda: False)
    monkeypatch.setattr(retry.time, "sleep", lambda s: None)
    calls, state = [], {"client": None}

    def racing_coordinator(**kw):
        if state["client"] is not None:
            raise RuntimeError(
                "distributed.initialize should only be called once"
            )
        state["client"] = "half-up"  # assigned BEFORE the connect, like jax
        calls.append(kw)
        if len(calls) < 3:
            raise RuntimeError("connection refused: coordinator not up yet")
        state["client"] = "connected"

    def fake_shutdown():
        if state["client"] == "half-up":
            state["client"] = None
            raise RuntimeError("shutdown of a never-connected client")
        state["client"] = None

    monkeypatch.setattr(jax.distributed, "initialize", racing_coordinator)
    monkeypatch.setattr(jax.distributed, "shutdown", fake_shutdown)
    multihost.initialize("10.0.0.1:1234", num_processes=2, process_id=1)
    assert len(calls) == 3  # two refused dials, then the join
    assert state["client"] == "connected"
    assert calls[0]["coordinator_address"] == "10.0.0.1:1234"

    # budget exhausted -> the ORIGINAL error propagates (never the
    # 'called once' refusal); without a coordinator there is ONE attempt
    calls.clear()
    state["client"] = None

    def always_down(**kw):
        calls.append(kw)
        raise RuntimeError("still down")

    monkeypatch.setattr(jax.distributed, "initialize", always_down)
    with pytest.raises(RuntimeError, match="still down"):
        multihost.initialize("10.0.0.1:1234", num_processes=2, process_id=1)
    assert len(calls) == 4
    calls.clear()
    multihost.initialize()  # no coordinator: single-process fallback
    assert len(calls) == 1


def test_composed_faults_in_one_chunk_all_fire(data_dir):
    """The composed-spec contract the faults docstring advertises: a nan
    and a die inside the SAME dispatch chunk both land on their exact
    steps — after the nan fires at the chunk head, the chunk is truncated
    again at the die so it cannot be dispatched past."""
    run = _session(data_dir, faults="nan@step=3,die@step=5")
    with pytest.raises(faults.InjectedFault, match="die@step=5"):
        while run.epoch < 2:
            run.train_steps(8)  # whole-epoch chunks: both faults mid-chunk
    assert all(f.fired for f in run._faults.faults)
    assert run.global_step == 5  # died BEFORE step 5 trained


def test_composed_faults_at_the_same_step_all_fire(data_dir):
    """Two faults on the SAME step: after the nan fires at the chunk head,
    the die scheduled at that very step must still fire before the dispatch
    (a single-shot chunk-head check would leave it pending forever — every
    later search window starts past its step — and the harness would
    mistake the uninjected run for a survived crash)."""
    run = _session(data_dir, faults="nan@step=3,die@step=3")
    with pytest.raises(faults.InjectedFault, match="die@step=3"):
        while run.epoch < 2:
            run.train_steps(8)
    assert all(f.fired for f in run._faults.faults)
    assert run.global_step == 3  # died BEFORE step 3 trained


def test_halt_flush_never_rotates_away_the_good_snapshot(data_dir, tmp_path):
    """keep=1 + a halt flush: the non-finite halt snapshot must not rotate
    the single retained GOOD snapshot away — otherwise the flush would
    make the blow-up UNrecoverable, the opposite of its purpose."""
    ck = tmp_path / "ck"
    run = _session(
        data_dir, checkpoint_dir=ck, checkpoint_keep=1,
        health="halt", faults="nan@step=3",
    )
    with pytest.raises(HealthError):
        while run.epoch < 2:
            run.train_steps(2)
            run.save_step_checkpoint()
    # rotation kept only step-3 of the grid snapshots; the halt flush (4)
    # rode along WITHOUT rotating, so the good snapshot survived
    assert [gs for gs, _ in list_step_checkpoints(ck)] == [3, 4]
    res = _session(data_dir, checkpoint_dir=ck, resume="auto")
    assert res.resumed_from == str(step_checkpoint_path(ck, 3))


def test_grid_saves_never_rotate_away_the_last_finite_snapshot(
    data_dir, tmp_path
):
    """Fix for the silent-NaN hazard: WITHOUT --health halt, a blown-up
    run keeps writing grid snapshots (all_finite: false) — unconditional
    rotation would delete the last healthy snapshot within keep intervals
    and make resume auto permanently unrecoverable. Rotation only runs
    after FINITE saves, so the healthy snapshot survives the blow-up."""
    ck = tmp_path / "ck"
    run = _session(
        data_dir, checkpoint_dir=ck, checkpoint_keep=1, faults="nan@step=3"
    )
    while run.epoch < 2:
        run.train_steps(1)
        run.save_step_checkpoint()
    # steps 0-2 were healthy (keep=1 rotated normally, down to step-3);
    # step 3 trained on poisoned params, so snapshots 4..8 are non-finite
    # and accumulate UNrotated beside the surviving healthy one
    assert [gs for gs, _ in list_step_checkpoints(ck)] == [3, 4, 5, 6, 7, 8]
    res = _session(data_dir, checkpoint_dir=ck, resume="auto")
    assert res.resumed_from == str(step_checkpoint_path(ck, 3))
    assert len(res._recovery["skipped"]) == 5  # every non-finite snapshot


def test_pending_faults_refuse_stepless_entry_points(data_dir):
    """A plan that cannot fire must REFUSE, not silently skip: injections
    land on step boundaries, so a whole-epoch or fused-run dispatch with
    pending injections would sail past them — and a recovery driver would
    mistake the uninjected run for a survived crash."""
    run = _session(data_dir, faults="die@step=6")
    with pytest.raises(ValueError, match="train_steps"):
        run.train_epoch()
    with pytest.raises(ValueError, match="train_steps"):
        run.train_run(1)
    assert run.global_step == 0  # nothing trained

    # once every injection has FIRED, the stepless entry points are legal
    # again (nan@0 fires at the first chunk head; no health monitor, so
    # the poisoned run keeps training)
    run2 = _session(data_dir, faults="nan@step=0")
    run2.train_steps(4)  # fires the poison, finishes epoch 0
    assert not run2._faults.pending
    run2.train_epoch()


def test_sigkill_mode_parses_but_is_not_fired_in_process():
    """mode=sigkill is the subprocess shape (make recovery-smoke and the
    CLI test kill real train.py runs with it); in-process tests only check
    it parses and targets the right signal surface."""
    plan = faults.FaultPlan.parse("die@step=4:mode=sigkill")
    assert plan.faults[0].mode == "sigkill"


# ---------------------------------------------------------------------------
# the async checkpoint writer, session level (PR 12)
# ---------------------------------------------------------------------------


def test_save_fault_grammar_round_trip():
    """The @save= anchor joins the grammar: die/slow/corrupt parse (and
    refuse what they must), due_at_save fires each exactly once with the
    <= catch-up anchor, and save faults never count as pending STEP
    injections (a training entry point must not refuse a run over
    them)."""
    plan = faults.FaultPlan.parse(
        "die@save=2:mode=sigkill, slow@save=1:ms=50, corrupt@save=3"
    )
    assert [repr(f) for f in plan.faults] == [
        "die@save=2:mode=sigkill", "slow@save=1:ms=50", "corrupt@save=3"
    ]
    assert plan.pending == []  # step-pending stays empty: entry points run
    assert [f.kind for f in plan.pending_save] == ["die", "slow", "corrupt"]
    assert [f.kind for f in plan.due_at_save(0)] == []
    assert [f.kind for f in plan.due_at_save(1)] == ["slow"]
    plan.faults[1].fired = True
    # catch-up: an anchor whose exact save never ran fires on the next
    assert [f.kind for f in plan.due_at_save(5)] == ["die", "corrupt"]
    for bad in (
        "nan@save=1",            # nan is not a writer fault
        "error@save=1",          # error is dispatch-only
        "slow@save=1",           # slow needs ms
        "corrupt@save=1:ms=5",   # corrupt takes no ms
        "corrupt@dispatch=1",    # corrupt is save-only
        "die@save=1:step=2",     # exactly one anchor
    ):
        with pytest.raises(ValueError, match="fault"):
            faults.FaultPlan.parse(bad)


def test_corrupt_buffer_breaks_checksum_deterministically():
    from shallowspeed_tpu.checkpoint import content_checksum

    arrays = {"w0": np.arange(64, dtype=np.float32).reshape(8, 8)}
    stamped = content_checksum(arrays)
    offs = faults.corrupt_buffer(arrays, seed=4)
    assert offs and content_checksum(arrays) != stamped
    arrays2 = {"w0": np.arange(64, dtype=np.float32).reshape(8, 8)}
    assert faults.corrupt_buffer(arrays2, seed=4) == offs
    with pytest.raises(ValueError, match="corrupt"):
        faults.corrupt_buffer({})


@pytest.mark.slow  # 1-core wall budget; make recovery-smoke --async leg drives this end to end
def test_async_kill_and_resume_bitwise_equals_uninterrupted(
    data_dir, tmp_path
):
    """The headline contract survives the move off the step path: a run
    checkpointing ASYNCHRONOUSLY dies mid-run (die@step, with saves still
    in flight through the bounded writer), resume auto discovers only
    fully-verifying snapshots, and the finish is bitwise the twin's. The
    v8 checkpoint records carry async/queue-depth/off-path evidence."""
    twin = _session(data_dir, optimizer="momentum")
    for _ in range(2):
        twin.train_epoch()

    ck = tmp_path / "ck"
    jsonl = tmp_path / "killed.jsonl"
    with JsonlMetrics(jsonl) as m:
        run = _session(
            data_dir, optimizer="momentum", checkpoint_dir=ck,
            async_checkpoint=True, faults="die@step=5", metrics=m,
        )
        with pytest.raises(faults.InjectedFault):
            while run.epoch < 2:
                run.train_steps(2)
                run.save_step_checkpoint()
        run.close()  # the die left the writer alive: drain it
    recs = [r for r in read_jsonl(jsonl) if r["kind"] == "checkpoint"]
    assert recs and all(r["async"] is True for r in recs)
    assert all(
        r["verify_s"] >= 0 and r["write_s"] >= 0 and r["queue_depth"] >= 0
        for r in recs
    )
    # every discoverable snapshot fully verifies (no torn file ever
    # rename-visible), and resume lands on the newest one
    res = _session(
        data_dir, optimizer="momentum", checkpoint_dir=ck, resume="auto",
    )
    assert res._recovery["skipped"] == []
    assert res.global_step == 5
    while res.epoch < 2:
        res.train_steps(2)
    assert_models_equal(res.params(), twin.params(), "resumed", "twin")


def test_async_halt_flush_stays_synchronous_and_drains_first(
    data_dir, tmp_path
):
    """The PR 6 health-halt flush contract under async checkpointing: the
    halt snapshot is written SYNCHRONOUSLY (the process is unwinding — a
    snapshot parked in a daemon queue would die with it) after draining
    whatever the writer still holds, so discovery sees the full history:
    healthy grid saves, then the non-finite halt snapshot it skips."""
    jsonl = tmp_path / "halt.jsonl"
    ck = tmp_path / "ck"
    with JsonlMetrics(jsonl) as m:
        run = _session(
            data_dir, checkpoint_dir=ck, async_checkpoint=True,
            health="halt", faults="nan@step=3", metrics=m,
        )
        with pytest.raises(HealthError):
            while run.epoch < 2:
                run.train_steps(2)
                run.save_step_checkpoint()
    steps = [gs for gs, _ in list_step_checkpoints(ck)]
    assert steps == [2, 3, 4]
    recs = [r for r in read_jsonl(jsonl) if r["kind"] == "checkpoint"]
    by_reason = {r["name"]: r for r in recs}
    assert by_reason["halt"]["async"] is False  # the flush stayed sync
    assert by_reason["step"]["async"] is True
    res = _session(data_dir, checkpoint_dir=ck, resume="auto")
    assert res.resumed_from == str(step_checkpoint_path(ck, 3))


def test_writer_failure_surfaces_on_the_training_thread(data_dir, tmp_path):
    """A writer-side failure (here: an injected in-window die) must
    re-raise on the thread that owns the training loop — at the next
    save or drain — never vanish into a daemon-thread traceback."""
    ck = tmp_path / "ck"
    run = _session(
        data_dir, checkpoint_dir=ck, async_checkpoint=True,
        faults="die@save=1",
    )
    run.train_steps(1)
    run.save_step_checkpoint()  # save 0: fine
    run.train_steps(1)
    run.save_step_checkpoint()  # save 1: dies inside the write window
    with pytest.raises(faults.InjectedFault, match="die@save=1"):
        run.drain_checkpoints()
    # the failed save never became visible; the good one verifies
    assert [gs for gs, _ in list_step_checkpoints(ck)] == [1]


def test_resume_auto_reads_the_snapshot_exactly_once(
    data_dir, tmp_path, monkeypatch
):
    """The folded double read: discovery verifies (read+checksum) the
    chosen snapshot and resume assembles from THOSE arrays — one read
    total of the restored file, where PR 6 documented a deliberate
    second verify-read."""
    from shallowspeed_tpu import checkpoint as C

    ck = tmp_path / "ck"
    run = _session(data_dir, checkpoint_dir=ck)
    run.train_steps(2)
    run.save_step_checkpoint()
    reads = []
    real = C._read_arrays

    def counting(path):
        reads.append(str(path))
        return real(path)

    monkeypatch.setattr(C, "_read_arrays", counting)
    res = _session(data_dir, checkpoint_dir=ck, resume="auto")
    assert res.global_step == 2
    assert reads == [str(step_checkpoint_path(ck, 2))]  # exactly one read


def test_rotation_trusts_the_snapshot_it_just_wrote(
    data_dir, tmp_path, monkeypatch
):
    """Review fix: rotation inside run_save_stages must trust the snapshot
    written moments earlier in the same stage pipeline (its checksum was
    computed in-process) — otherwise EVERY rotating save re-reads and
    re-checksums its own file, the exact redundant verify-read the
    trusted ranking exists to skip."""
    from shallowspeed_tpu import checkpoint as C

    ck = tmp_path / "ck"
    run = _session(data_dir, checkpoint_dir=ck, checkpoint_keep=2)
    for _ in range(3):
        run.train_steps(1)
        run.save_step_checkpoint()
    verified = []
    real = C.verify_checkpoint

    def counting(path, **kw):
        verified.append(str(path))
        return real(path, **kw)

    monkeypatch.setattr(C, "verify_checkpoint", counting)
    run.train_steps(1)
    run.save_step_checkpoint()  # rotation fires (4 snapshots > keep=2)
    # this session wrote every candidate finite: rotation re-verifies NONE
    assert verified == []
    assert [gs for gs, _ in list_step_checkpoints(ck)] == [3, 4]


def test_corrupt_save_injection_never_rotates_away_the_good_snapshot(
    data_dir, tmp_path
):
    """Review fix: a corrupt@save-injected snapshot is finite in its
    metadata but can never verify — it must count as UNUSABLE everywhere
    the finite flag gates: no rotation off it, never added to the
    trusted set. With keep=1 the corrupted save must not delete the one
    good snapshot the fallback path exists to land on."""
    ck = tmp_path / "ck"
    run = _session(
        data_dir, checkpoint_dir=ck, checkpoint_keep=1,
        faults="corrupt@save=1",
    )
    run.train_steps(1)
    run.save_step_checkpoint()  # save 0: good (rotation may run)
    run.train_steps(1)
    run.save_step_checkpoint()  # save 1: corrupted in flight
    run.drain_checkpoints()
    # both files visible; the corrupt one neither rotated the good one
    # away nor entered the trusted set
    steps = [gs for gs, _ in list_step_checkpoints(ck)]
    assert steps == [1, 2]
    assert str(step_checkpoint_path(ck, 2)) not in run._trusted_snapshots
    res = _session(data_dir, checkpoint_dir=ck, resume="auto")
    assert res.resumed_from == str(step_checkpoint_path(ck, 1))
    assert res._recovery["skipped"] and "checksum" in (
        res._recovery["skipped"][0]["cause"]
    )
