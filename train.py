"""Training driver CLI — the TPU-native counterpart of the reference train.py.

Same surface: ``python train.py [--dp N] [--pp M] [--schedule naive|gpipe|pipedream]``
(reference train.py:62-74), same flagship model (sizes [784,128,127,126,125,
124,123,10], train.py:98), same constants (EPOCHS=20, GLOBAL_BATCH_SIZE=128,
N_MUBATCHES=4, lr=0.006), same epoch structure (per-epoch validation accuracy,
final replica-sync check).

Differences by design:
- no mpirun: ONE process drives the whole (dp, pp) device mesh; the two MPI
  communicators become mesh axes (parallel/mesh.py);
- the per-batch instruction streams are compiled once to a tick program and
  the whole epoch runs as one jitted scan on device;
- extra capability flags: checkpoints, resume, profiling, precision.

All wiring lives in shallowspeed_tpu.api.TrainingSession — this file is the
argument surface plus the reporting loop.

Examples:
    python train.py                      # sequential, 1 device
    python train.py --dp 8               # 8-way data parallel
    python train.py --pp 4 --schedule gpipe
    python train.py --dp 2 --pp 4 --schedule pipedream
On a single-chip host, multi-device layouts run on emulated CPU devices:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python train.py --dp 2 --pp 4 --schedule gpipe
"""

import argparse
import contextlib
import json
import os
import sys
import time


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--dp", type=int, default=1, help="data-parallel replicas")
    ap.add_argument("--pp", type=int, default=1, help="pipeline stages")
    ap.add_argument(
        "--tp",
        type=int,
        default=1,
        help="tensor (model-axis) parallelism: shard every Linear "
        "Megatron-style across tp devices — even layers column-parallel "
        "(W split on the output dim, no forward collective), odd layers "
        "row-parallel (W split on the input dim, one all-reduce over tp) — "
        "so each fwd+bwd pass costs 2 all-reduces per layer pair and "
        "per-device weight memory/matmul FLOPs drop by tp. Composes with "
        "--dp/--pp/--zero1/--grad-bucket-bytes/--backward-split into a "
        "dp x pp x tp lattice (needs dp*pp*tp devices; --audit verifies "
        "the per-axis collective census; see docs/performance.md for "
        "when it pays)",
    )
    ap.add_argument(
        "--schedule",
        choices=["naive", "gpipe", "pipedream", "interleaved"],
        default="naive",
        help="pipeline schedule (ignored unless --pp > 1); 'interleaved' is "
        "Megatron-style virtual-stage 1F1B (use with --virtual-stages)",
    )
    ap.add_argument(
        "--virtual-stages",
        type=int,
        default=1,
        help="virtual stages per device for --schedule interleaved: the model "
        "is cut into pp x V stages, stage s on device s %% pp — the "
        "pipeline-fill bubble shrinks ~V-fold (beyond the reference)",
    )
    ap.add_argument("--epochs", type=int, default=20)
    ap.add_argument("--global-batch-size", type=int, default=128)
    ap.add_argument("--mubatches", type=int, default=4)
    ap.add_argument("--lr", type=float, default=0.006)
    ap.add_argument(
        "--optimizer",
        choices=["sgd", "momentum", "adam"],
        default="sgd",
        help="sgd = reference parity; momentum / adam = stateful optimizers "
        "(state is saved in checkpoints and restored on --resume, any "
        "layout). NOTE on lr: momentum's effective step is lr/(1-mu) — "
        "divide sgd's lr by ~1/(1-mu) (1e-3 reaches 99.65%% in 20 epochs; "
        "sgd's 6e-3 diverges late). adam's normalized step is ~lr per "
        "element — 2e-4 reaches 99.86%% after ONE epoch, but destabilizes on "
        "long runs (see BASELINE.md); prefer sgd/momentum past a few epochs",
    )
    ap.add_argument("--momentum", type=float, default=0.9)
    ap.add_argument(
        "--zero1",
        action="store_true",
        help="ZeRO-1: shard the optimizer state + update over the dp axis "
        "(reduce_scatter grads, per-replica chunk update, all_gather params; "
        "mesh layouts only — beyond the reference). Alias for --zero 1",
    )
    ap.add_argument(
        "--zero",
        type=int,
        choices=[0, 1, 2, 3],
        default=None,
        help="ZeRO stage on the dp axis (mesh layouts; supersedes --zero1): "
        "0 = replicate everything (the anchor all-reduce sync); 1 = shard "
        "the optimizer state + update; 2 = gradients also live as "
        "persistent reduce-scattered per-rank shards (composes with "
        "--grad-bucket-bytes; bitwise-equal weights to --zero 1 at the "
        "same layout); 3 = parameters sharded at rest too, all-gathered "
        "just-in-time per layer inside the tick scan (per-tick gradient "
        "reduce-scatter; cross-stage tolerance numerics, same-layout "
        "determinism). See docs/performance.md for when each stage pays",
    )
    ap.add_argument(
        "--grad-bucket-bytes",
        type=int,
        default=0,
        help="mesh layouts: bucket the DP gradient sync — the backward-"
        "ordered gradient tree is greedily packed into buckets of at most "
        "this many bytes and each bucket is synced by its OWN collective "
        "(all-reduce; reduce-scatter slice under --zero1), so XLA can "
        "overlap bucket communication with the update's compute. 0 "
        "(default) keeps the single whole-tree anchor psum. Bitwise-"
        "identical numerics either way; --audit verifies the bucket count "
        "and sizes in the compiled program (see docs/performance.md)",
    )
    ap.add_argument(
        "--backward-split",
        action="store_true",
        help="pipeline schedules (gpipe/pipedream/naive): two-stage backward "
        "— each microbatch's backward is split into the relay-critical "
        "B-input (d(loss)/d(input), at exactly the tick the combined "
        "backward would run, so upstream stages never wait longer) and a "
        "deferred B-weight (dW/db from the stashed activation + output-"
        "grad) packed into otherwise-idle bubble ticks (2BP, arXiv "
        "2405.18047). Bitwise-identical weights (the weight-grad "
        "accumulation order is preserved); shrinks the FLOP-weighted "
        "bubble fraction the report/show_schedule quote (see "
        "docs/performance.md for when it pays)",
    )
    ap.add_argument(
        "--model",
        choices=["mnist-mlp", "mlp-wide", "mlp-deep", "transformer"],
        default=None,
        help="model-zoo configuration (model.MODEL_ZOO): a named (sizes, "
        "activation family) pair. 'mnist-mlp' is the reference 8-layer "
        "ReLU MLP (the default sizes); 'mlp-wide'/'mlp-deep' are compute-"
        "bound ReLU MLPs (512x6 / 2048x22) that unmask the scheduling "
        "wins CPU dispatch overhead hides on the tiny reference; "
        "'transformer' is the gelu-family block model (x @ W_up -> gelu "
        "-> @ W_down + residual per slot pair, Megatron-parity sharding). "
        "All zoo models keep the 784-wide MNIST input",
    )
    ap.add_argument(
        "--recompute",
        action="store_true",
        help="pipeline schedules: activation recompute — forwards stash "
        "only the stage INPUT, and the stage forward re-runs inside the "
        "backward tick (OP_RECOMPUTE), shrinking the activation-stash "
        "lifetime from fwd->bwd to recompute->bwd (peak stash slots drop "
        "to 1 on gpipe/pipedream; ~4/3 FLOPs tax — see docs/lowering.md "
        "and docs/performance.md for when it pays). Bitwise-identical "
        "weights vs stashed training; mesh layouts only, not interleaved",
    )
    ap.add_argument("--data-dir", default=None)
    ap.add_argument("--no-eval", action="store_true", help="skip per-epoch accuracy")
    ap.add_argument(
        "--fused-run",
        action="store_true",
        help="run ALL epochs (+ per-epoch validation accuracy unless "
        "--no-eval) as one on-device program — works on every layout "
        "(sequential and DP x PP mesh). Prints the same per-epoch lines as "
        "the loop (without per-line clocks — everything returns in one "
        "dispatch). --profile-dir traces that single dispatch; --checkpoint "
        "writes once at the end instead of per epoch.",
    )
    ap.add_argument(
        "--checkpoint",
        default=None,
        help="path to save a checkpoint after each epoch (with --fused-run: "
        "the whole run is ONE dispatch, so exactly one checkpoint is saved, "
        "after it returns — the pinned contract)",
    )
    ap.add_argument(
        "--checkpoint-dir",
        default=None,
        help="directory for preemption-safe STEP checkpoints "
        "(step-<global_step>.npz, atomic + checksummed; see "
        "docs/robustness.md) — required by --checkpoint-every-steps and "
        "--resume auto",
    )
    ap.add_argument(
        "--checkpoint-every-steps",
        type=int,
        default=0,
        metavar="N",
        help="write a step checkpoint into --checkpoint-dir every N "
        "optimizer steps (0 = off). The epoch is dispatched in N-step "
        "chunks — bitwise-identical weights to whole-epoch dispatch — and "
        "a killed run resumes from the last snapshot with --resume auto",
    )
    ap.add_argument(
        "--keep",
        type=int,
        default=3,
        metavar="K",
        help="step-checkpoint retention: keep the newest K snapshots "
        "(older ones are rotated away; >1 keeps fallbacks for corrupt-"
        "newest recovery)",
    )
    ap.add_argument(
        "--async-checkpoint",
        action="store_true",
        help="write step checkpoints through the background writer: the "
        "step path pays only the device->host snapshot + a bounded-queue "
        "enqueue, while sha256/finiteness verification, the "
        "write-fsync-rename sequence and rotation run off-path "
        "(docs/robustness.md 'The async writer'). Crash windows are "
        "identical to the synchronous path — a kill at any instant "
        "leaves only fully-verifying snapshots discoverable — and the "
        "run drains the writer before exiting",
    )
    ap.add_argument(
        "--aot-cache",
        default=None,
        metavar="DIR",
        help="AOT executable cache directory: compiled programs (the "
        "inference rung ladder, the epoch audit probe) are serialized "
        "here and cold starts deserialize instead of recompiling — "
        "keyed by layout + jaxlib/backend fingerprint + lowered-program "
        "hash, re-verified by the audit census before first dispatch, "
        "falling back to a clean recompile on any corruption "
        "(docs/performance.md)",
    )
    ap.add_argument(
        "--resume",
        default=None,
        help="checkpoint to resume from (any layout -> any layout), or "
        "'auto': discover the newest VERIFYING step checkpoint in "
        "--checkpoint-dir (corrupt/torn/non-finite snapshots are skipped), "
        "resume mid-epoch at its exact step — or start fresh when the "
        "directory is empty. With 'auto', --epochs is the run's TOTAL "
        "epoch target (so a killed-and-resumed run ends where its "
        "uninterrupted twin does); with an explicit path it stays the "
        "number of ADDITIONAL epochs (the historical contract)",
    )
    ap.add_argument(
        "--profile-dir",
        default=None,
        help="write a jax.profiler trace of one training epoch to this directory",
    )
    ap.add_argument(
        "--dispatch-probe",
        action="store_true",
        help="after training, measure the op-issue roofline: dispatch "
        "extra training epochs under the jax profiler and report the "
        "share of host wall NOT covered by op execution "
        "(docs/performance.md 'The measured dispatch-overhead share'). "
        "The probe TRAINS the epochs it times (the epoch program donates "
        "its state) — it runs after the final model hash is printed, so "
        "the hash stays the training result",
    )
    ap.add_argument(
        "--dispatch-probe-out",
        default=None,
        metavar="JSON",
        help="also write the probe's measurement as a versioned bench "
        "record (bench: dispatch_overhead) to this file; implies "
        "--dispatch-probe",
    )
    ap.add_argument(
        "--metrics-out",
        default=None,
        help="record structured training telemetry (per-epoch loss, "
        "samples/s, MFU, grad-norm when clipping, per-step flight records, "
        "compile/lowering spans, pipeline program stats) to this JSONL "
        "file — see docs/observability.md for the schema; render it with "
        "`python -m shallowspeed_tpu.observability.report FILE`",
    )
    ap.add_argument(
        "--digests",
        action="store_true",
        help="numerics provenance: compute per-step per-LAYER digests "
        "(uint32 bitcast checksums of every post-update (W, b) block + "
        "param/grad block norms) inside the fused epoch program and "
        "stream them as schema-v12 digest records to --metrics-out; "
        "compare two runs' streams with `python -m "
        "shallowspeed_tpu.observability.divergence A.jsonl B.jsonl` to "
        "name the first divergent (step, layer, tensor)",
    )
    ap.add_argument(
        "--audit",
        action="store_true",
        help="XLA program audit: at jit time, census the compiled "
        "program's collectives (all-reduce / reduce-scatter / all-gather / "
        "collective-permute) and verify them against the layout's "
        "analytical comms contract — a mismatch aborts BEFORE the first "
        "dispatch. With --metrics-out the full audit (census, memory "
        "analysis, bytes/step comms model) lands as a schema-v3 "
        "xla_audit record; the report CLI renders its memory and comms "
        "sections",
    )
    ap.add_argument(
        "--health",
        choices=["record", "warn", "halt"],
        default=None,
        help="numerics health monitor over the per-step flight aux "
        "(NaN/Inf, rolling-window loss divergence, grad-norm spikes): "
        "'record' emits health records into --metrics-out, 'warn' also "
        "prints them, 'halt' additionally aborts the run (exit 3) at the "
        "first finding, naming the blown-up step",
    )
    ap.add_argument(
        "--fuse-mubatches",
        action="store_true",
        help="sequential path only: one full-batch forward/backward per step "
        "instead of the microbatch scan — same training (see docs/numerics.md), "
        "larger matmuls for the MXU",
    )
    ap.add_argument(
        "--megakernel",
        action="store_true",
        help="with --fuse-mubatches (SGD, momentum or adam): run each training batch as "
        "ONE Pallas kernel — forward, head, backward and update in a single "
        "op (identical numerics; shortest possible serial op chain)",
    )
    ap.add_argument(
        "--epoch-kernel",
        action="store_true",
        help="with --fuse-mubatches (SGD, momentum or adam): run each ENTIRE epoch as "
        "one Pallas kernel — the batch axis is the kernel grid and the "
        "params stay VMEM-resident across the epoch (identical numerics; "
        "one device op per epoch instead of one per batch)",
    )
    ap.add_argument(
        "--run-kernel",
        action="store_true",
        help="with --fuse-mubatches (SGD, momentum or adam): run the whole "
        "multi-epoch training run as ONE Pallas kernel when dispatched via "
        "--fused-run --no-eval (grid = epochs x batches, params VMEM-resident "
        "for the entire run; identical numerics). Per-epoch runs and the "
        "evaluated fused run ride the epoch kernel",
    )
    ap.add_argument(
        "--weight-decay",
        type=float,
        default=0.0,
        help="decoupled weight decay, uniform over every param element "
        "(0 = reference parity)",
    )
    ap.add_argument(
        "--clip-norm",
        type=float,
        default=None,
        help="global-norm gradient clipping over ALL params (the norm spans "
        "stages/replicas on mesh layouts); off by default",
    )
    ap.add_argument(
        "--scan-unroll",
        type=int,
        default=1,
        help="lax.scan unroll factor for the per-batch epoch loop "
        "(throughput knob, bit-identical numerics)",
    )
    ap.add_argument(
        "--tick-unroll",
        type=int,
        default=1,
        help="lax.scan unroll factor for the pipeline tick loop (mesh "
        "layouts; throughput knob, bit-identical numerics)",
    )
    ap.add_argument(
        "--precision",
        choices=["highest", "default"],
        default="highest",
        help="matmul precision: 'highest' = fp32 parity with the NumPy "
        "reference; 'default' = let the MXU use fast (bf16-input) passes",
    )
    ap.add_argument(
        "--runtime",
        choices=["lockstep", "mpmd"],
        default="lockstep",
        help="pipeline runtime (mesh layouts): 'lockstep' runs the whole "
        "lattice as ONE SPMD program (tick scan, ppermute relays — the "
        "correctness oracle); 'mpmd' compiles one program per stage role "
        "and dispatches them asynchronously from the host with device-to-"
        "device relays (arXiv 2412.14374) — bitwise-identical weights, "
        "no noop-tick dispatches (the measured op-issue roofline, "
        "docs/performance.md). mpmd drives the epoch loop (no "
        "--fused-run) and excludes --zero1/--grad-bucket-bytes/"
        "--clip-norm/--kernel-backend pallas for now",
    )
    ap.add_argument(
        "--kernel-backend",
        choices=["xla", "pallas"],
        default="xla",
        help="mesh layouts (--dp/--pp > 1): per-slot compute unit inside "
        "every pipeline tick — 'pallas' runs each slot as one fused "
        "flag-operand Pallas kernel (same math; see docs/performance.md). "
        "Sequential path: use --megakernel or SHALLOWSPEED_PALLAS=1",
    )
    args = ap.parse_args()

    # fail fast on incoherent fault-tolerance flag combinations — at
    # argparse time, before any backend or data is touched
    if args.checkpoint_every_steps < 0:
        ap.error("--checkpoint-every-steps must be >= 0")
    if args.checkpoint_every_steps and args.checkpoint_dir is None:
        ap.error("--checkpoint-every-steps needs --checkpoint-dir")
    if args.checkpoint_every_steps and args.fused_run:
        ap.error(
            "--checkpoint-every-steps is incompatible with --fused-run: the "
            "fused run is ONE on-device dispatch, so there is no step "
            "boundary for the host to checkpoint at — drop --fused-run for "
            "preemption-safe runs (--checkpoint still saves once after the "
            "fused dispatch)"
        )
    if args.resume == "auto" and args.checkpoint_dir is None:
        ap.error("--resume auto discovers snapshots in --checkpoint-dir")
    if args.async_checkpoint and args.checkpoint_dir is None:
        ap.error("--async-checkpoint needs --checkpoint-dir")
    if args.resume == "auto" and args.fused_run:
        ap.error(
            "--resume auto may land mid-epoch, and the fused run has no "
            "mid-epoch entry point — drop --fused-run to recover"
        )
    if args.keep < 1:
        ap.error("--keep must be >= 1")
    if args.runtime == "mpmd" and args.fused_run:
        ap.error(
            "--runtime mpmd schedules per-stage programs from the host; "
            "the fused ONE-dispatch run is a lockstep contract — drop "
            "--fused-run (the epoch loop dispatches MPMD)"
        )
    if args.digests and args.fused_run:
        ap.error(
            "--digests rides the epoch/step scan aux, which the fused "
            "multi-epoch run program does not thread — drop --fused-run "
            "(the epoch/step loops stream digest records)"
        )
    if args.runtime == "mpmd" and (args.dp, args.pp, args.tp) == (1, 1, 1):
        ap.error(
            "--runtime mpmd needs a mesh layout (dp/pp/tp > 1): the "
            "sequential path has no pipeline stages to decompose"
        )
    if args.recompute and (args.dp, args.pp, args.tp) == (1, 1, 1):
        ap.error(
            "--recompute drops pipeline activation stashes; the "
            "sequential path holds no cross-tick stash — use a mesh "
            "layout (dp/pp/tp > 1)"
        )
    if args.recompute and args.virtual_stages > 1:
        ap.error(
            "--recompute is not supported with interleaved virtual "
            "stages (the chunked stash rotation is its own lifetime "
            "discipline)"
        )
    # "plan is active" mirrors faults.FaultPlan.parse: any non-empty
    # comma-separated part is an injection (checked without importing the
    # package — argparse time stays jax-free)
    if args.zero1 and args.zero is not None and args.zero != 1:
        ap.error(
            f"conflicting dp-stage selectors: --zero1 and --zero {args.zero} "
            "— pass only --zero"
        )
    zero_stage = args.zero if args.zero is not None else (1 if args.zero1 else 0)
    if zero_stage == 3 and args.fused_run:
        ap.error(
            "--zero 3 is incompatible with --fused-run: the fused "
            "multi-epoch run's eval step consumes the full stacked layout "
            "every epoch, but stage 3 keeps parameters sharded at rest — "
            "drop --fused-run (the per-epoch loop dispatches ZeRO-3)"
        )
    if zero_stage == 3 and args.kernel_backend == "pallas":
        ap.error(
            "--zero 3 is incompatible with --kernel-backend pallas: the "
            "fused slot kernels consume resident {W, b} operands, but "
            "stage 3 materializes parameters per tick via all-gather — "
            "drop one of the two flags"
        )
    if zero_stage == 3 and args.grad_bucket_bytes:
        ap.error(
            "--zero 3 syncs gradients per tick (reduce-scatter into the "
            "persistent shard carry) — there is no tail collective for "
            "--grad-bucket-bytes to bucket; drop one of the two flags"
        )
    if zero_stage and args.runtime == "mpmd":
        ap.error(
            f"--runtime mpmd does not support --zero {zero_stage} yet: the "
            "ZeRO reduce-scatter/all-gather tail assumes the lockstep SPMD "
            "program's dp axis — drop one of the two flags"
        )
    if zero_stage >= 2 and args.digests:
        ap.error(
            f"--digests is incompatible with --zero {zero_stage}: the "
            "digest taps read the zero1 flat-chunk segment map, which "
            "stages 2-3 replace with the block-cyclic shard layout — drop "
            "one of the two flags"
        )
    faults_env = os.environ.get("SHALLOWSPEED_FAULTS", "")
    if args.fused_run and any(p.strip() for p in faults_env.split(",")):
        ap.error(
            f"SHALLOWSPEED_FAULTS={faults_env!r} is set but --fused-run "
            "dispatches the whole run as ONE program — step-granular "
            "injections can never fire, and a recovery driver would "
            "mistake the uninjected run for a survived crash; drop "
            "--fused-run (the fault harness needs the step loop)"
        )

    import jax

    from shallowspeed_tpu.api import TrainingSession
    from shallowspeed_tpu.checkpoint import CheckpointError
    from shallowspeed_tpu.observability import HealthError, JsonlMetrics, capture

    metrics = JsonlMetrics(args.metrics_out) if args.metrics_out else None
    try:
        run = TrainingSession(
            metrics=metrics,
            health=args.health,
            audit=args.audit,
            model=args.model,
            dp=args.dp,
            pp=args.pp,
            tp=args.tp,
            schedule=args.schedule,
            global_batch_size=args.global_batch_size,
            mubatches=args.mubatches,
            lr=args.lr,
            precision=args.precision,
            data_dir=args.data_dir,
            resume=args.resume,
            fuse_mubatches=args.fuse_mubatches,
            megakernel=args.megakernel,
            epoch_kernel=args.epoch_kernel,
            run_kernel=args.run_kernel,
            optimizer=args.optimizer,
            momentum=args.momentum,
            virtual_stages=args.virtual_stages,
            zero=zero_stage,
            grad_bucket_bytes=args.grad_bucket_bytes,
            backward_split=args.backward_split,
            recompute=args.recompute,
            scan_unroll=args.scan_unroll,
            tick_unroll=args.tick_unroll,
            weight_decay=args.weight_decay,
            clip_norm=args.clip_norm,
            kernel_backend=args.kernel_backend,
            checkpoint_dir=args.checkpoint_dir,
            checkpoint_keep=args.keep,
            async_checkpoint=args.async_checkpoint,
            aot_cache_dir=args.aot_cache,
            runtime=args.runtime,
            digests=args.digests,
        )
    except CheckpointError as e:
        # unrecoverable checkpoint state: the named file (or every snapshot
        # in the discovery directory) fails verification — distinct exit
        # code so drivers can tell "restore is impossible" from a crash
        # (exit-code contract: README / docs/observability.md)
        print(f"CHECKPOINT UNRECOVERABLE: {e}", file=sys.stderr)
        if metrics is not None:
            metrics.close()
        sys.exit(4)
    if args.fused_run and run.step_in_epoch > 0:
        # the late half of the fail-fast net: an EXPLICIT --resume
        # snapshot's cursor is only known after reading it, so this
        # contract violation surfaces post-restore — same clean message
        # and exit code (2) as the argparse-time checks, never a raw
        # mid-flight traceback out of the fused dispatch
        if metrics is not None:
            metrics.close()
        ap.error(
            f"--resume {args.resume} restored a mid-epoch cursor (epoch "
            f"{run.epoch}, step {run.step_in_epoch}), and the fused run "
            "has no mid-epoch entry point — drop --fused-run to finish "
            "the epoch with the step loop"
        )
    if (
        args.dp == 1
        and args.pp == 1
        and args.virtual_stages == 1
        and args.tp == 1
    ):
        layout = "sequential"
    elif args.virtual_stages > 1:
        layout = f"interleaved pipeline, V={args.virtual_stages}"
    elif args.pp > 1:
        layout = f"{args.schedule} pipeline"
    elif args.dp > 1:
        layout = "data-parallel"
    else:
        layout = "tensor-parallel"
    if args.tp > 1 and layout != "tensor-parallel":
        layout += " + tensor-parallel"
    note = ""
    if args.resume:
        if run.resumed_from is not None:
            note = f" resumed at epoch {run.epoch}"
            if run.step_in_epoch:
                note += f", step {run.step_in_epoch}"
        else:  # --resume auto on an empty checkpoint dir
            note = " no resumable checkpoint found — fresh start"
    if args.runtime == "mpmd":
        layout += ", mpmd runtime"
    print(
        f"devices={jax.devices()} layout: DP={args.dp} x PP={args.pp} x "
        f"TP={args.tp} ({layout}) batches/epoch={run.batches_per_epoch}" + note
    )

    def profiled(i):
        # trace one post-compile epoch when asked (observability.capture =
        # jax.profiler.trace + a profiler_capture record in the metrics
        # stream naming the trace artifact)
        if args.profile_dir and i == min(1, args.epochs - 1):
            return capture(args.profile_dir, metrics)
        return contextlib.nullcontext()

    t0 = time.time()
    try:
        if args.fused_run and args.epochs > 0:
            # same accuracy semantics as the loop below — the "Epoch: N ...
            # Accuracy" line reports the model's accuracy BEFORE epoch N trains
            # (the initial one costs a single pre-run dispatch; the rest come
            # out of the fused program's per-epoch accuracies). No per-epoch
            # "Time Spent" here: all lines print after the single dispatch
            # returns, so a per-line cumulative clock would be misleading.
            if not args.no_eval:
                print(f"Epoch: {run.epoch}, Accuracy: {run.accuracy() * 100:.2f}%")
            start = run.epoch
            if args.profile_dir:
                # AOT-compile first so the trace holds steady-state execution,
                # not compilation (mirrors the loop mode's post-compile trace)
                run.warm_run(args.epochs, with_eval=not args.no_eval)
            with capture(args.profile_dir, metrics):
                losses, accs = run.train_run(args.epochs, with_eval=not args.no_eval)
            for e, loss in enumerate(losses):
                print(f"Epoch: {start + e}, mean train loss: {loss:.5f}")
                if not args.no_eval and e < len(losses) - 1:
                    print(f"Epoch: {start + e + 1}, Accuracy: {accs[e] * 100:.2f}%")
            if args.checkpoint:
                run.save(args.checkpoint)
            final_acc = accs[-1] if accs else run.accuracy()
        elif (
            args.checkpoint_every_steps
            or run.faults_active
            or run.step_in_epoch > 0
            or args.resume == "auto"
        ):
            # the preemption-safe STEP loop: the epoch is dispatched in
            # chunks cut at the checkpoint grid (and at fault-injection
            # steps), bitwise-identical to whole-epoch dispatch; a snapshot
            # is written whenever global_step lands on the grid. With
            # --resume auto, --epochs is the TOTAL target so a resumed run
            # ends exactly where its uninterrupted twin does — which is why
            # resume-auto runs ALWAYS take this loop, even when the restored
            # cursor sits on an epoch boundary and no step grid is active.
            every = args.checkpoint_every_steps
            target = (
                args.epochs if args.resume == "auto"
                else run.epoch + args.epochs
            )
            nb = run.batches_per_epoch
            # trace one post-compile epoch, like the plain loop's profiled()
            prof_epoch = (
                run.epoch + min(1, max(target - run.epoch - 1, 0))
                if args.profile_dir and target > run.epoch
                else None
            )
            while run.epoch < target:
                if run.step_in_epoch == 0 and not args.no_eval:
                    print(
                        f"Epoch: {run.epoch}, Time Spent: "
                        f"{time.time() - t0:.2f}s, "
                        f"Accuracy: {run.accuracy() * 100:.2f}%"
                    )
                if every > 0:
                    n = min(
                        every - run.global_step % every,
                        nb - run.step_in_epoch,
                    )
                else:
                    n = nb - run.step_in_epoch
                with (
                    capture(args.profile_dir, metrics)
                    if run.epoch == prof_epoch
                    else contextlib.nullcontext()
                ):
                    _, epoch_loss = run.train_steps(n)
                if every > 0 and run.global_step % every == 0:
                    run.save_step_checkpoint()
                if epoch_loss is not None:
                    print(
                        f"Epoch: {run.epoch - 1}, mean train loss: "
                        f"{epoch_loss:.5f}"
                    )
                    if args.checkpoint:
                        run.save(args.checkpoint)
            final_acc = run.accuracy()
        else:
            for i in range(args.epochs):
                if not args.no_eval:
                    print(
                        f"Epoch: {run.epoch}, Time Spent: {time.time() - t0:.2f}s, "
                        f"Accuracy: {run.accuracy() * 100:.2f}%"
                    )
                with profiled(i):
                    loss = run.train_epoch()
                print(f"Epoch: {run.epoch - 1}, mean train loss: {loss:.5f}")
                if args.checkpoint:
                    run.save(args.checkpoint)
            final_acc = run.accuracy()
    except HealthError as e:
        # --health halt fired: the finding is already recorded (and the
        # JSONL flushed) by the monitor; stop with a distinct exit code so
        # drivers can tell "numerics blew up" from an infrastructure crash
        print(f"HEALTH HALT: {e}", file=sys.stderr)
        if metrics is not None:
            metrics.close()
            print(f"telemetry written: {metrics.path}")
        sys.exit(3)
    finally:
        # EVERY exceptional exit drains the async checkpoint writer — a
        # KeyboardInterrupt or a failing eval must not strand accepted
        # snapshots in a daemon thread's queue. Best-effort only while
        # an exception is propagating (a drain failure must never mask
        # it); the clean path closes below, LOUDLY, so writer errors
        # still fail the run.
        if sys.exc_info()[0] is not None:
            try:
                run.close()
            except Exception as e:  # noqa: BLE001 — never mask the exit
                print(
                    f"checkpoint writer drain failed: {e}", file=sys.stderr
                )
    # drain the async checkpoint writer BEFORE claiming success: a clean
    # exit must leave every accepted snapshot durable (writer-side
    # failures re-raise here instead of dying silently in a daemon thread)
    run.close()
    print(
        f"Epoch: {run.epoch}, Time Spent: {time.time() - t0:.2f}s, "
        f"Accuracy: {final_acc * 100:.2f}%"
    )
    run.assert_replicas_in_sync()
    if args.dp > 1:
        print("DP replicas in sync ✓")
    print("final model hash:", run.model_hash())
    if args.dispatch_probe or args.dispatch_probe_out:
        # the measured op-issue roofline (docs/performance.md): extra
        # profiled epochs AFTER the hash print, so the hash above stays
        # the training result the drivers compare
        rec = run.measure_dispatch_overhead()
        share = rec["dispatch_overhead"]
        if share is None:
            print(
                "dispatch overhead: unmeasurable — "
                + rec.get("reason", "no op events")
            )
        else:
            print(
                f"dispatch overhead: >= {share * 100:.1f}% of epoch wall "
                f"is host-side op issue (op busy "
                f"{rec['device_busy_s'] * 1e3:.1f} ms of "
                f"{rec['host_wall_s'] * 1e3:.1f} ms uninstrumented wall "
                f"over {rec['repeats']} epoch(s); {rec['op_events']} op "
                f"events, source {rec['op_source']}, profiler inflation "
                f"{rec['profiler_inflation']:.2f}x)"
            )
        if not rec["window_valid"]:
            # the machine-checked DISPATCH_r01 caveat: an invalid probe
            # window's share must never be quoted as a measurement
            print(
                "dispatch-probe window INVALID: "
                + (rec["window_invalid_reason"] or "unknown")
            )
        if args.dispatch_probe_out:
            bench_rec = {
                "bench": "dispatch_overhead",
                "bench_version": 1,
                "config": {
                    "dp": args.dp,
                    "pp": args.pp,
                    "tp": args.tp,
                    "schedule": args.schedule,
                    "global_batch_size": args.global_batch_size,
                    "mubatches": args.mubatches,
                    "backward_split": args.backward_split,
                    "grad_bucket_bytes": args.grad_bucket_bytes,
                    "platform": rec["platform"],
                },
                "value": share,
                "unit": "fraction of epoch wall not covered by op execution",
                **{
                    k: rec[k]
                    for k in (
                        "program", "runtime", "repeats", "host_wall_s",
                        "host_wall_instrumented_s", "profiler_inflation",
                        "device_busy_s", "device_comm_s",
                        "device_compute_s", "op_events", "op_source",
                        "events_per_batch", "window_valid",
                        "window_invalid_reason",
                        "dispatch_overhead_instrumented", "provenance",
                    )
                },
            }
            from shallowspeed_tpu.observability.metrics import json_safe

            with open(args.dispatch_probe_out, "w", encoding="utf-8") as f:
                f.write(
                    json.dumps(json_safe(bench_rec), indent=2, allow_nan=False)
                    + "\n"
                )
            print(f"dispatch-overhead record written: {args.dispatch_probe_out}")
    if metrics is not None:
        metrics.close()
        print(f"telemetry written: {metrics.path}")


if __name__ == "__main__":
    main()
