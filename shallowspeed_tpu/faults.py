"""Deterministic fault injection: kill, poison and corrupt ON PURPOSE.

The recovery contract ("crash at any step, resume, and the final weight hash
is identical") is only worth claiming if something actually crashes real
runs. This module is that something: a small set of injection points that
tests, ``make recovery-smoke`` and ad-hoc debugging activate either through
the API (``TrainingSession(faults=...)``) or the environment
(``SHALLOWSPEED_FAULTS``, so a *subprocess* train.py can be killed without
patching it).

Spec grammar — comma-separated injections, each anchored to a TRAINING
step (``kind@step=N[:mode=...]``), a SERVING dispatch
(``kind@dispatch=N[:mode=...][:ms=...]``), or a checkpoint SAVE
(``kind@save=N[:mode=...][:ms=...]``)::

    SHALLOWSPEED_FAULTS="die@step=7:mode=sigkill"     # hard kill at step 7
    SHALLOWSPEED_FAULTS="die@step=7"                  # raise InjectedFault
    SHALLOWSPEED_FAULTS="nan@step=3"                  # NaN into the gradients
    SHALLOWSPEED_FAULTS="flip@step=3"                 # single-bit param flip
    SHALLOWSPEED_FAULTS="die@step=9,nan@step=3"       # compose
    SHALLOWSPEED_FAULTS="error@dispatch=4"            # raise INSIDE dispatch 4
    SHALLOWSPEED_FAULTS="slow@dispatch=6:ms=50"       # stall dispatch 6 50 ms
    SHALLOWSPEED_FAULTS="nan@dispatch=8"              # poison served weights
    SHALLOWSPEED_FAULTS="die@save=2:mode=sigkill"     # kill INSIDE save 2's
                                                      #   write-verify-rename
                                                      #   window
    SHALLOWSPEED_FAULTS="slow@save=1:ms=200"          # stall the writer in
                                                      #   the same window
    SHALLOWSPEED_FAULTS="corrupt@save=3"              # flip bytes in the
                                                      #   in-flight buffer

Steps are GLOBAL optimizer-step indices (epoch * batches_per_epoch +
step_in_epoch — the same cursor the step checkpoints store). Dispatches
are the serving engine's attempted-dispatch sequence numbers (every
``step()`` that has work counts one, failures included, so a chaos spec
replays deterministically). Saves are ``save_step_checkpoint``'s save
sequence numbers (the Nth snapshot this process attempts, halt flushes
included) — the anchor the async checkpoint writer consults, so the
chaos harness can land a kill at a DETERMINISTIC point inside the
write/verify/rename window (docs/robustness.md "The async writer's
crash windows").

Injection points (all driven from the host-side step/serving loop, never
from inside a jitted program — an instrumented run executes the same XLA):

- ``die``   fire when the run reaches step/dispatch N, BEFORE the update
            or the batch pop: ``mode=exc`` (default) raises
            ``InjectedFault``; ``mode=sigkill`` sends SIGKILL to the
            current process — the real preemption shape, nothing flushes,
            no atexit runs. In serving, ``mode=exc`` models the dispatch
            loop dying: it fires before any request is popped, so the
            queue is intact when the operator loop re-enters.
- ``nan``   poison the parameters right before step/dispatch N, so step
            N's gradients (training) or dispatch N's predictions
            (serving) come out NaN — the deterministic blow-up the
            numerics health monitor / the serving health gate exists to
            catch.
- ``flip``  (step only) XOR the LOWEST mantissa bit of exactly one
            parameter element (flat index 0 of the first weight leaf —
            the same deterministic anchor ``nan`` poisons) right before
            step N: the silent single-bit corruption that stays finite,
            evades the health monitor, and only the per-layer digest
            stream (``--digests`` + observability/divergence) can
            attribute to its exact (step, layer, tensor).
- ``slow``  (dispatch/save) sleep ``ms`` inside dispatch N — the latency
            spike that drives deadline shedding — or inside save N's
            write window (after the temp write, before the rename), so
            an externally timed SIGKILL lands mid-save deterministically.
- ``error`` (dispatch only) raise ``InjectedFault`` INSIDE the dispatch
            wrapper, after the batch was popped — the failure shape the
            engine's dispatch-recovery path (re-queue + bounded retry)
            exists to survive.
- ``corrupt`` (save only) flip bytes in the IN-FLIGHT snapshot buffer
            AFTER its checksum was stamped — the written file renames
            into place but can never verify, exactly the bit-rot shape
            ``find_latest_good`` must skip past. Save-anchored ``die``
            fires INSIDE the writer's window: after the temp file is
            written and fsynced, BEFORE the atomic rename — the kill
            point the crash-safety contract says must leave only the
            older fully-verifying snapshots discoverable.

Checkpoint corruption of files AT REST stays a function, not a step
trigger (tests corrupt files directly): ``corrupt_checkpoint_bytes(path)``
flips bytes inside an existing checkpoint so its content checksum can no
longer verify — deterministic given ``seed``.
"""

import os
import signal

import numpy as np

ENV_VAR = "SHALLOWSPEED_FAULTS"
KINDS = ("die", "nan", "flip")  # step-triggered (training) kinds
SERVING_KINDS = ("die", "nan", "slow", "error")  # dispatch-triggered kinds
SAVE_KINDS = ("die", "slow", "corrupt")  # save-triggered (writer) kinds
DIE_MODES = ("exc", "sigkill")


class InjectedFault(RuntimeError):
    """Raised by a ``die`` injection with ``mode=exc`` (the soft kill) and
    by a serving ``error`` injection inside the dispatch wrapper."""


class Fault:
    """One parsed injection: ``kind`` at global ``step`` (+ ``mode``), at
    attempted-dispatch ``dispatch`` (serving; + ``ms`` for ``slow``), or
    at checkpoint-save sequence ``save`` (the writer anchor). Exactly one
    of ``step``/``dispatch``/``save`` is set; ``trigger`` names which."""

    __slots__ = ("kind", "step", "dispatch", "save", "mode", "ms", "fired")

    def __init__(self, kind, step=None, mode=None, dispatch=None, ms=None,
                 save=None):
        anchors = [a for a in (step, dispatch, save) if a is not None]
        if len(anchors) != 1:
            raise ValueError(
                "a fault anchors to exactly one of step/dispatch/save"
            )
        if step is not None:
            if kind not in KINDS:
                raise ValueError(
                    f"unknown step-fault kind {kind!r} (have {KINDS})"
                )
            if step < 0:
                raise ValueError(f"fault step must be >= 0, got {step}")
        elif dispatch is not None:
            if kind not in SERVING_KINDS:
                raise ValueError(
                    f"unknown dispatch-fault kind {kind!r} (have "
                    f"{SERVING_KINDS})"
                )
            if dispatch < 0:
                raise ValueError(
                    f"fault dispatch must be >= 0, got {dispatch}"
                )
        else:
            if kind not in SAVE_KINDS:
                raise ValueError(
                    f"unknown save-fault kind {kind!r} (have {SAVE_KINDS})"
                )
            if save < 0:
                raise ValueError(f"fault save must be >= 0, got {save}")
        if kind == "die":
            mode = mode or "exc"
            if mode not in DIE_MODES:
                raise ValueError(
                    f"die mode must be one of {DIE_MODES}, got {mode!r}"
                )
        elif mode is not None:
            raise ValueError(f"fault kind {kind!r} takes no mode")
        if kind == "slow":
            if ms is None:
                raise ValueError("slow faults need ms=<milliseconds>")
            ms = float(ms)
            if ms < 0:
                raise ValueError(f"slow ms must be >= 0, got {ms}")
        elif ms is not None:
            raise ValueError(f"fault kind {kind!r} takes no ms")
        self.kind = kind
        self.step = None if step is None else int(step)
        self.dispatch = None if dispatch is None else int(dispatch)
        self.save = None if save is None else int(save)
        self.mode = mode
        self.ms = ms
        self.fired = False

    @property
    def trigger(self):
        if self.step is not None:
            return "step"
        return "dispatch" if self.dispatch is not None else "save"

    def __repr__(self):
        at = f"{self.trigger}={getattr(self, self.trigger)}"
        mode = f":mode={self.mode}" if self.kind == "die" else ""
        ms = f":ms={self.ms:g}" if self.kind == "slow" else ""
        return f"{self.kind}@{at}{mode}{ms}"


class FaultPlan:
    """The active injections of one run; consulted at step boundaries."""

    def __init__(self, faults=()):
        self.faults = list(faults)

    @classmethod
    def parse(cls, spec):
        """Parse the spec grammar (see module docstring). ``None``/empty ->
        an empty plan; malformed specs raise ValueError naming the part."""
        faults = []
        for part in (spec or "").split(","):
            part = part.strip()
            if not part:
                continue
            try:
                kind, _, rest = part.partition("@")
                fields = dict(
                    kv.split("=", 1) for kv in rest.split(":") if kv
                )
                step = fields.pop("step", None)
                dispatch = fields.pop("dispatch", None)
                save = fields.pop("save", None)
                if sum(a is not None for a in (step, dispatch, save)) != 1:
                    raise ValueError(
                        "need exactly one of step=/dispatch=/save="
                    )
                faults.append(
                    Fault(
                        kind.strip(),
                        step=None if step is None else int(step),
                        dispatch=None if dispatch is None else int(dispatch),
                        save=None if save is None else int(save),
                        mode=fields.pop("mode", None),
                        ms=fields.pop("ms", None),
                    )
                )
                if fields:
                    raise ValueError(f"unknown fields {sorted(fields)}")
            except (KeyError, ValueError) as e:
                raise ValueError(f"bad fault spec {part!r}: {e}") from None
        return cls(faults)

    def __bool__(self):
        return bool(self.faults)

    @property
    def pending(self):
        """STEP-triggered injections that have not fired yet — non-empty
        means the run still needs step boundaries (``train_steps``) for
        them to land. Dispatch-triggered (serving) faults are excluded:
        they land in the serving engine's dispatch loop, so a training
        entry point must not refuse a run over them."""
        return [f for f in self.faults if not f.fired and f.step is not None]

    @property
    def pending_dispatch(self):
        """Dispatch-triggered injections that have not fired yet."""
        return [
            f for f in self.faults if not f.fired and f.dispatch is not None
        ]

    @property
    def pending_save(self):
        """Save-triggered (checkpoint-writer) injections not fired yet."""
        return [
            f for f in self.faults if not f.fired and f.save is not None
        ]

    def due_at_save(self, n):
        """Un-fired save faults scheduled AT OR BEFORE save sequence ``n``,
        in spec order — the checkpoint writer (sync path or the async
        background thread) fires each exactly once. The <= anchor mirrors
        ``due_at_dispatch``: a fault whose exact save never ran (e.g. the
        run died first and resumed with a shorter grid) still fires on
        the next save instead of silently never."""
        return [f for f in self.pending_save if f.save <= n]

    def first_in(self, lo, hi):
        """Earliest un-fired STEP fault with ``lo <= step < hi``, or None —
        the step loop truncates its dispatch chunks at this boundary so
        every injection lands exactly on its step."""
        pending = [f for f in self.pending if lo <= f.step < hi]
        return min(pending, key=lambda f: f.step) if pending else None

    def due_at_dispatch(self, n):
        """Un-fired dispatch faults scheduled AT OR BEFORE attempted
        dispatch ``n``, in spec order — the serving engine fires each
        exactly once. The <= (not ==) anchor is the serving mirror of the
        step loop's fire-loop: a fault whose exact dispatch was consumed
        by a same-dispatch ``die`` (or by a dispatch that only shed
        expired requests) fires on the next attempt instead of silently
        never."""
        return [f for f in self.pending_dispatch if f.dispatch <= n]

    def fire_die(self, fault):
        """Execute a ``die`` fault: SIGKILL the process (nothing flushes —
        the honest preemption) or raise InjectedFault."""
        fault.fired = True
        if fault.mode == "sigkill":
            os.kill(os.getpid(), signal.SIGKILL)
        raise InjectedFault(f"injected fault: {fault!r}")


def from_env(environ=None):
    """The plan configured in ``SHALLOWSPEED_FAULTS`` (empty when unset)."""
    return FaultPlan.parse((environ or os.environ).get(ENV_VAR, ""))


def make_plan(faults):
    """Normalize the ``faults=`` argument surface: None -> the env plan,
    a spec string -> parsed, a FaultPlan -> itself."""
    if faults is None:
        return from_env()
    if isinstance(faults, FaultPlan):
        return faults
    return FaultPlan.parse(faults)


def poison_nan(params):
    """The ``nan`` injection body: return ``params`` with one element of the
    first weight matrix set to NaN (host-side tree surgery — the poisoned
    value flows into the next step's forward, so that step's loss and every
    gradient behind it are NaN). Works on both layouts' param trees."""
    import jax
    import jax.numpy as jnp

    poisoned = [False]

    def poison(x):
        if poisoned[0] or not hasattr(x, "shape") or x.ndim < 1 or x.size == 0:
            return x
        poisoned[0] = True
        flat = jnp.ravel(jnp.asarray(x)).at[0].set(jnp.nan)
        return flat.reshape(x.shape).astype(x.dtype)

    out = jax.tree.map(poison, params)
    if not poisoned[0]:
        raise ValueError("no array leaf to poison in params")
    return out


def poison_bitflip(params):
    """The ``flip`` injection body: return ``params`` with the LOWEST
    mantissa bit of flat element 0 of the first weight leaf XOR-flipped —
    the same deterministic anchor ``poison_nan`` uses (global layer 0's W
    on every layout: both the sequential stage list and the stacked slot
    dict visit that block first), so the divergence CLI's attribution can
    be asserted against a known (step, layer, tensor). A 1-ulp flip stays
    finite, which is the point: nothing but the digest stream sees it."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    flipped = [False]

    def flip(x):
        if flipped[0] or not hasattr(x, "shape") or x.ndim < 1 or x.size == 0:
            return x
        flipped[0] = True
        flat = jnp.ravel(jnp.asarray(x))
        bits = lax.bitcast_convert_type(
            flat[0].astype(jnp.float32), jnp.uint32
        ) ^ jnp.uint32(1)
        new0 = lax.bitcast_convert_type(bits, jnp.float32)
        return flat.at[0].set(new0.astype(flat.dtype)).reshape(x.shape)

    out = jax.tree.map(flip, params)
    if not flipped[0]:
        raise ValueError("no array leaf to bit-flip in params")
    return out


def corrupt_buffer(arrays, nbytes=4, seed=0):
    """The ``corrupt@save=N`` injection body: flip ``nbytes`` bytes in the
    first (name-sorted) array of an IN-FLIGHT snapshot buffer — in place,
    AFTER the content checksum was stamped into the metadata, so the file
    the writer renames into place can never verify. The on-disk mirror of
    ``corrupt_checkpoint_bytes``, applied one stage earlier: it produces a
    rename-visible file that ``find_latest_good`` must skip, which is
    exactly the fallback path the chaos harness needs to exercise without
    racing the writer. Deterministic given ``seed``; returns the flipped
    byte offsets (within the chosen array) for test assertions."""
    names = sorted(n for n in arrays if n != "meta")
    if not names:
        raise ValueError("no array to corrupt in the in-flight buffer")
    target = arrays[names[0]]
    # explicit writable copy: host snapshots come off jax.device_get as
    # read-only views, and the corruption must land in the buffer the
    # writer will serialize, not raise out of the injection
    flat = np.array(target, copy=True).view(np.uint8).reshape(-1)
    if flat.size == 0:
        raise ValueError(f"array {names[0]!r} is empty — nothing to corrupt")
    rng = np.random.RandomState(seed)
    offsets = sorted(
        int(o)
        for o in rng.choice(flat.size, size=min(nbytes, flat.size),
                            replace=False)
    )
    for off in offsets:
        flat[off] ^= 0xFF
    arrays[names[0]] = flat.view(target.dtype).reshape(target.shape)
    return offsets


def corrupt_checkpoint_bytes(path, nbytes=16, seed=0):
    """Deterministically flip ``nbytes`` bytes in the middle of ``path`` —
    past the zip local-file header so the file still LOOKS like a .npz and
    only the content checksum (or the array parse) can catch it. Returns
    the byte offsets touched (for test assertions)."""
    path = os.fspath(path)
    size = os.path.getsize(path)
    if size == 0:
        raise ValueError(f"{path} is empty — nothing to corrupt")
    rng = np.random.RandomState(seed)
    # keep clear of the first 64 bytes (zip magic) when the file allows it
    lo = min(64, size - 1)
    offsets = sorted(
        int(o) for o in rng.choice(range(lo, size), size=min(nbytes, size - lo),
                                   replace=False)
    )
    with open(path, "r+b") as f:
        for off in offsets:
            f.seek(off)
            b = f.read(1)
            f.seek(off)
            f.write(bytes([b[0] ^ 0xFF]))
    return offsets
