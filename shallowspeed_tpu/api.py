"""High-level programmatic API: one object that wires the whole framework.

The reference's user assembles communicators, model, dataset, optimizer and
Worker by hand in train.py (train.py:87-129); here the same wiring is a
library object, so notebooks/tests/benchmarks get everything the CLI does:

    from shallowspeed_tpu.api import TrainingSession

    run = TrainingSession(dp=2, pp=4, schedule="gpipe", data_dir="data/mnist_784")
    for _ in range(20):
        loss = run.train_epoch()
        print(run.epoch, loss, run.accuracy())
    run.save("ck.npz")

Layouts are uniform: dp=pp=1 uses the fast sequential jitted path, anything
else the SPMD pipeline executor — same weights either way (tested layout
equivalence).
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from shallowspeed_tpu import model as Mo
from shallowspeed_tpu import schedules as S
from shallowspeed_tpu import trainer, utils
from shallowspeed_tpu.checkpoint import load_checkpoint, save_checkpoint
from shallowspeed_tpu.data import Dataset, default_data_dir
from shallowspeed_tpu.optimizer import SGD
from shallowspeed_tpu.parallel import executor as E
from shallowspeed_tpu.parallel import lower_schedule, make_mesh

# The reference's canonical training configuration (train.py:56-59,98,107) —
# the single source of truth for every benchmark script in this repo.
FLAGSHIP_SIZES = (784, 128, 127, 126, 125, 124, 123, 10)
FLAGSHIP_BATCH = 128
FLAGSHIP_MUBATCHES = 4
FLAGSHIP_LR = 0.006

_PRECISIONS = {
    "highest": lax.Precision.HIGHEST,
    "default": lax.Precision.DEFAULT,
}


class TrainingSession:
    """End-to-end training run: data + model + layout + optimizer + eval."""

    def __init__(
        self,
        sizes=FLAGSHIP_SIZES,
        dp=1,
        pp=1,
        schedule="gpipe",
        global_batch_size=128,
        mubatches=4,
        lr=0.006,
        precision="highest",
        data_dir=None,
        resume=None,
        devices=None,
        fuse_mubatches=False,
    ):
        if global_batch_size % dp != 0:
            raise ValueError("global batch size must be divisible by dp")
        local_batch = global_batch_size // dp
        if local_batch % mubatches != 0:
            raise ValueError("mubatches must divide the local batch")
        self.dp, self.pp = dp, pp
        self.B, self.M = global_batch_size, mubatches
        self.schedule = schedule
        if precision not in _PRECISIONS:
            raise ValueError(
                f"precision must be one of {sorted(_PRECISIONS)}, got {precision!r}"
            )
        if schedule not in S.SCHEDULES:
            raise ValueError(
                f"schedule must be one of {sorted(S.SCHEDULES)}, got {schedule!r}"
            )
        self.precision = _PRECISIONS[precision]
        if fuse_mubatches and not (dp == 1 and pp == 1):
            raise ValueError(
                "fuse_mubatches applies to the sequential path only; in the "
                "pipeline executor microbatches are semantic (they ARE the "
                "pipeline's unit of work)"
            )
        self.epoch = 0

        data_dir = data_dir or default_data_dir()
        self._train_ds = Dataset(data_dir, self.B, mubatch_size=local_batch // mubatches)
        self._train_ds.load(0, 1)
        # global_batch_size=1 so drop-last keeps EVERY validation sample (the
        # reference's val loader silently drops the tail to a batch multiple;
        # our accuracy() pads the ragged tail chunk instead)
        self._val = Dataset(data_dir, 1, mubatch_size=1, validation=True)
        self._val.load(0, 1)
        self._vx = jnp.asarray(self._val.input_X)
        self._vy = jnp.asarray(self._val.target_y)

        nb = self._train_ds.get_num_batches()
        if nb == 0:
            raise ValueError(
                f"training split has {self._train_ds.raw_len} samples — fewer "
                f"than one global batch of {self.B}"
            )
        Xb, Yb = self._train_ds.epoch_arrays()
        self._X = jnp.asarray(Xb.reshape(nb, self.B, Xb.shape[-1]))
        self._Y = jnp.asarray(Yb.reshape(nb, self.B, Yb.shape[-1]))
        self.batches_per_epoch = nb

        self.spec = Mo.make_model_spec(sizes, pp, self.B)
        opt = SGD(lr)
        self._sequential = dp == 1 and pp == 1

        if resume is not None:
            host_params, loaded_spec, meta = load_checkpoint(resume, pp, self.B)
            if tuple(loaded_spec.sizes) != tuple(self.spec.sizes):
                raise ValueError(
                    f"checkpoint sizes {loaded_spec.sizes} do not match the "
                    f"requested model sizes {self.spec.sizes}"
                )
            self.spec = loaded_spec
            self.epoch = meta["epoch"] + 1
        else:
            host_params = Mo.init_model(self.spec)

        if self._sequential:
            self._params = jax.tree.map(jnp.asarray, host_params)
            self._opt_state = ()
            self._epoch_fn = trainer.make_train_epoch(
                self.spec, opt, precision=self.precision,
                fuse_mubatches=fuse_mubatches,
            )
            self._predict = trainer.make_predict(self.spec, precision=self.precision)
            self._Xe = self._X.reshape(nb, self.M, self.B // self.M, -1)
            self._Ye = self._Y.reshape(nb, self.M, self.B // self.M, -1)
            self._X = self._Y = None  # the microbatched views are the only users
        else:
            self.mesh = make_mesh(dp, pp, devices)
            prog = lower_schedule(S.SCHEDULES[schedule], mubatches, pp)
            eval_prog = lower_schedule(S.InferenceSchedule, 1, pp, training=False)
            self._stacked, self._flags = E.put_stacked(
                *E.stack_params(host_params, self.spec), self.mesh
            )
            self._opt_state = opt.init(self._stacked)
            self._epoch_fn = E.make_pipeline_epoch(
                self.mesh, self.spec, prog, local_batch // mubatches, opt,
                precision=self.precision,
            )
            self._eval_step = E.make_pipeline_step(
                self.mesh, self.spec, eval_prog, self.B // dp, precision=self.precision
            )

    # -- training -----------------------------------------------------------

    def train_epoch(self) -> float:
        """One epoch over the training shard; returns the mean batch training
        loss (same definition on both layouts: global-batch-scaled MSE of each
        batch under its pre-update params, averaged over the epoch)."""
        if self._sequential:
            self._params, self._opt_state, mean_loss = self._epoch_fn(
                self._params, self._opt_state, self._Xe, self._Ye
            )
        else:
            self._stacked, self._opt_state, mean_loss = self._epoch_fn(
                self._stacked, self._flags, self._opt_state, self._X, self._Y
            )
        self.epoch += 1
        return float(mean_loss)

    # -- evaluation ---------------------------------------------------------

    def accuracy(self) -> float:
        """Argmax accuracy over the full validation split."""
        if self._sequential:
            return trainer.accuracy(self._predict, self._params, self._vx, self._vy)
        out_dim = self.spec.out_dim
        correct = total = 0
        for i in range(0, len(self._vx), self.B):
            xb, yb = self._vx[i : i + self.B], self._vy[i : i + self.B]
            n_valid = xb.shape[0]
            if n_valid < self.B:
                xb = jnp.pad(xb, ((0, self.B - n_valid), (0, 0)))
            preds = self._eval_step(self._stacked, self._flags, xb)[:n_valid]
            correct += int(
                (jnp.argmax(preds[:, :out_dim], 1) == jnp.argmax(yb, 1)).sum()
            )
            total += n_valid
        return correct / max(total, 1)

    # -- state --------------------------------------------------------------

    def params(self):
        """Logical per-stage params (host numpy), layout-independent order."""
        if self._sequential:
            return jax.device_get(self._params)
        return E.unstack_params(self._stacked, self.spec)

    def model_hash(self) -> str:
        return utils.model_hash(self.params())

    def assert_replicas_in_sync(self):
        if not self._sequential:
            utils.assert_dp_replicas_in_sync(self._stacked)

    def save(self, path):
        save_checkpoint(path, self.params(), self.spec, self.epoch - 1)
