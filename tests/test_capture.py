"""tpu_capture.main() plumbing test — the capture script runs at most once
per chip-claim window (the tunnel wedges for hours between them), so a
signature mismatch or key error anywhere in its phase sequence would burn
the round's only hardware window. This runs the REAL main() with every
heavy measurement stubbed: phase ordering, checkpoint-after-every-phase,
result-key assembly and the rename-into-place contract are exercised for
real; only the timing/convergence/trace work is faked.
"""

import json
import sys
from pathlib import Path

import pytest

ROOT = Path(__file__).resolve().parent.parent


@pytest.fixture()
def capture_mod():
    added = []
    for p in (str(ROOT), str(ROOT / "scripts")):
        if p not in sys.path:
            sys.path.insert(0, p)
            added.append(p)
    import tpu_capture

    yield tpu_capture
    for p in added:
        sys.path.remove(p)


def test_capture_main_plumbing(tmp_path, monkeypatch, capture_mod):
    tc = capture_mod
    import bench
    import bench_tpu_matrix

    eq = {"max_abs_param_diff": 0.0, "loss_abs_diff": 0.0, "bitwise_equal": True}
    monkeypatch.setattr(
        bench, "_ensure_responsive_backend",
        lambda *a, **k: ("", {"probes": [{"outcome": "ok", "seconds": 1.0}]}),
    )
    monkeypatch.setattr(bench, "numpy_baseline_sps", lambda n_batches=40: 50.0)
    monkeypatch.setattr(
        tc, "headline_sweep",
        lambda unrolls, trials, precision="highest": (
            {f"unroll={u}": 100.0 * u for u in unrolls}, {}
        ),
    )
    monkeypatch.setattr(
        tc, "megakernel_cells",
        lambda nb, trials: (
            {"fused+default+xla": 1.0, "fused+default+mega": 2.0,
             "fused+default+epoch": 3.0},
            {},
            {"mega": eq, "epoch": eq},
        ),
    )
    monkeypatch.setattr(
        tc, "convergence_run",
        lambda d, e: {"epochs": e, "final_val_accuracy": 0.99},
    )
    monkeypatch.setattr(
        tc, "megakernel_convergence",
        lambda d, e, variant="megakernel": {"variant": variant, "epochs": e},
    )
    monkeypatch.setattr(
        tc, "profile_one_epoch", lambda d, t: {"dir": str(t), "n_files": 1}
    )
    monkeypatch.setattr(
        tc, "profile_headline_epoch", lambda t: {"dir": str(t), "n_files": 1}
    )
    monkeypatch.setattr(
        bench_tpu_matrix, "run_matrix",
        lambda cells, nb, trials: {("fused", "default", "xla"): 123.0},
    )
    monkeypatch.setattr(
        tc, "executor_backend_cells",
        lambda nb, trials: ({"executor+default+xla": 1.0}, {}, eq),
    )
    monkeypatch.setattr(
        tc, "executor_backend_api_path",
        lambda d, epochs=2: {"hashes_match": True, "losses_match": True},
    )
    monkeypatch.setattr(
        tc, "adam_kernel_cells",
        lambda nb, trials: (
            {"adam+default+xla": 1.0}, {}, {"mega": eq, "epoch": eq}
        ),
    )
    monkeypatch.setattr(
        tc, "adam_epoch_kernel_convergence",
        lambda d: {"precision": "default", "loss": 0.1,
                   "val_accuracy": 0.99, "model_hash": "f" * 40},
    )

    out = tmp_path / "CAP.json"
    data_dir = tmp_path / "data"
    data_dir.mkdir()  # exists -> the prepare_data subprocess is skipped
    monkeypatch.setattr(
        sys, "argv",
        ["tpu_capture.py", "--quick", "--out", str(out),
         "--data-dir", str(data_dir)],
    )
    tc.main()

    assert out.is_file() and not Path(str(out) + ".partial").exists()
    result = json.loads(out.read_text())
    for key in (
        "info", "numpy_baseline_sps", "headline_sweep_default_precision",
        "headline_best_sps", "vs_baseline", "headline_sweep_fp32_highest",
        "megakernel_cells", "megakernel_onchip_equality", "convergence",
        "megakernel_convergence", "epoch_kernel_convergence", "trace",
        "trace_headline", "matrix", "matrix_full_epoch_fused",
        "executor_kernel_backends", "executor_onchip_equality",
        "executor_api_path", "adam_kernel_cells", "adam_onchip_equality",
        "adam_epoch_kernel_one_epoch", "completed_at",
    ):
        assert key in result, f"capture artifact missing {key!r}"
    assert result["epoch_kernel_convergence"]["variant"] == "epoch_kernel"
    assert result["megakernel_onchip_equality"]["epoch"]["bitwise_equal"]


def test_capture_aborts_cleanly_on_wedged_tunnel(tmp_path, monkeypatch, capture_mod):
    """A wedged probe must exit 3 BEFORE touching the device or writing
    anything — the claim stays free for a retry."""
    tc = capture_mod
    import bench

    monkeypatch.setattr(
        bench, "_ensure_responsive_backend",
        lambda *a, **k: ("_CPU_FALLBACK_TUNNEL_UNRESPONSIVE",
                         {"probes": [{"outcome": "timeout", "seconds": 150.0}]}),
    )
    out = tmp_path / "CAP.json"
    monkeypatch.setattr(sys, "argv", ["tpu_capture.py", "--out", str(out)])
    with pytest.raises(SystemExit) as exc:
        tc.main()
    assert exc.value.code == 3
    assert not out.exists() and not Path(str(out) + ".partial").exists()
