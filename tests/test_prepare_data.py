"""Data-prep tool tests: offline source chain, preprocessing, determinism."""

import numpy as np
import pytest

import prepare_data
from shallowspeed_tpu.data import Dataset


def test_synthetic_source_end_to_end(tmp_path):
    used = prepare_data.prepare(tmp_path / "d", source="synthetic")
    assert used == "synthetic"
    ds = Dataset(tmp_path / "d", 128, 32)
    ds.load(0, 1)
    assert ds.input_X.shape[1] == 784
    assert ds.target_y.shape[1] == 10
    # mean-centered features (reference preprocessing, download_dataset.py:12-13)
    assert abs(float(ds.input_X.mean())) < 0.05
    # one-hot targets
    np.testing.assert_allclose(ds.target_y.sum(axis=1), 1.0)


def test_digits_source_shapes(tmp_path):
    pytest.importorskip("sklearn")
    used = prepare_data.prepare(tmp_path / "d", source="digits")
    assert used == "digits"
    x = np.load(tmp_path / "d" / "x_train.npy")
    y = np.load(tmp_path / "d" / "y_train.npy")
    assert x.shape[1] == 784 and y.shape[1] == 10
    assert len(x) > 40000  # replicated to MNIST-like scale


def test_auto_falls_back_when_network_source_fails(tmp_path, monkeypatch):
    # deterministic offline simulation: the network source raises, the chain
    # lands on the next offline source (no real fetch, no retry stalls)
    def boom():
        raise OSError("no egress")

    monkeypatch.setattr(prepare_data, "_load_openml", boom)
    used = prepare_data.prepare(tmp_path / "d", source="auto")
    assert used in ("digits", "synthetic")


def test_split_is_deterministic_and_disjoint():
    x = np.arange(100, dtype=np.float32).reshape(100, 1)
    y = np.eye(10, dtype=np.float32)[np.arange(100) % 10]
    a = prepare_data._split(x, y)
    b = prepare_data._split(x, y)
    np.testing.assert_array_equal(a[0], b[0])
    assert len(a[1]) == 15  # 15% validation
    assert len(a[0]) + len(a[1]) == 100
    merged = np.sort(np.concatenate([a[0], a[1]]).reshape(-1))
    np.testing.assert_array_equal(merged, np.arange(100, dtype=np.float32))


def test_split_matches_reference_sklearn_permutation():
    """With sklearn present (it is, in this image), _split must reproduce the
    REFERENCE's exact validation membership: train_test_split(test_size=0.15,
    random_state=42) — /root/reference/download_dataset.py:16-18 — so
    cross-repo accuracy comparisons share sample-for-sample val sets."""
    from sklearn.model_selection import train_test_split

    x = np.arange(200, dtype=np.float32).reshape(200, 1)
    y = np.eye(10, dtype=np.float32)[np.arange(200) % 10]
    xt, xv, yt, yv = prepare_data._split(x, y)
    xt_r, xv_r, yt_r, yv_r = train_test_split(x, y, test_size=0.15, random_state=42)
    np.testing.assert_array_equal(xt, xt_r)
    np.testing.assert_array_equal(xv, xv_r)
    np.testing.assert_array_equal(yt, yt_r)
    np.testing.assert_array_equal(yv, yv_r)
