"""Optimizers over parameter pytrees, applied on-device inside the jitted step.

Capability parity: the reference ships plain stateless SGD
(/root/reference/shallowspeed/optimizer.py:4-13, ``param.data -= lr * grad``).
Here the update is a pytree map that XLA fuses into the training step — no
host round-trip per parameter.
"""

import dataclasses

import jax


@dataclasses.dataclass(frozen=True)
class SGD:
    """Stateless SGD. ``apply`` returns new params; grads are SUMS over the
    global batch (the loss is pre-scaled by the global batch size), so no
    averaging happens here — same ledger as the reference."""

    lr: float

    def init(self, params):
        return ()  # no optimizer state

    def apply(self, params, grads, state=()):
        new = jax.tree.map(lambda p, g: p - self.lr * g, params, grads)
        return new, state


@dataclasses.dataclass(frozen=True)
class MomentumSGD:
    """Heavy-ball SGD: v <- mu*v + g; p <- p - lr*v.

    The reference ships only plain SGD; this exists to exercise (and prove)
    the optimizer-state plumbing: state is a pytree mirroring the params, it
    threads through the sequential trainer AND the pipeline executor
    identically, so stateful optimizers keep the distributed == sequential
    invariant (tests/test_optimizer_state.py)."""

    lr: float
    momentum: float = 0.9

    def init(self, params):
        import jax.numpy as jnp

        return jax.tree.map(lambda p: jnp.zeros(p.shape, p.dtype), params)

    def apply(self, params, grads, state):
        velocity = jax.tree.map(lambda v, g: self.momentum * v + g, state, grads)
        new = jax.tree.map(lambda p, v: p - self.lr * v, params, velocity)
        return new, velocity


def is_stateless(opt) -> bool:
    """True iff the optimizer's state is the empty tuple (the stateless
    sentinel this package uses, e.g. SGD). The single source of truth for
    every call site that branches on optimizer statefulness."""
    import numpy as np

    probe = opt.init(np.zeros((1,), np.float32))
    return isinstance(probe, tuple) and probe == ()


def make_optimizer(name: str, lr: float, momentum: float = 0.9):
    """Optimizer registry for the CLI/API surface (reference hardwires SGD,
    train.py:107)."""
    if name == "sgd":
        return SGD(lr)
    if name == "momentum":
        return MomentumSGD(lr, momentum)
    raise ValueError(f"optimizer must be one of ['momentum', 'sgd'], got {name!r}")
