"""Capacity scoreboard tests (ISSUE 18): diurnal replay determinism,
the offline oracle on hand-computed synthetic traces, the shared
SLO-breach predicate, the autoscale policy against a fake fleet on an
injected clock, and the watch/report capacity surfaces.

Everything here is fleet-free and fast (tier-1): the driven-leg
integration lives in ``make replay-smoke``.
"""

import inspect
import json

import numpy as np
import pytest

from shallowspeed_tpu.observability import slo
from shallowspeed_tpu.observability.metrics import json_safe
from shallowspeed_tpu.serving import bench_replay
from shallowspeed_tpu.serving.autoscaler import AutoscalePolicy
from shallowspeed_tpu.serving.bench_serving import find_knee
from shallowspeed_tpu.serving.loadgen import run_open_loop
from shallowspeed_tpu.serving.replay import diurnal_rate, diurnal_trace


# -- trace determinism -------------------------------------------------------


def test_diurnal_trace_deterministic():
    """Same seed -> byte-identical arrival schedule and rate trace;
    a different seed -> a different trace."""
    a = diurnal_trace(day_s=30.0, base_rps=5.0, peak_rps=20.0, seed=7)
    b = diurnal_trace(day_s=30.0, base_rps=5.0, peak_rps=20.0, seed=7)
    assert np.array_equal(a["arrivals"], b["arrivals"])
    assert a["arrivals"].tobytes() == b["arrivals"].tobytes()
    assert json.dumps(a["buckets"]) == json.dumps(b["buckets"])
    assert a["config"] == b["config"]
    c = diurnal_trace(day_s=30.0, base_rps=5.0, peak_rps=20.0, seed=8)
    assert not np.array_equal(a["arrivals"], c["arrivals"])


def test_diurnal_trace_shape():
    """Arrivals are sorted inside [0, day_s); the bucket counts account
    for every arrival; the flash-crowd spike raises the analytic rate
    above the plain diurnal curve; the thinning envelope dominates."""
    tr = diurnal_trace(
        day_s=60.0, base_rps=4.0, peak_rps=16.0, seed=3,
        n_spikes=1, spike_mult=3.0,
    )
    arr = tr["arrivals"]
    assert arr.shape[0] == tr["config"]["n_arrivals"] > 0
    assert np.all(np.diff(arr) >= 0)
    assert arr[0] >= 0.0 and arr[-1] < 60.0
    assert sum(b["arrivals"] for b in tr["buckets"]) == arr.shape[0]
    cfg = tr["config"]
    (spike,) = cfg["spikes"]
    mid = spike["start"] + spike["duration"] / 2.0
    with_spike = diurnal_rate(mid, 60.0, 4.0, 16.0, cfg["spikes"])
    without = diurnal_rate(mid, 60.0, 4.0, 16.0, ())
    assert with_spike == pytest.approx(3.0 * without)
    for b in tr["buckets"]:
        assert b["rate_rps"] <= cfg["rate_max"] + 1e-9
    assert cfg["compression"] == pytest.approx(86400.0 / 60.0)


# -- the oracle and the scorers (hand-computed) ------------------------------


def _flat_buckets(rate, n=6, width=10.0):
    return [
        {
            "t0": i * width,
            "t1": (i + 1) * width,
            "rate_rps": rate,
            "arrivals": int(rate * width),
            "offered_rps": rate,
        }
        for i in range(n)
    ]


def test_oracle_constant_trace():
    """Constant demand 25 rps on a 10-rps knee: ceil(25/10) = 3 replicas
    every bucket; with max 3 the day is feasible (0 violation minutes,
    3 x 60s = 180 replica-seconds); with max 2 EVERY bucket is
    infeasible — exactly 1.0 violation minutes over the 60s trace."""
    buckets = _flat_buckets(25.0)
    oracle = bench_replay.oracle_schedule(buckets, 10.0, max_replicas=3)
    assert [b["replicas"] for b in oracle] == [3] * 6
    assert not any(b["infeasible"] for b in oracle)
    score = bench_replay.oracle_score(oracle)
    assert score["violation_minutes"] == 0.0
    assert score["replica_s"] == pytest.approx(180.0)
    clamped = bench_replay.oracle_schedule(buckets, 10.0, max_replicas=2)
    assert all(b["infeasible"] for b in clamped)
    assert bench_replay.oracle_score(clamped)["violation_minutes"] == (
        pytest.approx(1.0)
    )


def test_oracle_step_trace_and_waste():
    """Step trace (two quiet buckets at 5 rps, four busy at 25) on a
    10-rps knee: oracle = [1,1,3,3,3,3]. A static fleet of 3 wastes
    exactly 2 replicas x 20 quiet seconds = 40 replica-seconds; an
    autoscaled timeline that steps 1 -> 3 at t=20 wastes nothing."""
    buckets = _flat_buckets(5.0, n=2) + [
        {**b, "t0": b["t0"] + 20.0, "t1": b["t1"] + 20.0}
        for b in _flat_buckets(25.0, n=4)
    ]
    oracle = bench_replay.oracle_schedule(buckets, 10.0, max_replicas=3)
    assert [b["replicas"] for b in oracle] == [1, 1, 3, 3, 3, 3]
    static = [(0.0, 3)]
    assert bench_replay.replica_seconds(static, 60.0) == pytest.approx(180.0)
    assert bench_replay.wasted_replica_seconds(static, oracle) == (
        pytest.approx(40.0)
    )
    scaled = [(0.0, 1), (20.0, 3)]
    assert bench_replay.replica_seconds(scaled, 60.0) == pytest.approx(140.0)
    assert bench_replay.wasted_replica_seconds(scaled, oracle) == (
        pytest.approx(0.0)
    )


def test_oracle_spike_trace():
    """One spike bucket beyond max capacity: only ITS width is
    infeasible violation time; the clamp never under-runs min_replicas."""
    buckets = _flat_buckets(8.0, n=5)
    buckets[2] = {**buckets[2], "rate_rps": 55.0, "offered_rps": 55.0}
    oracle = bench_replay.oracle_schedule(
        buckets, 10.0, min_replicas=2, max_replicas=4
    )
    assert [b["replicas"] for b in oracle] == [2, 2, 4, 2, 2]
    assert [b["infeasible"] for b in oracle] == [
        False, False, True, False, False,
    ]
    score = bench_replay.oracle_score(oracle, compression=60.0)
    assert score["violation_s"] == pytest.approx(10.0)
    assert score["violation_minutes_modeled"] == pytest.approx(10.0)


def test_score_samples_charges_shed_load():
    """The violation scorer and find_knee share ONE breach definition:
    a bucket whose p99 beats the SLO but whose ok-rate fell under the
    achieved fraction still breaches (shed load is charged, not
    hidden), and the reason string is slo_breach's own."""
    buckets = _flat_buckets(10.0, n=2)
    # bucket 0: all 100 requests ok and fast -> no breach; bucket 1:
    # only 50 of 100 ok (rest dropped) -> achieved 5 < 0.9 x 10
    samples = [
        {"arrival": 0.5 + i * 0.05, "verdict": "ok", "latency_s": 0.005}
        for i in range(100)
    ]
    samples += [
        {
            "arrival": 10.5 + i * 0.05,
            "verdict": "ok" if i < 50 else "dropped",
            "latency_s": 0.005 if i < 50 else None,
        }
        for i in range(100)
    ]
    out = bench_replay.score_samples(samples, buckets, slo_ms=100.0)
    assert out["buckets"][0]["breach"] is None
    assert out["buckets"][1]["breach"] == "achieved_below_offered"
    assert out["violation_s"] == pytest.approx(10.0)
    assert out["verdicts"] == {"ok": 150, "dropped": 50}


def test_breach_predicate_is_shared():
    """Satellite 1: find_knee's default achieved fraction IS the slo
    module's, and the knee it returns is the first row slo_breach
    flags — the scoreboard and the knee can never disagree."""
    sig = inspect.signature(find_knee)
    assert (
        sig.parameters["achieved_fraction"].default
        is slo.SLO_ACHIEVED_FRACTION
    )
    assert slo.slo_breach(0.2, 10.0, 10.0, slo_ms=100.0) == "p99_above_slo"
    assert slo.slo_breach(0.01, 10.0, 8.0, slo_ms=100.0) == (
        "achieved_below_offered"
    )
    assert slo.slo_breach(0.01, 10.0, 9.95, slo_ms=100.0) is None
    # abstention: no p99 evidence only breaches through achieved; no
    # evidence at all is "no breach", never a guess
    assert slo.slo_breach(None, 10.0, 5.0, slo_ms=100.0) == (
        "achieved_below_offered"
    )
    assert slo.slo_breach(None, 0.0, None, slo_ms=None) is None
    rows = [
        {"offered_rps": 10.0, "p99_latency_s": 0.01, "achieved_rps": 10.0},
        {"offered_rps": 20.0, "p99_latency_s": 0.01, "achieved_rps": 17.0},
        {"offered_rps": 40.0, "p99_latency_s": 0.30, "achieved_rps": 39.0},
    ]
    assert find_knee(rows, slo_ms=100.0) == 20.0
    flagged = [
        r["offered_rps"]
        for r in rows
        if slo.slo_breach(
            r["p99_latency_s"], r["offered_rps"], r["achieved_rps"], 100.0
        )
    ]
    assert flagged[0] == find_knee(rows, slo_ms=100.0)


def test_scoreboard_record_deterministic():
    """Same trace + samples + timelines (an injected clock's numbers)
    -> the SAME scoreboard record, byte for byte: nothing inside the
    assembly reads a wall clock."""
    trace = diurnal_trace(day_s=30.0, base_rps=5.0, peak_rps=20.0, seed=1)
    oracle = bench_replay.oracle_schedule(
        trace["buckets"], 10.0, max_replicas=3
    )
    samples = [
        {"arrival": float(t), "verdict": "ok", "latency_s": 0.004}
        for t in trace["arrivals"][:50]
    ]

    def build():
        legs = {}
        for leg, timeline in (
            ("static", [(0.0, 2)]),
            ("autoscaled", [(0.0, 1), (9.0, 2), (22.0, 1)]),
            ("chaos", [(0.0, 1), (9.0, 2)]),
        ):
            legs[leg] = {
                **bench_replay.score_leg(
                    samples, trace["buckets"], 100.0, timeline, oracle,
                    compression=trace["config"]["compression"],
                ),
                "flaps": 0,
            }
        rec = bench_replay.scoreboard_record(
            trace, 10.0, 100.0, legs, oracle,
            config={"seed": 1}, caveats=["injected clock"],
        )
        return json.dumps(json_safe(rec), sort_keys=True, allow_nan=False)

    assert build() == build()
    rec = json.loads(build())
    assert rec["bench"] == "autoscale_scoreboard"
    assert "chaos_zero_flaps" in rec["verdicts"]
    assert "autoscaled_beats_static_violation_minutes" in rec["verdicts"]


# -- the policy on a fake fleet (injected clock) -----------------------------


class FakeFleet:
    """status()/scale_up/scale_down shaped like ServingFleet, fully
    synchronous and clockless — the policy's decisions are driven by
    the `now` values the test passes to tick()."""

    def __init__(self, n_ready=1):
        self._next = 0
        self.states = {}
        for _ in range(n_ready):
            self._add("ready")
        self.queue = 0
        self.dead = 0
        self.admitted = None
        self.window_end = None
        self.alerts_active = {}
        self.degraded = False
        self.scale_ups = []
        self.scale_downs = []
        self.gate = None

    def _add(self, state):
        rid = self._next
        self._next += 1
        self.states[rid] = state
        return rid

    def set_admission_gate(self, fn):
        self.gate = fn

    def scale_up(self, checkpoint=None, wait_ready=True):
        self.scale_ups.append(wait_ready)
        return self._add("starting")

    def scale_down(self, replica_id=None):
        rid = max(r for r, s in self.states.items() if s == "ready")
        self.states[rid] = "draining"
        self.scale_downs.append(rid)
        return rid

    def ready_all(self):
        for rid, s in self.states.items():
            if s == "starting":
                self.states[rid] = "ready"

    def kill_one(self):
        rid = max(r for r, s in self.states.items() if s == "ready")
        self.states[rid] = "dead"
        self.dead += 1

    def status(self):
        ready = sum(1 for s in self.states.values() if s == "ready")
        last = None
        if self.admitted is not None:
            last = {
                "window_end": self.window_end,
                "rates": {"admitted": {"rate": self.admitted}},
            }
        return {
            "queue_depth": self.queue,
            "inflight": 0,
            "degraded": self.degraded,
            "replicas_target": ready,
            "replicas_ready": ready,
            "replicas_dead": self.dead,
            "gate_dropped": 0,
            "per_replica": {
                rid: {
                    "state": s,
                    "queue_depth": 0,
                    "degraded": False,
                    "inflight": 0,
                    "last_health": None,
                }
                for rid, s in self.states.items()
            },
            "alerts_active": dict(self.alerts_active),
            "telemetry": {"rollup": {"last_window": last}, "alerts": {}},
        }


def _policy(fleet, **kw):
    kw.setdefault("knee_rps", 10.0)
    kw.setdefault("max_replicas", 3)
    p = AutoscalePolicy(**kw)
    p.attach(fleet)
    return p


def test_policy_scale_out_on_knee_edge():
    fleet = FakeFleet(n_ready=1)
    p = _policy(fleet)
    p.alert(
        {"name": "knee_proximity", "state": "firing", "value": 9.3,
         "threshold": 9.0, "reason": "admitted near knee"}
    )
    p.tick(1.0)
    assert fleet.scale_ups == [False]  # non-blocking growth
    d = p.decisions[-1]
    assert d["decision"] == "scale_out" and d["rule"] == "knee_proximity"
    assert d["direction"] == "out" and d["flap"] is False
    assert d["replicas_before"] == 1 and d["replicas_after"] == 2
    # a warming replica counts toward max: no runaway re-fire while it
    # warms, even long past the cooldown
    fleet.admitted = 9.5  # above 0.8 x knee x 1 ready
    p.tick(50.0)
    fleet.states[max(fleet.states)] = "ready"
    p.tick(60.0)  # 2 ready, admitted under 0.8 x knee x 2 -> no action
    assert fleet.scale_ups == [False]


def test_policy_scale_out_resolved_edge_ignored():
    fleet = FakeFleet(n_ready=1)
    p = _policy(fleet)
    p.alert({"name": "knee_proximity", "state": "resolved"})
    p.alert({"name": "error_burn", "state": "firing"})  # queue empty
    p.tick(1.0)
    assert fleet.scale_ups == []
    fleet.queue = 3  # burn concentrated in the fleet queue
    p.alert({"name": "error_burn", "state": "firing", "value": 8.0,
             "threshold": 6.0, "reason": "burn 8x"})
    p.tick(2.0)
    assert fleet.scale_ups == [False]
    assert p.decisions[-1]["rule"] == "error_burn"
    assert "fleet.queue" in p.decisions[-1]["reason"]


def test_policy_scale_in_hysteresis_and_flap_accounting():
    fleet = FakeFleet(n_ready=2)
    p = _policy(
        fleet, min_replicas=1, slack_hold_s=1.0, in_cooldown_s=2.0,
        out_cooldown_s=0.5, flap_window_s=30.0,
    )
    fleet.admitted = 3.0  # < 0.5 x knee x 1 remaining
    p.tick(0.0)
    p.tick(0.5)  # slack held 0.5s < 1.0 hold -> no action yet
    assert fleet.scale_downs == []
    p.tick(1.2)  # held >= 1.0s, no prior scale -> drain one
    assert len(fleet.scale_downs) == 1
    d = p.decisions[-1]
    assert d["decision"] == "scale_in" and d["direction"] == "in"
    assert d["flap"] is False
    # demand surges right back: the reversal inside the flap window is
    # counted — the accounting the chaos leg's zero-flap gate reads
    fleet.admitted = 9.5
    p.tick(3.5)
    assert fleet.scale_ups == [False]
    assert p.decisions[-1]["decision"] == "scale_out"
    assert p.decisions[-1]["flap"] is True and p.flaps == 1


def test_policy_slack_interrupted_resets_hold():
    fleet = FakeFleet(n_ready=2)
    p = _policy(fleet, min_replicas=1, slack_hold_s=1.0)
    fleet.admitted = 3.0
    p.tick(0.0)
    fleet.queue = 2  # backlog interrupts the slack streak
    p.tick(0.6)
    fleet.queue = 0
    p.tick(1.4)  # streak restarted at 1.4, not 1.4s held
    assert fleet.scale_downs == []
    p.tick(2.5)
    assert len(fleet.scale_downs) == 1


def test_policy_replacement_is_not_a_flap():
    fleet = FakeFleet(n_ready=2)
    p = _policy(fleet)
    fleet.kill_one()
    p.tick(1.0)
    assert fleet.scale_ups == [False]
    d = p.decisions[-1]
    assert d["decision"] == "replace" and d["direction"] == "hold"
    assert p.flaps == 0
    # the SAME death is never re-replaced on later ticks
    p.tick(2.0)
    assert fleet.scale_ups == [False]


def test_policy_backpressure_gate():
    fleet = FakeFleet(n_ready=1)
    p = _policy(fleet, warm_queue_budget=5)
    assert fleet.gate is not None and fleet.gate(fleet) is None
    fleet.scale_up(wait_ready=False)  # a replica warming...
    fleet.queue = 9  # ...and a backlog past the budget
    p.tick(1.0)
    assert p.decisions[-1]["decision"] == "backpressure_on"
    assert fleet.gate(fleet) == "backpressure_warming"
    fleet.queue = 2
    p.tick(2.0)
    assert p.decisions[-1]["decision"] == "backpressure_off"
    assert fleet.gate(fleet) is None


def test_policy_decisions_json_safe_and_require_knee():
    fleet = FakeFleet(n_ready=1)
    p = _policy(fleet)
    p.alert({"name": "knee_proximity", "state": "firing"})
    p.tick(1.0)
    json.dumps(json_safe(p.decisions), allow_nan=False)
    with pytest.raises(ValueError, match="knee"):
        AutoscalePolicy(knee_rps=None)
    with pytest.raises(RuntimeError, match="attach"):
        AutoscalePolicy(knee_rps=10.0).tick(0.0)


# -- the open-loop tick hook -------------------------------------------------


class _TickEngine:
    """Minimal engine for run_open_loop: injected clock advanced only by
    sleep and step, so tick cadence is fully deterministic."""

    def __init__(self):
        self.t = 0.0
        self.queue = []

    def clock(self):
        return self.t

    @property
    def queue_depth(self):
        return len(self.queue)

    def submit(self, x, deadline_ms=None, arrival_t=None):
        self.queue.append(x)

    def step(self):
        self.t += 0.001
        batch = list(self.queue)
        self.queue.clear()
        return batch


def test_run_open_loop_on_tick_caps_idle_sleep():
    eng = _TickEngine()
    ticks = []
    done = run_open_loop(
        eng,
        payloads=[1, 2],
        arrivals=[0.5, 1.0],
        sleep=lambda dt: setattr(eng, "t", eng.t + dt),
        on_tick=ticks.append,
        tick_s=0.05,
    )
    assert len(done) == 2
    assert len(ticks) >= 20  # ~1.0s of idle at <= 0.05s per sleep
    gaps = np.diff(ticks)
    assert gaps.max() <= 0.06  # idle sleeps capped at tick_s
    # without the hook, the driver sleeps straight to the next arrival
    eng2 = _TickEngine()
    sleeps = []
    run_open_loop(
        eng2,
        payloads=[1],
        arrivals=[0.5],
        sleep=lambda dt: (sleeps.append(dt),
                          setattr(eng2, "t", eng2.t + dt))[1],
    )
    assert sleeps and sleeps[0] == pytest.approx(0.5)


# -- watch + report capacity surfaces ----------------------------------------


def _autoscale_line(**over):
    rec = {
        "v": 13, "ts": 1.0, "kind": "autoscale", "name": "scale_out",
        "direction": "out", "rule": "knee_proximity", "t": 12.5,
        "replicas_before": 1, "replicas_after": 2, "replicas_ready": 1,
        "queue_depth": 4, "window_end": 12.0, "value": 9.3,
        "threshold": 9.0, "flap": False, "reason": "near knee",
        "leg": "autoscaled",
    }
    rec.update(over)
    return json.dumps(rec)


def test_watch_folds_autoscale(capsys):
    """Satellite 2: the live snapshot carries fleet size + the latest
    autoscale decision, as a pure fold of the bytes (same lines -> same
    snapshot, the --once/--follow parity object)."""
    from shallowspeed_tpu.observability.watch import WatchState

    def fold():
        st = WatchState()
        st.ingest_line(_autoscale_line())
        st.ingest_line(
            _autoscale_line(name="scale_in", direction="in", rule="poll",
                            t=40.0, replicas_before=2, replicas_after=1)
        )
        return st

    st = fold()
    snap = st.snapshot()
    assert snap["fleet"]["replicas"] == 1
    assert snap["fleet"]["autoscale_decisions"] == 2
    assert snap["fleet"]["last_autoscale"]["name"] == "scale_in"
    assert json.dumps(snap, sort_keys=True, default=str) == json.dumps(
        fold().snapshot(), sort_keys=True, default=str
    )
    text = st.render_text("x.jsonl", [])
    assert "fleet: 1 replica(s)" in text
    assert "scale_in" in text and "rule poll" in text
    # without a policy, fleet_health scale events still track size
    st2 = WatchState()
    st2.ingest_line(json.dumps(
        {"v": 13, "ts": 2.0, "kind": "fleet_health", "name": "scale_up",
         "replica_id": 2, "target": 3}
    ))
    assert st2.snapshot()["fleet"]["replicas"] == 3
    # an empty stream renders no fleet line and a None surface
    st3 = WatchState()
    assert st3.snapshot()["fleet"]["replicas"] is None
    assert "fleet:" not in st3.render_text("x.jsonl", [])


def test_report_capacity_section():
    from shallowspeed_tpu.observability.report import build_report, render

    records = [
        json.loads(_autoscale_line()),
        json.loads(_autoscale_line(
            name="replace", direction="hold", rule="poll", t=20.0,
            leg="chaos", replicas_before=2, replicas_after=2,
        )),
        {
            "v": 13, "ts": 3.0, "kind": "event", "name": "replay_trace",
            "seed": 0, "day_s": 90.0, "knee_rps": 10.0, "n_arrivals": 100,
            "compression": 960.0,
            "buckets": [
                {"t0": 0.0, "t1": 45.0, "rate_rps": 4.0,
                 "offered_rps": 4.2},
                {"t0": 45.0, "t1": 90.0, "rate_rps": 14.0,
                 "offered_rps": 13.8},
            ],
            "spikes": [{"start": 40.0, "duration": 9.0, "mult": 2.0}],
        },
        {
            "v": 13, "ts": 4.0, "kind": "event", "name": "replay_score",
            "leg": "autoscaled", "violation_s": 6.0,
            "violation_minutes_modeled": 96.0, "wasted_replica_s": 30.0,
            "wasted_replica_hours_modeled": 8.0, "flaps": 0,
        },
    ]
    report = build_report(records, source="replay.jsonl")
    cap = report["capacity"]
    assert cap["decisions"] == 2 and cap["flaps"] == 0
    assert set(cap["by_leg"]) == {"autoscaled", "chaos"}
    assert cap["trace"]["n_arrivals"] == 100
    assert cap["scores"][0]["leg"] == "autoscaled"
    text = render(report, "text")
    assert "capacity:" in text
    assert "offered load:" in text
    assert "flap count: 0" in text
    assert "scale_out (rule knee_proximity, 1→2" in text
    assert "score[autoscaled]" in text
    md = render(report, "md")
    assert "## Capacity" in md
    # a stream with no capacity records omits the section entirely
    empty = build_report([{"v": 13, "kind": "step", "ts": 1.0}], source="x")
    assert empty["capacity"] is None
    assert "capacity:" not in render(empty, "text")
