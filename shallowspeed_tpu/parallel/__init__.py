"""TPU execution layer: mesh construction, schedule lowering, SPMD executor.

The reference drives its pipeline MPMD-style — each MPI rank interprets a
different instruction stream against blocking Send/Recv
(/root/reference/shallowspeed/pipe.py:330-466). XLA/jit is SPMD: one traced
program for all devices. The bridge is this package:

- ``lowering``  compiles the per-stage instruction streams of any Schedule
                into a static *clock-tick program* (numpy tables) where every
                tick every stage runs the same jitted tick function and
                payloads move between neighbor stages via jax.lax.ppermute;
- ``mesh``      builds the (dp, pp[, tp]) jax.sharding.Mesh that replaces
                the reference's two MPI communicators (train.py:87-94) —
                the optional third axis is Megatron tensor parallelism;
- ``executor``  the shard_map + lax.scan runtime executing tick programs over
                padded stacked stage parameters, with jax.lax.psum as the DP
                gradient all-reduce and per-slot column/row tp shards when
                the mesh carries a tp axis.
"""

from shallowspeed_tpu.parallel.lowering import TickProgram, lower_schedule
from shallowspeed_tpu.parallel.mesh import make_mesh
