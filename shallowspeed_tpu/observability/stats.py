"""Shared summary statistics: the ONE percentile definition, and the ONE
serving-window definition.

Three consumers quote latency percentiles — the serving engine's
``stats()`` summary, the fleet's fleet-wide summary, and the report CLI's
killed-run fallback (recomputing p50/p99 from raw ``request`` records when
no summary landed). Before this module each carried its own
implementation; two of them agreed only by co-incidence of method
(np.percentile's default linear interpolation vs a hand-rolled
re-derivation of it), which is exactly the kind of duplicated definition
that lets a report and an engine summary disagree on the same data by one
ULP and flip an SLO verdict.

``percentile`` is now the single definition: ``np.percentile`` on float64
with its default (linear-interpolation) method — so every consumer is
EQUAL to ``np.percentile`` by construction, and the unit test pins that
equality rather than approximates it. ``None`` samples are ignored (the
recorders use None for "not measured") and an empty sample set returns
``None``, never 0.0 — an unmeasured percentile must not read as a fast
one.

``ThroughputWindow`` is the same move for the serving-rate denominator:
the engine and the fleet each kept a copy-pasted
``_first_enqueue_t``/``_last_complete_t`` pair to bound the window their
``achieved_rps``/``goodput_rps`` divide by. One drifting copy would
silently re-define goodput between the engine summary and the fleet
summary on the same traffic; both now fold through this helper. The
semantics are exactly the old fields': the window opens at the EARLIEST
timestamp ever noted via ``note_enqueue`` (the engine notes completed
requests' enqueue times, the fleet notes admission times — each caller
keeps its historical call sites) and closes at the LATEST
``note_complete``; ``window_s`` is ``None`` until both ends exist — an
unmeasured window must not read as an instant one.
"""

import numpy as np


def percentile(values, q):
    """The shared percentile: ``np.percentile(values, q)`` (float64,
    linear interpolation) over the non-``None`` samples; ``None`` when no
    sample survives the filter."""
    vals = [v for v in values if v is not None]
    if not vals:
        return None
    return float(np.percentile(np.asarray(vals, np.float64), q))


class ThroughputWindow:
    """First-enqueue → last-complete serving window (module docstring):
    the one definition of the wall-clock denominator behind
    ``achieved_rps``/``goodput_rps`` in the engine and fleet summaries."""

    __slots__ = ("first_enqueue_t", "last_complete_t")

    def __init__(self):
        self.first_enqueue_t = None
        self.last_complete_t = None

    def reset(self):
        self.first_enqueue_t = None
        self.last_complete_t = None

    def note_enqueue(self, t):
        """Earliest noted enqueue wins (requests can complete out of
        enqueue order, so every caller notes and the min is kept)."""
        if self.first_enqueue_t is None or t < self.first_enqueue_t:
            self.first_enqueue_t = t

    def note_complete(self, t):
        """Latest noted completion wins."""
        if self.last_complete_t is None or t > self.last_complete_t:
            self.last_complete_t = t

    @property
    def window_s(self):
        """Window length in seconds; ``None`` until both ends exist."""
        if self.first_enqueue_t is None or self.last_complete_t is None:
            return None
        return float(self.last_complete_t - self.first_enqueue_t)
