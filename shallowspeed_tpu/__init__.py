"""shallowspeed_tpu — a TPU-native distributed-training framework.

A brand-new JAX/XLA re-design of the capabilities of siboehm/ShallowSpeed
(reference mounted at /root/reference): deep-MLP SGD training on MNIST under
sequential, data-parallel (DP), pipeline-parallel (PP, naive / GPipe /
PipeDream-Flush schedules) and composed DP x PP layouts.

Architecture (TPU-first, not a port):

- ``ops``        pure jax.numpy forward + hand-written backward kernels
                 (the reference keeps these in NumPy: functional.py).
- ``model``      stage partitioning + explicit forward/backward over a params
                 pytree with residuals threaded explicitly (the reference uses
                 stateful Module._cache dicts: layers.py).
- ``schedules``  pipeline schedules as pure instruction-stream generators
                 (same load-bearing abstraction as reference pipe.py:141-299).
- ``parallel``   the TPU execution layer: a schedule -> clock-tick *lowering*
                 (MPMD instruction streams compiled to a static SPMD tick
                 program) and a shard_map executor over a 2-D (dp, pp)
                 jax.sharding.Mesh where jax.lax.ppermute replaces MPI
                 Send/Recv and jax.lax.psum replaces Iallreduce.
- ``data``       the MNIST-784 parquet/npy data layer with strided DP sharding
                 and microbatch slicing (reference dataset.py semantics).
- ``optimizer``  SGD over pytrees, applied on-device inside the jitted step.
"""

from shallowspeed_tpu import (
    checkpoint,
    data,
    model,
    ops,
    optimizer,
    schedules,
    trainer,
    utils,
)
from shallowspeed_tpu.model import ModelSpec, StageSpec, init_model, partition_sizes

__version__ = "0.1.0"
