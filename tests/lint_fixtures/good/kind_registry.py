"""SSP005 good twin: only registered record kinds reach _emit."""


class Recorder:
    def _emit(self, record):
        raise NotImplementedError

    def event(self, name, **fields):
        self._emit({"kind": "event", "name": name, **fields})

    def static_analysis(self, name, **fields):
        self._emit({"kind": "static_analysis", "name": name, **fields})
