"""Independent PyTorch ground truth for equivalence checks.

Counterpart of the reference's scripts/DDP_PyTorch_MNIST.py (an external
framework implementing the same training run, used to validate that the main
framework's distributed training matches serial training — reference
:157-167 prints total absolute weight divergence). Differences by design:

- torch runs the SAME flagship model/init/loss as shallowspeed_tpu (identical
  MT19937 init, identical softmax quirks, global-batch loss scaling), so it
  is a float-level oracle for the whole trajectory — and its gradients come
  from torch AUTOGRAD, independently checking our hand-written VJPs;
- "DDP" is simulated in-process: R replicas hold strided data shards, their
  per-batch gradient sums are SUM-reduced (the reference's Allreduce), every
  replica applies the same update, and a hash check asserts they stay
  bit-identical — no MPI in the loop;
- --compare takes a shallowspeed_tpu checkpoint (.npz) and prints the total
  absolute weight divergence between torch-trained and TPU-trained weights.

Usage:
    python scripts/torch_baseline.py --epochs 2 --data-dir data/mnist_784
    python scripts/torch_baseline.py --dp 4 --epochs 1
    python scripts/torch_baseline.py --epochs 2 --compare ck.npz
"""

import argparse
import hashlib
import sys
import time
from pathlib import Path

import numpy as np
import torch

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from shallowspeed_tpu.api import (  # noqa: E402 — the canonical config
    FLAGSHIP_BATCH as B,
    FLAGSHIP_LR as LR,
    FLAGSHIP_MUBATCHES as M,
    FLAGSHIP_SIZES as SIZES,
)
from shallowspeed_tpu.data import Dataset, default_data_dir  # noqa: E402
from shallowspeed_tpu.init import linear_init  # noqa: E402


def build_params():
    params = []
    for i in range(len(SIZES) - 1):
        w, b = linear_init(SIZES[i], SIZES[i + 1])
        params.append(
            (
                torch.tensor(w, requires_grad=True),
                torch.tensor(b, requires_grad=True),
            )
        )
    return params


def forward(params, x):
    n = len(params)
    for i, (w, b) in enumerate(params):
        x = x @ w.T + b
        if i < n - 1:
            x = torch.relu(x)
    # reference softmax quirks: global max, +1e-7 denominator
    ze = torch.exp(x - x.max())
    return ze / (ze.sum(dim=1, keepdim=True) + 1e-7)


def loss_fn(p, t):
    return ((t - p) ** 2).sum() / B  # GLOBAL batch scaling


def zero_grads(params):
    for w, b in params:
        if w.grad is not None:
            w.grad.zero_()
            b.grad.zero_()


def grads_of(params):
    return [(w.grad.clone(), b.grad.clone()) for w, b in params]


def apply_update(params, grads):
    with torch.no_grad():
        for (w, b), (gw, gb) in zip(params, grads):
            w -= LR * gw
            b -= LR * gb


def params_hash(params):
    h = hashlib.sha1()
    for w, b in params:
        h.update(w.detach().numpy().tobytes())
        h.update(b.detach().numpy().tobytes())
    return h.hexdigest()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--dp", type=int, default=1, help="simulated DP replicas")
    ap.add_argument("--data-dir", default=None)
    ap.add_argument("--compare", default=None, help="shallowspeed_tpu .npz checkpoint")
    args = ap.parse_args()
    torch.set_num_threads(1)
    data_dir = args.data_dir or default_data_dir()

    # each simulated replica loads its strided shard, exactly like a real rank
    replicas = []
    for r in range(args.dp):
        ds = Dataset(data_dir, B, mubatch_size=B // args.dp // M)
        ds.load(r, args.dp)
        replicas.append((build_params(), ds))

    val = Dataset(data_dir, B, mubatch_size=B, validation=True)
    val.load(0, 1)
    vx = torch.tensor(val.input_X)
    vy = torch.tensor(val.target_y)

    nb = replicas[0][1].get_num_batches()
    t0 = time.time()
    for epoch in range(args.epochs):
        for batch in range(nb):
            # per-replica gradient-accumulated backward over microbatches
            all_grads = []
            for params, ds in replicas:
                zero_grads(params)
                for mb in range(M):
                    x = torch.tensor(ds.load_micro_batch_input(batch, mb))
                    t = torch.tensor(ds.load_micro_batch_target(batch, mb))
                    loss_fn(forward(params, x), t).backward()
                all_grads.append(grads_of(params))
            # SUM-allreduce across replicas (the DDP Allreduce)
            total = [
                (
                    sum(g[i][0] for g in all_grads),
                    sum(g[i][1] for g in all_grads),
                )
                for i in range(len(SIZES) - 1)
            ]
            for params, _ in replicas:
                apply_update(params, total)
        with torch.no_grad():
            acc = (
                (forward(replicas[0][0], vx).argmax(1) == vy.argmax(1))
                .float()
                .mean()
                .item()
            )
        print(
            f"Epoch: {epoch + 1}, Time Spent: {time.time() - t0:.2f}s, "
            f"Accuracy: {acc * 100:.2f}%"
        )

    hashes = {params_hash(p) for p, _ in replicas}
    if len(hashes) != 1:
        raise SystemExit("FAIL: simulated DP replicas diverged")
    print(f"replicas in sync ({args.dp}): {hashes.pop()[:12]}")

    if args.compare:
        with np.load(args.compare) as z:
            div = 0.0
            for i, (w, b) in enumerate(replicas[0][0]):
                div += np.abs(w.detach().numpy() - z[f"w{i}"]).sum()
                div += np.abs(b.detach().numpy() - z[f"b{i}"].reshape(1, -1)).sum()
        n_params = sum(w.numel() + b.numel() for w, b in replicas[0][0])
        print(
            f"total |divergence| vs {args.compare}: {div:.6f} "
            f"({div / n_params:.3e} per weight)"
        )


if __name__ == "__main__":
    main()
