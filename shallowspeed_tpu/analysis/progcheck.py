"""Send/recv match & deadlock analysis over lowered tick programs.

The lowering simulator (parallel/lowering.py) refuses schedules it cannot
place, but the TickProgram it emits is then trusted as-is: the executor
dispatches the tables, and the planned MPMD runtime (ROADMAP item 1,
arXiv 2412.14374) will dispatch each stage's stream ASYNCHRONOUSLY with
no lockstep barrier. These passes re-derive, from the ARTIFACT alone,
exactly the properties that asynchronous dispatch needs — which makes
the analyzer the MPMD runtime's spec, the same static-schedule reasoning
PipeDream applies before execution (arXiv 1806.03377):

- ``check_send_recv``: a tick-replay over the mailbox tables. Every send
  has a delivery slot on the peer stage; every mailbox read consumes a
  message delivered on an EARLIER tick (the executor's deliver-at-end-of-
  tick timing); a delivery never clobbers an undelivered message; no
  message is left unconsumed at program end. Violations name the tick,
  stage and slot.
- ``check_deadlock_free``: the tick-free proof. Per-stage instruction
  streams are reconstructed from the tables and messages are matched by
  (chunk, microbatch) KEY — no tick numbers involved — then the
  happens-before graph (per-stage program order + send->recv edges +
  bounded-mailbox slot-reuse edges: the sender of a slot's next message
  waits for the consumer of its previous one) is checked acyclic. An
  acyclic graph means the streams, dispatched asynchronously with the
  program's finite mailbox depths, can always make progress; a cycle is
  reported as the literal wait chain, tick by tick.

``analyze_program`` runs every pass (including the stash-lifetime pass,
``analysis/stash.py``) and returns a JSON-able verdict dict — the field
set of the schema-v9 ``static_analysis`` record — raising
``ProgramAnalysisError`` on the first violated contract.
"""

import numpy as np

from shallowspeed_tpu.parallel.lowering import OP_NOOP


class ProgramAnalysisError(ValueError):
    """A lowered tick program violates a statically-checkable contract —
    either the tables were tampered with, or the lowering itself
    regressed. The message names the offending tick/stage/slot."""


def _active_cells(prog):
    """Per-stage MPMD streams: the (tick, stage) cells each device runs,
    in its own program order (tick order within a column)."""
    op = np.asarray(prog.op)
    return [
        [int(t) for t in np.nonzero(op[:, s] != OP_NOOP)[0]]
        for s in range(prog.num_stages)
    ]


def _cell_key(prog, t, s):
    """The (chunk, microbatch) identity of the compute at cell (t, s)."""
    chunk = int(prog.chunk[t, s]) if prog.chunk is not None else 0
    return (chunk, int(prog.mb[t, s]))


def _sent_key(prog, t, s, direction):
    """The key a send at (t, s) delivers under, after the ring's chunk
    shift (forward wrap P-1 -> 0 advances the chunk; backward mirrors)."""
    chunk, mb = _cell_key(prog, t, s)
    P = prog.num_stages
    if direction == "fwd":
        return (chunk + (1 if s == P - 1 else 0), mb)
    return (chunk - (1 if s == 0 else 0), mb)


def check_send_recv(prog):
    """Replay the mailbox tables tick by tick and prove the send/recv
    match contract (module docstring). Returns the pass's stats dict."""
    P, T = prog.num_stages, prog.num_ticks
    tables = {
        "fwd": (prog.send_fwd, prog.in_fwd_slot, prog.read_fwd_slot,
                int(prog.n_fwd_slots), +1),
        "bwd": (prog.send_bwd, prog.in_bwd_slot, prog.read_bwd_slot,
                int(prog.n_bwd_slots), -1),
    }
    # per (direction, stage): slot -> tick the occupying message was sent
    occupied = {d: [dict() for _ in range(P)] for d in tables}
    stats = {
        "sends_fwd": 0, "sends_bwd": 0,
        "mail_peak_fwd": 0, "mail_peak_bwd": 0,
    }
    for t in range(T):
        # reads first: a message consumed at tick t frees its slot for an
        # arrival in this very tick (lowering._Mailbox.consume semantics)
        for d, (_, _, read_tab, trash, _) in tables.items():
            for s in range(P):
                slot = int(read_tab[t, s])
                if slot == trash:
                    continue
                sent = occupied[d][s].pop(slot, None)
                if sent is None:
                    raise ProgramAnalysisError(
                        f"tick {t} stage {s}: reads {d} mailbox slot {slot}"
                        " which holds no message — recv with no matching"
                        " send"
                    )
                if sent >= t:
                    raise ProgramAnalysisError(
                        f"tick {t} stage {s}: reads {d} mailbox slot {slot}"
                        f" delivered this same tick (sent at tick {sent});"
                        " payloads are consumable from tick t+1"
                    )
        # then deliveries
        for d, (send_tab, in_tab, _, trash, step) in tables.items():
            for s in range(P):
                dst = (s + step) % P
                sends = int(send_tab[t, s])
                slot = int(in_tab[t, dst])
                if sends:
                    stats[f"sends_{d}"] += 1
                    if slot == trash:
                        raise ProgramAnalysisError(
                            f"tick {t} stage {s}: {d} send has no delivery"
                            f" slot on peer stage {dst} — unmatched send"
                        )
                    if slot in occupied[d][dst]:
                        raise ProgramAnalysisError(
                            f"tick {t} stage {s}: {d} send clobbers mailbox"
                            f" slot {slot} on stage {dst} (still holding the"
                            f" message sent at tick {occupied[d][dst][slot]})"
                        )
                    occupied[d][dst][slot] = t
                    stats[f"mail_peak_{d}"] = max(
                        stats[f"mail_peak_{d}"], len(occupied[d][dst])
                    )
                elif slot != trash:
                    raise ProgramAnalysisError(
                        f"tick {t} stage {dst}: {d} delivery into slot"
                        f" {slot} with no send from stage {s} this tick —"
                        " phantom arrival"
                    )
    for d, (_, _, _, trash, _) in tables.items():
        for s in range(P):
            if occupied[d][s]:
                slot, sent = next(iter(occupied[d][s].items()))
                raise ProgramAnalysisError(
                    f"stage {s}: {d} mailbox slot {slot} still holds the"
                    f" message sent at tick {sent} at program end — send"
                    " with no consuming recv on the peer stage"
                )
    for d in tables:
        depth = int(prog.n_fwd_slots if d == "fwd" else prog.n_bwd_slots)
        peak = stats[f"mail_peak_{d}"]
        if peak > depth:
            raise ProgramAnalysisError(
                f"{d} mailbox peak occupancy {peak} exceeds the allocated"
                f" depth {depth}"
            )
    return stats


def _message_edges(prog):
    """Key-matched send->recv pairs plus bounded-mailbox slot-reuse
    pairs, as ``(edge_kind, (t_from, s_from), (t_to, s_to))`` cell edges
    — derived WITHOUT comparing tick numbers (ticks only order cells
    within one stage's own stream), so the deadlock proof does not
    assume the lockstep schedule it is meant to replace. ``"msg"`` edges
    run sender-cell -> consumer-cell; ``"reuse"`` edges run
    previous-consumer-cell -> next-sender-cell (a bounded mailbox's slot
    must be freed before it can take the next delivery)."""
    P, T = prog.num_stages, prog.num_ticks
    edges = []
    for d, (send_tab, in_tab, read_tab, trash, step) in {
        "fwd": (prog.send_fwd, prog.in_fwd_slot, prog.read_fwd_slot,
                int(prog.n_fwd_slots), +1),
        "bwd": (prog.send_bwd, prog.in_bwd_slot, prog.read_bwd_slot,
                int(prog.n_bwd_slots), -1),
    }.items():
        # sends per (dst stage, key) — the ring is neighbor-only, so the
        # (src, dst, key) triple names one message
        sends = {}
        for s in range(P):
            dst = (s + step) % P
            for t in range(T):
                if int(send_tab[t, s]):
                    key = (dst, _sent_key(prog, t, s, d))
                    if key in sends:
                        raise ProgramAnalysisError(
                            f"tick {t} stage {s}: duplicate {d} send for"
                            f" (chunk, microbatch) {key[1]} to stage {dst}"
                        )
                    sends[key] = (t, s)
        # recv (consuming cell) per key; slot-reuse chains per (stage,
        # slot) in the receiver's own stream order
        for s in range(P):
            prev_consumer_of_slot = {}
            for t in range(T):
                slot = int(read_tab[t, s])
                if slot != trash:
                    key = (s, _cell_key(prog, t, s))
                    sender = sends.pop(key, None)
                    if sender is None:
                        raise ProgramAnalysisError(
                            f"tick {t} stage {s}: {d} recv for (chunk,"
                            f" microbatch) {key[1]} has no matching send"
                            " on the peer stage"
                        )
                    edges.append(("msg", sender, (t, s)))
                    prev_consumer_of_slot[slot] = (t, s)
                # a delivery into slot k can only happen once slot k's
                # previous message was consumed: under async dispatch the
                # SENDER of the new message waits on that consumer
                in_slot = int(in_tab[t, s])
                if in_slot != trash:
                    src = (s - step) % P
                    prev = prev_consumer_of_slot.get(in_slot)
                    if prev is not None and int(send_tab[t, src]):
                        edges.append(("reuse", prev, (t, src)))
        if sends:
            (dst, key), (t, s) = next(iter(sends.items()))
            raise ProgramAnalysisError(
                f"tick {t} stage {s}: {d} send for (chunk, microbatch)"
                f" {key} has no consuming recv on stage {dst}"
            )
    return edges


def check_deadlock_free(prog):
    """Prove the per-stage streams cannot deadlock under asynchronous
    (MPMD) dispatch with the program's bounded mailboxes.

    Each cell is modeled as TWO events — ``R`` (its recvs complete; the
    consumed mailbox slots free here) and ``X`` (its compute and sends
    complete) — because a blocked sender waits only on the consumer
    FREEING the slot, not on the consumer's whole cell: collapsing the
    two manufactures wait cycles in perfectly healthy steady states
    (e.g. the interleaved schedule's same-tick consume-and-send ring).
    The happens-before graph is then:

    - ``R -> X`` within each cell;
    - ``X(prev) -> R(next)`` along each stage's own stream (serial
      async dispatch);
    - ``X(sender) -> R(consumer)`` for every key-matched message;
    - ``R(previous consumer) -> X(next sender)`` for every reuse of a
      bounded mailbox slot (the send blocks until the slot frees).

    Acyclic means the streams, dispatched with no lockstep barrier and
    the program's finite mailbox depths, always make progress; a cycle
    raises ``ProgramAnalysisError`` spelling out the literal wait chain
    tick by tick. Returns the pass's stats dict."""
    R, X = 0, 1
    streams = _active_cells(prog)
    succ = {}

    def node(cell, phase):
        v = (cell[0], cell[1], phase)
        succ.setdefault(v, [])
        return v

    for s, ticks in enumerate(streams):
        for t in ticks:
            succ.setdefault((t, s, R), []).append(node((t, s), X))
        for a, b in zip(ticks, ticks[1:]):
            succ[(a, s, X)].append(node((b, s), R))
    n_message_edges = n_reuse_edges = 0
    for kind, frm, to in _message_edges(prog):
        if kind == "msg":
            succ[node(frm, X)].append(node(to, R))
            n_message_edges += 1
        else:  # reuse: the new send waits on the old message's consumer
            if frm == to:
                continue  # a cell may free and refill its own slot
            succ[node(frm, R)].append(node(to, X))
            n_reuse_edges += 1
    # iterative 3-color DFS; a back edge is a genuine wait cycle
    WHITE, GREY, BLACK = 0, 1, 2
    color = {v: WHITE for v in succ}
    for root in succ:
        if color[root] != WHITE:
            continue
        stack = [(root, iter(succ[root]))]
        color[root] = GREY
        path = [root]
        while stack:
            _, it = stack[-1]
            advanced = False
            for nxt in it:
                if color[nxt] == GREY:
                    i = path.index(nxt)
                    cycle = path[i:] + [nxt]
                    chain = " -> ".join(
                        f"stage {s} tick {t} ({'recv' if p == R else 'send'})"
                        for t, s, p in cycle
                    )
                    raise ProgramAnalysisError(
                        "cyclic wait under asynchronous (MPMD) dispatch: "
                        + chain
                    )
                if color[nxt] == WHITE:
                    color[nxt] = GREY
                    stack.append((nxt, iter(succ[nxt])))
                    path.append(nxt)
                    advanced = True
                    break
            if not advanced:
                color[path[-1]] = BLACK
                stack.pop()
                path.pop()
    return {
        "cells": sum(len(t) for t in streams),
        "message_edges": n_message_edges,
        "reuse_edges": n_reuse_edges,
    }


def analyze_program(prog, program="program"):
    """Run every program-level static pass over one lowered TickProgram.

    Returns the JSON-able verdict dict the schema-v9 ``static_analysis``
    record carries (pass names + per-pass stats, zero findings — a
    violated contract raises ``ProgramAnalysisError`` instead, naming the
    offending tick, BEFORE any dispatch can happen)."""
    from shallowspeed_tpu.analysis.stash import check_stash_lifetime

    send_recv = check_send_recv(prog)
    deadlock = check_deadlock_free(prog)
    stash = check_stash_lifetime(prog)
    return {
        "program": program,
        "passes": ["send_recv", "deadlock", "stash"],
        "findings": 0,
        "is_training": bool(prog.is_training),
        "num_ticks": int(prog.num_ticks),
        "num_stages": int(prog.num_stages),
        "send_recv": send_recv,
        "deadlock": deadlock,
        "stash": stash,
    }
