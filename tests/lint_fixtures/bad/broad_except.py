"""BLE001 bad twin: a broad except that swallows, with no justification."""


def load(path):
    try:
        return open(path).read()
    except Exception:  # MARK
        pass
