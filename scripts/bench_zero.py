"""The ZeRO memory scoreboard (PR 20): same-window zero1/zero2/zero3
epochs on the compute-bound flagship zoo model (``mlp-wide``, Adam — two
params-shaped state parts, so the stage ladder has something to shard),
written as ZERO_r01.json beside the other bench records.

Two layouts, the dp2 flagship and the dp2 x pp2 composition:

1. **Measured peak HBM** — every leg compiles with ``audit=True`` and a
   metrics recorder, so the schema-v3 ``xla_audit`` record lands with the
   shared ``memory_stats`` analysis; the scoreboard reads the epoch
   program's measured ``peak_hbm_bytes`` per stage and asserts the
   flagship ladder is STRICTLY decreasing zero1 -> zero2 -> zero3 (the
   claim the stages exist to make). The analytical
   ``zero_peak_forecast`` (params+grads+state ÷ dp residency) is recorded
   next to each measurement — forecast vs measured is the calibration the
   report's OOM-forecast row rests on.

2. **Epoch pair** — the stages' training epochs interleaved per trial
   (the BENCH_r0x protocol), per-leg minima. On CPU the ZeRO collectives
   are op-issue-bound host work, so the walls show the stages' COST here,
   not their chip behavior — recorded with that caveat, the memory ladder
   is the headline.

The fixed-layout numerics contract rides along as a hash-pin pair per
layout at ``mubatches=1``: there the anchor zero-2 per-tick
reduce-scatter carries exactly one contribution per shard element, so
its final weights hash must equal zero-1's BITWISE (same tick table,
same update math, different residency). The measured-window legs run at
``mubatches=4``, where the sharded accumulator's microbatch-outer sum is
a different (equally valid) float reduction tree than zero-1's dp-outer
one — tolerance territory by design, see docs/performance.md.

CPU-fallback caveat, as everywhere: emulated devices validate machinery
and RELATIVE ratios, not chip performance — but ``peak_hbm_bytes`` comes
from XLA's own buffer-assignment analysis of the compiled program, which
is exactly the quantity the stages shrink.
"""

import argparse
import json
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

BENCH_VERSION = 1
STAGES = (1, 2, 3)

LAYOUTS = (
    ("dp2", dict(dp=2)),
    ("dp2xpp2", dict(dp=2, pp=2, schedule="gpipe")),
)


def _synth_data(work, n_train=4096, n_val=512):
    """MNIST-shaped synthetic data (784 -> 10): the zoo models keep the
    784-wide input, and the scoreboard measures programs, not accuracy."""
    d = Path(work) / "data"
    d.mkdir(parents=True, exist_ok=True)
    rng = np.random.RandomState(0)
    for suffix, n in (("train", n_train), ("val", n_val)):
        x = rng.rand(n, 784).astype(np.float32)
        y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, n)]
        np.save(d / f"x_{suffix}.npy", x)
        np.save(d / f"y_{suffix}.npy", y)
    return d


def _epoch_audit(path):
    """The epoch program's xla_audit record from a leg's metrics file."""
    recs = [json.loads(l) for l in open(path) if l.strip()]
    audits = [
        r for r in recs
        if r.get("kind") == "xla_audit" and r.get("name") == "epoch_program"
    ]
    assert audits, f"{path}: no epoch_program xla_audit record"
    rec = audits[-1]
    assert rec.get("census_ok"), f"{path}: census mismatch: {rec.get('mismatches')}"
    return rec


def bench_layout(name, kw, data_dir, work, trials, model, optimizer):
    from shallowspeed_tpu.api import TrainingSession
    from shallowspeed_tpu.observability.metrics import JsonlMetrics

    sessions, metrics_paths = {}, {}
    for stage in STAGES:
        path = Path(work) / f"{name}_z{stage}.jsonl"
        metrics_paths[stage] = path
        sessions[stage] = TrainingSession(
            model=model, optimizer=optimizer, global_batch_size=128,
            mubatches=4, data_dir=str(data_dir), zero=stage, audit=True,
            metrics=JsonlMetrics(str(path)), **kw,
        )
    walls = {stage: [] for stage in STAGES}
    for stage, s in sessions.items():
        s.train_epoch()  # compile (and audit) outside the measured window
    for _ in range(trials):
        for stage, s in sessions.items():
            t0 = time.perf_counter()
            s.train_epoch()
            walls[stage].append(time.perf_counter() - t0)
    hashes = {stage: s.model_hash() for stage, s in sessions.items()}
    for s in sessions.values():
        s._metrics.close()

    # the fixed-layout hash pin: mubatches=1 legs, where anchor zero-2's
    # per-tick scatter is one contribution per element -> bitwise zero-1
    pin_hashes = {}
    for stage in (1, 2):
        s = TrainingSession(
            model=model, optimizer=optimizer, global_batch_size=128,
            mubatches=1, data_dir=str(data_dir), zero=stage, audit=True,
            **kw,
        )
        s.train_epoch()
        pin_hashes[stage] = s.model_hash()

    legs = {}
    for stage in STAGES:
        audit = _epoch_audit(metrics_paths[stage])
        mem = audit.get("memory") or {}
        forecast = (audit.get("expected") or {}).get("zero_forecast") or {}
        fc_stage = (forecast.get("stages") or {}).get(str(stage)) or {}
        legs[f"zero{stage}"] = {
            "peak_hbm_bytes": mem.get("peak_hbm_bytes"),
            "temp_bytes": mem.get("temp_size_in_bytes"),
            "argument_bytes": mem.get("argument_size_in_bytes"),
            "epoch_wall_s": min(walls[stage]),
            "trials_s": walls[stage],
            "model_hash": hashes[stage],
            "forecast_model_state_bytes": fc_stage.get("total_bytes"),
            "forecast": fc_stage,
        }
    peaks = [legs[f"zero{s}"]["peak_hbm_bytes"] for s in STAGES]
    out = {
        "legs": legs,
        "peak_ladder_bytes": peaks,
        "verdicts": {
            "peak_strictly_decreasing": bool(
                all(p is not None for p in peaks)
                and peaks[0] > peaks[1] > peaks[2]
            ),
            "zero2_hash_equals_zero1_at_mub1": pin_hashes[2] == pin_hashes[1],
        },
        "hash_pin_mub1": {f"zero{s}": pin_hashes[s] for s in (1, 2)},
    }
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=None,
                    help="record path (default: ZERO_r01.json at the repo "
                    "root)")
    ap.add_argument("--trials", type=int, default=3)
    ap.add_argument("--model", default="mlp-wide")
    ap.add_argument("--optimizer", default="adam")
    args = ap.parse_args(argv)

    import tempfile

    import jax

    work = Path(tempfile.mkdtemp(prefix="bench_zero_"))
    data_dir = _synth_data(work)

    layouts = {}
    for name, kw in LAYOUTS:
        print(f"[{name}] measuring zero1/zero2/zero3 ...", flush=True)
        layouts[name] = bench_layout(
            name, kw, data_dir, work, args.trials, args.model, args.optimizer
        )

    record = {
        "bench": "zero_memory_scoreboard",
        "bench_version": BENCH_VERSION,
        "created": time.strftime("%Y-%m-%d %H:%M:%S"),
        "config": {
            "model": args.model, "optimizer": args.optimizer,
            "global_batch_size": 128, "mubatches": 4, "trials": args.trials,
            "platform": jax.devices()[0].platform,
        },
        "cpu_fallback_caveat": (
            "emulated CPU devices: the memory ladder is XLA's own "
            "buffer-assignment peak of the compiled program (the honest "
            "quantity); the walls are op-issue-bound host dispatch, not "
            "chip behavior — ZeRO-3's per-tick gathers COST wall time "
            "here, the stage is a memory trade"
        ),
        "protocol": (
            "same-window: the three stages' epochs interleaved per trial, "
            "per-leg minima; every leg compiled under audit=True (census "
            "enforced at jit time) with the measured peak read from the "
            "epoch program's xla_audit record; zero2 final weights "
            "asserted hash-equal to zero1 per layout on the mubatches=1 "
            "hash-pin pair (per-tick scatter reassociates the microbatch "
            "sum at M>1)"
        ),
        "layouts": layouts,
    }
    out = Path(
        args.out
        if args.out
        else Path(__file__).resolve().parent.parent / "ZERO_r01.json"
    )
    out.write_text(json.dumps(record, indent=2) + "\n")
    print(f"record written: {out}")

    failed = []
    for name, lay in layouts.items():
        ladder = " -> ".join(
            f"z{s} {lay['legs'][f'zero{s}']['peak_hbm_bytes']:,} B"
            for s in STAGES
        )
        print(f"[{name}] measured peak HBM: {ladder}")
        for s in STAGES:
            leg = lay["legs"][f"zero{s}"]
            print(
                f"[{name}]   z{s}: forecast model state "
                f"{leg['forecast_model_state_bytes']:,} B, epoch wall "
                f"{leg['epoch_wall_s']:.2f}s"
            )
        for verdict, ok in lay["verdicts"].items():
            print(f"[{name}] {verdict}: {'OK' if ok else 'FAILED'}")
            if not ok:
                failed.append(f"{name}:{verdict}")
    if failed:
        print("FAILED verdicts:", ", ".join(failed))
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
