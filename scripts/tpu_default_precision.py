"""Chip experiment: does DEFAULT-precision training reach reference accuracy?

The round-2 tuning matrix (TPU_CAPTURE_r02.json) measured the fused
sequential epoch at ~3.8x higher throughput with matmul
``precision=DEFAULT`` (bf16-input, fp32-accumulate on the MXU) than with
``HIGHEST`` in the same contention window. HIGHEST is the framework default
because the NumPy-trajectory parity tests require it — but the north-star
criterion (BASELINE.json) is "reaches NumPy-reference loss", a convergence
property, not bitwise parity. This script settles whether the fast config
is *convergence-equivalent*: 20-epoch flagship run at precision=default
(fused mubatches), per-epoch validation accuracy, final loss, plus a
throughput point for the same config, all in one chip claim.

Writes TPU_DEFAULT_PRECISION_r02.json at the repo root.
Run:  python scripts/tpu_default_precision.py [--epochs 20]
"""

import argparse
import json
import sys
import time
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

import bench


def convergence(data_dir, epochs, precision):
    from shallowspeed_tpu.api import TrainingSession

    run = TrainingSession(
        data_dir=data_dir, precision=precision, fuse_mubatches=True
    )
    accs, losses = [], []
    t0 = time.perf_counter()
    for _ in range(epochs):
        losses.append(run.train_epoch())
        accs.append(round(run.accuracy(), 4))
    wall = time.perf_counter() - t0
    return {
        "precision": precision,
        "epochs": epochs,
        "wall_s_incl_eval": round(wall, 3),
        "per_epoch_val_accuracy": accs,
        "final_val_accuracy": accs[-1],
        "first_loss": round(losses[0], 4),
        "final_loss": round(losses[-1], 4),
        "model_hash": run.model_hash(),
    }


def throughput_pair():
    # the exact code path AND config the published headline uses
    # (bench.jax_sps_many defaults: trials=5, unroll from
    # SHALLOWSPEED_BENCH_UNROLL), with the two cells' trials INTERLEAVED so
    # the recorded default/highest ratio really is same-window
    return bench.jax_sps_many(("default", "highest"))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data-dir", default="/tmp/ssd_data")
    ap.add_argument("--epochs", type=int, default=20, choices=range(1, 1001),
                    metavar="1..1000")
    ap.add_argument("--out", default=str(ROOT / "TPU_DEFAULT_PRECISION_r04.json"))
    args = ap.parse_args()

    tag, _probe_diag = bench._ensure_responsive_backend()
    if tag:
        print(f"tunnel not healthy ({tag}); aborting", file=sys.stderr)
        sys.exit(3)

    import jax

    dev = jax.devices()[0]
    print(f"device: {dev} ({dev.platform})", flush=True)

    if not Path(args.data_dir).is_dir():
        import subprocess

        subprocess.run(
            [sys.executable, str(ROOT / "prepare_data.py"), "--save-dir", args.data_dir],
            check=True,
        )

    out = {
        "info": {
            "platform": dev.platform,
            "device": str(dev),
            "captured_at": time.strftime("%Y-%m-%d %H:%M:%S"),
        }
    }

    print("throughput pair (interleaved trials, same-window)...", flush=True)
    pair = throughput_pair()
    sps_d, sps_h = pair["default"], pair["highest"]
    print(f"  fused+default+xla: {sps_d:,.0f} samples/s", flush=True)
    print(f"  fused+highest+xla: {sps_h:,.0f} samples/s", flush=True)
    out["throughput"] = {
        "fused+default+xla": round(sps_d, 1),
        "fused+highest+xla": round(sps_h, 1),
        "default_over_highest": round(sps_d / sps_h, 2),
    }

    print(f"convergence at precision=default ({args.epochs} epochs)...", flush=True)
    conv_d = convergence(args.data_dir, args.epochs, "default")
    print(f"  {conv_d}", flush=True)
    out["convergence_default"] = conv_d

    Path(args.out).write_text(json.dumps(out, indent=2) + "\n")
    print(json.dumps({k: out[k] for k in ("throughput",)}))


if __name__ == "__main__":
    main()
