"""Latency-denominated load bench: p50/p99, goodput and the saturation knee —
plus the seeded CHAOS SOAK behind ``make chaos-smoke`` and the FLEET
chaos soak behind ``make fleet-smoke``.

    python -m shallowspeed_tpu.serving.bench_serving [--dp N] [--pp M]
        [--schedule gpipe] [--rates 50,100,200,400] [--requests 100]
        [--slo-ms 50] [--seed 0] [--out BENCH_SERVING.json]

    # chaos soak: inject die/slow/nan/error faults + one mid-traffic hot
    # reload into seeded open-loop traffic and measure what degrades
    python -m shallowspeed_tpu.serving.bench_serving --dp 2 \
        --chaos "error@dispatch=3,slow@dispatch=5:ms=30,die@dispatch=7,nan@dispatch=9" \
        --reload-dir ck/ --reload-at 5 --requests 80 --rates 300 \
        --slo-ms 2000 --chaos-out CHAOS.json --metrics-out chaos.jsonl

    # fleet chaos soak: 3 replica worker processes behind the router, the
    # busiest one SIGKILLed after 20 served responses, a replacement
    # scaled up from the newest good snapshot — zero silently-lost
    # requests, worker-side bitwise parity, measured goodput dip +
    # recovery (docs/serving.md "Fleet")
    python -m shallowspeed_tpu.serving.bench_serving --fleet 3 \
        --checkpoint ck/step-00000008.npz --reload-dir ck/ \
        --kill-after 20 --requests 120 --rates 300 --slo-ms 2000 \
        --fleet-out FLEET_CHAOS.json --metrics-out fleet.jsonl

``bench_scaling`` scores the framework in samples/s; this bench opens the
second scoreboard the ROADMAP's "millions of users" north star asks for —
tail latency under load. For each offered rate it drives ``--requests``
seeded Poisson arrivals through a ``ServingEngine`` in open-loop mode
(arrivals independent of completions, enqueue backdated to scheduled
arrival — queueing delay lands in latency, never silently throttles the
offered load) and records p50/p99 latency, goodput (SLO-met completions per
second), achieved rate, queue depth and padding waste. The saturation knee
is the first rate whose tail violates the SLO or whose achieved rate falls
measurably below the offered one — the operating ceiling every future speed
PR is measured against.

Output is ONE versioned JSON document (``bench_version`` + per-row fields,
beside ``bench_scaling``'s records): the analytical latency floor
(``costmodel.serving_latency_bound`` — inference ticks x per-tick cost) is
recorded next to the measured percentiles so the gap between model and tail
is a number, not prose.

The chaos soak (``chaos_soak``) replays the SAME seeded stream twice — a
clean baseline pass, then a pass with a ``faults.py`` dispatch-fault plan
active and one mid-traffic hot weight reload — and reports availability,
goodput retention, the per-verdict terminal counts, breaker trips, the
measured recovery time, and two hard invariants: ZERO silently-lost
requests (every submitted id reaches a terminal verdict) and bitwise
parity of every ``"ok"`` response against a direct ``predict()`` under
the weights active at its dispatch (verified per dispatch, so a hot
reload between dispatches cannot confuse the oracle). ``die`` faults
raise ``InjectedFault`` out of ``step()``; the soak's operator loop
catches and re-enters — the queue is intact by the engine's contract, so
a "dispatch loop crash" costs wall time, never requests.

NOTE on interpretation (the honest caveat every CPU bench row in this repo
carries): on emulated CPU devices dispatch overhead dominates the tiny MLP,
so absolute latencies validate the machinery; the SHAPE of the sweep (flat
-> knee -> queue blow-up) is the transferable result.
"""

import argparse
import json
import os
import sys
import time

import numpy as np

from shallowspeed_tpu import faults as F
from shallowspeed_tpu.observability import slo
from shallowspeed_tpu.observability.metrics import json_safe
from shallowspeed_tpu.serving.engine import ServingEngine
from shallowspeed_tpu.serving.loadgen import (
    poisson_arrivals,
    request_payloads,
    run_open_loop,
)

BENCH_VERSION = 1
CHAOS_VERSION = 1
FLEET_CHAOS_VERSION = 1
SWEEP_ROW_FIELDS = (
    "offered_rps",
    "completed",
    "dropped",
    "p50_latency_s",
    "p99_latency_s",
    "goodput_rps",
    "achieved_rps",
    "queue_depth_max",
    "queue_depth_mean",
    "padding_waste",
    "dispatches",
)


def find_knee(rows, slo_ms, achieved_fraction=slo.SLO_ACHIEVED_FRACTION):
    """The saturation knee: the first offered rate (rows are swept in
    ascending offered order) that breaches the shared SLO predicate —
    p99 above the SLO, or achieved rate below ``achieved_fraction`` x
    offered. The breach definition lives in ``observability.slo.
    slo_breach`` (the capacity scoreboard scores violation minutes with
    the SAME call, so knee and scoreboard can never disagree). None =
    no knee inside the swept range (the verdict then says so instead of
    guessing)."""
    for row in rows:
        if slo.slo_breach(
            row.get("p99_latency_s"),
            row.get("offered_rps"),
            row.get("achieved_rps"),
            slo_ms,
            achieved_fraction=achieved_fraction,
        ):
            return row["offered_rps"]
    return None


def sweep(
    session,
    rates,
    n_requests=100,
    seed=0,
    slo_ms=None,
    rows_choices=(1, 2, 3, 4, 8),
    metrics=None,
    max_slots=None,
    dispatch_floor_ms=0.0,
):
    """Run the offered-load sweep on an existing session; returns the
    versioned JSON-able bench record. The SAME seeded request stream is
    replayed at every rate (only the arrival clock changes), so rows
    differ by load, not workload. ``dispatch_floor_ms``/``max_slots``
    shape the engine exactly as a replay fleet's workers would be shaped
    (engine.py "dispatch floor") — measure the knee with the SAME values
    you arm the autoscaler with, or the measurement prices the wrong
    machine."""
    engine = ServingEngine(
        session, slo_ms=slo_ms, metrics=metrics, max_slots=max_slots,
        dispatch_floor_ms=dispatch_floor_ms,
    )
    # compile every rung before the sweep: the percentiles must measure
    # serving under load, not the first rate's XLA compiles
    engine.warm_ladder()
    payloads = request_payloads(
        n_requests, session.spec.sizes[0], seed=seed, rows_choices=rows_choices
    )
    rows = []
    for rate in sorted(rates):
        engine.reset_stats()
        arrivals = poisson_arrivals(rate, n_requests, seed=seed)
        run_open_loop(engine, payloads, arrivals)
        rec = engine.record_summary(offered_rps=rate)
        rows.append({k: rec.get(k) for k in SWEEP_ROW_FIELDS})
    bound = session.inference_latency_bound()
    knee_rps = find_knee(rows, slo_ms)
    record = {
        "bench": "serving",
        "bench_version": BENCH_VERSION,
        "config": {
            "dp": session.dp,
            "pp": session.pp,
            "tp": session.tp,
            "schedule": session.schedule,
            "slot_rows": session.slot_rows,
            "slot_ladder": list(session.slot_ladder),
            "requests_per_rate": n_requests,
            "seed": seed,
            "slo_ms": slo_ms,
            "rows_choices": list(rows_choices),
            "max_slots": max_slots,
            "dispatch_floor_ms": dispatch_floor_ms,
        },
        "latency_bound_s": bound["seconds"],
        "latency_bound_ticks": bound["ticks"],
        "latency_bound_source": bound["peak_source"],
        "sweep": rows,
        "knee_rps": knee_rps,
    }
    if metrics is not None:
        # the sweep summary in the metrics stream too (schema v11): the
        # measured knee lands beside the run it came from, so the
        # knee-proximity alert rule can be armed from the record —
        # never from a hand-copied constant (slo.default_serving_rules)
        metrics.serving(
            "sweep",
            knee_rps=knee_rps,
            rates=[r.get("offered_rps") for r in rows],
            slo_ms=slo_ms,
            requests_per_rate=n_requests,
            latency_bound_s=bound["seconds"],
        )
    return record


def chaos_soak(
    session,
    faults,
    n_requests=80,
    rate=200.0,
    seed=0,
    slo_ms=None,
    rows_choices=(1, 2, 3, 4, 8),
    deadline_ms=None,
    metrics=None,
    reload_dir=None,
    reload_at=None,
    loaded_step=None,
    retry_budget=2,
    breaker_threshold=2,
    max_slots=None,
    verify=True,
    baseline=True,
):
    """The seeded degradation experiment (module docstring): returns the
    versioned JSON-able chaos record. ``faults`` is a ``@dispatch=``
    fault spec/plan; ``reload_at`` triggers the checkpoint-dir WATCHER
    reload once attempted dispatch N is reached (the breaker triggers its
    own reloads independently when poisoned weights trip it);
    ``baseline=True`` first replays the identical stream through a clean
    engine so goodput/p99 retention are measured, not guessed."""
    payloads = request_payloads(
        n_requests, session.spec.sizes[0], seed=seed, rows_choices=rows_choices
    )
    arrivals = poisson_arrivals(rate, n_requests, seed=seed)
    base_stats = None
    if baseline:
        # faults="" pins an EMPTY plan: the engine default falls back to
        # the SHALLOWSPEED_FAULTS environment, which would make the
        # "clean" baseline anything but
        clean = ServingEngine(session, slo_ms=slo_ms, faults="")
        clean.warm_ladder()
        run_open_loop(clean, payloads, arrivals, deadline_ms=deadline_ms)
        base_stats = clean.stats()
    engine = ServingEngine(
        session,
        slo_ms=slo_ms,
        metrics=metrics,
        retry=retry_budget,
        breaker_threshold=breaker_threshold,
        reload_dir=reload_dir,
        loaded_step=loaded_step,
        faults=faults,
        # a small packing capacity spreads the stream over MORE dispatches,
        # so every @dispatch= anchor in the plan is actually reached
        max_slots=max_slots,
    )
    engine.warm_ladder()
    # the zero-recompile audit anchor: every rung is compiled (and censused
    # under audit) by now — any jit_compiles growth past this point is a
    # recompile the hot reload was contractually forbidden to cause
    counters = getattr(session._metrics, "counters", None)
    compiles_before = counters.get("jit_compiles") if counters else None
    cache_before = set(getattr(session, "_predict_cache", {}))
    submitted, done = [], []
    crashes = 0
    parity_mismatches = 0
    reload_done = reload_at is None or reload_dir is None
    t0 = engine.clock()
    i, n = 0, n_requests
    while i < n or engine.queue_depth:
        now = engine.clock() - t0
        while i < n and arrivals[i] <= now:
            submitted.append(
                engine.submit(
                    payloads[i], deadline_ms=deadline_ms,
                    arrival_t=t0 + arrivals[i],
                )
            )
            i += 1
        if not reload_done and engine.dispatch_seq >= reload_at:
            engine.watch_reload()  # the mid-traffic hot swap (watcher leg)
            reload_done = True
        if engine.queue_depth:
            try:
                batch = engine.step()
            except F.InjectedFault:
                # the injected dispatch-loop death: queue intact (die fires
                # before any pop), the operator loop simply re-enters
                crashes += 1
                continue
            if verify:
                # parity under the weights active AT THIS DISPATCH — the
                # oracle runs before any later reload can swap them
                for r in batch:
                    if r.verdict == "ok" and not np.array_equal(
                        r.result, session.predict(payloads[r.id])
                    ):
                        parity_mismatches += 1
            done.extend(batch)
        elif i < n:
            time.sleep(max(0.0, arrivals[i] - (engine.clock() - t0)))
    stats = engine.record_summary(offered_rps=rate, name="chaos")
    compiles_after = counters.get("jit_compiles") if counters else None
    lost = [r.id for r in submitted if r.verdict == "queued"]
    verdicts = {}
    for r in submitted:
        verdicts[r.verdict] = verdicts.get(r.verdict, 0) + 1
    retention = None
    if base_stats and base_stats.get("goodput_rps") and stats.get("goodput_rps"):
        retention = stats["goodput_rps"] / base_stats["goodput_rps"]
    return {
        "bench": "serving_chaos",
        "bench_version": CHAOS_VERSION,
        "config": {
            "dp": session.dp,
            "pp": session.pp,
            "tp": session.tp,
            "schedule": session.schedule,
            "requests": n_requests,
            "rate": rate,
            "seed": seed,
            "slo_ms": slo_ms,
            "deadline_ms": deadline_ms,
            "faults": str(faults),
            "reload_at": reload_at,
            "reload_dir": None if reload_dir is None else str(reload_dir),
            "retry_budget": retry_budget,
            "breaker_threshold": breaker_threshold,
        },
        "submitted": len(submitted),
        "verdicts": verdicts,
        "silently_lost": lost,  # MUST be [] — the no-silent-loss invariant
        # a plan entry that never fired means the soak ended before its
        # dispatch anchor — the chaos coverage claim would be hollow
        "faults_unfired": len(engine._faults.pending_dispatch),
        "parity_mismatches": parity_mismatches,
        "crashes_recovered": crashes,
        "availability": stats.get("availability"),
        "goodput_rps": stats.get("goodput_rps"),
        "baseline_goodput_rps": base_stats.get("goodput_rps") if base_stats else None,
        "goodput_retention": retention,
        "p99_latency_s": stats.get("p99_latency_s"),
        "baseline_p99_latency_s": base_stats.get("p99_latency_s") if base_stats else None,
        "breaker_trips": stats.get("breaker_trips"),
        "reloads": stats.get("reloads"),
        "recovery_s": stats.get("recovery_s"),
        "degraded_at_exit": stats.get("degraded"),
        # the zero-recompile contract across hot reloads (None without a
        # metrics recorder on the session — the counter needs one)
        "recompiles": (
            None
            if compiles_before is None
            else int(compiles_after - compiles_before)
        ),
        "predict_cache_stable": set(
            getattr(session, "_predict_cache", {})
        ) == cache_before,
    }


def fleet_chaos_soak(
    worker_config,
    in_dim,
    n_replicas=3,
    kill_after=20,
    scale_up=True,
    n_requests=120,
    rate=300.0,
    seed=0,
    slo_ms=None,
    deadline_ms=None,
    rows_choices=(1, 2, 3, 4, 8),
    metrics=None,
    retry=2,
    policy="least_queue",
):
    """The FLEET chaos soak (``make fleet-smoke``): drive the seeded
    stream through a ``ServingFleet`` and SIGKILL one replica mid-soak —
    the honest preemption, nothing flushes — then (``scale_up=True``)
    spawn a replacement from the newest good snapshot once the death is
    detected. Returns the versioned JSON-able record.

    The kill is anchored at the ``kill_after``-th served response (a
    completion count, so it replays deterministically against the seeded
    stream) and lands on the ready replica with the MOST un-acked
    in-flight requests — the worst case failover has to re-route.

    Hard invariants the record carries (the fleet-smoke gate asserts
    them): ``silently_lost`` must be ``[]`` (every admitted request
    reaches exactly one terminal verdict, SIGKILL or not),
    ``parity_mismatches`` must be 0 (every "ok" response bitwise-equal
    to its replica's direct ``predict()``, checked in the worker before
    the pipe hop). The degradation story is measured, not guessed:
    goodput before the kill vs after, the service stall (kill -> next
    served response), failover + requeue counts, the replacement's
    spawn-to-ready wall, and the fleet's own ``recovery_s``."""
    from shallowspeed_tpu.serving.fleet import FleetError, ServingFleet

    config = dict(worker_config)
    config["verify"] = True  # the parity invariant is the point
    fleet = ServingFleet(
        config,
        n_replicas=n_replicas,
        policy=policy,
        slo_ms=slo_ms,
        retry=retry,
        metrics=metrics,
        seed=seed,
    )
    payloads = request_payloads(
        n_requests, in_dim, seed=seed, rows_choices=rows_choices
    )
    arrivals = poisson_arrivals(rate, n_requests, seed=seed)
    submitted, done, ok_times = [], [], []
    victim = None
    kill_t = None
    killed_inflight = None
    scaled = False
    scale_t = None
    initial_ready = []
    try:
        fleet.start()  # every ladder warmed before traffic
        # the initial replicas' spawn->ready walls: the cold-start
        # baseline the replacement's scale_up_s is compared against
        # (with a shared --aot-cache the FIRST replicas write the cache
        # entries concurrently, so the replacement deserializes instead
        # of recompiling — the cache-warm scoreboard)
        initial_ready = [
            w
            for w in (
                info.snapshot()["ready_wall_s"]
                for info in fleet.replicas.values()
            )
            if w is not None
        ]
        t0 = fleet.clock()
        i = 0
        while i < n_requests or fleet.queue_depth:
            now = fleet.clock() - t0
            while i < n_requests and arrivals[i] <= now:
                submitted.append(
                    fleet.submit(
                        payloads[i], deadline_ms=deadline_ms,
                        arrival_t=t0 + arrivals[i],
                    )
                )
                i += 1
            batch = fleet.step()
            done.extend(batch)
            for r in batch:
                if r.verdict == "ok":
                    ok_times.append(r.complete_t - t0)
            if victim is None and len(ok_times) >= kill_after:
                ready = [
                    info for info in fleet.replicas.values()
                    if info.state == "ready"
                ]
                if ready:
                    # the worst case: the replica holding the most
                    # un-acked work (ties to the lowest id — replayable).
                    # Wait for a moment when the victim actually HOLDS
                    # work — a kill with nothing in flight exercises
                    # death detection but not failover; the bounded
                    # fallback (twice the anchor) keeps the kill certain
                    # even if the stream never catches a replica busy
                    chosen = max(
                        ready, key=lambda r: (r.inflight, -r.replica_id)
                    )
                    if (
                        chosen.inflight >= 1
                        or len(ok_times) >= 2 * kill_after
                        or i >= n_requests
                    ):
                        victim = chosen.replica_id
                        killed_inflight = chosen.inflight
                        kill_t = fleet.clock() - t0
                        fleet.sigkill_replica(victim)
            if (
                victim is not None
                and scale_up
                and not scaled
                and any(
                    info.state == "dead" for info in fleet.replicas.values()
                )
            ):
                # elasticity as the recovery path: replacement from the
                # newest find_latest_good snapshot, warming off-path
                fleet.scale_up(wait_ready=False)
                scaled = True
                scale_t = fleet.clock() - t0
            if not fleet.queue_depth and i < n_requests:
                time.sleep(max(0.0, arrivals[i] - (fleet.clock() - t0)))
        if scaled:
            # let the replacement finish warming so its spawn-to-ready
            # wall is measured, not cut off by the soak ending first
            try:
                fleet.wait_ready()
            except FleetError:
                pass  # a failed replacement is part of the record
        end_t = fleet.clock() - t0
        stats = fleet.record_summary(offered_rps=rate)
    finally:
        fleet.stop()
    # the distributed-tracing gate (docs/observability.md § Tracing):
    # with a JSONL sink attached, re-read the parent + .r* shards and
    # assert every terminal request left a complete, clock-aligned span
    # chain — a SIGKILL that orphans a chain is a tracing bug even when
    # no request was lost (make trace-smoke gates on these fields)
    trace_chains = trace_problems = None
    metrics_path = getattr(metrics, "path", None)
    if metrics_path:
        from shallowspeed_tpu.observability import tracing
        from shallowspeed_tpu.observability.metrics import read_jsonl

        metrics.flush()
        try:
            recs = read_jsonl(f"{metrics_path}*")
        except (OSError, ValueError) as e:
            trace_problems = [f"trace shards unreadable: {e}"[:200]]
        else:
            chains = tracing.assemble_chains(recs)
            trace_chains = len(chains)
            trace_problems = tracing.verify_terminal_chains(recs, chains)
    lost = [r.id for r in submitted if r.verdict == "queued"]
    verdicts = {}
    for r in submitted:
        verdicts[r.verdict] = verdicts.get(r.verdict, 0) + 1
    # the goodput dip, measured: served rate before the kill, the service
    # stall the kill caused, and the served rate over the recovery tail
    before = [t for t in ok_times if kill_t is None or t < kill_t]
    after = [t for t in ok_times if kill_t is not None and t >= kill_t]
    goodput_before = (
        len(before) / kill_t if kill_t else None
    )
    goodput_after = (
        len(after) / (end_t - kill_t)
        if kill_t is not None and end_t > kill_t
        else None
    )
    stall_s = (min(after) - kill_t) if after else None
    return {
        "bench": "serving_fleet_chaos",
        "bench_version": FLEET_CHAOS_VERSION,
        "config": {
            "n_replicas": n_replicas,
            "policy": policy,
            "requests": n_requests,
            "rate": rate,
            "seed": seed,
            "slo_ms": slo_ms,
            "deadline_ms": deadline_ms,
            "kill_after": kill_after,
            "scale_up": scale_up,
            "fleet_retry": retry,
            "session": {
                k: str(v) if k in ("data_dir", "resume") and v else v
                for k, v in (worker_config.get("session") or {}).items()
            },
        },
        "submitted": len(submitted),
        "verdicts": verdicts,
        "silently_lost": lost,  # MUST be [] — the no-silent-loss invariant
        "parity_mismatches": stats.get("parity_mismatches"),
        # span-chain completeness over the merged shards (None without a
        # JSONL sink); trace_problems MUST be [] — zero orphan/unclosed
        # chains across the kill, the trace-smoke gate
        "trace_chains": trace_chains,
        "trace_problems": trace_problems,
        "killed_replica": victim,
        "kill_t_s": kill_t,
        # how much un-acked work the SIGKILL destroyed — 0 means the
        # bounded fallback fired on an idle replica, so a failover count
        # of 0 is the honest outcome, not a miss (the smoke gates on
        # this pair together)
        "killed_inflight": killed_inflight,
        "replicas_dead": stats.get("replicas_dead"),
        "failovers": stats.get("failovers"),
        "failover_requeued": stats.get("failover_requeued"),
        "reroutes": stats.get("reroutes"),
        "scale_ups": stats.get("scale_ups"),
        "scale_up_s": stats.get("scale_up_s"),
        # spawn->ready walls of the INITIAL replicas (cold start, or
        # cache-writing start when an aot cache is configured): the
        # baseline a cache-warm replacement's scale_up_s reads against
        "initial_ready_s": initial_ready,
        "initial_ready_s_mean": (
            sum(initial_ready) / len(initial_ready) if initial_ready else None
        ),
        "recovery_s": stats.get("recovery_s"),
        "goodput_before_rps": goodput_before,
        "goodput_after_rps": goodput_after,
        "kill_stall_s": stall_s,
        "availability": stats.get("availability"),
        "p50_latency_s": stats.get("p50_latency_s"),
        "p99_latency_s": stats.get("p99_latency_s"),
        "routing": stats.get("routing"),
        "routing_skew": stats.get("routing_skew"),
        "degraded_at_exit": stats.get("degraded"),
    }


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m shallowspeed_tpu.serving.bench_serving",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("--dp", type=int, default=1)
    ap.add_argument("--pp", type=int, default=1)
    ap.add_argument(
        "--tp", type=int, default=1,
        help="tensor (model-axis) parallelism for the served layout",
    )
    ap.add_argument(
        "--schedule",
        choices=["naive", "gpipe", "pipedream", "interleaved"],
        default="gpipe",
    )
    ap.add_argument("--global-batch-size", type=int, default=128)
    ap.add_argument("--mubatches", type=int, default=4)
    ap.add_argument("--data-dir", default=None)
    ap.add_argument(
        "--checkpoint", default=None, help="serve these weights (PR6 loader)"
    )
    ap.add_argument(
        "--rates",
        default="50,100,200,400",
        help="comma-separated offered loads (requests/second)",
    )
    ap.add_argument("--requests", type=int, default=100, help="requests per rate")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--slo-ms", type=float, default=None)
    ap.add_argument(
        "--rows",
        default="1,2,3,4,8",
        help="comma-separated request row-count choices",
    )
    ap.add_argument("--out", default=None, help="write the JSON record here")
    ap.add_argument(
        "--chaos",
        default=None,
        help="run the chaos soak instead of the sweep: a dispatch-fault "
        "spec (e.g. 'error@dispatch=3,nan@dispatch=9') injected into the "
        "seeded stream",
    )
    ap.add_argument(
        "--reload-dir",
        default=None,
        help="step-checkpoint directory the engine hot-reloads verified "
        "weights from (breaker-triggered, plus --reload-at's watcher leg)",
    )
    ap.add_argument(
        "--reload-at",
        type=int,
        default=None,
        help="trigger one mid-traffic watch_reload() once attempted "
        "dispatch N is reached",
    )
    ap.add_argument("--deadline-ms", type=float, default=None)
    ap.add_argument("--retry-budget", type=int, default=2)
    ap.add_argument("--breaker", type=int, default=2)
    ap.add_argument(
        "--max-slots",
        type=int,
        default=None,
        help="chaos soak: packing capacity per dispatch — small values "
        "spread the stream over more dispatches so every @dispatch= "
        "anchor is reached",
    )
    ap.add_argument(
        "--dispatch-floor-ms",
        type=float,
        default=0.0,
        help="per-dispatch service-time floor (engine.py 'dispatch "
        "floor'): measure the knee with the SAME floor the replay "
        "fleet's workers run, so the knee transfers to the fleet path",
    )
    ap.add_argument(
        "--chaos-out", default=None, help="write the chaos JSON record here"
    )
    ap.add_argument(
        "--fleet",
        type=int,
        default=0,
        metavar="N",
        help="run the FLEET chaos soak instead: N replica worker "
        "processes behind the router, one SIGKILLed mid-soak "
        "(docs/serving.md 'Fleet', make fleet-smoke)",
    )
    ap.add_argument(
        "--kill-after",
        type=int,
        default=20,
        help="fleet soak: SIGKILL the busiest replica once this many "
        "responses have served (a completion anchor — deterministic "
        "against the seeded stream)",
    )
    ap.add_argument(
        "--no-scale-up",
        action="store_true",
        help="fleet soak: do NOT spawn a replacement replica after the "
        "kill (measures failover without elasticity)",
    )
    ap.add_argument(
        "--fleet-policy",
        choices=["least_queue", "p2c"],
        default="least_queue",
    )
    ap.add_argument(
        "--fleet-retry",
        type=int,
        default=2,
        help="fleet-level placement budget per request",
    )
    ap.add_argument(
        "--fleet-out", default=None, help="write the fleet chaos JSON here"
    )
    ap.add_argument(
        "--aot-cache",
        default=None,
        metavar="DIR",
        help="AOT executable cache directory (shared by every fleet "
        "replica: the first replicas write entries on their cold "
        "compile, a scale-up replacement deserializes — cache-warm "
        "scale_up_s vs the initial replicas' cold ready walls is the "
        "record's scoreboard)",
    )
    ap.add_argument(
        "--metrics-out",
        default=None,
        help="JSONL sink for the chaos pass's request/serving_health/"
        "reload records (the report CLI's Degradation evidence)",
    )
    args = ap.parse_args(argv)

    from shallowspeed_tpu.api import TrainingSession
    from shallowspeed_tpu.checkpoint import STEP_CHECKPOINT_RE
    from shallowspeed_tpu.observability import JsonlMetrics

    metrics = JsonlMetrics(args.metrics_out) if args.metrics_out else None
    if args.fleet:
        return _fleet_main(args, metrics)
    session = TrainingSession(
        dp=args.dp,
        pp=args.pp,
        tp=args.tp,
        schedule=args.schedule,
        global_batch_size=args.global_batch_size,
        mubatches=args.mubatches,
        data_dir=args.data_dir,
        resume=args.checkpoint,
        metrics=metrics,
        aot_cache_dir=args.aot_cache,
    )
    if args.chaos is not None or args.reload_dir is not None:
        # a session restored from a step snapshot seeds the watcher's
        # freshness floor, so --reload-at picks up strictly NEWER weights
        loaded_step = None
        if args.checkpoint:
            m = STEP_CHECKPOINT_RE.match(os.path.basename(args.checkpoint))
            if m:
                loaded_step = int(m.group(1))
        record = chaos_soak(
            session,
            faults=args.chaos,
            n_requests=args.requests,
            rate=float(args.rates.split(",")[0]),
            seed=args.seed,
            slo_ms=args.slo_ms,
            rows_choices=tuple(
                int(r) for r in args.rows.split(",") if r.strip()
            ),
            deadline_ms=args.deadline_ms,
            metrics=metrics,
            reload_dir=args.reload_dir,
            reload_at=args.reload_at,
            loaded_step=loaded_step,
            retry_budget=args.retry_budget,
            breaker_threshold=args.breaker,
            max_slots=args.max_slots,
        )
        text = json.dumps(json_safe(record), indent=2, allow_nan=False)
        if args.chaos_out:
            with open(args.chaos_out, "w", encoding="utf-8") as f:
                f.write(text + "\n")
            print(f"chaos record written: {args.chaos_out}")
        else:
            print(text)
        print(
            f"chaos: {record['submitted']} submitted, verdicts "
            f"{record['verdicts']}, availability "
            + (
                f"{record['availability'] * 100:.1f}%"
                if record["availability"] is not None
                else "n/a"
            )
            + f", {record['breaker_trips']} breaker trip(s), "
            f"{record['reloads']} reload(s), "
            f"{record['crashes_recovered']} crash(es) recovered"
        )
        if metrics is not None:
            metrics.close()
            print(f"telemetry written: {metrics.path}")
        failures = []
        if record["silently_lost"]:
            failures.append(f"{len(record['silently_lost'])} request(s) LOST")
        if record["parity_mismatches"]:
            failures.append(
                f"{record['parity_mismatches']} parity MISMATCH(ES)"
            )
        if record["recompiles"]:
            failures.append(
                f"{record['recompiles']} recompile(s) after hot reload"
            )
        if not record["predict_cache_stable"]:
            failures.append("predict cache changed across reload")
        if failures:
            print("chaos: " + "; ".join(failures), file=sys.stderr)
            return 1
        return 0
    record = sweep(
        session,
        rates=[float(r) for r in args.rates.split(",") if r.strip()],
        n_requests=args.requests,
        seed=args.seed,
        slo_ms=args.slo_ms,
        rows_choices=tuple(int(r) for r in args.rows.split(",") if r.strip()),
        metrics=metrics,
        max_slots=args.max_slots,
        dispatch_floor_ms=args.dispatch_floor_ms,
    )
    text = json.dumps(json_safe(record), indent=2, allow_nan=False)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(text + "\n")
        print(f"bench_serving record written: {args.out}")
        knee = record["knee_rps"]
        print(
            "saturation knee: "
            + (f"{knee} rps" if knee is not None else "not reached in sweep")
        )
    else:
        print(text)
    if metrics is not None:
        metrics.close()
    return 0


def _fleet_main(args, metrics):
    """The ``--fleet N`` bench path: the fleet chaos soak (one replica
    SIGKILLed mid-soak, replacement scaled up), its JSON record, and the
    gate on its hard invariants."""
    from shallowspeed_tpu.serving.loadgen import payload_in_dim

    in_dim = payload_in_dim(args.data_dir)
    worker_config = {
        "session": dict(
            dp=args.dp,
            pp=args.pp,
            tp=args.tp,
            schedule=args.schedule,
            global_batch_size=args.global_batch_size,
            mubatches=args.mubatches,
            data_dir=args.data_dir,
            resume=args.checkpoint,
            aot_cache_dir=args.aot_cache,
        ),
        "engine": dict(
            max_slots=args.max_slots,
            slo_ms=args.slo_ms,
            retry=args.retry_budget,
            breaker_threshold=args.breaker,
            reload_dir=args.reload_dir,
        ),
    }
    record = fleet_chaos_soak(
        worker_config,
        in_dim=in_dim,
        n_replicas=args.fleet,
        kill_after=args.kill_after,
        scale_up=not args.no_scale_up,
        n_requests=args.requests,
        rate=float(args.rates.split(",")[0]),
        seed=args.seed,
        slo_ms=args.slo_ms,
        deadline_ms=args.deadline_ms,
        rows_choices=tuple(int(r) for r in args.rows.split(",") if r.strip()),
        metrics=metrics,
        retry=args.fleet_retry,
        policy=args.fleet_policy,
    )
    text = json.dumps(json_safe(record), indent=2, allow_nan=False)
    if args.fleet_out:
        with open(args.fleet_out, "w", encoding="utf-8") as f:
            f.write(text + "\n")
        print(f"fleet chaos record written: {args.fleet_out}")
    else:
        print(text)
    kill_t = record["kill_t_s"]
    print(
        f"fleet chaos: {record['submitted']} submitted, verdicts "
        f"{record['verdicts']}, replica {record['killed_replica']} "
        f"SIGKILLed at t={'n/a' if kill_t is None else f'{kill_t:.2f}s'}, "
        f"{record['failovers']} failover(s) ({record['failover_requeued']} "
        f"requeued), {record['scale_ups']} scale-up(s)"
        + (
            f" (ready in {record['scale_up_s']:.2f}s)"
            if record["scale_up_s"] is not None
            else ""
        )
        + ", availability "
        + (
            f"{record['availability'] * 100:.1f}%"
            if record["availability"] is not None
            else "n/a"
        )
    )
    if metrics is not None:
        metrics.close()
        print(f"telemetry written: {metrics.path} (+ .r* replica shards)")
    failures = []
    if record["silently_lost"]:
        failures.append(f"{len(record['silently_lost'])} request(s) LOST")
    if record["parity_mismatches"]:
        failures.append(f"{record['parity_mismatches']} parity MISMATCH(ES)")
    if record["trace_problems"]:
        failures.append(
            f"{len(record['trace_problems'])} incomplete span chain(s): "
            + "; ".join(record["trace_problems"][:3])
        )
    if record["killed_replica"] is None:
        failures.append(
            "the SIGKILL never fired (stream ended before --kill-after)"
        )
    if record["degraded_at_exit"]:
        failures.append("fleet DEGRADED at exit (quorum down)")
    if not args.no_scale_up and not record["scale_ups"]:
        failures.append("scale-up never triggered")
    if failures:
        print("fleet chaos: " + "; ".join(failures), file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
