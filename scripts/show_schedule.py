"""Render a pipeline schedule's clock-tick program as an ASCII pebble diagram.

The reference's README illustrates its schedules with a pebble-graph GIF
(README.md:41) that is a static asset; here the diagram is generated from
the ACTUAL lowered tick program, so what you see is exactly what the SPMD
executor will run — forward cells, backward cells, and the bubbles.

    python scripts/show_schedule.py gpipe --mubatches 4 --stages 4
    python scripts/show_schedule.py pipedream --backward-split
    python scripts/show_schedule.py --all

Legend: F<m> forward of microbatch m · B<m> combined backward · b<m>
backward-input (split: the relay-critical dgrad half) · W<m>
backward-weight (split: the deferred wgrad half, packed into bubbles) ·
r<m> recompute (``--recompute``: the stage forward re-run at the
backward boundary, arXiv 2004.09910 — the residual stash shrinks to one
slot, paid for with the extra forward tick) · '.' bubble (noop tick).

Each diagram prints BOTH utilizations: equal-weight (active cells / all
cells) and FLOP-weighted (a combined backward cell is 2x a forward's work;
the split halves are 1x each — the metric that can see the split win).
``--model`` resolves a model-zoo config and prints its per-stage stash
footprint under the diagram, so the recompute trade is visible in bytes,
not just cells.
"""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from shallowspeed_tpu import schedules as S  # noqa: E402
from shallowspeed_tpu.parallel.lowering import (  # noqa: E402
    OP_BWD,
    OP_BWD_W,
    OP_FWD,
    OP_RECOMPUTE,
    lower_schedule,
    program_stats,
    utilization,
    weighted_utilization,
)

ALL = {**S.SCHEDULES, "inference": S.InferenceSchedule}


def render(name, M, stages, virtual=1, backward_split=False,
           recompute=False, model=None):
    prog = lower_schedule(
        ALL[name], M, stages, virtual=virtual, backward_split=backward_split,
        recompute=recompute,
    )
    # interleaved cells carry the virtual chunk as a suffix: F2'1 = forward
    # of microbatch 2, chunk 1
    width = max(2, len(str(M - 1)) + 1) + (2 if virtual > 1 else 0)
    lines = []
    for s in range(stages):
        cells = []
        for t in range(prog.num_ticks):
            op, mb = int(prog.op[t, s]), int(prog.mb[t, s])
            ck = f"'{int(prog.chunk[t, s])}" if virtual > 1 else ""
            if op == OP_FWD:
                cells.append(f"F{mb}{ck}".ljust(width))
            elif op == OP_BWD:
                # split programs: lowercase b = B-input (dgrad half only)
                tag = "b" if prog.backward_split else "B"
                cells.append(f"{tag}{mb}{ck}".ljust(width))
            elif op == OP_BWD_W:
                cells.append(f"W{mb}{ck}".ljust(width))
            elif op == OP_RECOMPUTE:
                cells.append(f"r{mb}{ck}".ljust(width))
            else:
                cells.append(".".ljust(width))
        lines.append(f"stage {s} │ " + " ".join(cells))
    util = utilization(prog)
    wutil = weighted_utilization(prog)
    vtag = f" V={virtual}" if virtual > 1 else ""
    stag = " split-bwd" if prog.backward_split else ""
    rtag = " recompute" if prog.recompute else ""
    header = (
        f"{name}{stag}{rtag}  M={M} S={stages}{vtag}: {prog.num_ticks} ticks, "
        f"utilization {util * 100:.0f}% (bubbles {100 - util * 100:.0f}%) · "
        f"weighted {wutil * 100:.0f}% (bubbles {100 - wutil * 100:.0f}%)"
    )
    print(header)
    print("─" * len(header))
    tick_hdr = "        │ " + " ".join(str(t).ljust(width) for t in range(prog.num_ticks))
    print(tick_hdr)
    for line in lines:
        print(line)
    if prog.recompute or model:
        # the stash story in slots (and, with --model, bytes from the
        # real spec's padded slot shapes): what the r<m> cells buy
        parts = [
            f"stash: {prog.n_stash_slots} residual slot(s)"
            + (f" + {prog.n_xin_slots} input slot(s)" if prog.recompute else "")
        ]
        if model:
            from shallowspeed_tpu import model as Mo
            from shallowspeed_tpu.api import FLAGSHIP_BATCH
            from shallowspeed_tpu.observability.program_audit import (
                format_bytes,
            )

            sizes, act = Mo.resolve_model(model)
            spec = Mo.make_model_spec(
                sizes, stages * virtual, FLAGSHIP_BATCH, act=act
            )
            stats = program_stats(
                prog, spec=spec, mubatch_size=FLAGSHIP_BATCH // M
            )
            parts.append(
                f"peak {format_bytes(stats['stash_bytes_peak'])}/device "
                f"[{model}, B={FLAGSHIP_BATCH}]"
            )
        print("  ".join(parts))
    print()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("schedule", nargs="?", choices=sorted(ALL), default=None)
    ap.add_argument("--mubatches", "-m", type=int, default=4)
    ap.add_argument("--stages", "-s", type=int, default=4)
    ap.add_argument(
        "--virtual", "-v", type=int, default=1,
        help="virtual stages per device (interleaved schedule only)",
    )
    ap.add_argument(
        "--backward-split", action="store_true",
        help="render the two-stage backward variant: b<m> = B-input at the "
        "combined backward's tick, W<m> = deferred B-weight packed into "
        "bubbles (gpipe/pipedream/naive)",
    )
    ap.add_argument(
        "--recompute", action="store_true",
        help="render the activation-recompute variant: r<m> = the stage "
        "forward re-run at microbatch m's backward boundary (the residual "
        "stash shrinks to 1 slot; gpipe/pipedream/naive)",
    )
    ap.add_argument(
        "--model", default=None,
        help="model-zoo config (model.MODEL_ZOO): print the rendered "
        "program's peak stash bytes for this model under the diagram",
    )
    ap.add_argument(
        "--all",
        action="store_true",
        help="render every schedule, including the forward-only inference relay",
    )
    args = ap.parse_args()
    if args.schedule and not args.all:
        names = [args.schedule]
    elif args.all:
        names = sorted(ALL)
    else:
        names = sorted(S.SCHEDULES)
    for name in names:
        v = args.virtual if name == "interleaved" else 1
        if name == "interleaved" and args.mubatches % args.stages != 0:
            if args.schedule == "interleaved":
                raise SystemExit(
                    f"interleaved needs M % S == 0 (got M={args.mubatches}, "
                    f"S={args.stages})"
                )
            print(
                f"interleaved  (skipped: needs M % S == 0, got "
                f"M={args.mubatches}, S={args.stages})\n"
            )
            continue
        # split/recompute apply to the flat training schedules only (the
        # inference relay has no backward; interleaved is lowering-rejected)
        split = args.backward_split and name not in ("interleaved", "inference")
        if args.backward_split and name in ("interleaved", "inference"):
            if args.schedule == name:
                raise SystemExit(f"--backward-split does not apply to {name}")
            print(f"{name}  (rendered without --backward-split)\n")
        rec = args.recompute and name not in ("interleaved", "inference")
        if args.recompute and name in ("interleaved", "inference"):
            if args.schedule == name:
                raise SystemExit(f"--recompute does not apply to {name}")
            print(f"{name}  (rendered without --recompute)\n")
        render(name, args.mubatches, args.stages, virtual=v,
               backward_split=split, recompute=rec, model=args.model)


if __name__ == "__main__":
    main()
