"""Load generation for the serving engine: seeded arrivals + drive modes.

Everything is seeded and deterministic — two runs with the same seed offer
the identical request stream (sizes, contents, arrival times), which is what
lets ``make serve-smoke`` assert bitwise response parity under load and the
bench sweep compare rates on the same workload.

Two drive modes (the classic load-testing pair):

- **open loop** (``run_open_loop``): requests arrive on a Poisson schedule
  REGARDLESS of completions — the arrival process models independent users,
  so queueing delay shows up as latency instead of silently throttling the
  offered load. Enqueue timestamps are backdated to the scheduled arrival
  (the coordinated-omission correction): a request that arrived while the
  engine was busy is charged its full wait.
- **closed loop** (``run_closed_loop``): a fixed population of
  ``concurrency`` outstanding requests, each completion immediately
  replaced — measures the engine's sustainable service rate with bounded
  queue depth.

Clock-domain contract (docs/observability.md § Tracing): both drivers
read ``engine.clock`` — the clock of the process that ADMITS requests —
so every timestamp they produce (scheduled arrivals, the backdated
``arrival_t``, deadline budgets) lives in that one clock domain. Driving
a ``ServingFleet``, that is the PARENT process's ``perf_counter``: the
fleet's request records and parent-side trace spans share it end to end,
while each worker's ``.r*`` shard records its own clock's values, which
only the fleet handshake's per-replica ``clock_offset`` estimate can
place on this timeline. Never compare raw timestamps across the two
domains — join them through ``observability.tracing``, which aligns (or
refuses, when no offset was recorded) instead of guessing.
"""

import os
import time

import numpy as np

from shallowspeed_tpu.faults import InjectedFault


def _step_reentrant(engine):
    """One engine.step() under the operator-loop contract: an injected
    dispatch-loop death (``die@dispatch=N``, mode=exc) fires BEFORE any
    request is popped, so the queue is intact — the drivers catch it and
    simply re-enter on the next iteration, which is the re-entry the
    fault models (``mode=sigkill`` still kills the process honestly).
    Real dispatch exceptions are the ENGINE's to recover (re-queue +
    retry budget) and never reach here."""
    try:
        return engine.step()
    except InjectedFault:
        return []


def payload_in_dim(data_dir, default=784):
    """The request payload width for a fleet CLI: the data layer's
    training-split width when ``data_dir`` holds one, else ``default``
    (the flagship MLP's MNIST input). The fleet parent never builds a
    session of its own, so it reads the dimension the way the worker
    sessions will."""
    if data_dir:
        x_path = os.path.join(os.fspath(data_dir), "x_train.npy")
        if os.path.exists(x_path):
            return int(np.load(x_path, mmap_mode="r").shape[1])
    return int(default)


def poisson_arrivals(rate_rps, n, seed=0):
    """``n`` seeded Poisson arrival times (seconds from start): cumulative
    exponential interarrivals at ``rate_rps`` requests/second."""
    if rate_rps <= 0:
        raise ValueError("rate_rps must be positive")
    if n < 1:
        raise ValueError("n must be >= 1")
    rng = np.random.RandomState(seed)
    return np.cumsum(rng.exponential(1.0 / rate_rps, size=n))


def request_payloads(n, in_dim, seed=0, rows_choices=(1, 2, 3, 4, 8), data=None):
    """``n`` seeded variable-size request payloads, each ``(rows, in_dim)``
    float32 with ``rows`` drawn from ``rows_choices``. ``data``: an
    optional ``(N, in_dim)`` pool (e.g. the validation split) to sample
    real rows from; default is standard-normal synthetic inputs."""
    rng = np.random.RandomState(seed)
    sizes = rng.choice(list(rows_choices), size=n)
    payloads = []
    for rows in sizes:
        if data is not None:
            idx = rng.randint(0, data.shape[0], size=int(rows))
            payloads.append(np.asarray(data[idx], np.float32))
        else:
            payloads.append(rng.randn(int(rows), in_dim).astype(np.float32))
    return payloads


def run_open_loop(
    engine, payloads, arrivals, deadline_ms=None, sleep=time.sleep,
    should_stop=None, on_tick=None, tick_s=0.05,
):
    """Replay ``payloads`` against the engine on the ``arrivals`` schedule
    (seconds from start, one per payload); returns the completed requests.

    Single-threaded approximation of an open-loop client: all due arrivals
    are submitted (backdated to their scheduled time), then one batching
    step serves the queue's head; the host sleeps only when idle. The
    engine drains fully before returning.

    Deadline semantics: ``deadline_ms`` counts from the SCHEDULED arrival
    (the backdated ``arrival_t``), so a request that sat unsubmitted while
    the host was busy has already burned queue time against its deadline —
    the coordinated-omission-corrected reading (contrast the closed-loop
    driver below).

    ``should_stop``: an optional zero-arg callable polled each iteration —
    the graceful-drain hook (serving ``__main__``'s SIGTERM/SIGINT
    handler): once it returns True, ADMISSION stops (remaining payloads
    are never submitted) but everything already queued is drained to a
    terminal verdict before returning.

    ``on_tick``: an optional ``on_tick(elapsed_s)`` callable invoked once
    per loop iteration with seconds since the drive started — the
    autoscaler's poll hook (``serving/autoscaler.py``): the policy makes
    its between-edge decisions here, on the driver thread, so scaling
    actions never race the submit/step loop. When set, idle sleeps are
    capped at ``tick_s`` so the policy keeps observing through quiet
    troughs instead of sleeping until the next arrival."""
    if len(payloads) != len(arrivals):
        raise ValueError("one arrival time per payload")
    t0 = engine.clock()
    done, i, n = [], 0, len(payloads)
    while i < n or engine.queue_depth:
        if should_stop is not None and should_stop():
            while engine.queue_depth:
                done.extend(_step_reentrant(engine))
            break
        if on_tick is not None:
            on_tick(engine.clock() - t0)
        now = engine.clock() - t0
        while i < n and arrivals[i] <= now:
            engine.submit(
                payloads[i], deadline_ms=deadline_ms, arrival_t=t0 + arrivals[i]
            )
            i += 1
        if engine.queue_depth:
            done.extend(_step_reentrant(engine))
        elif i < n:
            idle = max(0.0, arrivals[i] - (engine.clock() - t0))
            sleep(min(idle, tick_s) if on_tick is not None else idle)
    return done


def run_closed_loop(
    engine, payloads, concurrency=4, deadline_ms=None, should_stop=None
):
    """Drive a fixed in-flight population: keep ``concurrency`` requests
    queued, submitting the next as completions free slots; returns the
    completed requests. ``should_stop`` is the same graceful-drain hook as
    ``run_open_loop``'s.

    Deadline semantics — deliberately DIFFERENT from the open loop: a
    closed-loop driver never backdates arrivals (there is no arrival
    schedule — the population model admits a request the moment a slot
    frees), so ``deadline_ms`` counts from the SUBMIT-time clock and
    ``met_deadline``/``slo_ok`` score pure service latency with no queue
    backlog charged. Pinned by ``test_closed_vs_open_loop_deadline_
    accounting``; use the open loop when coordinated-omission-corrected
    tails are the question."""
    if concurrency < 1:
        raise ValueError("concurrency must be >= 1")
    done, i, n = [], 0, len(payloads)
    while i < n or engine.queue_depth:
        if should_stop is not None and should_stop():
            while engine.queue_depth:
                done.extend(_step_reentrant(engine))
            break
        while i < n and engine.queue_depth < concurrency:
            engine.submit(payloads[i], deadline_ms=deadline_ms)
            i += 1
        done.extend(_step_reentrant(engine))
    return done
