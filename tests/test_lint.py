"""House-rule linter tests: the fixture corpus (every bad snippet one
finding with file:line, every good twin clean), the suppression idiom,
the schema-kind registry plumbing, the JSON report shape — and the gate
itself: the repo at HEAD must lint clean (`make lint` inside tier-1)."""

import json
from pathlib import Path

import pytest

from shallowspeed_tpu.analysis import lint as lint_cli
from shallowspeed_tpu.analysis.rules import (
    Scope,
    lint_file,
    lint_source,
    load_schema_kinds,
    scope_for,
)

FIXTURES = Path(__file__).parent / "lint_fixtures"

# (fixture stem, expected rule, scope override forcing the path-scoped
# rules on — fixture files live under tests/, outside the real scopes)
CORPUS = (
    ("broad_except", "BLE001", Scope()),
    ("metrics_nan", "SSP002", Scope(metrics_path=True)),
    ("raw_write", "SSP003", Scope(atomic_module=True)),
    ("donation", "SSP004", Scope()),
    ("kind_registry", "SSP005", Scope()),
    ("lock_discipline", "SSP006", Scope()),
)


def _marker_line(path):
    for i, line in enumerate(path.read_text().splitlines(), start=1):
        if "# MARK" in line:
            return i
    raise AssertionError(f"{path}: no # MARK line")


@pytest.mark.parametrize("stem,rule,scope", CORPUS, ids=[c[0] for c in CORPUS])
def test_bad_fixture_produces_exactly_one_finding(stem, rule, scope):
    """Each known-bad snippet yields EXACTLY one finding, of the expected
    rule, anchored at the marked file:line — the refusal is actionable."""
    path = FIXTURES / "bad" / f"{stem}.py"
    findings = lint_file(path, scope=scope)
    assert len(findings) == 1, findings
    f = findings[0]
    assert f.rule == rule
    assert f.path == str(path)
    assert f.line == _marker_line(path)
    assert f"{path}:{f.line}" in f.format()


@pytest.mark.parametrize("stem,rule,scope", CORPUS, ids=[c[0] for c in CORPUS])
def test_good_twin_is_clean(stem, rule, scope):
    findings = lint_file(FIXTURES / "good" / f"{stem}.py", scope=scope)
    assert findings == [], [f.format() for f in findings]


def test_repo_is_lint_clean():
    """The gate: `make lint` must exit 0 on HEAD — every rule the linter
    enforces holds (or is justified) across the whole lintable tree.
    Running it here puts the lint gate inside tier-1."""
    findings, n_files = lint_cli.lint_paths()
    assert n_files > 40  # the real tree, not an accidental empty walk
    assert findings == [], "\n".join(f.format() for f in findings)


def test_justified_noqa_suppresses_and_bare_noqa_does_not():
    bad = "try:\n    pass\nexcept Exception:  {}\n    pass\n"
    justified = bad.format("# noqa: BLE001 — probe only, absence is fine")
    assert lint_source(justified, path="x.py") == []
    bare = bad.format("# noqa: BLE001")
    assert [f.rule for f in lint_source(bare, path="x.py")] == ["BLE001"]
    wrong_rule = bad.format("# noqa: SSP002 — not the rule that fired")
    assert [f.rule for f in lint_source(wrong_rule, path="x.py")] == ["BLE001"]


def test_broad_except_that_reraises_is_lawful():
    src = (
        "try:\n    pass\n"
        "except BaseException:\n    cleanup = 1\n    raise\n"
    )
    assert lint_source(src, path="x.py") == []


def test_non_literal_kind_is_refused():
    src = (
        "class R:\n"
        "    def _emit(self, r):\n        pass\n"
        "    def go(self, kind):\n"
        "        self._emit({'kind': kind, 'name': 'x'})\n"
    )
    findings = lint_source(src, path="x.py")
    assert [f.rule for f in findings] == ["SSP005"]
    assert "string literal" in findings[0].message


def test_schema_kinds_registry_matches_metrics():
    """The AST-parsed registry equals the imported one — the linter's
    ground truth can never drift from what the recorders actually emit."""
    from shallowspeed_tpu.observability.metrics import (
        SCHEMA_KINDS,
        SCHEMA_VERSION,
    )

    parsed = load_schema_kinds()
    assert parsed == SCHEMA_KINDS
    assert parsed["static_analysis"] == 9
    assert max(parsed.values()) == SCHEMA_VERSION


def test_scope_for_real_paths():
    assert scope_for("shallowspeed_tpu/observability/metrics.py").metrics_path
    assert scope_for("shallowspeed_tpu/serving/engine.py").metrics_path
    assert scope_for("shallowspeed_tpu/checkpoint.py").atomic_module
    assert scope_for("shallowspeed_tpu/aot_cache.py").atomic_module
    assert scope_for("shallowspeed_tpu/trainer.py").donation_ok
    assert scope_for("shallowspeed_tpu/parallel/executor.py").donation_ok
    neutral = scope_for("shallowspeed_tpu/api.py")
    assert not (
        neutral.metrics_path or neutral.atomic_module or neutral.donation_ok
    )


def test_cli_exit_codes_and_json_report(capsys):
    """Exit 2 + file:line text on findings, exit 0 clean, and the stable
    --format json shape (lint_report_version, findings, counts)."""
    bad = str(FIXTURES / "bad" / "broad_except.py")
    good = str(FIXTURES / "good" / "broad_except.py")
    assert lint_cli.main([good]) == 0
    out = capsys.readouterr().out
    assert "clean: 0 findings" in out
    assert lint_cli.main([bad]) == 2
    out = capsys.readouterr().out
    assert f"{bad}:{_marker_line(Path(bad))}" in out and "BLE001" in out
    assert lint_cli.main([bad, "--format", "json"]) == 2
    rep = json.loads(capsys.readouterr().out)
    assert rep["lint_report_version"] == lint_cli.LINT_REPORT_VERSION
    assert rep["files_scanned"] == 1
    assert rep["counts"] == {"BLE001": 1}
    assert rep["findings"][0]["rule"] == "BLE001"
    assert rep["findings"][0]["path"] == bad
    assert rep["findings"][0]["line"] == _marker_line(Path(bad))
    assert lint_cli.main(["/nonexistent/nope.py"]) == 1


def test_cli_metrics_out_records_lint_verdict(tmp_path, capsys):
    """--metrics-out appends the static_analysis record named 'lint'
    with the rule ids and per-rule finding counts (stamped with the
    CURRENT schema version — the pin itself lives with the newest
    schema's test, per the bump convention)."""
    from shallowspeed_tpu.observability import SCHEMA_VERSION, read_jsonl

    bad = str(FIXTURES / "bad" / "broad_except.py")
    out = tmp_path / "lint.jsonl"
    assert lint_cli.main([bad, "--metrics-out", str(out)]) == 2
    capsys.readouterr()
    recs = [r for r in read_jsonl(out) if r["kind"] == "static_analysis"]
    assert len(recs) == 1
    r = recs[0]
    assert r["name"] == "lint" and r["v"] == SCHEMA_VERSION
    assert r["findings"] == 1 and r["by_rule"] == {"BLE001": 1}
    assert r["passes"] == sorted(
        ("BLE001", "SSP002", "SSP003", "SSP004", "SSP005", "SSP006")
    )
    assert any("broad_except.py" in line for line in r["finding_lines"])


def test_syntax_error_is_a_finding_not_a_crash(tmp_path):
    p = tmp_path / "broken.py"
    p.write_text("def f(:\n")
    findings = lint_file(p)
    assert [f.rule for f in findings] == ["E999"]


def test_default_targets_exclude_tests():
    """The fixture corpus must never fail the repo gate: tests/ is not in
    the default lint walk."""
    files = lint_cli.iter_target_files()
    assert not any("lint_fixtures" in str(f) for f in files)
    assert not any(f.name == "test_lint.py" for f in files)
    assert any(f.name == "metrics.py" for f in files)
    assert any(f.name == "lowering.py" for f in files)
