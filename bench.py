"""Benchmark: MNIST-MLP training samples/sec/chip vs the NumPy reference.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "samples/s", "vs_baseline": N}

Protocol (BASELINE.md: the reference publishes no numbers, so the baseline is
measured here): train the flagship 7-layer MLP (sizes [784,128,...,10],
GLOBAL_BATCH=128, 4 microbatches, SGD lr=0.006) on MNIST-sized data and
report end-to-end training throughput.

- baseline: an independent NumPy implementation of the identical training
  step (microbatch grad accumulation, global-batch loss scaling) timed on
  this host's CPU — the reference's compute engine (NumPy+BLAS) doing the
  reference's exact work.
- value: this framework's jitted whole-epoch lax.scan on the default JAX
  device (the TPU chip when run by the driver).
- vs_baseline: value / baseline  (>1 = faster than the NumPy reference).
"""

import json
import os
import subprocess
import sys
import time

import numpy as np


def _ensure_responsive_backend(probe_timeout_s=180):
    """Never hang the benchmark on a wedged accelerator tunnel.

    Backend init for a remote-tunneled TPU can block indefinitely if the
    chip's claim is held by a dead client. When the tunnel plugin is active
    (PALLAS_AXON_POOL_IPS — the only configuration where the hang exists),
    probe device init in a subprocess; on timeout or init failure, fall back
    to the CPU platform. Returns a reason tag ('' = healthy) so the caller
    can label the published metric honestly and distinguish a hung tunnel
    from a backend that failed fast.

    stdout goes to DEVNULL and stderr to a temp FILE (never a pipe): a tunnel
    helper grandchild surviving the timeout kill would keep a captured pipe
    open and make the probe itself hang in communicate(), while a file lets
    us still report the backend's last error line.
    """
    if not os.environ.get("PALLAS_AXON_POOL_IPS"):
        return ""  # no tunnel plugin, nothing to guard (and nothing to pay)
    # stderr goes to a FILE, not a pipe: a tunnel-helper grandchild surviving
    # the timeout kill would hold a pipe open and hang the probe itself
    import tempfile

    with tempfile.TemporaryFile() as errf:
        try:
            subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                timeout=probe_timeout_s,
                check=True,
                stdout=subprocess.DEVNULL,
                stderr=errf,
            )
            return ""
        except subprocess.TimeoutExpired:
            detail = f"unresponsive (> {probe_timeout_s}s to init)"
            tag = "_CPU_FALLBACK_TUNNEL_UNRESPONSIVE"
        except subprocess.CalledProcessError:
            # e.g. "UNAVAILABLE: TPU backend setup/compile error" — the real
            # run would die the same way; a degraded CPU number beats none
            errf.seek(0)
            tail = errf.read().decode(errors="replace").strip().splitlines()
            detail = f"failed to initialize ({tail[-1] if tail else 'no stderr'})"
            tag = "_CPU_FALLBACK_BACKEND_INIT_FAILED"
    print(f"bench: accelerator backend {detail}; falling back to CPU", file=sys.stderr)
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    return tag

from shallowspeed_tpu.api import (  # the reference's canonical config
    FLAGSHIP_BATCH as B,
    FLAGSHIP_LR as LR,
    FLAGSHIP_MUBATCHES as M,
    FLAGSHIP_SIZES as SIZES,
)
N_SAMPLES = 59392  # MNIST train size after drop-last to 128-multiples


def numpy_baseline_sps(n_batches=40):
    """Fresh NumPy training step (reference-equivalent math), timed."""
    from shallowspeed_tpu.init import linear_init

    params = [linear_init(SIZES[i], SIZES[i + 1]) for i in range(len(SIZES) - 1)]
    rng = np.random.RandomState(0)
    xb = rng.randn(M, B // M, SIZES[0]).astype(np.float32)
    yb = np.eye(SIZES[-1], dtype=np.float32)[rng.randint(0, SIZES[-1], (M, B // M))]

    def train_batch(params):
        acc = [(np.zeros_like(w), np.zeros_like(b)) for w, b in params]
        n = len(params)
        for x, t in zip(xb, yb):
            caches = []
            for i, (w, b) in enumerate(params):
                z = x @ w.T + b
                if i < n - 1:
                    caches.append((x, z > 0))
                    x = np.maximum(z, 0.0)
                else:
                    caches.append((x, None))
                    x = z
            ze = np.exp(x - np.max(x))
            p = ze / (ze.sum(axis=1, keepdims=True) + 1e-7)
            g = -2.0 * (t - p) / B
            gz = p * g
            g = gz - p * gz.sum(axis=1, keepdims=True)
            for i in reversed(range(n)):
                xi, mask = caches[i]
                if mask is not None:
                    g = g * mask
                acc[i] = (acc[i][0] + g.T @ xi, acc[i][1] + g.sum(0, keepdims=True))
                g = g @ params[i][0]
        return [
            (w - LR * gw, b - LR * gb) for (w, b), (gw, gb) in zip(params, acc)
        ]

    params = train_batch(params)  # warm BLAS
    t0 = time.perf_counter()
    for _ in range(n_batches):
        params = train_batch(params)
    dt = time.perf_counter() - t0
    return n_batches * B / dt


def jax_sps(n_epochs=5):
    import jax
    import jax.numpy as jnp

    from shallowspeed_tpu import model as Mo
    from shallowspeed_tpu import trainer
    from shallowspeed_tpu.optimizer import SGD

    spec = Mo.make_model_spec(SIZES, 1, B)
    params = jax.tree.map(jnp.asarray, Mo.init_model(spec))
    # fuse_mubatches: identical training (sum-gradient ledger), one full-batch
    # forward/backward per step — the TPU-shaped way to run the sequential
    # path. unroll: batch-scan unroll factor (bit-identical numerics); the
    # default can be overridden with the value scripts/tpu_capture.py measures
    # best on the chip.
    unroll = int(os.environ.get("SHALLOWSPEED_BENCH_UNROLL", "1"))
    epoch = trainer.make_train_epoch(
        spec, SGD(LR), fuse_mubatches=True, unroll=unroll
    )

    nb = N_SAMPLES // B
    rng = np.random.RandomState(0)
    X = jnp.asarray(rng.rand(nb, M, B // M, SIZES[0]).astype(np.float32))
    Y = jnp.asarray(
        np.eye(SIZES[-1], dtype=np.float32)[rng.randint(0, SIZES[-1], (nb, M, B // M))]
    )

    state = ()
    params, state, _ = epoch(params, state, X, Y)  # compile + warmup
    jax.block_until_ready(params)
    t0 = time.perf_counter()
    for _ in range(n_epochs):
        params, state, _ = epoch(params, state, X, Y)
    jax.block_until_ready(params)
    dt = time.perf_counter() - t0
    return n_epochs * nb * B / dt


def main():
    fallback_tag = _ensure_responsive_backend()
    baseline = numpy_baseline_sps()
    value = jax_sps()
    # a degraded run is unmistakable in the recorded metric itself
    metric = "mnist_mlp_train_samples_per_sec_per_chip" + fallback_tag
    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(value, 1),
                "unit": "samples/s",
                "vs_baseline": round(value / baseline, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
