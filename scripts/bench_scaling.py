"""Scaling benchmark: the five BASELINE.md configs, samples/sec + efficiency.

Measures MNIST-MLP training throughput for:
    seq          sequential (1 device)
    dp4          DP=4
    pp4-naive    PP=4, naive schedule
    pp4-gpipe    PP=4, GPipe
    dp2pp4-gpipe DP=2 x PP=4 (8 devices)

and reports samples/sec plus scaling efficiency vs the sequential run
(efficiency = throughput / (n_devices * seq_throughput)). Emits one JSON line
per config. Configs needing more devices than available are skipped with a
note (a single-chip host runs only `seq`; use the 8-virtual-device CPU mesh
to exercise the rest:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 ...).

NOTE on interpretation: pipeline parallelism on this tiny MLP exists to
demonstrate/validate the machinery (the reference is an educational
framework); per-device efficiency is expected to be <1 because the model is
far too small to fill a pipeline — the numbers quantify schedule overhead
(naive vs GPipe vs 1F1B bubbles), which is exactly what the reference's
pebble diagrams illustrate.
"""

import argparse
import json
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from shallowspeed_tpu.api import (  # the reference's canonical config
    FLAGSHIP_BATCH as B,
    FLAGSHIP_LR as LR,
    FLAGSHIP_MUBATCHES as M,
    FLAGSHIP_SIZES as SIZES,
)


def _data(nb, rng, sizes=SIZES):
    X = rng.rand(nb, B, sizes[0]).astype(np.float32)
    Y = np.eye(sizes[-1], dtype=np.float32)[rng.randint(0, sizes[-1], (nb, B))]
    return X, Y


# 16-size flagship-class list: 15 Linears over up to 8 stages, so every
# stage owns at least one Linear — avoids the reference's 0-Linear
# partitioning quirk that changes the MODEL when 8 stages meet 8 sizes
# (reference layers.py:253-257; see BASELINE.md round-2 convergence notes).
# Rows on this list compare against the seq16 reference row, not seq.
SIZES16 = (784, 256, 224, 192, 176, 160, 144, 128, 112, 96, 80, 64, 48, 32, 16, 10)


def bench_sequential(nb, reps, sizes=SIZES, act="relu"):
    import jax
    import jax.numpy as jnp

    from shallowspeed_tpu import model as Mo
    from shallowspeed_tpu import trainer
    from shallowspeed_tpu.optimizer import SGD

    spec = Mo.make_model_spec(sizes, 1, B, act=act)
    params = jax.tree.map(jnp.asarray, Mo.init_model(spec))
    epoch = trainer.make_train_epoch(spec, SGD(LR))
    X, Y = _data(nb, np.random.RandomState(0), sizes=sizes)
    Xe = jnp.asarray(X.reshape(nb, M, B // M, -1))
    Ye = jnp.asarray(Y.reshape(nb, M, B // M, -1))
    st = ()
    params, st, _ = epoch(params, st, Xe, Ye)
    jax.block_until_ready(params)
    t0 = time.perf_counter()
    for _ in range(reps):
        params, st, _ = epoch(params, st, Xe, Ye)
    jax.block_until_ready(params)
    return reps * nb * B / (time.perf_counter() - t0)


def _pipeline_epoch_setup(
    dp, pp, sched_name, nb, virtual=1, sizes=SIZES, zero1=False,
    optimizer=None, grad_bucket_bytes=0, backward_split=False, tp=1,
    digests=False, act="relu", recompute=False,
):
    """Build one mesh config's epoch fn + initial state + data: the shared
    setup behind the plain timing rows and the same-window pairs. Returns
    the lowered TickProgram first, so pair benchmarks that record program
    metrics describe exactly the program they time."""
    import jax.numpy as jnp

    from shallowspeed_tpu import model as Mo
    from shallowspeed_tpu import schedules as S
    from shallowspeed_tpu.optimizer import SGD, make_optimizer
    from shallowspeed_tpu.parallel import executor as E
    from shallowspeed_tpu.parallel import lower_schedule, make_mesh

    mesh = make_mesh(dp, pp, tp=tp)
    spec = Mo.make_model_spec(sizes, pp * virtual, B, act=act)
    order = E.interleave_order(pp * virtual, pp) if virtual > 1 else None
    prog = lower_schedule(
        S.SCHEDULES[sched_name], M, pp, virtual=virtual,
        backward_split=backward_split, recompute=recompute,
    )
    stacked, flags = E.init_stacked(spec, mesh, order=order)
    opt = make_optimizer(optimizer, 2e-4) if optimizer else SGD(LR)
    epoch = E.make_pipeline_epoch(
        mesh, spec, prog, B // dp // M, opt, zero1=zero1,
        grad_bucket_bytes=grad_bucket_bytes, with_digests=digests,
    )
    st = E.zero1_init_state(opt, spec, mesh) if zero1 else opt.init(stacked)
    X, Y = _data(nb, np.random.RandomState(0), sizes=sizes)
    return prog, epoch, stacked, flags, st, jnp.asarray(X), jnp.asarray(Y)


def bench_pipeline(
    dp, pp, sched_name, nb, reps, virtual=1, sizes=SIZES, zero1=False,
    optimizer=None,
):
    import jax

    _, epoch, stacked, flags, st, Xj, Yj = _pipeline_epoch_setup(
        dp, pp, sched_name, nb, virtual=virtual, sizes=sizes, zero1=zero1,
        optimizer=optimizer,
    )
    stacked, st, _ = epoch(stacked, flags, st, Xj, Yj)
    jax.block_until_ready(stacked["W"])
    t0 = time.perf_counter()
    for _ in range(reps):
        stacked, st, _ = epoch(stacked, flags, st, Xj, Yj)
    jax.block_until_ready(stacked["W"])
    return reps * nb * B / (time.perf_counter() - t0)


# anchor-vs-bucketed gradient-sync pairs (dp and ZeRO-1): measured with
# bench.py's interleaved-trial slope protocol so each pair shares its
# contention window — the ratio is same-window, like the TPU captures'.
# On emulated CPU devices these rows validate the machinery and record
# the bucket plan; the RATIO only means something on a real multi-chip
# mesh (one CPU host has no interconnect to overlap against).
GRAD_SYNC_BUCKET_BYTES = 65536
SYNC_PAIRS = [
    ("dp2", dict(dp=2, pp=1, sched="gpipe")),
    ("dp2-zero1", dict(dp=2, pp=1, sched="gpipe", zero1=True)),
]


def bench_sync_pair(name, cfg, nb, sizes=SIZES, act="relu", model=None):
    """One anchor-vs-bucketed pair, same-window: returns a list of record
    dicts (one per mode) carrying grad_bucket_bytes + bucket count so a
    MULTICHIP capture of these rows is self-describing."""
    from bench import make_run_k, slope_epoch_seconds_many

    from shallowspeed_tpu import model as Mo
    from shallowspeed_tpu.parallel import gradsync

    dp, pp = cfg["dp"], cfg["pp"]
    zero1 = cfg.get("zero1", False)
    spec = Mo.make_model_spec(sizes, pp, B, act=act)
    plan = gradsync.plan_buckets(
        spec, dp, pp, GRAD_SYNC_BUCKET_BYTES, zero1=zero1
    )
    modes = {f"{name}-anchor": 0, f"{name}-bucketed": GRAD_SYNC_BUCKET_BYTES}
    run_ks = {}
    for label, gbb in modes.items():
        _, epoch, stacked, flags, st, Xj, Yj = _pipeline_epoch_setup(
            dp, pp, cfg["sched"], nb, zero1=zero1, grad_bucket_bytes=gbb,
            sizes=sizes, act=act,
        )

        def epoch_fn(p, s, X, Y, _epoch=epoch, _flags=flags):
            return _epoch(p, _flags, s, X, Y)

        run_ks[label] = make_run_k(epoch_fn, stacked, st, Xj, Yj)
    # min_delta_s=0: no tunnel transport constants to resolve above on a
    # local backend — fixed short legs, trials still interleaved
    slopes = slope_epoch_seconds_many(
        run_ks, k1=1, k2=3, trials=2, min_delta_s=0
    )
    anchor_sps = nb * B / slopes[f"{name}-anchor"]
    records = []
    for label, gbb in modes.items():
        sps = nb * B / slopes[label]
        records.append(
            {
                "config": label,
                "devices": dp * pp,
                "samples_per_sec": round(sps, 1),
                "model": model,
                "grad_bucket_bytes": gbb,
                "grad_buckets": plan.num_buckets if gbb else 0,
                "zero1": zero1,
                "same_window": True,
                "vs_anchor": round(sps / anchor_sps, 4),
            }
        )
    return records


# digests-off vs digests-on pairs: same-window via the interleaved-trial
# slope protocol. The digest aux (per-layer uint32 checksums + norms as
# extra scan ys, one psum over the pipeline axes — docs/numerics.md
# § Divergence debugging) is designed to be cheap next to the matmuls;
# this pair MEASURES that claim instead of asserting it. Records carry
# `digests` so a multichip capture of these rows is self-describing.
DIGEST_PAIRS = [
    ("dp2-digests", dict(dp=2, pp=1, sched="gpipe")),
    ("pp4-gpipe-digests", dict(dp=1, pp=4, sched="gpipe")),
]


def bench_digest_pair(name, cfg, nb):
    """One digests-off-vs-on pair, same-window: returns a list of record
    dicts (one per mode) carrying the digests flag + vs_off ratio — the
    measured on-path overhead of the numerics-provenance aux."""
    from bench import make_run_k, slope_epoch_seconds_many

    dp, pp = cfg["dp"], cfg["pp"]
    modes = {f"{name}-off": False, f"{name}-on": True}
    run_ks = {}
    for label, dig in modes.items():
        _, epoch, stacked, flags, st, Xj, Yj = _pipeline_epoch_setup(
            dp, pp, cfg["sched"], nb, digests=dig
        )

        # the digests leg returns a 4th output (the digest aux) — the
        # timed loop still carries it to the host boundary, which is the
        # honest cost, but bench's run_k unpacks 3
        def epoch_fn(p, s, X, Y, _epoch=epoch, _flags=flags):
            out = _epoch(p, _flags, s, X, Y)
            return out[0], out[1], out[2]

        run_ks[label] = make_run_k(epoch_fn, stacked, st, Xj, Yj)
    slopes = slope_epoch_seconds_many(run_ks, k1=1, k2=3, trials=2, min_delta_s=0)
    off_sps = nb * B / slopes[f"{name}-off"]
    records = []
    for label, dig in modes.items():
        sps = nb * B / slopes[label]
        records.append(
            {
                "config": label,
                "devices": dp * pp,
                "samples_per_sec": round(sps, 1),
                "digests": dig,
                "same_window": True,
                "vs_off": round(sps / off_sps, 4),
            }
        )
    return records


# tensor-parallel vs sequential pairs: same-window via the interleaved-trial
# slope protocol. TP's win is weight-bandwidth/matmul-size denominated (per-
# device weight memory and matmul FLOPs drop by tp at 2 all-reduces per layer
# pair); on emulated CPU devices the extra dispatch + memcpy "collectives"
# are pure overhead against an op-issue-bound MLP, so — exactly like the
# grad-bucket and split-backward pairs — expect seq to win here and the
# ratio to mean something only on a real multi-chip mesh. Records carry tp,
# vs_seq and the mesh placement note so the pending on-chip tunnel window
# re-measures self-describing rows.
TP_PAIRS = [
    ("tp2", dict(dp=1, pp=1, tp=2)),
    ("dp2tp2", dict(dp=2, pp=1, tp=2)),
]


def bench_tp_pair(name, cfg, nb, sizes=SIZES, act="relu", model=None):
    """One sequential-vs-tp pair, same-window: returns a list of record
    dicts (one per mode) carrying tp + vs_seq + the mesh layout note."""
    import jax
    import jax.numpy as jnp

    from bench import make_run_k, slope_epoch_seconds_many

    from shallowspeed_tpu import model as Mo
    from shallowspeed_tpu import trainer
    from shallowspeed_tpu.optimizer import SGD
    from shallowspeed_tpu.parallel.mesh import make_mesh_with_layout

    dp, pp, tp = cfg["dp"], cfg["pp"], cfg["tp"]
    run_ks = {}
    # sequential leg
    spec1 = Mo.make_model_spec(sizes, 1, B, act=act)
    params = jax.tree.map(jnp.asarray, Mo.init_model(spec1))
    seq_epoch = trainer.make_train_epoch(spec1, SGD(LR))
    X, Y = _data(nb, np.random.RandomState(0), sizes=sizes)
    Xe = jnp.asarray(X.reshape(nb, M, B // M, -1))
    Ye = jnp.asarray(Y.reshape(nb, M, B // M, -1))

    def seq_fn(p, s, X_, Y_, _e=seq_epoch):
        return _e(p, s, X_, Y_)

    run_ks[f"{name}-seq"] = make_run_k(seq_fn, params, (), Xe, Ye)
    # tp leg: the shared mesh setup, plus the placement note for the
    # records (deterministic — same device order as the setup's mesh)
    mesh_layout = make_mesh_with_layout(dp, pp, tp=tp)[1]
    _, epoch, stacked, flags, st, Xj, Yj = _pipeline_epoch_setup(
        dp, pp, "gpipe", nb, tp=tp, sizes=sizes, act=act,
    )

    def tp_fn(p, s, X_, Y_, _e=epoch, _f=flags):
        return _e(p, _f, s, X_, Y_)

    run_ks[f"{name}-tp"] = make_run_k(tp_fn, stacked, st, Xj, Yj)
    slopes = slope_epoch_seconds_many(run_ks, k1=1, k2=3, trials=2, min_delta_s=0)
    seq_sps = nb * B / slopes[f"{name}-seq"]
    records = []
    for label, tp_val, devices in (
        (f"{name}-seq", 1, 1),
        (f"{name}-tp", tp, dp * pp * tp),
    ):
        sps = nb * B / slopes[label]
        records.append(
            {
                "config": label,
                "devices": devices,
                "samples_per_sec": round(sps, 1),
                "model": model,
                "tp": tp_val,
                "mesh_layout": mesh_layout if tp_val > 1 else None,
                "same_window": True,
                "vs_seq": round(sps / seq_sps, 4),
            }
        )
    return records


# split-vs-unsplit backward pairs at pp4 (gpipe + 1F1B): same-window via the
# interleaved-trial slope protocol, like the gradient-sync pairs. The split
# schedule's win is FLOP-weighted bubble time (the record carries both
# programs' weighted bubble fractions); on emulated CPU devices the extra
# OP_BWD_W ticks are pure op-issue overhead with nothing to overlap, so —
# exactly like grad bucketing — expect the unsplit row to win here and the
# ratio to mean something only on a real multi-chip mesh.
SPLIT_PAIRS = [
    ("pp4-gpipe-split", dict(dp=1, pp=4, sched="gpipe")),
    ("pp4-pipedream-split", dict(dp=1, pp=4, sched="pipedream")),
]


def bench_split_pair(name, cfg, nb, sizes=SIZES, act="relu", model=None):
    """One unsplit-vs-split backward pair, same-window: returns a list of
    record dicts (one per mode) carrying backward_split + the lowered
    programs' weighted bubble fractions so a MULTICHIP capture of these
    rows is self-describing."""
    from bench import make_run_k, slope_epoch_seconds_many

    from shallowspeed_tpu.parallel.lowering import weighted_utilization

    dp, pp = cfg["dp"], cfg["pp"]
    modes = {f"{name}-unsplit": False, f"{name}-split": True}
    run_ks, wbubble = {}, {}
    for label, bs in modes.items():
        # the setup's own lowered program feeds the recorded metric, so
        # the weighted bubble always describes the program being timed
        prog, epoch, stacked, flags, st, Xj, Yj = _pipeline_epoch_setup(
            dp, pp, cfg["sched"], nb, backward_split=bs, sizes=sizes, act=act,
        )
        wbubble[label] = round(1.0 - weighted_utilization(prog), 4)

        def epoch_fn(p, s, X, Y, _epoch=epoch, _flags=flags):
            return _epoch(p, _flags, s, X, Y)

        run_ks[label] = make_run_k(epoch_fn, stacked, st, Xj, Yj)
    slopes = slope_epoch_seconds_many(run_ks, k1=1, k2=3, trials=2, min_delta_s=0)
    unsplit_sps = nb * B / slopes[f"{name}-unsplit"]
    records = []
    for label, bs in modes.items():
        sps = nb * B / slopes[label]
        records.append(
            {
                "config": label,
                "devices": dp * pp,
                "samples_per_sec": round(sps, 1),
                "model": model,
                "backward_split": bs,
                "weighted_bubble_fraction": wbubble[label],
                "same_window": True,
                "vs_unsplit": round(sps / unsplit_sps, 4),
            }
        )
    return records


# stashed-vs-recompute pairs at pp4: same-window via the interleaved-trial
# slope protocol. Recompute trades the residual-stash footprint for a
# ~4/3 forward-FLOP tax (docs/lowering.md § Recompute ticks) — on a
# compute-bound model the tax should be VISIBLE here (vs_stashed < 1),
# which is the honest direction: this pair measures what recompute costs,
# the stash-peak fields record what it buys.
RECOMPUTE_PAIRS = [
    ("pp4-gpipe-recompute", dict(dp=1, pp=4, sched="gpipe")),
]


def bench_recompute_pair(name, cfg, nb, sizes=SIZES, act="relu", model=None):
    """One stashed-vs-recompute pair, same-window: returns a list of
    record dicts (one per mode) carrying the recompute flag, the lowered
    programs' stash peaks (the memory the tax buys back), and vs_stashed."""
    from bench import make_run_k, slope_epoch_seconds_many

    dp, pp = cfg["dp"], cfg["pp"]
    modes = {f"{name}-stashed": False, f"{name}-on": True}
    run_ks, peaks = {}, {}
    for label, rec in modes.items():
        prog, epoch, stacked, flags, st, Xj, Yj = _pipeline_epoch_setup(
            dp, pp, cfg["sched"], nb, sizes=sizes, act=act, recompute=rec,
        )
        peaks[label] = {
            "stash_slots": int(prog.n_stash_slots),
            "xin_slots": int(prog.n_xin_slots),
        }

        def epoch_fn(p, s, X, Y, _epoch=epoch, _flags=flags):
            return _epoch(p, _flags, s, X, Y)

        run_ks[label] = make_run_k(epoch_fn, stacked, st, Xj, Yj)
    slopes = slope_epoch_seconds_many(run_ks, k1=1, k2=3, trials=2, min_delta_s=0)
    stashed_sps = nb * B / slopes[f"{name}-stashed"]
    records = []
    for label, rec in modes.items():
        sps = nb * B / slopes[label]
        records.append(
            {
                "config": label,
                "devices": dp * pp,
                "samples_per_sec": round(sps, 1),
                "model": model,
                "recompute": rec,
                **peaks[label],
                "same_window": True,
                "vs_stashed": round(sps / stashed_sps, 4),
            }
        )
    return records


# lockstep-vs-MPMD runtime pairs: same-window via the interleaved-trial
# slope protocol (the MPMD runner's ``run`` is epoch-shaped with the
# lockstep signature, so both legs time the identical loop). The MPMD
# per-stage runtime removes the lockstep lax.switch op-issue wall; on a
# dispatch-bound toy MLP that win was masked by the runtime's own host
# cost (MPMD_r01.json: 0.86x) — a compute-bound model is where it gets
# to show, or where the refutation earns its caveat.
MPMD_PAIRS = [
    ("pp4-gpipe-mpmd", dict(dp=1, pp=4, sched="gpipe")),
]


def bench_mpmd_pair(name, cfg, nb, sizes=SIZES, act="relu", model=None):
    """One lockstep-vs-MPMD runtime pair, same-window: returns a list of
    record dicts (one per mode) carrying runtime + vs_lockstep."""
    from bench import make_run_k, slope_epoch_seconds_many

    from shallowspeed_tpu.optimizer import SGD
    from shallowspeed_tpu.parallel import mpmd

    dp, pp = cfg["dp"], cfg["pp"]
    prog, epoch, stacked, flags, st, Xj, Yj = _pipeline_epoch_setup(
        dp, pp, cfg["sched"], nb, sizes=sizes, act=act,
    )

    def lockstep_fn(p, s, X, Y, _epoch=epoch, _flags=flags):
        return _epoch(p, _flags, s, X, Y)

    # the MPMD leg drives the SAME lowered program through the per-stage
    # runtime — with its OWN param/state buffers: the lockstep epoch
    # donates its inputs, so sharing one stacked tree across legs would
    # hand the runner deleted arrays
    from shallowspeed_tpu.parallel import make_mesh

    _, _, stacked2, flags2, st2, _, _ = _pipeline_epoch_setup(
        dp, pp, cfg["sched"], nb, sizes=sizes, act=act,
    )
    mesh = make_mesh(dp, pp)
    runner = mpmd.MpmdTrainRunner(mesh, _mpmd_spec(sizes, pp, act), prog,
                                  B // dp // M, SGD(LR))

    def mpmd_fn(p, s, X, Y, _r=runner, _flags=flags2):
        return _r.run(p, _flags, s, X, Y)

    run_ks = {
        f"{name}-lockstep": make_run_k(lockstep_fn, stacked, st, Xj, Yj),
        f"{name}-mpmd": make_run_k(mpmd_fn, stacked2, st2, Xj, Yj),
    }
    slopes = slope_epoch_seconds_many(run_ks, k1=1, k2=3, trials=2, min_delta_s=0)
    lockstep_sps = nb * B / slopes[f"{name}-lockstep"]
    records = []
    for label, rt in ((f"{name}-lockstep", "lockstep"), (f"{name}-mpmd", "mpmd")):
        sps = nb * B / slopes[label]
        records.append(
            {
                "config": label,
                "devices": dp * pp,
                "samples_per_sec": round(sps, 1),
                "model": model,
                "runtime": rt,
                "same_window": True,
                "vs_lockstep": round(sps / lockstep_sps, 4),
            }
        )
    return records


def _mpmd_spec(sizes, pp, act):
    from shallowspeed_tpu import model as Mo

    return Mo.make_model_spec(sizes, pp, B, act=act)


CONFIGS = [
    # the five BASELINE.md configs...  (name, kwargs)
    ("seq", dict(dp=1, pp=1)),
    ("dp4", dict(dp=4, pp=1, sched="gpipe")),
    ("pp4-naive", dict(dp=1, pp=4, sched="naive")),
    ("pp4-gpipe", dict(dp=1, pp=4, sched="gpipe")),
    ("dp2pp4-gpipe", dict(dp=2, pp=4, sched="gpipe")),
    # ...plus the schedules/optimizers the reference never implemented
    ("pp4-pipedream", dict(dp=1, pp=4, sched="pipedream")),
    ("dp4-zero1-adam", dict(dp=4, pp=1, sched="gpipe", zero1=True,
                            optimizer="adam")),
    # 16-size rows (quirk-free 8-stage partition): their efficiency is
    # reported against seq16, the same model run sequentially
    ("seq16", dict(dp=1, pp=1, sizes=SIZES16)),
    ("pp4v2-interleaved-16", dict(dp=1, pp=4, sched="interleaved", virtual=2,
                                  sizes=SIZES16)),
]


def bench_dispatch_probe(nb, sizes, act, model):
    """The measured op-issue share on this model (train.py
    --dispatch-probe's machinery, bounded window): the number that says
    whether a bench row on THIS model is compute- or dispatch-bound —
    the compute-bound zoo exists so this drops below the toy MLP's
    ~0.7."""
    import tempfile

    from shallowspeed_tpu.api import TrainingSession

    with tempfile.TemporaryDirectory() as td:
        rng = np.random.RandomState(0)
        X, Y = _data(nb, rng, sizes=sizes)
        np.save(Path(td) / "x_train.npy", X.reshape(-1, sizes[0]))
        np.save(Path(td) / "y_train.npy", Y.reshape(-1, sizes[-1]))
        np.save(Path(td) / "x_val.npy", X[0])
        np.save(Path(td) / "y_val.npy", Y[0])
        s = TrainingSession(
            model=model, dp=1, pp=4, schedule="gpipe",
            global_batch_size=B, mubatches=M, data_dir=td,
        )
        rec = s.measure_dispatch_overhead(repeats=2)
    keep = (
        "dispatch_overhead", "dispatch_overhead_instrumented",
        "host_wall_s", "device_busy_s", "op_events", "op_source",
        "profiler_inflation", "batches_per_epoch", "events_per_batch",
        "window_valid", "window_invalid_reason",
    )
    row = {k: rec.get(k) for k in keep if rec.get(k) is not None}
    row["config"] = "pp4-gpipe-dispatch-probe"
    row["model"] = model
    return row


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batches", type=int, default=64, help="batches per rep")
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument(
        "--model", default=None,
        help="model-zoo config (model.MODEL_ZOO) to bench instead of the "
        "flagship toy MLP: the compute-bound rows that unmask "
        "dispatch-bound ratios (docs/performance.md). Rows record the "
        "model name so captures stay self-describing.",
    )
    ap.add_argument(
        "--pairs-only", action="store_true",
        help="skip the plain throughput rows; run only the same-window "
        "pairs (+ the dispatch probe when --model is set) — the "
        "COMPUTE_r01.json protocol",
    )
    ap.add_argument(
        "--out", default=None,
        help="also write every emitted record into FILE as one JSON "
        "document ({bench, model, records: [...]})",
    )
    args = ap.parse_args()

    act = "relu"
    sizes = SIZES
    if args.model:
        from shallowspeed_tpu import model as Mo

        sizes, act = Mo.resolve_model(args.model)

    import jax

    n_dev = len(jax.devices())
    results = {}
    emitted = []

    def emit(rec):
        emitted.append(rec)
        print(json.dumps(rec))

    if not args.pairs_only:
        for name, cfg in CONFIGS:
            dp, pp = cfg.get("dp", 1), cfg.get("pp", 1)
            need = dp * pp
            if need > n_dev:
                emit({"config": name, "skipped": f"needs {need} devices, have {n_dev}"})
                continue
            if args.model and "sizes" in cfg:
                continue  # the 16-size quirk rows only describe the toy MLP
            row_sizes = cfg.get("sizes", sizes)
            if pp == 1 and dp == 1:
                sps = bench_sequential(
                    args.batches, args.reps, sizes=row_sizes, act=act
                )
            else:
                sps = bench_pipeline(
                    dp, pp, cfg["sched"], args.batches, args.reps,
                    virtual=cfg.get("virtual", 1), sizes=row_sizes,
                    zero1=cfg.get("zero1", False), optimizer=cfg.get("optimizer"),
                )
            results[name] = sps
            ref = "seq16" if row_sizes is SIZES16 else "seq"
            eff = (
                sps / (need * results[ref])
                if ref in results and name != ref
                else 1.0
            )
            emit(
                {
                    "config": name,
                    "devices": need,
                    "samples_per_sec": round(sps, 1),
                    "model": args.model,
                    "efficiency_vs_seq": round(eff, 4),
                }
            )

    pair_kwargs = dict(sizes=sizes, act=act, model=args.model)

    # the anchor-vs-bucketed gradient-sync pairs (same-window per pair)
    for name, cfg in SYNC_PAIRS:
        need = cfg["dp"] * cfg["pp"]
        if need > n_dev:
            emit({"config": name, "skipped": f"needs {need} devices, have {n_dev}"})
            continue
        if args.pairs_only and cfg.get("zero1"):
            continue  # COMPUTE protocol: the plain dp2 pair carries the story
        for rec in bench_sync_pair(name, cfg, args.batches, **pair_kwargs):
            emit(rec)

    # the unsplit-vs-split backward pairs (same-window per pair)
    for name, cfg in SPLIT_PAIRS:
        need = cfg["dp"] * cfg["pp"]
        if need > n_dev:
            emit({"config": name, "skipped": f"needs {need} devices, have {n_dev}"})
            continue
        for rec in bench_split_pair(name, cfg, args.batches, **pair_kwargs):
            emit(rec)

    if not args.pairs_only:
        # the digests-off-vs-on pairs (same-window per pair): the measured
        # on-path overhead of the numerics-provenance aux
        for name, cfg in DIGEST_PAIRS:
            need = cfg["dp"] * cfg["pp"]
            if need > n_dev:
                emit({"config": name, "skipped": f"needs {need} devices, have {n_dev}"})
                continue
            for rec in bench_digest_pair(name, cfg, args.batches):
                emit(rec)

    # the sequential-vs-tensor-parallel pairs (same-window per pair)
    for name, cfg in TP_PAIRS:
        need = cfg["dp"] * cfg["pp"] * cfg["tp"]
        if need > n_dev:
            emit({"config": name, "skipped": f"needs {need} devices, have {n_dev}"})
            continue
        if args.pairs_only and cfg["dp"] > 1:
            continue  # COMPUTE protocol: tp2-vs-seq is the story row
        for rec in bench_tp_pair(name, cfg, args.batches, **pair_kwargs):
            emit(rec)

    # the stashed-vs-recompute pairs (same-window per pair)
    for name, cfg in RECOMPUTE_PAIRS:
        need = cfg["dp"] * cfg["pp"]
        if need > n_dev:
            emit({"config": name, "skipped": f"needs {need} devices, have {n_dev}"})
            continue
        for rec in bench_recompute_pair(name, cfg, args.batches, **pair_kwargs):
            emit(rec)

    # the lockstep-vs-MPMD runtime pairs (same-window per pair)
    for name, cfg in MPMD_PAIRS:
        need = cfg["dp"] * cfg["pp"]
        if need > n_dev:
            emit({"config": name, "skipped": f"needs {need} devices, have {n_dev}"})
            continue
        for rec in bench_mpmd_pair(name, cfg, args.batches, **pair_kwargs):
            emit(rec)

    if args.model:
        emit(bench_dispatch_probe(args.batches, sizes, act, args.model))

    if args.out:
        Path(args.out).write_text(
            json.dumps(
                {
                    "bench": "scaling",
                    "model": args.model,
                    "act": act,
                    "sizes": list(sizes),
                    "batches": args.batches,
                    "platform": jax.devices()[0].platform,
                    "n_devices": n_dev,
                    "cpu_fallback_caveat": (
                        "emulated CPU devices on one shared host core: "
                        "machinery + relative ratios, not chip performance"
                        if jax.devices()[0].platform == "cpu"
                        else None
                    ),
                    "records": emitted,
                },
                indent=1,
            )
            + "\n"
        )


if __name__ == "__main__":
    main()
