"""Training driver CLI — the TPU-native counterpart of the reference train.py.

Same surface: ``python train.py [--dp N] [--pp M] [--schedule naive|gpipe|pipedream]``
(reference train.py:62-74), same flagship model (sizes [784,128,127,126,125,
124,123,10], train.py:98), same constants (EPOCHS=20, GLOBAL_BATCH_SIZE=128,
N_MUBATCHES=4, lr=0.006), same epoch structure (per-epoch validation accuracy
from the last stage, final replica-sync check).

Differences by design:
- no mpirun: ONE process drives the whole (dp, pp) device mesh; the two MPI
  communicators become mesh axes (parallel/mesh.py);
- the per-batch instruction streams are compiled once to a tick program and
  the whole epoch runs as one jitted scan on device;
- extra flags (epochs, batch size, lr, data dir, platform) are exposed
  instead of module constants.

Examples:
    python train.py                      # sequential, 1 device
    python train.py --dp 8               # 8-way data parallel
    python train.py --pp 4 --schedule gpipe
    python train.py --dp 2 --pp 4 --schedule pipedream
On a single-chip host, multi-device layouts run on emulated CPU devices:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python train.py --dp 2 --pp 4 --schedule gpipe
"""

import argparse
import time

LAYER_SIZES = (784, 128, 127, 126, 125, 124, 123, 10)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--dp", type=int, default=1, help="data-parallel replicas")
    ap.add_argument("--pp", type=int, default=1, help="pipeline stages")
    ap.add_argument(
        "--schedule",
        choices=["naive", "gpipe", "pipedream"],
        default="naive",
        help="pipeline schedule (ignored unless --pp > 1)",
    )
    ap.add_argument("--epochs", type=int, default=20)
    ap.add_argument("--global-batch-size", type=int, default=128)
    ap.add_argument("--mubatches", type=int, default=4)
    ap.add_argument("--lr", type=float, default=0.006)
    ap.add_argument("--data-dir", default=None)
    ap.add_argument("--no-eval", action="store_true", help="skip per-epoch accuracy")
    ap.add_argument(
        "--checkpoint", default=None, help="path to save a checkpoint after each epoch"
    )
    ap.add_argument(
        "--resume",
        default=None,
        help="checkpoint to resume from (any layout -> any layout)",
    )
    ap.add_argument(
        "--profile-dir",
        default=None,
        help="write a jax.profiler trace of one training epoch to this directory",
    )
    ap.add_argument(
        "--precision",
        choices=["highest", "default"],
        default="highest",
        help="matmul precision: 'highest' = fp32 parity with the NumPy "
        "reference; 'default' = let the MXU use fast (bf16-input) passes",
    )
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from shallowspeed_tpu import model as Mo
    from shallowspeed_tpu import schedules as S
    from shallowspeed_tpu import trainer, utils
    from shallowspeed_tpu.checkpoint import load_checkpoint, save_checkpoint
    from shallowspeed_tpu.data import Dataset, default_data_dir
    from shallowspeed_tpu.optimizer import SGD
    from shallowspeed_tpu.parallel import executor as E
    from shallowspeed_tpu.parallel import lower_schedule, make_mesh

    import contextlib

    def profiled(epoch_idx):
        """Trace exactly one epoch (the second, past compile) when asked."""
        if args.profile_dir and epoch_idx == min(1, args.epochs - 1):
            return jax.profiler.trace(args.profile_dir)
        return contextlib.nullcontext()

    from jax import lax as _lax

    precision = (
        _lax.Precision.HIGHEST if args.precision == "highest" else _lax.Precision.DEFAULT
    )

    B, M = args.global_batch_size, args.mubatches
    assert B % args.dp == 0, "batch size must be divisible by DP"
    local_batch = B // args.dp
    assert local_batch % M == 0, "microbatches must divide the local batch"
    data_dir = args.data_dir or default_data_dir()

    ds = Dataset(data_dir, B, mubatch_size=local_batch // M)
    ds.load(0, 1)  # one process holds the global batch; the mesh shards it
    val = Dataset(data_dir, B, mubatch_size=B, validation=True)
    val.load(0, 1)
    vx, vy = jnp.asarray(val.input_X), jnp.asarray(val.target_y)

    spec = Mo.make_model_spec(LAYER_SIZES, args.pp, B)
    opt = SGD(args.lr)
    nb = ds.get_num_batches()
    Xb, Yb = ds.epoch_arrays()  # (nb, M, mb_local*dp, d) ordering: global batches
    X = jnp.asarray(Xb.reshape(nb, B, Xb.shape[-1]))
    Y = jnp.asarray(Yb.reshape(nb, B, Yb.shape[-1]))

    print(
        f"devices={jax.devices()} layout: DP={args.dp} x PP={args.pp}"
        f" schedule={args.schedule if args.pp > 1 else 'sequential'}"
        f" batches/epoch={nb}"
    )

    start_epoch = 0
    if args.dp == 1 and args.pp == 1:
        if args.resume:
            host_params, spec, meta = load_checkpoint(args.resume, 1, B)
            start_epoch = meta["epoch"] + 1
            print(f"resumed from {args.resume} (epoch {meta['epoch']})")
            params = jax.tree.map(jnp.asarray, host_params)
        else:
            params = jax.tree.map(jnp.asarray, Mo.init_model(spec))
        epoch_fn = trainer.make_train_epoch(spec, opt, precision=precision)
        predict = trainer.make_predict(spec, precision=precision)
        state = ()
        Xe = X.reshape(nb, M, B // M, -1)
        Ye = Y.reshape(nb, M, B // M, -1)
        t0 = time.time()
        for e in range(start_epoch, start_epoch + args.epochs):
            if not args.no_eval:
                acc = trainer.accuracy(predict, params, vx, vy)
                print(
                    f"Epoch: {e}, Time Spent: {time.time() - t0:.2f}s, "
                    f"Accuracy: {acc * 100:.2f}%"
                )
            with profiled(e - start_epoch):
                params, state = epoch_fn(params, state, Xe, Ye)
                jax.block_until_ready(params)
            if args.checkpoint:
                save_checkpoint(args.checkpoint, params, spec, e)
        jax.block_until_ready(params)
        acc = trainer.accuracy(predict, params, vx, vy)
        print(
            f"Epoch: {start_epoch + args.epochs}, Time Spent: {time.time() - t0:.2f}s, "
            f"Accuracy: {acc * 100:.2f}%"
        )
        print("final model hash:", utils.model_hash(params))
        return

    mesh = make_mesh(args.dp, args.pp)
    sched_cls = S.SCHEDULES[args.schedule]
    prog = lower_schedule(sched_cls, M, args.pp)
    eval_prog = lower_schedule(S.InferenceSchedule, 1, args.pp, training=False)
    if args.resume:
        host_params, spec, meta = load_checkpoint(args.resume, args.pp, B)
        start_epoch = meta["epoch"] + 1
        print(f"resumed from {args.resume} (epoch {meta['epoch']})")
        stacked, flags = E.put_stacked(*E.stack_params(host_params, spec), mesh)
    else:
        stacked, flags = E.init_stacked(spec, mesh)
    mb_sz = local_batch // M
    epoch_fn = E.make_pipeline_epoch(mesh, spec, prog, mb_sz, opt, precision=precision)
    # validation runs the inference tick program with one full-batch microbatch
    # on a pp-only slice of the mesh semantics (dp shards the val batch too)
    eval_step = E.make_pipeline_step(mesh, spec, eval_prog, B // args.dp, precision=precision)

    def pipeline_accuracy(stacked):
        """Full-split accuracy; the ragged tail chunk is zero-padded up to B
        and only its valid rows are counted (eval shapes stay static)."""
        correct = total = 0
        for i in range(0, len(val.input_X), B):
            xb, yb = vx[i : i + B], vy[i : i + B]
            n_valid = xb.shape[0]
            if n_valid < B:
                xb = jnp.pad(xb, ((0, B - n_valid), (0, 0)))
            preds = eval_step(stacked, flags, xb)[:n_valid]
            correct += int((jnp.argmax(preds[:, :10], 1) == jnp.argmax(yb, 1)).sum())
            total += n_valid
        return correct / max(total, 1)

    t0 = time.time()
    for e in range(start_epoch, start_epoch + args.epochs):
        if not args.no_eval:
            acc = pipeline_accuracy(stacked)
            print(
                f"Epoch: {e}, Time Spent: {time.time() - t0:.2f}s, "
                f"Accuracy: {acc * 100:.2f}%"
            )
        with profiled(e - start_epoch):
            stacked, mean_loss = epoch_fn(stacked, flags, X, Y)
            jax.block_until_ready(stacked)
        print(f"Epoch: {e}, mean train loss: {float(mean_loss):.5f}")
        if args.checkpoint:
            save_checkpoint(args.checkpoint, E.unstack_params(stacked, spec), spec, e)
    jax.block_until_ready(stacked)
    acc = pipeline_accuracy(stacked)
    print(
        f"Epoch: {start_epoch + args.epochs}, Time Spent: {time.time() - t0:.2f}s, "
        f"Accuracy: {acc * 100:.2f}%"
    )
    utils.assert_dp_replicas_in_sync(stacked)
    print("DP replicas in sync ✓")
    print("final model hash:", utils.model_hash(E.unstack_params(stacked, spec)))


if __name__ == "__main__":
    main()
