"""Numerics provenance: first-divergence attribution + checkpoint-bisect
replay over per-layer digest streams (docs/numerics.md "Divergence
debugging").

Two runs that should match — a resumed run vs an uninterrupted one, MPMD
vs lockstep, bucketed sync vs the anchor — historically compared ONE
number: ``utils.model_hash`` at the end. A mismatch said "something,
somewhere, at some point". The digest stream (schema v12,
``TrainingSession(digests=True)`` / ``train.py --digests``) records a
per-step, per-LAYER checksum + norm row computed inside the fused epoch
program, and this module turns two such streams into an attribution:

- ``first_divergence``  joins the streams and names the FIRST divergent
  ``(global_step, layer, tensor)`` — walking steps ascending, layers
  ascending, W before b — classified as a tolerance class from the
  recorded block norms (``ulp-level`` / ``float-tolerance`` / ``gross``)
  or ``structurally-missing`` (a step or layer one stream never
  recorded);
- ``tensor_diff``       elementwise float32 forensics for the bisect
  replay: max ULP distance (int32-lexicographic), the first differing
  flat index, and value-domain deltas;
- ``assert_models_equal`` / ``assert_digest_streams_equal``  the
  test-suite comparators: bitwise equality checks that FAIL with the
  attribution above instead of a bare hash mismatch;
- the CLI              ``python -m shallowspeed_tpu.observability.divergence
  runA.jsonl runB.jsonl`` — exit 0 when the streams are bitwise-equal,
  2 on divergence (printing the attribution), 1 on usage/read errors.
  ``--bisect CKPT_DIR_A CKPT_DIR_B`` additionally restores each run's
  last agreeing step checkpoint (the ``digest_config`` record carries
  the session config + fault plan; ``die`` faults are stripped, step
  faults re-arm so injected flips reproduce), re-executes exactly ONE
  step under both configs, and dumps the offending tensor's diff.

The digest-at-step-N ↔ checkpoint-at-step-N+1 correspondence the bisect
relies on: a digest row covers the params AFTER step N's update, which
is exactly what the ``step-(N+1)`` snapshot holds (its cursor says "N+1
steps trained").
"""

import argparse
import sys

import numpy as np

# tolerance classes for a crc mismatch, from the recorded block norms:
# the max relative norm delta bounds how large the value-domain change
# can be, so a flipped mantissa LSB classifies as ulp-level while a real
# algorithmic drift classifies as float-tolerance or gross
TOLERANCE_CLASSES = (("ulp-level", 1e-9), ("float-tolerance", 1e-6))

_TENSORS = (("W", "crc_w", "pnorm_w", "gnorm_w"), ("b", "crc_b", "pnorm_b", "gnorm_b"))


def classify_rel(rel):
    """Map a max relative norm delta to its tolerance-class name."""
    for name, thr in TOLERANCE_CLASSES:
        if rel <= thr:
            return name
    return "gross"


def digest_stream(records, name="train"):
    """Index a record list's ``digest`` records by global step.

    Accepts the full ``read_jsonl`` output of a run (other kinds are
    skipped). The first record per step wins — a resumed run may re-emit
    a tail step it re-trained; the divergence walk wants the FIRST
    evidence for each step, matching the numbering contract (one
    optimizer step, one digest row).
    """
    out = {}
    for r in records:
        if r.get("kind") == "digest" and r.get("name", name) == name:
            out.setdefault(int(r["step"]), r)
    return out


def _rel_delta(a, b):
    return abs(a - b) / max(abs(a), abs(b), 1e-30)


def first_divergence(recs_a, recs_b):
    """First divergent ``(global_step, layer, tensor)`` between two digest
    streams, or None when every recorded step is bitwise-equal.

    ``recs_a`` / ``recs_b``: record lists (``read_jsonl`` output) or the
    ``digest_stream`` dicts built from them. Returns a dict:
    ``step``/``layer``/``tensor`` name the divergence,
    ``classification`` is ``structurally-missing`` or a tolerance class
    from the recorded norms, ``last_agreeing_step`` is the newest step
    BEFORE it whose whole row matched (None when divergence is at the
    first recorded step) — the bisect replay's restore target is that
    step's post-update snapshot (``step-(last_agreeing+1)``).
    """
    sa = recs_a if isinstance(recs_a, dict) else digest_stream(recs_a)
    sb = recs_b if isinstance(recs_b, dict) else digest_stream(recs_b)
    last_agreeing = None
    for step in sorted(set(sa) | set(sb)):
        a, b = sa.get(step), sb.get(step)
        if a is None or b is None:
            return {
                "step": step, "layer": None, "tensor": None,
                "classification": "structurally-missing",
                "detail": f"step {step} missing from run "
                          f"{'A' if a is None else 'B'}",
                "last_agreeing_step": last_agreeing,
            }
        la, lb = int(a.get("layers", 0)), int(b.get("layers", 0))
        if la != lb:
            return {
                "step": step, "layer": min(la, lb), "tensor": None,
                "classification": "structurally-missing",
                "detail": f"step {step} records {la} layers in A vs {lb} in B",
                "last_agreeing_step": last_agreeing,
            }
        for layer in range(la):
            for tensor, ck, pk, gk in _TENSORS:
                ca, cb = int(a[ck][layer]), int(b[ck][layer])
                if ca == cb:
                    continue
                rel_p = _rel_delta(float(a[pk][layer]), float(b[pk][layer]))
                rel_g = _rel_delta(float(a[gk][layer]), float(b[gk][layer]))
                return {
                    "step": step, "layer": layer, "tensor": tensor,
                    "classification": classify_rel(max(rel_p, rel_g)),
                    "crc_a": ca, "crc_b": cb,
                    "pnorm_a": float(a[pk][layer]),
                    "pnorm_b": float(b[pk][layer]),
                    "rel_pnorm_delta": rel_p, "rel_gnorm_delta": rel_g,
                    "last_agreeing_step": last_agreeing,
                }
        last_agreeing = step
    return None


def format_divergence(div, label_a="run A", label_b="run B"):
    """Human-readable attribution lines for a ``first_divergence`` result."""
    lines = [
        f"first divergence: step {div['step']}"
        + (f" layer {div['layer']}" if div["layer"] is not None else "")
        + (f" tensor {div['tensor']}" if div["tensor"] else "")
    ]
    if "crc_a" in div:
        lines.append(
            f"  crc {label_a}=0x{div['crc_a']:08x} "
            f"{label_b}=0x{div['crc_b']:08x}"
        )
        lines.append(
            f"  classification: {div['classification']} "
            f"(rel pnorm delta {div['rel_pnorm_delta']:.3e}, "
            f"rel gnorm delta {div['rel_gnorm_delta']:.3e})"
        )
    else:
        lines.append(f"  classification: {div['classification']}"
                     f" — {div.get('detail', '')}")
    la = div.get("last_agreeing_step")
    lines.append(
        "  last agreeing step: "
        + ("none (diverged at the first recorded step)" if la is None else str(la))
    )
    return lines


def _f32_lex(a):
    """int32-lexicographic keys of float32 values: monotonic in the float
    order, adjacent representable floats differ by exactly 1 — so key
    distance IS the ULP distance. Both zeros map to 0."""
    u = np.ascontiguousarray(a, np.float32).view(np.uint32).astype(np.int64)
    return np.where(u < 0x80000000, u, 0x80000000 - u)


def tensor_diff(a, b):
    """Elementwise forensics for one block pair: ``n_diff`` (bitwise
    differing elements), ``first_index`` (first differing FLAT index, or
    None), ``max_ulp`` (int32-lexicographic ULP distance), and the
    value-domain ``max_abs_delta`` / ``max_rel_delta``."""
    fa = np.ascontiguousarray(np.asarray(a), np.float32).ravel()
    fb = np.ascontiguousarray(np.asarray(b), np.float32).ravel()
    if fa.shape != fb.shape:
        raise ValueError(f"shape mismatch: {fa.shape} vs {fb.shape}")
    neq = fa.view(np.uint32) != fb.view(np.uint32)
    n_diff = int(neq.sum())
    if n_diff == 0:
        return {"n_diff": 0, "first_index": None, "max_ulp": 0,
                "max_abs_delta": 0.0, "max_rel_delta": 0.0}
    ulp = np.abs(_f32_lex(fa) - _f32_lex(fb))
    da = np.abs(fa.astype(np.float64) - fb.astype(np.float64))
    denom = np.maximum(np.maximum(np.abs(fa), np.abs(fb)), 1e-30)
    return {
        "n_diff": n_diff,
        "first_index": int(np.argmax(neq)),
        "max_ulp": int(ulp.max()),
        "max_abs_delta": float(da.max()),
        "max_rel_delta": float((da / denom).max()),
    }


def assert_models_equal(params_a, params_b, label_a="A", label_b="B"):
    """Bitwise equality of two logical params trees, failing with the
    digest attribution — which (layer, tensor) diverged, how far —
    instead of a bare hash mismatch. The blocks compared are exactly
    ``utils.iter_param_blocks``'s (the ONE shared digest definition)."""
    from shallowspeed_tpu import utils

    blocks_a = list(utils.iter_param_blocks(params_a))
    blocks_b = list(utils.iter_param_blocks(params_b))
    if len(blocks_a) != len(blocks_b):
        raise AssertionError(
            f"models differ structurally: {len(blocks_a)} blocks in "
            f"{label_a} vs {len(blocks_b)} in {label_b}"
        )
    bad = []
    for (gl, key, aa), (_, _, ab) in zip(blocks_a, blocks_b):
        if aa.shape != ab.shape:
            raise AssertionError(
                f"layer {gl} {key}: shape {aa.shape} in {label_a} vs "
                f"{ab.shape} in {label_b}"
            )
        if aa.tobytes() != ab.tobytes():
            d = tensor_diff(aa, ab)
            bad.append(
                f"layer {gl} {key}: {d['n_diff']}/{aa.size} elements "
                f"differ, max ulp {d['max_ulp']}, first flat index "
                f"{d['first_index']}, max rel delta {d['max_rel_delta']:.3e}"
            )
    if bad:
        raise AssertionError(
            f"models diverge ({label_a} vs {label_b}) — first at "
            + bad[0].split(":")[0] + ":\n  " + "\n  ".join(bad)
        )


def assert_digest_streams_equal(recs_a, recs_b, label_a="A", label_b="B"):
    """Bitwise equality of two digest streams, failing with the
    first-divergence attribution."""
    div = first_divergence(recs_a, recs_b)
    if div is not None:
        raise AssertionError(
            f"digest streams diverge ({label_a} vs {label_b}):\n"
            + "\n".join(format_divergence(div, label_a, label_b))
        )


# ---------------------------------------------------------------------------
# checkpoint-bisect replay
# ---------------------------------------------------------------------------


def _digest_config(records, path):
    for r in records:
        if r.get("kind") == "event" and r.get("name") == "digest_config":
            return r
    raise ValueError(
        f"{path}: no digest_config record — was the run started with "
        "digests enabled (train.py --digests) and a metrics sink?"
    )


def _session_from_config(cfg, resume_path):
    """Reconstruct the recorded session (numerics-relevant config only),
    resumed from ``resume_path``, with ``die`` faults stripped — the
    replay must survive to the divergent step — and step faults
    (nan/flip) re-armed so an injected divergence reproduces."""
    from shallowspeed_tpu import faults as F
    from shallowspeed_tpu.api import TrainingSession

    plan = F.FaultPlan.parse(cfg.get("faults") or "")
    keep = ",".join(repr(f) for f in plan.faults if f.kind != "die")
    return TrainingSession(
        sizes=tuple(cfg["sizes"]),
        model=cfg.get("model"),
        dp=cfg["dp"], pp=cfg["pp"], tp=cfg["tp"],
        schedule=cfg["schedule"],
        global_batch_size=cfg["global_batch_size"],
        mubatches=cfg["mubatches"],
        lr=cfg["lr"],
        precision=cfg["precision"],
        data_dir=cfg.get("data_dir"),
        resume=resume_path,
        fuse_mubatches=cfg.get("fuse_mubatches", False),
        optimizer=cfg.get("optimizer", "sgd"),
        momentum=cfg.get("momentum", 0.9),
        virtual_stages=cfg.get("virtual_stages", 1),
        zero1=cfg.get("zero1", False),
        grad_bucket_bytes=cfg.get("grad_bucket_bytes", 0),
        backward_split=cfg.get("backward_split", False),
        recompute=cfg.get("recompute", False),
        scan_unroll=cfg.get("scan_unroll", 1),
        tick_unroll=cfg.get("tick_unroll", 1),
        weight_decay=cfg.get("weight_decay", 0.0),
        clip_norm=cfg.get("clip_norm"),
        faults=keep,
    )


def _advance_to(session, target_step):
    """Train the session forward until ``global_step == target_step``
    (chunk boundaries land on fault steps automatically)."""
    while session.global_step < target_step:
        session.train_steps(target_step - session.global_step)
    if session.global_step != target_step:
        raise ValueError(
            f"replay overshot: wanted step {target_step}, at "
            f"{session.global_step}"
        )


def bisect_replay(records_a, records_b, ckpt_dir_a, ckpt_dir_b, div, out=print):
    """Restore each run's last agreeing snapshot, re-execute ONE step
    under both recorded configs, and dump the offending tensor's diff.

    ``div`` is the ``first_divergence`` result; the divergent step s*
    means: params after step s*−1 agree (snapshot ``step-(s*)``), params
    after step s* differ. Each side restores its newest verifying
    snapshot at-or-before s*, trains forward to global_step == s*, then
    trains exactly step s* — with the recorded fault plan re-armed
    (minus ``die``), so an injected flip fires again on its step.
    Returns the list of per-block ``tensor_diff`` results that differ.
    """
    from shallowspeed_tpu import checkpoint as C
    from shallowspeed_tpu import utils

    s_star = int(div["step"])
    cfg_a = _digest_config(records_a, "run A")
    cfg_b = _digest_config(records_b, "run B")
    sessions = []
    for label, cfg, ckpt_dir in (("A", cfg_a, ckpt_dir_a),
                                 ("B", cfg_b, ckpt_dir_b)):
        got, path, _meta, skipped = C.find_step_at_or_before(ckpt_dir, s_star)
        if got is None:
            raise ValueError(
                f"run {label}: no verifying step checkpoint at or before "
                f"step {s_star} in {ckpt_dir} (skipped: {skipped})"
            )
        out(f"run {label}: restoring {path} (step {got}), replaying "
            f"forward to step {s_star}")
        s = _session_from_config(cfg, path)
        _advance_to(s, s_star)
        sessions.append(s)
    sa, sb = sessions
    pre_a, pre_b = sa.params(), sb.params()
    pre_equal = utils.model_hash(pre_a) == utils.model_hash(pre_b)
    out(f"pre-step params at step {s_star}: "
        + ("bitwise-equal (divergence is INSIDE step "
           f"{s_star})" if pre_equal else
           "already differ (divergence predates the restored window — "
           "re-run with a denser checkpoint cadence)"))
    sa.train_steps(1)
    sb.train_steps(1)
    post_a, post_b = sa.params(), sb.params()
    diffs = []
    for (gl, key, aa), (_, _, ab) in zip(
        utils.iter_param_blocks(post_a), utils.iter_param_blocks(post_b)
    ):
        if aa.tobytes() == ab.tobytes():
            continue
        d = tensor_diff(aa, ab)
        d.update(layer=gl, tensor=key)
        diffs.append(d)
        out(
            f"  layer {gl} {key}: {d['n_diff']}/{aa.size} elements "
            f"differ, max ulp {d['max_ulp']}, first flat index "
            f"{d['first_index']}, max abs delta {d['max_abs_delta']:.6e}, "
            f"max rel delta {d['max_rel_delta']:.3e}"
        )
    if not diffs:
        out("  post-step params are bitwise-equal under replay — the "
            "recorded divergence did not reproduce (nondeterministic "
            "cause, or an un-rearmable fault)")
    elif div.get("layer") is not None:
        first = (diffs[0]["layer"], diffs[0]["tensor"])
        want = (div["layer"], div["tensor"])
        out(
            "  replay attribution "
            + ("MATCHES" if first == want else "DIFFERS FROM")
            + f" the stream's: first divergent block {first} vs "
            f"recorded {want}"
        )
    return diffs


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


class _Parser(argparse.ArgumentParser):
    # exit-code contract: 0 identical, 2 divergence — so usage/read
    # errors must NOT collide with argparse's default exit code 2
    def error(self, message):
        self.print_usage(sys.stderr)
        self.exit(1, f"{self.prog}: error: {message}\n")


def main(argv=None):
    ap = _Parser(
        prog="python -m shallowspeed_tpu.observability.divergence",
        description="Join two runs' digest streams and name the first "
        "divergent (global_step, layer, tensor). Exit 0 when the streams "
        "are bitwise-equal, 2 on divergence, 1 on usage/read errors.",
    )
    ap.add_argument("run_a", help="metrics JSONL of run A (digest records)")
    ap.add_argument("run_b", help="metrics JSONL of run B")
    ap.add_argument(
        "--bisect", nargs=2, metavar=("CKPT_DIR_A", "CKPT_DIR_B"),
        default=None,
        help="restore each run's last agreeing step checkpoint and "
        "re-execute ONE step under both recorded configs, dumping the "
        "offending tensor's elementwise diff (max ULP distance, first "
        "differing flat index)",
    )
    args = ap.parse_args(argv)

    from shallowspeed_tpu.observability.metrics import read_jsonl

    try:
        records_a = read_jsonl(args.run_a)
        records_b = read_jsonl(args.run_b)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    sa, sb = digest_stream(records_a), digest_stream(records_b)
    if not sa or not sb:
        empty = args.run_a if not sa else args.run_b
        print(
            f"error: {empty}: no digest records — was the run started "
            "with --digests and --metrics-out?",
            file=sys.stderr,
        )
        return 1
    div = first_divergence(sa, sb)
    if div is None:
        steps = len(set(sa) & set(sb))
        layers = next(iter(sa.values())).get("layers", 0)
        print(
            f"IDENTICAL: {steps} steps x {layers} layers bitwise-equal "
            f"({args.run_a} vs {args.run_b})"
        )
        return 0
    print("DIVERGENT:")
    for line in format_divergence(div, "run-a", "run-b"):
        print(line)
    if args.bisect is not None:
        try:
            bisect_replay(records_a, records_b, args.bisect[0],
                          args.bisect[1], div)
        except ValueError as e:
            print(f"bisect error: {e}", file=sys.stderr)
            return 1
    return 2


if __name__ == "__main__":
    sys.exit(main())
