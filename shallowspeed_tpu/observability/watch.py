"""Live telemetry watcher: a refreshing terminal dashboard over JSONL
metrics shards.

    # tail a LIVE run (new shards are picked up as they appear)
    python -m shallowspeed_tpu.observability.watch run.jsonl --follow \
        [--interval 0.5] [--idle-exit 5] [--max-wall 300]

    # one shot over a FINISHED run (CI / post-mortem)
    python -m shallowspeed_tpu.observability.watch run.jsonl --once \
        [--format text|json]

Path resolution is the ONE shard-glob path ``read_jsonl`` uses
(``metrics._expand_shards``): an existing file is read as-is, an
explicit glob expands sorted, and a bare missing path falls back to its
``.p[0-9]*`` / ``.r[0-9]*`` shards — so a live fleet run's per-replica
shards and a finished single file resolve IDENTICALLY in both readers
(pass ``fleet.jsonl*`` to merge a fleet parent with its ``.r*``
shards, exactly as ``read_jsonl`` would).

Determinism contract: every aggregate the watcher shows is a pure
function of the record BYTES read so far — windows close on record
``ts`` (rollup.py), never on wall clock, and the final ``--follow``
snapshot over a finished file equals the ``--once`` snapshot over the
same file bit-for-bit (``make alerts-smoke`` gates on this). Wall
clock only decides WHEN to poll and when to give up (``--idle-exit``:
exit once no shard grows for that many seconds; ``--max-wall``: hard
cap — both are how CI runs a watcher against a live run and still
terminates).

Compatibility: records with a schema version NEWER than this reader
are counted (``skipped_newer``) and skipped, not misread — the live
dashboard stays up through a rolling upgrade, while the strict
``read_jsonl`` contract (refuse loudly) still guards programmatic
consumers. Incomplete trailing lines (a writer mid-append) are left in
the tail buffer until their newline arrives; complete-but-malformed
lines are counted as ``malformed`` and fail ``--once`` loudly.
"""

import argparse
import json
import os
import sys
import time
from collections import deque

from shallowspeed_tpu.observability.metrics import (
    SCHEMA_VERSION,
    _expand_shards,
)
from shallowspeed_tpu.observability.rollup import RollupBuilder

_SPARK = "▁▂▃▄▅▆▇█"


def _sparkline(values, width=32):
    """Tiny local sparkline (non-finite-free inputs): last ``width``
    values scaled to the observed range."""
    vals = [v for v in values if v is not None][-width:]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    if hi <= lo:
        return _SPARK[0] * len(vals)
    return "".join(
        _SPARK[int((v - lo) / (hi - lo) * (len(_SPARK) - 1))] for v in vals
    )


def _fmt(v, unit=""):
    if v is None:
        return "n/a"
    if isinstance(v, float):
        if abs(v) >= 100:
            return f"{v:.0f}{unit}"
        if abs(v) >= 1:
            return f"{v:.2f}{unit}"
        return f"{v:.4g}{unit}"
    return f"{v}{unit}"


class _Tailer:
    """Incremental reader of one shard: consumes complete lines only,
    keeps the partial tail until its newline lands."""

    __slots__ = ("path", "offset", "buf")

    def __init__(self, path):
        self.path = path
        self.offset = 0
        self.buf = ""

    def poll(self):
        """Yield newly-completed lines since the last poll."""
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return []
        if size <= self.offset:
            return []
        with open(self.path, encoding="utf-8") as f:
            f.seek(self.offset)
            chunk = f.read(size - self.offset)
            self.offset = f.tell()
        data = self.buf + chunk
        lines = data.split("\n")
        self.buf = lines.pop()  # "" when chunk ended on a newline
        return [ln for ln in lines if ln.strip()]


class WatchState:
    """The fold: record stream in, deterministic snapshot out."""

    def __init__(self, window_s=1.0, history=240):
        self.records = 0
        self.skipped_newer = 0
        self.malformed = 0
        self.rollups = {}  # display key -> latest emitted rollup record
        self.trends = {}  # display key -> deque of terminal/steps rate
        self.alerts = []  # alert transitions in stream order
        self.active = {}  # (rule, replica_id) -> latest firing record
        self.events = deque(maxlen=12)  # recent health events
        self.summaries = {}  # latest `serving`/`fleet` summary per name
        # the capacity loop (schema v13): live fleet size + the most
        # recent autoscale decision — folded from `autoscale` records
        # and the fleet_health scale_up/scale_down target field, so the
        # dashboard shows the loop acting whether or not a policy runs
        self.fleet_replicas = None
        self.autoscale_count = 0
        self.last_autoscale = None
        self.history = history
        # the watcher's OWN rollups recomputed from raw records — the
        # surface for runs that predate v11 emitters, and the
        # determinism gate's comparison object
        self.computed = {
            "serving": RollupBuilder("serving", window_s=window_s),
            "train": RollupBuilder("train", window_s=window_s),
        }

    def ingest_line(self, line):
        try:
            rec = json.loads(line)
        except ValueError:
            self.malformed += 1
            return
        if not isinstance(rec, dict):
            self.malformed += 1
            return
        if rec.get("v", 0) > SCHEMA_VERSION:
            self.skipped_newer += 1
            return
        self.records += 1
        kind = rec.get("kind")
        ts = rec.get("ts")
        if kind == "rollup":
            rid = rec.get("replica_id")
            key = rec.get("name") or "?"
            if rid is not None:
                key = f"{key}.r{rid}"
            self.rollups[key] = rec
            rate = None
            for counter in ("terminal", "steps"):
                r = (rec.get("rates") or {}).get(counter)
                if r is not None:
                    rate = r.get("rate")
                    break
            trend = self.trends.get(key)
            if trend is None:
                trend = self.trends[key] = deque(maxlen=self.history)
            trend.append(rate)
        elif kind == "alert":
            self.alerts.append(rec)
            akey = (rec.get("name"), rec.get("replica_id"))
            if rec.get("state") == "firing":
                self.active[akey] = rec
            else:
                self.active.pop(akey, None)
        elif kind == "request" and ts is not None:
            c = self.computed["serving"]
            c.count(ts, rec.get("name") or "?")
            c.count(ts, "terminal")
            if rec.get("latency_s") is not None:
                c.observe(ts, "latency_s", rec["latency_s"])
            if rec.get("queue_s") is not None:
                c.observe(ts, "queue_s", rec["queue_s"])
        elif kind == "step" and ts is not None:
            c = self.computed["train"]
            c.count(ts, "steps")
            if rec.get("loss") is not None:
                c.gauge(ts, "loss", rec["loss"])
        elif kind == "autoscale":
            self.autoscale_count += 1
            self.last_autoscale = rec
            if rec.get("replicas_after") is not None:
                self.fleet_replicas = rec["replicas_after"]
        elif kind in ("serving_health", "fleet_health", "health"):
            self.events.append(rec)
            if (
                kind == "fleet_health"
                and rec.get("name") in ("scale_up", "scale_down")
                and rec.get("target") is not None
            ):
                self.fleet_replicas = rec["target"]
        elif kind in ("serving", "fleet"):
            self.summaries[f"{kind}:{rec.get('name')}"] = rec

    def snapshot(self):
        """JSON-able state — a pure function of the bytes ingested (the
        --follow == --once determinism object)."""
        return {
            "records": self.records,
            "skipped_newer": self.skipped_newer,
            "malformed": self.malformed,
            "rollups": {
                k: {f: v for f, v in rec.items() if f != "sketches"}
                for k, rec in sorted(self.rollups.items())
            },
            "computed": {
                name: b.snapshot() for name, b in self.computed.items()
            },
            "alerts": {
                "transitions": list(self.alerts),
                "active": sorted(
                    r.get("name") or "?" for r in self.active.values()
                ),
                "fired": sum(
                    1 for a in self.alerts if a.get("state") == "firing"
                ),
                "resolved": sum(
                    1 for a in self.alerts if a.get("state") == "resolved"
                ),
            },
            "summaries": dict(sorted(self.summaries.items())),
            "fleet": {
                "replicas": self.fleet_replicas,
                "autoscale_decisions": self.autoscale_count,
                "last_autoscale": self.last_autoscale,
            },
        }

    # -- text rendering -----------------------------------------------------

    def render_text(self, path, shards):
        lines = [
            f"watch {path} — {len(shards)} shard(s), {self.records} "
            f"record(s)"
            + (
                f", {self.skipped_newer} newer-schema skipped"
                if self.skipped_newer
                else ""
            )
            + (f", {self.malformed} MALFORMED" if self.malformed else "")
        ]
        if self.active:
            firing = ", ".join(
                f"{rec.get('name')}[{rec.get('severity')}]"
                for rec in self.active.values()
            )
            lines.append(f"ALERTS FIRING: {firing}")
        else:
            lines.append("alerts: none firing")
        if self.fleet_replicas is not None or self.last_autoscale:
            parts = [f"fleet: {_fmt(self.fleet_replicas)} replica(s)"]
            la = self.last_autoscale
            if la:
                parts.append(
                    f"last autoscale [{_fmt(la.get('t'))}] "
                    f"{la.get('name')} ({la.get('direction')}, rule "
                    f"{la.get('rule')}, {_fmt(la.get('replicas_before'))}"
                    f"→{_fmt(la.get('replicas_after'))})"
                )
            lines.append(" | ".join(parts))
        for a in self.alerts[-6:]:
            t = a.get("t")
            lines.append(
                f"  [{_fmt(t)}] {a.get('name')} {a.get('state', '?').upper()}"
                f" — {a.get('reason', '')}"
            )
        for key, rec in sorted(self.rollups.items()):
            counters = rec.get("counters") or {}
            rates = rec.get("rates") or {}
            gauges = rec.get("gauges") or {}
            quant = rec.get("quantiles") or {}
            parts = [
                f"{key:<12} win#{rec.get('seq')} "
                f"[{_fmt(rec.get('window_start'))},"
                f"{_fmt(rec.get('window_end'))})"
            ]
            for counter in ("terminal", "steps"):
                if counter in rates:
                    parts.append(
                        f"{_fmt(counters.get(counter))} {counter} "
                        f"({_fmt(rates[counter].get('rate'))}/s, "
                        f"ewma {_fmt(rates[counter].get('ewma'))}/s)"
                    )
            lat = quant.get("latency_s") or quant.get("step_s")
            if lat:
                parts.append(
                    f"p50 {_fmt(lat.get('p50'))}s p99 {_fmt(lat.get('p99'))}s"
                )
            for gname in ("queue_depth", "loss", "throughput", "mfu"):
                g = gauges.get(gname)
                if g:
                    parts.append(f"{gname} {_fmt(g.get('last'))}")
            lines.append(" | ".join(parts))
            spark = _sparkline(self.trends.get(key, ()))
            if spark:
                lines.append(f"{'':<12} rate {spark}")
        for name, builder in sorted(self.computed.items()):
            snap = builder.snapshot()
            last = snap["last_window"] or snap["live_window"]
            if not last:
                continue
            counters = last.get("counters") or {}
            quant = last.get("quantiles") or {}
            parts = [f"computed:{name:<4} windows {snap['windows_closed']}"]
            if counters:
                top = sorted(counters.items())[:4]
                parts.append(
                    " ".join(f"{k}={_fmt(v)}" for k, v in top)
                )
            lat = quant.get("latency_s")
            if lat:
                parts.append(
                    f"p50 {_fmt(lat.get('p50'))}s p99 {_fmt(lat.get('p99'))}s"
                )
            lines.append(" | ".join(parts))
        for ev in list(self.events)[-4:]:
            lines.append(
                f"  health [{_fmt(ev.get('ts'))}] {ev.get('kind')}:"
                f"{ev.get('name')}"
            )
        return "\n".join(lines)


def _resolve(path):
    """The shared resolution, softened for a not-yet-written live run:
    an unmatched glob means "no shards yet", not an error."""
    try:
        return [s for s in _expand_shards(path) if os.path.exists(s)]
    except FileNotFoundError:
        return []


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m shallowspeed_tpu.observability.watch",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("path", help="metrics JSONL path, glob, or shard base")
    mode = ap.add_mutually_exclusive_group()
    mode.add_argument(
        "--follow", action="store_true", help="tail live shards (default)"
    )
    mode.add_argument(
        "--once", action="store_true", help="read everything once and exit"
    )
    ap.add_argument("--format", choices=["text", "json"], default="text")
    ap.add_argument(
        "--interval", type=float, default=0.5, help="poll period (seconds)"
    )
    ap.add_argument(
        "--window",
        type=float,
        default=1.0,
        help="tumbling-window width for the watcher's own computed rollups",
    )
    ap.add_argument(
        "--idle-exit",
        type=float,
        default=None,
        metavar="S",
        help="--follow: exit 0 once no shard has grown for S seconds "
        "(after at least one record)",
    )
    ap.add_argument(
        "--max-wall",
        type=float,
        default=None,
        metavar="S",
        help="--follow: hard wall-clock cap",
    )
    args = ap.parse_args(argv)
    follow = not args.once

    state = WatchState(window_s=args.window)
    tailers = {}
    start = time.monotonic()
    last_growth = start
    clear = follow and args.format == "text" and sys.stdout.isatty()
    rendered = False

    while True:
        shards = _resolve(args.path)
        grew = False
        for shard in shards:
            tailer = tailers.get(shard)
            if tailer is None:
                tailer = tailers[shard] = _Tailer(shard)
            for line in tailer.poll():
                state.ingest_line(line)
                grew = True
        now = time.monotonic()
        if grew:
            last_growth = now
        if args.format == "text" and (grew or not rendered):
            frame = state.render_text(args.path, shards)
            if clear:
                sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
            else:
                print(frame)
                print("---")
            sys.stdout.flush()
            rendered = True
        if not follow:
            break
        if (
            args.idle_exit is not None
            and state.records > 0
            and now - last_growth >= args.idle_exit
        ):
            break
        if args.max_wall is not None and now - start >= args.max_wall:
            break
        time.sleep(args.interval)

    if args.format == "json":
        # the deterministic final snapshot (no wall clock inside) — what
        # make alerts-smoke diffs between --follow and --once
        print(
            json.dumps(
                _json_safe_snapshot(state.snapshot()),
                indent=2,
                allow_nan=False,
                sort_keys=True,
            )
        )
    if not follow and (state.records == 0 or state.malformed):
        return 1
    return 0


def _json_safe_snapshot(snapshot):
    from shallowspeed_tpu.observability.metrics import json_safe

    return json_safe(snapshot)


if __name__ == "__main__":
    sys.exit(main())
