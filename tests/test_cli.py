"""End-to-end CLI smoke tests: run train.py as a subprocess on tiny data.

The reference has no driver-level tests at all; these execute the actual
user-facing command (sequential and a DP x PP mesh layout) against a small
synthetic dataset and assert on the printed contract: per-epoch accuracy
lines, mean-train-loss lines, the replica-sync confirmation and the final
model hash.
"""

import os
import re
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

ROOT = Path(__file__).resolve().parent.parent


@pytest.fixture(scope="module")
def tiny_data(tmp_path_factory):
    d = tmp_path_factory.mktemp("tiny_mnist")
    rng = np.random.RandomState(0)
    for suffix, n in (("train", 256), ("val", 96)):
        np.save(d / f"x_{suffix}.npy", rng.rand(n, 784).astype(np.float32))
        np.save(
            d / f"y_{suffix}.npy",
            np.eye(10, dtype=np.float32)[rng.randint(0, 10, n)],
        )
    return d


def _run_raw(args, data_dir, extra_env=None):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never dial the TPU tunnel in tests
    env["JAX_PLATFORMS"] = "cpu"
    env.update(extra_env or {})
    return subprocess.run(
        [sys.executable, str(ROOT / "train.py"), "--data-dir", str(data_dir), *args],
        capture_output=True,
        text=True,
        timeout=540,
        cwd=ROOT,
        env=env,
    )


def _run(args, data_dir, extra_env=None):
    r = _run_raw(args, data_dir, extra_env=extra_env)
    assert r.returncode == 0, r.stderr[-2000:]
    return r.stdout


def test_sequential_cli(tiny_data):
    out = _run(
        ["--epochs", "2", "--global-batch-size", "32", "--mubatches", "2"], tiny_data
    )
    assert out.count("mean train loss") == 2
    assert "Accuracy:" in out
    assert re.search(r"final model hash: [0-9a-f]{40}", out)
    assert "(sequential)" in out


def test_sequential_cli_fused(tiny_data):
    out = _run(
        ["--epochs", "1", "--global-batch-size", "32", "--mubatches", "2",
         "--no-eval", "--fuse-mubatches"],
        tiny_data,
    )
    assert re.search(r"final model hash: [0-9a-f]{40}", out)


def test_sequential_cli_epoch_kernel_matches_fused(tiny_data):
    """--epoch-kernel (whole epoch as one Pallas kernel) trains to the same
    model hash as the fused XLA path through the real CLI."""
    hashes = {}
    for extra in ([], ["--epoch-kernel"]):
        out = _run(
            ["--epochs", "1", "--global-batch-size", "32", "--mubatches", "2",
             "--no-eval", "--fuse-mubatches", *extra],
            tiny_data,
        )
        hashes[bool(extra)] = re.search(
            r"final model hash: ([0-9a-f]{40})", out
        ).group(1)
    assert hashes[False] == hashes[True]


def test_mesh_cli_dp2_pp2(tiny_data):
    out = _run(
        [
            "--dp", "2", "--pp", "2", "--schedule", "pipedream",
            "--epochs", "1", "--global-batch-size", "32", "--mubatches", "2",
            "--no-eval",
        ],
        tiny_data,
        extra_env={"XLA_FLAGS": "--xla_force_host_platform_device_count=8"},
    )
    assert "(pipedream pipeline)" in out
    assert "DP replicas in sync" in out
    assert re.search(r"final model hash: [0-9a-f]{40}", out)


def test_mesh_cli_grad_bucket_bytes_matches_anchor(tiny_data):
    """--grad-bucket-bytes through the real CLI (with --audit enforcing
    the bucketed census): the final model hash must equal the anchor
    run's — the knob is a scheduling choice, never a numerics one."""
    common = [
        "--dp", "2", "--epochs", "1", "--global-batch-size", "32",
        "--mubatches", "2", "--no-eval", "--audit",
    ]
    env = {"XLA_FLAGS": "--xla_force_host_platform_device_count=8"}
    anchor = _run(common, tiny_data, extra_env=env)
    bucketed = _run(
        common + ["--grad-bucket-bytes", "65536"], tiny_data, extra_env=env
    )
    h = re.compile(r"final model hash: ([0-9a-f]{40})")
    assert h.search(anchor).group(1) == h.search(bucketed).group(1)
    assert "DP replicas in sync" in bucketed


def test_mesh_cli_backward_split_matches_unsplit(tiny_data):
    """--backward-split through the real CLI (with --audit enforcing the
    split program's collective contract): the final model hash must equal
    the unsplit run's — the deferred B-weights change tick packing, never
    the numerics."""
    common = [
        "--pp", "4", "--schedule", "pipedream", "--epochs", "1",
        "--global-batch-size", "32", "--mubatches", "2", "--no-eval",
    ]
    env = {"XLA_FLAGS": "--xla_force_host_platform_device_count=8"}
    unsplit = _run(common, tiny_data, extra_env=env)
    split = _run(common + ["--backward-split", "--audit"], tiny_data, extra_env=env)
    h = re.compile(r"final model hash: ([0-9a-f]{40})")
    assert h.search(unsplit).group(1) == h.search(split).group(1)


def test_mesh_cli_interleaved_zero1_momentum(tiny_data):
    """The round-2 flag surface in one run: interleaved virtual stages,
    ZeRO-1 sharded momentum."""
    out = _run(
        [
            "--dp", "2", "--pp", "2", "--schedule", "interleaved",
            "--virtual-stages", "2", "--zero1", "--optimizer", "momentum",
            "--epochs", "1", "--global-batch-size", "32", "--mubatches", "2",
            "--no-eval",
        ],
        tiny_data,
        extra_env={"XLA_FLAGS": "--xla_force_host_platform_device_count=8"},
    )
    assert "(interleaved pipeline, V=2)" in out
    assert "DP replicas in sync" in out
    assert re.search(r"final model hash: [0-9a-f]{40}", out)


@pytest.mark.slow
def test_mesh_cli_zero23_hash_pin(tiny_data):
    """The ZeRO lattice's CLI surface: --zero 2 and --zero 1 at
    --mubatches 1 print the SAME final model hash (the fixed-layout
    bitwise pin — one scatter contribution per shard element), and
    --zero 3 trains, evals and syncs on the same layout. (Slow tier:
    `make zero-smoke` runs the identical CLI pin end-to-end, and the
    session/executor pins cover it in tier-1.)"""
    common = [
        "--dp", "2", "--pp", "2", "--optimizer", "momentum",
        "--epochs", "1", "--global-batch-size", "32", "--mubatches", "1",
    ]
    env = {"XLA_FLAGS": "--xla_force_host_platform_device_count=8"}
    hashes = {}
    for stage in ("1", "2"):
        out = _run(common + ["--zero", stage, "--no-eval"], tiny_data,
                   extra_env=env)
        hashes[stage] = re.search(r"final model hash: ([0-9a-f]{40})", out).group(1)
    assert hashes["1"] == hashes["2"]
    out = _run(common + ["--zero", "3"], tiny_data, extra_env=env)
    assert "DP replicas in sync" in out
    assert re.search(r"final model hash: [0-9a-f]{40}", out)


def test_cli_zero_refusals_exit_2(tiny_data):
    """The six fail-fast lattice refusals, all at argparse time (exit 2,
    pre-backend): stage conflicts and the combinations the executor has
    no program for."""
    cases = [
        (["--zero1", "--zero", "2"], "conflicting dp-stage selectors"),
        (["--zero", "3", "--dp", "2", "--fused-run"],
         "incompatible with --fused-run"),
        (["--zero", "3", "--dp", "2", "--kernel-backend", "pallas"],
         "incompatible with --kernel-backend pallas"),
        (["--zero", "3", "--dp", "2", "--grad-bucket-bytes", "1024"],
         "syncs gradients per tick"),
        (["--zero", "2", "--dp", "2", "--pp", "2", "--runtime", "mpmd"],
         "does not support --zero"),
        (["--zero", "2", "--dp", "2", "--digests"],
         "--digests is incompatible"),
    ]
    for args, msg in cases:
        r = _run_raw(args, tiny_data)
        assert r.returncode == 2, (args, r.stderr[-500:])
        assert msg in r.stderr, (args, r.stderr[-500:])


def test_mesh_cli_kernel_backend_pallas_matches_xla(tiny_data):
    """The executor's Pallas backend is a product feature, not a test-only
    artifact: the CLI flag must train bit-identically to the default XLA
    backend (interpreter mode off-TPU — same contract as on hardware)."""
    hashes = {}
    for kb in ("xla", "pallas"):
        out = _run(
            [
                "--dp", "2", "--pp", "2", "--schedule", "gpipe",
                "--epochs", "1", "--global-batch-size", "32", "--mubatches", "2",
                "--no-eval", "--kernel-backend", kb,
            ],
            tiny_data,
            extra_env={"XLA_FLAGS": "--xla_force_host_platform_device_count=8"},
        )
        hashes[kb] = re.search(r"final model hash: ([0-9a-f]{40})", out).group(1)
    assert hashes["xla"] == hashes["pallas"]


def test_cli_clip_and_decay_flags(tiny_data):
    out = _run(
        ["--epochs", "1", "--global-batch-size", "32", "--mubatches", "2",
         "--no-eval", "--clip-norm", "0.5", "--weight-decay", "0.01",
         "--optimizer", "momentum", "--lr", "0.001"],
        tiny_data,
    )
    assert re.search(r"final model hash: [0-9a-f]{40}", out)


def test_cli_checkpoint_resume_round_trip(tiny_data, tmp_path):
    ck = tmp_path / "ck.npz"
    _run(
        ["--epochs", "1", "--global-batch-size", "32", "--mubatches", "2",
         "--no-eval", "--checkpoint", str(ck)],
        tiny_data,
    )
    assert ck.exists()
    out = _run(
        ["--epochs", "1", "--global-batch-size", "32", "--mubatches", "2",
         "--no-eval", "--resume", str(ck)],
        tiny_data,
    )
    assert "resumed at epoch 1" in out


def test_fused_run_cli_matches_loop(tiny_data):
    """--fused-run (all epochs + eval in one device program) prints the SAME
    per-epoch contract as the epoch loop — same epoch-labeled accuracy
    sequence (pre-epoch semantics), same losses, same final hash."""
    common = ["--epochs", "2", "--global-batch-size", "32", "--mubatches", "2"]
    fused = _run(common + ["--fused-run"], tiny_data)
    loop = _run(common, tiny_data)

    def contract(out):
        # fused mode omits the per-line cumulative clock (all its lines print
        # after the one dispatch) — epoch labels and values must still agree
        accs = re.findall(
            r"Epoch: (\d+),(?: Time Spent: [\d.]+s,)? Accuracy: ([\d.]+)%", out
        )
        losses = re.findall(r"Epoch: (\d+), mean train loss: ([\d.]+)", out)
        h = re.search(r"final model hash: ([0-9a-f]{40})", out).group(1)
        return accs, losses, h

    f_accs, f_losses, f_hash = contract(fused)
    l_accs, l_losses, l_hash = contract(loop)
    assert f_losses == l_losses and len(f_losses) == 2
    assert f_accs == l_accs and len(f_accs) == 3  # pre-run, between, final
    assert f_hash == l_hash


def test_fused_run_cli_no_eval(tiny_data):
    """--fused-run honors --no-eval: losses printed, no accuracy lines except
    the final summary."""
    out = _run(
        ["--epochs", "2", "--global-batch-size", "32", "--mubatches", "2",
         "--fused-run", "--no-eval"],
        tiny_data,
    )
    assert out.count("mean train loss") == 2
    assert out.count("Accuracy:") == 1  # the final summary only


def test_sequential_cli_run_kernel_matches_fused(tiny_data):
    """--run-kernel --fused-run --no-eval (the ENTIRE 2-epoch run as one
    Pallas kernel) trains to the same model hash and prints the same
    per-epoch losses as the fused XLA run through the real CLI."""
    import re as _re

    outs = {}
    for extra in ([], ["--run-kernel"]):
        outs[bool(extra)] = _run(
            ["--epochs", "2", "--global-batch-size", "32", "--mubatches", "2",
             "--no-eval", "--fuse-mubatches", "--fused-run", *extra],
            tiny_data,
        )
    for key in (r"final model hash: ([0-9a-f]{40})",):
        a = _re.search(key, outs[False]).group(1)
        b = _re.search(key, outs[True]).group(1)
        assert a == b
    losses = {
        k: _re.findall(r"mean train loss: ([0-9.]+)", v) for k, v in outs.items()
    }
    assert losses[False] == losses[True] and len(losses[True]) == 2


# ---------------------------------------------------------------------------
# fault-tolerance CLI contracts (docs/robustness.md)
# ---------------------------------------------------------------------------


def test_fused_run_checkpoint_contract(tiny_data, tmp_path):
    """The pinned --checkpoint x --fused-run contract: the fused run is ONE
    dispatch, so --checkpoint saves exactly once, after it returns — and
    the STEP-checkpoint flags (which need a host step boundary) fail fast
    at argparse time with a message naming the conflict."""
    ck = tmp_path / "fused.npz"
    out = _run(
        ["--epochs", "2", "--global-batch-size", "32", "--mubatches", "2",
         "--no-eval", "--fused-run", "--checkpoint", str(ck)],
        tiny_data,
    )
    assert ck.exists()
    from shallowspeed_tpu.checkpoint import verify_checkpoint

    # one snapshot, of the post-run state: epoch = last COMPLETED epoch
    assert verify_checkpoint(ck)["epoch"] == 1
    assert re.search(r"final model hash: [0-9a-f]{40}", out)

    # step checkpointing and auto-resume have no fused-run entry point
    r = _run_raw(
        ["--fused-run", "--checkpoint-every-steps", "2",
         "--checkpoint-dir", str(tmp_path / "d")],
        tiny_data,
    )
    assert r.returncode == 2  # argparse contract violation, pre-backend
    assert "incompatible with --fused-run" in r.stderr
    r = _run_raw(
        ["--fused-run", "--resume", "auto", "--checkpoint-dir",
         str(tmp_path / "d")],
        tiny_data,
    )
    assert r.returncode == 2
    assert "no mid-epoch entry point" in r.stderr
    # incoherent flag combinations fail the same fast way
    r = _run_raw(["--checkpoint-every-steps", "2"], tiny_data)
    assert r.returncode == 2 and "--checkpoint-dir" in r.stderr
    r = _run_raw(["--resume", "auto"], tiny_data)
    assert r.returncode == 2 and "--checkpoint-dir" in r.stderr
    # an active env fault plan needs the step loop — silently completing
    # the uninjected fused run would fake a survived crash
    r = _run_raw(
        ["--fused-run", "--epochs", "1", "--no-eval"],
        tiny_data,
        extra_env={"SHALLOWSPEED_FAULTS": "die@step=3:mode=sigkill"},
    )
    assert r.returncode == 2
    assert "SHALLOWSPEED_FAULTS" in r.stderr and "step loop" in r.stderr


def test_fused_run_rejects_explicit_mid_epoch_resume(tiny_data, tmp_path):
    """--resume <path> escapes the argparse-time net (the snapshot's cursor
    is only known after reading it): restoring a MID-EPOCH snapshot under
    --fused-run must exit 2 with the same clean contract message as the
    argparse checks — not a raw mid-flight traceback out of the fused
    dispatch (which drivers would misread as an infrastructure crash)."""
    ck_dir = tmp_path / "ck"
    r = _run_raw(
        ["--epochs", "1", "--global-batch-size", "32", "--mubatches", "2",
         "--no-eval", "--checkpoint-dir", str(ck_dir),
         "--checkpoint-every-steps", "2"],
        tiny_data,
        extra_env={"SHALLOWSPEED_FAULTS": "die@step=3"},
    )
    assert r.returncode != 0  # the injected death left a mid-epoch snapshot
    snap = ck_dir / "step-00000002.npz"
    assert snap.exists()
    r = _run_raw(
        ["--epochs", "1", "--global-batch-size", "32", "--mubatches", "2",
         "--no-eval", "--fused-run", "--resume", str(snap)],
        tiny_data,
    )
    assert r.returncode == 2, (r.stdout, r.stderr[-2000:])
    assert "no mid-epoch entry point" in r.stderr
    assert "Traceback" not in r.stderr


def test_exit_code_3_on_health_halt(tiny_data):
    """The exit-code contract (README): a numerics halt exits 3 — here a
    NaN injected into the params at step 2 via the env-var fault harness,
    caught by --health halt, after flushing the finding to telemetry."""
    r = _run_raw(
        ["--epochs", "1", "--global-batch-size", "32", "--mubatches", "2",
         "--no-eval", "--health", "halt"],
        tiny_data,
        extra_env={"SHALLOWSPEED_FAULTS": "nan@step=2"},
    )
    assert r.returncode == 3, (r.stdout, r.stderr[-2000:])
    assert "HEALTH HALT" in r.stderr


def test_exit_code_4_on_unrecoverable_checkpoint_state(tiny_data, tmp_path):
    """The exit-code contract (README): --resume auto over a directory
    where NO snapshot verifies exits 4 (unrecoverable checkpoint state),
    naming every candidate and its failure cause."""
    ck_dir = tmp_path / "ck"
    ck_dir.mkdir()
    (ck_dir / "step-00000004.npz").write_bytes(b"not a zip archive")
    r = _run_raw(
        ["--epochs", "1", "--global-batch-size", "32", "--mubatches", "2",
         "--no-eval", "--resume", "auto", "--checkpoint-dir", str(ck_dir)],
        tiny_data,
    )
    assert r.returncode == 4, (r.stdout, r.stderr[-2000:])
    assert "CHECKPOINT UNRECOVERABLE" in r.stderr
    assert "step-00000004.npz" in r.stderr


@pytest.mark.slow
def test_sigkill_and_resume_auto_round_trip(tiny_data, tmp_path):
    """The real preemption shape through the real CLI (the in-suite twin of
    `make recovery-smoke`): SIGKILL a checkpointing run at an injected
    step — nothing flushes — then `--resume auto` finishes on exactly the
    uninterrupted twin's final hash."""
    common = ["--epochs", "2", "--global-batch-size", "32", "--mubatches",
              "2", "--no-eval"]
    twin = _run(common, tiny_data)
    ck_dir = tmp_path / "ck"
    r = _run_raw(
        common + ["--checkpoint-dir", str(ck_dir),
                  "--checkpoint-every-steps", "4"],
        tiny_data,
        extra_env={"SHALLOWSPEED_FAULTS": "die@step=11:mode=sigkill"},
    )
    assert r.returncode == -9  # killed, not exited
    assert (ck_dir / "step-00000008.npz").exists()
    out = _run(
        common + ["--checkpoint-dir", str(ck_dir),
                  "--checkpoint-every-steps", "4", "--resume", "auto"],
        tiny_data,
    )
    assert "resumed at epoch 1" in out
    want = re.search(r"final model hash: ([0-9a-f]{40})", twin).group(1)
    got = re.search(r"final model hash: ([0-9a-f]{40})", out).group(1)
    assert got == want


def test_resume_auto_epoch_boundary_honors_total_epochs(tiny_data, tmp_path):
    """--resume auto's TOTAL-epochs contract holds even when the restored
    cursor sits ON an epoch boundary and no step grid is active on the
    resuming run: 1 epoch trained + resume --epochs 2 == exactly one more
    epoch, bitwise equal to the uninterrupted 2-epoch twin."""
    common = ["--global-batch-size", "32", "--mubatches", "2", "--no-eval"]
    twin = _run(common + ["--epochs", "2"], tiny_data)
    ck_dir = tmp_path / "ck"
    _run(
        common + ["--epochs", "1", "--checkpoint-dir", str(ck_dir),
                  "--checkpoint-every-steps", "8"],
        tiny_data,
    )
    assert (ck_dir / "step-00000008.npz").exists()  # the epoch boundary
    out = _run(
        common + ["--epochs", "2", "--checkpoint-dir", str(ck_dir),
                  "--resume", "auto"],
        tiny_data,
    )
    assert "resumed at epoch 1" in out
    assert out.count("mean train loss") == 1  # ONE more epoch, not two
    want = re.search(r"final model hash: ([0-9a-f]{40})", twin).group(1)
    got = re.search(r"final model hash: ([0-9a-f]{40})", out).group(1)
    assert got == want
