"""Smoke tests for the developer tools (pebble renderer, scaling bench CLI)."""

import subprocess
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent


def test_show_schedule_renders_all(capsys):
    scripts_dir = str(ROOT / "scripts")
    sys.path.insert(0, scripts_dir)
    try:
        import show_schedule
    finally:
        sys.path.remove(scripts_dir)
    for name in ("gpipe", "naive", "pipedream", "inference"):
        show_schedule.render(name, 4, 4)
    out = capsys.readouterr().out
    assert "utilization" in out
    assert "F0" in out and "B0" in out
    # GPipe's lowered latency shows up in the header
    assert "gpipe  M=4 S=4: 14 ticks" in out


def test_train_cli_help():
    r = subprocess.run(
        [sys.executable, str(ROOT / "train.py"), "--help"],
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert r.returncode == 0
    for flag in ("--dp", "--pp", "--schedule", "--checkpoint", "--resume", "--precision"):
        assert flag in r.stdout
