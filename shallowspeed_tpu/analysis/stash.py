"""Stash-lifetime analysis over lowered tick programs.

The activation stash (and, in split-backward programs, the grad stash)
is the schedule's REAL memory: lowering sizes the physical buffers from
the simulator's peak slot pressure (``n_stash_slots``/``n_gstash_slots``
— GPipe peaks at M, PipeDream-Flush at min(M, depth - stage)). This pass
re-proves, from the emitted tables alone, the lifetime discipline those
buffer shapes assume:

- **write-before-read**: a backward (or split B-input peek / B-weight
  read) only ever touches a slot a forward filled and has not yet freed;
- **no double-write**: a forward never claims a live slot, and never
  reuses a slot in the very tick that freed it (stash reuse is legal
  from the NEXT tick — ``stash_free_from[slot] = t + 1`` in the
  simulator — unlike the mailboxes' same-tick reuse);
- **no leak**: every claimed slot is freed by program end;
- **exact peak**: the measured peak of concurrently-live slots equals
  the allocated depth — the buffers are sized to the schedule's true
  pressure, neither torn (too small) nor quietly padded (too large).

Recompute programs add the stage-input (xin) stash — filled by forwards,
freed by the matching OP_RECOMPUTE cell — replayed under the same
discipline, and ``assert_recompute_peak_drop`` is the pass the smoke
targets run: it proves, from the two programs' ACTUAL tick tables, that
the recompute twin's activation-stash peak is strictly below the stashed
twin's (or already at the floor of one live slot, where no schedule can
go lower).

Violations raise ``ProgramAnalysisError`` naming the tick, stage and
slot. Inference programs (no stash tables in use) pass trivially with
zeroed stats.
"""

from shallowspeed_tpu.analysis.progcheck import ProgramAnalysisError


def _check_one_stash(prog, label, write_tab, read_tab, peek_tab, depth):
    """Replay one stash's write/peek/read tables; returns (peak, writes)."""
    P, T = prog.num_stages, prog.num_ticks
    trash = int(depth)
    live = [dict() for _ in range(P)]  # slot -> claiming tick
    freed_at = [dict() for _ in range(P)]  # slot -> freeing tick
    peak = writes = reads = peeks = 0
    for t in range(T):
        for s in range(P):
            r = int(read_tab[t, s]) if read_tab is not None else trash
            w = int(write_tab[t, s]) if write_tab is not None else trash
            p = int(peek_tab[t, s]) if peek_tab is not None else trash
            if p != trash and p not in live[s]:
                raise ProgramAnalysisError(
                    f"tick {t} stage {s}: peeks {label} slot {p} which"
                    " holds no live value — read before write"
                )
            if p != trash:
                peeks += 1
            if r != trash:
                if r not in live[s]:
                    raise ProgramAnalysisError(
                        f"tick {t} stage {s}: reads {label} slot {r} which"
                        " holds no live value — read before write"
                    )
                del live[s][r]
                freed_at[s][r] = t
                reads += 1
            if w != trash:
                if w >= depth:
                    raise ProgramAnalysisError(
                        f"tick {t} stage {s}: writes {label} slot {w}"
                        f" outside the allocated depth {depth}"
                    )
                if w in live[s]:
                    raise ProgramAnalysisError(
                        f"tick {t} stage {s}: writes {label} slot {w}"
                        f" while it still holds the value stashed at tick"
                        f" {live[s][w]} — double write"
                    )
                if freed_at[s].get(w) == t:
                    raise ProgramAnalysisError(
                        f"tick {t} stage {s}: writes {label} slot {w} in"
                        " the same tick that freed it (stash reuse is"
                        " legal from the next tick)"
                    )
                live[s][w] = t
                writes += 1
                peak = max(peak, max(len(live[d]) for d in range(P)))
    for s in range(P):
        if live[s]:
            slot, t0 = next(iter(live[s].items()))
            raise ProgramAnalysisError(
                f"stage {s}: {label} slot {slot} (stashed at tick {t0}) is"
                " still live at program end — leaked stash slot"
            )
    if writes and peak != depth:
        raise ProgramAnalysisError(
            f"{label} measured peak {peak} != allocated depth {depth} —"
            " the buffers are not sized to the schedule's true pressure"
        )
    return {"peak": peak, "writes": writes, "reads": reads, "peeks": peeks}


def check_stash_lifetime(prog):
    """Prove the stash-lifetime contract for one lowered TickProgram
    (module docstring). Returns the pass's stats dict."""
    stats = {
        "stash_slots": int(prog.n_stash_slots),
        "gstash_slots": int(prog.n_gstash_slots),
    }
    if not prog.is_training:
        # inference programs stash nothing; their tables are all-trash
        stats["stash"] = {"peak": 0, "writes": 0, "reads": 0, "peeks": 0}
        stats["gstash"] = {"peak": 0, "writes": 0, "reads": 0, "peeks": 0}
        return stats
    stats["stash"] = _check_one_stash(
        prog, "activation stash", prog.stash_write, prog.stash_read,
        prog.stash_peek, int(prog.n_stash_slots),
    )
    if prog.backward_split:
        # split programs: every B-input must also have peeked the
        # activation stash its B-weight frees
        stats["gstash"] = _check_one_stash(
            prog, "grad stash", prog.gstash_write, prog.gstash_read,
            None, int(prog.n_gstash_slots),
        )
        if stats["gstash"]["writes"] != stats["gstash"]["reads"]:
            raise ProgramAnalysisError(
                "grad stash writes and reads disagree: "
                f"{stats['gstash']['writes']} B-inputs vs "
                f"{stats['gstash']['reads']} B-weights"
            )
    else:
        stats["gstash"] = {"peak": 0, "writes": 0, "reads": 0, "peeks": 0}
    stats["xin_slots"] = int(getattr(prog, "n_xin_slots", 0) or 0)
    if getattr(prog, "recompute", False):
        stats["xin"] = _check_one_stash(
            prog, "recompute input stash", prog.xin_write, prog.xin_read,
            None, int(prog.n_xin_slots),
        )
        if stats["xin"]["writes"] != stats["xin"]["reads"]:
            raise ProgramAnalysisError(
                "recompute input stash writes and reads disagree: "
                f"{stats['xin']['writes']} forwards stashed vs "
                f"{stats['xin']['reads']} recomputes freed"
            )
    else:
        stats["xin"] = {"peak": 0, "writes": 0, "reads": 0, "peeks": 0}
    if stats["stash"]["writes"] != stats["stash"]["reads"]:
        raise ProgramAnalysisError(
            "activation stash writes and reads disagree: "
            f"{stats['stash']['writes']} forwards stashed vs "
            f"{stats['stash']['reads']} backwards freed"
        )
    return stats


def assert_recompute_peak_drop(stashed_prog, rec_prog):
    """Prove — from the two twins' ACTUAL tick tables, not their
    allocation metadata — that recompute shortened the activation-stash
    lifetime: the recompute program's measured peak of concurrently-live
    residual-stash slots must be STRICTLY below the stashed twin's, or
    already sit at the floor of one live slot (a schedule that never
    holds more than one stash — the naive schedules — has nothing left
    to reclaim; demanding a drop there would be dishonest). The grad
    stash of split programs is held to the same bar. Returns the
    comparison dict the smoke target prints."""
    if not getattr(rec_prog, "recompute", False):
        raise ProgramAnalysisError(
            "assert_recompute_peak_drop: second program is not a"
            " recompute program"
        )
    if getattr(stashed_prog, "recompute", False):
        raise ProgramAnalysisError(
            "assert_recompute_peak_drop: first program must be the"
            " stashed twin"
        )
    s0 = check_stash_lifetime(stashed_prog)
    s1 = check_stash_lifetime(rec_prog)
    out = {
        "stash_peak_stashed": s0["stash"]["peak"],
        "stash_peak_recompute": s1["stash"]["peak"],
        "gstash_peak_stashed": s0["gstash"]["peak"],
        "gstash_peak_recompute": s1["gstash"]["peak"],
        "xin_peak": s1["xin"]["peak"],
    }
    for name in ("stash", "gstash"):
        p0, p1 = s0[name]["peak"], s1[name]["peak"]
        if p0 == 0:
            continue  # e.g. no grad stash in combined-backward programs
        if p0 > 1 and not p1 < p0:
            raise ProgramAnalysisError(
                f"recompute did not shorten the {name} lifetime: peak"
                f" {p1} is not strictly below the stashed twin's {p0}"
            )
        if p0 == 1 and p1 != 1:
            raise ProgramAnalysisError(
                f"{name} peak {p1} regressed from the stashed twin's"
                " floor of 1 live slot"
            )
    return out
