"""TrainingSession API tests: layout uniformity, resume, hash stability."""

import numpy as np
import pytest

from shallowspeed_tpu.api import TrainingSession

SIZES = (24, 20, 18, 16, 14, 12, 11, 10)
N, GBS = 512, 64


@pytest.fixture()
def data_dir(tmp_path):
    rng = np.random.RandomState(0)
    for suffix, n in (("train", N), ("val", 128)):
        x = rng.randn(n, SIZES[0]).astype(np.float32)
        y = np.eye(SIZES[-1], dtype=np.float32)[rng.randint(0, SIZES[-1], n)]
        np.save(tmp_path / f"x_{suffix}.npy", x)
        np.save(tmp_path / f"y_{suffix}.npy", y)
    return tmp_path


def _session(data_dir, **kw):
    kw.setdefault("sizes", SIZES)
    kw.setdefault("global_batch_size", GBS)
    kw.setdefault("lr", 0.01)
    return TrainingSession(data_dir=data_dir, **kw)


def test_layouts_converge_to_same_hash_class(data_dir):
    """Sequential, DP, PP and DP x PP sessions train to matching weights."""
    results = {}
    for name, kw in {
        "seq": dict(),
        "dp2pp4": dict(dp=2, pp=4, schedule="gpipe"),
        "pp4": dict(pp=4, schedule="pipedream"),
    }.items():
        run = _session(data_dir, **kw)
        for _ in range(2):
            run.train_epoch()
        run.assert_replicas_in_sync()
        results[name] = [l for st in run.params() for l in st]
        assert run.epoch == 2
    for other in ("dp2pp4", "pp4"):
        for a, b in zip(results["seq"], results[other]):
            np.testing.assert_allclose(
                np.asarray(a["W"]), np.asarray(b["W"]), rtol=3e-4, atol=3e-6
            )


def test_train_epoch_returns_decreasing_loss(data_dir):
    run = _session(data_dir, dp=2, pp=2, schedule="gpipe")
    losses = [run.train_epoch() for _ in range(3)]
    assert losses[2] < losses[0]


def test_accuracy_runs_all_layouts(data_dir):
    for kw in (dict(), dict(pp=4, schedule="gpipe")):
        run = _session(data_dir, **kw)
        acc = run.accuracy()
        assert 0.0 <= acc <= 1.0


def test_predict_agrees_across_layouts(data_dir):
    """Public predict(): same probabilities on every layout, ragged batch."""
    x = np.random.RandomState(7).randn(13, SIZES[0]).astype(np.float32)
    runs = [
        _session(data_dir, **kw)
        for kw in (
            dict(),
            dict(dp=2, pp=2, schedule="gpipe"),
            dict(pp=2, schedule="interleaved", virtual_stages=2),
        )
    ]
    preds = [r.predict(x) for r in runs]
    for p in preds:
        assert p.shape == (13, SIZES[-1])
        np.testing.assert_allclose(p.sum(1), 1.0, rtol=1e-4)
    for p in preds[1:]:
        np.testing.assert_allclose(p, preds[0], rtol=2e-4, atol=2e-5)


def test_save_resume_round_trip(data_dir, tmp_path):
    run = _session(data_dir)
    run.train_epoch()
    ck = tmp_path / "ck.npz"
    run.save(ck)
    resumed = _session(data_dir, dp=2, pp=4, schedule="gpipe", resume=ck)
    assert resumed.epoch == 1
    assert resumed.model_hash() == run.model_hash()  # layout-independent hash


def test_momentum_resume_matches_uninterrupted_run(data_dir, tmp_path):
    """Velocity is checkpointed: save-after-epoch-1 + resume must reproduce
    the uninterrupted 2-epoch trajectory bit-for-bit on the same layout, and
    within float tolerance across layouts (velocity re-partitioned like the
    weights)."""
    ref = _session(data_dir, optimizer="momentum")
    ref.train_epoch()
    ref.train_epoch()

    run = _session(data_dir, optimizer="momentum")
    run.train_epoch()
    ck = tmp_path / "m.npz"
    run.save(ck)

    resumed = _session(data_dir, optimizer="momentum", resume=ck)
    resumed.train_epoch()
    assert resumed.model_hash() == ref.model_hash()

    resumed_pp = _session(
        data_dir, optimizer="momentum", dp=2, pp=4, schedule="gpipe", resume=ck
    )
    resumed_pp.train_epoch()
    want = [l for st in ref.params() for l in st]
    got = [l for st in resumed_pp.params() for l in st]
    for a, b in zip(want, got):
        np.testing.assert_allclose(
            np.asarray(a["W"]), np.asarray(b["W"]), rtol=3e-4, atol=3e-6
        )

    # cross-layout state round-trip: save from the mesh layout, resume seq
    ck2 = tmp_path / "m2.npz"
    resumed_pp.save(ck2)
    back = _session(data_dir, optimizer="momentum", resume=ck2)
    st = back.opt_state_logical()
    assert st is not None
    vel = st["parts"][""]
    assert sum(float(np.abs(np.asarray(l["W"])).sum()) for s in vel for l in s) > 0


def test_adam_pipeline_equals_sequential_and_resumes(data_dir, tmp_path):
    """Adam's multi-part state (m, v, step count) through the full surface:
    layout parity, checkpoint round-trip, bit-exact same-layout resume."""
    ref = _session(data_dir, optimizer="adam")
    ref.train_epoch()
    ref.train_epoch()

    pp = _session(data_dir, optimizer="adam", dp=2, pp=4, schedule="gpipe")
    pp.train_epoch()
    pp.train_epoch()
    want = [l for st in ref.params() for l in st]
    got = [l for st in pp.params() for l in st]
    for a, b in zip(want, got):
        np.testing.assert_allclose(
            np.asarray(a["W"]), np.asarray(b["W"]), rtol=3e-4, atol=3e-6
        )

    run = _session(data_dir, optimizer="adam")
    run.train_epoch()
    ck = tmp_path / "a.npz"
    run.save(ck)
    st = run.opt_state_logical()
    assert set(st["parts"]) == {"m", "v"} and st["scalars"]["t"] > 0
    resumed = _session(data_dir, optimizer="adam", resume=ck)
    resumed.train_epoch()
    assert resumed.model_hash() == ref.model_hash()

    # and across layouts, through the stacked-state path
    resumed_pp = _session(
        data_dir, optimizer="adam", dp=2, pp=2, schedule="pipedream", resume=ck
    )
    resumed_pp.train_epoch()
    got2 = [l for s in resumed_pp.params() for l in s]
    for a, b in zip(want, got2):
        np.testing.assert_allclose(
            np.asarray(a["W"]), np.asarray(b["W"]), rtol=3e-4, atol=3e-6
        )


def test_optimizer_mismatch_on_resume_rejected(data_dir, tmp_path):
    run = _session(data_dir, optimizer="momentum")
    run.train_epoch()
    ck = tmp_path / "m.npz"
    run.save(ck)
    with pytest.raises(ValueError, match="optimizer"):
        _session(data_dir, optimizer="sgd", resume=ck)
    with pytest.raises(ValueError, match="momentum"):
        _session(data_dir, optimizer="momentum", momentum=0.5, resume=ck)


def test_invalid_config_rejected(data_dir):
    with pytest.raises(ValueError):
        _session(data_dir, dp=3)  # 64 % 3 != 0
    with pytest.raises(ValueError):
        _session(data_dir, mubatches=7)
    with pytest.raises(ValueError):
        _session(data_dir, precision="float32")
    with pytest.raises(ValueError):
        _session(data_dir, pp=2, schedule="1f1b")  # not a registered name
    with pytest.raises(ValueError):
        _session(data_dir, global_batch_size=4096)  # > training split


def test_grad_bucket_bytes_validation(data_dir):
    with pytest.raises(ValueError, match="sequential path has no gradient"):
        _session(data_dir, grad_bucket_bytes=4096)  # dp=pp=1: no sync
    with pytest.raises(ValueError, match=">= 0"):
        _session(data_dir, dp=2, grad_bucket_bytes=-1)
    # 0 / None are the legacy anchor, valid anywhere
    _session(data_dir, grad_bucket_bytes=0)
    _session(data_dir, dp=2, grad_bucket_bytes=None)


def test_grad_bucket_bytes_session_matches_anchor(data_dir):
    """Bucketed vs anchor THROUGH the session surface (per-epoch loop and
    the fused run): identical model hashes — the API plumbing changes
    nothing about the training computation."""
    runs = {}
    for gbb in (0, 2048):
        run = _session(data_dir, dp=2, pp=2, schedule="gpipe",
                       grad_bucket_bytes=gbb)
        run.train_epoch()
        runs[gbb] = run.model_hash()
        fused = _session(data_dir, dp=2, pp=2, schedule="gpipe",
                         grad_bucket_bytes=gbb, zero1=True)
        fused.train_run(1, with_eval=False)
        runs[f"z1-{gbb}"] = fused.model_hash()
    assert runs[0] == runs[2048]
    assert runs["z1-0"] == runs["z1-2048"]


def test_backward_split_validation(data_dir):
    with pytest.raises(ValueError, match="sequential path has no schedule"):
        _session(data_dir, backward_split=True)  # dp=pp=1: no schedule
    with pytest.raises(ValueError, match="interleaved"):
        _session(data_dir, pp=2, schedule="interleaved", virtual_stages=2,
                 backward_split=True)
    with pytest.raises(ValueError, match="pallas"):
        _session(data_dir, pp=2, schedule="gpipe", kernel_backend="pallas",
                 backward_split=True)


def test_recompute_validation(data_dir):
    """The recompute refusal matrix: every unsupported combination is
    refused at construction with an error naming the reason, not at the
    first backward tick."""
    with pytest.raises(ValueError, match="no cross-tick stash"):
        _session(data_dir, recompute=True)  # dp=pp=1: nothing stashed
    with pytest.raises(ValueError, match="interleaved virtual"):
        _session(data_dir, pp=2, schedule="interleaved", virtual_stages=2,
                 recompute=True)
    with pytest.raises(ValueError, match="no recompute branch"):
        _session(data_dir, pp=2, schedule="gpipe", kernel_backend="pallas",
                 recompute=True)


def test_model_zoo_validation(data_dir):
    """Zoo resolution refusals: unknown names list the zoo; gelu-family
    models refuse the relu-only pallas backend by name."""
    with pytest.raises(ValueError, match="unknown model"):
        _session(data_dir, model="mnist-cnn")
    with pytest.raises(ValueError, match="gelu-family"):
        _session(data_dir, model="transformer", pp=2, schedule="gpipe",
                 kernel_backend="pallas")


def test_backward_split_session_matches_unsplit(data_dir):
    """Split vs unsplit THROUGH the session surface (per-epoch loop and
    the fused run, ZeRO-1 included): identical model hashes — the split
    schedule changes tick packing, never the training computation."""
    runs = {}
    for bs in (False, True):
        run = _session(data_dir, pp=4, schedule="pipedream", backward_split=bs)
        run.train_epoch()
        runs[bs] = run.model_hash()
        fused = _session(data_dir, dp=2, pp=2, schedule="gpipe", zero1=True,
                         clip_norm=0.05, backward_split=bs)
        fused.train_run(1, with_eval=False)
        runs[f"z1-{bs}"] = fused.model_hash()
    assert runs[False] == runs[True]
    assert runs["z1-False"] == runs["z1-True"]


def test_train_run_matches_epoch_loop(data_dir):
    """The fused multi-epoch program (one dispatch for every epoch + its
    on-device full-split accuracy) must reproduce the looped
    train_epoch()/accuracy() path: same losses, same accuracies, same
    final weights."""
    looped = _session(data_dir)
    loop_losses, loop_accs = [], []
    for _ in range(3):
        loop_losses.append(looped.train_epoch())
        loop_accs.append(looped.accuracy())

    fused = _session(data_dir)
    losses, accs = fused.train_run(3)
    assert fused.epoch == 3
    assert np.allclose(losses, loop_losses, rtol=1e-6, atol=0)
    assert np.allclose(accs, loop_accs, atol=1e-6)
    assert fused.model_hash() == looped.model_hash()

    # a second fused run continues from the advanced state
    more_losses, _ = fused.train_run(2)
    assert fused.epoch == 5
    assert more_losses[0] < losses[0]


def test_train_run_mesh_fused(data_dir):
    """Mesh layouts run the whole multi-epoch program on-device too
    (executor.make_pipeline_run) and agree with the sequential run and with
    the mesh epoch loop."""
    run = _session(data_dir, dp=2, pp=2, schedule="gpipe")
    losses, accs = run.train_run(2)
    assert len(losses) == len(accs) == 2 and run.epoch == 2

    seq = _session(data_dir)
    seq_losses, seq_accs = seq.train_run(2)
    assert np.allclose(losses, seq_losses, rtol=1e-5)
    assert np.allclose(accs, seq_accs, atol=1e-6)

    looped = _session(data_dir, dp=2, pp=2, schedule="gpipe")
    loop_losses = [looped.train_epoch() for _ in range(2)]
    assert np.allclose(losses, loop_losses, rtol=1e-6)
    assert run.model_hash() == looped.model_hash()

    # losses-only variant and the interleaved inference-program branch
    ne = _session(data_dir, dp=2, pp=2, schedule="gpipe")
    ne_losses, ne_accs = ne.train_run(2, with_eval=False)
    assert ne_accs is None and np.allclose(ne_losses, losses, rtol=1e-6)
    iv = _session(data_dir, pp=2, virtual_stages=2, schedule="interleaved")
    iv_losses, iv_accs = iv.train_run(2)
    assert len(iv_losses) == len(iv_accs) == 2


def test_train_run_rejects_nonpositive_epochs(data_dir):
    with pytest.raises(ValueError, match="epochs"):
        _session(data_dir).train_run(0)


def test_train_run_without_eval(data_dir):
    """with_eval=False: no val split load, accs is None, same training."""
    ref = _session(data_dir)
    ref_losses, _ = ref.train_run(2)
    run = _session(data_dir)
    losses, accs = run.train_run(2, with_eval=False)
    assert accs is None and run._vx is None  # val split never loaded
    assert np.allclose(losses, ref_losses, rtol=1e-6, atol=0)
    assert run.model_hash() == ref.model_hash()


def test_warm_run_precompiles_and_matches(data_dir):
    """warm_run AOT-compiles the fused program; the next train_run reuses the
    executable and produces identical results to the un-warmed path."""
    ref = _session(data_dir)
    ref_losses, ref_accs = ref.train_run(2)

    warmed = _session(data_dir)
    warmed.warm_run(2)
    assert (True, 2) in warmed._compiled_runs
    losses, accs = warmed.train_run(2)
    assert np.allclose(losses, ref_losses, rtol=1e-6, atol=0)
    assert np.allclose(accs, ref_accs, atol=1e-6)
    assert warmed.model_hash() == ref.model_hash()

    # mesh layout too
    m = _session(data_dir, dp=2, pp=2, schedule="gpipe")
    m.warm_run(2)
    m_losses, _ = m.train_run(2)
    assert np.allclose(m_losses, ref_losses, rtol=1e-5)


def test_kernel_backend_pallas_matches_xla_via_api(data_dir):
    """The executor's Pallas backend through the product surface
    (TrainingSession(kernel_backend="pallas")): bit-identical training and
    evaluation vs the XLA backend on a DP x PP mesh."""
    runs = {}
    for kb in ("xla", "pallas"):
        run = _session(data_dir, dp=2, pp=2, schedule="gpipe", kernel_backend=kb)
        losses = [run.train_epoch() for _ in range(2)]
        runs[kb] = (
            losses,
            [l for st in run.params() for l in st],
            run.accuracy(),
        )
    assert runs["xla"][0] == runs["pallas"][0]
    for a, b in zip(runs["xla"][1], runs["pallas"][1]):
        np.testing.assert_array_equal(np.asarray(a["W"]), np.asarray(b["W"]))
        np.testing.assert_array_equal(np.asarray(a["b"]), np.asarray(b["b"]))
    assert runs["xla"][2] == runs["pallas"][2]


def test_kernel_backend_validation(data_dir):
    with pytest.raises(ValueError, match="kernel_backend"):
        _session(data_dir, kernel_backend="mosaic")
    # the sequential path has its own pallas routes (megakernel /
    # SHALLOWSPEED_PALLAS); the executor backend needs a mesh
    with pytest.raises(ValueError, match="mesh layout"):
        _session(data_dir, kernel_backend="pallas")


def test_epoch_kernel_matches_fused_via_api(data_dir):
    """TrainingSession(epoch_kernel=True): the whole-epoch Pallas kernel
    through the product surface trains bit-identically to the fused XLA
    path (and its epoch losses match)."""
    runs = {}
    for kw in ({}, {"epoch_kernel": True}):
        run = _session(data_dir, fuse_mubatches=True, **kw)
        losses = [run.train_epoch() for _ in range(2)]
        runs[bool(kw)] = (losses, run.model_hash())
    assert runs[False][0] == runs[True][0]
    assert runs[False][1] == runs[True][1]


def test_adam_epoch_kernel_checkpoint_resume_cross_layout(data_dir, tmp_path):
    """Optimizer state PRODUCED BY the epoch kernel (adam's m/v mirrors +
    the step counter advanced inside the kernel) must ride the checkpoint
    protocol like scan-produced state: resuming an interrupted kernel run
    reproduces the uninterrupted trajectory bit-for-bit, and the same
    checkpoint resumes onto a DP x PP mesh."""
    kw = dict(optimizer="adam", lr=2e-4, fuse_mubatches=True, epoch_kernel=True)
    ref = _session(data_dir, **kw)
    ref.train_epoch()
    ref.train_epoch()

    run = _session(data_dir, **kw)
    run.train_epoch()
    ck = tmp_path / "adam_kernel.npz"
    run.save(ck)
    resumed = _session(data_dir, resume=ck, **kw)
    resumed.train_epoch()
    assert resumed.model_hash() == ref.model_hash()

    # cross-layout: the kernel-trained state stacks onto a mesh session
    mesh = _session(
        data_dir, optimizer="adam", lr=2e-4, dp=2, pp=2, schedule="gpipe",
        resume=ck,
    )
    mesh.train_epoch()
    np.testing.assert_allclose(
        np.concatenate([
            np.asarray(l["W"]).ravel() for st in mesh.params() for l in st
        ]),
        np.concatenate([
            np.asarray(l["W"]).ravel() for st in ref.params() for l in st
        ]),
        rtol=2e-4, atol=2e-6,
    )


def test_run_kernel_via_api_matches_epoch_kernel(data_dir):
    """TrainingSession(run_kernel=True): the eval-free fused run is ONE
    device op and must reproduce the epoch-kernel session's losses and
    final hash; the evaluated surfaces (train_epoch, accuracy) still work
    and ride the epoch kernel."""
    from shallowspeed_tpu.api import TrainingSession

    losses = {}
    hashes = {}
    for kw in ({"epoch_kernel": True}, {"run_kernel": True}):
        run = TrainingSession(
            sizes=SIZES, data_dir=data_dir, fuse_mubatches=True,
            global_batch_size=32, mubatches=2, **kw,
        )
        losses[tuple(kw)], _ = run.train_run(2, with_eval=False)
        hashes[tuple(kw)] = run.model_hash()
        assert 0.0 <= run.accuracy() <= 1.0  # eval path still alive
    assert losses[("epoch_kernel",)] == losses[("run_kernel",)]
    assert hashes[("epoch_kernel",)] == hashes[("run_kernel",)]


def test_run_kernel_api_validation(data_dir):
    from shallowspeed_tpu.api import TrainingSession
    import pytest as _pytest

    with _pytest.raises(ValueError, match="fuse_mubatches"):
        TrainingSession(sizes=SIZES, data_dir=data_dir, run_kernel=True)
    with _pytest.raises(ValueError, match="subsumes"):
        TrainingSession(
            sizes=SIZES, data_dir=data_dir, fuse_mubatches=True,
            run_kernel=True, epoch_kernel=True,
        )


def test_run_kernel_state_rides_checkpoint_protocol(data_dir, tmp_path):
    """Optimizer state produced INSIDE the whole-run kernel (adam's m/v
    mirrors + step counter advanced across a multi-epoch grid) must ride
    the checkpoint protocol: save after a 2-epoch one-op run, resume, and
    land bit-for-bit on the uninterrupted 4-epoch one-op run."""
    kw = dict(optimizer="adam", lr=2e-4, fuse_mubatches=True, run_kernel=True)
    ref = _session(data_dir, **kw)
    ref.train_run(4, with_eval=False)

    run = _session(data_dir, **kw)
    run.train_run(2, with_eval=False)
    ck = tmp_path / "run_kernel.npz"
    run.save(ck)
    resumed = _session(data_dir, resume=ck, **kw)
    resumed.train_run(2, with_eval=False)
    assert resumed.model_hash() == ref.model_hash()
