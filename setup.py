from setuptools import find_packages, setup

setup(
    name="shallowspeed_tpu",
    version="0.1.0",
    description="TPU-native distributed-training framework (DP x PP on a JAX mesh)",
    packages=find_packages(include=["shallowspeed_tpu", "shallowspeed_tpu.*"]),
    python_requires=">=3.10",
    install_requires=["jax>=0.7", "numpy"],
)
