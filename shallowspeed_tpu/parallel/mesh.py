"""Device-mesh construction: the TPU replacement for the reference's two MPI
communicators (train.py:87-94 — dp_comm = Split(rank % PP), pp_comm =
Split(rank // PP)).

A 2-D ``jax.sharding.Mesh`` with axes ``('dp', 'pp')`` expresses the same
grid: rows are model replicas (the pp_comm groups), columns are same-stage
ranks across replicas (the dp_comm groups). Collectives over axis 'dp' =
Iallreduce over dp_comm; ppermute over axis 'pp' = the stage-relay Send/Recv
pairs. On a real slice the mesh rides ICI; on CPU tests it rides the
host-emulated devices from --xla_force_host_platform_device_count.

With ``tp > 1`` a THIRD axis is appended — ``('dp', 'pp', 'tp')`` — the
model (tensor-parallel) axis the Megatron-sharded layers all-reduce over
(parallel/executor.py). ``tp`` is the INNERMOST dimension of the topology
placement: a layer-pair costs two all-reduces over tp every microbatch
(the chattiest axis by far), so its group members must sit on adjacent
ICI links; dp (one gradient sync per batch) stays outermost. At ``tp == 1``
the mesh is the historical 2-axis grid, byte for byte — no degenerate
third axis ever reaches the compiled program, which is what keeps tp=1
programs anchored to the pre-TP hashes.
"""

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(dp: int, pp: int, devices=None, tp: int = 1) -> Mesh:
    """(dp, pp[, tp]) mesh. See ``make_mesh_with_layout`` for the placement
    rules; this wrapper drops the provenance note."""
    return make_mesh_with_layout(dp, pp, devices, tp)[0]


def make_mesh_with_layout(dp: int, pp: int, devices=None, tp: int = 1):
    """Build the mesh AND say how its devices were placed.

    Returns ``(mesh, layout)`` where ``layout`` is ``"topology-aware"``
    (jax.experimental.mesh_utils placement — on a real slice, ``tp``/``pp``
    neighbors sit on adjacent ICI links) or ``"order-preserving"`` (the
    plain ``jax.devices()`` order, reshaped). Bench records and the metrics
    stream carry this note so a measured number always says which placement
    it measured — the two can differ materially on a real slice.

    When devices aren't pinned explicitly, topology-aware placement is
    attempted first; only the errors ``mesh_utils`` actually raises for
    unplaceable shapes (ValueError / NotImplementedError) fall through to
    the order-preserving layout. Anything else — an ImportError from a
    broken install, a backend crash — propagates: a silent catch-all here
    once hid real failures behind an unlabeled placement change.
    """
    if tp < 1:
        raise ValueError(f"tp must be >= 1, got {tp}")
    explicit = devices is not None
    if devices is None:
        devices = jax.devices()
    need = dp * pp * tp
    if need > len(devices):
        raise ValueError(
            f"need {need} devices for DP={dp} x PP={pp} x TP={tp}, "
            f"have {len(devices)}"
        )
    shape = (dp, pp, tp) if tp > 1 else (dp, pp)
    axes = ("dp", "pp", "tp") if tp > 1 else ("dp", "pp")
    if not explicit and need == len(devices):
        try:
            from jax.experimental import mesh_utils

            grid = mesh_utils.create_device_mesh(shape)
            return Mesh(grid, axes), "topology-aware"
        except (ValueError, NotImplementedError):
            pass  # fall through to the order-preserving layout
    grid = np.asarray(devices[:need]).reshape(shape)
    return Mesh(grid, axes), "order-preserving"


def mesh_tp(mesh: Mesh) -> int:
    """The mesh's tensor-parallel degree: the size of its ``tp`` axis, 1
    when the axis is absent (every pre-TP 2-axis mesh). The ONE accessor
    executor/gradsync/audit code uses, so "no tp axis" and "tp axis of
    size 1" can never be treated differently by different consumers."""
    return int(dict(mesh.shape).get("tp", 1))
