"""JAX version compatibility shims for the parallel execution layer.

The ONE place version differences are absorbed, so every caller imports
``shard_map`` from here instead of guessing where this jax puts it:

- jax >= 0.6 exposes ``jax.shard_map`` (keyword-only mesh/specs, with the
  replication checker spelled ``check_vma``);
- older releases (e.g. 0.4.x) only have ``jax.experimental.shard_map``,
  whose checker kwarg is spelled ``check_rep``.

The exported ``shard_map`` accepts the NEW spelling everywhere and
translates for old runtimes, so executor code is written once against the
current API.
"""

import inspect

try:  # jax >= 0.6: the graduated public API
    from jax import shard_map as _shard_map
except ImportError:  # jax 0.4.x/0.5.x: still under jax.experimental
    from jax.experimental.shard_map import shard_map as _shard_map

_PARAMS = inspect.signature(_shard_map).parameters

if "check_vma" in _PARAMS:
    shard_map = _shard_map
else:

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kwargs):
        """``jax.shard_map``-style signature on an old experimental import:
        maps ``check_vma`` onto the legacy ``check_rep`` kwarg."""
        if check_vma is not None and "check_rep" in _PARAMS:
            kwargs.setdefault("check_rep", check_vma)
        return _shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
        )


__all__ = ["shard_map"]
