"""Sequential-trainer tests: NumPy-oracle parity + microbatch-count invariance.

The reference's whole correctness story is "distributed == sequential ==
full-batch" (SURVEY §3.3). Here: the jitted JAX step must match an independent
NumPy implementation step-for-step, and the result must be invariant to how
the batch is sliced into microbatches.
"""

import jax
import jax.numpy as jnp
import numpy as np

from shallowspeed_tpu import model as M
from shallowspeed_tpu.optimizer import SGD
from shallowspeed_tpu import trainer

import oracle_numpy

SIZES = (20, 16, 12, 10)
B = 32
LR = 0.006


def _data(num_batches, mubatches, rng):
    mb = B // mubatches
    X = rng.randn(num_batches, mubatches, mb, SIZES[0]).astype(np.float32)
    Y = np.eye(SIZES[-1], dtype=np.float32)[
        rng.randint(0, SIZES[-1], (num_batches, mubatches, mb))
    ]
    return X, Y


def _flat(params_list):
    return [(np.asarray(l["W"]), np.asarray(l["b"])) for s in params_list for l in s]


def test_matches_numpy_oracle_over_steps():
    spec = M.make_model_spec(SIZES, 1, B)
    params = jax.tree.map(jnp.asarray, M.init_model(spec))
    step = trainer.make_train_step(spec, SGD(LR))
    opt_state = ()

    oracle = oracle_numpy.init_params(SIZES)
    rng = np.random.RandomState(0)
    X, Y = _data(5, 4, rng)
    for b in range(5):
        params, opt_state = step(params, opt_state, jnp.asarray(X[b]), jnp.asarray(Y[b]))
        oracle = oracle_numpy.train_step(oracle, X[b], Y[b], LR, B)
    for (jw, jb), (ow, ob) in zip(_flat(params), oracle):
        np.testing.assert_allclose(jw, ow, rtol=2e-4, atol=2e-6)
        np.testing.assert_allclose(jb, ob, rtol=2e-4, atol=2e-6)


def test_mubatch_count_invariance():
    """Training with M=1, 2, 4 microbatches must give (nearly) identical
    weights — the global-batch loss scaling + sum accumulation ledger."""
    rng = np.random.RandomState(1)
    Xflat, _ = _data(3, 1, rng)
    Yflat = np.eye(SIZES[-1], dtype=np.float32)[
        rng.randint(0, SIZES[-1], (3, 1, B))
    ]
    results = []
    for m in (1, 2, 4):
        spec = M.make_model_spec(SIZES, 1, B)
        params = jax.tree.map(jnp.asarray, M.init_model(spec))
        step = trainer.make_train_step(spec, SGD(LR))
        opt_state = ()
        X = Xflat.reshape(3, m, B // m, SIZES[0])
        Y = Yflat.reshape(3, m, B // m, SIZES[-1])
        for b in range(3):
            params, opt_state = step(
                params, opt_state, jnp.asarray(X[b]), jnp.asarray(Y[b])
            )
        results.append(_flat(params))
    for other in results[1:]:
        for (w0, b0), (w1, b1) in zip(results[0], other):
            np.testing.assert_allclose(w0, w1, rtol=1e-5, atol=1e-7)


def test_fused_mubatches_matches_scanned():
    """fuse_mubatches=True must train to the same weights as the microbatch
    scan — the sum-gradient ledger makes them the same computation, and the
    softmax head's stability max is grouped per microbatch. The data is made
    adversarial: one microbatch's inputs are scaled 50x so its logits dwarf
    the others' — exactly the case where an ungrouped global max would make
    the fused path diverge through the +1e-7 softmax denominator."""
    spec = M.make_model_spec(SIZES, 1, B)
    rng = np.random.RandomState(7)
    X, Y = _data(4, 4, rng)
    X[:, 2] *= 50.0  # adversarial magnitude spread across microbatches
    results = []
    for fuse in (False, True):
        params = jax.tree.map(jnp.asarray, M.init_model(spec))
        step = trainer.make_train_step(spec, SGD(LR), fuse_mubatches=fuse)
        st = ()
        for b in range(4):
            params, st = step(params, st, jnp.asarray(X[b]), jnp.asarray(Y[b]))
        results.append(_flat(params))
    for (w0, b0), (w1, b1) in zip(*results):
        np.testing.assert_allclose(w0, w1, rtol=1e-5, atol=1e-7)
        np.testing.assert_allclose(b0, b1, rtol=1e-5, atol=1e-7)


def test_epoch_scan_matches_per_batch_steps():
    spec = M.make_model_spec(SIZES, 1, B)
    rng = np.random.RandomState(2)
    X, Y = _data(4, 4, rng)

    params = jax.tree.map(jnp.asarray, M.init_model(spec))
    step = trainer.make_train_step(spec, SGD(LR))
    st = ()
    for b in range(4):
        params, st = step(params, st, jnp.asarray(X[b]), jnp.asarray(Y[b]))

    params2 = jax.tree.map(jnp.asarray, M.init_model(spec))
    epoch = trainer.make_train_epoch(spec, SGD(LR))
    params2, _, mean_loss = epoch(params2, (), jnp.asarray(X), jnp.asarray(Y))
    assert float(mean_loss) > 0.0

    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7),
        params,
        params2,
    )


def test_epoch_unroll_is_bit_identical():
    """unroll is a scheduling knob: same ops in the same order, so the
    trained weights must match bit-for-bit."""
    spec = M.make_model_spec(SIZES, 1, B)
    rng = np.random.RandomState(3)
    X, Y = _data(6, 4, rng)
    outs = []
    for unroll in (1, 3):
        params = jax.tree.map(jnp.asarray, M.init_model(spec))
        epoch = trainer.make_train_epoch(spec, SGD(LR), unroll=unroll)
        params, _, loss = epoch(params, (), jnp.asarray(X), jnp.asarray(Y))
        outs.append((jax.device_get(params), float(loss)))
    assert outs[0][1] == outs[1][1]
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(a, b), outs[0][0], outs[1][0]
    )


def test_training_learns_separable_data():
    spec = M.make_model_spec((8, 16, 10), 1, B)
    params = jax.tree.map(jnp.asarray, M.init_model(spec))
    rng = np.random.RandomState(3)
    labels = rng.randint(0, 10, 512)
    centers = rng.randn(10, 8).astype(np.float32) * 2
    Xall = (centers[labels] + rng.randn(512, 8).astype(np.float32) * 0.1)
    Yall = np.eye(10, dtype=np.float32)[labels]
    loss_fn = trainer.make_loss_fn(spec)
    step = trainer.make_train_step(spec, SGD(0.05))
    before = float(loss_fn(params, jnp.asarray(Xall[:B]), jnp.asarray(Yall[:B])))
    st = ()
    for epoch in range(30):
        for i in range(0, 512, B):
            xb = Xall[i : i + B].reshape(4, B // 4, 8)
            yb = Yall[i : i + B].reshape(4, B // 4, 10)
            params, st = step(params, st, jnp.asarray(xb), jnp.asarray(yb))
    after = float(loss_fn(params, jnp.asarray(Xall[:B]), jnp.asarray(Yall[:B])))
    assert after < before * 0.5
    predict = trainer.make_predict(spec)
    acc = trainer.accuracy(predict, params, jnp.asarray(Xall), jnp.asarray(Yall), 256)
    assert acc > 0.9
