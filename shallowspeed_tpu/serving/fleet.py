"""Serving fleet: N engine replicas in worker processes behind a router.

One ``ServingEngine`` is one process feeding one mesh; the north star's
"heavy traffic from millions of users" needs N of them. ``ServingFleet``
is that layer: it spawns N replica WORKER PROCESSES (``multiprocessing``
spawn context — each worker initializes its own JAX CPU runtime, builds a
``TrainingSession`` from a checkpoint through the PR 6 loader, wraps it
in a ``ServingEngine`` and pre-compiles its whole rung ladder with
``warm_ladder()`` before announcing ready), and fronts them with the
``router.Router``: a bounded fleet queue and least-queue-depth or
power-of-two-choices placement driven by replica HEARTBEATS (worker queue
depth, breaker state, last ``serving_health`` event).

The per-request contract is the engine's, lifted fleet-wide:

- **terminal verdicts, never silence**: every request the fleet admits
  reaches exactly one of ok/dropped/expired/error/unhealthy — across
  replica deaths, breaker trips, drains and scale events. The chaos soak
  (``bench_serving.fleet_chaos_soak``, ``make fleet-smoke``) SIGKILLs a
  replica mid-soak and machine-checks that no admitted id is still
  ``"queued"`` at the end;
- **failover = requeue-at-head, one level up**: a replica that dies (pipe
  EOF / process exit) has its un-acked in-flight requests re-queued at
  the FLEET queue head in original submit order, under the shared
  bounded ``retry.RetryPolicy`` placement budget — exhausted requests
  complete as ``"error"``, exactly the engine's dispatch-recovery shape.
  A replica that trips its breaker (heartbeat ``degraded``) stops
  receiving traffic, and worker-terminal ``error``/``dropped``/
  ``"unhealthy"`` responses are re-placed on healthy replicas while the
  budget lasts — a poisoned replica's failure is another replica's
  request;
- **bitwise parity, machine-checked per response**: with
  ``verify=True`` in the worker config, every ``"ok"`` response is
  re-computed IN THE WORKER with a direct ``session.predict()`` of the
  same rows and compared bitwise before it crosses the pipe — the
  engine's parity contract survives the process hop because it is
  checked before the hop;
- **elasticity rides what exists**: ``scale_up()`` spawns a replica from
  the newest ``checkpoint.find_latest_good`` snapshot (its ladder warmed
  before it takes traffic), ``scale_down()`` drains-and-retires,
  ``watch_reload()`` broadcasts the per-replica hot-reload poll — the
  zero-downtime deploy path. With an ``aot_cache_dir`` in the worker's
  session kwargs (shallowspeed_tpu/aot_cache.py) the warm-up
  deserializes the ladder the first replicas compiled instead of
  recompiling it, so a replacement's measured ``scale_up_s`` drops from
  seconds-of-XLA to the session build + deserialize (every deserialized
  program re-audited before it serves; per-replica ``ready_wall_s`` in
  the summary is the scoreboard);
- **quorum**: the fleet refuses admission (verdict ``"dropped"``, reason
  ``"fleet_degraded"``) while fewer than a majority of its target
  replicas are healthy (``router.quorum``); the serve CLI exits 3 when
  still degraded at exit, mirroring train.py's health-halt code.

Observability: the PARENT emits schema-v7 ``fleet``/``fleet_health``
records (every one tagged ``replica_id``) plus a fleet-wide ``serving``
summary; each WORKER writes its engine's ``request``/``serving_health``/
``reload`` records to its own ``<path>.r{replica_id}`` JSONL shard
(``metrics.replica_shard_path`` — the multihost ``.p*`` convention
reused), with ``replica_id`` as the join key. The report CLI renders the
Fleet section from the merged stream (``report fleet.jsonl*``).

Timing is measured on the parent clock end to end: a fleet request's
latency covers fleet queueing, the pipe hop, worker queueing, dispatch
and any failover re-placements — ``recovery_s`` is replica-loss to the
next served response, ``scale_up_s`` is spawn to ready (ladder warmed).

Clock-domain contract (docs/observability.md § Tracing): every
``FleetRequest`` timestamp and every parent-side span is a PARENT-process
``perf_counter`` value; each WORKER's engine records its own clock's
values into its ``.r{replica_id}`` shard (``clock: "worker"`` on its
trace records). The two domains share no origin — the heartbeat
handshake therefore round-trips ``clock_probe`` messages per worker and
records the best offset estimate WITH its uncertainty (a ``clock_offset``
trace record: ``offset = tw - (t0 + t1)/2``, uncertainty = half the round
trip), which is what lets ``observability.tracing`` place all shards on
one parent timeline when joining a request's cross-process span chain.

Tracing (schema v10): with a metrics recorder attached every admitted
request leaves a cross-process chain — parent-side ``fleet.queue`` /
``route`` / ``failover.requeue`` / terminal ``ack`` spans in the parent's
JSONL, worker-side ``worker.queue``/``pack``/``dispatch``/``verify``
spans in the serving replica's shard — linked by the trace context the
router ships alongside the request and the ``last_span_id`` each response
carries back. A replica SIGKILLed mid-dispatch leaves its partial chain;
the ``failover.requeue`` span links it to the surviving replica's
completion, so the report's Tracing section can attribute the tail
latency a death costs (``make trace-smoke`` gates on zero orphan
chains).

Live telemetry (schema v11, docs/observability.md § Live telemetry &
alerting): the parent owns a fleet-level ``slo.LiveTelemetry`` sensor —
every fleet-terminal verdict, router queue-depth sample and
``fleet_degraded``/``fleet_recovered`` edge feeds tumbling ``rollup``
windows (closed on PARENT-CLOCK timestamps) and the SLO rule set,
whose firing→resolved transitions emit ``alert`` records and call any
attached ``AlertSink`` (ROADMAP item 4's autoscaler hook). Each WORKER
engine runs its own sensor tagged with its ``replica_id`` into its
``.r*`` shard, so ``observability.watch`` tails the whole fleet from
the shard glob and ``rollup.merge_rollup_records`` re-aligns the
per-replica windows through the clock offsets above. ``status()`` is
the live snapshot surface the watch CLI and the autoscaler poll.

The same "many independent programs, dispatched asynchronously from one
host" shape is where the MPMD pipeline direction (arXiv 2412.14374) is
headed; this module's process/IPC plumbing is deliberately generic
(spawn + duplex pipes + heartbeats) so that work can reuse it.
"""

import multiprocessing
import os
import signal
import time

import numpy as np

from shallowspeed_tpu import retry as R
from shallowspeed_tpu.observability import NullMetrics
from shallowspeed_tpu.observability.metrics import replica_shard_path
from shallowspeed_tpu.observability.slo import LiveTelemetry
from shallowspeed_tpu.observability.stats import ThroughputWindow, percentile
from shallowspeed_tpu.observability.tracing import Tracer
from shallowspeed_tpu.serving.router import (
    FleetRequest,
    ReplicaInfo,
    Router,
    quorum,
    routing_skew,
)


class FleetError(RuntimeError):
    """A fleet-level operational failure: a replica failed to start
    (its ``fatal`` message is embedded), or the platform cannot spawn
    worker processes at all."""


# ---------------------------------------------------------------------------
# the worker process
# ---------------------------------------------------------------------------


class _HealthTap:
    """Delegating metrics proxy that remembers the last ``serving_health``
    event name — what the worker's heartbeat reports as its health
    verdict (the breaker flag says "degraded", this says WHY)."""

    def __init__(self, inner):
        self._inner = inner
        self.last_health = None

    def serving_health(self, name, **fields):
        self.last_health = name
        self._inner.serving_health(name, **fields)

    def __getattr__(self, name):
        return getattr(self._inner, name)


def _child_env(session_kwargs):
    """The environment overrides a replica worker must see: CPU platform,
    no TPU tunnel plugin, and enough emulated host devices for the
    replica's own mesh.

    These are staged in the PARENT around ``proc.start()`` — a spawn
    child unpickles its target by importing this module, which pulls the
    package root (and therefore jax) BEFORE any worker code runs, so
    env mutation inside the worker would land after jax already captured
    ``JAX_PLATFORMS``. The child's exec inherits the parent's
    environment at start() time; staging there is the one reliable
    hook. Returns ``{var: value-or-None}`` (None = unset)."""
    devices = (
        int(session_kwargs.get("dp") or 1)
        * int(session_kwargs.get("pp") or 1)
        * int(session_kwargs.get("tp") or 1)
    )
    env = {
        "PALLAS_AXON_POOL_IPS": None,  # never dial the TPU tunnel
        "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS") or "cpu",
    }
    flags = os.environ.get("XLA_FLAGS", "")
    if devices > 1 and "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={max(devices, 8)}"
        ).strip()
    return env


def _pin_worker_backend():
    """Belt to the parent-side env staging's braces: re-pin the already-
    imported jax config onto the CPU platform (the conftest trick — the
    config update works post-import), so a worker stays a CPU replica
    even if a site plugin re-registered itself at interpreter startup."""
    if os.environ.get("JAX_PLATFORMS", "cpu") == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")


def _response_msg(req, fleet_id, engine, parity_ok):
    """One engine-terminal request, serialized for the pipe. The engine's
    breaker state and queue depth piggyback on every response — a
    response is a fresher heartbeat than the heartbeat. ``last_span_id``
    carries the worker's newest span back so the parent's terminal ``ack``
    links into the worker-side chain."""
    return {
        "kind": "response",
        "id": fleet_id,
        "verdict": req.verdict,
        "result": np.asarray(req.result) if req.verdict == "ok" else None,
        "latency_s": req.latency_s,
        "queue_s": req.queue_s,
        "attempts": req.attempts,
        "parity_ok": parity_ok,
        "degraded": engine.degraded,
        "queue_depth": engine.queue_depth,
        "last_span_id": req.last_span_id,
    }


def _heartbeat_msg(engine, tap):
    return {
        "kind": "heartbeat",
        "queue_depth": engine.queue_depth,
        "degraded": engine.degraded,
        "dispatch_seq": engine.dispatch_seq,
        "last_health": tap.last_health,
    }


def _worker_main(conn, config):
    """The replica worker: session + engine + warm ladder, then a serve
    loop multiplexing pipe messages with engine steps. Spawned (never
    forked — a forked JAX runtime is undefined) with ``config``:

    - ``replica_id``; ``session``: ``TrainingSession`` kwargs (checkpoint
      via ``resume=``); ``engine``: ``ServingEngine`` kwargs;
    - ``verify``: re-compute every "ok" response with a direct
      ``session.predict()`` and ship the bitwise verdict (``parity_ok``);
    - ``metrics_path``: this replica's own ``.r{id}`` JSONL shard;
    - ``heartbeat_s``: heartbeat cadence;
    - ``clock_offset_s``: TEST HOOK — shift this worker's engine clock by
      a constant, so the clock-alignment handshake can be proven to
      reconstruct correct cross-process span durations against an
      artificially skewed clock domain (tests/test_tracing.py).

    The worker answers parent ``clock_probe`` messages immediately with
    its engine clock — the round-trip half of the alignment handshake.

    Exit paths: a ``stop``/``drain`` control message, parent death (pipe
    EOF — a fleet worker never outlives its fleet), or a fatal setup
    error (reported as a ``fatal`` message, so the parent can raise it
    with the real cause instead of a bare dead replica)."""
    config = dict(config)
    session_kwargs = dict(config.get("session") or {})
    engine_kwargs = dict(config.get("engine") or {})
    rid = int(config.get("replica_id", 0))
    inner = None
    try:
        _pin_worker_backend()
        from shallowspeed_tpu import faults as F
        from shallowspeed_tpu.api import TrainingSession
        from shallowspeed_tpu.observability import JsonlMetrics
        from shallowspeed_tpu.serving.engine import ServingEngine

        inner = (
            JsonlMetrics(config["metrics_path"])
            if config.get("metrics_path")
            else NullMetrics()
        )
        tap = _HealthTap(inner)
        session = TrainingSession(metrics=inner, **session_kwargs)
        # the worker's clock domain: engine timestamps, trace spans and
        # clock-probe replies all read the SAME clock, so the handshake
        # offset maps every one of them onto the parent timeline (the
        # test hook skews it to prove the alignment recovers it)
        skew = float(config.get("clock_offset_s") or 0.0)
        if skew:
            clock = lambda: time.perf_counter() + skew  # noqa: E731
        else:
            clock = time.perf_counter
        tracer = Tracer(
            inner, process=f"r{rid}", replica_id=rid,
            clock_domain="worker", terminal_ack=False,
        )
        # the worker's sensor tags every rollup/alert record with this
        # replica's id — the join key the shard merge aligns windows by
        engine_kwargs.setdefault("replica_id", rid)
        engine = ServingEngine(
            session, metrics=tap, clock=clock, tracer=tracer,
            **engine_kwargs,
        )
        # pre-compile the whole rung ladder BEFORE announcing ready: a
        # replica that would pay XLA inside its first requests' latency
        # must not take traffic yet (the scale_up contract)
        engine.warm_ladder()
        conn.send(
            {
                "kind": "ready",
                "replica_id": rid,
                "slot_rows": session.slot_rows,
                "ladder": list(session.slot_ladder),
                "max_slots": engine._max_slots,
                "loaded_step": engine_kwargs.get("loaded_step"),
            }
        )
    except Exception as e:  # noqa: BLE001 — ship the real cause, then die
        try:
            conn.send(
                {
                    "kind": "fatal",
                    "replica_id": rid,
                    "error": f"{type(e).__name__}: {e}"[:500],
                }
            )
        except Exception:  # noqa: BLE001 — the pipe to the parent is already dead; the fatal report is best-effort
            pass
        if inner is not None:
            inner.close()
        return

    verify = bool(config.get("verify"))
    hb_s = float(config.get("heartbeat_s", 0.25))
    draining = False
    fleet_ids = {}  # engine request id -> fleet request id

    def send(msg):
        try:
            conn.send(msg)
            return True
        except (BrokenPipeError, OSError):
            return False  # parent gone — nothing left to serve for

    try:
        last_hb = time.perf_counter()
        while True:
            timeout = 0.0 if engine.queue_depth else 0.005
            try:
                has_msg = conn.poll(timeout)
            except OSError:
                return
            while has_msg:
                try:
                    msg = conn.recv()
                except (EOFError, OSError):
                    return
                kind = msg.get("kind")
                if kind == "request":
                    fid = msg["id"]
                    if draining:
                        # the parent stops routing before it drains; a
                        # straggler still gets a verdict, never silence
                        send(
                            {
                                "kind": "response",
                                "id": fid,
                                "verdict": "dropped",
                                "result": None,
                                "latency_s": None,
                                "queue_s": None,
                                "attempts": 0,
                                "parity_ok": None,
                                "degraded": engine.degraded,
                                "queue_depth": engine.queue_depth,
                                "last_span_id": None,
                            }
                        )
                    else:
                        req = engine.submit(
                            msg["x"], deadline_ms=msg.get("deadline_ms"),
                            trace=msg.get("trace"),
                        )
                        if req.verdict == "queued":
                            fleet_ids[req.id] = fid
                        else:  # refused at admission (degraded / shed)
                            if not send(_response_msg(req, fid, engine, None)):
                                return
                elif kind == "clock_probe":
                    # the alignment handshake's worker half: answer NOW
                    # with the engine clock — every poll-loop microsecond
                    # before this reply widens the parent's uncertainty
                    # bound, never skews the estimate past it
                    send(
                        {
                            "kind": "clock_probe_reply",
                            "t0": msg["t0"],
                            "tw": engine.clock(),
                        }
                    )
                elif kind == "reload":
                    try:
                        engine.watch_reload()
                    except ValueError:
                        pass  # no reload_dir configured — a no-op poll
                elif kind == "drain":
                    draining = True
                elif kind == "stop":
                    return
                has_msg = conn.poll(0)
            if engine.queue_depth:
                try:
                    done = engine.step()
                except F.InjectedFault:
                    # injected dispatch-loop death: the queue is intact by
                    # the engine's contract — the worker loop IS the
                    # operator loop, so it simply re-enters
                    done = []
                for r in done:
                    fid = fleet_ids.pop(r.id, None)
                    if fid is None:
                        continue
                    parity = None
                    if verify and r.verdict == "ok":
                        tv0 = engine.clock()
                        parity = bool(
                            np.array_equal(r.result, session.predict(r.x))
                        )
                        # the parity re-predict is the expensive half of
                        # verification — its own span, chained after the
                        # engine's finiteness-gate verify
                        sid = tracer.span(
                            "verify", r.trace_id, tv0, engine.clock(),
                            parent=r.last_span_id, parity=parity,
                        )
                        if sid is not None:
                            r.last_span_id = sid
                    if not send(_response_msg(r, fid, engine, parity)):
                        return
                if not send(_heartbeat_msg(engine, tap)):
                    return
                last_hb = time.perf_counter()
            now = time.perf_counter()
            if now - last_hb >= hb_s:
                if not send(_heartbeat_msg(engine, tap)):
                    return
                last_hb = now
            if draining and not engine.queue_depth and not fleet_ids:
                send({"kind": "drained", "stats": engine.stats()})
                return
    finally:
        inner.close()


def _probe_main(conn):
    """Spawn-capability probe body (must be module-level for spawn)."""
    conn.send("ok")
    conn.close()


_SPAWN_SUPPORTED = None


def fleet_workers_supported(timeout_s=30.0):
    """Can this platform spawn fleet worker processes? (multiprocessing
    spawn context + a live pipe round trip.) Cached; the fleet tests
    skip-with-reason when False — mirroring the multihost collectives
    skip — so tier-1 stays green on constrained runners."""
    global _SPAWN_SUPPORTED
    if _SPAWN_SUPPORTED is None:
        try:
            ctx = multiprocessing.get_context("spawn")
            parent, child = ctx.Pipe()
            proc = ctx.Process(target=_probe_main, args=(child,), daemon=True)
            proc.start()
            child.close()
            ok = parent.poll(timeout_s) and parent.recv() == "ok"
            proc.join(5)
            parent.close()
            _SPAWN_SUPPORTED = bool(ok)
        except Exception:  # noqa: BLE001 — any failure means "cannot spawn"
            _SPAWN_SUPPORTED = False
    return _SPAWN_SUPPORTED


# ---------------------------------------------------------------------------
# the parent
# ---------------------------------------------------------------------------


class ReplicaHandle:
    """Process + pipe + state for one replica, parent-side."""

    def __init__(self, info, proc, conn):
        self.info = info
        self.proc = proc
        self.conn = conn
        self.inflight = {}  # fleet request id -> FleetRequest (un-acked)
        self.dead = False
        self.fatal_error = None
        # clock-alignment handshake state: the best (lowest-uncertainty)
        # round-trip offset estimate so far, when we last probed, and how
        # many probes this replica has answered (bounds the refinement)
        self.clock_offset = None  # {"offset_s", "rtt_s", "uncertainty_s"}
        self.last_probe_t = None
        self.probes_answered = 0

    def send(self, msg):
        if self.dead:
            return False
        try:
            self.conn.send(msg)
            return True
        except (BrokenPipeError, OSError):
            return False

    def poll(self):
        """Drain every buffered message; returns ``(messages, died)``.
        Death shows as pipe EOF (a SIGKILLed worker's buffered messages
        are still delivered first — nothing acked is lost) or as an
        exited process with an empty pipe."""
        msgs = []
        died = False
        try:
            while self.conn.poll(0):
                msgs.append(self.conn.recv())
        except (EOFError, OSError):
            died = True
        if not died and not self.proc.is_alive():
            died = True
        return msgs, died

    def close(self):
        try:
            self.conn.close()
        except OSError:
            pass


class ServingFleet:
    """N replica worker processes behind the router (module docstring).

    ``worker_config``: the per-replica recipe — ``{"session": {...
    TrainingSession kwargs, checkpoint via "resume"}, "engine": {...
    ServingEngine kwargs}, "verify": bool}``; everything must be
    picklable (the spawn context ships it to each worker). A
    ``metrics_path`` base may be given explicitly, else it is derived
    from a ``JsonlMetrics`` parent recorder's path — each replica writes
    ``<base>.r{replica_id}``.

    ``retry`` is the fleet-level PLACEMENT budget per request (int or
    ``retry.RetryPolicy`` — the same shared policy the engine's dispatch
    recovery uses): every placement on a replica consumes one attempt,
    and a request whose replica died (or answered with a re-routable
    ``error``/``dropped``/``unhealthy`` verdict) is re-queued at the
    fleet-queue head while the budget lasts. ``inflight_window`` bounds
    un-acked requests per replica — both the failover blast radius and
    the staleness the placement score can accumulate between heartbeats.

    ``route_stall_timeout_s`` bounds the no-routable-replica wait: with
    every replica degraded (but alive) for that long, queued requests
    complete as ``"error"``/``no_routable_replica`` — ``drain()`` is
    bounded by construction, like the engine's. A fleet with NO live
    replica fails its queue immediately (``fleet_down``).

    ``telemetry_window_s`` / ``knee_rps`` / ``alert_rules`` /
    ``alert_sinks`` configure the fleet-level live-telemetry sensor
    (module docstring). ``alert_rules=None`` builds the default serving
    set (``slo.default_serving_rules`` — its ``fleet_degraded`` event
    rule is the deterministic alerting gate at this level), ``[]``
    disables alerting while keeping the rollup windows; ``knee_rps``
    must come from a measured ``bench_serving`` sweep record.
    """

    def __init__(
        self,
        worker_config,
        n_replicas=2,
        policy="least_queue",
        max_queue=None,
        slo_ms=None,
        retry=2,
        inflight_window=8,
        metrics=None,
        heartbeat_s=0.25,
        route_stall_timeout_s=30.0,
        spawn_timeout_s=300.0,
        seed=0,
        clock=time.perf_counter,
        telemetry_window_s=1.0,
        knee_rps=None,
        alert_rules=None,
        alert_sinks=(),
    ):
        if n_replicas < 1:
            raise ValueError("n_replicas must be >= 1")
        self._worker_config = dict(worker_config or {})
        self._metrics = metrics if metrics is not None else NullMetrics()
        if "metrics_path" not in self._worker_config:
            base = getattr(self._metrics, "path", None)
            if base is not None:
                self._worker_config["metrics_path"] = os.fspath(base)
        self._worker_config.setdefault("heartbeat_s", heartbeat_s)
        self._n_initial = int(n_replicas)
        self._router = Router(policy=policy, max_queue=max_queue, seed=seed)
        self._slo_ms = slo_ms
        if isinstance(retry, R.RetryPolicy):
            self._retry = retry
        else:
            self._retry = R.RetryPolicy(attempts=int(retry), base=0.0, jitter=0)
        if inflight_window < 1:
            raise ValueError("inflight_window must be >= 1")
        self._window = int(inflight_window)
        self._heartbeat_s = heartbeat_s
        self._stall_timeout = route_stall_timeout_s
        self._spawn_timeout = spawn_timeout_s
        self.clock = clock
        self._ctx = multiprocessing.get_context("spawn")
        self._replicas = {}  # replica_id -> ReplicaHandle
        self._target = 0  # intended fleet size (deaths do NOT reduce it)
        self._next_replica_id = 0
        self._next_request_id = 0
        self._slot_rows = None
        self._max_slots = None
        self._degraded = False
        self._stall_t = None
        self._impair_t = None  # replica lost / quorum lost, awaiting an ok
        # request tracing (schema v10): the parent mints every trace id,
        # emits the parent-side spans (fleet.queue/route/failover.requeue/
        # terminal ack) and records each worker's clock-offset estimate
        self._tracer = Tracer(self._metrics, process="f")
        self._probe_every_s = 2.0  # re-probe cadence piggybacking heartbeats
        # live telemetry (module docstring): the fleet-level sensor.
        # Windows close on parent-clock timestamps; worker engines run
        # their own replica-tagged sensors into the .r* shards. No
        # replica_id here — the parent's records are the fleet-wide view.
        self._telemetry = LiveTelemetry(
            "fleet",
            metrics=self._metrics,
            window_s=telemetry_window_s,
            rules=alert_rules,
            sinks=alert_sinks,
            slo_ms=slo_ms,
            knee_rps=knee_rps,
        )
        # completions collected OUTSIDE step() (wait_ready pumps the
        # pipes too) are stashed and returned by the next step() — a
        # completed request must always reach a caller's hands
        self._stash_done = []
        # growth replicas spawned without blocking join the quorum
        # denominator only when READY: growing a healthy fleet must not
        # degrade it for the length of an XLA warm-up
        self._deferred_target = set()
        # accounting (the engine's scalar-samples discipline: latencies
        # only, payloads stay with the caller); the serving window folds
        # through the same shared helper the engine uses
        self._samples = []  # (latency_s, queue_s, deadline_ms)
        self._serve_window = ThroughputWindow()
        self._dropped = 0
        self._expired = 0
        self._errors = 0
        self._unhealthy = 0
        self._reroutes = 0
        self._failovers = 0
        self._failover_requeued = 0
        self._failover_exhausted = 0
        self._scale_ups = 0
        self._scale_downs = 0
        self._replaced = 0  # deaths answered by a replacement scale-up
        self._replicas_dead = 0
        self._replicas_retired = 0
        self._last_scale_up_s = None
        self._recovery_s = None
        self._depth_max = 0
        self._depth_sum = 0.0
        self._depth_n = 0
        self._parity_mismatches = 0
        # admission gate (serving/autoscaler.py backpressure): consulted
        # per submit AFTER the degraded check; a reason string sheds the
        # request as dropped with that reason
        self._admission_gate = None
        self._gate_dropped = 0

    # -- lifecycle -----------------------------------------------------------

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.stop()
        return False

    def start(self, wait_ready=True):
        """Spawn the initial replicas; with ``wait_ready`` (default),
        block until every one has warmed its ladder and announced ready
        (or raise ``FleetError`` with the first fatal cause)."""
        for _ in range(self._n_initial):
            self._spawn_replica()
        if wait_ready:
            self.wait_ready()
        return self

    def _spawn_replica(self, checkpoint=None, count_target=True):
        rid = self._next_replica_id
        self._next_replica_id += 1
        if count_target:
            self._target += 1
        config = dict(self._worker_config)
        config["replica_id"] = rid
        config["session"] = dict(config.get("session") or {})
        config["engine"] = dict(config.get("engine") or {})
        if checkpoint is not None:
            config["session"]["resume"] = os.fspath(checkpoint)
        # a replica restored from a step snapshot seeds its watcher's
        # freshness floor, so a watch_reload() broadcast picks up only
        # STRICTLY newer weights — not the snapshot it already serves
        resume = config["session"].get("resume")
        if resume and config["engine"].get("loaded_step") is None:
            from shallowspeed_tpu.checkpoint import STEP_CHECKPOINT_RE

            m = STEP_CHECKPOINT_RE.match(os.path.basename(os.fspath(resume)))
            if m:
                config["engine"]["loaded_step"] = int(m.group(1))
        if config.get("metrics_path"):
            config["metrics_path"] = replica_shard_path(
                self._worker_config["metrics_path"], rid
            )
        parent_conn, child_conn = self._ctx.Pipe()
        proc = self._ctx.Process(
            target=_worker_main, args=(child_conn, config), daemon=True
        )
        # stage the child's environment around start(): the spawn child
        # inherits the parent env at exec, and imports jax (via the
        # package root) while unpickling the target — before any worker
        # code could set these itself (_child_env docstring)
        overrides = _child_env(config["session"])
        saved = {k: os.environ.get(k) for k in overrides}
        try:
            for k, v in overrides.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
            proc.start()
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        # the parent MUST close its copy of the child end, or a dead
        # worker's pipe never reads as EOF and failover never triggers
        child_conn.close()
        info = ReplicaInfo(rid, spawn_t=self.clock())
        self._replicas[rid] = ReplicaHandle(info, proc, parent_conn)
        self._metrics.fleet_health(
            "replica_spawned",
            replica_id=rid,
            checkpoint=None if checkpoint is None else str(checkpoint),
        )
        return rid

    def wait_ready(self, timeout_s=None):
        """Pump until no replica is still starting; raise ``FleetError``
        on a fatal start or on timeout."""
        deadline = self.clock() + (
            timeout_s if timeout_s is not None else self._spawn_timeout
        )
        while any(
            h.info.state == "starting" and not h.dead
            for h in self._replicas.values()
        ):
            # responses arriving during the wait are stashed for the next
            # step() — waiting on a warm-up must not swallow completions
            self._pump_messages(self._stash_done)
            starting_dead = [
                h
                for h in self._replicas.values()
                if h.dead and h.info.state == "dead" and h.info.ready_t is None
            ]
            if starting_dead:
                h = starting_dead[0]
                raise FleetError(
                    f"replica {h.info.replica_id} failed to start: "
                    f"{h.fatal_error or 'process died before ready'}"
                )
            if self.clock() > deadline:
                raise FleetError(
                    f"fleet start timed out after {self._spawn_timeout:g}s "
                    f"({self.n_ready}/{self._target} replicas ready)"
                )
            time.sleep(0.01)
        self._update_degraded()

    def stop(self):
        """Terminate every worker (best effort: polite stop, then
        terminate, then kill) and close the pipes. Queued/in-flight
        requests are NOT completed — callers drain first; stop() is the
        shutdown path, not the graceful one."""
        for h in self._replicas.values():
            if h.proc.is_alive():
                h.send({"kind": "stop"})
        for h in self._replicas.values():
            if h.proc.is_alive():
                h.proc.join(timeout=5)
            if h.proc.is_alive():
                h.proc.terminate()
                h.proc.join(timeout=5)
            if h.proc.is_alive():
                h.proc.kill()
                h.proc.join(timeout=5)
            h.close()

    # -- introspection -------------------------------------------------------

    @property
    def replicas(self):
        """Read-only view: replica_id -> ReplicaInfo."""
        return {rid: h.info for rid, h in self._replicas.items()}

    @property
    def n_ready(self):
        return sum(1 for h in self._replicas.values() if h.info.routable())

    @property
    def n_alive(self):
        return sum(1 for h in self._replicas.values() if h.info.alive)

    @property
    def target_replicas(self):
        return self._target

    @property
    def degraded(self):
        """True while fewer than a quorum of the target replicas are
        healthy — admission refused (already-admitted work still
        drains)."""
        return self._degraded

    @property
    def inflight(self):
        return sum(len(h.inflight) for h in self._replicas.values())

    @property
    def queue_depth(self):
        """Requests the fleet still owes a verdict: fleet-queued plus
        un-acked in-flight. (The loadgen drivers' loop condition — a
        fleet with responses still on the wire has not drained.)"""
        return len(self._router.queue) + self.inflight

    @property
    def parity_mismatches(self):
        """Worker-reported bitwise-parity failures among "ok" responses
        (0 is the contract; needs ``verify`` in the worker config)."""
        return self._parity_mismatches

    def pid(self, replica_id):
        return self._replicas[replica_id].proc.pid

    def sigkill_replica(self, replica_id):
        """Chaos harness leg: SIGKILL one replica's process — the honest
        preemption (nothing flushes, no atexit). The fleet finds out the
        way it would in production: the pipe goes EOF and failover runs.
        Recorded so the soak's record shows the kill was injected, not
        organic."""
        h = self._replicas[replica_id]
        self._metrics.fleet_health(
            "replica_sigkill", replica_id=replica_id, pid=h.proc.pid
        )
        os.kill(h.proc.pid, signal.SIGKILL)

    # -- admission -----------------------------------------------------------

    def set_admission_gate(self, gate):
        """Install (or clear, with ``None``) the admission gate: a
        ``gate(fleet) -> reason|None`` callable consulted on every
        ``submit`` after the degraded check. A truthy reason sheds the
        request immediately — ``dropped`` with that reason — which is the
        autoscaler's warm-up backpressure hook (``serving/autoscaler.py``):
        while replacement replicas warm, an unbounded backlog would burn
        every queued deadline past the analytical latency floor, so the
        policy sheds at admission instead and the refusals are scored
        honestly as violations by the capacity scoreboard."""
        self._admission_gate = gate

    @property
    def gate_dropped(self):
        """Requests shed by the admission gate (backpressure refusals)."""
        return self._gate_dropped

    def submit(self, x, deadline_ms=None, arrival_t=None):
        """Admit one request of ``(rows, in_dim)`` inputs; returns its
        ``FleetRequest`` (terminal immediately when refused).
        ``arrival_t`` backdates the enqueue timestamp — the open-loop
        coordinated-omission correction, same contract as the engine's
        ``submit``."""
        x = np.asarray(x, np.float32)
        if x.ndim != 2 or x.shape[0] < 1:
            raise ValueError(f"request must be (rows >= 1, in_dim), got {x.shape}")
        if (
            self._slot_rows is not None
            and self._max_slots is not None
            and -(-x.shape[0] // self._slot_rows) > self._max_slots
        ):
            raise ValueError(
                f"request of {x.shape[0]} rows exceeds one replica dispatch "
                f"({self._max_slots} slots x {self._slot_rows} rows); split it"
            )
        t = self.clock() if arrival_t is None else float(arrival_t)
        req = FleetRequest(self._next_request_id, x, deadline_ms, t)
        self._next_request_id += 1
        if self._tracer.enabled:
            req.trace_id = self._tracer.new_trace(req.id)
        self._serve_window.note_enqueue(t)
        if self._degraded:
            self._complete(req, "dropped", reason="fleet_degraded")
            return req
        if self._admission_gate is not None:
            reason = self._admission_gate(self)
            if reason:
                self._gate_dropped += 1
                self._complete(req, "dropped", reason=str(reason))
                return req
        if not self._router.admit(req):
            self._complete(req, "dropped", reason="fleet_queue_full")
            return req
        req.admitted = True
        self._telemetry.note_admit(t)
        self._record_depth(t)
        return req

    # -- the pump ------------------------------------------------------------

    def step(self):
        """One fleet pump: collect replica messages (responses,
        heartbeats, deaths -> failover), refresh the quorum verdict,
        route the queue's head onto the least-loaded routable replicas,
        and bound the stall paths. Returns the fleet requests that
        reached a terminal verdict during this pump (plus any stashed by
        an intervening ``wait_ready``)."""
        done = self._stash_done
        self._stash_done = []
        progressed = self._pump_messages(done)
        self._update_degraded()
        progressed = self._route(done) or progressed
        self._reap_stalls(done)
        if done:
            progressed = True
        if not progressed and self.queue_depth:
            time.sleep(0.002)  # idle tick: don't hot-spin the pipe polls
        return done

    def drain(self):
        """Pump until every admitted request has its terminal verdict.
        Bounded: failover budgets, the fleet-down sweep and the
        route-stall timeout guarantee progress even with every replica
        dead or degraded."""
        done = []
        while self.queue_depth or self._stash_done:
            done.extend(self.step())
        return done

    def _pump_messages(self, done):
        progressed = False
        for h in list(self._replicas.values()):
            if h.dead:
                continue
            msgs, died = h.poll()
            for msg in msgs:
                progressed = True
                self._handle_message(h, msg, done)
            if died and not h.dead:
                self._on_replica_dead(h, done)
                progressed = True
        return progressed

    def _handle_message(self, h, msg, done):
        info = h.info
        kind = msg.get("kind")
        if kind == "ready":
            info.state = "ready" if info.state == "starting" else info.state
            if info.replica_id in self._deferred_target:
                # a non-blocking GROWTH replica joins the quorum
                # denominator now that it can actually serve
                self._deferred_target.discard(info.replica_id)
                self._target += 1
            info.ready_t = self.clock()
            info.loaded_step = msg.get("loaded_step")
            if self._slot_rows is None:
                self._slot_rows = int(msg["slot_rows"])
                self._max_slots = int(msg["max_slots"])
            wall = (
                info.ready_t - info.spawn_t if info.spawn_t is not None else None
            )
            if self._scale_ups and self._last_scale_up_s is None:
                self._last_scale_up_s = wall
            self._metrics.fleet_health(
                "replica_ready",
                replica_id=info.replica_id,
                wall_s=wall,
                loaded_step=info.loaded_step,
            )
            # the alignment handshake: a burst of probes right at ready
            # (the worker sits in its message loop, so all three answer
            # back to back with tight round trips — the min-uncertainty
            # fold keeps the best)
            self._probe_clock(h, burst=3)
        elif kind == "clock_probe_reply":
            self._note_clock_reply(h, msg)
        elif kind == "heartbeat":
            was_degraded = info.degraded
            info.queue_depth = int(msg.get("queue_depth", 0))
            info.degraded = bool(msg.get("degraded"))
            info.last_health = msg.get("last_health")
            info.last_heartbeat_t = self.clock()
            if info.degraded and not was_degraded:
                if self._impair_t is None:
                    self._impair_t = self.clock()
                self._metrics.fleet_health(
                    "replica_degraded",
                    replica_id=info.replica_id,
                    last_health=info.last_health,
                )
                self._telemetry.note_health(
                    self.clock(), "replica_degraded",
                    replica_id=info.replica_id,
                )
            elif was_degraded and not info.degraded:
                self._metrics.fleet_health(
                    "replica_recovered", replica_id=info.replica_id
                )
                self._telemetry.note_health(
                    self.clock(), "replica_recovered",
                    replica_id=info.replica_id,
                )
            # keep the clock estimate fresh: one probe per heartbeat
            # window, piggybacking the traffic that already flows
            if self._tracer.enabled and (
                h.last_probe_t is None
                or self.clock() - h.last_probe_t >= self._probe_every_s
            ):
                self._probe_clock(h)
        elif kind == "response":
            req = h.inflight.pop(msg["id"], None)
            if req is None:
                return
            info.inflight = len(h.inflight)
            info.degraded = bool(msg.get("degraded", info.degraded))
            info.queue_depth = int(msg.get("queue_depth", info.queue_depth))
            verdict = msg["verdict"]
            info.note_verdict(verdict)
            req.worker_latency_s = msg.get("latency_s")
            if msg.get("last_span_id") is not None:
                # the worker's chain tail: the terminal ack (or, on a
                # re-route, the NEXT route span) parents to it, so the
                # failed attempt's spans stay linked into the chain
                req.trace_tail = msg["last_span_id"]
            if verdict == "ok":
                req.result = msg.get("result")
                req.parity_ok = msg.get("parity_ok")
                if req.parity_ok is False:
                    self._parity_mismatches += 1
                self._complete(req, "ok")
                done.append(req)
            elif verdict == "expired":
                # a missed deadline is missed everywhere — never re-routed
                self._complete(req, "expired", reason="replica_shed")
                done.append(req)
            else:  # error / dropped / unhealthy — re-routable failures
                if self._retry.exhausted(req.attempts):
                    self._complete(req, verdict, reason="retry_exhausted")
                    done.append(req)
                else:
                    req.replica_id = None
                    self._router.requeue_head([req])
                    self._reroutes += 1
                    self._metrics.fleet_health(
                        "reroute",
                        replica_id=info.replica_id,
                        request_id=req.id,
                        worker_verdict=verdict,
                        attempts=req.attempts,
                    )
        elif kind == "drained":
            info.state = "retired"
            self._replicas_retired += 1
            h.proc.join(timeout=10)
            h.close()
            h.dead = True
            self._metrics.fleet_health(
                "replica_retired",
                replica_id=info.replica_id,
                stats=msg.get("stats"),
            )
        elif kind == "fatal":
            h.fatal_error = msg.get("error")

    def _probe_clock(self, h, burst=1):
        """Send ``burst`` clock probes to one worker (module docstring:
        the round-trip offset handshake). Replies fold through
        ``_note_clock_reply``; probes on a broken pipe are dropped — the
        death path owns that replica now. A metrics-disabled fleet sends
        none: an estimate that can never be recorded is wasted IPC."""
        if not self._tracer.enabled:
            return
        for _ in range(burst):
            if not h.send({"kind": "clock_probe", "t0": self.clock()}):
                return
        h.last_probe_t = self.clock()

    # refinement bounds: chase a sub-millisecond estimate with immediate
    # follow-up probes (the worker answers from inside its message loop
    # and the parent stamps t1 in the very pump that reads the reply, so
    # chained round trips tighten fast), but never more than a fixed
    # probe budget per replica — alignment must stay background noise
    _PROBE_TARGET_UNCERTAINTY_S = 0.0005
    _PROBE_BUDGET = 24

    def _note_clock_reply(self, h, msg):
        """One probe's round trip: offset = tw - (t0 + t1)/2, uncertainty
        = rtt/2 (the true offset provably lies inside the bound — the
        reply can sit anywhere between the two parent timestamps). Keep
        and record only IMPROVED estimates, so the reader's last-wins
        fold always holds the best, and the JSONL stays bounded; while
        the bound is still loose (parent pump lag dominates the first
        round trips), chase it with an immediate follow-up probe."""
        t1 = self.clock()
        t0 = float(msg["t0"])
        rtt = t1 - t0
        est = {
            "offset_s": float(msg["tw"]) - 0.5 * (t0 + t1),
            "rtt_s": rtt,
            "uncertainty_s": 0.5 * rtt,
        }
        h.probes_answered += 1
        best = h.clock_offset
        if best is None or est["uncertainty_s"] < best["uncertainty_s"]:
            h.clock_offset = est
            self._tracer.clock_offset(
                replica_id=h.info.replica_id,
                offset_s=est["offset_s"],
                rtt_s=est["rtt_s"],
                uncertainty_s=est["uncertainty_s"],
            )
        if (
            h.clock_offset["uncertainty_s"] > self._PROBE_TARGET_UNCERTAINTY_S
            and h.probes_answered < self._PROBE_BUDGET
        ):
            self._probe_clock(h)

    def _on_replica_dead(self, h, done):
        """Death -> failover: the dead replica's un-acked in-flight
        requests re-enter the fleet queue HEAD in original submit order
        under the placement budget; exhausted ones complete as "error".
        Nothing it acked is affected (acked = a response we already
        processed), and nothing vanishes as "queued"."""
        info = h.info
        h.dead = True
        was_working = info.state in ("ready", "draining")
        # a growth replica that died before ready never joined the
        # quorum denominator — nothing to count it against
        self._deferred_target.discard(info.replica_id)
        info.state = "dead"
        self._replicas_dead += 1
        h.proc.join(timeout=5)
        h.close()
        inflight = sorted(h.inflight.values(), key=lambda r: r.id)
        h.inflight.clear()
        info.inflight = 0
        self._metrics.fleet_health(
            "replica_dead",
            replica_id=info.replica_id,
            inflight=len(inflight),
            error=h.fatal_error,
        )
        self._telemetry.note_health(
            self.clock(), "replica_dead", replica_id=info.replica_id
        )
        if was_working and self._impair_t is None:
            self._impair_t = self.clock()
        if not inflight:
            return
        self._failovers += 1
        requeue = []
        t_detect = self.clock()
        for req in inflight:
            req.replica_id = None
            if req.trace_id is not None:
                # the failover.requeue span links the dead replica's
                # partial chain (its tail is this request's last route
                # span — or the worker's last shipped span) to whatever
                # serves the request next
                req.trace_tail = self._tracer.span(
                    "failover.requeue", req.trace_id, t_detect, t_detect,
                    parent=req.trace_tail,
                    from_replica=info.replica_id,
                    requeued=not self._retry.exhausted(req.attempts),
                ) or req.trace_tail
            if self._retry.exhausted(req.attempts):
                self._failover_exhausted += 1
                self._complete(req, "error", reason="replica_died")
                done.append(req)
            else:
                requeue.append(req)
        self._router.requeue_head(requeue)
        self._failover_requeued += len(requeue)
        self._metrics.fleet_health(
            "failover",
            replica_id=info.replica_id,
            requeued=len(requeue),
            exhausted=len(inflight) - len(requeue),
        )
        self._metrics.flush()

    def _update_degraded(self):
        healthy = self.n_ready
        degraded_now = healthy < quorum(self._target)
        if degraded_now and not self._degraded:
            self._degraded = True
            if self._impair_t is None:
                self._impair_t = self.clock()
            self._metrics.fleet_health(
                "fleet_degraded",
                replica_id=None,
                healthy=healthy,
                target=self._target,
                quorum=quorum(self._target),
            )
            self._telemetry.note_health(
                self.clock(), "fleet_degraded",
                healthy=healthy, target=self._target,
            )
            self._metrics.flush()
        elif not degraded_now and self._degraded:
            self._degraded = False
            self._metrics.fleet_health(
                "fleet_recovered",
                replica_id=None,
                healthy=healthy,
                target=self._target,
            )
            self._telemetry.note_health(
                self.clock(), "fleet_recovered",
                healthy=healthy, target=self._target,
            )

    def _route(self, done):
        routed_any = False
        while self._router.queue:
            req = self._router.queue[0]
            now = self.clock()
            remaining = req.remaining_deadline_ms(now)
            if remaining is not None and remaining <= 0:
                # fleet-level deadline shed: the queue wait already spent
                # the budget — don't burn a pipe hop on a hopeless request
                self._router.queue.popleft()
                self._complete(req, "expired", reason="fleet_deadline")
                done.append(req)
                continue
            candidates = [
                h.info
                for h in self._replicas.values()
                if not h.dead and h.info.inflight < self._window
            ]
            target = self._router.place(candidates)
            if target is None:
                break
            self._router.queue.popleft()
            h = self._replicas[target.replica_id]
            req.attempts += 1
            req.route_t = now
            req.replica_id = target.replica_id
            req.replicas_tried.append(target.replica_id)
            trace_ctx = None
            if req.trace_id is not None:
                if req.trace_root is None:
                    # the chain root: fleet admission -> first placement
                    req.trace_root = self._tracer.span(
                        "fleet.queue", req.trace_id, req.enqueue_t, now,
                        parent=None,
                    )
                    req.trace_tail = req.trace_root
                # the route span closes BEFORE the pipe write; the
                # transit to the worker's admission lands in the gap the
                # reader charges to this phase
                route_span = self._tracer.span(
                    "route", req.trace_id, now, self.clock(),
                    parent=req.trace_tail,
                    to_replica=target.replica_id, attempt=req.attempts,
                )
                if route_span is not None:
                    req.trace_tail = route_span
                    trace_ctx = {"trace_id": req.trace_id,
                                 "parent": route_span}
            if not h.send(
                {
                    "kind": "request",
                    "id": req.id,
                    "x": req.x,
                    "deadline_ms": remaining,
                    "trace": trace_ctx,
                }
            ):
                # pipe broke mid-send: put it back (the attempt was spent
                # honestly — the budget bounds placements, not successes)
                # and let the next pump run the death path
                self._router.requeue_head([req])
                break
            h.inflight[req.id] = req
            target.inflight = len(h.inflight)
            target.routed += 1
            routed_any = True
        if routed_any:
            self._record_depth(self.clock())
        return routed_any

    def _reap_stalls(self, done):
        """The bounded-drain guarantees: a fleet with no live replica
        fails its queue NOW (``fleet_down``); a fleet whose replicas are
        all alive-but-unroutable (degraded, draining) for longer than the
        stall timeout fails it then (``no_routable_replica``). Either
        way every admitted request still terminates."""
        if not self._router.queue:
            self._stall_t = None
            return
        if self.n_alive == 0:
            while self._router.queue:
                req = self._router.queue.popleft()
                self._complete(req, "error", reason="fleet_down")
                done.append(req)
            self._stall_t = None
            return
        can_route = any(
            h.info.routable() or h.info.state == "starting"
            for h in self._replicas.values()
        )
        if can_route:
            self._stall_t = None
            return
        now = self.clock()
        if self._stall_t is None:
            self._stall_t = now
        elif now - self._stall_t > self._stall_timeout:
            while self._router.queue:
                req = self._router.queue.popleft()
                self._complete(req, "error", reason="no_routable_replica")
                done.append(req)
            self._stall_t = None

    # -- elasticity ----------------------------------------------------------

    def scale_up(self, checkpoint=None, wait_ready=True):
        """Add one replica. Weights: ``checkpoint`` if given, else the
        newest verifying snapshot in the worker config's ``reload_dir``
        (``checkpoint.find_latest_good`` — the same discovery the hot
        reload uses), else the base config's own checkpoint. The replica
        warms its full ladder before announcing ready, and takes traffic
        only then — ``wait_ready=False`` keeps serving while it warms
        (the chaos soak's recovery path).

        A scale-up while dead replicas are unreplaced is a REPLACEMENT:
        the fleet target (the quorum denominator) stays put — a
        replacement must never raise the healthy-replica bar while it
        warms, it exists to get back UNDER it. With no deaths
        outstanding it is growth: target += 1 — counted at READY when
        ``wait_ready=False``, so growing a healthy fleet cannot flip it
        degraded for the length of the warm-up either."""
        replacement = self._replicas_dead > self._replaced
        if checkpoint is None:
            reload_dir = (self._worker_config.get("engine") or {}).get(
                "reload_dir"
            )
            if reload_dir is not None:
                from shallowspeed_tpu.checkpoint import find_latest_good

                found, _meta, _skipped = find_latest_good(reload_dir)
                if found is not None:
                    checkpoint = found
        rid = self._spawn_replica(
            checkpoint=checkpoint,
            count_target=not replacement and wait_ready,
        )
        if replacement:
            self._replaced += 1
        elif not wait_ready:
            self._deferred_target.add(rid)
        self._scale_ups += 1
        self._last_scale_up_s = None  # measured when this replica readies
        self._metrics.fleet_health(
            "scale_up",
            replica_id=rid,
            checkpoint=None if checkpoint is None else str(checkpoint),
            replacement=replacement,
            target=self._target,
        )
        if wait_ready:
            self.wait_ready()
        return rid

    def scale_down(self, replica_id=None):
        """Drain-and-retire one replica (default: the newest routable
        one). It stops receiving traffic immediately, serves out its
        internal queue, reports its engine stats in the ``drained``
        message, and exits; the fleet's target shrinks with it."""
        if replica_id is None:
            ready = [
                h.info.replica_id
                for h in self._replicas.values()
                if h.info.routable()
            ]
            if not ready:
                raise FleetError("no routable replica to scale down")
            replica_id = max(ready)
        h = self._replicas[replica_id]
        if not h.info.alive:
            raise FleetError(f"replica {replica_id} is not alive")
        h.info.state = "draining"
        self._target -= 1
        self._scale_downs += 1
        h.send({"kind": "drain"})
        self._metrics.fleet_health(
            "scale_down", replica_id=replica_id, target=self._target
        )
        return replica_id

    def watch_reload(self):
        """Broadcast the checkpoint-dir watcher poll to every live
        replica — the zero-downtime deploy path, one level up: each
        replica hot-swaps between its own dispatches, traffic keeps
        flowing through the others meanwhile."""
        polled = []
        for h in self._replicas.values():
            if h.info.alive and h.send({"kind": "reload"}):
                polled.append(h.info.replica_id)
        self._metrics.fleet_health("reload_broadcast", replica_id=None,
                                   replicas=polled)
        return polled

    # -- accounting ----------------------------------------------------------

    def _complete(self, req, verdict, reason=None):
        t = self.clock()
        req.verdict = verdict
        req.complete_t = t
        req.reason = reason
        self._trace_ack(req, t, reason)
        # one telemetry sample per fleet-terminal verdict — every path
        # (ok, shed, drop, failover-exhausted) crosses this choke point
        self._telemetry.note_request(
            t, verdict, latency_s=req.latency_s, queue_s=req.queue_s
        )
        if verdict == "ok":
            self._samples.append((req.latency_s, req.queue_s, req.deadline_ms))
            self._serve_window.note_complete(t)
            if self._impair_t is not None:
                # recovery: replica lost (or quorum lost) -> next served
                # response — the fleet mirror of the engine's
                # breaker-open -> first-ok measurement
                self._recovery_s = t - self._impair_t
                self._impair_t = None
        elif verdict == "dropped":
            self._dropped += 1
        elif verdict == "expired":
            self._expired += 1
        elif verdict == "error":
            self._errors += 1
        elif verdict == "unhealthy":
            self._unhealthy += 1
        if verdict != "ok":
            # fleet-terminal failures never reached a worker's recorder
            # (or were decided here, one level above it) — record them so
            # the merged stream holds every fleet-level verdict exactly
            # once; "ok" and worker-terminal verdicts live in the .r
            # shards
            self._metrics.request(
                verdict,
                id=req.id,
                rows=req.rows,
                replica_id=req.replica_id,
                enqueue_ts=req.enqueue_t,
                complete_ts=req.complete_t,
                latency_s=req.latency_s,
                deadline_ms=req.deadline_ms,
                attempts=req.attempts,
                reason=reason,
                trace_id=req.trace_id,
            )

    def _trace_ack(self, req, t, reason=None):
        """The one terminal span per fleet request. A request that was
        admitted but never routed (fleet_down, no_routable_replica,
        fleet-deadline shed) still gets its fleet.queue root here, so its
        chain tells the full story: it waited, then the fleet decided."""
        if req.trace_id is None:
            return
        if req.trace_root is None and req.admitted:
            req.trace_root = self._tracer.span(
                "fleet.queue", req.trace_id, req.enqueue_t, t, parent=None,
            )
            req.trace_tail = req.trace_root
        self._tracer.span(
            "ack", req.trace_id, t, t,
            parent=req.trace_tail or req.trace_root,
            terminal=True, verdict=req.verdict,
            deadline_ms=req.deadline_ms, reason=reason,
            replica_id_served=req.replica_id,
        )

    def _record_depth(self, t):
        depth = len(self._router.queue)
        self._depth_max = max(self._depth_max, depth)
        self._depth_sum += depth
        self._depth_n += 1
        self._metrics.gauge("fleet.queue_depth", depth)
        self._telemetry.note_queue_depth(t, depth)

    def status(self):
        """The LIVE snapshot surface (module docstring): operational
        state + per-replica heartbeat view + the current/last rollup
        window + active alerts — cheap, JSON-able, callable
        mid-traffic (everything here is parent-process state; no pipe
        round trips). The fleet mirror of ``ServingEngine.status()``:
        what ``observability.watch`` renders and what ROADMAP item 4's
        autoscaler polls between ``AlertSink`` edges."""
        infos = [h.info for h in self._replicas.values()]
        return {
            "queue_depth": len(self._router.queue),
            "inflight": self.inflight,
            "degraded": self._degraded,
            "replicas_target": self._target,
            "replicas_ready": self.n_ready,
            "replicas_dead": self._replicas_dead,
            "gate_dropped": self._gate_dropped,
            "per_replica": {
                i.replica_id: {
                    "state": i.state,
                    "queue_depth": i.queue_depth,
                    "degraded": i.degraded,
                    "inflight": i.inflight,
                    "last_health": i.last_health,
                }
                for i in infos
            },
            "alerts_active": self._telemetry.evaluator.active(),
            "telemetry": self._telemetry.snapshot(),
        }

    def stats(self):
        """Fleet-wide aggregate: the engine's summary fields measured on
        the parent clock, plus the fleet story — routing counts + skew,
        failover/reroute/scale accounting, per-replica snapshots."""
        lats = [lat for lat, _, _ in self._samples]
        queues = [q for _, q, _ in self._samples if q is not None]
        slo_flags = []
        for lat, _, dl in self._samples:
            bound = dl if dl is not None else self._slo_ms
            slo_flags.append(
                None if bound is None or lat is None else lat <= bound / 1000.0
            )
        met = sum(1 for ok in slo_flags if ok)
        ok_n = len(self._samples)
        terminal = (
            ok_n + self._dropped + self._expired + self._errors
            + self._unhealthy
        )
        window = self._serve_window.window_s if self._samples else None
        infos = [h.info for h in self._replicas.values()]
        routing = {i.replica_id: i.routed for i in infos}
        return {
            "completed": ok_n,
            "dropped": self._dropped,
            "expired": self._expired,
            "errors": self._errors,
            "unhealthy": self._unhealthy,
            "availability": (ok_n / terminal) if terminal else None,
            "parity_mismatches": self._parity_mismatches,
            "reroutes": self._reroutes,
            "failovers": self._failovers,
            "failover_requeued": self._failover_requeued,
            "failover_exhausted": self._failover_exhausted,
            "replicas_target": self._target,
            "replicas_started": self._next_replica_id,
            "replicas_ready": self.n_ready,
            "replicas_dead": self._replicas_dead,
            "replicas_retired": self._replicas_retired,
            "scale_ups": self._scale_ups,
            "scale_downs": self._scale_downs,
            "scale_up_s": self._last_scale_up_s,
            "degraded": self._degraded,
            "recovery_s": self._recovery_s,
            "routing": routing,
            "routing_skew": routing_skew(routing.values()),
            "per_replica": {i.replica_id: i.snapshot() for i in infos},
            "p50_latency_s": percentile(lats, 50),
            "p99_latency_s": percentile(lats, 99),
            "max_latency_s": max(lats) if lats else None,
            "mean_queue_s": (sum(queues) / len(queues)) if queues else None,
            "window_s": window,
            "achieved_rps": (ok_n / window) if window else None,
            "goodput_rps": (
                met / window
                if window and any(ok is not None for ok in slo_flags)
                else None
            ),
            "slo_ms": self._slo_ms,
            "slo_met": met if any(ok is not None for ok in slo_flags) else None,
            "queue_depth_max": self._depth_max,
            "queue_depth_mean": (
                self._depth_sum / self._depth_n if self._depth_n else 0.0
            ),
        }

    def record_summary(self, offered_rps=None):
        """Emit (and return) the fleet's evidence pair: the schema-v7
        ``fleet`` summary (per-replica detail, routing skew, failover +
        scale accounting) plus a fleet-wide ``serving`` summary so the
        report's Serving section reads the fleet like one big engine.
        The live-telemetry window still open at summary time is flushed
        first, so the trailing partial ``rollup`` record lands before
        the summary it feeds."""
        self._telemetry.flush()
        rec = self.stats()
        rec["offered_rps"] = offered_rps
        self._metrics.fleet("summary", **rec)
        serving_fields = {
            k: rec.get(k)
            for k in (
                "completed", "dropped", "expired", "errors", "unhealthy",
                "availability", "p50_latency_s", "p99_latency_s",
                "max_latency_s", "mean_queue_s", "window_s", "achieved_rps",
                "goodput_rps", "slo_ms", "slo_met", "queue_depth_max",
                "queue_depth_mean", "offered_rps", "degraded", "recovery_s",
            )
        }
        self._metrics.serving("fleet", **serving_fields)
        return rec
