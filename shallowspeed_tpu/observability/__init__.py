"""Training telemetry: structured metrics, profiling spans, trace analysis.

The framework's north star is "as fast as the hardware allows" — which is
unclaimable without instrumentation. This package is the single home for
everything that *observes* a run, so every perf PR can ship a recomputable
evidence trail instead of prose:

- ``metrics``      the recording surface: ``MetricsRecorder`` (in-memory
                   counters / gauges / timers / per-step histograms),
                   ``JsonlMetrics`` (the versioned JSONL sink) and
                   ``NullMetrics`` (the zero-overhead default — recording
                   disabled costs nothing on the hot path);
- ``spans``        profiling spans: wall-clock + ``jax.profiler``
                   TraceAnnotation context managers (so host-side phases —
                   schedule lowering, jit compile, device put, epoch
                   execution — are labeled inside profiler captures AND
                   timed into the metrics stream), plus ``capture`` wrapping
                   ``jax.profiler.trace``;
- ``trace_stats``  the chrome-trace analyzer behind docs/performance.md's
                   roofline numbers (promoted from scripts/ to an importable,
                   tested module; the script remains as a thin shim);
- ``flight``       the step-level flight recorder: a bounded ring buffer of
                   per-step (loss, grad-norm, param-norm) samples, fed by
                   the fused epoch programs' aux outputs (never host
                   callbacks inside the scan) and emitted as schema-v2
                   ``step`` records;
- ``health``       the numerics health monitor: NaN/Inf, rolling-window
                   loss-divergence and grad-spike checks over the flight
                   aux, with a record/warn/halt policy
                   (``TrainingSession(health=...)``, ``train.py --health``);
- ``stats``        the ONE percentile definition (np.percentile, linear
                   interpolation) shared by the serving engine's summary,
                   the fleet summary and the report CLI's killed-run
                   fallback — three consumers, one definition, so p99 can
                   never disagree with itself — plus the ONE
                   first-enqueue→last-complete serving-window definition
                   (``ThroughputWindow``) behind both summaries' rates;
- ``tracing``      distributed request tracing (schema-v10 ``trace``
                   records): the span ``Tracer`` the serving engine and
                   fleet emit through, cross-process clock alignment from
                   the fleet handshake's round-trip offset estimates, the
                   chain reader that joins parent + ``.r*`` shards onto
                   one parent timeline (refusing orphan/unclosed chains
                   for terminal requests), and the phase-attribution /
                   waterfall analysis behind the report's Tracing
                   section;
- ``rollup``       streaming rollups (schema-v11 ``rollup`` records):
                   tumbling-window online counters / gauges / EWMA rates
                   and the mergeable log-bucketed ``QuantileSketch``
                   (documented relative-error bound vs ``stats``'s
                   percentile), closed purely on record timestamps with
                   a bounded ring, plus the ``.r*``/``.p*`` shard merge
                   that re-aligns windows via the tracing clock offsets;
- ``slo``          SLO alerting (schema-v11 ``alert`` records):
                   multi-window multi-burn-rate rules, event-triggered
                   breaker/health rules, the firing→resolved lifecycle,
                   the ``AlertSink`` hook (ROADMAP item 4's autoscaler
                   contract) and ``LiveTelemetry`` — the rollup+rules
                   sensor the engine, fleet and training session own;
- ``watch``        the live dashboard CLI
                   (``python -m shallowspeed_tpu.observability.watch``):
                   tails live JSONL shards (``--follow``) or reads
                   finished runs (``--once``), rendering current-window
                   throughput / p50 / p99 / queue depth / alert state;
- ``costmodel``    analytical MLP FLOPs + ``Compiled.cost_analysis()``
                   cross-check + MFU accounting (``model_flops``,
                   ``achieved_flops_per_sec``, ``mfu`` gauges per layout);
- ``program_audit`` the XLA program audit: collective census parsed from
                   ``Compiled.as_text()``, ``memory_analysis()`` through
                   one shared helper, the analytical comms model derived
                   from the layout + lowered tick tables, and the
                   census-vs-contract cross-check that fails loudly
                   (``TrainingSession(audit=True)`` / ``train.py --audit``;
                   schema-v3 ``xla_audit`` records);
- ``report``       the run-report CLI
                   (``python -m shallowspeed_tpu.observability.report``):
                   throughput, MFU, span breakdown, bubble fraction,
                   step-loss sparkline, health verdict, and a
                   ``--baseline`` regression gate for CI/bench.

Wiring: ``TrainingSession(metrics=JsonlMetrics(path))`` records per-epoch
training telemetry (loss, samples/s, grad-norm when clipping), per-step
flight records, MFU gauges, compile-time spans, and — on mesh layouts — the
lowered pipeline program's static tick stats (ticks, sends, stage occupancy,
bubble fraction). The CLI flags are ``train.py --metrics-out FILE`` and
``--health record|warn|halt``. See docs/observability.md.
"""

from shallowspeed_tpu.observability.flight import FlightRecorder
from shallowspeed_tpu.observability.health import (
    HealthError,
    HealthMonitor,
)
from shallowspeed_tpu.observability.metrics import (
    SCHEMA_VERSION,
    JsonlMetrics,
    MetricsRecorder,
    NullMetrics,
    read_jsonl,
    replica_shard_path,
)
from shallowspeed_tpu.observability.program_audit import AuditMismatchError
from shallowspeed_tpu.observability.rollup import (
    QuantileSketch,
    RollupBuilder,
    merge_rollup_records,
)
from shallowspeed_tpu.observability.slo import (
    AlertSink,
    BurnRateRule,
    EventRule,
    LiveTelemetry,
    SloEvaluator,
    ThresholdRule,
)
from shallowspeed_tpu.observability.spans import Span, capture, span
from shallowspeed_tpu.observability.stats import ThroughputWindow, percentile
from shallowspeed_tpu.observability.tracing import TraceError, Tracer

__all__ = [
    "SCHEMA_VERSION",
    "AlertSink",
    "AuditMismatchError",
    "BurnRateRule",
    "EventRule",
    "FlightRecorder",
    "HealthError",
    "HealthMonitor",
    "JsonlMetrics",
    "LiveTelemetry",
    "MetricsRecorder",
    "NullMetrics",
    "QuantileSketch",
    "RollupBuilder",
    "SloEvaluator",
    "Span",
    "ThresholdRule",
    "ThroughputWindow",
    "TraceError",
    "Tracer",
    "capture",
    "merge_rollup_records",
    "percentile",
    "read_jsonl",
    "replica_shard_path",
    "span",
]
