"""Lowering tests: replay tick programs symbolically and verify dataflow.

The tick program is the load-bearing artifact of the whole SPMD pipeline —
these tests interpret it with symbolic payloads (no arrays, no jax) and
assert that every stage's forward consumes exactly the right microbatch's
activations from its predecessor, every backward consumes the right gradient
from its successor, mailboxes never collide, and the tick counts match the
textbook formulas for each schedule.
"""

import numpy as np
import pytest

from shallowspeed_tpu import schedules as S
from shallowspeed_tpu.parallel.lowering import (
    OP_BWD,
    OP_FWD,
    OP_NOOP,
    ScheduleLoweringError,
    lower_schedule,
)

TRAIN = [S.NaiveParallelSchedule, S.GPipeSchedule, S.PipeDreamFlushSchedule]
GRID = [(4, 1), (4, 2), (4, 4), (2, 4), (8, 4), (1, 3), (4, 8)]


def replay(p):
    """Symbolically execute a TickProgram; returns per-stage event log.

    Payloads are tuples ("act"|"grad", mubatch, from_stage). Raises on any
    mailbox misuse; returns events[(t, s)] = (op, mb, consumed_payload).
    """
    Kf, Kb, Ks = p.n_fwd_slots, p.n_bwd_slots, p.n_stash_slots
    fwd_mail = [[None] * Kf for _ in range(p.num_stages)]
    bwd_mail = [[None] * Kb for _ in range(p.num_stages)]
    stash = [[None] * Ks for _ in range(p.num_stages)]
    events = {}
    for t in range(p.num_ticks):
        outgoing = []  # (dst, direction, slot, payload)
        for s in range(p.num_stages):
            op, mb = int(p.op[t, s]), int(p.mb[t, s])
            consumed = None
            rf, rb = int(p.read_fwd_slot[t, s]), int(p.read_bwd_slot[t, s])
            if rf != Kf:
                consumed = fwd_mail[s][rf]
                assert consumed is not None, f"read from empty fwd slot at t={t} s={s}"
                fwd_mail[s][rf] = None
            if rb != Kb:
                assert consumed is None
                consumed = bwd_mail[s][rb]
                assert consumed is not None, f"read from empty bwd slot at t={t} s={s}"
                bwd_mail[s][rb] = None
            # activation stash: forwards write a free slot, the matching
            # backward (same stage, same microbatch) reads and frees it
            sw, sr = int(p.stash_write[t, s]), int(p.stash_read[t, s])
            if sw != Ks:
                assert op == OP_FWD
                assert stash[s][sw] is None, f"stash overwrite t={t} s={s}"
                stash[s][sw] = mb
            if sr != Ks:
                assert op == OP_BWD
                assert stash[s][sr] == mb, (
                    f"backward reads wrong stash at t={t} s={s}: "
                    f"expected mb={mb}, slot holds {stash[s][sr]}"
                )
                stash[s][sr] = None
            if p.is_training and op == OP_BWD:
                assert sr != Ks, f"backward without stash read at t={t} s={s}"
            if op != OP_NOOP:
                events[(t, s)] = (op, mb, consumed)
            if p.send_fwd[t, s]:
                assert op == OP_FWD
                outgoing.append((s + 1, "fwd", ("act", mb, s)))
            if p.send_bwd[t, s]:
                assert op == OP_BWD
                outgoing.append((s - 1, "bwd", ("grad", mb, s)))
        for dst, direction, payload in outgoing:
            mail = fwd_mail if direction == "fwd" else bwd_mail
            slot_tab = p.in_fwd_slot if direction == "fwd" else p.in_bwd_slot
            slot = int(slot_tab[t, dst])
            assert slot != (Kf if direction == "fwd" else Kb), (
                f"payload to stage {dst} at t={t} has no assigned slot"
            )
            assert mail[dst][slot] is None, f"mailbox collision at t={t} dst={dst}"
            mail[dst][slot] = payload
    for s in range(p.num_stages):
        assert all(x is None for x in fwd_mail[s] + bwd_mail[s]), "leftover messages"
        assert all(x is None for x in stash[s]), "leaked activation stash"
    return events


@pytest.mark.parametrize("cls", TRAIN)
@pytest.mark.parametrize("M,St", GRID)
def test_dataflow_correctness(cls, M, St):
    p = lower_schedule(cls, M, St)
    events = replay(p)
    for (t, s), (op, mb, consumed) in events.items():
        if op == OP_FWD:
            if s == 0:
                assert consumed is None  # loads from the dataset
            else:
                assert consumed == ("act", mb, s - 1), (t, s, mb, consumed)
        elif op == OP_BWD:
            if s == St - 1:
                assert consumed is None  # consumes loaded targets
            else:
                assert consumed == ("grad", mb, s + 1), (t, s, mb, consumed)
    # every stage does M forwards and M backwards
    for s in range(St):
        ops_s = [v[0] for (t, ss), v in events.items() if ss == s]
        assert ops_s.count(OP_FWD) == M and ops_s.count(OP_BWD) == M


@pytest.mark.parametrize("M,St", GRID)
def test_inference_dataflow(M, St):
    p = lower_schedule(S.InferenceSchedule, M, St)
    events = replay(p)
    assert all(v[0] == OP_FWD for v in events.values())
    assert not p.is_training


class TestTickCounts:
    """Lowered latency must equal the textbook schedule depth."""

    @pytest.mark.parametrize("M,St", [(4, 2), (4, 4), (8, 4), (2, 4)])
    def test_gpipe(self, M, St):
        assert lower_schedule(S.GPipeSchedule, M, St).num_ticks == 2 * (M + St - 1)

    @pytest.mark.parametrize("M,St", [(4, 2), (4, 4), (8, 4)])
    def test_pipedream_no_slower_than_gpipe(self, M, St):
        assert (
            lower_schedule(S.PipeDreamFlushSchedule, M, St).num_ticks
            <= lower_schedule(S.GPipeSchedule, M, St).num_ticks
        )

    @pytest.mark.parametrize("M,St", [(4, 2), (4, 4)])
    def test_naive(self, M, St):
        assert lower_schedule(S.NaiveParallelSchedule, M, St).num_ticks == 2 * M * St

    @pytest.mark.parametrize("M,St", [(4, 4), (8, 2)])
    def test_inference(self, M, St):
        assert lower_schedule(S.InferenceSchedule, M, St).num_ticks == M + St - 1


class TestPipelineUtilization:
    def test_gpipe_bubble_fraction(self):
        """Busy ticks / total = M/(M+S-1) per phase — the GPipe bubble law."""
        M, St = 8, 4
        p = lower_schedule(S.GPipeSchedule, M, St)
        busy = (np.asarray(p.op) != OP_NOOP).sum()
        assert busy == 2 * M * St  # total work
        assert p.num_ticks == 2 * (M + St - 1)

    def test_naive_only_one_stage_active(self):
        p = lower_schedule(S.NaiveParallelSchedule, 4, 4)
        active_per_tick = (np.asarray(p.op) != OP_NOOP).sum(axis=1)
        assert (active_per_tick <= 1).all()


class TestValidation:
    def test_malformed_schedule_deadlocks(self):
        class Broken(S.Schedule):
            def steps(self):
                yield [S.ZeroGrad()]
                # stage 1 receives but stage 0 never sends -> deadlock
                if self.stage_id == 0:
                    yield [S.LoadMuBatchInput(mubatch_id=0), S.Forward(mubatch_id=0)]
                    yield [
                        S.LoadMuBatchTarget(mubatch_id=0),
                        S.BackwardGradAllReduce(mubatch_id=0),
                    ]
                else:
                    yield [S.RecvActivations(), S.Forward(mubatch_id=0)]
                    yield [S.BackwardGradAllReduce(mubatch_id=0)]
                yield [S.OptimizerStep()]

        with pytest.raises(ScheduleLoweringError):
            lower_schedule(Broken, 1, 2)

    def test_missing_optimizer_step_rejected(self):
        class NoOpt(S.Schedule):
            def steps(self):
                yield [S.ZeroGrad()]
                yield [S.LoadMuBatchInput(mubatch_id=0), S.Forward(mubatch_id=0)]
                yield [
                    S.LoadMuBatchTarget(mubatch_id=0),
                    S.BackwardGradAllReduce(mubatch_id=0),
                ]

        with pytest.raises(ScheduleLoweringError):
            lower_schedule(NoOpt, 1, 1, training=True)

    def test_out_of_order_consumer_pairs_correctly(self):
        """A receiver that consumes microbatches in a different order than its
        peer emits them must get the RIGHT payloads (mailbox binds messages by
        microbatch id, not FIFO position) — never silently mispair."""

        class Swapped(S.Schedule):
            # stage 0 sends fwd mb0 then mb1; stage 1 consumes mb1 first
            def steps(self):
                yield [S.ZeroGrad()]
                if self.stage_id == 0:
                    for mb in (0, 1):
                        yield [
                            S.LoadMuBatchInput(mubatch_id=mb),
                            S.Forward(mubatch_id=mb),
                            S.SendActivations(),
                        ]
                    for mb in (0, 1):
                        yield [
                            S.RecvOutputGrad(),
                            (S.BackwardGradAllReduce if mb == 1 else S.BackwardGradAcc)(
                                mubatch_id=mb
                            ),
                        ]
                else:
                    for mb in (1, 0):  # swapped consumption order
                        yield [S.RecvActivations(), S.Forward(mubatch_id=mb)]
                    for mb in (0, 1):
                        yield [
                            S.LoadMuBatchTarget(mubatch_id=mb),
                            (S.BackwardGradAllReduce if mb == 1 else S.BackwardGradAcc)(
                                mubatch_id=mb
                            ),
                            S.SendInputGrad(),
                        ]
                yield [S.OptimizerStep()]

        p = lower_schedule(Swapped, 2, 2)
        events = replay(p)  # replay asserts every consume matches its mubatch
        fwd_order_s1 = [
            v[1] for (t, s), v in sorted(events.items()) if s == 1 and v[0] == OP_FWD
        ]
        assert fwd_order_s1 == [1, 0]

    def test_incomplete_mubatch_coverage_rejected(self):
        class Skips(S.GPipeSchedule):
            def steps(self):
                for step in super().steps():
                    # drop forward of mubatch 1
                    yield [
                        c
                        for c in step
                        if not (isinstance(c, S.Forward) and c.mubatch_id == 1)
                    ]

        with pytest.raises(ScheduleLoweringError):
            lower_schedule(Skips, 2, 1)
