"""Live-telemetry tests: quantile-sketch error bound vs the shared
stats.percentile oracle, shard-merge determinism, bounded window rings,
SLO alert rule lifecycles, and the watch CLI fold (--once == --follow).
"""

import json

import numpy as np
import pytest

from shallowspeed_tpu.observability.metrics import (
    SCHEMA_VERSION,
    JsonlMetrics,
    read_jsonl,
)
from shallowspeed_tpu.observability.rollup import (
    DEFAULT_RING,
    TEST_RELATIVE_BOUND,
    EwmaRate,
    QuantileSketch,
    RollupBuilder,
    merge_rollup_records,
)
from shallowspeed_tpu.observability.slo import (
    BurnRateRule,
    EventRule,
    LiveTelemetry,
    SloEvaluator,
    ThresholdRule,
    default_serving_rules,
    default_training_rules,
)
from shallowspeed_tpu.observability.stats import percentile
from shallowspeed_tpu.observability.watch import WatchState
from shallowspeed_tpu.observability.watch import main as watch_main

QUANTS = (50.0, 90.0, 99.0)


# ---------------------------------------------------------------------------
# quantile sketch: accuracy vs the shared oracle
# ---------------------------------------------------------------------------


def _sketch_of(samples):
    sk = QuantileSketch()
    for v in samples:
        sk.add(v)
    return sk


def _assert_within_bound(samples, quantiles=QUANTS):
    samples = [float(v) for v in samples]
    sk = _sketch_of(samples)
    for q in quantiles:
        truth = percentile(samples, q)
        got = sk.percentile(q)
        rel = abs(got - truth) / max(abs(truth), 1e-12)
        assert rel <= TEST_RELATIVE_BOUND, (
            f"p{q:g}: sketch {got} vs oracle {truth} "
            f"(rel {rel:.4f} > {TEST_RELATIVE_BOUND})"
        )


def test_sketch_accuracy_heavy_tail():
    # Pareto(1.5): the tail the report CLI's p99 actually faces —
    # latency-like, orders of magnitude between p50 and p99
    rng = np.random.RandomState(0)
    _assert_within_bound(0.001 * (1.0 + rng.pareto(1.5, 5000)))


def test_sketch_accuracy_lognormal():
    rng = np.random.RandomState(1)
    _assert_within_bound(rng.lognormal(-4.0, 1.0, 5000))


def test_sketch_accuracy_bimodal():
    # cache-hit/cache-miss shape. Quantiles chosen OFF the mass
    # boundary (40% fast mode): interpolating BETWEEN the modes
    # manufactures a value no sample takes, which no sketch can match.
    rng = np.random.RandomState(2)
    fast = rng.uniform(0.001, 0.002, 2000)
    slow = rng.uniform(0.4, 0.6, 3000)
    _assert_within_bound(np.concatenate([fast, slow]), quantiles=QUANTS)


def test_sketch_constant_stream_exact():
    sk = _sketch_of([0.25] * 1000)
    for q in (0.0, 50.0, 99.0, 100.0):
        assert sk.percentile(q) == 0.25  # clamped into exact [min, max]
    assert sk.min == sk.max == 0.25
    assert sk.mean == pytest.approx(0.25)


def test_sketch_zero_and_negative_samples():
    # loss deltas go negative; a queue wait can be exactly 0.0
    samples = [-2.0] * 10 + [0.0] * 30 + [1.0] * 60
    sk = _sketch_of(samples)
    assert sk.zero == 30
    assert sk.percentile(0.0) == pytest.approx(-2.0, rel=TEST_RELATIVE_BOUND)
    assert sk.percentile(20.0) == 0.0
    got = sk.percentile(90.0)
    assert abs(got - 1.0) / 1.0 <= TEST_RELATIVE_BOUND


def test_sketch_rejects_non_finite_and_bad_alpha():
    sk = QuantileSketch()
    with pytest.raises(ValueError, match="non-finite"):
        sk.add(float("nan"))
    with pytest.raises(ValueError, match="alpha"):
        QuantileSketch(alpha=1.5)
    assert QuantileSketch().percentile(50.0) is None  # empty


# ---------------------------------------------------------------------------
# quantile sketch: merge determinism
# ---------------------------------------------------------------------------


def _assert_structurally_equal(a, b):
    """Exact on every structural field (and therefore every percentile);
    only the float ``sum`` is subject to addition-order rounding."""
    assert a.count == b.count
    assert a.zero == b.zero
    assert a.min == b.min and a.max == b.max
    assert a.buckets == b.buckets
    assert a.neg_buckets == b.neg_buckets
    for q in (0.0, 50.0, 90.0, 99.0, 100.0):
        assert a.percentile(q) == b.percentile(q)
    assert a.sum == pytest.approx(b.sum, rel=1e-9)


def test_sketch_merge_equals_concatenation():
    rng = np.random.RandomState(3)
    shards = [rng.lognormal(-3.0, 1.0, 1000) for _ in range(4)]
    shards[1][:5] = 0.0  # exercise zero + negative paths through merge
    shards[2][:5] = -shards[2][:5]
    merged = QuantileSketch()
    for shard in shards:
        # JSON round trip on the way in: what merge_rollup_records does
        merged.merge(QuantileSketch.from_dict(_sketch_of(shard).to_dict()))
    _assert_structurally_equal(merged, _sketch_of(np.concatenate(shards)))


def test_sketch_merge_order_independent():
    rng = np.random.RandomState(4)
    shards = [rng.pareto(1.5, 500) + 1.0 for _ in range(3)]
    fwd = QuantileSketch()
    for shard in shards:
        fwd.merge(_sketch_of(shard))
    rev = QuantileSketch()
    for shard in reversed(shards):
        rev.merge(_sketch_of(shard))
    _assert_structurally_equal(fwd, rev)


def test_sketch_merge_refuses_alpha_mismatch():
    with pytest.raises(ValueError, match="alpha"):
        QuantileSketch(alpha=0.01).merge(QuantileSketch(alpha=0.02))


def test_sketch_json_round_trip_exact():
    sk = _sketch_of([0.0, -1.0, 0.5, 2.0, 2.0])
    _assert_structurally_equal(
        QuantileSketch.from_dict(json.loads(json.dumps(sk.to_dict()))), sk
    )


# ---------------------------------------------------------------------------
# rollup builder: tumbling windows, late samples, bounded ring
# ---------------------------------------------------------------------------


def test_rollup_builder_window_semantics(tmp_path):
    path = tmp_path / "m.jsonl"
    closed = []
    with JsonlMetrics(path) as m:
        b = RollupBuilder(
            "serving", window_s=1.0, metrics=m, replica_id=2,
            on_close=closed.append,
        )
        b.count(10.25, "terminal")
        b.observe(10.5, "latency_s", 0.02)
        b.gauge(10.75, "queue_depth", 3)
        b.count(11.0, "terminal")  # t >= window_end: closes [10, 11)
        b.count(10.2, "terminal")  # late: folds into CURRENT window
        b.flush()
    assert [w["window_start"] for w in closed] == [10.0, 11.0]
    w0, w1 = closed
    assert w0["window_end"] == 11.0 and w0["seq"] == 0
    assert w0["counters"] == {"terminal": 1.0}
    assert w0["rates"]["terminal"]["rate"] == 1.0
    assert w0["gauges"]["queue_depth"] == {"last": 3, "min": 3, "max": 3}
    assert w0["quantiles"]["latency_s"]["count"] == 1
    assert w0["late"] == 0 and w0["replica_id"] == 2
    assert w1["counters"] == {"terminal": 2.0} and w1["late"] == 1
    # the emitted records match the on_close summaries field for field
    recs = [r for r in read_jsonl(path) if r["kind"] == "rollup"]
    assert len(recs) == 2
    assert all(r["name"] == "serving" and r["v"] == SCHEMA_VERSION
               for r in recs)
    assert recs[0]["counters"] == w0["counters"]
    assert recs[0]["sketches"]["latency_s"]["count"] == 1
    assert recs[1]["sketches"] == {}  # nothing observed in [11, 12)


def test_rollup_ring_stays_bounded():
    b = RollupBuilder("serving", window_s=1.0)
    n_windows = 4 * DEFAULT_RING
    for i in range(2 * n_windows):  # 2 samples per window, long stream
        t = i * 0.5
        b.count(t, "terminal")
        b.observe(t, "latency_s", 0.01)
    b.flush()
    assert len(b.closed) == DEFAULT_RING  # bounded, not the full history
    assert b.closed[-1]["seq"] == n_windows - 1  # ...but nothing unseen
    assert b.closed[0]["seq"] == n_windows - DEFAULT_RING
    snap = b.snapshot()
    assert snap["windows_closed"] == n_windows
    assert snap["live_window"] is None  # flushed


def test_ewma_rate_time_constant():
    e = EwmaRate(tau_s=30.0)
    assert e.update(10.0, 1.0) == 10.0  # first window seeds
    v = e.update(0.0, 1.0)
    k = 1.0 - np.exp(-1.0 / 30.0)
    assert v == pytest.approx(10.0 * (1.0 - k))


# ---------------------------------------------------------------------------
# shard merging onto one timeline
# ---------------------------------------------------------------------------


def _shard_records(replica_id, t0, samples, window_s=1.0):
    closed = []
    b = RollupBuilder(
        "serving", window_s=window_s, replica_id=replica_id,
        on_close=closed.append,
    )
    for i, v in enumerate(samples):
        t = t0 + i * (window_s / max(len(samples), 1)) * 1.9
        b.count(t, "terminal")
        b.observe(t, "latency_s", v)
        b.gauge(t, "queue_depth", replica_id + i)
    b.flush()
    return [{"kind": "rollup", "name": "serving", **w} for w in closed]


def test_merge_rollup_records_aligns_and_adds():
    rng = np.random.RandomState(5)
    vals0 = rng.lognormal(-3.0, 0.5, 40)
    vals1 = rng.lognormal(-3.0, 0.5, 40)
    # replica 1's clock reads 0.98s BEHIND the parent; the PR 14 offset
    # estimate (worker t + offset = parent t) shifts its window bounds,
    # and the snap lands them on the parent's grid (99.02 + 0.98 is not
    # exactly 100.0 in floats — that's what the snap is for)
    off = 0.98
    recs = _shard_records(0, 100.0, vals0) + _shard_records(
        1, 100.0 - off, vals1
    )
    merged = merge_rollup_records(recs, offsets={1: off})
    starts = sorted({c["window_start"] for c in merged})
    assert starts[0] == 100.0  # snapped onto the parent grid
    total = sum(c["counters"]["terminal"] for c in merged)
    assert total == len(vals0) + len(vals1)
    both = [c for c in merged if c["shards"] == 2]
    assert both and both[0]["replica_ids"] == [0, 1]
    # merged-cell percentiles == sketch-of-all-window-samples percentiles
    cell = both[0]
    oracle = QuantileSketch()
    for r in recs:
        shard_off = off if r["replica_id"] == 1 else 0.0
        if round(r["window_start"] + shard_off) == cell["window_start"]:
            oracle.merge(
                QuantileSketch.from_dict(r["sketches"]["latency_s"])
            )
    got = QuantileSketch.from_dict(cell["sketches"]["latency_s"])
    _assert_structurally_equal(got, oracle)


def test_merge_rollup_records_order_independent():
    recs = _shard_records(0, 50.0, [0.01, 0.02, 0.03]) + _shard_records(
        1, 49.6, [0.04, 0.05, 0.06]
    )
    offsets = {1: {"offset_s": 0.4}}  # full clock_offsets dict form
    fwd = merge_rollup_records(recs, offsets=offsets)
    rev = merge_rollup_records(list(reversed(recs)), offsets=offsets)
    assert json.dumps(fwd, sort_keys=True) == json.dumps(rev, sort_keys=True)


# ---------------------------------------------------------------------------
# SLO alert rules
# ---------------------------------------------------------------------------


class _Sink:
    def __init__(self):
        self.records = []

    def alert(self, record):
        self.records.append(record)


class _BoomSink:
    def alert(self, record):
        raise RuntimeError("broken alert consumer")


def test_event_rule_lifecycle_and_sink_isolation():
    sink = _Sink()
    ev = SloEvaluator(
        [EventRule("breaker_open", ("breaker_open",), ("breaker_closed",))],
        sinks=(_BoomSink(), sink),  # a raising sink must not block the next
        replica_id=0,
    )
    ev.note_event(1.0, "breaker_open")
    assert ev.active() == {"breaker_open": "page"}
    ev.note_event(1.5, "breaker_open")  # same state: edges only, no spam
    assert len(sink.records) == 1
    ev.note_event(2.0, "breaker_closed")
    assert ev.active() == {}
    assert [r["state"] for r in sink.records] == ["firing", "resolved"]
    assert sink.records[0]["rule"] == "breaker_open"
    assert sink.records[0]["severity"] == "page"
    assert sink.records[0]["replica_id"] == 0
    snap = ev.snapshot()
    assert snap["fired"] == 1 and snap["resolved"] == 1


def test_burn_rate_rule_fires_and_resolves():
    rule = BurnRateRule(
        "error_burn", budget=0.01, long_s=30.0, short_s=5.0, burn=6.0,
        min_samples=10,
    )
    ev = SloEvaluator([rule])
    for i in range(20):  # clean baseline: never fires
        ev.note_request(0.1 * i, "completed")
    assert ev.active() == {}
    for i in range(20):  # error burst: burns far past 6x in BOTH windows
        ev.note_request(10.0 + 0.05 * i, "error")
    assert ev.active() == {"error_burn": "page"}
    firing = ev.history[-1]
    assert firing["state"] == "firing"
    assert firing["burn_fast"] >= 6.0 and firing["burn_slow"] >= 6.0
    # recovery: the SHORT window going clean resolves, even though the
    # long window still remembers the burst
    for i in range(20):
        ev.note_request(12.0 + 0.3 * i, "completed")
    assert ev.active() == {}
    assert ev.history[-1]["state"] == "resolved"
    assert ev.snapshot() == {
        "rules": [
            {"name": "error_burn", "state": "ok", "severity": "page"}
        ],
        "active": {},
        "fired": 1,
        "resolved": 1,
    }


def test_threshold_rule_streaks():
    rule = ThresholdRule(
        "p99_slo", lambda s: s.get("v"), 10.0, for_windows=2,
        clear_windows=2,
    )
    ev = SloEvaluator([rule])
    ev.note_window({"v": 15.0, "window_end": 1.0})
    assert ev.active() == {}  # one breaching window is not a streak
    ev.note_window({"v": 20.0, "window_end": 2.0})
    assert ev.active() == {"p99_slo": "ticket"}
    ev.note_window({"v": 1.0, "window_end": 3.0})
    assert ev.active() == {"p99_slo": "ticket"}  # one clean one is not either
    ev.note_window({"window_end": 4.0})  # metric absent: streak untouched
    ev.note_window({"v": 2.0, "window_end": 5.0})
    assert ev.active() == {}
    assert [h["state"] for h in ev.history] == ["firing", "resolved"]
    assert ev.history[0]["value"] == 20.0
    assert ev.history[0]["threshold"] == 10.0


def test_default_rule_sets():
    names = {r.name for r in default_serving_rules()}
    assert names == {"breaker_open", "fleet_degraded", "error_burn"}
    armed = {r.name for r in default_serving_rules(slo_ms=50.0, knee_rps=100.0)}
    assert armed == names | {"p99_slo", "knee_proximity"}
    train = {r.name for r in default_training_rules()}
    assert train == {"training_health", "checkpoint_overhead"}


def test_live_telemetry_end_to_end(tmp_path):
    path = tmp_path / "m.jsonl"
    with JsonlMetrics(path) as m:
        lt = LiveTelemetry("serving", metrics=m, window_s=1.0, replica_id=0)
        for i in range(20):
            t = 0.05 * i
            lt.note_admit(t)
            lt.note_request(t, "completed", latency_s=0.01, queue_s=0.001)
        lt.note_queue_depth(0.5, 4)
        lt.note_health(1.2, "breaker_open")
        lt.note_health(1.6, "breaker_closed")
        lt.flush()
    recs = read_jsonl(path)
    rollups = [r for r in recs if r["kind"] == "rollup"]
    alerts = [r for r in recs if r["kind"] == "alert"]
    w0 = next(r for r in rollups if r["window_start"] == 0.0)
    assert w0["counters"]["terminal"] == 20.0
    assert w0["counters"]["completed"] == 20.0
    assert w0["counters"]["admitted"] == 20.0
    assert w0["gauges"]["queue_depth"]["last"] == 4
    assert w0["quantiles"]["latency_s"]["count"] == 20
    assert w0["replica_id"] == 0
    assert [(a["name"], a["state"]) for a in alerts] == [
        ("breaker_open", "firing"),
        ("breaker_open", "resolved"),
    ]
    snap = lt.snapshot()
    assert snap["alerts"]["active"] == {}
    assert snap["rollup"]["windows_closed"] >= 1


# ---------------------------------------------------------------------------
# watch CLI: the deterministic fold and its exit codes
# ---------------------------------------------------------------------------


def _write_run(path):
    with JsonlMetrics(path) as m:
        lt = LiveTelemetry("serving", metrics=m, window_s=1.0)
        for i in range(30):
            t = 0.1 * i
            # telemetry verdicts all clean so ONLY the breaker events
            # below drive alert transitions; the raw request records
            # still carry errors for the watcher's computed rollups
            lt.note_request(t, "completed",
                            latency_s=0.005 + 0.001 * (i % 5))
            m.request("completed" if i % 7 else "error", ts=t,
                      latency_s=0.005 + 0.001 * (i % 5))
        lt.note_health(1.1, "breaker_open")
        lt.note_health(2.2, "breaker_closed")
        lt.flush()


def test_watch_once_equals_follow(tmp_path, capsys):
    path = tmp_path / "run.jsonl"
    _write_run(path)
    assert watch_main([str(path), "--once", "--format", "json"]) == 0
    once = capsys.readouterr().out
    assert (
        watch_main([
            str(path), "--follow", "--format", "json",
            "--interval", "0.05", "--idle-exit", "0.2",
        ])
        == 0
    )
    follow = capsys.readouterr().out
    assert once == follow  # byte-identical: the determinism contract
    snap = json.loads(once)
    assert snap["records"] > 0 and snap["malformed"] == 0
    assert snap["alerts"]["fired"] == 1 and snap["alerts"]["resolved"] == 1
    assert snap["alerts"]["active"] == []
    assert "serving" in snap["rollups"]


def test_watch_resolves_replica_shards(tmp_path, capsys):
    # satellite: watch and read_jsonl share ONE shard-glob resolution —
    # a bare missing base path falls back to its .r* shards
    base = tmp_path / "fleet.jsonl"
    _write_run(tmp_path / "fleet.jsonl.r0")
    _write_run(tmp_path / "fleet.jsonl.r1")
    assert watch_main([str(base), "--once", "--format", "json"]) == 0
    snap = json.loads(capsys.readouterr().out)
    assert snap["records"] == 2 * len(read_jsonl(tmp_path / "fleet.jsonl.r0"))


def test_watch_once_exit_codes(tmp_path, capsys):
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    assert watch_main([str(empty), "--once", "--format", "json"]) == 1
    capsys.readouterr()
    bad = tmp_path / "bad.jsonl"
    bad.write_text('{"v": 1, "kind": "meta", "name": "x", "ts": 0}\n'
                   "this is not json\n")
    assert watch_main([str(bad), "--once", "--format", "json"]) == 1
    snap = json.loads(capsys.readouterr().out)
    assert snap["malformed"] == 1
    # a NEWER schema is skipped (live dashboard survives a rolling
    # upgrade), not a failure — unlike the strict read_jsonl contract
    newer = tmp_path / "newer.jsonl"
    newer.write_text(
        json.dumps({"v": SCHEMA_VERSION + 1, "kind": "mystery"}) + "\n"
        + json.dumps({"v": 1, "kind": "meta", "name": "x", "ts": 0}) + "\n"
    )
    assert watch_main([str(newer), "--once", "--format", "json"]) == 0
    snap = json.loads(capsys.readouterr().out)
    assert snap["skipped_newer"] == 1 and snap["records"] == 1


def test_watch_state_is_pure_fold():
    lines = [
        json.dumps({"v": SCHEMA_VERSION, "kind": "request",
                    "name": "completed", "ts": 0.1 * i,
                    "latency_s": 0.01})
        for i in range(25)
    ]
    a = WatchState()
    for ln in lines:
        a.ingest_line(ln)
    b = WatchState()
    for ln in reversed(lines):  # arbitrary interleave across shards...
        b.ingest_line(ln)
    # ...does not change counts (window assignment is ts-driven, so the
    # sketch contents match too — late arrivals only move the `late` tally)
    sa, sb = a.snapshot(), b.snapshot()
    assert sa["records"] == sb["records"] == 25
    ca = sa["computed"]["serving"]
    assert ca["windows_closed"] >= 2
