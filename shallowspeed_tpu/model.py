"""Model layer: stage partitioning + explicit forward/backward over pytrees.

Capability parity with the reference's Module/Sequential/MLP stack
(/root/reference/shallowspeed/layers.py), re-designed functionally for JAX:

- parameters are a pytree ``[{"W": (out,in), "b": (1,out)}, ...]`` per stage —
  no Parameter objects, no mutable .grad fields;
- the per-microbatch activation caches (reference ``Module._cache`` keyed by
  mubatch_id, layers.py:70,86,117) become *residuals returned by the forward
  pass* and threaded explicitly into the backward pass — idiomatic JAX, and
  what lets the whole step jit/scan cleanly;
- gradient accumulation (reference ``param.grad +=``, layers.py:135-136) is a
  pytree add performed by the caller (a lax.scan carry), not hidden state.

Stage partitioning semantics match reference layers.py:236-270 ("MLP"):
``len(sizes) % n_stages == 0``; stage i owns the sizes slice
``[i*ss : i*ss+ss+1]`` (overlapping boundary entry) giving ``len(local)-1``
Linear layers; every Linear has a fused ReLU except the last Linear of the
last stage; the last stage appends the softmax + MSE loss head. Stages are
deliberately UNEQUAL (e.g. 2/2/2/1 Linears at PP=4) — the SPMD executor
handles that via zero-padded stacked params (see parallel/executor.py).

Faithful reference quirk: when the last stage owns ZERO Linears (e.g. 8
sizes at PP=8), the no-relu-on-final-Linear rule never fires — the global
final Linear (owned by the second-to-last stage) keeps its ReLU, so that
layout is architecturally DIFFERENT from the sequential model. This matches
the reference exactly (layers.py:253-257); layout/sequential equivalence
holds whenever the last stage has at least one Linear.
"""

import dataclasses
from typing import Sequence

import jax.numpy as jnp

from shallowspeed_tpu import ops
from shallowspeed_tpu.init import linear_init


@dataclasses.dataclass(frozen=True)
class StageSpec:
    """Static description of one pipeline stage (trace-time constant)."""

    local_sizes: tuple  # activation dims owned by this stage, len = n_linears+1
    relu_flags: tuple  # per-Linear fused-ReLU flag
    has_head: bool  # softmax + MSE head lives on the last stage
    global_batch_size: int

    @property
    def n_linears(self):
        return len(self.local_sizes) - 1

    @property
    def in_dim(self):
        return self.local_sizes[0]

    @property
    def out_dim(self):
        # softmax & loss head do not change the output dim (layers.py:268-270)
        return self.local_sizes[-1]


@dataclasses.dataclass(frozen=True)
class ModelSpec:
    """Static description of the whole (possibly pipelined) model."""

    sizes: tuple
    n_stages: int
    global_batch_size: int
    stages: tuple  # tuple[StageSpec]

    @property
    def in_dim(self):
        return self.sizes[0]

    @property
    def out_dim(self):
        return self.sizes[-1]


def partition_sizes(sizes: Sequence[int], n_stages: int):
    """Slice the global layer-size list into per-stage local size lists.

    Same arithmetic as reference layers.py:242-250, including the overlapping
    boundary entry and the possibility of a 0-Linear trailing stage.
    """
    sizes = tuple(int(s) for s in sizes)
    if len(sizes) % n_stages != 0:
        raise ValueError(
            f"len(sizes)={len(sizes)} must be divisible by n_stages={n_stages}"
        )
    stage_size = len(sizes) // n_stages
    return [
        sizes[i * stage_size : min(len(sizes), i * stage_size + stage_size + 1)]
        for i in range(n_stages)
    ]


def make_model_spec(sizes, n_stages, global_batch_size) -> ModelSpec:
    locals_ = partition_sizes(sizes, n_stages)
    if len(locals_[-1]) == 1:
        import warnings

        warnings.warn(
            f"the last of {n_stages} pipeline stages owns no Linear under "
            "this partitioning, so the 'no relu on the final Linear' rule "
            "never fires and the trained MODEL differs from shallower "
            "partitionings (faithful reference quirk, layers.py:253-257) — "
            "expect worse accuracy; prefer a size list that gives every "
            "stage a Linear",
            stacklevel=2,
        )
    stages = []
    for i, loc in enumerate(locals_):
        is_last = i == n_stages - 1
        n_lin = len(loc) - 1
        relu_flags = tuple(
            not (is_last and l == n_lin - 1) for l in range(n_lin)
        )  # last Linear of last stage has no activation (layers.py:253-257)
        stages.append(
            StageSpec(
                local_sizes=tuple(loc),
                relu_flags=relu_flags,
                has_head=is_last,
                global_batch_size=global_batch_size,
            )
        )
    return ModelSpec(
        sizes=tuple(int(s) for s in sizes),
        n_stages=n_stages,
        global_batch_size=global_batch_size,
        stages=tuple(stages),
    )


def init_stage_params(spec: StageSpec):
    """Host-side deterministic init for one stage; list of {"W","b"} numpy."""
    return [
        dict(zip(("W", "b"), linear_init(spec.local_sizes[l], spec.local_sizes[l + 1])))
        for l in range(spec.n_linears)
    ]


def init_model(spec: ModelSpec):
    """Per-stage parameter pytrees (host numpy; caller device_puts/shards)."""
    return [init_stage_params(s) for s in spec.stages]


# ---------------------------------------------------------------------------
# Forward / backward. Pure functions; residuals are explicit.
#
# Residuals structure per stage (static given the spec):
#   (layer_caches, z)
#     layer_caches: tuple per Linear of (x_in, relu_bitmask)  — bitmask is a
#                   zero-size placeholder for no-relu layers
#     z:            head-input logits if has_head else zero-size placeholder
# ---------------------------------------------------------------------------


def _placeholder(dtype=jnp.float32):
    return jnp.zeros((0,), dtype)


def stage_forward(
    params, spec: StageSpec, x, precision=ops.DEFAULT_PRECISION, head_group_rows=None
):
    """Run one stage's Linears (+head); return (out, residuals).

    In training the caller keeps residuals; for inference discard them (XLA
    dead-code-eliminates the cache outputs under jit).

    ``head_group_rows``: when several microbatches are fused into one call,
    the softmax head's stability max is taken per group of this many rows so
    the result is float-identical to a per-microbatch loop.

    Mirrors reference Sequential.forward + Linear.forward + head modules
    (layers.py:115-122,152-155,176-180) with caches made explicit.
    """
    caches = []
    for l in range(spec.n_linears):
        if spec.relu_flags[l]:
            y, mask = ops.linear_relu_fused(
                x, params[l]["W"], params[l]["b"], precision=precision
            )
            caches.append((x, mask))
            x = y
        else:
            y = ops.linear(x, params[l]["W"], params[l]["b"], precision=precision)
            caches.append((x, _placeholder(jnp.bool_)))
            x = y
    if spec.has_head:
        z = x
        out = ops.softmax(z, group_rows=head_group_rows)
        return out, (tuple(caches), z)
    return x, (tuple(caches), _placeholder())


def stage_backward(
    params,
    spec: StageSpec,
    residuals,
    dout,
    precision=ops.DEFAULT_PRECISION,
    head_group_rows=None,
):
    """Backward through one stage; returns (dx, grads) with grads ≅ params.

    Contract matches the reference Worker: for the head stage ``dout`` is the
    TARGET microbatch (the reference loads targets into the output buffer and
    MSELoss.backward consumes them, pipe.py:361-365 + layers.py:157-163);
    for other stages it is the gradient w.r.t. this stage's output.
    """
    caches, z = residuals
    if spec.has_head:
        g = ops.softmax_mse_head_grad(
            z, dout, spec.global_batch_size, group_rows=head_group_rows
        )
    else:
        g = dout
    grads = [None] * spec.n_linears
    for l in reversed(range(spec.n_linears)):
        x_in, bitmask = caches[l]
        if spec.relu_flags[l]:
            g, dw, db = ops.linear_relu_grad_fused(
                g, bitmask, x_in, params[l]["W"], precision=precision
            )
        else:
            g, dw, db = ops.linear_grad(g, x_in, params[l]["W"], precision=precision)
        grads[l] = {"W": dw, "b": jnp.reshape(db, (1, -1))}
    return g, grads


def model_forward(
    params_list, spec: ModelSpec, x, precision=ops.DEFAULT_PRECISION, head_group_rows=None
):
    """Chain all stages (the sequential / single-process path)."""
    residuals = []
    for params, sspec in zip(params_list, spec.stages):
        x, res = stage_forward(
            params, sspec, x, precision=precision, head_group_rows=head_group_rows
        )
        residuals.append(res)
    return x, residuals


def model_backward(
    params_list,
    spec: ModelSpec,
    residuals,
    target,
    precision=ops.DEFAULT_PRECISION,
    head_group_rows=None,
):
    """Chain all stages backward; ``target`` feeds the head stage."""
    g = target
    grads_list = [None] * spec.n_stages
    for i in reversed(range(spec.n_stages)):
        g, grads_list[i] = stage_backward(
            params_list[i],
            spec.stages[i],
            residuals[i],
            g,
            precision=precision,
            head_group_rows=head_group_rows,
        )
    return g, grads_list
