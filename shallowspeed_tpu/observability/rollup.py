"""Streaming rollups: tumbling-window online aggregation with a
mergeable, deterministic quantile sketch.

The report CLI computes percentiles AFTER a run finishes, from the full
raw record stream; nothing in the repo computed anything while a run was
alive. This module is the sensor half of the live-telemetry layer
(docs/observability.md § Live telemetry): bounded-memory aggregators
that fold a sample stream into TUMBLING windows (fixed width, aligned
to ``floor(t / window_s)``), close each window the moment a sample with
``t >= window_end`` arrives, and keep a bounded ring of closed windows.
Closing is driven purely by SAMPLE timestamps, never by wall clock —
the property that makes ``observability.watch --follow`` and ``--once``
produce bit-identical rollups over the same bytes, and makes replays
deterministic.

Per window, a :class:`RollupWindow` aggregates:

- counters   monotonic per-name sums (``completed``, ``errors``, ...);
- rates      each counter's per-window rate (count / window_s) plus an
             EWMA of that rate across windows (:class:`EwmaRate`) —
             the smoothed signal slow burn-rate rules read;
- gauges     last value wins within the window (``queue_depth``,
             ``loss``, ...), plus per-window min/max;
- sketches   a :class:`QuantileSketch` per observed metric
             (``latency_s``, ``step_s``, ...) — p50/p90/p99 per window.

THE SKETCH AND ITS ERROR BOUND. ``QuantileSketch`` is a log-bucketed
histogram (the DDSketch construction, arXiv 1908.10693): positive
values land in bucket ``ceil(log_gamma(x))`` with
``gamma = (1 + alpha) / (1 - alpha)``, so bucket ``i`` covers
``(gamma^(i-1), gamma^i]`` and the bucket representative
``2 * gamma^i / (gamma + 1)`` is within RELATIVE error ``alpha`` of
every value in the bucket. Consequence (the documented bound):
``percentile(q)`` returns a value within relative error ``alpha``
(default ``DEFAULT_ALPHA`` = 1%) of the empirical q-quantile SAMPLE.
The shared oracle ``stats.percentile`` linearly interpolates between
the two adjacent order statistics, so the tested bound against it is
``TEST_RELATIVE_BOUND`` = 2.5 x alpha — alpha for the bucket plus
slack for interpolation between adjacent samples (the tests pick
quantiles that do not sit exactly on a bimodal mass boundary, where
linear interpolation manufactures a value BETWEEN the modes that no
sketch — and no sample — can match). Buckets are exact integer counts
in a dict keyed by bucket index: merging two sketches is bucket-count
addition, which is associative and commutative, so
merge-of-shard-sketches == sketch-of-concatenated-samples EXACTLY on
every structural field (bucket counts, count, zero, min, max — and
therefore every percentile, tested), with only the float ``sum``
subject to addition-order rounding.

SHARD MERGING. Fleet replicas write ``.r{replica_id}`` shards and
multihost processes ``.p{process}`` shards, each rolling up in its own
clock domain. :func:`merge_rollup_records` re-aligns each shard's
window bounds onto the parent timeline using the PR 14 clock-offset
estimates (``tracing.clock_offsets`` — worker t + offset = parent t),
snaps to the nearest window boundary, and merges windows that land on
the same (source, window) cell: counters add, sketches merge, gauges
last-wins with a (window_end, replica_id) tie-break so the result is
independent of shard read order.
"""

import math

DEFAULT_ALPHA = 0.01  # sketch relative-error bound (module docstring)
# tested tolerance vs the linear-interpolating stats.percentile oracle:
# alpha for the bucket representative + slack for interpolation between
# adjacent order statistics
TEST_RELATIVE_BOUND = 2.5 * DEFAULT_ALPHA
DEFAULT_WINDOW_S = 1.0
DEFAULT_RING = 64  # closed windows kept per builder (bounded memory)
DEFAULT_QUANTILES = (50.0, 90.0, 99.0)


class QuantileSketch:
    """Mergeable log-bucketed quantile sketch with a relative-error
    guarantee of ``alpha`` vs the empirical quantile (module docstring).

    Non-positive values (a latency can be 0.0; a loss delta can be
    negative) are counted exactly: zeros in ``zero``, negatives in a
    mirrored bucket table — the guarantee is relative error ``alpha``
    on ``|x|`` for every sample.
    """

    __slots__ = (
        "alpha",
        "_gamma",
        "_log_gamma",
        "count",
        "sum",
        "min",
        "max",
        "zero",
        "buckets",
        "neg_buckets",
    )

    def __init__(self, alpha=DEFAULT_ALPHA):
        if not 0.0 < alpha < 1.0:
            raise ValueError(f"alpha must be in (0, 1), got {alpha!r}")
        self.alpha = float(alpha)
        self._gamma = (1.0 + self.alpha) / (1.0 - self.alpha)
        self._log_gamma = math.log(self._gamma)
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None
        self.zero = 0
        self.buckets = {}  # bucket index -> exact count (positive values)
        self.neg_buckets = {}  # same table for -x of negative values

    def _index(self, x):
        return int(math.ceil(math.log(x) / self._log_gamma - 1e-12))

    def _representative(self, idx):
        # midpoint of bucket (gamma^(i-1), gamma^i] in relative terms:
        # max relative error (gamma - 1) / (gamma + 1) == alpha exactly
        return 2.0 * self._gamma**idx / (self._gamma + 1.0)

    def add(self, x, count=1):
        x = float(x)
        if not math.isfinite(x):
            raise ValueError(f"QuantileSketch.add: non-finite sample {x!r}")
        if count <= 0:
            return
        self.count += count
        self.sum += x * count
        if self.min is None or x < self.min:
            self.min = x
        if self.max is None or x > self.max:
            self.max = x
        if x == 0.0:
            self.zero += count
        elif x > 0.0:
            idx = self._index(x)
            self.buckets[idx] = self.buckets.get(idx, 0) + count
        else:
            idx = self._index(-x)
            self.neg_buckets[idx] = self.neg_buckets.get(idx, 0) + count

    def merge(self, other):
        """Fold ``other`` into this sketch in place. Bucket-count
        addition: exact, associative, commutative — the merge-of-shards
        == sketch-of-concatenation property the tests pin."""
        if not isinstance(other, QuantileSketch):
            raise TypeError(f"cannot merge {type(other).__name__}")
        if other.alpha != self.alpha:
            raise ValueError(
                f"cannot merge sketches with different alpha "
                f"({self.alpha} vs {other.alpha})"
            )
        self.count += other.count
        self.sum += other.sum
        if other.min is not None and (self.min is None or other.min < self.min):
            self.min = other.min
        if other.max is not None and (self.max is None or other.max > self.max):
            self.max = other.max
        self.zero += other.zero
        for idx, c in other.buckets.items():
            self.buckets[idx] = self.buckets.get(idx, 0) + c
        for idx, c in other.neg_buckets.items():
            self.neg_buckets[idx] = self.neg_buckets.get(idx, 0) + c
        return self

    def percentile(self, q):
        """The q-th percentile (0..100): a value within relative error
        ``alpha`` of the empirical q-quantile sample; ``None`` when
        empty. Clamped into the exact observed [min, max], so a
        constant stream reads back exactly."""
        if self.count == 0:
            return None
        if not 0.0 <= q <= 100.0:
            raise ValueError(f"percentile q must be in [0, 100], got {q!r}")
        # rank of the target order statistic under the shared
        # stats.percentile definition's index scale
        target = (q / 100.0) * (self.count - 1)
        rank = int(math.floor(target + 0.5))  # nearest sample's rank
        cum = 0
        value = None
        # ascending value order: negatives (most negative first = largest
        # |x| bucket first), then zeros, then positives ascending
        for idx in sorted(self.neg_buckets, reverse=True):
            cum += self.neg_buckets[idx]
            if cum > rank:
                value = -self._representative(idx)
                break
        if value is None and self.zero:
            cum += self.zero
            if cum > rank:
                value = 0.0
        if value is None:
            for idx in sorted(self.buckets):
                cum += self.buckets[idx]
                if cum > rank:
                    value = self._representative(idx)
                    break
        if value is None:  # numerically impossible, but never under-report
            value = self.max
        if self.min is not None:
            value = max(value, self.min)
        if self.max is not None:
            value = min(value, self.max)
        return float(value)

    @property
    def mean(self):
        return self.sum / self.count if self.count else None

    def summary(self, quantiles=DEFAULT_QUANTILES):
        """JSON-able per-window quantile summary (the ``quantiles``
        block of a rollup record)."""
        out = {
            "count": self.count,
            "sum": self.sum,
            "min": self.min,
            "max": self.max,
            "mean": self.mean,
        }
        for q in quantiles:
            out[f"p{q:g}"] = self.percentile(q)
        return out

    def to_dict(self):
        """Full JSON-able state — what rollup records carry so a reader
        can re-merge shard sketches EXACTLY (JSON object keys must be
        strings, so bucket indices are stringified)."""
        return {
            "alpha": self.alpha,
            "count": self.count,
            "sum": self.sum,
            "min": self.min,
            "max": self.max,
            "zero": self.zero,
            "buckets": {str(i): c for i, c in sorted(self.buckets.items())},
            "neg_buckets": {
                str(i): c for i, c in sorted(self.neg_buckets.items())
            },
        }

    @classmethod
    def from_dict(cls, d):
        sk = cls(alpha=d.get("alpha", DEFAULT_ALPHA))
        sk.count = int(d.get("count", 0))
        sk.sum = float(d.get("sum", 0.0))
        sk.min = d.get("min")
        sk.max = d.get("max")
        sk.zero = int(d.get("zero", 0))
        sk.buckets = {int(i): int(c) for i, c in (d.get("buckets") or {}).items()}
        sk.neg_buckets = {
            int(i): int(c) for i, c in (d.get("neg_buckets") or {}).items()
        }
        return sk


class EwmaRate:
    """EWMA of a per-window rate, decayed by window width: after each
    closed window, ``ewma += (1 - exp(-window_s / tau)) * (rate - ewma)``
    — a time-constant smoother independent of window width choice."""

    __slots__ = ("tau_s", "value")

    def __init__(self, tau_s=30.0):
        self.tau_s = float(tau_s)
        self.value = None

    def update(self, rate, window_s):
        if self.value is None:
            self.value = float(rate)
        else:
            k = 1.0 - math.exp(-float(window_s) / self.tau_s)
            self.value += k * (float(rate) - self.value)
        return self.value


class RollupWindow:
    """One live tumbling window: counters + gauges + per-metric sketches."""

    __slots__ = ("start", "end", "counters", "gauges", "sketches", "late")

    def __init__(self, start, end):
        self.start = start
        self.end = end
        self.counters = {}
        self.gauges = {}  # name -> (last_t, last_value, min, max)
        self.sketches = {}
        self.late = 0

    def count(self, name, inc=1.0):
        self.counters[name] = self.counters.get(name, 0.0) + inc

    def gauge(self, t, name, value):
        prev = self.gauges.get(name)
        if prev is None:
            self.gauges[name] = [t, value, value, value]
            return
        if t >= prev[0]:
            prev[0], prev[1] = t, value
        prev[2] = min(prev[2], value)
        prev[3] = max(prev[3], value)

    def observe(self, name, value, alpha=DEFAULT_ALPHA):
        sk = self.sketches.get(name)
        if sk is None:
            sk = self.sketches[name] = QuantileSketch(alpha=alpha)
        sk.add(value)


class RollupBuilder:
    """The streaming aggregator one telemetry source owns.

    Feed methods take the SAMPLE timestamp ``t`` explicitly (record
    ``ts``, a completion clock — never "now"): a sample with
    ``t >= window_end`` first closes the current window (pushing its
    summary onto the bounded ``closed`` ring and emitting a ``rollup``
    record through ``metrics`` when attached), then opens the sample's
    own window. Samples OLDER than the current window (out-of-order
    arrivals across shard interleave) fold into the CURRENT window and
    bump its ``late`` counter — deterministic in stream order, and the
    lateness is visible rather than silently re-writing closed history.
    """

    def __init__(
        self,
        source,
        window_s=DEFAULT_WINDOW_S,
        ring=DEFAULT_RING,
        metrics=None,
        replica_id=None,
        alpha=DEFAULT_ALPHA,
        ewma_tau_s=30.0,
        quantiles=DEFAULT_QUANTILES,
        on_close=None,
    ):
        if window_s <= 0:
            raise ValueError(f"window_s must be positive, got {window_s!r}")
        self.source = source
        self.window_s = float(window_s)
        self.metrics = metrics
        self.replica_id = replica_id
        self.alpha = float(alpha)
        self.quantiles = tuple(quantiles)
        self.on_close = on_close  # callback(summary) — the SLO evaluator taps here
        self._ewma = {}  # counter name -> EwmaRate
        self._ewma_tau_s = float(ewma_tau_s)
        self._window = None
        self._seq = 0
        self._ring = int(ring)
        self.closed = []  # bounded ring of closed-window summaries

    # -- feeding ------------------------------------------------------------

    def _roll(self, t):
        w = self._window
        if w is None:
            start = math.floor(t / self.window_s) * self.window_s
            self._window = RollupWindow(start, start + self.window_s)
            return self._window
        if t >= w.end:
            self._close(w)
            start = math.floor(t / self.window_s) * self.window_s
            self._window = RollupWindow(start, start + self.window_s)
            return self._window
        if t < w.start:
            w.late += 1
        return w

    def count(self, t, name, inc=1.0):
        self._roll(t).count(name, inc)

    def gauge(self, t, name, value):
        self._roll(t).gauge(t, name, value)

    def observe(self, t, name, value):
        self._roll(t).observe(name, value, alpha=self.alpha)

    def flush(self):
        """Close the live window now (run end / summary time); no-op when
        nothing was fed since the last close."""
        if self._window is not None:
            self._close(self._window)
            self._window = None

    # -- closing ------------------------------------------------------------

    def _close(self, w):
        summary = self._summarize(w)
        self.closed.append(summary)
        if len(self.closed) > self._ring:
            del self.closed[: len(self.closed) - self._ring]
        self._seq += 1
        if self.metrics is not None:
            self.metrics.rollup(self.source, **summary)
        if self.on_close is not None:
            self.on_close(summary)
        return summary

    def _summarize(self, w):
        rates = {}
        for name, total in w.counters.items():
            rate = total / self.window_s
            ewma = self._ewma.get(name)
            if ewma is None:
                ewma = self._ewma[name] = EwmaRate(tau_s=self._ewma_tau_s)
            rates[name] = {"rate": rate, "ewma": ewma.update(rate, self.window_s)}
        return {
            "window_start": w.start,
            "window_end": w.end,
            "window_s": self.window_s,
            "seq": self._seq,
            "counters": dict(w.counters),
            "rates": rates,
            "gauges": {
                name: {"last": g[1], "min": g[2], "max": g[3]}
                for name, g in w.gauges.items()
            },
            "quantiles": {
                name: sk.summary(self.quantiles)
                for name, sk in w.sketches.items()
            },
            "sketches": {
                name: sk.to_dict() for name, sk in w.sketches.items()
            },
            "late": w.late,
            "replica_id": self.replica_id,
        }

    # -- live snapshot ------------------------------------------------------

    def snapshot(self):
        """The status() surface: the last CLOSED window summary plus the
        live (still-open) window's partial aggregates."""
        live = None
        if self._window is not None:
            live = self._summarize(self._window)
        return {
            "source": self.source,
            "window_s": self.window_s,
            "windows_closed": self._seq,
            "last_window": self.closed[-1] if self.closed else None,
            "live_window": live,
        }


# -- shard merging ----------------------------------------------------------


def merge_rollup_records(records, offsets=None):
    """Merge ``rollup`` records across ``.r*``/``.p*`` shards onto one
    timeline (module docstring).

    ``offsets`` maps ``replica_id`` to the PR 14 clock-offset estimate
    (either the bare ``offset_s`` float or the full
    ``tracing.clock_offsets`` dict per replica); a shard's window bounds
    are shifted by its offset, snapped to the nearest window boundary,
    and windows landing on the same (source, window_start) cell merge:
    counters/rates add, sketches merge exactly, gauges last-wins with a
    (window_end, replica_id) tie-break — independent of shard order.

    Returns the merged summaries sorted by (source, window_start).
    """
    offsets = offsets or {}
    cells = {}
    for rec in records:
        if rec.get("kind") != "rollup":
            continue
        rid = rec.get("replica_id")
        off = offsets.get(rid, 0.0)
        if isinstance(off, dict):
            off = off.get("offset_s", 0.0) or 0.0
        window_s = rec.get("window_s") or DEFAULT_WINDOW_S
        start = (rec.get("window_start") or 0.0) + off
        aligned = round(start / window_s) * window_s
        key = (rec.get("name"), aligned)
        cell = cells.get(key)
        if cell is None:
            cell = cells[key] = {
                "source": rec.get("name"),
                "window_start": aligned,
                "window_end": aligned + window_s,
                "window_s": window_s,
                "counters": {},
                "gauges": {},
                "sketches": {},
                "late": 0,
                "shards": 0,
                "replica_ids": [],
                "_gauge_order": {},
            }
        cell["shards"] += 1
        if rid not in cell["replica_ids"]:
            cell["replica_ids"].append(rid)
        cell["late"] += rec.get("late") or 0
        for name, total in (rec.get("counters") or {}).items():
            cell["counters"][name] = cell["counters"].get(name, 0.0) + total
        # gauge last-wins across shards, ordered by the shard's aligned
        # window_end then replica_id — NOT by shard read order
        order_key = (
            (rec.get("window_end") or 0.0) + off,
            -1 if rid is None else rid,
        )
        for name, g in (rec.get("gauges") or {}).items():
            prev_key = cell["_gauge_order"].get(name)
            prev = cell["gauges"].get(name)
            if prev is None or prev_key is None or order_key >= prev_key:
                merged = dict(g)
                if prev is not None:
                    merged["min"] = min(prev["min"], g["min"])
                    merged["max"] = max(prev["max"], g["max"])
                cell["gauges"][name] = merged
                cell["_gauge_order"][name] = order_key
            else:
                prev["min"] = min(prev["min"], g["min"])
                prev["max"] = max(prev["max"], g["max"])
        for name, sk_dict in (rec.get("sketches") or {}).items():
            sk = QuantileSketch.from_dict(sk_dict)
            have = cell["sketches"].get(name)
            if have is None:
                cell["sketches"][name] = sk
            else:
                have.merge(sk)
    out = []
    for key in sorted(cells, key=lambda k: (str(k[0]), k[1])):
        cell = cells[key]
        cell.pop("_gauge_order")
        cell["replica_ids"].sort(key=lambda r: -1 if r is None else r)
        window_s = cell["window_s"]
        cell["rates"] = {
            name: {"rate": total / window_s}
            for name, total in cell["counters"].items()
        }
        cell["quantiles"] = {
            name: sk.summary() for name, sk in cell["sketches"].items()
        }
        cell["sketches"] = {
            name: sk.to_dict() for name, sk in cell["sketches"].items()
        }
        out.append(cell)
    return out
