"""Data-layer tests: DP shard arithmetic, microbatch slicing, epoch arrays.

Unlike the reference's tests/test_dataset.py (which requires a downloaded
MNIST), these generate a tiny deterministic dataset in tmp_path.
"""

import numpy as np
import pytest

from shallowspeed_tpu.data import Dataset

N, DIM, CLASSES = 1000, 12, 10
GBS = 64


@pytest.fixture()
def data_dir(tmp_path):
    rng = np.random.RandomState(0)
    for suffix, n in (("train", N), ("val", 200)):
        x = rng.randn(n, DIM).astype(np.float32)
        y = np.eye(CLASSES, dtype=np.float32)[rng.randint(0, CLASSES, n)]
        np.save(tmp_path / f"x_{suffix}.npy", x)
        np.save(tmp_path / f"y_{suffix}.npy", y)
    return tmp_path


def test_drop_last_and_strided_shard(data_dir):
    ds = Dataset(data_dir, GBS, mubatch_size=16)
    ds.load(DP_rank=1, DP_size=2)
    full = (N // GBS) * GBS  # 960
    assert len(ds) == full // 2
    raw = np.load(data_dir / "x_train.npy")
    np.testing.assert_array_equal(ds.input_X, raw[1:full:2])


def test_shards_partition_the_data(data_dir):
    shards = []
    for r in range(4):
        ds = Dataset(data_dir, GBS, mubatch_size=4)
        ds.load(r, 4)
        shards.append(ds.input_X)
    raw = np.load(data_dir / "x_train.npy")[: (N // GBS) * GBS]
    recon = np.empty_like(raw)
    for r in range(4):
        recon[r::4] = shards[r]
    np.testing.assert_array_equal(recon, raw)


def test_mubatch_slicing_matches_epoch_arrays(data_dir):
    ds = Dataset(data_dir, GBS, mubatch_size=16)
    ds.load(0, 1)
    X, Y = ds.epoch_arrays()
    assert X.shape == (N // GBS, 4, 16, DIM)
    for b in (0, 3):
        for m in range(4):
            np.testing.assert_array_equal(X[b, m], ds.load_micro_batch_input(b, m))
            np.testing.assert_array_equal(Y[b, m], ds.load_micro_batch_target(b, m))


def test_divisibility_errors(data_dir):
    with pytest.raises(ValueError):
        Dataset(data_dir, GBS, mubatch_size=16).load(0, 3)  # 64 % 3 != 0
    with pytest.raises(ValueError):
        Dataset(data_dir, GBS, mubatch_size=7).load(0, 1)  # 7 ∤ 64


def test_validation_split(data_dir):
    ds = Dataset(data_dir, GBS, mubatch_size=GBS, validation=True)
    ds.load(0, 1)
    assert len(ds) == (200 // GBS) * GBS
