"""Single-chip tuning matrix: precision x microbatch-fusion x kernel backend.

For a healthy accelerator, sweeps the sequential-trainer configurations that
matter on the MXU and prints one JSON line per cell:

    {"config": "fused+default+pallas", "samples_per_sec": ..., "speedup_vs_ref_cfg": ...}

Reference cell: scanned microbatches + HIGHEST precision + XLA kernels (the
NumPy-parity configuration). Runs anywhere (CPU included) — on CPU it mostly
measures XLA CPU codegen, which is still useful for regression tracking.

All cells share one dataset upload and their slope-timing trials are
INTERLEAVED (bench.slope_epoch_seconds_many): the chip pool shows transient
multi-tenant contention, and cells measured minutes apart can have their
ratios inverted by a contention window — interleaving makes every in-matrix
ratio a same-window comparison.

    python scripts/bench_tpu_matrix.py --batches 116 --trials 3
"""

import argparse
import itertools
import json
import sys
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from shallowspeed_tpu.api import (  # the reference's canonical config
    FLAGSHIP_BATCH as B,
    FLAGSHIP_LR as LR,
    FLAGSHIP_MUBATCHES as M,
    FLAGSHIP_SIZES as SIZES,
)


# The full matrix: every (fused, precision, pallas) combination. The single
# cell enumeration shared by this CLI and scripts/tpu_capture.py.
ALL_CELLS = list(
    itertools.product((False, True), ("highest", "default"), (False, True))
)


def matrix_data(nb):
    """The shared (X, Y) epoch arrays every cell measures on."""
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    X = jnp.asarray(rng.rand(nb, M, B // M, SIZES[0]).astype(np.float32))
    Y = jnp.asarray(
        np.eye(SIZES[-1], dtype=np.float32)[rng.randint(0, SIZES[-1], (nb, M, B // M))]
    )
    return X, Y


def build_cell(fused, precision_name, pallas, X, Y):
    """Build + warm one cell's timing harness (bench.make_run_k). The pallas
    flag is a trace-time global: it must be set while the warmup call traces
    the program, after which the compiled executable keeps its kernels."""
    import jax
    import jax.numpy as jnp

    import bench
    from shallowspeed_tpu import model as Mo
    from shallowspeed_tpu import ops, trainer
    from shallowspeed_tpu.api import PRECISIONS
    from shallowspeed_tpu.optimizer import SGD

    ops.set_pallas(pallas)
    try:
        spec = Mo.make_model_spec(SIZES, 1, B)
        params = jax.tree.map(jnp.asarray, Mo.init_model(spec))
        epoch = trainer.make_train_epoch(
            spec, SGD(LR), precision=PRECISIONS[precision_name], fuse_mubatches=fused
        )
        return bench.make_run_k(epoch, params, (), X, Y)
    finally:
        ops.set_pallas(False)


def run_matrix(cells, nb, trials):
    """Measure the given (fused, precision, pallas) cells with interleaved
    trials on shared data. Returns {cell_tuple: samples_per_sec}; a cell the
    estimator refuses to resolve (contention) is reported on stderr and
    omitted rather than discarding the other cells' completed measurements.
    """
    import bench

    X, Y = matrix_data(nb)
    run_ks = {}
    for fused, prec, pallas in cells:
        key = (
            "fused" if fused else "scanned",
            prec,
            "pallas" if pallas else "xla",
        )
        run_ks[key] = build_cell(fused, prec, pallas, X, Y)
        print(f"  built {'+'.join(key)}", file=sys.stderr, flush=True)
    failures = {}
    slopes = bench.slope_epoch_seconds_many(run_ks, trials=trials, failures=failures)
    for key, err in failures.items():
        print(f"  UNRESOLVED {'+'.join(key)}: {err}", file=sys.stderr, flush=True)
    samples_per_epoch = nb * B
    return {key: samples_per_epoch / s for key, s in slopes.items()}


def measure(fused, precision_name, pallas, nb, trials):
    """Single-cell measurement (non-interleaved) — kept for one-off
    regression checks; the matrix path goes through run_matrix."""
    import bench

    X, Y = matrix_data(nb)
    run_k = build_cell(fused, precision_name, pallas, X, Y)
    return nb * B / bench.slope_epoch_seconds(run_k, trials=trials)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batches", type=int, default=116)
    ap.add_argument(
        "--trials",
        type=int,
        default=3,
        help="slope-timing trials per cell, interleaved across cells; leg "
        "sizes are ADAPTED per cell until device time resolves above the "
        "transport constants — on a high-RTT tunnel legs can grow to "
        "hundreds/thousands of epochs (bench.slope_epoch_seconds_many)",
    )
    ap.add_argument("--skip-pallas", action="store_true")
    args = ap.parse_args()

    cells = [
        c for c in ALL_CELLS if not (c[2] and args.skip_pallas)
    ]
    results = run_matrix(cells, args.batches, args.trials)
    ref_key = ("scanned", "highest", "xla")
    for key, sps in results.items():
        print(
            json.dumps(
                {
                    "config": "+".join(key),
                    "samples_per_sec": round(sps, 1),
                    "speedup_vs_ref_cfg": round(sps / results[ref_key], 3)
                    if ref_key in results
                    else None,
                }
            ),
            flush=True,
        )


if __name__ == "__main__":
    main()
