"""SPMD executor tests on the 8-virtual-device CPU mesh.

The correctness bar is the reference's own: every distributed layout must
reproduce SEQUENTIAL training (SURVEY §3.3 — the three-sums gradient ledger),
and DP replicas must end bit-identical. These run the real shard_map +
ppermute + psum code paths, which the reference never covered with tests at
all (its multi-process checks were runtime asserts only).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shallowspeed_tpu import model as Mo
from shallowspeed_tpu import schedules as S
from shallowspeed_tpu import trainer, utils
from shallowspeed_tpu.optimizer import SGD
from shallowspeed_tpu.parallel import executor as E
from shallowspeed_tpu.parallel import lower_schedule, make_mesh

SIZES = (784, 128, 127, 126, 125, 124, 123, 10)  # flagship, uneven stages
SMALL = (24, 20, 18, 16, 14, 12, 11, 10)  # same shape class, faster
B, M, LR = 64, 4, 0.01
NB = 3  # batches


def _data(sizes, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(NB, B, sizes[0]).astype(np.float32)
    Y = np.eye(sizes[-1], dtype=np.float32)[rng.randint(0, sizes[-1], (NB, B))]
    return X, Y


def _sequential_params(sizes, X, Y):
    spec = Mo.make_model_spec(sizes, 1, B)
    params = jax.tree.map(jnp.asarray, Mo.init_model(spec))
    step = trainer.make_train_step(spec, SGD(LR))
    st = ()
    for i in range(NB):
        params, st = step(
            params,
            st,
            jnp.asarray(X[i].reshape(M, B // M, sizes[0])),
            jnp.asarray(Y[i].reshape(M, B // M, sizes[-1])),
        )
    return [l for stage in params for l in stage]


def _pipeline_params(sizes, X, Y, dp, pp, sched_cls, use_epoch=False):
    mesh = make_mesh(dp, pp)
    spec = Mo.make_model_spec(sizes, pp, B)
    prog = lower_schedule(sched_cls, M, pp)
    stacked, flags = E.init_stacked(spec, mesh)
    mb_sz = B // dp // M
    if use_epoch:
        epoch = E.make_pipeline_epoch(mesh, spec, prog, mb_sz, SGD(LR))
        stacked, _, _ = epoch(stacked, flags, (), jnp.asarray(X), jnp.asarray(Y))
    else:
        step = E.make_pipeline_step(mesh, spec, prog, mb_sz, SGD(LR))
        for i in range(NB):
            stacked, _, _ = step(stacked, flags, (), jnp.asarray(X[i]), jnp.asarray(Y[i]))
    return stacked, spec, flags, mesh


def _assert_matches_sequential(sizes, stacked, spec, rtol=3e-4, atol=3e-6):
    X, Y = _data(sizes)
    want = _sequential_params(sizes, X, Y)
    got = [l for stage in E.unstack_params(stacked, spec) for l in stage]
    assert len(want) == len(got)
    for a, b in zip(want, got):
        np.testing.assert_allclose(np.asarray(a["W"]), b["W"], rtol=rtol, atol=atol)
        np.testing.assert_allclose(
            np.asarray(a["b"]).reshape(-1), b["b"].reshape(-1), rtol=rtol, atol=atol
        )


LAYOUTS = [
    (1, 1, S.GPipeSchedule),
    (4, 1, S.NaiveParallelSchedule),
    (8, 1, S.GPipeSchedule),
    (1, 4, S.NaiveParallelSchedule),
    (1, 4, S.GPipeSchedule),
    (1, 4, S.PipeDreamFlushSchedule),
    (2, 4, S.GPipeSchedule),
    (2, 4, S.PipeDreamFlushSchedule),
    (2, 2, S.NaiveParallelSchedule),
]


@pytest.mark.parametrize("dp,pp,sched", LAYOUTS)
def test_layout_equals_sequential(dp, pp, sched):
    """The headline invariant: any DP x PP x schedule == sequential."""
    X, Y = _data(SMALL)
    stacked, spec, _, _ = _pipeline_params(SMALL, X, Y, dp, pp, sched)
    _assert_matches_sequential(SMALL, stacked, spec)


def test_pp8_with_linear_on_last_stage_equals_sequential():
    """PP=8 parity needs a size list whose last stage owns a Linear: with
    exactly 8 sizes the reference's partitioning gives the last stage zero
    Linears, so its 'no relu on the final Linear' rule never fires and the
    PP=8 model architecturally differs from sequential (reference
    layers.py:253-257 — a faithful quirk, covered in test_model). 16 sizes
    give stage 7 a real Linear and exact parity."""
    sizes16 = (24, 22, 21, 20, 19, 18, 17, 16, 16, 15, 14, 13, 13, 12, 11, 10)
    X, Y = _data(sizes16)
    stacked, spec, _, _ = _pipeline_params(sizes16, X, Y, 1, 8, S.GPipeSchedule)
    _assert_matches_sequential(sizes16, stacked, spec)


def test_flagship_dp2_pp4_gpipe_equals_sequential():
    """Full-size model (784-wide, uneven 2/2/2/1 stages) on the full mesh."""
    X, Y = _data(SIZES)
    stacked, spec, _, _ = _pipeline_params(SIZES, X, Y, 2, 4, S.GPipeSchedule)
    _assert_matches_sequential(SIZES, stacked, spec)


def test_pallas_kernel_backend_matches_xla_on_mesh():
    """The executor's Pallas backend (flag-operand fused kernels, the traced
    relu flag as a kernel operand) must reproduce the XLA backend bit-for-bit
    on the mesh path: same dots at the same precision, flag-selected relu.
    Interpret mode off-TPU, the real kernels on hardware — same contract."""
    X, Y = _data(SMALL)
    mesh = make_mesh(2, 4)
    spec = Mo.make_model_spec(SMALL, 4, B)
    prog = lower_schedule(S.GPipeSchedule, M, 4)
    mb_sz = B // 2 // M
    results = {}
    for kb in ("xla", "pallas"):
        stacked, flags = E.init_stacked(spec, mesh)
        step = E.make_pipeline_step(mesh, spec, prog, mb_sz, SGD(LR), kernel_backend=kb)
        losses = []
        for i in range(NB):
            stacked, _, loss = step(
                stacked, flags, (), jnp.asarray(X[i]), jnp.asarray(Y[i])
            )
            losses.append(float(loss))
        results[kb] = (jax.device_get(stacked), losses)
    assert results["xla"][1] == results["pallas"][1]
    for a, b in zip(
        jax.tree.leaves(results["xla"][0]), jax.tree.leaves(results["pallas"][0])
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_pallas_kernel_backend_tiled_slots_match_xla(monkeypatch):
    """Slots beyond the single-block VMEM budget no longer reject the pallas
    backend: they auto-dispatch to the grid-tiled flag kernels. Budget
    forced to 0 so EVERY slot takes the tiled path. Tolerance, not
    bit-equality: tiling pads the contraction dim to a tile boundary, which
    reassociates the dot's reduction tree vs XLA's unpadded dot (adding
    exact zeros is a no-op, but the grouping of the NONZERO partial sums
    changes) — same reason TestTiledKernels uses allclose. Bit-identity
    holds for the single-block regime (test_pallas_kernel_backend_matches_
    xla_on_mesh); multi-tile contraction math is covered at kernel level in
    test_pallas_ops.TestTiledFlagKernels."""
    from shallowspeed_tpu import pallas_ops

    monkeypatch.setattr(pallas_ops, "SINGLE_BLOCK_BUDGET_BYTES", 0)
    monkeypatch.setattr(pallas_ops, "TILE", 128)
    X, Y = _data(SMALL)
    mesh = make_mesh(1, 2)
    spec = Mo.make_model_spec(SMALL, 2, B)
    prog = lower_schedule(S.GPipeSchedule, M, 2)
    mb_sz = B // M
    results = {}
    for kb in ("xla", "pallas"):
        stacked, flags = E.init_stacked(spec, mesh)
        step = E.make_pipeline_step(mesh, spec, prog, mb_sz, SGD(LR), kernel_backend=kb)
        losses = []
        for i in range(NB):
            stacked, _, loss = step(
                stacked, flags, (), jnp.asarray(X[i]), jnp.asarray(Y[i])
            )
            losses.append(float(loss))
        results[kb] = (jax.device_get(stacked), losses)
    np.testing.assert_allclose(
        results["xla"][1], results["pallas"][1], rtol=1e-6, atol=0
    )
    for a, b in zip(
        jax.tree.leaves(results["xla"][0]), jax.tree.leaves(results["pallas"][0])
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7
        )


def test_epoch_scan_matches_per_batch():
    X, Y = _data(SMALL)
    a, spec, _, _ = _pipeline_params(SMALL, X, Y, 2, 4, S.GPipeSchedule)
    b, _, _, _ = _pipeline_params(SMALL, X, Y, 2, 4, S.GPipeSchedule, use_epoch=True)
    ua = [l for st in E.unstack_params(a, spec) for l in st]
    ub = [l for st in E.unstack_params(b, spec) for l in st]
    for x, y in zip(ua, ub):
        np.testing.assert_allclose(x["W"], y["W"], rtol=1e-6, atol=1e-7)


def test_schedules_agree_with_each_other():
    """naive, gpipe and pipedream must produce identical updates — they
    reorder the same microbatch work."""
    X, Y = _data(SMALL)
    results = []
    for sched in (S.NaiveParallelSchedule, S.GPipeSchedule, S.PipeDreamFlushSchedule):
        stacked, spec, _, _ = _pipeline_params(SMALL, X, Y, 1, 4, sched)
        results.append([l for st in E.unstack_params(stacked, spec) for l in st])
    for other in results[1:]:
        for a, b in zip(results[0], other):
            np.testing.assert_allclose(a["W"], b["W"], rtol=1e-5, atol=1e-7)


def test_dp_replicas_stay_in_sync():
    X, Y = _data(SMALL)
    stacked, spec, flags, mesh = _pipeline_params(SMALL, X, Y, 4, 2, S.GPipeSchedule)
    utils.assert_dp_replicas_in_sync(stacked)


def test_padded_regions_stay_zero():
    """The zero-padding invariant after real training steps (per-slot stacks)."""
    X, Y = _data(SMALL)
    stacked, spec, _, _ = _pipeline_params(SMALL, X, Y, 2, 4, S.GPipeSchedule)
    Ws = [np.asarray(jax.device_get(w)) for w in stacked["W"]]
    bs = [np.asarray(jax.device_get(b)) for b in stacked["b"]]
    for s, sspec in enumerate(spec.stages):
        for l in range(len(Ws)):
            if l < sspec.n_linears:
                out_d, in_d = sspec.local_sizes[l + 1], sspec.local_sizes[l]
                block = Ws[l][s].copy()
                block[:out_d, :in_d] = 0
                assert (block == 0).all(), f"stage {s} layer {l} leaked outside block"
                assert (bs[l][s, out_d:] == 0).all()
            else:
                assert (Ws[l][s] == 0).all() and (bs[l][s] == 0).all()


def test_pipeline_inference_matches_sequential_predict():
    X, Y = _data(SMALL)
    mesh = make_mesh(2, 4)
    spec = Mo.make_model_spec(SMALL, 4, B)
    eval_prog = lower_schedule(S.InferenceSchedule, M, 4, training=False)
    stacked, flags = E.init_stacked(spec, mesh)
    eval_step = E.make_pipeline_step(mesh, spec, eval_prog, B // 2 // M)
    preds = eval_step(stacked, flags, jnp.asarray(X[0]))

    spec1 = Mo.make_model_spec(SMALL, 1, B)
    params1 = jax.tree.map(jnp.asarray, Mo.init_model(spec1))
    want = trainer.make_predict(spec1)(params1, jnp.asarray(X[0]))
    np.testing.assert_allclose(
        np.asarray(preds)[:, : SMALL[-1]], np.asarray(want), rtol=2e-4, atol=1e-5
    )
    assert (np.asarray(preds)[:, SMALL[-1] :] == 0).all()


def test_tick_and_batch_unroll_bit_identical():
    """Scan unroll factors are scheduling-only: identical results."""
    X, Y = _data(SMALL)
    mesh = make_mesh(2, 4)
    spec = Mo.make_model_spec(SMALL, 4, B)
    prog = lower_schedule(S.GPipeSchedule, M, 4)
    outs = []
    for unroll, tick_unroll in ((1, 1), (2, 4)):
        stacked, flags = E.init_stacked(spec, mesh)
        epoch = E.make_pipeline_epoch(
            mesh, spec, prog, B // 2 // M, SGD(LR),
            unroll=unroll, tick_unroll=tick_unroll,
        )
        stacked, _, loss = epoch(stacked, flags, (), jnp.asarray(X), jnp.asarray(Y))
        outs.append((E.unstack_params(stacked, spec), float(loss)))
    assert outs[0][1] == outs[1][1]
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(a, b), outs[0][0], outs[1][0]
    )


def test_train_loss_decreases():
    rng = np.random.RandomState(7)
    labels = rng.randint(0, 10, (8, B))
    centers = rng.randn(10, SMALL[0]).astype(np.float32) * 2
    X = np.stack([centers[lb] + 0.1 * rng.randn(B, SMALL[0]).astype(np.float32) for lb in labels])
    Y = np.eye(10, dtype=np.float32)[labels]
    mesh = make_mesh(2, 4)
    spec = Mo.make_model_spec(SMALL, 4, B)
    prog = lower_schedule(S.GPipeSchedule, M, 4)
    stacked, flags = E.init_stacked(spec, mesh)
    step = E.make_pipeline_step(mesh, spec, prog, B // 2 // M, SGD(0.05))
    losses = []
    for e in range(6):
        for i in range(8):
            stacked, _, loss = step(stacked, flags, (), jnp.asarray(X[i]), jnp.asarray(Y[i]))
        losses.append(float(loss))
    assert all(b < a for a, b in zip(losses, losses[1:])), losses
    assert losses[-1] < losses[0] - 5e-3, losses


def test_relay_width_is_true_boundary_maximum():
    """The pp-axis payload/mailbox width must be the widest inter-stage
    boundary, not the model input width (VERDICT round-1 weak #2: sizing to
    D_in=784 shipped ~6x the needed bytes per tick)."""
    from shallowspeed_tpu.api import FLAGSHIP_SIZES

    spec = Mo.make_model_spec(FLAGSHIP_SIZES, 4, B)
    w = E.relay_width(spec)
    assert w == max(s.in_dim for s in spec.stages[1:])
    assert w == 127  # stage boundaries 127/125/123 — and far below 784
    assert w < spec.stages[0].in_dim
    # degenerate single-stage model: no boundary to relay
    assert E.relay_width(Mo.make_model_spec((8, 4), 1, B)) == 1
