"""Pipeline schedules as pure instruction streams.

This preserves the reference's best abstraction (pipe.py:12-299): a schedule
is trace-time *data* — a generator of steps, each step a list of small
dataclass instructions — with zero knowledge of communication or arrays. The
TPU twist is what consumes them: instead of an MPI-interpreting Worker, the
``parallel.lowering`` module compiles the per-stage instruction streams into a
static clock-tick program executed SPMD under shard_map (MPMD -> SPMD).

Instruction set parity (reference pipe.py:12-138): ZeroGrad, OptimizerStep,
Recv/SendActivations, Recv/SendOutputGrad/InputGrad, Forward,
BackwardGradAcc, BackwardGradAllReduce, LoadMuBatchInput/Target — plus the
split-backward trio beyond the reference (``backward_split=True``):
BackwardInputGradAcc (the relay-critical dx half, at the combined
backward's tick), BackwardWeightGradAcc (the deferrable dW/db half, packed
into bubble ticks by the lowering) and BackwardWeightGradAllReduce (the
DP-sync anchor, moved to the final weight half).

Schedules: Naive (pipe.py:184-222), GPipe (pipe.py:225-272), Inference
(pipe.py:275-294) — and PipeDream-Flush (1F1B), which the reference declares
but leaves as a ``raise NotImplementedError`` stub (pipe.py:297-299); here it
is fully implemented.
"""

import dataclasses
from abc import ABC, abstractmethod


# ---------------------------------------------------------------------------
# Instruction set: the schedule <-> executor contract.
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Instruction:
    pass


@dataclasses.dataclass(frozen=True)
class ZeroGrad(Instruction):
    """Reset gradient accumulators (start of every training batch)."""


@dataclasses.dataclass(frozen=True)
class OptimizerStep(Instruction):
    """Apply the optimizer update (end of every training batch)."""


@dataclasses.dataclass(frozen=True)
class BufferInstruction(Instruction):
    buffer_id: int = 0


@dataclasses.dataclass(frozen=True)
class RecvActivations(BufferInstruction):
    """Receive the forward activations of a microbatch from stage-1."""


@dataclasses.dataclass(frozen=True)
class SendActivations(BufferInstruction):
    """Send this stage's forward output for a microbatch to stage+1."""


@dataclasses.dataclass(frozen=True)
class RecvOutputGrad(BufferInstruction):
    """Receive d(loss)/d(stage output) for a microbatch from stage+1."""


@dataclasses.dataclass(frozen=True)
class SendInputGrad(BufferInstruction):
    """Send d(loss)/d(stage input) for a microbatch to stage-1."""


@dataclasses.dataclass(frozen=True)
class ComputeInstruction(Instruction):
    buffer_id: int = 0
    mubatch_id: int = 0
    chunk_id: int = 0  # virtual-stage chunk on this device (interleaved only)


@dataclasses.dataclass(frozen=True)
class Forward(ComputeInstruction):
    """Forward one microbatch through the local stage, stashing residuals."""


@dataclasses.dataclass(frozen=True)
class RecomputeForward(ComputeInstruction):
    """Activation recompute (torchgpipe, arxiv 2004.09910): re-run the local
    stage forward for one microbatch from the stashed STAGE INPUT — the
    character-identical forward expressions — re-materializing the per-slot
    residuals right before the backward consumes them. Emitted only under
    ``Schedule(recompute=True)``, immediately ahead of each backward step;
    no messages in or out (the input was stashed at the forward tick)."""


@dataclasses.dataclass(frozen=True)
class BackwardGradAcc(ComputeInstruction):
    """Backward one microbatch, accumulating into the gradient buffers."""


@dataclasses.dataclass(frozen=True)
class BackwardGradAllReduce(ComputeInstruction):
    """Backward + DP gradient all-reduce. Appears exactly once per batch, on
    the final backward microbatch — it marks WHERE the cross-replica psum is
    allowed to overlap the remaining backward compute (reference
    pipe.py:108-122, 302-327). The SPMD executor lowers it to jax.lax.psum
    over the ``dp`` mesh axis; XLA's latency-hiding scheduler provides the
    compute/communication overlap the reference hand-rolls with Iallreduce."""


@dataclasses.dataclass(frozen=True)
class BackwardInputGradAcc(ComputeInstruction):
    """The relay-critical HALF of a split backward (2BP, arxiv 2405.18047):
    compute d(loss)/d(stage input) for one microbatch — dx from W and the
    relu masks only — and stash the per-slot effective output-grads for the
    deferred weight half. This is the only backward product the upstream
    stage waits for, so it runs (and relays, via a following SendInputGrad)
    at exactly the tick the combined backward would have."""


@dataclasses.dataclass(frozen=True)
class BackwardWeightGradAcc(ComputeInstruction):
    """The deferrable HALF of a split backward: dW/db for one microbatch
    from the stashed activation and the stashed output-grad, accumulated
    into the gradient buffers. No messages in or out — the lowering packs
    these greedily into otherwise-idle bubble ticks, preserving the
    per-stage accumulation order of the combined schedule (so the fp sum,
    and therefore the weight hash, is bit-identical)."""


@dataclasses.dataclass(frozen=True)
class BackwardWeightGradAllReduce(BackwardWeightGradAcc):
    """Split-schedule DP-sync anchor: the FINAL weight-grad compute of the
    batch. In a split schedule the gradient is not complete until the last
    deferred B-weight lands, so the all-reduce anchor moves here from the
    final backward (every B-weight completes before the dp psum)."""


@dataclasses.dataclass(frozen=True)
class LoadInstruction(Instruction):
    mubatch_id: int = 0
    buffer_id: int = 0


@dataclasses.dataclass(frozen=True)
class LoadMuBatchInput(LoadInstruction):
    """First stage only: load a microbatch of inputs into the input buffer."""


@dataclasses.dataclass(frozen=True)
class LoadMuBatchTarget(LoadInstruction):
    """Last stage only: load a microbatch of targets into the output buffer
    (the backward pass consumes targets where upstream grads would sit)."""


# ---------------------------------------------------------------------------
# Schedule ABC (reference pipe.py:141-181).
# ---------------------------------------------------------------------------


class Schedule(ABC):
    """Emits, for ONE pipeline stage, an ordered stream of instruction steps.

    Pure data: no arrays, no communication — which is exactly why it can be
    unit-tested stream-wise and compiled to a clock-tick program.
    """

    def __init__(
        self,
        num_micro_batches: int,
        num_stages: int,
        stage_id: int,
        backward_split: bool = False,
        recompute: bool = False,
    ):
        assert num_micro_batches > 0 and num_stages > 0
        assert 0 <= stage_id < num_stages
        self.num_micro_batches = num_micro_batches
        self.num_stages = num_stages
        self.stage_id = stage_id
        # two-stage backward: emit BackwardInputGradAcc + a deferred
        # BackwardWeightGradAcc per microbatch instead of the combined
        # Backward (the lowering packs the weight halves into bubble ticks)
        self.backward_split = backward_split
        # activation recompute: the forward stashes only the stage INPUT;
        # a RecomputeForward re-materializes the residuals right before
        # each backward step (torchgpipe trade: FLOPs for stash peak)
        self.recompute = recompute

    @abstractmethod
    def steps(self):
        """Yield lists of Instructions, in per-stage program order."""

    @property
    def is_first_stage(self):
        return self.stage_id == 0

    @property
    def is_last_stage(self):
        return self.stage_id == self.num_stages - 1

    def is_first_mubatch(self, mubatch_id):
        return mubatch_id == 0

    def is_last_mubatch(self, mubatch_id):
        return mubatch_id == self.num_micro_batches - 1

    # -- shared step builders ------------------------------------------------

    def _fwd_step(self, mb):
        cmds = []
        if self.is_first_stage:
            cmds.append(LoadMuBatchInput(mubatch_id=mb))
        else:
            cmds.append(RecvActivations())
        cmds.append(Forward(mubatch_id=mb))
        return cmds

    def _fwd_step_send(self, mb):
        """Forward step that relays activations downstream; the last stage
        discards its forward output — backward needs only targets + residuals
        (reference pipe.py:262-266)."""
        cmds = self._fwd_step(mb)
        if not self.is_last_stage:
            cmds.append(SendActivations())
        return cmds

    def _bwd_compute(self, mb, allreduce):
        """The backward compute (+ input-grad send) for one microbatch —
        combined, or the split B-input/B-weight pair. The send always
        follows the compute that produces dx (B-input when split), and the
        DP-sync anchor rides the final backward's WEIGHT half when split
        (the gradient is not complete until the last deferred B-weight)."""
        cmds = []
        if self.backward_split:
            cmds.append(BackwardInputGradAcc(mubatch_id=mb))
            if not self.is_first_stage:
                cmds.append(SendInputGrad())
            wcls = BackwardWeightGradAllReduce if allreduce else BackwardWeightGradAcc
            cmds.append(wcls(mubatch_id=mb))
        else:
            cls = BackwardGradAllReduce if allreduce else BackwardGradAcc
            cmds.append(cls(mubatch_id=mb))
            if not self.is_first_stage:
                cmds.append(SendInputGrad())
        return cmds

    def _bwd_step(self, mb, allreduce):
        cmds = []
        if self.recompute:
            # re-materialize the residuals FIRST: the recompute binds no
            # messages (its input was stashed at the forward tick), so the
            # Recv/Load that follows still binds to the backward compute
            cmds.append(RecomputeForward(mubatch_id=mb))
        if self.is_last_stage:
            cmds.append(LoadMuBatchTarget(mubatch_id=mb))
        else:
            cmds.append(RecvOutputGrad())
        cmds.extend(self._bwd_compute(mb, allreduce))
        return cmds


class NaiveParallelSchedule(Schedule):
    """One microbatch fully forward AND backward at a time; only one stage is
    active at any moment (reference pipe.py:184-222)."""

    def steps(self):
        yield [ZeroGrad()]
        for mb in range(self.num_micro_batches):
            cmds = self._fwd_step(mb)
            if not self.is_last_stage:
                cmds.append(SendActivations())
            if self.recompute:
                # same contract as _bwd_step: re-materialize residuals
                # ahead of the Recv/Load that binds to the backward
                cmds.append(RecomputeForward(mubatch_id=mb))
            if self.is_last_stage:
                cmds.append(LoadMuBatchTarget(mubatch_id=mb))
            else:
                cmds.append(RecvOutputGrad())
            cmds.extend(self._bwd_compute(mb, self.is_last_mubatch(mb)))
            yield cmds
        yield [OptimizerStep()]


class GPipeSchedule(Schedule):
    """All microbatches forward, then all backward in reverse order
    (reference pipe.py:225-272). The DP all-reduce interleaves into the LAST
    executed backward, which is microbatch 0."""

    def steps(self):
        yield [ZeroGrad()]
        for mb in range(self.num_micro_batches):
            yield self._fwd_step_send(mb)
        for mb in reversed(range(self.num_micro_batches)):
            yield self._bwd_step(mb, allreduce=self.is_first_mubatch(mb))
        yield [OptimizerStep()]


class PipeDreamFlushSchedule(Schedule):
    """PipeDream-Flush / 1F1B with a full flush per batch — same weight-update
    semantics as GPipe (synchronous, one optimizer step per batch) but peak
    activation memory of min(M, depth - stage) microbatches instead of M.

    The reference registers this schedule in its CLI but leaves the class an
    unimplemented stub (pipe.py:297-299, train.py:50-54); this is the real
    thing. Structure per stage: warmup of ``min(depth - 1 - stage, M)``
    forwards, then 1F1B steady state, then the remaining backwards (flush).
    """

    def steps(self):
        yield [ZeroGrad()]
        M = self.num_micro_batches
        warmup = min(self.num_stages - 1 - self.stage_id, M)
        # warmup forwards
        for mb in range(warmup):
            yield self._fwd_step_send(mb)
        # steady state: one forward, one backward
        fwd_mb, bwd_mb = warmup, 0
        while fwd_mb < M:
            yield self._fwd_step_send(fwd_mb)
            yield self._bwd_step(bwd_mb, allreduce=bwd_mb == M - 1)
            fwd_mb += 1
            bwd_mb += 1
        # cooldown/flush: drain the remaining backwards
        while bwd_mb < M:
            yield self._bwd_step(bwd_mb, allreduce=bwd_mb == M - 1)
            bwd_mb += 1
        yield [OptimizerStep()]


class InferenceSchedule(Schedule):
    """Forward-only relay for validation/accuracy (reference pipe.py:275-294)."""

    def steps(self):
        for mb in range(self.num_micro_batches):
            yield self._fwd_step_send(mb)


# ---------------------------------------------------------------------------
# Interleaved (virtual-stage) schedules — beyond the reference.
# ---------------------------------------------------------------------------


class InterleavedSchedule(Schedule):
    """Megatron-style interleaved pipeline: S = P x V model stages on P
    devices, stage ``s`` on device ``s mod P`` as virtual chunk ``s // P``.
    The reference has nothing like this (its Worker owns exactly one stage,
    pipe.py:330-353); on TPU it is a natural fit because EVERY stage-to-stage
    link — including the device-(P-1) -> device-0 wraps between chunks —
    becomes the same ring ``ppermute`` shift over the ``pp`` axis.

    This class emits per-DEVICE streams (stage_id is the device id), with
    ``chunk_id`` on each compute naming the virtual stage. Schedule shape is
    1F1B over (chunk, microbatch) pairs in Megatron's order — microbatches
    grouped P at a time, each group pushed through every chunk before the
    next group starts — which shrinks the pipeline-fill bubble by ~V versus
    giving each device one fat stage. Requires M % P == 0 (same restriction
    as Megatron's interleaved mode).

    Subclasses set ``num_chunks`` via the constructor (V=1 degenerates to
    PipeDream-Flush over P stages).
    """

    def __init__(self, num_micro_batches, num_stages, stage_id, num_chunks=2):
        super().__init__(num_micro_batches, num_stages, stage_id)
        if num_micro_batches % num_stages != 0:
            raise ValueError(
                f"interleaved schedule needs M % P == 0 "
                f"(got M={num_micro_batches}, P={num_stages})"
            )
        assert num_chunks >= 1
        self.num_chunks = num_chunks

    # (chunk, microbatch) of the k-th forward in device execution order
    def _fwd_k(self, k):
        P = self.num_stages
        return (k // P) % self.num_chunks, (k // (P * self.num_chunks)) * P + k % P

    # backwards run chunks in reverse
    def _bwd_k(self, k):
        P = self.num_stages
        c = self.num_chunks - 1 - (k // P) % self.num_chunks
        return c, (k // (P * self.num_chunks)) * P + k % P

    def _is_input_end(self, chunk):
        return self.is_first_stage and chunk == 0

    def _is_head_end(self, chunk):
        return self.is_last_stage and chunk == self.num_chunks - 1

    def _ifwd(self, k):
        c, mb = self._fwd_k(k)
        cmds = []
        if self._is_input_end(c):
            cmds.append(LoadMuBatchInput(mubatch_id=mb))
        else:
            cmds.append(RecvActivations())
        cmds.append(Forward(mubatch_id=mb, chunk_id=c))
        if not self._is_head_end(c):
            cmds.append(SendActivations())
        return cmds

    def _ibwd(self, k, total):
        c, mb = self._bwd_k(k)
        cmds = []
        if self._is_head_end(c):
            cmds.append(LoadMuBatchTarget(mubatch_id=mb))
        else:
            cmds.append(RecvOutputGrad())
        cls = BackwardGradAllReduce if k == total - 1 else BackwardGradAcc
        cmds.append(cls(mubatch_id=mb, chunk_id=c))
        if not self._is_input_end(c):
            cmds.append(SendInputGrad())
        return cmds

    def steps(self):
        P, V, M = self.num_stages, self.num_chunks, self.num_micro_batches
        total = M * V
        # Megatron warmup: enough forwards to fill the pipeline ahead of the
        # first backward, shrunk by rank and grown by (V-1) microbatch groups
        warmup = min((P - self.stage_id - 1) * 2 + (V - 1) * P, total)
        yield [ZeroGrad()]
        for k in range(warmup):
            yield self._ifwd(k)
        fwd_k, bwd_k = warmup, 0
        while fwd_k < total:
            yield self._ifwd(fwd_k)
            yield self._ibwd(bwd_k, total)
            fwd_k += 1
            bwd_k += 1
        while bwd_k < total:
            yield self._ibwd(bwd_k, total)
            bwd_k += 1
        yield [OptimizerStep()]


class InterleavedInferenceSchedule(InterleavedSchedule):
    """Forward-only relay over virtual chunks (interleaved accuracy path).
    No M % P restriction — there is no 1F1B steady state to group for, so
    microbatches simply stream through the chunks in stage order."""

    def __init__(self, num_micro_batches, num_stages, stage_id, num_chunks=2):
        Schedule.__init__(self, num_micro_batches, num_stages, stage_id)
        assert num_chunks >= 1
        self.num_chunks = num_chunks

    def _fwd_k(self, k):
        M = self.num_micro_batches
        return k // M, k % M

    def steps(self):
        for k in range(self.num_micro_batches * self.num_chunks):
            yield self._ifwd(k)


SCHEDULES = {
    "naive": NaiveParallelSchedule,
    "gpipe": GPipeSchedule,
    "pipedream": PipeDreamFlushSchedule,
    "interleaved": InterleavedSchedule,
}


def flat_commands(schedule: Schedule):
    """The stage's instruction stream flattened to a single command list."""
    return [cmd for step in schedule.steps() for cmd in step]
