"""Numerics provenance tests: the per-layer digest stream, first-divergence
attribution, and the checkpoint-bisect replay (observability/divergence.py,
docs/numerics.md "Divergence debugging").

The load-bearing invariants:

- the in-program digest aux (fused scan aux, psum'd over the mesh) equals
  ``utils.layer_digests`` — the host reference over the logical blocks —
  exactly (crc) / to float tolerance (norms) on EVERY layout, so a digest
  row is layout-independent evidence;
- the digest block definition is THE ``model_hash`` block definition
  (satellite: one shared ``iter_param_blocks``), pinned by literal hash;
- ``digests=False`` changes nothing: the uninstrumented program trains to
  the instrumented twin's exact bits;
- a ``flip@step=N`` injection — finite, invisible to loss/health — is
  named by the comparator at exactly (step N, layer 0, W).
"""

import json

import numpy as np
import pytest

from shallowspeed_tpu import utils
from shallowspeed_tpu.api import TrainingSession
from shallowspeed_tpu.observability import JsonlMetrics, MetricsRecorder, read_jsonl
from shallowspeed_tpu.observability.divergence import (
    assert_digest_streams_equal,
    assert_models_equal,
    digest_stream,
    first_divergence,
    main as divergence_main,
    tensor_diff,
)

SIZES = (24, 20, 18, 16, 14, 12, 11, 10)
N, GBS = 256, 64  # 4 batches/epoch


@pytest.fixture()
def data_dir(tmp_path_factory):
    tmp_path = tmp_path_factory.mktemp("divergence_data")
    rng = np.random.RandomState(0)
    for suffix, n in (("train", N), ("val", 64)):
        x = rng.randn(n, SIZES[0]).astype(np.float32)
        y = np.eye(SIZES[-1], dtype=np.float32)[rng.randint(0, SIZES[-1], n)]
        np.save(tmp_path / f"x_{suffix}.npy", x)
        np.save(tmp_path / f"y_{suffix}.npy", y)
    return tmp_path


class _Rec(MetricsRecorder):
    """In-memory record capture (the JSONL sink without the file)."""

    def __init__(self):
        super().__init__()
        self.records = []

    def _emit(self, rec):
        self.records.append(rec)


def _session(data_dir, **kw):
    kw.setdefault("sizes", SIZES)
    kw.setdefault("global_batch_size", GBS)
    kw.setdefault("lr", 0.01)
    return TrainingSession(data_dir=data_dir, **kw)


def _digests(metrics):
    return [r for r in metrics.records if r["kind"] == "digest"]


# ---------------------------------------------------------------------------
# the shared block definition (satellite: ONE digest definition)
# ---------------------------------------------------------------------------


def test_model_hash_value_is_pinned():
    """The iter_param_blocks refactor must not move the hash: the SHA1 of
    a fixed two-stage params tree is a literal constant (float32 bytes in
    global layer order, W before b — the reference's definition)."""
    params = [
        [{"W": np.arange(6, dtype=np.float32).reshape(2, 3),
          "b": np.zeros(2, np.float32)}],
        [{"W": np.ones((1, 2), np.float32), "b": np.array([3.0], np.float32)}],
    ]
    assert utils.model_hash(params) == "29a2cfd20d8a5732b5b216051efe74c6e4a160b6"
    blocks = list(utils.iter_param_blocks(params))
    assert [(gl, k) for gl, k, _ in blocks] == [
        (0, "W"), (0, "b"), (1, "W"), (1, "b")
    ]
    # the checksum is the exact uint32 wrap-sum of the bit patterns:
    # 1.0=0x3f800000, -0.0=0x80000000, 2.5=0x40200000
    assert utils.block_checksum(
        np.array([1.0, -0.0, 2.5], np.float32)
    ) == 0xFFA00000
    assert utils.block_checksum(params[0][0]["W"]) == 0x40E00000
    digs = utils.layer_digests(params)
    assert [d["layer"] for d in digs] == [0, 1]
    assert digs[1]["crc_b"] == 0x40400000  # 3.0 = 0x40400000
    assert digs[0]["pnorm_b"] == 0.0


# ---------------------------------------------------------------------------
# in-program digests == host reference, per layout
# ---------------------------------------------------------------------------


# the exotic layouts ride the slow tier (tier-1 keeps seq + the stacked
# dp2pp2 mesh; make diverge-smoke exercises dp2 + gpipe-pp4 end to end)
@pytest.mark.parametrize(
    "kw",
    [
        dict(),
        dict(dp=2, pp=2, schedule="gpipe"),
        pytest.param(
            dict(pp=2, tp=2, schedule="gpipe"), marks=pytest.mark.slow
        ),
        pytest.param(
            dict(dp=2, pp=2, schedule="gpipe", zero1=True,
                 optimizer="momentum"),
            marks=pytest.mark.slow,
        ),
        pytest.param(
            dict(pp=2, virtual_stages=2, schedule="interleaved"),
            marks=pytest.mark.slow,
        ),
    ],
    ids=["seq", "dp2pp2", "pp2tp2", "zero1", "interleaved"],
)
def test_digest_stream_matches_host_reference(data_dir, kw):
    """The fused aux's psum'd uint32 checksums equal the logical blocks'
    host checksums BITWISE on every layout (stacking, padding, tp shards
    and zero1 flat chunks all cancel out), and the in-program float32
    norms match the float64 host norms to float tolerance."""
    m = _Rec()
    run = _session(data_dir, digests=True, metrics=m, **kw)
    run.train_epoch()
    digs = _digests(m)
    assert [d["step"] for d in digs] == [0, 1, 2, 3]
    host = utils.layer_digests(run.params())
    last = digs[-1]
    assert last["layers"] == len(host) == 7
    for gl, h in enumerate(host):
        assert last["crc_w"][gl] == h["crc_w"], (kw, gl)
        assert last["crc_b"][gl] == h["crc_b"], (kw, gl)
        assert last["pnorm_w"][gl] == pytest.approx(h["pnorm_w"], rel=1e-4)
        assert last["pnorm_b"][gl] == pytest.approx(h["pnorm_b"], rel=1e-4)
        assert np.isfinite(last["gnorm_w"][gl]) and last["gnorm_w"][gl] >= 0


@pytest.mark.slow  # 1-core wall budget; make diverge-smoke drives this end to end
def test_digests_off_is_bitwise_identical_and_chunk_invariant(data_dir):
    """digests=True must observe, never perturb: the instrumented session
    trains to the uninstrumented twin's exact bits — and chunked
    train_steps dispatch emits the same digest rows as whole-epoch
    dispatch (the stream numbering is global-step, not dispatch)."""
    m = _Rec()
    on = _session(data_dir, digests=True, metrics=m, dp=2, schedule="gpipe")
    on.train_epoch()
    off = _session(data_dir, dp=2, schedule="gpipe")
    off.train_epoch()
    assert_models_equal(on.params(), off.params(), "digests-on", "digests-off")

    # chunk invariance is host-side numbering (api._record_digests stamps
    # global steps, not dispatch indices) — the cheap sequential program
    # exercises it identically
    m2 = _Rec()
    whole = _session(data_dir, digests=True, metrics=m2)
    whole.train_epoch()
    m3 = _Rec()
    chunked = _session(data_dir, digests=True, metrics=m3)
    while chunked.epoch < 1:
        chunked.train_steps(3)
    assert_digest_streams_equal(
        _digests(m2), _digests(m3), "whole-epoch", "chunked"
    )


# ---------------------------------------------------------------------------
# first-divergence attribution (pure stream logic)
# ---------------------------------------------------------------------------


def _row(step, **over):
    base = dict(
        kind="digest", name="train", step=step, epoch=0, layers=2,
        crc_w=[10, 20], crc_b=[30, 40],
        pnorm_w=[1.0, 2.0], pnorm_b=[0.5, 0.25],
        gnorm_w=[0.1, 0.2], gnorm_b=[0.01, 0.02],
    )
    base.update(over)
    return base


def test_first_divergence_attribution_and_classes():
    a = [_row(0), _row(1), _row(2)]
    assert first_divergence(a, [_row(0), _row(1), _row(2)]) is None

    # crc flip on layer 1's b at step 2, norms bit-identical -> ulp-level
    b = [_row(0), _row(1), _row(2, crc_b=[30, 41])]
    d = first_divergence(a, b)
    assert (d["step"], d["layer"], d["tensor"]) == (2, 1, "b")
    assert d["classification"] == "ulp-level"
    assert d["last_agreeing_step"] == 1

    # a real drift: crc and norms both move -> classified by norm delta
    c = [_row(0), _row(1, crc_w=[11, 20], pnorm_w=[1.0000001, 2.0]), _row(2)]
    d = first_divergence(a, c)
    assert (d["step"], d["layer"], d["tensor"]) == (1, 0, "W")
    assert d["classification"] == "float-tolerance"
    g = first_divergence(a, [_row(0), _row(1, crc_w=[11, 20], pnorm_w=[9.0, 2.0])])
    assert g["classification"] == "gross"

    # W reported before b, lower layer before higher, lower step first
    both = [_row(0), _row(1, crc_w=[10, 21], crc_b=[31, 40])]
    d = first_divergence(a, both)
    assert (d["step"], d["layer"], d["tensor"]) == (1, 0, "b")

    # structurally-missing: a step one stream never recorded
    d = first_divergence(a, [_row(0), _row(2)])
    assert d["step"] == 1 and d["classification"] == "structurally-missing"
    # ... and a layer-count mismatch
    d = first_divergence(a, [_row(0, layers=1)])
    assert d["step"] == 0 and d["classification"] == "structurally-missing"

    with pytest.raises(AssertionError, match="step 2 layer 1 tensor b"):
        assert_digest_streams_equal(a, b)


def test_tensor_diff_ulp_forensics():
    a = np.array([1.0, -1.0, 0.0, 2.0], np.float32)
    assert tensor_diff(a, a)["n_diff"] == 0
    # one-ulp neighbors in both directions, and the signed-zero identity
    b = np.array([np.nextafter(np.float32(1.0), np.float32(2.0)),
                  np.nextafter(np.float32(-1.0), np.float32(-2.0)),
                  -0.0, 2.0], np.float32)
    d = tensor_diff(a, b)
    assert d["max_ulp"] == 1 and d["first_index"] == 0
    assert d["n_diff"] == 3  # -0.0 differs BITWISE even at 0 ulp distance
    # ulp distance crosses zero correctly: smallest subnormals are 2 apart
    tiny = np.float32(1e-45)
    d = tensor_diff(np.array([tiny]), np.array([-tiny]))
    assert d["max_ulp"] == 2
    with pytest.raises(ValueError, match="shape mismatch"):
        tensor_diff(a, a[:2])


def test_assert_models_equal_names_the_block():
    pa = [[{"W": np.ones((2, 2), np.float32), "b": np.zeros(2, np.float32)}],
          [{"W": np.ones((1, 2), np.float32), "b": np.zeros(1, np.float32)}]]
    pb = [[{"W": np.ones((2, 2), np.float32), "b": np.zeros(2, np.float32)}],
          [{"W": np.ones((1, 2), np.float32), "b": np.zeros(1, np.float32)}]]
    assert_models_equal(pa, pb)
    pb[1][0]["W"][0, 1] = np.nextafter(np.float32(1.0), np.float32(2.0))
    with pytest.raises(AssertionError) as e:
        assert_models_equal(pa, pb, "anchor", "candidate")
    msg = str(e.value)
    assert "layer 1 W" in msg and "max ulp 1" in msg and "flat index 1" in msg


# ---------------------------------------------------------------------------
# the flip injection + the CLI
# ---------------------------------------------------------------------------


@pytest.mark.slow  # make diverge-smoke runs this end to end via train.py
def test_flip_fault_is_named_by_the_stream_and_cli(
    data_dir, tmp_path, capsys
):
    """A single-bit flip injected at step 2 stays finite (loss/health see
    nothing) but the comparator names exactly (step 2, layer 0, W) — and
    the CLI exits 0 on identical streams, 2 on the flipped one, 1 on
    usage/read errors (never colliding 2 with argparse's usage exit).
    (Independent twin runs comparing IDENTICAL is make diverge-smoke's
    e2e leg; here the clean stream doubles as its own twin.)"""
    paths = {}
    for tag, faults in (("a", ""), ("f", "flip@step=2")):
        p = tmp_path / f"{tag}.jsonl"
        with JsonlMetrics(p) as m:
            run = _session(
                data_dir, digests=True, metrics=m, dp=2, schedule="gpipe",
                faults=faults,
            )
            while run.epoch < 1:
                run.train_steps(2)
        paths[tag] = str(p)

    assert divergence_main([paths["a"], paths["a"]]) == 0
    assert "IDENTICAL" in capsys.readouterr().out

    assert divergence_main([paths["a"], paths["f"]]) == 2
    out = capsys.readouterr().out
    assert "first divergence: step 2 layer 0 tensor W" in out
    assert "ulp-level" in out and "last agreeing step: 1" in out
    d = first_divergence(
        read_jsonl(paths["a"]), read_jsonl(paths["f"])
    )
    assert (d["step"], d["layer"], d["tensor"]) == (2, 0, "W")

    # the flipped run's own config record carries the plan for replay
    cfgs = [
        r for r in read_jsonl(paths["f"])
        if r["kind"] == "event" and r["name"] == "digest_config"
    ]
    assert len(cfgs) == 1 and cfgs[0]["faults"] == "flip@step=2"

    # exit 1: unreadable file / stream without digests
    assert divergence_main([str(tmp_path / "nope.jsonl"), paths["a"]]) == 1
    plain = tmp_path / "plain.jsonl"
    plain.write_text(json.dumps({"v": 1, "kind": "event", "name": "x"}) + "\n")
    assert divergence_main([str(plain), paths["a"]]) == 1


@pytest.mark.slow
def test_bisect_replay_reproduces_the_flip(data_dir, tmp_path):
    """--bisect restores each run's last agreeing snapshot, replays ONE
    step with the recorded fault plan re-armed, and the replayed diff
    names the same (layer, tensor) as the stream — max ulp 1 at flat
    index 0, the flip's exact anchor."""
    from shallowspeed_tpu.observability.divergence import bisect_replay

    recs = {}
    for tag, faults in (("a", ""), ("f", "flip@step=5")):
        with JsonlMetrics(tmp_path / f"{tag}.jsonl") as m:
            run = _session(
                data_dir, digests=True, metrics=m, dp=2, schedule="gpipe",
                faults=faults, checkpoint_dir=tmp_path / f"ck_{tag}",
                checkpoint_keep=16,
            )
            while run.epoch < 2:
                run.train_steps(1)
                run.save_step_checkpoint()
        recs[tag] = read_jsonl(tmp_path / f"{tag}.jsonl")

    div = first_divergence(recs["a"], recs["f"])
    assert (div["step"], div["layer"], div["tensor"]) == (5, 0, "W")
    lines = []
    diffs = bisect_replay(
        recs["a"], recs["f"], str(tmp_path / "ck_a"), str(tmp_path / "ck_f"),
        div, out=lines.append,
    )
    assert [(d["layer"], d["tensor"]) for d in diffs][0] == (0, "W")
    assert diffs[0]["max_ulp"] == 1 and diffs[0]["first_index"] == 0
    text = "\n".join(lines)
    assert "bitwise-equal (divergence is INSIDE step 5)" in text
    assert "replay attribution MATCHES" in text


# ---------------------------------------------------------------------------
# refusals: paths that cannot thread the aux say so
# ---------------------------------------------------------------------------


def test_digest_refusals(data_dir):
    run = _session(data_dir, digests=True)
    with pytest.raises(ValueError, match="digests ride the epoch/step scan"):
        run.train_run(1)
    with pytest.raises(ValueError, match="digests"):
        _session(data_dir, digests=True, dp=2, pp=2, schedule="gpipe",
                 runtime="mpmd")
    with pytest.raises(ValueError, match="kernel paths"):
        _session(data_dir, digests=True, fuse_mubatches=True,
                 epoch_kernel=True)


@pytest.mark.slow  # make diverge-smoke greps the rendered section e2e
def test_report_renders_divergence_section(data_dir, tmp_path):
    from shallowspeed_tpu.observability.report import build_report, render

    p = tmp_path / "m.jsonl"
    with JsonlMetrics(p) as m:
        run = _session(data_dir, digests=True, metrics=m)
        run.train_epoch()
    report = build_report(read_jsonl(p), str(p), None, None)
    info = report["divergence"]
    assert info["records"] == 4 and info["layers"] == 7
    assert (info["first_step"], info["last_step"]) == (0, 3)
    text = render(report, "md")
    assert "## Divergence" in text
    assert "digest rows: 4 steps (0..3) x 7 layers" in text
