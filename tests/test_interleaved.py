"""Interleaved (virtual-stage) pipeline tests.

The capability under test goes BEYOND the reference (its Worker owns exactly
one stage, pipe.py:330-353): S = P x V model stages on P devices, stage s on
device s % P as chunk s // P, every stage link — including the device
(P-1) -> 0 wraps — one ring ppermute. Correctness bars:

  1. interleaved == the non-interleaved pipeline at the SAME stage
     granularity (the strongest check: same math, different placement);
  2. interleaved == sequential (on a size list where the deepest layout
     keeps a Linear on the head stage — see test_executor.py's pp8 note);
  3. the P=1 degenerate ring (all relays are self-delivery);
  4. lowered program shape: the V-fold bubble shrink is real.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shallowspeed_tpu import model as Mo
from shallowspeed_tpu import schedules as S
from shallowspeed_tpu import trainer
from shallowspeed_tpu.optimizer import SGD
from shallowspeed_tpu.parallel import executor as E
from shallowspeed_tpu.parallel import lower_schedule, make_mesh

SIZES16 = (24, 22, 21, 20, 19, 18, 17, 16, 16, 15, 14, 13, 13, 12, 11, 10)
SIZES8 = (24, 20, 18, 16, 14, 12, 11, 10)
B, M, LR, NB = 64, 4, 0.01, 3


def _data(sizes, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(NB, B, sizes[0]).astype(np.float32)
    Y = np.eye(sizes[-1], dtype=np.float32)[rng.randint(0, sizes[-1], (NB, B))]
    return X, Y


def _sequential(sizes, X, Y):
    spec = Mo.make_model_spec(sizes, 1, B)
    params = jax.tree.map(jnp.asarray, Mo.init_model(spec))
    step = trainer.make_train_step(spec, SGD(LR))
    st = ()
    for i in range(NB):
        params, st = step(
            params,
            st,
            jnp.asarray(X[i].reshape(M, B // M, -1)),
            jnp.asarray(Y[i].reshape(M, B // M, -1)),
        )
    return [l for stage in params for l in stage]


def _interleaved(sizes, X, Y, dp, P, V):
    mesh = make_mesh(dp, P)
    spec = Mo.make_model_spec(sizes, P * V, B)
    order = E.interleave_order(P * V, P)
    prog = lower_schedule(S.InterleavedSchedule, M, P, virtual=V)
    stacked, flags = E.init_stacked(spec, mesh, order=order)
    step = E.make_pipeline_step(mesh, spec, prog, B // dp // M, SGD(LR))
    for i in range(NB):
        stacked, _, loss = step(stacked, flags, (), jnp.asarray(X[i]), jnp.asarray(Y[i]))
    flat = [l for s in E.unstack_params(stacked, spec, order=order) for l in s]
    return flat, float(loss), (stacked, flags, spec, order, mesh)


@pytest.mark.parametrize("dp,P,V", [(1, 4, 2), (2, 4, 2), (1, 2, 4)])
def test_interleaved_equals_sequential(dp, P, V):
    X, Y = _data(SIZES16)
    got, loss, _ = _interleaved(SIZES16, X, Y, dp, P, V)
    want = _sequential(SIZES16, X, Y)
    assert np.isfinite(loss)
    for a, b in zip(want, got):
        np.testing.assert_allclose(np.asarray(a["W"]), b["W"], rtol=3e-4, atol=3e-6)
        np.testing.assert_allclose(
            np.asarray(a["b"]).reshape(-1), b["b"].reshape(-1), rtol=3e-4, atol=3e-6
        )


def test_interleaved_equals_flat_pipeline_same_granularity():
    """P=4 x V=2 must match PP=8 GPipe (identical 8-stage math, different
    placement) to near-bit tolerance — isolates placement bugs from the
    fp-reassociation noise a sequential comparison carries."""
    X, Y = _data(SIZES8)
    got, _, _ = _interleaved(SIZES8, X, Y, 1, 4, 2)
    mesh8 = make_mesh(1, 8)
    spec8 = Mo.make_model_spec(SIZES8, 8, B)
    prog8 = lower_schedule(S.GPipeSchedule, M, 8)
    st8, fl8 = E.init_stacked(spec8, mesh8)
    step8 = E.make_pipeline_step(mesh8, spec8, prog8, B // M, SGD(LR))
    for i in range(NB):
        st8, _, _ = step8(st8, fl8, (), jnp.asarray(X[i]), jnp.asarray(Y[i]))
    want = [l for s in E.unstack_params(st8, spec8) for l in s]
    for a, b in zip(want, got):
        np.testing.assert_allclose(a["W"], b["W"], rtol=1e-6, atol=1e-7)


def test_interleaved_single_device_ring():
    """P=1, V=4: every relay is a self-delivery over the one-device ring."""
    X, Y = _data(SIZES16)
    got, _, _ = _interleaved(SIZES16, X, Y, 1, 1, 4)
    want = _sequential(SIZES16, X, Y)
    for a, b in zip(want, got):
        np.testing.assert_allclose(np.asarray(a["W"]), b["W"], rtol=3e-4, atol=3e-6)


def test_interleaved_inference_matches_sequential_predict():
    X, Y = _data(SIZES16)
    _, _, (stacked, flags, spec, order, mesh) = _interleaved(SIZES16, X, Y, 2, 4, 2)
    eval_prog = lower_schedule(
        S.InterleavedInferenceSchedule, 1, 4, training=False, virtual=2
    )
    ev = E.make_pipeline_step(mesh, spec, eval_prog, B // 2)
    preds = np.asarray(ev(stacked, flags, jnp.asarray(X[0])))

    spec1 = Mo.make_model_spec(SIZES16, 1, B)
    seq_params = [E.unstack_params(stacked, spec, order=order)]
    flat = [l for s in seq_params[0] for l in s]
    params1 = [[{"W": jnp.asarray(l["W"]), "b": jnp.asarray(l["b"])} for l in flat]]
    pred1 = np.asarray(trainer.make_predict(spec1)(params1, jnp.asarray(X[0])))
    np.testing.assert_allclose(preds[:, : SIZES16[-1]], pred1, rtol=2e-4, atol=1e-5)


def replay_chunked(p):
    """Symbolic dataflow replay of an interleaved TickProgram (the chunk-aware
    analogue of tests/test_lowering.py::replay): payloads are
    ("act"|"grad", receiver_chunk, mubatch, sender_global_stage) routed over
    the ring; asserts every consume pairs with exactly the right producer,
    mailboxes never collide, stashes pair by (chunk, mubatch), and the
    load_in/is_head tables mark exactly the global end stages."""
    P, V = p.num_stages, p.num_chunks
    Kf, Kb, Ks = p.n_fwd_slots, p.n_bwd_slots, p.n_stash_slots
    fwd_mail = [[None] * Kf for _ in range(P)]
    bwd_mail = [[None] * Kb for _ in range(P)]
    stash = [[None] * Ks for _ in range(P)]
    S_g = P * V
    for t in range(p.num_ticks):
        outgoing = []
        for s in range(P):
            op, mb = int(p.op[t, s]), int(p.mb[t, s])
            c = int(p.chunk[t, s])
            stage_g = c * P + s
            li, ih = int(p.load_in[t, s]), int(p.is_head[t, s])
            if op != 0:
                assert li == int(stage_g == 0 and op == 1), (t, s)
                assert ih == int(stage_g == S_g - 1), (t, s)
            consumed = None
            rf, rb = int(p.read_fwd_slot[t, s]), int(p.read_bwd_slot[t, s])
            if rf != Kf:
                consumed = fwd_mail[s][rf]
                assert consumed is not None, f"empty fwd slot t={t} s={s}"
                fwd_mail[s][rf] = None
            if rb != Kb:
                assert consumed is None
                consumed = bwd_mail[s][rb]
                assert consumed is not None, f"empty bwd slot t={t} s={s}"
                bwd_mail[s][rb] = None
            sw, sr = int(p.stash_write[t, s]), int(p.stash_read[t, s])
            if sw != Ks:
                assert stash[s][sw] is None, f"stash overwrite t={t} s={s}"
                stash[s][sw] = (c, mb)
            if sr != Ks:
                assert stash[s][sr] == (c, mb), (t, s, stash[s][sr], (c, mb))
                stash[s][sr] = None
            if op == 1:  # forward
                if stage_g == 0:
                    assert consumed is None
                else:
                    assert consumed == ("act", c, mb, stage_g - 1), (t, s, consumed)
            elif op == 2:  # backward
                if stage_g == S_g - 1:
                    assert consumed is None
                else:
                    assert consumed == ("grad", c, mb, stage_g + 1), (t, s, consumed)
            if p.send_fwd[t, s]:
                dst = (s + 1) % P
                rc = c + (1 if s == P - 1 else 0)
                outgoing.append((dst, "fwd", ("act", rc, mb, stage_g)))
            if p.send_bwd[t, s]:
                dst = (s - 1) % P
                rc = c - (1 if s == 0 else 0)
                outgoing.append((dst, "bwd", ("grad", rc, mb, stage_g)))
        for dst, direction, payload in outgoing:
            mail = fwd_mail if direction == "fwd" else bwd_mail
            slot_tab = p.in_fwd_slot if direction == "fwd" else p.in_bwd_slot
            slot = int(slot_tab[t, dst])
            assert slot != (Kf if direction == "fwd" else Kb), (t, dst)
            assert mail[dst][slot] is None, f"mailbox collision t={t} dst={dst}"
            mail[dst][slot] = payload
    for s in range(P):
        assert all(x is None for x in fwd_mail[s] + bwd_mail[s]), "leftover msgs"
        assert all(x is None for x in stash[s]), "leaked stash"


@pytest.mark.parametrize("M,P,V", [(4, 4, 2), (4, 2, 4), (8, 4, 2), (2, 2, 2), (4, 1, 4), (3, 3, 2)])
def test_interleaved_dataflow_replay(M, P, V):
    replay_chunked(lower_schedule(S.InterleavedSchedule, M, P, virtual=V))


@pytest.mark.parametrize("M,P,V", [(1, 4, 2), (4, 4, 2), (2, 3, 3)])
def test_interleaved_inference_dataflow_replay(M, P, V):
    replay_chunked(
        lower_schedule(S.InterleavedInferenceSchedule, M, P, training=False, virtual=V)
    )


class TestLoweredShape:
    def test_bubble_shrinks_with_v(self):
        """Interleaving buys the V-fold warmup shrink: at equal per-device
        work (ticks are 1/V the compute), P=4 V=2 M=4 has the same tick
        count as flat P=8 but each tick is half a fat-stage compute."""
        pi = lower_schedule(S.InterleavedSchedule, 4, 4, virtual=2)
        p8 = lower_schedule(S.PipeDreamFlushSchedule, 4, 8)
        p4 = lower_schedule(S.PipeDreamFlushSchedule, 4, 4)
        assert pi.num_ticks == p8.num_ticks == 22
        assert p4.num_ticks == 14
        # busy fraction: 2*M*V of num_ticks vs 2*M of num_ticks
        assert 2 * 4 * 2 / pi.num_ticks > 2 * 4 / p4.num_ticks

    def test_m_not_divisible_by_p_rejected(self):
        with pytest.raises(Exception, match="M % P"):
            lower_schedule(S.InterleavedSchedule, 2, 4, virtual=2)

    def test_chunk_tables_well_formed(self):
        p = lower_schedule(S.InterleavedSchedule, 4, 4, virtual=2)
        assert p.num_chunks == 2
        assert p.chunk.min() == 0 and p.chunk.max() == 1
        # input loads only on device 0, head only on device P-1
        assert (p.load_in[:, 1:] == 0).all()
        assert (p.is_head[:, :-1] == 0).all()
        # every (chunk, mb) forwarded and backwarded once per device
        for s in range(4):
            fwd = sorted(
                (int(p.chunk[t, s]), int(p.mb[t, s]))
                for t in range(p.num_ticks)
                if p.op[t, s] == 1
            )
            assert fwd == [(c, m) for c in range(2) for m in range(4)]
