"""Numerics health monitor: NaN/Inf, loss divergence, grad-norm spikes.

Consumes the fused per-step aux the flight recorder threads out of the epoch
programs (loss, pre-clip grad norm, post-update param norm) and checks it ON
HOST, after each epoch's single readback — the checks live entirely outside
the jitted program, so an instrumented run executes the exact same XLA
computation as a bare one.

Checks (each produces a *finding* dict naming the step it fired on):

- ``non_finite``       any NaN/Inf in loss, grad norm or param norm — the
                       unambiguous blowup signal;
- ``loss_divergence``  rolling-window least-squares regression over recent
                       step losses: fires when the window's slope is
                       positive AND the current loss has grown past
                       ``divergence_factor`` x the window minimum (the
                       slope test alone would fire on benign noise, the
                       level test alone on a one-step blip);
- ``grad_spike``       current grad norm >= ``spike_factor`` x the rolling
                       median (median, not mean: one spike must not drag
                       the baseline up and mask the next one).

Policy (``record`` / ``warn`` / ``halt``) decides what a finding DOES:

- ``record``  emit a schema-v2 ``health`` record per finding, keep going;
- ``warn``    record + print a warning to stderr;
- ``halt``    record (and flush, so the evidence is on disk), then raise
              ``HealthError`` naming the first finding — the training loop
              stops with the blown-up step identified instead of burning
              the rest of the run on NaN arithmetic.

Wiring: ``TrainingSession(health="halt")`` / ``train.py --health halt``
(a ``HealthMonitor`` instance is accepted wherever the policy string is,
for non-default windows/factors).
"""

import math
import sys
from collections import deque

POLICIES = ("record", "warn", "halt")


class HealthError(RuntimeError):
    """Raised under policy='halt' when a health check fires.

    ``finding`` is the first finding dict (check/epoch/step/value/detail);
    the epoch's parameter update has already been applied when this raises —
    the monitor observes the fused program's outputs, it cannot unwind them.
    """

    def __init__(self, finding):
        self.finding = finding
        where = f"epoch {finding.get('epoch')}"
        if finding.get("step") is not None:
            where += f", step {finding['step']}"
        super().__init__(
            f"numerics health halt: {finding.get('check')} at {where} "
            f"({finding.get('detail')})"
        )


def _is_finite(v):
    return v is not None and math.isfinite(v)


def _slope(values):
    """Least-squares slope of values over 0..n-1 (the rolling regression)."""
    n = len(values)
    if n < 2:
        return 0.0
    xm = (n - 1) / 2.0
    ym = sum(values) / n
    num = sum((i - xm) * (v - ym) for i, v in enumerate(values))
    den = sum((i - xm) ** 2 for i in range(n))
    return num / den


class HealthMonitor:
    """Stateful rolling-window checker; one instance per training run."""

    def __init__(
        self,
        policy="record",
        window=32,
        min_history=8,
        divergence_factor=3.0,
        spike_factor=10.0,
    ):
        if policy not in POLICIES:
            raise ValueError(f"policy must be one of {POLICIES}, got {policy!r}")
        if min_history < 2 or window < min_history:
            raise ValueError("need window >= min_history >= 2")
        self.policy = policy
        self.window = int(window)
        self.min_history = int(min_history)
        self.divergence_factor = float(divergence_factor)
        self.spike_factor = float(spike_factor)
        self._losses = deque(maxlen=self.window)
        self._gnorms = deque(maxlen=self.window)
        self.findings = []  # everything ever found, in firing order

    # -- checks -------------------------------------------------------------

    def check_step(self, epoch, loss, step=None, grad_norm=None, param_norm=None):
        """Check one step's scalars; returns this step's findings (and
        appends them to ``self.findings``). Rolling windows only ever ingest
        finite values — a NaN step must not poison the baseline the NEXT
        step is judged against."""
        found = []

        def finding(check, value, detail):
            f = {
                "check": check,
                "epoch": int(epoch),
                "step": None if step is None else int(step),
                "value": None if value is None else float(value),
                "detail": detail,
            }
            found.append(f)
            return f

        for field, v in (
            ("loss", loss), ("grad_norm", grad_norm), ("param_norm", param_norm)
        ):
            if v is not None and not math.isfinite(v):
                finding(
                    "non_finite", v, f"{field} is {float(v)!r}"
                )["field"] = field

        if _is_finite(loss):
            if len(self._losses) >= self.min_history:
                wmin = min(self._losses)
                slope = _slope(list(self._losses) + [float(loss)])
                if wmin > 0 and loss >= self.divergence_factor * wmin and slope > 0:
                    f = finding(
                        "loss_divergence",
                        loss,
                        f"loss {float(loss):.6g} >= {self.divergence_factor}x "
                        f"window min {wmin:.6g} with rising slope",
                    )
                    f["slope"] = float(slope)
                    f["window_min"] = float(wmin)
            self._losses.append(float(loss))

        if _is_finite(grad_norm):
            if len(self._gnorms) >= self.min_history:
                med = sorted(self._gnorms)[len(self._gnorms) // 2]
                if med > 0 and grad_norm >= self.spike_factor * med:
                    f = finding(
                        "grad_spike",
                        grad_norm,
                        f"grad norm {float(grad_norm):.6g} >= "
                        f"{self.spike_factor}x rolling median {med:.6g}",
                    )
                    f["window_median"] = float(med)
            self._gnorms.append(float(grad_norm))

        self.findings.extend(found)
        return found

    def check_epoch(
        self, epoch, losses, grad_norms=None, param_norms=None, first_step=None
    ):
        """Check one epoch's per-step arrays (the flight-recorder aux);
        ``first_step=None`` means step identity is unknown (epoch-granular
        callers, e.g. the kernel paths) and findings carry ``step: null``."""
        found = []
        for i, loss in enumerate(losses):
            found.extend(
                self.check_step(
                    epoch,
                    loss,
                    step=None if first_step is None else first_step + i,
                    grad_norm=None if grad_norms is None else grad_norms[i],
                    param_norm=None if param_norms is None else param_norms[i],
                )
            )
        return found

    def check_run(self, start_epoch, losses, grad_norms=None):
        """Check a fused multi-epoch run's per-EPOCH scalars (one loss — and
        optionally one mean grad norm — per epoch; the fused run returns in
        one dispatch, so step granularity does not exist there)."""
        found = []
        for i, loss in enumerate(losses):
            found.extend(
                self.check_step(
                    start_epoch + i,
                    loss,
                    step=None,
                    grad_norm=None if grad_norms is None else grad_norms[i],
                )
            )
        return found

    # -- policy -------------------------------------------------------------

    def dispatch(self, findings, metrics=None):
        """Apply the policy to a batch of findings: emit one ``health``
        record per finding (action-stamped), warn/halt per policy. Under
        ``halt`` every finding is recorded AND flushed before the raise, so
        the JSONL evidence trail survives the abort."""
        if metrics is not None:
            for f in findings:
                metrics.health(
                    f["check"],
                    action=self.policy,
                    **{k: v for k, v in f.items() if k != "check"},
                )
        if not findings:
            return
        if self.policy == "warn":
            for f in findings:
                where = f"epoch {f['epoch']}" + (
                    f", step {f['step']}" if f.get("step") is not None else ""
                )
                print(
                    f"health warning: {f['check']} at {where}: {f['detail']}",
                    file=sys.stderr,
                )
        elif self.policy == "halt":
            if metrics is not None:
                metrics.flush()
            raise HealthError(findings[0])


def make_monitor(health):
    """Normalize the ``health=`` argument surface: None -> None, a policy
    string -> a default-window HealthMonitor, a HealthMonitor -> itself."""
    if health is None or isinstance(health, HealthMonitor):
        return health
    return HealthMonitor(policy=health)
