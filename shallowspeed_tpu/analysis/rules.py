"""House-rule AST lint rules — the conventions generic linters can't see.

Every rule encodes an invariant this repo already enforces by review
(docs/static-analysis.md has the catalog with the why behind each):

- ``BLE001``  a broad ``except Exception``/``except BaseException``/bare
              ``except`` must either re-raise or carry the justification
              idiom ``# noqa: BLE001 — <why>`` on the except line;
- ``SSP002``  ``json.dumps`` on metrics paths (``observability/``,
              ``serving/``) must pass ``allow_nan=False`` — every record
              line must be STRICT JSON (the ``_json_safe`` lesson);
- ``SSP003``  modules owning durable on-disk formats (``checkpoint.py``,
              ``aot_cache.py``) may only write through
              ``checkpoint.atomic_write`` — no raw ``open(.., "w")``,
              ``os.fdopen`` write modes or ``Path.write_*`` outside the
              ``atomic_write`` body itself;
- ``SSP004``  ``donate_argnums`` is allowed only in the whitelisted
              trainer/executor modules (the donation hazard PR 1/PR 12
              document: a donating program must never be deserialized
              and dispatched);
- ``SSP005``  every dict literal handed to ``_emit`` must carry a
              ``"kind"`` that is a string literal registered in the
              ``metrics.SCHEMA_KINDS`` table (schema-version
              discipline);
- ``SSP006``  in a class that owns a ``threading.Lock``/``RLock``,
              attributes ever ASSIGNED under a ``with self.<lock>:``
              block are lock-guarded: touching them outside a with-lock
              block in that class (``__init__`` excepted — construction
              happens-before publication) is a data race waiting for a
              second thread.

Suppression: ``# noqa: <RULE> — <why>`` on the offending line (the
BLE001 idiom generalized); a bare ``noqa`` without a justification does
NOT suppress. Rules are pure ``ast`` + source text — no imports of the
linted code, so the linter runs without jax.
"""

import ast
import dataclasses
import re
from pathlib import Path

RULE_IDS = ("BLE001", "SSP002", "SSP003", "SSP004", "SSP005", "SSP006")

# the justification idiom: rule id(s) then an em-dash (or --) and WHY
_NOQA_RE = re.compile(
    r"#\s*noqa:\s*(?P<ids>[A-Z]+\d+(?:\s*,\s*[A-Z]+\d+)*)"
    r"(?:\s*[—–-]+\s*(?P<why>\S.*))?"
)

_WRITE_MODE_RE = re.compile(r"[wax+]")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint finding: stable rule id + precise location + message."""

    rule: str
    path: str
    line: int
    col: int
    message: str

    def format(self):
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"

    def as_dict(self):
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class Scope:
    """Which path-scoped rules apply to the file being linted. Derived
    from the repo-relative path by ``scope_for``; tests may force flags
    to exercise scoped rules on fixture files."""

    metrics_path: bool = False  # SSP002: observability/ + serving/
    atomic_module: bool = False  # SSP003: checkpoint.py + aot_cache.py
    donation_ok: bool = False  # SSP004: trainer.py + parallel/executor.py


def scope_for(path):
    """Default rule scope for a repo file, by its path."""
    p = Path(path).as_posix()
    return Scope(
        metrics_path=(
            "shallowspeed_tpu/observability/" in p
            or "shallowspeed_tpu/serving/" in p
        ),
        atomic_module=p.endswith(
            ("shallowspeed_tpu/checkpoint.py", "shallowspeed_tpu/aot_cache.py")
        ),
        donation_ok=p.endswith(
            ("shallowspeed_tpu/trainer.py", "shallowspeed_tpu/parallel/executor.py")
        ),
    )


_SCHEMA_KINDS_CACHE = {}


def load_schema_kinds(metrics_path=None):
    """The ``SCHEMA_KINDS`` registry, parsed from metrics.py by AST — the
    linter must not import the package it lints (and must run without
    jax). Returns ``{kind: version_introduced}``."""
    if metrics_path is None:
        metrics_path = (
            Path(__file__).resolve().parents[1] / "observability" / "metrics.py"
        )
    key = str(metrics_path)
    if key not in _SCHEMA_KINDS_CACHE:
        tree = ast.parse(Path(metrics_path).read_text(encoding="utf-8"))
        kinds = None
        for node in tree.body:
            if (
                isinstance(node, ast.Assign)
                and any(
                    isinstance(t, ast.Name) and t.id == "SCHEMA_KINDS"
                    for t in node.targets
                )
            ):
                kinds = ast.literal_eval(node.value)
        if not isinstance(kinds, dict) or not kinds:
            raise ValueError(
                f"{metrics_path}: no SCHEMA_KINDS table found — the metrics"
                " schema registry is the linter's ground truth"
            )
        _SCHEMA_KINDS_CACHE[key] = kinds
    return _SCHEMA_KINDS_CACHE[key]


def _suppressed(lines, lineno, rule):
    """True when the source line carries a JUSTIFIED noqa for ``rule``."""
    if not 1 <= lineno <= len(lines):
        return False
    m = _NOQA_RE.search(lines[lineno - 1])
    if not m or not m.group("why"):
        return False
    ids = {i.strip() for i in m.group("ids").split(",")}
    return rule in ids


class _RuleVisitor(ast.NodeVisitor):
    """One pass over a module collecting findings for every rule."""

    def __init__(self, path, lines, scope, schema_kinds):
        self.path = str(path)
        self.lines = lines
        self.scope = scope
        self.schema_kinds = schema_kinds
        self.findings = []
        self._func_stack = []

    def _emit(self, rule, node, message):
        if not _suppressed(self.lines, node.lineno, rule):
            self.findings.append(
                Finding(rule, self.path, node.lineno, node.col_offset, message)
            )

    # -- BLE001: justified broad excepts ------------------------------------

    def visit_ExceptHandler(self, node):
        names = set()
        types = (
            node.type.elts if isinstance(node.type, ast.Tuple)
            else [node.type] if node.type is not None else []
        )
        for t in types:
            if isinstance(t, ast.Name):
                names.add(t.id)
        broad = node.type is None or names & {"Exception", "BaseException"}
        reraises = any(isinstance(n, ast.Raise) for n in ast.walk(node))
        if broad and not reraises:
            self._emit(
                "BLE001", node,
                "broad except that swallows: justify with"
                " '# noqa: BLE001 — <why>' (or narrow / re-raise)",
            )
        self.generic_visit(node)

    # -- function context (SSP003 exempts atomic_write itself) ---------------

    def visit_FunctionDef(self, node):
        self._func_stack.append(node.name)
        self.generic_visit(node)
        self._func_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    # -- call-shaped rules ---------------------------------------------------

    def visit_Call(self, node):
        self._check_json_dumps(node)
        self._check_raw_write(node)
        self._check_donation(node)
        self._check_emit_kind(node)
        self.generic_visit(node)

    def _check_json_dumps(self, node):
        if not self.scope.metrics_path:
            return
        f = node.func
        if not (
            isinstance(f, ast.Attribute)
            and f.attr == "dumps"
            and isinstance(f.value, ast.Name)
            and f.value.id == "json"
        ):
            return
        for kw in node.keywords:
            if kw.arg == "allow_nan":
                if isinstance(kw.value, ast.Constant) and kw.value.value is False:
                    return
                break
        self._emit(
            "SSP002", node,
            "json.dumps on a metrics path must pass allow_nan=False"
            " (every record line must be strict JSON)",
        )

    def _check_raw_write(self, node):
        if not self.scope.atomic_module or "atomic_write" in self._func_stack:
            return
        f = node.func
        opener = None
        if isinstance(f, ast.Name) and f.id == "open":
            opener, mode_pos = "open", 1
        elif (
            isinstance(f, ast.Attribute) and f.attr == "fdopen"
            and isinstance(f.value, ast.Name) and f.value.id == "os"
        ):
            opener, mode_pos = "os.fdopen", 1
        if opener is not None:
            mode = None
            if len(node.args) > mode_pos:
                mode = node.args[mode_pos]
            for kw in node.keywords:
                if kw.arg == "mode":
                    mode = kw.value
            if mode is None:
                return  # default "r": a read is not a write
            if not isinstance(mode, ast.Constant) or (
                isinstance(mode.value, str) and _WRITE_MODE_RE.search(mode.value)
            ):
                self._emit(
                    "SSP003", node,
                    f"raw {opener}(..) write in a durable-format module:"
                    " route it through checkpoint.atomic_write",
                )
            return
        if isinstance(f, ast.Attribute) and f.attr in (
            "write_text", "write_bytes",
        ):
            self._emit(
                "SSP003", node,
                f"Path.{f.attr} in a durable-format module: route it"
                " through checkpoint.atomic_write",
            )

    def _check_donation(self, node):
        if self.scope.donation_ok:
            return
        for kw in node.keywords:
            if kw.arg == "donate_argnums":
                self._emit(
                    "SSP004", node,
                    "donate_argnums outside the whitelisted trainer/executor"
                    " modules (a donating program must never reach the"
                    " serving or AOT-deserialize paths)",
                )

    def _check_emit_kind(self, node):
        f = node.func
        name = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None
        )
        if name != "_emit" or not node.args:
            return
        rec = node.args[0]
        if not isinstance(rec, ast.Dict):
            return  # pass-through dicts are built from already-linted sites
        for k, v in zip(rec.keys, rec.values):
            if isinstance(k, ast.Constant) and k.value == "kind":
                if not (isinstance(v, ast.Constant) and isinstance(v.value, str)):
                    self._emit(
                        "SSP005", v if v is not None else node,
                        "record 'kind' must be a string literal (the schema"
                        " registry cannot check a computed kind)",
                    )
                elif v.value not in self.schema_kinds:
                    self._emit(
                        "SSP005", v,
                        f"record kind {v.value!r} is not registered in"
                        " metrics.SCHEMA_KINDS — register it with its"
                        " schema version (additive bump) first",
                    )

    # -- SSP006: lock discipline --------------------------------------------

    def visit_ClassDef(self, node):
        self._check_lock_discipline(node)
        self.generic_visit(node)

    def _check_lock_discipline(self, cls):
        locks = set()
        for n in ast.walk(cls):
            if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
                f = n.value.func
                if (
                    isinstance(f, ast.Attribute)
                    and f.attr in ("Lock", "RLock")
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "threading"
                ):
                    for t in n.targets:
                        if self._self_attr(t):
                            locks.add(t.attr)
        if not locks:
            return
        methods = [
            n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        guarded = set()
        for m in methods:
            if m.name == "__init__":
                continue
            self._walk_lock(m.body, False, locks, guarded, collect=True)
        guarded -= locks
        if not guarded:
            return
        for m in methods:
            if m.name == "__init__":
                continue
            self._walk_lock(m.body, False, locks, guarded, collect=False)

    @staticmethod
    def _self_attr(node):
        return (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
        )

    def _is_lock_with(self, stmt, locks):
        return isinstance(stmt, ast.With) and any(
            self._self_attr(item.context_expr)
            and item.context_expr.attr in locks
            for item in stmt.items
        )

    def _walk_lock(self, stmts, under_lock, locks, guarded, collect):
        """Walk statements tracking with-lock nesting. ``collect=True``
        gathers attrs ASSIGNED under a lock; ``collect=False`` flags any
        access to a guarded attr outside a lock."""
        for stmt in stmts:
            locked = under_lock or self._is_lock_with(stmt, locks)
            # examine this statement's own expressions (not nested blocks)
            for n in ast.walk(stmt):
                if not self._self_attr(n) or n.attr in locks:
                    continue
                # a nested statement list re-walks with its own lock state;
                # restrict this sweep to nodes not inside a deeper With
                if self._in_nested_block(stmt, n):
                    continue
                if collect:
                    if locked and isinstance(n.ctx, ast.Store):
                        guarded.add(n.attr)
                elif not locked and n.attr in guarded:
                    self._emit(
                        "SSP006", n,
                        f"attribute self.{n.attr} is lock-guarded (assigned"
                        " under a with-lock block in this class) but touched"
                        " here outside the lock",
                    )
            for field in ("body", "orelse", "finalbody"):
                inner = getattr(stmt, field, None)
                if inner:
                    self._walk_lock(inner, locked, locks, guarded, collect)
            for h in getattr(stmt, "handlers", ()):
                self._walk_lock(h.body, locked, locks, guarded, collect)

    @staticmethod
    def _in_nested_block(stmt, node):
        """True when ``node`` sits inside a nested compound statement of
        ``stmt`` (those are re-walked with their own lock state)."""
        for field in ("body", "orelse", "finalbody"):
            for inner in getattr(stmt, field, ()):
                if node in set(ast.walk(inner)):
                    return True
        for h in getattr(stmt, "handlers", ()):
            for inner in h.body:
                if node in set(ast.walk(inner)):
                    return True
        return False


def lint_source(source, path="<string>", scope=None, schema_kinds=None):
    """Lint one module's source text; returns a list of Findings."""
    if scope is None:
        scope = scope_for(path)
    if schema_kinds is None:
        schema_kinds = load_schema_kinds()
    try:
        tree = ast.parse(source, filename=str(path))
    except SyntaxError as e:
        return [
            Finding(
                "E999", str(path), e.lineno or 1, e.offset or 0,
                f"syntax error: {e.msg}",
            )
        ]
    visitor = _RuleVisitor(str(path), source.splitlines(), scope, schema_kinds)
    visitor.visit(tree)
    return sorted(visitor.findings, key=lambda f: (f.path, f.line, f.col))


def lint_file(path, scope=None, schema_kinds=None):
    """Lint one file; returns a list of Findings."""
    text = Path(path).read_text(encoding="utf-8")
    return lint_source(text, path=path, scope=scope, schema_kinds=schema_kinds)
