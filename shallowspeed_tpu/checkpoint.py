"""Checkpoint / resume: layout-independent on-disk snapshots.

The reference has NO checkpointing in its framework (SURVEY §5.4 — only its
PyTorch baseline script saves weights for divergence comparison). Here it is
a first-class subsystem, designed around the same principle as init and
hashing: checkpoints store the *logical* per-layer (W, b) blocks in global
layer order, so a model trained DP=2 x PP=4 can be saved and resumed
sequentially, or vice versa — the layout is a property of the run, not of
the checkpoint.

Format: a single .npz (atomic rename on save) with arrays ``w{i}``/``b{i}``
per global layer, optional optimizer-state arrays ``ow{i}``/``ob{i}`` in the
same logical order (for stateful optimizers, e.g. momentum velocity), plus a
JSON metadata blob (sizes, global batch size, epoch, optimizer config).

Format v2 (additive; v1 files load unchanged) makes checkpoints the
RESUMABLE unit of fault tolerance (docs/robustness.md):

- a step cursor: ``global_step`` / ``step_in_epoch`` — a snapshot taken
  mid-epoch resumes exactly at its step, not at the last epoch boundary;
- a content ``checksum`` (sha256 over every array's bytes, name-sorted):
  a torn or bit-flipped file is DETECTED on load instead of silently
  training on garbage;
- an ``all_finite`` flag, so resume discovery can skip a snapshot flushed
  mid-blow-up (the health monitor's halt path) without re-reading it.

Step-checkpoint directories (``step-<global_step>.npz``, rotating retention)
plus ``find_latest_good`` — newest-first discovery that VERIFIES each
candidate and falls back past corrupt ones — are what ``--resume auto``
runs on. Loader errors surface as ``CheckpointError`` naming the path and
the suspected cause (zero-byte / truncated / wrong format / checksum
mismatch), never a raw NumPy/zipfile traceback.
"""

import hashlib
import json
import os
import re
import tempfile
import zipfile
from pathlib import Path

import numpy as np

from shallowspeed_tpu import retry
from shallowspeed_tpu.model import ModelSpec, make_model_spec

FORMAT_VERSION = 2
SUPPORTED_VERSIONS = (1, 2)

STEP_CHECKPOINT_RE = re.compile(r"^step-(\d+)\.npz$")


class CheckpointError(RuntimeError):
    """A checkpoint file that cannot be trusted: unreadable, truncated,
    wrong format, or failing its content checksum. Carries the ``path``
    and a human ``cause`` so the error names what to look at."""

    def __init__(self, path, cause):
        self.path = str(path)
        self.cause = cause
        super().__init__(f"checkpoint {self.path}: {cause}")


def _flatten_logical(params_list):
    """Per-stage ragged params -> flat global layer list (host numpy)."""
    import jax

    out = []
    for stage in params_list:
        for layer in stage:
            out.append(
                (
                    np.asarray(jax.device_get(layer["W"]), np.float32),
                    np.asarray(jax.device_get(layer["b"]), np.float32).reshape(1, -1),
                )
            )
    return out


def _opt_prefix(key):
    """Array-name prefix for an optimizer-state part. The unnamed part
    (momentum's whole-state mirror) keeps the original ``ow{i}``/``ob{i}``
    names, so round-1 checkpoints load unchanged; named parts (Adam's m/v)
    get ``o_{key}_w{i}``."""
    return ("ow", "ob") if key == "" else (f"o_{key}_w", f"o_{key}_b")


def content_checksum(arrays):
    """sha256 over every non-meta array's name, dtype, shape and bytes, in
    name-sorted order — the torn/corrupt-file detector format v2 stores in
    (and verifies against) the metadata blob."""
    h = hashlib.sha256()
    for name in sorted(arrays):
        if name == "meta":
            continue
        a = np.ascontiguousarray(arrays[name])
        h.update(name.encode())
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def save_checkpoint(
    path,
    params_list,
    spec: ModelSpec,
    epoch: int,
    extra=None,
    opt_state=None,
    step_in_epoch=None,
    global_step=None,
):
    """Atomically write params (+ metadata) to ``path`` (.npz).

    ``opt_state``: optional logical optimizer state, as
    ``{"parts": {key: ragged_list}, "scalars": {key: float}}`` where each
    ragged_list has the SAME structure as ``params_list`` (state parts
    mirror the params — momentum velocity, Adam moments) — stored in the
    same logical layer order, so it is exactly as layout-independent as the
    weights; scalars (Adam's step count) go into the metadata blob.

    ``step_in_epoch`` / ``global_step``: the v2 resumable cursor — with
    them set, ``epoch`` means "the epoch IN PROGRESS" and resume restarts
    at exactly this optimizer step; without them (the legacy epoch-boundary
    save), ``epoch`` means "last COMPLETED epoch" and resume restarts at
    ``epoch + 1``. A mid-stream failure never leaves a temp file behind,
    and transient ``OSError`` on the write path is retried with bounded
    backoff (retry.retry_call) before surfacing.

    Returns ``(bytes_written, all_finite)`` — the finiteness flag that was
    stamped into the metadata, so callers can gate retention on it without
    re-scanning the arrays (a non-finite snapshot must never rotate the
    last healthy one away).
    """
    path = Path(path)
    flat = _flatten_logical(params_list)
    if len(flat) != len(spec.sizes) - 1:
        raise ValueError(
            f"param count {len(flat)} does not match spec sizes {spec.sizes}"
        )
    parts = (opt_state or {}).get("parts", {})
    scalars = (opt_state or {}).get("scalars", {})
    meta = {
        "format_version": FORMAT_VERSION,
        "sizes": list(spec.sizes),
        "global_batch_size": spec.global_batch_size,
        "epoch": int(epoch),
        "step_in_epoch": None if step_in_epoch is None else int(step_in_epoch),
        "global_step": None if global_step is None else int(global_step),
        "has_opt_state": "" in parts,  # legacy momentum flag (round-1 readers)
        "opt_parts": sorted(parts),
        "opt_scalars": {k: float(v) for k, v in scalars.items()},
        "extra": extra or {},
    }
    arrays = {}
    for i, (w, b) in enumerate(flat):
        arrays[f"w{i}"] = w
        arrays[f"b{i}"] = b
    for key, ragged in parts.items():
        pw, pb = _opt_prefix(key)
        flat_opt = _flatten_logical(ragged)
        if len(flat_opt) != len(flat):
            raise ValueError(
                f"optimizer-state part {key!r} layer count {len(flat_opt)} != "
                f"param count {len(flat)}"
            )
        for i, (ow, ob) in enumerate(flat_opt):
            if ow.shape != flat[i][0].shape or ob.shape != flat[i][1].shape:
                raise ValueError(
                    f"optimizer-state part {key!r} layer {i} shape "
                    f"{ow.shape}/{ob.shape} does not mirror the params "
                    f"{flat[i][0].shape}/{flat[i][1].shape}"
                )
            arrays[f"{pw}{i}"] = ow
            arrays[f"{pb}{i}"] = ob
    # checksum + finiteness are computed over the EXACT arrays written, and
    # land in the metadata blob inside the same atomic file
    meta["checksum"] = content_checksum(arrays)
    meta["all_finite"] = bool(
        all(np.isfinite(a).all() for a in arrays.values())
    )
    arrays["meta"] = np.frombuffer(json.dumps(meta).encode(), dtype=np.uint8)
    path.parent.mkdir(parents=True, exist_ok=True)

    def write_once():
        # mkstemp INSIDE the retried body: each attempt owns (and on any
        # failure removes) its own temp file, so a mid-stream exception —
        # first attempt or last — never leaks a *.npz.tmp beside the target
        fd, tmp = tempfile.mkstemp(dir=path.parent, suffix=".npz.tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                np.savez(f, **arrays)
            os.replace(tmp, path)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise
        return os.path.getsize(path)

    nbytes = retry.retry_call(write_once, attempts=3, retry_on=(OSError,))
    return nbytes, meta["all_finite"]


def _partition(flat, spec: ModelSpec):
    """Flat global layer list -> per-stage ragged list for ``spec``."""
    out, k = [], 0
    for sspec in spec.stages:
        layers = []
        for _ in range(sspec.n_linears):
            w, b = flat[k]
            layers.append({"W": w, "b": b})
            k += 1
        out.append(layers)
    return out


def _read_arrays(path):
    """Open ``path`` and return ``(meta, arrays)`` with every failure mode
    translated into a ``CheckpointError`` naming the path and the suspected
    cause (raw NumPy/zipfile tracebacks name neither). Verifies the v2
    content checksum when the metadata carries one."""
    path = Path(path)
    try:
        size = path.stat().st_size
    except OSError as e:
        raise CheckpointError(path, f"cannot stat file ({e})") from e
    if size == 0:
        raise CheckpointError(
            path, "file is empty (zero bytes — torn write or placeholder)"
        )
    try:
        with np.load(path) as z:
            arrays = {name: z[name] for name in z.files}
    except zipfile.BadZipFile as e:
        raise CheckpointError(
            path,
            f"truncated or corrupt .npz archive ({e}) — the write likely "
            "died mid-stream",
        ) from e
    except (OSError, EOFError) as e:
        raise CheckpointError(path, f"unreadable ({e})") from e
    except ValueError as e:
        raise CheckpointError(
            path, f"not a .npz checkpoint (wrong format: {e})"
        ) from e
    if "meta" not in arrays:
        raise CheckpointError(
            path, "no metadata blob — not a shallowspeed checkpoint"
        )
    try:
        meta = json.loads(bytes(arrays["meta"]).decode())
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise CheckpointError(
            path, f"metadata blob is not valid JSON ({e}) — corrupt file"
        ) from e
    if meta.get("format_version") not in SUPPORTED_VERSIONS:
        raise CheckpointError(
            path,
            f"unsupported format version {meta.get('format_version')!r} "
            f"(this reader understands {SUPPORTED_VERSIONS})",
        )
    saved_sum = meta.get("checksum")
    if saved_sum is not None:
        actual = content_checksum(arrays)
        if actual != saved_sum:
            raise CheckpointError(
                path,
                f"content checksum mismatch (stored {saved_sum[:12]}…, "
                f"recomputed {actual[:12]}…) — torn or corrupted write",
            )
    return meta, arrays


def verify_checkpoint(path, require_finite=False):
    """Full verification pass (read + parse + checksum): returns the
    metadata dict of a trustworthy checkpoint, raises ``CheckpointError``
    otherwise. ``require_finite=True`` additionally rejects snapshots whose
    arrays contain NaN/Inf (resume discovery uses this so a checkpoint
    flushed mid-blow-up is skipped in favor of the last healthy one)."""
    meta, arrays = _read_arrays(path)
    if require_finite:
        finite = meta.get("all_finite")
        if finite is None:  # v1 file: flag absent, check the arrays
            finite = all(
                np.isfinite(a).all()
                for name, a in arrays.items()
                if name != "meta" and np.issubdtype(a.dtype, np.floating)
            )
        if not finite:
            raise CheckpointError(
                path, "contains non-finite values (snapshot of a blown-up run)"
            )
    return meta


def load_checkpoint(path, n_stages: int, global_batch_size=None, with_opt_state=False):
    """Load a checkpoint and re-partition it for an ``n_stages`` layout.

    ``global_batch_size``: the CURRENT run's global batch size — it feeds the
    loss-scaling spec, so resurrecting the saved value when the run uses a
    different batch size would silently mis-scale every gradient. Defaults to
    the saved value for same-configuration resumes.

    Returns (params_list, spec, meta): params_list is per-stage ragged host
    numpy ready for ``jax.tree.map(jnp.asarray, ...)`` (sequential) or
    ``executor.stack_params`` (pipeline). With ``with_opt_state=True``,
    returns (params_list, spec, meta, opt_state) where opt_state is
    ``{"parts": {key: ragged_list}, "scalars": {key: float}}`` (each part
    mirrors params_list), or None when the checkpoint stored none.

    An unreadable / truncated / checksum-failing file raises
    ``CheckpointError`` naming the path and the suspected cause.
    """
    meta, z = _read_arrays(path)
    try:
        n_layers = len(meta["sizes"]) - 1
        flat = [(z[f"w{i}"], z[f"b{i}"]) for i in range(n_layers)]
        # opt_parts supersedes has_opt_state; round-1 files have only the
        # latter (and only the unnamed part)
        part_keys = meta.get("opt_parts")
        if part_keys is None:
            part_keys = [""] if meta.get("has_opt_state") else []
        flat_parts = {}
        for key in part_keys:
            pw, pb = _opt_prefix(key)
            flat_parts[key] = [(z[f"{pw}{i}"], z[f"{pb}{i}"]) for i in range(n_layers)]
    except KeyError as e:
        raise CheckpointError(
            path, f"missing array {e} — truncated or foreign file"
        ) from e
    if global_batch_size is None:
        global_batch_size = meta["global_batch_size"]
    spec = make_model_spec(meta["sizes"], n_stages, global_batch_size)
    params_list = _partition(flat, spec)
    # shape sanity against the re-partitioned spec
    for sspec, layers in zip(spec.stages, params_list):
        for l, layer in enumerate(layers):
            want = (sspec.local_sizes[l + 1], sspec.local_sizes[l])
            if layer["W"].shape != want:
                raise ValueError(
                    f"checkpoint layer shape {layer['W'].shape} != spec {want}"
                )
    if not with_opt_state:
        return params_list, spec, meta
    opt_state = None
    if flat_parts or meta.get("opt_scalars"):
        opt_state = {
            "parts": {k: _partition(v, spec) for k, v in flat_parts.items()},
            "scalars": dict(meta.get("opt_scalars", {})),
        }
    return params_list, spec, meta, opt_state


# ---------------------------------------------------------------------------
# step-checkpoint directories: rotation + crash-recovery discovery
# ---------------------------------------------------------------------------


def step_checkpoint_path(ckpt_dir, global_step):
    """Canonical name of the snapshot at ``global_step``: zero-padded so
    lexical order == step order (``step-00000042.npz``)."""
    return Path(ckpt_dir) / f"step-{int(global_step):08d}.npz"


def list_step_checkpoints(ckpt_dir):
    """``[(global_step, path), ...]`` ascending by step; [] for a missing
    directory (a fresh run's ``--resume auto`` finds nothing, starts clean)."""
    d = Path(ckpt_dir)
    if not d.is_dir():
        return []
    out = []
    for p in d.iterdir():
        m = STEP_CHECKPOINT_RE.match(p.name)
        if m:
            out.append((int(m.group(1)), p))
    return sorted(out)


def rotate_step_checkpoints(ckpt_dir, keep, trusted=()):
    """Delete all but ``keep`` step snapshots; returns the removed paths.
    Retention is the corrupt-newest safety margin: fallback needs older
    snapshots to still exist.

    Ranking is usability-first, then step: a snapshot that fully verifies
    (checksum intact, all values finite — exactly ``find_latest_good``'s
    resume criteria) always outranks one that does not, regardless of step
    number. A blown-up or bit-rotted run leaves high-step unusable
    snapshots behind (a blow-up's own saves skip rotation — see
    ``save_step_checkpoint``); ranked purely by step they would crowd the
    healthy snapshots out of the keep window and rotation would delete the
    only ``resume='auto'`` targets — permanently unrecoverable. Instead
    the stale unusable pile is what rotation reclaims. Verification reads
    each candidate once per rotation; a caller that just wrote (and
    checksummed) snapshots in-process can list them in ``trusted`` to skip
    re-reading them (``TrainingSession`` passes the paths it wrote finite
    this run)."""
    if keep < 1:
        raise ValueError("keep must be >= 1")
    snaps = list_step_checkpoints(ckpt_dir)
    if len(snaps) <= keep:
        return []
    trusted = {Path(p).resolve() for p in trusted}

    def rank(item):
        step, path = item
        if path.resolve() in trusted:
            return (True, step)
        try:
            verify_checkpoint(path, require_finite=True)
        except CheckpointError:
            return (False, step)
        return (True, step)

    victims = [p for _, p in sorted(snaps, key=rank)[:-keep]]
    for p in victims:
        try:
            p.unlink()
        except OSError:
            pass  # retention is best-effort; a stale extra snapshot is harmless
    return victims


def find_newer_good(ckpt_dir, than_step=None, require_finite=True):
    """Checkpoint-dir WATCHER discovery: the newest verifying step snapshot
    STRICTLY newer than ``than_step`` (``None`` accepts any step). Returns
    ``(step, path, meta, skipped)`` — ``skipped`` lists ``(path, cause)``
    for every newer candidate that failed verification — or
    ``(None, None, None, skipped)`` when nothing newer verifies. This is
    ``find_latest_good`` with a freshness floor: the serving engine's hot
    weight reload polls it between dispatches to pick up snapshots a
    concurrent training run keeps writing, without ever re-loading the
    snapshot it already serves."""
    skipped = []
    for step, p in reversed(list_step_checkpoints(ckpt_dir)):
        if than_step is not None and step <= than_step:
            break  # list is step-ascending: nothing older can be newer
        try:
            meta = verify_checkpoint(p, require_finite=require_finite)
        except CheckpointError as e:
            skipped.append((p, e.cause))
            continue
        return step, p, meta, skipped
    return None, None, None, skipped


def find_latest_good(ckpt_dir, require_finite=True):
    """Crash-recovery discovery: walk the step snapshots NEWEST FIRST,
    verify each (read + checksum + optional finiteness), and return
    ``(path, meta, skipped)`` for the first one that verifies — ``skipped``
    lists ``(path, cause)`` for every newer snapshot that failed (the
    evidence the recovery record carries). Returns ``(None, None, skipped)``
    when nothing in the directory verifies (or it is empty/missing)."""
    skipped = []
    for _, p in reversed(list_step_checkpoints(ckpt_dir)):
        try:
            meta = verify_checkpoint(p, require_finite=require_finite)
        except CheckpointError as e:
            skipped.append((p, e.cause))
            continue
        return p, meta, skipped
    return None, None, skipped
