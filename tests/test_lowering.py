"""Lowering tests: replay tick programs symbolically and verify dataflow.

The tick program is the load-bearing artifact of the whole SPMD pipeline —
these tests interpret it with symbolic payloads (no arrays, no jax) and
assert that every stage's forward consumes exactly the right microbatch's
activations from its predecessor, every backward consumes the right gradient
from its successor, mailboxes never collide, and the tick counts match the
textbook formulas for each schedule.
"""

import numpy as np
import pytest

from shallowspeed_tpu import schedules as S
from shallowspeed_tpu.parallel.lowering import (
    OP_BWD,
    OP_BWD_W,
    OP_FWD,
    OP_NOOP,
    ScheduleLoweringError,
    lower_schedule,
    weighted_utilization,
)

TRAIN = [S.NaiveParallelSchedule, S.GPipeSchedule, S.PipeDreamFlushSchedule]
GRID = [(4, 1), (4, 2), (4, 4), (2, 4), (8, 4), (1, 3), (4, 8)]


def replay(p):
    """Symbolically execute a TickProgram; returns per-stage event log.

    Payloads are tuples ("act"|"grad", mubatch, from_stage). Raises on any
    mailbox misuse; returns events[(t, s)] = (op, mb, consumed_payload).
    Split programs additionally model the two-stash discipline: a B-input
    PEEKS the activation stash (written by the forward, still held) and
    fills a grad-stash slot; the matching B-weight frees both.
    """
    Kf, Kb, Ks = p.n_fwd_slots, p.n_bwd_slots, p.n_stash_slots
    Kg = p.n_gstash_slots
    fwd_mail = [[None] * Kf for _ in range(p.num_stages)]
    bwd_mail = [[None] * Kb for _ in range(p.num_stages)]
    stash = [[None] * Ks for _ in range(p.num_stages)]
    gstash = [[None] * Kg for _ in range(p.num_stages)]
    events = {}
    for t in range(p.num_ticks):
        outgoing = []  # (dst, direction, slot, payload)
        for s in range(p.num_stages):
            op, mb = int(p.op[t, s]), int(p.mb[t, s])
            consumed = None
            rf, rb = int(p.read_fwd_slot[t, s]), int(p.read_bwd_slot[t, s])
            if rf != Kf:
                consumed = fwd_mail[s][rf]
                assert consumed is not None, f"read from empty fwd slot at t={t} s={s}"
                fwd_mail[s][rf] = None
            if rb != Kb:
                assert consumed is None
                consumed = bwd_mail[s][rb]
                assert consumed is not None, f"read from empty bwd slot at t={t} s={s}"
                bwd_mail[s][rb] = None
            # activation stash: forwards write a free slot, the matching
            # backward (B-weight in a split program) reads and frees it
            sw, sr = int(p.stash_write[t, s]), int(p.stash_read[t, s])
            if sw != Ks:
                assert op == OP_FWD
                assert stash[s][sw] is None, f"stash overwrite t={t} s={s}"
                stash[s][sw] = mb
            if sr != Ks:
                assert op == (OP_BWD_W if p.backward_split else OP_BWD)
                assert stash[s][sr] == mb, (
                    f"backward reads wrong stash at t={t} s={s}: "
                    f"expected mb={mb}, slot holds {stash[s][sr]}"
                )
                stash[s][sr] = None
            if p.backward_split:
                sp = int(p.stash_peek[t, s])
                gw, gr = int(p.gstash_write[t, s]), int(p.gstash_read[t, s])
                if sp != Ks:
                    # B-input peek: the slot must hold THIS microbatch's
                    # residuals and must NOT be freed (B-weight frees it)
                    assert op == OP_BWD
                    assert stash[s][sp] == mb, f"B-in peeks wrong stash t={t} s={s}"
                if gw != Kg:
                    assert op == OP_BWD
                    assert gstash[s][gw] is None, f"grad-stash overwrite t={t} s={s}"
                    gstash[s][gw] = mb
                if gr != Kg:
                    assert op == OP_BWD_W
                    assert gstash[s][gr] == mb, (
                        f"B-weight reads wrong grad stash at t={t} s={s}"
                    )
                    gstash[s][gr] = None
                if p.is_training and op == OP_BWD:
                    assert sp != Ks and gw != Kg, f"B-in without stashes t={t} s={s}"
                if op == OP_BWD_W:
                    assert sr != Ks and gr != Kg, f"B-w without stashes t={t} s={s}"
            elif p.is_training and op == OP_BWD:
                assert sr != Ks, f"backward without stash read at t={t} s={s}"
            if op != OP_NOOP:
                events[(t, s)] = (op, mb, consumed)
            if p.send_fwd[t, s]:
                assert op == OP_FWD
                outgoing.append((s + 1, "fwd", ("act", mb, s)))
            if p.send_bwd[t, s]:
                assert op == OP_BWD  # B-weights never send
                outgoing.append((s - 1, "bwd", ("grad", mb, s)))
        for dst, direction, payload in outgoing:
            mail = fwd_mail if direction == "fwd" else bwd_mail
            slot_tab = p.in_fwd_slot if direction == "fwd" else p.in_bwd_slot
            slot = int(slot_tab[t, dst])
            assert slot != (Kf if direction == "fwd" else Kb), (
                f"payload to stage {dst} at t={t} has no assigned slot"
            )
            assert mail[dst][slot] is None, f"mailbox collision at t={t} dst={dst}"
            mail[dst][slot] = payload
    for s in range(p.num_stages):
        assert all(x is None for x in fwd_mail[s] + bwd_mail[s]), "leftover messages"
        assert all(x is None for x in stash[s]), "leaked activation stash"
        assert all(x is None for x in gstash[s]), "leaked grad stash"
    return events


@pytest.mark.parametrize("cls", TRAIN)
@pytest.mark.parametrize("M,St", GRID)
def test_dataflow_correctness(cls, M, St):
    p = lower_schedule(cls, M, St)
    events = replay(p)
    for (t, s), (op, mb, consumed) in events.items():
        if op == OP_FWD:
            if s == 0:
                assert consumed is None  # loads from the dataset
            else:
                assert consumed == ("act", mb, s - 1), (t, s, mb, consumed)
        elif op == OP_BWD:
            if s == St - 1:
                assert consumed is None  # consumes loaded targets
            else:
                assert consumed == ("grad", mb, s + 1), (t, s, mb, consumed)
    # every stage does M forwards and M backwards
    for s in range(St):
        ops_s = [v[0] for (t, ss), v in events.items() if ss == s]
        assert ops_s.count(OP_FWD) == M and ops_s.count(OP_BWD) == M


@pytest.mark.parametrize("M,St", GRID)
def test_inference_dataflow(M, St):
    p = lower_schedule(S.InferenceSchedule, M, St)
    events = replay(p)
    assert all(v[0] == OP_FWD for v in events.values())
    assert not p.is_training


class TestTickCounts:
    """Lowered latency must equal the textbook schedule depth."""

    @pytest.mark.parametrize("M,St", [(4, 2), (4, 4), (8, 4), (2, 4)])
    def test_gpipe(self, M, St):
        assert lower_schedule(S.GPipeSchedule, M, St).num_ticks == 2 * (M + St - 1)

    @pytest.mark.parametrize("M,St", [(4, 2), (4, 4), (8, 4)])
    def test_pipedream_no_slower_than_gpipe(self, M, St):
        assert (
            lower_schedule(S.PipeDreamFlushSchedule, M, St).num_ticks
            <= lower_schedule(S.GPipeSchedule, M, St).num_ticks
        )

    @pytest.mark.parametrize("M,St", [(4, 2), (4, 4)])
    def test_naive(self, M, St):
        assert lower_schedule(S.NaiveParallelSchedule, M, St).num_ticks == 2 * M * St

    @pytest.mark.parametrize("M,St", [(4, 4), (8, 2)])
    def test_inference(self, M, St):
        assert lower_schedule(S.InferenceSchedule, M, St).num_ticks == M + St - 1


class TestPipelineUtilization:
    def test_gpipe_bubble_fraction(self):
        """Busy ticks / total = M/(M+S-1) per phase — the GPipe bubble law."""
        M, St = 8, 4
        p = lower_schedule(S.GPipeSchedule, M, St)
        busy = (np.asarray(p.op) != OP_NOOP).sum()
        assert busy == 2 * M * St  # total work
        assert p.num_ticks == 2 * (M + St - 1)

    def test_naive_only_one_stage_active(self):
        p = lower_schedule(S.NaiveParallelSchedule, 4, 4)
        active_per_tick = (np.asarray(p.op) != OP_NOOP).sum(axis=1)
        assert (active_per_tick <= 1).all()


class TestValidation:
    def test_malformed_schedule_deadlocks(self):
        class Broken(S.Schedule):
            def steps(self):
                yield [S.ZeroGrad()]
                # stage 1 receives but stage 0 never sends -> deadlock
                if self.stage_id == 0:
                    yield [S.LoadMuBatchInput(mubatch_id=0), S.Forward(mubatch_id=0)]
                    yield [
                        S.LoadMuBatchTarget(mubatch_id=0),
                        S.BackwardGradAllReduce(mubatch_id=0),
                    ]
                else:
                    yield [S.RecvActivations(), S.Forward(mubatch_id=0)]
                    yield [S.BackwardGradAllReduce(mubatch_id=0)]
                yield [S.OptimizerStep()]

        with pytest.raises(ScheduleLoweringError):
            lower_schedule(Broken, 1, 2)

    def test_missing_optimizer_step_rejected(self):
        class NoOpt(S.Schedule):
            def steps(self):
                yield [S.ZeroGrad()]
                yield [S.LoadMuBatchInput(mubatch_id=0), S.Forward(mubatch_id=0)]
                yield [
                    S.LoadMuBatchTarget(mubatch_id=0),
                    S.BackwardGradAllReduce(mubatch_id=0),
                ]

        with pytest.raises(ScheduleLoweringError):
            lower_schedule(NoOpt, 1, 1, training=True)

    def test_out_of_order_consumer_pairs_correctly(self):
        """A receiver that consumes microbatches in a different order than its
        peer emits them must get the RIGHT payloads (mailbox binds messages by
        microbatch id, not FIFO position) — never silently mispair."""

        class Swapped(S.Schedule):
            # stage 0 sends fwd mb0 then mb1; stage 1 consumes mb1 first
            def steps(self):
                yield [S.ZeroGrad()]
                if self.stage_id == 0:
                    for mb in (0, 1):
                        yield [
                            S.LoadMuBatchInput(mubatch_id=mb),
                            S.Forward(mubatch_id=mb),
                            S.SendActivations(),
                        ]
                    for mb in (0, 1):
                        yield [
                            S.RecvOutputGrad(),
                            (S.BackwardGradAllReduce if mb == 1 else S.BackwardGradAcc)(
                                mubatch_id=mb
                            ),
                        ]
                else:
                    for mb in (1, 0):  # swapped consumption order
                        yield [S.RecvActivations(), S.Forward(mubatch_id=mb)]
                    for mb in (0, 1):
                        yield [
                            S.LoadMuBatchTarget(mubatch_id=mb),
                            (S.BackwardGradAllReduce if mb == 1 else S.BackwardGradAcc)(
                                mubatch_id=mb
                            ),
                            S.SendInputGrad(),
                        ]
                yield [S.OptimizerStep()]

        p = lower_schedule(Swapped, 2, 2)
        events = replay(p)  # replay asserts every consume matches its mubatch
        fwd_order_s1 = [
            v[1] for (t, s), v in sorted(events.items()) if s == 1 and v[0] == OP_FWD
        ]
        assert fwd_order_s1 == [1, 0]

    def test_incomplete_mubatch_coverage_rejected(self):
        class Skips(S.GPipeSchedule):
            def steps(self):
                for step in super().steps():
                    # drop forward of mubatch 1
                    yield [
                        c
                        for c in step
                        if not (isinstance(c, S.Forward) and c.mubatch_id == 1)
                    ]

        with pytest.raises(ScheduleLoweringError):
            lower_schedule(Skips, 2, 1)


# ---------------------------------------------------------------------------
# Split backward (B-input / B-weight)
# ---------------------------------------------------------------------------

SPLIT_TRAIN = TRAIN  # every flat training schedule lowers a split variant


@pytest.mark.parametrize("cls", SPLIT_TRAIN)
@pytest.mark.parametrize("M,St", [(4, 2), (4, 4), (8, 4), (2, 4)])
def test_split_dataflow_and_bin_ticks_match_unsplit(cls, M, St):
    """The split program's relays must be indistinguishable from the
    unsplit one: every B-input sits at EXACTLY the tick (and consumes
    exactly the payload) the combined backward would have, forwards are
    untouched, and the deferred B-weights pair one-to-one with their
    B-inputs through the stash discipline (replay() asserts it)."""
    u = lower_schedule(cls, M, St)
    p = lower_schedule(cls, M, St, backward_split=True)
    assert p.backward_split and not u.backward_split
    T = u.num_ticks
    assert p.num_ticks >= T
    # identical F and B(-input) placement over the unsplit makespan, and
    # nothing but B-weights in the extension
    assert ((p.op[:T] == OP_FWD) == (u.op == OP_FWD)).all()
    assert ((p.op[:T] == OP_BWD) == (u.op == OP_BWD)).all()
    assert np.isin(p.op[T:], (OP_NOOP, OP_BWD_W)).all()
    # same send tables over the shared prefix, none after (B-w never sends)
    assert (p.send_fwd[:T] == u.send_fwd).all() and (p.send_bwd[:T] == u.send_bwd).all()
    assert not p.send_fwd[T:].any() and not p.send_bwd[T:].any()
    events = replay(p)
    for (t, s), (op, mb, consumed) in events.items():
        if op == OP_BWD and s != St - 1:
            assert consumed == ("grad", mb, s + 1)
        elif op == OP_BWD_W:
            assert consumed is None
    # every stage: M forwards, M B-inputs, M B-weights
    for s in range(St):
        ops_s = [v[0] for (t, ss), v in events.items() if ss == s]
        assert ops_s.count(OP_FWD) == M
        assert ops_s.count(OP_BWD) == M
        assert ops_s.count(OP_BWD_W) == M


@pytest.mark.parametrize("cls", SPLIT_TRAIN)
@pytest.mark.parametrize("M,St", [(4, 4), (8, 4)])
def test_split_bweight_order_matches_backward_order(cls, M, St):
    """Per stage, B-weights execute in the B-input (= combined backward)
    order — the weight-grad accumulation-order contract behind bitwise
    parity."""
    p = lower_schedule(cls, M, St, backward_split=True)
    for s in range(St):
        bin_order = [int(p.mb[t, s]) for t in range(p.num_ticks) if p.op[t, s] == OP_BWD]
        bww_order = [
            int(p.mb[t, s]) for t in range(p.num_ticks) if p.op[t, s] == OP_BWD_W
        ]
        assert bww_order == bin_order


def test_split_weighted_bubble_shrinks_1f1b_p4_m8():
    """The acceptance criterion, from the ACTUAL lowered tick tables:
    split 1F1B at P=4, M=8 has a strictly smaller FLOP-weighted bubble
    fraction than unsplit 1F1B (and GPipe behaves the same way)."""
    u = lower_schedule(S.PipeDreamFlushSchedule, 8, 4)
    p = lower_schedule(S.PipeDreamFlushSchedule, 8, 4, backward_split=True)
    assert (1 - weighted_utilization(p)) < (1 - weighted_utilization(u))
    # pin the measured figures docs/lowering.md quotes (40% -> 11%)
    assert round((1 - weighted_utilization(u)) * 100) == 40
    assert round((1 - weighted_utilization(p)) * 100) == 11
    ug = lower_schedule(S.GPipeSchedule, 8, 4)
    pg = lower_schedule(S.GPipeSchedule, 8, 4, backward_split=True)
    assert (1 - weighted_utilization(pg)) < (1 - weighted_utilization(ug))


def test_split_anchor_is_final_bweight():
    """In a split stream the DP all-reduce anchor is the last B-WEIGHT,
    never a B-input (the gradient is incomplete until the last deferred
    wgrad lands)."""
    for cls in SPLIT_TRAIN:
        for stage in range(4):
            cmds = S.flat_commands(
                cls(num_micro_batches=4, num_stages=4, stage_id=stage,
                    backward_split=True)
            )
            ar = [c for c in cmds if isinstance(c, S.BackwardWeightGradAllReduce)]
            bww = [c for c in cmds if isinstance(c, S.BackwardWeightGradAcc)]
            assert len(ar) == 1 and bww[-1] is ar[0]
            assert not any(isinstance(c, S.BackwardGradAllReduce) for c in cmds)


class TestSplitValidation:
    def _lower_mangled(self, mangle):
        """Lower split GPipe with ``mangle`` applied to each stage's
        flattened command list (a deliberately broken stream generator)."""

        class Mangled(S.GPipeSchedule):
            def steps(self):
                cmds = [c for step in super().steps() for c in step]
                yield mangle(list(cmds))

        return lower_schedule(Mangled, 2, 2, backward_split=True)

    def test_misordered_bweight_stream_rejected(self):
        """The acceptance criterion: a B-weight stream whose order
        disagrees with the B-input order (breaking the accumulation-order
        contract) fails at lowering time — even though every B-weight
        still FOLLOWS its own B-input."""

        def defer_weights_reversed(cmds):
            # pull every B-weight out and append them all at the end in
            # REVERSED (= forward) order: GPipe's B-inputs ran in backward
            # order, so the accumulation order no longer matches
            ws = [c for c in cmds if isinstance(c, S.BackwardWeightGradAcc)]
            rest = [c for c in cmds if not isinstance(c, S.BackwardWeightGradAcc)]
            opt = rest.pop()  # OptimizerStep stays last
            return rest + list(reversed(ws)) + [opt]

        with pytest.raises(ScheduleLoweringError, match="order"):
            self._lower_mangled(defer_weights_reversed)

    def test_bweight_before_its_binput_rejected(self):
        def hoist_weight(cmds):
            i = next(
                i for i, c in enumerate(cmds)
                if isinstance(c, S.BackwardWeightGradAcc)
            )
            w = cmds.pop(i)
            # re-insert it before the backward phase begins: its B-input
            # (and everyone else's) has not run yet
            j = next(
                j for j, c in enumerate(cmds)
                if isinstance(
                    c,
                    (S.RecvOutputGrad, S.LoadMuBatchTarget, S.BackwardInputGradAcc),
                )
            )
            cmds.insert(j, w)
            return cmds

        with pytest.raises(ScheduleLoweringError, match="precedes"):
            self._lower_mangled(hoist_weight)

    def test_missing_bweight_rejected(self):
        def drop_weight(cmds):
            i = next(
                i for i, c in enumerate(cmds)
                if type(c) is S.BackwardWeightGradAcc
            )
            cmds.pop(i)
            return cmds

        with pytest.raises(ScheduleLoweringError):
            self._lower_mangled(drop_weight)

    def test_mixed_split_and_combined_rejected(self):
        def mix(cmds):
            # replace the first B-input/B-weight pair with a combined
            # backward: the stream now mixes both styles
            i = next(
                i for i, c in enumerate(cmds)
                if isinstance(c, S.BackwardInputGradAcc)
            )
            first = cmds[i]
            cmds[i] = S.BackwardGradAcc(mubatch_id=first.mubatch_id)
            j = next(
                j for j, c in enumerate(cmds)
                if isinstance(c, S.BackwardWeightGradAcc)
                and c.mubatch_id == first.mubatch_id
            )
            cmds.pop(j)
            return cmds

        with pytest.raises(ScheduleLoweringError, match="mixes"):
            self._lower_mangled(mix)

    def test_interleaved_split_rejected(self):
        with pytest.raises(ScheduleLoweringError, match="interleaved"):
            lower_schedule(S.InterleavedSchedule, 4, 4, virtual=2, backward_split=True)
