"""SSP004 good twin: no donation outside the whitelist (serving-shaped
code holds its buffers — the params serve the very next dispatch)."""


def make_step(jax, step_impl):
    return jax.jit(step_impl)
