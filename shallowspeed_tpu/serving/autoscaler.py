"""Closed-loop autoscaling policy: the mechanism the capacity
scoreboard judges (ROADMAP item 4 / ISSUE 18).

Every input is something the repo already measures — nothing here is a
guess. The knee comes from ``bench_serving``'s swept ``knee_rps``; SLO
burn and knee proximity arrive as ``SloEvaluator`` edges through the
:class:`~shallowspeed_tpu.observability.slo.AlertSink` contract; queue
depth, replica readiness and the rolling admitted rate come from
``ServingFleet.status()`` polled between edges. Decisions flow the other
way as schema-v13 ``autoscale`` records, each carrying its evidence
(rule, rollup window, fleet size before/after), so the report CLI's
Capacity section can replay WHY the loop acted, not just when.

RE-ENTRANCY: ``alert()`` is called synchronously from inside the
fleet's telemetry choke points (mid ``submit``/``step``), so the sink
only QUEUES the edge; all scaling actions happen in :meth:`tick`, which
the open-loop driver calls from its own iteration (``run_open_loop``'s
``on_tick`` hook) — the policy never mutates the fleet from inside the
fleet.

POLICY (docs/serving.md § Autoscaling & capacity scoreboard):

- **scale out** when a ``knee_proximity`` edge fires (admitted rate
  within 10% of the measured knee), when ``error_burn`` fires with the
  p99 burn concentrated in the fleet queue (backlog, not worker
  pathology), or when the polled admitted rate exceeds ``headroom`` x
  knee x ready replicas — growth is cheap to reverse, so the out path
  is eager (cooldown ``out_cooldown_s``).
- **scale in** only on SUSTAINED slack — no active alerts, an empty
  fleet queue, and an admitted rate the remaining replicas could carry
  at under ``slack_fraction`` of their aggregate knee, all holding
  continuously for ``slack_hold_s`` — and only after the longer
  ``in_cooldown_s`` since the last scaling action. The asymmetry IS the
  hysteresis: the scoreboard's flap count (a direction reversal within
  ``flap_window_s``) must stay zero through the kill-injected chaos
  leg.
- **replace** a dead replica immediately (``wait_ready=False`` — the
  fleet keeps serving on the survivors while the replacement warms).
  Replacement restores the intended size; it is NOT a direction change
  and can never flap.
- **admission backpressure** while replacements warm: with fewer ready
  replicas than intended and a backlog deeper than the analytical
  drain budget (queue that the ready replicas can clear inside the SLO
  at the measured per-request floor), the gate sheds new arrivals at
  admission — refusals the scoreboard charges honestly as violations —
  instead of letting an unbounded backlog burn every queued deadline.
"""

import math

from shallowspeed_tpu.observability.metrics import NullMetrics
from shallowspeed_tpu.observability.slo import AlertSink

AUTOSCALER_VERSION = 1

# the scale-out alert edges the sink reacts to (module docstring);
# fleet_degraded routes to the replacement path, not growth
_OUT_EDGE_RULES = ("knee_proximity", "error_burn")


class AutoscalePolicy(AlertSink):
    """The closed-loop policy. Construct, pass as an ``alert_sinks``
    entry to ``ServingFleet``, then :meth:`attach` the started fleet and
    call :meth:`tick` from the drive loop (``run_open_loop(...,
    on_tick=policy.tick)``). ``decisions`` accumulates every JSON-able
    decision record (the same dict emitted as a v13 ``autoscale``
    metrics record); ``flaps`` counts direction reversals inside
    ``flap_window_s``."""

    def __init__(
        self,
        knee_rps,
        min_replicas=1,
        max_replicas=4,
        metrics=None,
        slo_ms=None,
        floor_s=None,
        headroom=0.8,
        slack_fraction=0.5,
        slack_hold_s=6.0,
        out_cooldown_s=2.0,
        in_cooldown_s=12.0,
        flap_window_s=30.0,
        warm_queue_budget=32,
        tags=None,
    ):
        if knee_rps is None or knee_rps <= 0:
            raise ValueError(
                "AutoscalePolicy needs the measured knee_rps — run the "
                "bench_serving sweep first (measurement before mechanism)"
            )
        if min_replicas < 1 or max_replicas < min_replicas:
            raise ValueError("need 1 <= min_replicas <= max_replicas")
        self.knee_rps = float(knee_rps)
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.slo_ms = slo_ms
        self.floor_s = floor_s
        self.headroom = float(headroom)
        self.slack_fraction = float(slack_fraction)
        self.slack_hold_s = float(slack_hold_s)
        self.out_cooldown_s = float(out_cooldown_s)
        self.in_cooldown_s = float(in_cooldown_s)
        self.flap_window_s = float(flap_window_s)
        self.warm_queue_budget = int(warm_queue_budget)
        # constant evidence merged into every decision record — the
        # bench tags each leg (leg="autoscaled"/"chaos") so one JSONL
        # stream can carry all three replays
        self.tags = dict(tags or {})
        self._metrics = metrics if metrics is not None else NullMetrics()
        self._fleet = None
        self._pending_edges = []  # queued by alert(), drained by tick()
        self._deaths_handled = 0
        self._slack_since = None
        self._last_scale_t = None
        self._last_direction = None  # "out" | "in" — replacements excluded
        self._backpressure = False
        self.decisions = []
        self.flaps = 0

    # -- wiring --------------------------------------------------------------

    def attach(self, fleet):
        """Bind the policy to a (started) fleet: installs the admission
        gate and baselines the death counter so pre-attach history is
        not re-replaced."""
        self._fleet = fleet
        self._deaths_handled = fleet.status()["replicas_dead"]
        fleet.set_admission_gate(self._gate)
        return self

    def _gate(self, _fleet):
        # consulted per submit — a flag read, nothing else (the heavy
        # reasoning happened in tick, on the driver thread)
        return "backpressure_warming" if self._backpressure else None

    # -- the AlertSink half (edges) ------------------------------------------

    def alert(self, record):
        """Queue the edge for the next tick (sink contract: called from
        inside fleet telemetry — never scale from here)."""
        self._pending_edges.append(dict(record))

    # -- the polling half (decisions) ----------------------------------------

    def tick(self, now):
        """One decision pass at ``now`` (seconds on the drive/trace
        timeline — ``run_open_loop`` passes elapsed time). Order
        matters: replacement first (restores intended capacity),
        backpressure next (bounds the backlog while warming), then
        scale-out edges/poll, then the slack scale-in."""
        if self._fleet is None:
            raise RuntimeError("attach(fleet) before tick()")
        status = self._fleet.status()
        edges = self._pending_edges
        self._pending_edges = []
        if self._check_replace(now, status):
            # the respawn changed the live count — re-read before the
            # sizing rules, or scale-out would price the dead replica's
            # slot twice and overshoot max_replicas
            status = self._fleet.status()
        self._check_backpressure(now, status)
        self._check_scale_out(now, status, edges)
        self._check_scale_in(now, status)

    # -- decision paths ------------------------------------------------------

    @staticmethod
    def _live(status):
        """Replicas the fleet is paying for and intends to keep:
        ``starting`` (spawned, warming its ladder) + ``ready``. NOT
        ``replicas_target`` — a non-blocking growth replica joins the
        quorum target only at READY (the fleet's deferred-target rule),
        so counting the target would let the policy re-fire scale-out
        every cooldown while the first new replica is still warming."""
        return sum(
            1
            for pr in status["per_replica"].values()
            if pr["state"] in ("starting", "ready")
        )

    def _check_replace(self, now, status):
        """Respawn for a newly observed death; returns True when a
        replacement was spawned (the caller re-reads fleet status)."""
        dead = status["replicas_dead"]
        if dead <= self._deaths_handled:
            return False
        self._deaths_handled = dead
        self._fleet.scale_up(wait_ready=False)
        after = self._fleet.status()
        self._record(
            now,
            "replace",
            direction="hold",
            rule="poll",
            status=status,
            replicas_after=self._live(after),
            value=dead,
            threshold=None,
            reason=(
                f"replica death #{dead}: respawn while survivors serve "
                f"(wait_ready=False; target unchanged — replacement, not "
                f"growth)"
            ),
        )
        return True

    def _warm_budget(self, status):
        ready = max(1, status["replicas_ready"])
        if self.slo_ms is not None and self.floor_s:
            # the analytical drain budget: backlog the ready replicas
            # can clear inside the SLO at the measured service floor
            return max(1, int(math.floor(
                (self.slo_ms / 1000.0) / self.floor_s * ready
            )))
        return self.warm_queue_budget

    def _check_backpressure(self, now, status):
        live = self._live(status)
        warming = status["replicas_ready"] < live
        budget = self._warm_budget(status)
        if self._backpressure:
            # release hysteresis: once shedding, hold the gate until the
            # backlog drains to half the engage budget — a queue hovering
            # at the budget must not toggle the gate every tick
            want = warming and status["queue_depth"] > max(1, budget // 2)
        else:
            want = warming and status["queue_depth"] > budget
        if want == self._backpressure:
            return
        self._backpressure = want
        self._record(
            now,
            "backpressure_on" if want else "backpressure_off",
            direction="hold",
            rule="poll",
            status=status,
            replicas_after=live,
            value=status["queue_depth"],
            threshold=budget,
            reason=(
                f"backlog {status['queue_depth']} vs drain budget {budget} "
                f"with {status['replicas_ready']}/{live} replicas ready"
                if want
                else "backlog drained under budget or fleet fully ready"
            ),
        )

    def _admitted_rate(self, status):
        last = (status.get("telemetry") or {}).get("rollup", {}).get(
            "last_window"
        )
        if not last:
            return None, None
        rate = (last.get("rates") or {}).get("admitted", {}).get("rate")
        return rate, last.get("window_end")

    def _can_grow(self, now, status):
        if self._live(status) >= self.max_replicas:
            return False
        return (
            self._last_scale_t is None
            or now - self._last_scale_t >= self.out_cooldown_s
        )

    def _check_scale_out(self, now, status, edges):
        trigger = None
        for edge in edges:
            if edge.get("state") != "firing":
                continue
            rule = edge.get("name")
            if rule == "knee_proximity":
                # the rule's threshold is calibrated against ONE
                # replica's measured knee (the engine semantics the
                # evaluator was armed with), so the edge is trusted
                # verbatim only while one replica carries the fleet;
                # past that, fleet-wide admitted rate crossing a
                # single-replica knee says nothing about aggregate
                # headroom — require the capacity-scaled poll criterion
                # to corroborate before buying
                if self._live(status) > 1:
                    rate, _ = self._admitted_rate(status)
                    cap = (self.headroom * self.knee_rps
                           * max(1, self._live(status)))
                    if rate is None or rate <= cap:
                        continue
                trigger = (rule, edge.get("value"), edge.get("threshold"),
                           edge.get("reason"))
                break
            if rule == "error_burn" and status["queue_depth"] > 0:
                trigger = (rule, edge.get("value"), edge.get("threshold"),
                           f"{edge.get('reason')} with p99 burn concentrated "
                           f"in fleet.queue (depth {status['queue_depth']})")
                break
        window_end = None
        if trigger is None:
            rate, window_end = self._admitted_rate(status)
            # size by LIVE replicas (starting + ready): a replica still
            # warming is capacity already bought, and pricing it at zero
            # would re-buy for the same demand every poll until it is
            # READY
            live = max(1, self._live(status))
            cap = self.headroom * self.knee_rps * live
            if rate is not None and rate > cap:
                trigger = (
                    "poll",
                    rate,
                    cap,
                    f"admitted rate {rate:.1f} rps above {self.headroom:g} x "
                    f"knee x {live} live replicas ({cap:.1f} rps)",
                )
        if trigger is None or not self._can_grow(now, status):
            return
        rule, value, threshold, reason = trigger
        self._fleet.scale_up(wait_ready=False)
        after = self._fleet.status()
        self._slack_since = None
        self._record(
            now,
            "scale_out",
            direction="out",
            rule=rule,
            status=status,
            replicas_after=self._live(after),
            value=value,
            threshold=threshold,
            reason=reason,
            window_end=window_end,
        )

    def _check_scale_in(self, now, status):
        rate, window_end = self._admitted_rate(status)
        live = self._live(status)
        remaining = status["replicas_ready"] - 1
        # active alerts veto the drain — EXCEPT knee_proximity, whose
        # threshold is one replica's knee (see _check_scale_out): a
        # multi-replica fleet holds it active whenever fleet-wide rate
        # exceeds one replica's capacity, which is normal operation, not
        # distress; the slack threshold below already prices remaining
        # capacity
        blocking_alerts = {
            name: sev
            for name, sev in status["alerts_active"].items()
            if name != "knee_proximity"
        }
        slack = (
            not blocking_alerts
            and not status["degraded"]
            and status["queue_depth"] == 0
            and status["replicas_ready"] == live  # nothing still warming
            and live > self.min_replicas
            and rate is not None
            and remaining >= 1
            and rate < self.slack_fraction * self.knee_rps * remaining
        )
        if not slack:
            self._slack_since = None
            return
        if self._slack_since is None:
            self._slack_since = now
        if now - self._slack_since < self.slack_hold_s:
            return
        if (
            self._last_scale_t is not None
            and now - self._last_scale_t < self.in_cooldown_s
        ):
            return
        retired = self._fleet.scale_down()
        after = self._fleet.status()
        self._slack_since = None
        self._record(
            now,
            "scale_in",
            direction="in",
            rule="poll",
            status=status,
            replicas_after=self._live(after),
            value=rate,
            threshold=self.slack_fraction * self.knee_rps * remaining,
            reason=(
                f"sustained slack >= {self.slack_hold_s:g}s: admitted "
                f"{rate:.1f} rps under {self.slack_fraction:g} x knee x "
                f"{remaining} remaining replicas; drained replica {retired}"
            ),
            window_end=window_end,
        )

    # -- the evidence trail --------------------------------------------------

    def _record(
        self,
        now,
        decision,
        direction,
        rule,
        status,
        replicas_after,
        value,
        threshold,
        reason,
        window_end=None,
    ):
        flap = False
        if direction in ("out", "in"):
            if (
                self._last_direction is not None
                and self._last_direction != direction
                and self._last_scale_t is not None
                and now - self._last_scale_t < self.flap_window_s
            ):
                flap = True
                self.flaps += 1
            self._last_direction = direction
            self._last_scale_t = now
        if window_end is None:
            _rate, window_end = self._admitted_rate(status)
        record = {
            "direction": direction,
            "rule": rule,
            "t": now,
            "replicas_before": self._live(status),
            "replicas_after": replicas_after,
            "replicas_ready": status["replicas_ready"],
            "queue_depth": status["queue_depth"],
            "window_end": window_end,
            "value": value,
            "threshold": threshold,
            "flap": flap,
            "reason": reason,
            **self.tags,
        }
        self.decisions.append({"decision": decision, **record})
        self._metrics.autoscale(decision, **record)
