"""Multi-host wrapper smoke tests (single-process semantics only — the CI
environment has no second host; the executor itself is the tested surface)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from shallowspeed_tpu.parallel import make_mesh, multihost


def test_initialize_is_noop_single_process():
    multihost.initialize()  # must not raise without a coordinator
    assert jax.process_count() == 1


def test_shard_batch_for_process_places_on_mesh():
    mesh = make_mesh(2, 4)
    x = np.arange(16 * 3, dtype=np.float32).reshape(16, 3)
    arr = multihost.shard_batch_for_process(x, mesh, P("dp"))
    assert arr.shape == (16, 3)
    np.testing.assert_array_equal(np.asarray(arr), x)
    # sharded over dp, replicated over pp: 8 devices, 2 distinct row-shards
    assert len({s.index for s in arr.addressable_shards}) == 2
