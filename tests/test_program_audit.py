"""XLA program audit tests: HLO collective parsing, memory analysis through
the shared helper, the analytical comms model, and the census-vs-contract
invariant across every layout (seq / DP / pipeline / ZeRO-1) — the
acceptance criterion that "the DP all-reduce really is one psum" is a
tested property of the COMPILED program, not prose.
"""

import json

import numpy as np
import pytest

from shallowspeed_tpu.observability import JsonlMetrics, read_jsonl
from shallowspeed_tpu.observability import program_audit as pa

SIZES = (24, 20, 18, 16, 14, 12, 11, 10)
N, GBS = 256, 64


@pytest.fixture()
def data_dir(tmp_path):
    rng = np.random.RandomState(0)
    for suffix, n in (("train", N), ("val", 96)):
        x = rng.randn(n, SIZES[0]).astype(np.float32)
        y = np.eye(SIZES[-1], dtype=np.float32)[rng.randint(0, SIZES[-1], n)]
        np.save(tmp_path / f"x_{suffix}.npy", x)
        np.save(tmp_path / f"y_{suffix}.npy", y)
    return tmp_path


# ---------------------------------------------------------------------------
# HLO parsing
# ---------------------------------------------------------------------------

_SYNTHETIC_HLO = """\
HloModule jit_epoch, entry_computation_layout={...}

%region_0.4 (a: f32[], b: f32[]) -> f32[] {
  ROOT %add = f32[] add(f32[] %a, f32[] %b)
}

ENTRY %main {
  %p0 = f32[2,4]{1,0} parameter(0)
  %all-reduce.1 = f32[2,4]{1,0} all-reduce(f32[2,4]{1,0} %p0), replica_groups={{0,2},{1,3}}, to_apply=%region_0.4, metadata={op_name="jit(f)/psum"}
  %cp = f32[2,4]{1,0} collective-permute(f32[2,4]{1,0} %all-reduce.1), source_target_pairs={{0,1},{1,0}}
  %ars = f32[8]{0} all-reduce-start(f32[8]{0} %p1), to_apply=%region_0.4
  %ard = f32[8]{0} all-reduce-done(f32[8]{0} %ars)
  %rs = f32[4]{0} reduce-scatter(f32[8]{0} %ard), dimensions={0}, to_apply=%region_0.4
  %ag = f32[8]{0} all-gather(f32[4]{0} %rs), dimensions={0}
  %tup = (f32[4]{0}, bf16[2,2]{1,0}) all-gather(f32[2]{0} %rs, bf16[1,2]{1,0} %x), dimensions={0}
  ROOT %out = f32[8]{0} copy(f32[8]{0} %ag)
}
"""


def test_parse_collectives_counts_kinds_and_bytes():
    """Kinds, byte sizes (incl. tuple results and bf16), async -start
    counted once with its -done half skipped, metadata op_name strings
    never matched."""
    ops = pa.parse_collectives(_SYNTHETIC_HLO)
    kinds = sorted(o["kind"] for o in ops)
    assert kinds == [
        "all_gather", "all_gather", "all_reduce", "all_reduce",
        "collective_permute", "reduce_scatter",
    ]
    census = pa.collective_census(_SYNTHETIC_HLO)
    assert census["all_reduce"]["count"] == 2  # plain + -start (not -done)
    assert census["all_reduce"]["bytes"] == 2 * 4 * 4 + 8 * 4
    assert census["collective_permute"] == {"count": 1, "bytes": 32}
    assert census["reduce_scatter"] == {"count": 1, "bytes": 16}
    # tuple result: f32[8] one op + (f32[4] + bf16[2,2]) the other
    assert census["all_gather"]["count"] == 2
    assert census["all_gather"]["bytes"] == 8 * 4 + (4 * 4 + 2 * 2 * 2)
    assert "all_to_all" not in census


def test_parse_collectives_ignores_non_collective_lines():
    hlo = "%f = f32[4]{0} fusion(f32[4]{0} %x), kind=kLoop\n%c = f32[] copy(%y)\n"
    assert pa.parse_collectives(hlo) == []
    assert pa.collective_census(hlo) == {}


def test_parse_collectives_tpu_tiled_layouts():
    """TPU post-optimization HLO: tiled layouts put PARENTHESES inside the
    result type (``{1,0:T(8,128)}``) and async collectives return tuples —
    a paren-naive tuple match would drop exactly the ops the audit exists
    to see (a correct dp program would then fail its own contract)."""
    hlo = (
        "%ars = (f32[8,128]{1,0:T(8,128)}, f32[8,128]{1,0:T(8,128)}) "
        "all-reduce-start(f32[8,128]{1,0:T(8,128)} %p), to_apply=%sum\n"
        "%ard = f32[8,128]{1,0:T(8,128)} all-reduce-done(%ars)\n"
        "%cp = f32[4,128]{1,0:T(8,128)(4,1)} collective-permute("
        "f32[4,128]{1,0:T(8,128)(4,1)} %x), source_target_pairs={{0,1}}\n"
    )
    census = pa.collective_census(hlo)
    assert census["all_reduce"]["count"] == 1  # -start counted, -done not
    # the start tuple pairs the aliased operand with the result; only the
    # result leg counts, so the payload is not double-counted
    assert census["all_reduce"]["bytes"] == 8 * 128 * 4
    assert census["collective_permute"]["count"] == 1
    assert census["collective_permute"]["bytes"] == 4 * 128 * 4


# ---------------------------------------------------------------------------
# the contract check (fails loudly on a mismatched census)
# ---------------------------------------------------------------------------


def test_check_census_contract_rules():
    seq = {"required": [], "forbidden": ["all_reduce", "collective_permute"]}
    assert pa.check_census({}, seq) == []
    assert pa.check_census({"all_reduce": {"count": 3, "bytes": 1}}, seq)

    dp = {"required": ["all_reduce", "collective_permute"],
          "forbidden": ["reduce_scatter", "all_gather"]}
    ok = {"all_reduce": {"count": 14, "bytes": 1},
          "collective_permute": {"count": 2, "bytes": 1}}
    assert pa.check_census(ok, dp) == []
    # missing required kind
    assert pa.check_census({"collective_permute": {"count": 2, "bytes": 1}}, dp)
    # forbidden kind present (a ZeRO-1 lowering leaking into plain DP)
    bad = dict(ok, reduce_scatter={"count": 1, "bytes": 9})
    assert any("reduce_scatter" in m for m in pa.check_census(bad, dp))
    # a one-directional relay is a broken pipeline, even though the kind
    # is present
    one_way = dict(ok, collective_permute={"count": 1, "bytes": 1})
    assert any("BOTH directions" in m for m in pa.check_census(one_way, dp))


def test_verify_census_raises_loudly_on_mismatch():
    """The acceptance criterion's negative leg: a deliberately mismatched
    census fails with AuditMismatchError naming the violation."""
    expected = {"required": ["all_reduce"], "forbidden": ["all_gather"]}
    census = {"all_gather": {"count": 1, "bytes": 64}}
    with pytest.raises(pa.AuditMismatchError, match="all_reduce"):
        pa.verify_census(census, expected)
    with pytest.raises(pa.AuditMismatchError, match="forbidden"):
        pa.verify_census(
            {"all_reduce": {"count": 1, "bytes": 4},
             "all_gather": {"count": 1, "bytes": 64}},
            expected,
        )
    # matching census passes silently
    pa.verify_census({"all_reduce": {"count": 5, "bytes": 4}}, expected)
    # with per-op evidence, verify_census enforces the bucketed leg too
    bucketed = {
        "dp": 2, "zero1": False, "required": ["all_reduce"], "forbidden": [],
        "axes": {"dp": {"mode": "bucketed", "num_buckets": 2,
                        "bucket_census_bytes": [1024, 512]}},
    }
    leafy = [{"kind": "all_reduce", "bytes": b} for b in (700, 836)]
    with pytest.raises(pa.AuditMismatchError, match="bucketed sync"):
        pa.verify_census(pa.census_of_ops(leafy), bucketed, ops=leafy)


# ---------------------------------------------------------------------------
# the analytical comms model
# ---------------------------------------------------------------------------


def _mesh_session(data_dir, **kw):
    from shallowspeed_tpu.api import TrainingSession

    return TrainingSession(
        sizes=SIZES, global_batch_size=GBS, lr=0.01, data_dir=data_dir,
        record_steps=False, **kw,
    )


def test_expected_comms_pipeline_bytes_from_tick_tables(data_dir):
    """The pp-axis wire bytes are 2 ppermutes x ticks x payload from the
    ACTUAL lowered tables, with the send-table useful bytes alongside."""
    from shallowspeed_tpu.parallel.executor import relay_width
    from shallowspeed_tpu.parallel.lowering import program_comm_bytes

    run = _mesh_session(data_dir, pp=4, schedule="gpipe")
    exp = run._expected_comms
    prog, spec, mb = run._prog, run.spec, run._mubatch_local
    payload = 4 * mb * relay_width(spec)
    comm = program_comm_bytes(prog, spec, mb)
    assert comm["relay_payload_bytes"] == payload
    assert comm["wire_bytes_per_device"] == 2 * prog.num_ticks * payload
    sends = int(np.sum(prog.send_fwd) + np.sum(prog.send_bwd))
    assert comm["useful_sends"] == sends
    assert comm["useful_bytes_per_device"] == sends * payload / prog.num_stages

    pp_axis = exp["axes"]["pp"]
    assert pp_axis["bytes_per_step_per_device"] == comm["wire_bytes_per_device"]
    # useful <= wire: the relay's own padding tax is visible
    assert pp_axis["useful_bytes_per_step_per_device"] < pp_axis[
        "bytes_per_step_per_device"
    ]
    assert exp["required"] == ["collective_permute"]  # dp=1: no psum demanded
    assert "reduce_scatter" in exp["forbidden"]


def test_expected_comms_dp_ring_and_zero1_bytes(data_dir):
    """dp ring all-reduce moves 2(dp-1)/dp x padded grad bytes; ZeRO-1
    moves the same factor of the padded FLAT vector via reduce-scatter +
    all-gather (and requires both kinds, dp=1 included)."""
    from shallowspeed_tpu.parallel.executor import slot_shapes

    run = _mesh_session(data_dir, dp=2, pp=2, schedule="gpipe")
    exp = run._expected_comms
    dims = slot_shapes(run.spec)
    V = run.spec.n_stages // 2
    flat = sum(V * o * i for o, i in dims) + sum(V * o for o, _ in dims)
    assert exp["axes"]["dp"]["grad_bytes_per_device"] == 4 * flat
    assert exp["axes"]["dp"]["bytes_per_step_per_device"] == pytest.approx(
        2 * (2 - 1) / 2 * 4 * flat
    )
    assert "all_reduce" in exp["required"]
    assert exp["bytes_per_step_per_device"] == pytest.approx(
        exp["axes"]["dp"]["bytes_per_step_per_device"]
        + exp["axes"]["pp"]["bytes_per_step_per_device"]
    )

    z1 = _mesh_session(data_dir, dp=2, pp=2, schedule="gpipe", zero1=True)
    zexp = z1._expected_comms
    csz = -(-flat // 2)
    assert zexp["axes"]["dp"]["grad_bytes_per_device"] == 4 * csz * 2
    assert set(zexp["required"]) >= {"reduce_scatter", "all_gather",
                                     "collective_permute"}
    # ZeRO-1 at dp=1 still lowers both collectives — the contract says so
    z1s = _mesh_session(data_dir, dp=1, pp=2, schedule="gpipe", zero1=True)
    assert set(z1s._expected_comms["required"]) >= {"reduce_scatter",
                                                    "all_gather"}


def test_expected_comms_sequential_forbids_everything(data_dir):
    run = _mesh_session(data_dir)
    exp = run._expected_comms
    assert exp["sequential"] is True
    assert exp["required"] == []
    assert set(exp["forbidden"]) == {
        "all_reduce", "all_gather", "reduce_scatter", "collective_permute",
        "all_to_all",
    }
    assert exp["bytes_per_step_per_device"] == 0
    assert exp["bound"] == "compute"  # comms lower bound is zero


def test_bandwidth_and_hbm_provenance(monkeypatch):
    bw, src = pa.interconnect_bytes_per_sec("tpu")
    assert bw == pa.INTERCONNECT_BYTES_PER_SEC["tpu"] and "datasheet" in src
    bw, src = pa.interconnect_bytes_per_sec("cpu")
    assert "nominal" in src
    _, src = pa.interconnect_bytes_per_sec("rocm")
    assert "unknown-platform" in src
    monkeypatch.setenv(pa.ENV_BW, "123.0")
    bw, src = pa.interconnect_bytes_per_sec("tpu")
    assert bw == 123.0 and src == f"env:{pa.ENV_BW}"

    cap, src = pa.hbm_per_chip("axon")  # the tunnel platform is a TPU
    assert cap == pa.HBM_PER_CHIP["tpu"] and "datasheet" in src
    monkeypatch.setenv(pa.ENV_HBM, "456")
    cap, src = pa.hbm_per_chip("cpu")
    assert cap == 456.0 and src == f"env:{pa.ENV_HBM}"


def test_check_census_bucketed_rules():
    """The bucketed-sync contract leg: every planned bucket accounted for
    by a sync op of its exact size, or by a combiner-merged ADJACENT
    run's summed size; unaccountable sizes fail; skipped at dp=1 (XLA
    may elide degenerate collectives) and without the per-op list
    (census aggregates cannot carry sizes)."""
    expected = {
        "dp": 2, "zero1": False, "required": ["all_reduce"], "forbidden": [],
        "axes": {"dp": {"mode": "bucketed", "num_buckets": 3,
                        "bucket_census_bytes": [1024, 512, 256]}},
    }
    ops = [{"kind": "all_reduce", "bytes": 1024},
           {"kind": "all_reduce", "bytes": 512},
           {"kind": "all_reduce", "bytes": 256},
           {"kind": "all_reduce", "bytes": 4}]  # loss psum: ignored extra
    assert pa.check_census(pa.census_of_ops(ops), expected, ops=ops) == []
    # combiner-merged neighbors: [1024+512, 256] and the fully-merged
    # [1024+512+256] both account for every planned byte — accepted
    for merged_sizes in ([1536, 256], [1792]):
        merged = [{"kind": "all_reduce", "bytes": b} for b in merged_sizes]
        assert pa.check_census(
            pa.census_of_ops(merged), expected, ops=merged
        ) == [], merged_sizes
    # a NON-adjacent merge (1024+256, skipping the middle bucket) is not
    # a combiner shape — fails, naming both size lists
    wrong = [{"kind": "all_reduce", "bytes": 1280},
             {"kind": "all_reduce", "bytes": 512}]
    msgs = pa.check_census(pa.census_of_ops(wrong), expected, ops=wrong)
    assert any("cannot account" in m and "512" in m for m in msgs)
    # the unwired-knob shape: per-leaf anchor ops whose sizes cannot be
    # partitioned into the planned bucket sums
    leafy = [{"kind": "all_reduce", "bytes": b} for b in (700, 324, 400, 112, 200, 56)]
    assert pa.check_census(pa.census_of_ops(leafy), expected, ops=leafy)
    # zero1 buckets check reduce_scatter, not all_reduce
    z1 = {
        "dp": 2, "zero1": True,
        "required": ["reduce_scatter", "all_gather"], "forbidden": [],
        "axes": {"dp": {"mode": "bucketed", "num_buckets": 2,
                        "bucket_census_bytes": [256, 128]}},
    }
    zops = [{"kind": "reduce_scatter", "bytes": 256},
            {"kind": "reduce_scatter", "bytes": 128},
            {"kind": "all_gather", "bytes": 512}]
    assert pa.check_census(pa.census_of_ops(zops), z1, ops=zops) == []
    # dp=1: the bucketed legs are skipped entirely
    exp1 = dict(expected, dp=1, required=[])
    assert pa.check_census({}, exp1, ops=[]) == []
    # without ops only the kind legs run (no size evidence, no claim)
    assert pa.check_census(pa.census_of_ops(ops), expected) == []


# ---------------------------------------------------------------------------
# real compiled programs: the invariant across layouts (acceptance criterion)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "kw, present, absent",
    [
        (dict(), (), ("all_reduce", "collective_permute", "reduce_scatter",
                      "all_gather")),
        (dict(dp=2), ("all_reduce", "collective_permute"),
         ("reduce_scatter", "all_gather")),
        (dict(pp=4, schedule="gpipe"), ("collective_permute",),
         ("reduce_scatter", "all_gather")),
        (dict(dp=2, pp=2, schedule="gpipe", zero1=True),
         ("collective_permute", "reduce_scatter", "all_gather"), ()),
    ],
    ids=["seq", "dp2", "gpipe-pp4", "zero1"],
)
def test_compiled_census_matches_layout_contract(data_dir, kw, present, absent):
    """Each layout's COMPILED epoch program contains exactly the collective
    kinds its contract names: none sequentially, the dp grad all-reduce
    under DP, both relay permutes under pipeline, reduce-scatter +
    all-gather under ZeRO-1 — and audit_compiled agrees (census_ok)."""
    run = _mesh_session(data_dir, **kw)
    compiled = run._epoch_fn.lower(*run._epoch_args()).compile()
    rec = pa.audit_compiled(
        compiled, expected=run._expected_comms, platform="cpu",
        n_devices=run._cost_model.n_devices,
    )
    assert rec["hlo_available"] is True
    assert rec["census_ok"] is True, rec["mismatches"]
    census = rec["census"]
    for kind in present:
        assert census.get(kind, {}).get("count", 0) >= 1, (kind, census)
    for kind in absent:
        assert kind not in census, (kind, census)
    if "collective_permute" in present:
        assert census["collective_permute"]["count"] >= 2  # both directions
    # memory analysis through the shared helper: a positive peak and the
    # headroom leg against the (nominal) cpu capacity
    assert rec["memory"]["peak_hbm_bytes"] > 0
    assert rec["hbm_per_chip"] > 0 and "nominal" in rec["hbm_source"]
    assert rec["hbm_headroom_fraction"] < 1.0


def test_compiled_split_backward_census_and_tick_model(data_dir):
    """A --backward-split session's COMPILED program still satisfies the
    layout contract (both relay permutes, no dp collectives at dp=1), and
    its comms model honestly derives from the SPLIT tick tables: more
    ticks than the unsplit twin (the deferred B-weights extend the
    program; the uniform per-tick permutes really ship those extra zero
    payloads) at the same useful send count."""
    run = _mesh_session(data_dir, pp=4, schedule="pipedream", backward_split=True)
    ref = _mesh_session(data_dir, pp=4, schedule="pipedream")
    compiled = run._epoch_fn.lower(*run._epoch_args()).compile()
    rec = pa.audit_compiled(
        compiled, expected=run._expected_comms, platform="cpu",
        n_devices=run._cost_model.n_devices,
    )
    assert rec["census_ok"] is True, rec["mismatches"]
    assert rec["census"]["collective_permute"]["count"] >= 2
    split_pp = run._expected_comms["axes"]["pp"]
    ref_pp = ref._expected_comms["axes"]["pp"]
    assert split_pp["ticks"] > ref_pp["ticks"]
    assert split_pp["payload_bytes"] == ref_pp["payload_bytes"]
    assert (
        split_pp["useful_bytes_per_step_per_device"]
        == ref_pp["useful_bytes_per_step_per_device"]
    )
    # identical padded FLOPs: the split spreads the backward's work over
    # two cells, it never adds or recomputes any
    assert (
        run._cost_model.padded_flops_per_batch
        == ref._cost_model.padded_flops_per_batch
    )


def test_expected_comms_bucketed_contract_and_overlap_bounds(data_dir):
    """A bucketed session's contract: the dp axis carries the plan
    (mode/num_buckets/per-bucket bytes), TOTAL bytes are unchanged vs the
    anchor session, and the two step-time lower bounds hold their
    defining relations (serial = comm + compute, overlapped = max)."""
    anchor = _mesh_session(data_dir, dp=2, pp=2, schedule="gpipe")
    bucketed = _mesh_session(
        data_dir, dp=2, pp=2, schedule="gpipe", grad_bucket_bytes=2048
    )
    a, b = anchor._expected_comms, bucketed._expected_comms
    assert a["axes"]["dp"]["mode"] == "anchor"
    dpax = b["axes"]["dp"]
    assert dpax["mode"] == "bucketed"
    assert dpax["grad_bucket_bytes"] == 2048
    assert dpax["num_buckets"] == bucketed._sync_plan.num_buckets >= 2
    assert sum(dpax["bucket_grad_bytes"]) == dpax["grad_bytes_per_device"]
    # bucketing moves op granularity, never bytes
    assert b["bytes_per_step_per_device"] == a["bytes_per_step_per_device"]
    for exp in (a, b):
        ct, xt = exp["comms_time_per_step_s"], exp["compute_time_per_step_s"]
        assert exp["serial_bound_s"] == pytest.approx(ct + xt)
        assert exp["overlapped_bound_s"] == pytest.approx(max(ct, xt))
        assert exp["model_hidden_comm_share"] == pytest.approx(
            min(ct, xt) / ct
        )
        assert exp["serial_bound_s"] >= exp["overlapped_bound_s"]


@pytest.mark.parametrize(
    "kw, kind",
    [
        (dict(dp=2), "all_reduce"),
        (dict(dp=2, pp=2, schedule="gpipe", zero1=True), "reduce_scatter"),
    ],
    ids=["dp2-bucketed", "zero1-bucketed"],
)
def test_compiled_census_matches_bucket_plan(data_dir, kw, kind):
    """The bucketed acceptance criterion, positive leg: the COMPILED
    bucketed program really contains one sync collective per planned
    bucket at exactly the planned result sizes (the emitters lower one
    flat op per bucket; XLA does not merge them) — and audit_compiled
    agrees (census_ok)."""
    from collections import Counter

    run = _mesh_session(data_dir, grad_bucket_bytes=2048, **kw)
    plan = run._sync_plan
    assert plan is not None and plan.num_buckets >= 2
    compiled = run._epoch_fn.lower(*run._epoch_args()).compile()
    rec = pa.audit_compiled(
        compiled, expected=run._expected_comms, platform="cpu",
        n_devices=run._cost_model.n_devices,
    )
    assert rec["census_ok"] is True, rec["mismatches"]
    assert rec["census"][kind]["count"] >= plan.num_buckets
    ops = pa.parse_collectives(compiled.as_text())
    compiled_sizes = Counter(o["bytes"] for o in ops if o["kind"] == kind)
    planned = Counter(plan.bucket_census_bytes())
    assert not (planned - compiled_sizes), (planned, compiled_sizes)


def test_session_audit_raises_on_bucket_plan_mismatch(data_dir):
    """The bucketed negative leg: a deliberate plan/program mismatch (a
    contract demanding bucket sizes the emitters never lowered) raises
    AuditMismatchError BEFORE the first dispatch — and is never latched,
    so a retry re-refuses."""
    run = _mesh_session(data_dir, dp=2, audit=True, grad_bucket_bytes=2048)
    dpax = dict(run._expected_comms["axes"]["dp"])
    dpax["num_buckets"] = dpax["num_buckets"] + 7
    dpax["bucket_census_bytes"] = list(dpax["bucket_census_bytes"]) + [12345]
    run._expected_comms = dict(
        run._expected_comms,
        axes=dict(run._expected_comms["axes"], dp=dpax),
    )
    with pytest.raises(pa.AuditMismatchError, match="bucketed sync"):
        run.train_epoch()
    with pytest.raises(pa.AuditMismatchError, match="bucketed sync"):
        run.train_epoch()


def test_session_audit_true_raises_on_contract_violation(data_dir, monkeypatch):
    """TrainingSession(audit=True) fails loudly BEFORE the first dispatch
    when the census disagrees with the contract (forced here by breaking
    the contract, not the lowering — same mismatch path)."""
    run = _mesh_session(data_dir, dp=2, audit=True)
    run._expected_comms = dict(
        run._expected_comms, required=["all_to_all"], forbidden=["all_reduce"]
    )
    with pytest.raises(pa.AuditMismatchError, match="all_to_all"):
        run.train_epoch()
    # a caught-and-retried failure is re-audited and re-refused — the
    # mismatch is never latched as 'audited' (no silent training after)
    with pytest.raises(pa.AuditMismatchError, match="all_to_all"):
        run.train_epoch()


def test_expected_comms_pp1_permutes_are_not_interconnect_traffic(data_dir):
    """dp-only (pp=1) mesh layouts: the executor's relay permutes are
    device-local self-loops — allowed in the census but neither required
    nor counted as interconnect bytes, so the bandwidth bound reflects
    only the real dp all-reduce traffic."""
    run = _mesh_session(data_dir, dp=2)
    exp = run._expected_comms
    assert "collective_permute" not in exp["required"]
    assert "collective_permute" not in exp["forbidden"]
    assert "pp" not in exp["axes"]
    assert exp["bytes_per_step_per_device"] == exp["axes"]["dp"][
        "bytes_per_step_per_device"
    ]


def test_memory_stats_shared_helper_fields():
    """The one shared memory_analysis path: field split + peak estimate
    (args + outputs + temp - aliased when no explicit peak)."""
    import jax
    import jax.numpy as jnp

    compiled = jax.jit(lambda x: x * 2.0).lower(jnp.ones((128, 128))).compile()
    mem = pa.memory_stats(compiled)
    if mem is None:  # backend without memory_analysis: helper stays quiet
        pytest.skip("backend exposes no memory_analysis")
    assert mem["peak_hbm_bytes"] > 0
    est = (
        mem.get("argument_size_in_bytes", 0)
        + mem.get("output_size_in_bytes", 0)
        + mem.get("temp_size_in_bytes", 0)
        - mem.get("alias_size_in_bytes", 0)
    )
    assert mem["peak_hbm_bytes"] == est or mem["peak_hbm_bytes"] > 0

    class _NoMA:
        def memory_analysis(self):
            raise NotImplementedError

    assert pa.memory_stats(_NoMA()) is None


# ---------------------------------------------------------------------------
# end to end: the xla_audit record in the JSONL + the report sections
# ---------------------------------------------------------------------------


def test_session_emits_xla_audit_record_and_report_sections(
    data_dir, tmp_path, capsys
):
    """Acceptance: a CPU run's JSONL contains an xla_audit record whose
    census matches the contract, and the report CLI renders the memory
    (peak HBM + headroom) and comms (bytes/step + bound verdict) sections
    with exit 0."""
    from shallowspeed_tpu.observability.report import main as report_main

    path = tmp_path / "audit.jsonl"
    with JsonlMetrics(path) as m:
        run = _mesh_session(data_dir, dp=2, pp=2, schedule="gpipe",
                            metrics=m, audit=True)
        run.train_epoch()
    recs = read_jsonl(path)
    audits = [r for r in recs if r.get("kind") == "xla_audit"]
    assert len(audits) == 1
    rec = audits[0]
    assert rec["name"] == "epoch_program"
    assert rec["census_ok"] is True
    assert rec["census"]["all_reduce"]["count"] >= 1
    assert rec["census"]["collective_permute"]["count"] >= 2
    assert rec["expected"]["bytes_per_step_per_device"] > 0
    assert rec["expected"]["bound"] in ("comms", "compute")
    assert rec["memory"]["peak_hbm_bytes"] > 0

    assert report_main([str(path), "--format", "md"]) == 0
    out = capsys.readouterr().out
    assert "Memory (compiled program)" in out
    assert "peak HBM" in out and "headroom" in out
    assert "Comms (XLA program audit)" in out
    assert "matches the layout contract" in out
    assert "-bound" in out  # the comms- vs compute-bound verdict


def test_report_renders_contract_mismatch_and_oom_forecast(tmp_path, capsys):
    """The report's negative paths: a census_ok=False record renders the
    MISMATCH loudly, and a peak beyond capacity renders the OOM forecast."""
    from shallowspeed_tpu.observability.metrics import SCHEMA_VERSION
    from shallowspeed_tpu.observability.report import main as report_main

    path = tmp_path / "bad.jsonl"
    rec = {
        "v": SCHEMA_VERSION, "ts": 0.0, "kind": "xla_audit",
        "name": "epoch_program", "hlo_available": True,
        "census": {"all_gather": {"count": 1, "bytes": 64}},
        "memory": {"peak_hbm_bytes": 32 * 2**30},
        "n_devices": 1, "platform": "cpu", "hbm_per_chip": 8 * 2**30,
        "hbm_source": "nominal-cpu-default",
        "peak_hbm_per_chip_bytes": 32 * 2**30,
        "hbm_headroom_fraction": 1.0 - 32 / 8,
        "expected": {"required": ["all_reduce"], "forbidden": [],
                     "axes": {}, "bytes_per_step_per_device": 0},
        "mismatches": ["required collective 'all_reduce' is absent"],
        "census_ok": False,
    }
    path.write_text(json.dumps(rec) + "\n")
    assert report_main([str(path), "--format", "text"]) == 0
    out = capsys.readouterr().out
    assert "CONTRACT MISMATCH" in out and "all_reduce" in out
    assert "OOM FORECAST" in out


def test_bucketed_run_jsonl_and_overlap_report(data_dir, tmp_path, capsys):
    """End-to-end for the bucketed observability loop: the JSONL carries
    the grad_sync_plan event and a census-clean bucketed audit, and the
    report renders the overlap-efficiency row plus the serial-vs-
    overlapped step bounds and the bucketed sync line."""
    from shallowspeed_tpu.observability.report import main as report_main

    path = tmp_path / "bucketed.jsonl"
    with JsonlMetrics(path) as m:
        run = _mesh_session(
            data_dir, dp=2, metrics=m, audit=True, grad_bucket_bytes=2048
        )
        run.train_epoch()
    recs = read_jsonl(path)
    plans = [
        r for r in recs
        if r.get("kind") == "event" and r.get("name") == "grad_sync_plan"
    ]
    assert len(plans) == 1
    assert plans[0]["mode"] == "dp" and plans[0]["num_buckets"] >= 2
    assert sum(plans[0]["bucket_grad_bytes"]) == plans[0]["total_grad_bytes"]
    audit = [r for r in recs if r.get("kind") == "xla_audit"][-1]
    assert audit["census_ok"] is True
    assert audit["expected"]["axes"]["dp"]["mode"] == "bucketed"

    assert report_main([str(path), "--format", "md"]) == 0
    out = capsys.readouterr().out
    assert "overlap efficiency" in out
    assert "comm hideable (model bound" in out and "buckets" in out
    assert "gradient sync: bucketed" in out
    assert "serial (anchor)" in out and "overlapped (bucketed, perfect)" in out


def test_fused_run_audits_run_program(data_dir, tmp_path):
    """A fused-run-only session still gets its census verified/recorded —
    as the run_program audit."""
    path = tmp_path / "run.jsonl"
    with JsonlMetrics(path) as m:
        run = _mesh_session(data_dir, dp=2, metrics=m, audit=True)
        run.train_run(2, with_eval=False)
        # a DIFFERENT run variant is a different compiled program — it
        # must be audited too (per-variant dedup, not per-label)
        run.train_run(1, with_eval=False)
    audits = [r for r in read_jsonl(path) if r.get("kind") == "xla_audit"]
    assert [a["name"] for a in audits] == ["run_program", "run_program"]
    for a in audits:
        assert a["census_ok"] is True
        assert a["census"]["all_reduce"]["count"] >= 1


def test_chunked_train_steps_audits_chunk_programs(data_dir, tmp_path):
    """A train_steps slice shorter than the epoch is a DISTINCT XLA
    program — the audit contract ("a mislowered layout never trains a
    step") must census IT, not the never-dispatched full-epoch program.
    One audit per distinct chunk length (the scan body is
    length-independent), and a full-epoch slice takes the epoch path."""
    path = tmp_path / "chunks.jsonl"
    with JsonlMetrics(path) as m:
        run = _mesh_session(data_dir, dp=2, metrics=m, audit=True)
        assert run.batches_per_epoch == 4
        run.train_steps(1)
        run.train_steps(1)  # same chunk length: deduped, no second audit
        run.train_steps(2)  # new chunk length: its own audit
    audits = [r for r in read_jsonl(path) if r.get("kind") == "xla_audit"]
    assert [a["name"] for a in audits] == ["chunk_program", "chunk_program"]
    for a in audits:
        assert a["census_ok"] is True
        assert a["census"]["all_reduce"]["count"] >= 1

    # a slice spanning the whole epoch is the epoch program (and a chunked
    # session that later goes whole-epoch reuses that one audit)
    with JsonlMetrics(tmp_path / "full.jsonl") as m:
        run2 = _mesh_session(data_dir, dp=2, metrics=m, audit=True)
        run2.train_steps(run2.batches_per_epoch)
        run2.train_epoch()
    audits2 = [
        r for r in read_jsonl(tmp_path / "full.jsonl")
        if r.get("kind") == "xla_audit"
    ]
    assert [a["name"] for a in audits2] == ["epoch_program"]


def test_chunked_train_steps_audit_refuses_before_dispatch(data_dir):
    """audit=True refuses a mislowered CHUNK program before it trains a
    step — same unlatched strictness as the epoch path."""
    run = _mesh_session(data_dir, dp=2, audit=True)
    run._expected_comms = dict(
        run._expected_comms, required=["all_to_all"], forbidden=["all_reduce"]
    )
    with pytest.raises(pa.AuditMismatchError, match="all_to_all"):
        run.train_steps(1)
    assert run.step_in_epoch == 0  # nothing trained
    with pytest.raises(pa.AuditMismatchError, match="all_to_all"):
        run.train_steps(1)
