"""Serving fleet tests: router placement/quorum/failover bookkeeping as
pure logic, and the multi-process fleet itself — replica workers, death ->
failover requeue, elastic scale, loadgen graceful drain, the CLI's
quorum-down exit code (docs/serving.md "Fleet", docs/robustness.md).

The multi-process tests carry the ``fleet`` marker and skip-with-reason
when the platform cannot spawn worker processes (the multihost
collectives skip, mirrored); the router/request tests run everywhere.
"""

import os
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

from shallowspeed_tpu.serving import fleet as fleet_mod
from shallowspeed_tpu.serving import loadgen, router
from shallowspeed_tpu.serving.fleet import ServingFleet

SIZES = (24, 20, 18, 16, 14, 12, 11, 10)
GBS = 64


@pytest.fixture()
def data_dir(tmp_path):
    rng = np.random.RandomState(0)
    for suffix, n in (("train", 256), ("val", 96)):
        x = rng.randn(n, SIZES[0]).astype(np.float32)
        y = np.eye(SIZES[-1], dtype=np.float32)[rng.randint(0, SIZES[-1], n)]
        np.save(tmp_path / f"x_{suffix}.npy", x)
        np.save(tmp_path / f"y_{suffix}.npy", y)
    return tmp_path


# ---------------------------------------------------------------------------
# router: pure placement/quorum logic (no processes)
# ---------------------------------------------------------------------------


def _ready(rid, queue_depth=0, inflight=0, degraded=False):
    info = router.ReplicaInfo(rid)
    info.state = "ready"
    info.queue_depth = queue_depth
    info.inflight = inflight
    info.degraded = degraded
    return info


def test_quorum_majority_of_target():
    assert [router.quorum(n) for n in (1, 2, 3, 4, 5)] == [1, 2, 2, 3, 3]


def test_least_queue_places_on_lowest_load():
    r = router.Router(policy="least_queue", seed=0)
    replicas = [_ready(0, queue_depth=4), _ready(1, inflight=1), _ready(2)]
    assert r.place(replicas).replica_id == 2
    # load counts BOTH heartbeated queue depth and un-acked in-flight
    replicas[2].inflight = 5
    assert r.place(replicas).replica_id == 1


def test_placement_skips_unroutable_replicas():
    r = router.Router(seed=0)
    degraded = _ready(0, degraded=True)
    starting = router.ReplicaInfo(1)  # state "starting"
    draining = _ready(2)
    draining.state = "draining"
    assert r.place([degraded, starting, draining]) is None
    healthy = _ready(3, queue_depth=99)
    assert r.place([degraded, starting, draining, healthy]).replica_id == 3


def test_p2c_seeded_and_prefers_less_loaded():
    """Power-of-two-choices: two seeded candidates, the less-loaded wins —
    and the same seed replays the same placement sequence."""
    def run(seed):
        r = router.Router(policy="p2c", seed=seed)
        replicas = [_ready(i, queue_depth=i) for i in range(6)]
        return [r.place(replicas).replica_id for _ in range(30)]

    a, b = run(7), run(7)
    assert a == b  # seeded -> replayable
    # the heaviest replica (load 5) can only win a draw against itself,
    # which sampling-without-replacement forbids
    assert 5 not in a


def test_tie_break_spreads_instead_of_pinning():
    """Equal-load ties draw from the seeded stream: a fixed tie-break
    would pin every low-load request to replica 0 and read as
    pathological routing skew."""
    r = router.Router(policy="least_queue", seed=3)
    replicas = [_ready(i) for i in range(3)]
    placed = [r.place(replicas).replica_id for _ in range(60)]
    assert set(placed) == {0, 1, 2}


def test_bounded_fleet_queue_and_requeue_at_head():
    r = router.Router(max_queue=2, seed=0)
    reqs = [
        router.FleetRequest(i, np.zeros((1, 4), np.float32), None, float(i))
        for i in range(4)
    ]
    assert r.admit(reqs[0]) and r.admit(reqs[1])
    assert not r.admit(reqs[2])  # bound hit -> caller drops, never silence
    # failover re-admission goes to the HEAD in original submit order
    r.requeue_head([reqs[2], reqs[3]])
    assert [q.id for q in r.queue] == [2, 3, 0, 1]


def test_routing_skew_definition():
    assert router.routing_skew([]) is None
    assert router.routing_skew([0, 0]) is None
    assert router.routing_skew([10, 10]) == 1.0
    assert router.routing_skew([30, 10, 20]) == pytest.approx(1.5)


def test_fleet_request_accounting():
    req = router.FleetRequest(0, np.zeros((3, 4), np.float32), 100.0, 10.0)
    assert req.rows == 3 and req.verdict == "queued"
    assert req.latency_s is None and req.slo_ok() is None
    # the worker is told the REMAINING deadline: fleet queue wait already
    # burned against the budget (coordinated-omission, one level up)
    assert req.remaining_deadline_ms(10.04) == pytest.approx(60.0)
    req.route_t = 10.05
    req.complete_t = 10.08
    assert req.queue_s == pytest.approx(0.05)
    assert req.latency_s == pytest.approx(0.08)
    assert req.slo_ok() is True  # its own 100 ms tag
    assert req.slo_ok(slo_ms=1.0) is True  # own tag wins over the SLO
    untagged = router.FleetRequest(1, np.zeros((1, 4), np.float32), None, 0.0)
    untagged.complete_t = 2.0
    assert untagged.remaining_deadline_ms(1.0) is None
    assert untagged.slo_ok(slo_ms=1000.0) is False


# ---------------------------------------------------------------------------
# the multi-process fleet
# ---------------------------------------------------------------------------


def _require_workers():
    if not fleet_mod.fleet_workers_supported():
        pytest.skip(
            "this platform cannot spawn fleet worker processes "
            "(multiprocessing spawn context unavailable or broken)"
        )


def _worker_config(data_dir, ck=None, **engine_kw):
    return {
        "session": dict(
            sizes=SIZES,
            global_batch_size=GBS,
            lr=0.01,
            data_dir=os.fspath(data_dir),
            resume=None if ck is None else os.fspath(ck),
            # a two-rung ladder keeps each worker's warm-up to two small
            # compiles — the tests measure fleet behavior, not XLA
            predict_slot_ladder=(1, 2),
        ),
        "engine": dict(retry=2, breaker_threshold=3, **engine_kw),
        "verify": True,
    }


@pytest.fixture(scope="module")
def fleet_checkpoint(tmp_path_factory):
    """One seed checkpoint every fleet test serves (saved via the PR 6
    path, restored by every worker through the loader)."""
    from shallowspeed_tpu.api import TrainingSession

    base = tmp_path_factory.mktemp("fleet_ck")
    rng = np.random.RandomState(0)
    for suffix, n in (("train", 256), ("val", 96)):
        x = rng.randn(n, SIZES[0]).astype(np.float32)
        y = np.eye(SIZES[-1], dtype=np.float32)[rng.randint(0, SIZES[-1], n)]
        np.save(base / f"x_{suffix}.npy", x)
        np.save(base / f"y_{suffix}.npy", y)
    session = TrainingSession(
        sizes=SIZES, global_batch_size=GBS, lr=0.01, data_dir=base
    )
    session.train_epoch()
    ck = base / "serve.npz"
    session.save(ck)
    return base, ck, session


@pytest.mark.fleet
@pytest.mark.slow  # 1-core wall budget; make fleet-smoke drives this end to end
def test_fleet_serves_failover_and_scales(fleet_checkpoint):
    """The tentpole end to end, in one fleet lifetime: 3 checkpoint-loaded
    replicas serve a seeded open-loop stream (worker-verified bitwise
    parity); one replica is SIGKILLed mid-stream — its un-acked in-flight
    requests fail over (requeue-at-head) and every admitted request still
    reaches a terminal verdict; a replacement scales up from the same
    weights without raising the quorum bar; a scale-down drains and
    retires cleanly."""
    _require_workers()
    data_dir, ck, parent_session = fleet_checkpoint
    done = []
    with ServingFleet(
        _worker_config(data_dir, ck),
        n_replicas=3,
        slo_ms=5000,
        retry=2,
        seed=0,
    ) as fleet:
        fleet.start()
        assert fleet.n_ready == 3 and not fleet.degraded
        payloads = loadgen.request_payloads(40, SIZES[0], seed=0)
        arrivals = loadgen.poisson_arrivals(400.0, 40, seed=0)
        t0 = fleet.clock()
        i, killed, scaled = 0, False, False
        submitted = []
        while i < 40 or fleet.queue_depth:
            now = fleet.clock() - t0
            while i < 40 and arrivals[i] <= now:
                submitted.append(
                    fleet.submit(payloads[i], arrival_t=t0 + arrivals[i])
                )
                i += 1
            done.extend(fleet.step())
            if not killed and len(done) >= 5:
                infos = [r for r in fleet.replicas.values() if r.state == "ready"]
                victim = max(infos, key=lambda r: (r.inflight, -r.replica_id))
                fleet.sigkill_replica(victim.replica_id)
                killed = True
            if killed and not scaled and any(
                r.state == "dead" for r in fleet.replicas.values()
            ):
                fleet.scale_up(wait_ready=False)  # replacement, off-path
                scaled = True
            if not fleet.queue_depth and i < 40:
                time.sleep(max(0.0, arrivals[i] - (fleet.clock() - t0)))
        assert killed and scaled
        # terminal-verdict contract, fleet-wide: nothing admitted is still
        # "queued", SIGKILL or not
        assert all(r.verdict != "queued" for r in submitted)
        # 2 healthy of target 3 is a quorum: the kill must not have
        # degraded admission, so nothing was dropped
        assert all(r.verdict == "ok" for r in submitted)
        # worker-side bitwise parity held on every ok response
        assert fleet.parity_mismatches == 0
        assert all(r.parity_ok for r in done if r.verdict == "ok")
        # ...and the fleet's responses match the PARENT's own session on
        # the same checkpoint (cross-process determinism of the slot
        # programs — the fleet-level parity claim)
        sample = next(r for r in done if r.verdict == "ok")
        np.testing.assert_array_equal(
            sample.result, parent_session.predict(sample.x)
        )
        fleet.wait_ready()  # let the replacement finish warming
        stats = fleet.stats()
        assert stats["replicas_dead"] == 1
        assert stats["failovers"] >= 1 or stats["failover_requeued"] >= 0
        assert stats["scale_ups"] == 1 and stats["scale_up_s"] is not None
        assert stats["replicas_target"] == 3  # replacement, not growth
        assert stats["availability"] == 1.0
        assert stats["recovery_s"] is not None
        assert not fleet.degraded
        # drain-and-retire: the newest routable replica leaves cleanly
        retired = fleet.scale_down()
        deadline = time.time() + 60
        while (
            fleet.replicas[retired].state != "retired"
            and time.time() < deadline
        ):
            fleet.step()
        assert fleet.replicas[retired].state == "retired"
        assert fleet.target_replicas == 2


@pytest.mark.fleet
def test_loadgen_open_loop_should_stop_drains_fleet(fleet_checkpoint):
    """Satellite: the loadgen drivers run unchanged over the router.
    A seeded open-loop stream stopped mid-flight (should_stop) stops
    ADMISSION but drains everything already admitted to a terminal
    verdict, and the coordinated-omission backdating survives the fleet
    hop (enqueue timestamps equal the scheduled arrivals)."""
    _require_workers()
    data_dir, ck, _ = fleet_checkpoint
    with ServingFleet(
        _worker_config(data_dir, ck), n_replicas=2, slo_ms=5000, seed=0
    ) as fleet:
        fleet.start()
        payloads = loadgen.request_payloads(24, SIZES[0], seed=1)
        arrivals = loadgen.poisson_arrivals(300.0, 24, seed=1)
        seen = []
        orig_submit = fleet.submit

        def tracking_submit(x, deadline_ms=None, arrival_t=None):
            req = orig_submit(x, deadline_ms=deadline_ms, arrival_t=arrival_t)
            seen.append((req, arrival_t))
            return req

        fleet.submit = tracking_submit
        stop_after = 10

        def should_stop():
            return len(seen) >= stop_after

        done = loadgen.run_open_loop(
            fleet, payloads, arrivals, deadline_ms=None,
            should_stop=should_stop,
        )
        # admission stopped mid-stream; everything admitted drained to a
        # terminal verdict — the graceful-drain contract, fleet-wide
        assert stop_after <= len(seen) < 24
        assert fleet.queue_depth == 0
        assert all(req.verdict != "queued" for req, _ in seen)
        assert done and {r.verdict for r in done} == {"ok"}
        # coordinated-omission accounting preserved across the router:
        # every enqueue timestamp IS the scheduled arrival it was
        # backdated to
        for req, arrival_t in seen:
            assert req.enqueue_t == pytest.approx(arrival_t)


@pytest.mark.fleet
def test_fleet_cli_exit_3_when_quorum_down(data_dir):
    """Satellite: the serve CLI's fleet exit-code contract. A 1-replica
    fleet whose only replica is SIGKILLed by the env fault plan (the
    engine's own chaos grammar, inherited by the worker) leaves the
    fleet quorum-down at exit -> documented exit code 3, with every
    admitted request still reaching a terminal verdict first."""
    _require_workers()
    # the CLI serves the flagship model: 784-wide data for this one
    rng = np.random.RandomState(0)
    flag_dir = data_dir / "flagship"
    flag_dir.mkdir()
    for suffix, n in (("train", 256), ("val", 96)):
        np.save(flag_dir / f"x_{suffix}.npy",
                rng.rand(n, 784).astype(np.float32))
        np.save(flag_dir / f"y_{suffix}.npy",
                np.eye(10, dtype=np.float32)[rng.randint(0, 10, n)])
    env = {k: v for k, v in os.environ.items() if k != "PALLAS_AXON_POOL_IPS"}
    env["JAX_PLATFORMS"] = "cpu"
    env["SHALLOWSPEED_FAULTS"] = "die@dispatch=1:mode=sigkill"
    proc = subprocess.run(
        [
            sys.executable, "-m", "shallowspeed_tpu.serving",
            "--fleet", "1", "--data-dir", os.fspath(flag_dir),
            "--global-batch-size", str(GBS),
            "--slot-ladder", "1,2",
            "--requests", "12", "--rate", "300", "--seed", "0",
        ],
        env=env,
        cwd=Path(__file__).resolve().parent.parent,
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert proc.returncode == 3, proc.stdout + proc.stderr
    assert "DEGRADED at exit (quorum of replicas down)" in proc.stderr
    # the kill cost capacity, never silence: the summary still accounts
    # every admitted request as a terminal verdict
    assert "completed" in proc.stdout


def test_fleet_rejects_bad_config():
    with pytest.raises(ValueError, match="n_replicas"):
        ServingFleet({}, n_replicas=0)
    with pytest.raises(ValueError, match="inflight_window"):
        ServingFleet({}, n_replicas=1, inflight_window=0)
    with pytest.raises(ValueError, match="policy"):
        router.Router(policy="round_robin")
