"""Utilities: layout-independent model hashing + replica-sync verification.

Capability parity with /root/reference/shallowspeed/utils.py (rank-0 print,
SHA1-of-SHA1s model hash, cross-replica sync assert), strengthened for the
mesh world: the hash is computed over the *logical* per-layer (W, b) blocks in
global layer order, so a sequential run, a DP=4 run and a DP=2xPP=4 run of the
same model produce the SAME hash — the reference could only compare hashes
within one layout (utils.py:13-31).
"""

from hashlib import sha1

import jax
import numpy as np


def iter_param_blocks(params_list):
    """Yield ``(global_layer, key, float32_array)`` for every logical (W, b)
    block of a logical params tree, in global layer order.

    This is the ONE digest-block definition shared by ``model_hash``, the
    per-layer checksum stream (``layer_digests`` and the in-program scan
    aux that mirrors it) and the divergence comparator: the exact float32
    bytes each of them hashes/sums come from here, so the hash and the
    digest stream can never disagree about what a "block" is.

    ``params_list``: list (per stage) of lists of {"W","b"} arrays (jax or
    numpy).
    """
    gl = 0
    for stage in params_list:
        for layer in stage:
            for key in ("W", "b"):
                yield gl, key, np.ascontiguousarray(
                    jax.device_get(layer[key]), np.float32
                )
            gl += 1


def model_hash(params_list) -> str:
    """SHA1 over concatenated per-parameter SHA1s, in global layer order.

    Mirrors reference utils.py:13-24 (sha1 of each param's bytes,
    concatenated, re-hashed); the bytes hashed are exactly the
    ``iter_param_blocks`` blocks, so the hash and the divergence digest
    stream share one block definition (the hash value itself is pinned by
    tests/test_divergence.py).
    """
    acc = ""
    for _gl, _key, arr in iter_param_blocks(params_list):
        acc += sha1(arr.tobytes()).hexdigest()
    return sha1(acc.encode("utf-8")).hexdigest()


def block_checksum(arr) -> int:
    """The host-side digest checksum of one logical block: the uint32
    wrap-around sum of the block's float32 bytes reinterpreted as uint32
    words — exactly what the fused scan aux computes in-program with
    ``jnp.sum(lax.bitcast_convert_type(x, jnp.uint32), dtype=jnp.uint32)``,
    so mesh-psum'd digests can be asserted equal to this logical value.
    """
    a = np.ascontiguousarray(np.asarray(jax.device_get(arr), np.float32))
    return int(a.view(np.uint32).sum(dtype=np.uint64) % (1 << 32))


def layer_digests(params_list):
    """Per-global-layer host digests of a logical params tree: a list of
    ``{"layer", "crc_w", "crc_b", "pnorm_w", "pnorm_b"}`` dicts over the
    ``iter_param_blocks`` blocks — the reference implementation the
    in-program digest stream is tested against (tests/test_divergence.py).
    """
    out = {}
    for gl, key, arr in iter_param_blocks(params_list):
        d = out.setdefault(gl, {"layer": gl})
        suffix = "w" if key == "W" else "b"
        d[f"crc_{suffix}"] = block_checksum(arr)
        d[f"pnorm_{suffix}"] = float(np.sqrt(np.sum(arr.astype(np.float64) ** 2)))
    return [out[gl] for gl in sorted(out)]


def assert_dp_replicas_in_sync(arr) -> None:
    """Verify every data-parallel replica holds bit-identical parameters.

    The reference gathers per-process hashes over the dp communicator and
    compares (utils.py:27-31, train.py:154-155). Here replication is a
    *sharding invariant* of the params jax.Array (replicated over the ``dp``
    mesh axis); we verify it physically by hashing every addressable shard
    per device-row and comparing. Works on any pytree of arrays.
    """
    mismatches = []

    def check(x):
        if not isinstance(x, jax.Array):
            return
        by_index = {}
        for shard in x.addressable_shards:
            h = sha1(np.ascontiguousarray(shard.data).tobytes()).hexdigest()
            # key by the index's string form: shard.index is a tuple of
            # slice objects, which are unhashable on Python < 3.12
            prev = by_index.setdefault(str(shard.index), h)
            if prev != h:
                mismatches.append((shard.device, shard.index))

    jax.tree.map(check, arr)
    if mismatches:
        raise ValueError(f"replica desync detected at shards: {mismatches}")


def assert_dp_replicas_in_sync_global(arr) -> None:
    """Multi-process extension of ``assert_dp_replicas_in_sync``.

    One process can only hash the shards it can address, so on a
    process-spanning mesh the local assert never compares the replicas that
    live on OTHER hosts. Here every process hashes its addressable shards
    (first 8 bytes of the SHA1, as two uint32 lanes — uint64 would be
    silently truncated under JAX's default x64-disabled mode), the
    per-device hash vectors are summed across processes with
    ``multihost_utils.process_allgather`` (each device slot is filled by
    exactly one process), and devices holding the same logical shard index
    are compared — the cross-host analogue of the reference's
    gather-hashes-over-the-dp-communicator check (utils.py:27-31). Raises
    on desync, on every process.
    """
    if jax.process_count() == 1:
        return assert_dp_replicas_in_sync(arr)
    from jax.experimental import multihost_utils

    leaves = [x for x in jax.tree.leaves(arr) if isinstance(x, jax.Array)]
    vecs, groups = [], []
    for li, x in enumerate(leaves):
        # identical on all processes: the full device->shard-index map
        dev_index = sorted(
            x.sharding.devices_indices_map(x.shape).items(),
            key=lambda kv: kv[0].id,
        )
        pos_of = {d.id: p for p, (d, _) in enumerate(dev_index)}
        v = np.zeros((len(dev_index), 2), np.uint32)
        for shard in x.addressable_shards:
            h = sha1(np.ascontiguousarray(shard.data).tobytes()).digest()
            # +1 so a real hash can't collide with the "not mine" sentinel 0
            v[pos_of[shard.device.id], 0] = np.uint32(
                int.from_bytes(h[:4], "big") % (2**32 - 1) + 1
            )
            v[pos_of[shard.device.id], 1] = np.uint32(int.from_bytes(h[4:8], "big"))
        vecs.append(v)
        by_index = {}
        for p, (d, idx) in enumerate(dev_index):
            by_index.setdefault(str(idx), []).append(p)
        groups.append((li, by_index))
    summed = [
        np.asarray(g).sum(axis=0, dtype=np.uint64)
        for g in multihost_utils.process_allgather(vecs)
    ]
    mismatches = []
    for (li, by_index), total in zip(groups, summed):
        for idx, positions in by_index.items():
            hashes = {(int(total[p, 0]), int(total[p, 1])) for p in positions}
            if len(hashes) > 1:
                mismatches.append((li, idx))
    if mismatches:
        raise ValueError(
            f"cross-process replica desync at (leaf, shard-index): {mismatches}"
        )


def p0print(*args, **kwargs):
    """Print from process 0 only (reference rprint, utils.py:8-10)."""
    if jax.process_index() == 0:
        print(*args, **kwargs)
