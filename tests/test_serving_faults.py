"""Serving-layer fault-tolerance tests (docs/robustness.md "Serving
faults"): the dispatch-fault grammar, dispatch recovery + retry budget,
deadline shedding, health-gated responses + the breaker, hot weight
reload (zero recompiles), graceful drain, the chaos soak, and the report
CLI's Degradation subsection."""

import json

import numpy as np
import pytest

from shallowspeed_tpu import faults, retry
from shallowspeed_tpu.api import TrainingSession
from shallowspeed_tpu.checkpoint import (
    CheckpointError,
    find_newer_good,
    save_checkpoint,
    step_checkpoint_path,
)
from shallowspeed_tpu.serving import bench_serving, loadgen
from shallowspeed_tpu.serving.engine import ServingEngine

SIZES = (24, 20, 18, 16, 14, 12, 11, 10)
N, GBS = 512, 64


@pytest.fixture()
def data_dir(tmp_path_factory):
    tmp_path = tmp_path_factory.mktemp("data")
    rng = np.random.RandomState(0)
    for suffix, n in (("train", N), ("val", 128)):
        x = rng.randn(n, SIZES[0]).astype(np.float32)
        y = np.eye(SIZES[-1], dtype=np.float32)[rng.randint(0, SIZES[-1], n)]
        np.save(tmp_path / f"x_{suffix}.npy", x)
        np.save(tmp_path / f"y_{suffix}.npy", y)
    return tmp_path


def _session(data_dir, **kw):
    kw.setdefault("sizes", SIZES)
    kw.setdefault("global_batch_size", GBS)
    kw.setdefault("lr", 0.01)
    return TrainingSession(data_dir=data_dir, **kw)


def _payloads(n, seed=5, rows=(1, 2, 3)):
    rng = np.random.RandomState(seed)
    return [
        rng.randn(rng.choice(rows), SIZES[0]).astype(np.float32)
        for _ in range(n)
    ]


# ---------------------------------------------------------------------------
# fault grammar: @dispatch anchors
# ---------------------------------------------------------------------------


def test_dispatch_fault_grammar():
    plan = faults.FaultPlan.parse(
        "error@dispatch=3, slow@dispatch=5:ms=20, nan@dispatch=7,"
        "die@dispatch=9:mode=sigkill, die@step=4"
    )
    kinds = [(f.kind, f.trigger) for f in plan.faults]
    assert kinds == [
        ("error", "dispatch"), ("slow", "dispatch"), ("nan", "dispatch"),
        ("die", "dispatch"), ("die", "step"),
    ]
    assert plan.faults[1].ms == 20.0
    assert "slow@dispatch=5:ms=20" in repr(plan.faults[1])
    # step-side surfaces see ONLY step faults (a serving plan must not
    # make train_epoch refuse), and vice versa
    assert [f.kind for f in plan.pending] == ["die"]
    assert len(plan.pending_dispatch) == 4
    assert plan.first_in(0, 10).step == 4
    # due_at_dispatch: <= anchor, spec order, fired ones drop out
    due = plan.due_at_dispatch(5)
    assert [f.kind for f in due] == ["error", "slow"]
    due[0].fired = True
    assert [f.kind for f in plan.due_at_dispatch(5)] == ["slow"]
    for bad in (
        "slow@step=3:ms=5",            # slow is dispatch-only
        "error@step=3",                # error is dispatch-only
        "slow@dispatch=3",             # missing ms
        "nan@dispatch=3:ms=5",         # ms on a non-slow kind
        "die@step=3:mode=nope",
        "nan@step=1:dispatch=2",       # two anchors
        "nan",                         # no anchor at all
    ):
        with pytest.raises(ValueError, match="bad fault spec"):
            faults.FaultPlan.parse(bad)
    with pytest.raises(ValueError, match="exactly one"):
        faults.Fault("nan", step=1, dispatch=2)


def test_retry_policy_value():
    pol = retry.RetryPolicy(attempts=3, base=0.5, jitter=0, seed=1)
    assert not pol.exhausted(2) and pol.exhausted(3)
    assert pol.delay(0) == retry.backoff_delay(0, base=0.5, jitter=0)
    assert pol.delay(2) == retry.backoff_delay(2, base=0.5, jitter=0)
    with pytest.raises(ValueError):
        retry.RetryPolicy(attempts=0)
    with pytest.raises(ValueError):
        retry.RetryPolicy(attempts=2, base=-1)
    zero = retry.RetryPolicy(attempts=2, base=0.0, jitter=0)
    assert zero.delay(5) == 0.0


# ---------------------------------------------------------------------------
# dispatch recovery (satellite 1: the request-loss regression)
# ---------------------------------------------------------------------------


def test_failed_dispatch_requeues_at_head_nothing_lost(data_dir, monkeypatch):
    """The PR-seed regression: a raising predict() used to lose every
    popped request with verdict 'queued' and no record. Now the batch is
    re-queued at the HEAD in original order, accounting stays consistent,
    and the retry serves bitwise-identical responses."""
    run = _session(data_dir)
    eng = ServingEngine(run, retry=3, breaker_threshold=99)
    payloads = _payloads(3)
    reqs = [eng.submit(p) for p in payloads]
    orig = run.predict
    calls = {"n": 0}

    def flaky(x):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("transient backend failure")
        return orig(x)

    monkeypatch.setattr(run, "predict", flaky)
    out = eng.step()
    assert out == []  # nothing terminal yet — and nothing lost
    assert eng.queue_depth == 3
    assert [r.id for r in eng._queue] == [0, 1, 2]  # order preserved
    assert all(r.verdict == "queued" and r.attempts == 1 for r in reqs)
    st = eng.stats()
    assert st["failed_dispatches"] == 1 and st["retries"] == 3
    assert st["errors"] == 0 and st["completed"] == 0
    done = eng.drain()
    assert [r.id for r in done] == [0, 1, 2]
    for req in done:
        assert req.verdict == "ok"
        np.testing.assert_array_equal(req.result, orig(payloads[req.id]))
    assert eng.stats()["completed"] == 3


def test_exhausted_retry_budget_completes_as_error(data_dir, monkeypatch, tmp_path):
    from shallowspeed_tpu.observability import JsonlMetrics, read_jsonl

    run = _session(data_dir)
    m = JsonlMetrics(tmp_path / "err.jsonl")
    eng = ServingEngine(run, retry=2, breaker_threshold=99, metrics=m)
    payloads = _payloads(2)
    reqs = [eng.submit(p) for p in payloads]
    monkeypatch.setattr(
        run, "predict",
        lambda x: (_ for _ in ()).throw(RuntimeError("hard down")),
    )
    done = eng.drain()  # budget 2: one requeue, then terminal — bounded
    assert [r.verdict for r in done] == ["error", "error"]
    assert all(r.attempts == 2 and r.result is None for r in reqs)
    assert eng.queue_depth == 0
    st = eng.stats()
    assert st["errors"] == 2 and st["failed_dispatches"] == 2
    assert st["availability"] == 0.0
    m.close()
    recs = read_jsonl(m.path)
    errs = [r for r in recs if r["kind"] == "request" and r["name"] == "error"]
    assert len(errs) == 2
    assert all(
        r["attempts"] == 2 and "RuntimeError" in r["reason"] for r in errs
    )
    health = [r for r in recs if r["kind"] == "serving_health"]
    assert [r["name"] for r in health] == ["dispatch_error", "dispatch_error"]
    assert health[0]["requeued"] == 2 and health[1]["exhausted"] == 2


# ---------------------------------------------------------------------------
# satellite 2: one clock for depth ring and request records
# ---------------------------------------------------------------------------


def test_record_depth_uses_request_timeline_clock(data_dir, tmp_path):
    from shallowspeed_tpu.observability import JsonlMetrics, read_jsonl

    run = _session(data_dir)
    t = {"now": 100.0}
    m = JsonlMetrics(tmp_path / "depth.jsonl")
    eng = ServingEngine(
        run, max_queue=1, metrics=m, clock=lambda: t["now"]
    )
    x = _payloads(1)[0]
    eng.submit(x, arrival_t=50.0)
    # the depth sample carries the BACKDATED arrival — the same clock the
    # request's own timeline uses, so the two streams join
    assert eng._depths[-1] == (50.0, 1)
    dropped = eng.submit(x, arrival_t=51.0)  # over max_queue
    assert dropped.verdict == "dropped"
    assert len(eng._depths) == 1  # a drop never changed the queue
    m.close()
    recs = read_jsonl(m.path)
    drop = [r for r in recs if r["kind"] == "request"][-1]
    assert drop["name"] == "dropped" and drop["enqueue_ts"] == 51.0
    assert drop["reason"] == "queue_full"


# ---------------------------------------------------------------------------
# deadline shedding
# ---------------------------------------------------------------------------


def test_pack_time_shedding_before_costing_a_slot(data_dir):
    run = _session(data_dir)
    t = {"now": 0.0}
    eng = ServingEngine(run, clock=lambda: t["now"])
    eng._latency_floor = 0.0  # isolate the already-passed-deadline leg
    p = _payloads(2)
    r0 = eng.submit(p[0], deadline_ms=100.0)
    r1 = eng.submit(p[1])  # no deadline — never shed
    t["now"] = 0.5  # r0's 100 ms deadline is long dead
    done = eng.step()
    assert [r.verdict for r in done] == ["expired", "ok"]
    assert r0.result is None and r0.complete_t == 0.5
    st = eng.stats()
    assert st["expired"] == 1 and st["completed"] == 1
    # the shed request never cost a slot: only r1's slot dispatched
    assert st["slots_dispatched"] == r1.slots


def test_provable_floor_shedding_and_admission_backpressure(data_dir):
    run = _session(data_dir)
    t = {"now": 0.0}
    eng = ServingEngine(run, clock=lambda: t["now"])
    eng._latency_floor = 10.0  # an analytical floor no 5 s deadline survives
    req = eng.submit(_payloads(1)[0], deadline_ms=5000.0)
    done = eng.step()  # deadline in the future, but provably unmeetable
    assert done == [req] and req.verdict == "expired"
    assert eng.stats()["slots_dispatched"] == 0
    # the same estimate as admission backpressure (opt-in)
    eng2 = ServingEngine(run, clock=lambda: t["now"], shed_on_submit=True)
    eng2._latency_floor = 10.0
    r = eng2.submit(_payloads(1)[0], deadline_ms=5000.0)
    assert r.verdict == "expired" and eng2.queue_depth == 0
    ok = eng2.submit(_payloads(1)[0], deadline_ms=60_000.0)
    assert ok.verdict == "queued"  # a meetable deadline is admitted


# ---------------------------------------------------------------------------
# health gate + breaker
# ---------------------------------------------------------------------------


def test_health_gate_breaker_and_degraded_admission(data_dir, tmp_path):
    from shallowspeed_tpu.observability import JsonlMetrics, read_jsonl

    run = _session(data_dir)
    m = JsonlMetrics(tmp_path / "health.jsonl")
    eng = ServingEngine(run, breaker_threshold=2, metrics=m)
    p = _payloads(5)
    run.poison_weights()  # every dispatch from here is non-finite
    for x in p[:3]:
        eng.submit(x)
    done = eng.step()  # one dispatch, three unhealthy completions
    assert [r.verdict for r in done] == ["unhealthy"] * 3
    assert all(r.result is None for r in done)
    assert not eng.degraded  # 1 consecutive failure < threshold 2
    eng.submit(p[3])
    eng.step()  # second consecutive unhealthy dispatch trips the breaker
    assert eng.degraded
    refused = eng.submit(p[4])
    assert refused.verdict == "dropped"
    st = eng.stats()
    assert st["unhealthy"] == 4 and st["breaker_trips"] == 1
    assert st["degraded"] is True and st["availability"] == 0.0
    # manual recovery without a reload dir: close_breaker re-admits
    eng.close_breaker()
    assert not eng.degraded and eng.submit(p[4]).verdict == "queued"
    m.close()
    recs = read_jsonl(m.path)
    health = [r for r in recs if r["kind"] == "serving_health"]
    names = [r["name"] for r in health]
    assert names == [
        "unhealthy_dispatch", "unhealthy_dispatch", "breaker_open",
        "breaker_closed",
    ]
    assert health[2]["consecutive_failures"] == 2
    drop = [
        r for r in recs
        if r["kind"] == "request" and r["name"] == "dropped"
    ]
    assert drop and drop[0]["reason"] == "degraded"


# ---------------------------------------------------------------------------
# hot weight reload
# ---------------------------------------------------------------------------


def _checkpoint_pair(run, ck_dir):
    """step-0 = the session's current weights; step-8 = one epoch later.
    Leaves the session serving the OLD (step-0) weights."""
    save_checkpoint(
        step_checkpoint_path(ck_dir, 0), run.params(), run.spec, 0,
        step_in_epoch=0, global_step=0,
    )
    run.train_epoch()
    save_checkpoint(
        step_checkpoint_path(ck_dir, 8), run.params(), run.spec, 1,
        step_in_epoch=0, global_step=8,
    )
    new_hash = run.model_hash()
    run.load_weights(step_checkpoint_path(ck_dir, 0))
    assert run.model_hash() != new_hash  # the swap is observable
    return new_hash


def test_find_newer_good_watcher_helper(data_dir, tmp_path):
    run = _session(data_dir)
    ck = tmp_path / "ck"
    _checkpoint_pair(run, ck)
    step, path, meta, skipped = find_newer_good(ck, than_step=0)
    assert step == 8 and path.name == "step-00000008.npz"
    assert meta["global_step"] == 8 and skipped == []
    assert find_newer_good(ck, than_step=8)[0] is None
    assert find_newer_good(ck)[0] == 8  # None floor accepts any step
    # a corrupt newest candidate is skipped WITH its cause
    faults.corrupt_checkpoint_bytes(step_checkpoint_path(ck, 8), seed=3)
    step, path, meta, skipped = find_newer_good(ck, than_step=0)
    assert step is None and len(skipped) == 1
    assert "corrupt" in skipped[0][1] or "checksum" in skipped[0][1]


@pytest.mark.slow  # 1-core wall budget; make chaos-smoke drives this end to end
def test_hot_reload_bitwise_parity_and_zero_recompiles(data_dir, tmp_path):
    """The reload contract: the queue is untouched, every response after
    the swap is bitwise-equal to a direct predict() under the NEW weights,
    and the rung program cache survives — zero recompiles, pinned by the
    jit_compiles counter the program audit shares."""
    from shallowspeed_tpu.observability import JsonlMetrics, read_jsonl

    m = JsonlMetrics(tmp_path / "reload.jsonl")
    run = _session(data_dir, dp=2, pp=2, schedule="gpipe", metrics=m)
    ck = tmp_path / "ck"
    new_hash = _checkpoint_pair(run, ck)
    eng = ServingEngine(run, reload_dir=ck, loaded_step=0, metrics=m)
    eng.warm_ladder()
    compiles0 = m.counters["jit_compiles"]
    cache0 = set(run._predict_cache)
    payloads = _payloads(6, rows=(1, 3, 9))
    for x in payloads[:3]:
        eng.submit(x)
    pre = eng.step()
    assert all(r.verdict == "ok" for r in pre)
    # the watcher leg picks up the strictly-newer snapshot mid-queue
    for x in payloads[3:]:
        eng.submit(x)
    assert eng.watch_reload() == 8
    assert run.model_hash() == new_hash
    assert eng.queue_depth == 3  # the queue was never touched
    post = eng.drain()
    for r in post:
        assert r.verdict == "ok"
        np.testing.assert_array_equal(
            r.result, run.predict(payloads[r.id])
        )
    # zero recompiles: same shapes, same cached rung programs
    assert m.counters["jit_compiles"] == compiles0
    assert set(run._predict_cache) == cache0
    assert eng.watch_reload() is None  # nothing newer than step 8
    assert eng.stats()["reloads"] == 1
    m.close()
    recs = read_jsonl(m.path)
    reloads = [r for r in recs if r["kind"] == "reload"]
    assert len(reloads) == 1
    assert reloads[0]["name"] == "ok" and reloads[0]["reason"] == "watch"
    assert reloads[0]["step"] == 8 and reloads[0]["programs_cached"] >= 1


def test_breaker_triggered_reload_recovers(data_dir, tmp_path):
    """nan-poisoned weights trip the breaker; the breaker-triggered
    reload restores the newest GOOD snapshot, closes the breaker, and the
    next dispatch serves healthy responses again — with a measured
    recovery time."""
    run = _session(data_dir)
    ck = tmp_path / "ck"
    new_hash = _checkpoint_pair(run, ck)
    eng = ServingEngine(
        run, reload_dir=ck, loaded_step=0, breaker_threshold=1, retry=1,
        faults="nan@dispatch=1",
    )
    p = _payloads(3)
    assert eng.submit(p[0]) and eng.step()[0].verdict == "ok"  # dispatch 0
    eng.submit(p[1])
    done = eng.step()  # dispatch 1: nan fires -> unhealthy -> breaker -> reload
    assert done[0].verdict == "unhealthy"
    assert not eng.degraded  # the reload already closed the breaker
    assert run.model_hash() == new_hash  # restored from step-8
    eng.submit(p[2])
    ok = eng.step()
    assert ok[0].verdict == "ok"
    np.testing.assert_array_equal(ok[0].result, run.predict(p[2]))
    st = eng.stats()
    assert st["breaker_trips"] == 1 and st["reloads"] == 1
    assert st["unhealthy"] == 1 and st["recovery_s"] is not None
    assert st["recovery_s"] >= 0


def test_reload_failure_paths(data_dir, tmp_path):
    run = _session(data_dir)
    eng = ServingEngine(run)
    with pytest.raises(ValueError, match="reload_dir"):
        eng.reload()
    with pytest.raises(ValueError, match="reload_dir"):
        eng.watch_reload()
    empty = tmp_path / "empty_ck"
    empty.mkdir()
    eng2 = ServingEngine(run, reload_dir=empty)
    with pytest.raises(CheckpointError, match="no snapshot verifies"):
        eng2.reload()
    # load_weights refuses a checkpoint whose shapes would invalidate the
    # compiled programs — before any state changes
    from shallowspeed_tpu import model as Mo

    other_spec = Mo.make_model_spec((SIZES[0], 12, 10), 1, GBS)
    other = tmp_path / "other.npz"
    save_checkpoint(other, Mo.init_model(other_spec), other_spec, 0)
    before = run.model_hash()
    with pytest.raises(ValueError, match="must preserve"):
        run.load_weights(other)
    assert run.model_hash() == before


# ---------------------------------------------------------------------------
# chaos injections in the dispatch loop
# ---------------------------------------------------------------------------


def test_die_fault_raises_before_pop_queue_intact(data_dir):
    run = _session(data_dir)
    eng = ServingEngine(run, faults="die@dispatch=0")
    p = _payloads(2)
    for x in p:
        eng.submit(x)
    with pytest.raises(faults.InjectedFault, match="die@dispatch=0"):
        eng.step()
    assert eng.queue_depth == 2  # nothing was popped: the loop re-enters
    done = eng.drain()
    assert [r.verdict for r in done] == ["ok", "ok"]
    for r in done:
        np.testing.assert_array_equal(r.result, run.predict(p[r.id]))
    # the loadgen drivers ARE the operator loop: they absorb the injected
    # death and re-enter, so a die costs wall time, never requests
    eng2 = ServingEngine(run, faults="die@dispatch=0")
    done2 = loadgen.run_open_loop(eng2, p, arrivals=[0.0, 0.0])
    assert [r.verdict for r in done2] == ["ok", "ok"]
    eng3 = ServingEngine(run, faults="die@dispatch=0")
    done3 = loadgen.run_closed_loop(eng3, p, concurrency=2)
    assert [r.verdict for r in done3] == ["ok", "ok"]


def test_error_and_slow_faults_inside_dispatch(data_dir, tmp_path):
    from shallowspeed_tpu.observability import JsonlMetrics, read_jsonl

    run = _session(data_dir)
    m = JsonlMetrics(tmp_path / "chaos.jsonl")
    eng = ServingEngine(
        run, retry=2, breaker_threshold=99, metrics=m,
        faults="error@dispatch=0,slow@dispatch=1:ms=30",
    )
    req = eng.submit(_payloads(1)[0])
    assert eng.step() == []  # error fired inside the wrapper: requeued
    assert req.attempts == 1 and eng.queue_depth == 1
    t0 = eng.clock()
    done = eng.step()  # dispatch 1: slow stalls, then serves
    assert eng.clock() - t0 >= 0.03
    assert done[0].verdict == "ok"
    m.close()
    injected = [
        r for r in read_jsonl(m.path)
        if r["kind"] == "serving_health" and r["name"] == "fault_injected"
    ]
    assert len(injected) == 2
    assert "error@dispatch=0" in injected[0]["fault"]
    assert "slow@dispatch=1" in injected[1]["fault"]


# ---------------------------------------------------------------------------
# graceful drain
# ---------------------------------------------------------------------------


def test_drivers_stop_admission_and_drain(data_dir):
    run = _session(data_dir)
    eng = ServingEngine(run)
    payloads = _payloads(10)
    # first three arrive immediately, the rest far in the future — the
    # stop latch flips after the first dispatch, so admission ends there
    arrivals = [0.0] * 3 + [60.0] * 7
    stop = {"flag": False}

    def should_stop():
        if eng.stats()["dispatches"] >= 1:
            stop["flag"] = True
        return stop["flag"]

    done = loadgen.run_open_loop(
        eng, payloads, arrivals, should_stop=should_stop
    )
    assert 1 <= len(done) <= 3 and eng.queue_depth == 0
    assert all(r.verdict == "ok" for r in done)
    # the closed loop honors the same hook
    eng2 = ServingEngine(run)
    done2 = loadgen.run_closed_loop(
        eng2, payloads, concurrency=2, should_stop=lambda: True
    )
    assert done2 == [] and eng2.queue_depth == 0


def test_serve_cli_sigterm_graceful_drain(data_dir, tmp_path, capsys, monkeypatch):
    """SIGTERM mid-traffic: admission stops, the queue drains, metrics
    flush, exit 0 — the serve CLI's documented drain contract, driven
    in-process by invoking the installed handler after the first
    dispatch."""
    import signal as signal_mod

    from shallowspeed_tpu.serving.__main__ import main as serve_main

    handlers = {}
    orig_signal = signal_mod.signal

    def capture_signal(sig, h):
        handlers[sig] = h
        return signal_mod.SIG_DFL

    monkeypatch.setattr(signal_mod, "signal", capture_signal)
    orig_step = ServingEngine.step

    def step_then_sigterm(self):
        out = orig_step(self)
        h = handlers.get(signal_mod.SIGTERM)
        if h is not None and self.stats()["dispatches"] >= 1:
            h(signal_mod.SIGTERM, None)
        return out

    monkeypatch.setattr(ServingEngine, "step", step_then_sigterm)
    out = tmp_path / "drain.jsonl"
    rc = serve_main(
        [
            "--global-batch-size", str(GBS),
            "--data-dir", str(data_dir),
            "--requests", "50", "--rate", "30", "--seed", "0",
            "--slot-ladder", "1,2,4",
            "--metrics-out", str(out),
        ]
    )
    monkeypatch.setattr(signal_mod, "signal", orig_signal)
    assert rc == 0
    text = capsys.readouterr().out
    assert "SIGTERM received: admission stopped, queue drained" in text
    assert out.exists()


def test_serve_cli_degraded_exit_code(data_dir, capsys):
    """nan-poisoned weights with no reload dir: the breaker opens and
    stays open — exit 3, the serving mirror of train.py's health halt."""
    from shallowspeed_tpu.serving.__main__ import main as serve_main

    rc = serve_main(
        [
            "--global-batch-size", str(GBS),
            "--data-dir", str(data_dir),
            "--requests", "12", "--rate", "3000", "--seed", "0",
            "--slot-ladder", "1,2,4",
            "--faults", "nan@dispatch=0",
            "--breaker", "1",
        ]
    )
    assert rc == 3
    assert "DEGRADED" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# the chaos soak + report Degradation subsection
# ---------------------------------------------------------------------------


@pytest.mark.slow  # 1-core wall budget; make chaos-smoke drives this end to end
def test_chaos_soak_invariants(data_dir, tmp_path):
    """The make chaos-smoke contract in miniature: die/slow/nan/error +
    one mid-traffic watcher reload; zero silently-lost requests, bitwise
    parity of every ok response under the weights active at its dispatch,
    breaker-then-recovery, zero recompiles."""
    from shallowspeed_tpu.observability import JsonlMetrics, read_jsonl

    m = JsonlMetrics(tmp_path / "soak.jsonl")
    run = _session(data_dir, dp=2, metrics=m)
    ck = tmp_path / "ck"
    _checkpoint_pair(run, ck)
    rec = bench_serving.chaos_soak(
        run,
        faults="error@dispatch=2,slow@dispatch=3:ms=10,die@dispatch=4,"
        "nan@dispatch=6",
        n_requests=30,
        rate=300.0,
        seed=0,
        slo_ms=10_000,
        metrics=m,
        reload_dir=ck,
        reload_at=5,
        loaded_step=0,
        retry_budget=2,
        breaker_threshold=1,
        max_slots=2,
    )
    assert rec["bench"] == "serving_chaos" and rec["bench_version"] == 1
    assert rec["submitted"] == 30
    assert rec["silently_lost"] == []  # every id reached a terminal verdict
    assert rec["parity_mismatches"] == 0
    assert rec["crashes_recovered"] == 1  # the die@dispatch=4 re-entry
    assert rec["breaker_trips"] >= 1 and rec["reloads"] >= 2
    assert rec["recovery_s"] is not None and not rec["degraded_at_exit"]
    assert rec["recompiles"] == 0 and rec["predict_cache_stable"]
    assert rec["faults_unfired"] == 0
    assert rec["verdicts"].get("ok", 0) >= 1
    assert rec["availability"] is not None
    assert rec["goodput_retention"] is not None
    json.dumps(rec)  # published record stays strict-JSON-able
    m.close()
    recs = read_jsonl(m.path)
    assert any(r["kind"] == "serving_health" for r in recs)
    assert any(
        r["kind"] == "reload" and r["name"] == "ok" for r in recs
    )
    # the report renders the Degradation subsection from these records
    from shallowspeed_tpu.observability.report import build_report, render

    rep = build_report(recs, source="soak", slo_ms=10_000)
    deg = rep["serving"]["degradation"]
    assert deg is not None and deg["breaker_trips"] >= 1
    assert deg["reloads"] >= 2 and not deg["degraded_at_exit"]
    assert deg["verdict"].startswith("recovered")
    text = render(rep, "md")
    assert "### Degradation" in text
    assert "breaker:" in text and "availability" in text


def test_report_degradation_section_synthetic_and_pre_v6(tmp_path):
    from shallowspeed_tpu.observability.report import build_report, render

    base = {"v": 6, "ts": 10.0}
    recs = [
        dict(base, kind="request", name="ok", id=0, rows=1, slots=1,
             latency_s=0.01, queue_s=0.001),
        dict(base, kind="request", name="expired", id=1, rows=1, slots=1),
        dict(base, kind="request", name="error", id=2, rows=1, slots=1,
             attempts=2),
        dict(base, kind="request", name="unhealthy", id=3, rows=1, slots=1),
        dict(base, kind="serving_health", name="breaker_open", dispatch=4,
             consecutive_failures=2, ts=11.0),
        dict(base, kind="reload", name="ok", path="ck/step-8", step=8,
             reason="breaker", ts=11.5),
        dict(base, kind="serving_health", name="breaker_closed", dispatch=5,
             ts=11.5),
    ]
    rep = build_report(recs, source="x", slo_ms=50.0)
    srv = rep["serving"]
    assert srv["expired"] == 1 and srv["errors"] == 1 and srv["unhealthy"] == 1
    deg = srv["degradation"]
    assert deg["breaker_trips"] == 1 and deg["reloads"] == 1
    assert deg["recovery_s"] == pytest.approx(0.5)
    assert deg["availability"] == pytest.approx(0.25)
    assert deg["verdict"].startswith("recovered")
    text = render(rep, "md")
    assert "### Degradation" in text and "1 ERRORED" in text
    # an open breaker with no close after it reads DEGRADED
    rep2 = build_report(recs[:5], source="x")
    assert rep2["serving"]["degradation"]["degraded_at_exit"] is True
    assert "DEGRADED" in rep2["serving"]["degradation"]["verdict"]
    # a clean v6 run renders no Degradation subsection; pre-v6 streams
    # keep the PR7 Serving section byte-identical in shape
    clean = build_report(
        [dict(base, kind="request", name="ok", id=0, rows=1, slots=1,
              latency_s=0.01, queue_s=0.001)],
        source="clean",
    )
    assert clean["serving"]["degradation"] is None
    assert "### Degradation" not in render(clean, "md")
    old = build_report(
        [{"v": 5, "ts": 0.0, "kind": "request", "name": "ok", "id": 0,
          "rows": 1, "slots": 1, "latency_s": 0.01, "queue_s": 0.001}],
        source="old",
    )
    assert old["serving"]["degradation"] is None
    assert "### Degradation" not in render(old, "md")


# ---------------------------------------------------------------------------
# single-verified-read reload + verify accounting (PR 12)
# ---------------------------------------------------------------------------


def test_reload_reads_the_snapshot_exactly_once_and_records_verify_s(
    data_dir, tmp_path, monkeypatch
):
    """Both reload legs (breaker-style discovery and the watcher) assemble
    the weights from the arrays discovery already verified: the restored
    snapshot is read+checksummed ONCE, the TOCTOU window between verify
    and load is gone by construction (pinned by deleting the file between
    the two), and the reload record carries the discovery's verify time
    so the Degradation accounting can see it."""
    from shallowspeed_tpu import checkpoint as C
    from shallowspeed_tpu.observability import JsonlMetrics, read_jsonl

    m = JsonlMetrics(tmp_path / "reload.jsonl")
    run = _session(data_dir, metrics=m)
    ck = tmp_path / "ck"
    new_hash = _checkpoint_pair(run, ck)

    reads = []
    real = C._read_arrays

    def counting(path):
        reads.append(str(path))
        return real(path)

    monkeypatch.setattr(C, "_read_arrays", counting)
    eng = ServingEngine(run, reload_dir=ck, loaded_step=0, metrics=m)

    # watcher leg: one read of the newer snapshot, then DELETE it before
    # the swap has any chance to re-read — the load still succeeds
    # because it assembles the verified arrays, not the file
    orig_reload = eng.reload

    def delete_then_reload(path=None, **kw):
        step_checkpoint_path(ck, 8).unlink()
        return orig_reload(path=path, **kw)

    monkeypatch.setattr(eng, "reload", delete_then_reload)
    assert eng.watch_reload() == 8
    assert run.model_hash() == new_hash
    assert reads.count(str(step_checkpoint_path(ck, 8))) == 1
    monkeypatch.setattr(eng, "reload", orig_reload)

    # breaker-style discovery leg: newest good is now step-0; again one
    # read of the restored file
    reads.clear()
    eng.reload(reason="manual")
    assert reads.count(str(step_checkpoint_path(ck, 0))) == 1
    m.close()
    reloads = [r for r in read_jsonl(m.path) if r["kind"] == "reload"]
    assert [r["name"] for r in reloads] == ["ok", "ok"]
    for r in reloads:
        assert r["verify_s"] is not None and r["verify_s"] >= 0
        assert r["wall_s"] >= r["verify_s"]
