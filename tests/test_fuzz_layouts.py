"""Randomized cross-layout consistency: the strongest generic property.

For randomly generated model shapes (monotone-decreasing, like the reference
family), random DP x PP layouts and random schedules, pipeline training must
match sequential training float-for-float. Any latent bug in stage
partitioning, per-slot padding, mailbox routing, microbatch ordering or the
gradient ledger shows up here as a weight mismatch.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shallowspeed_tpu import model as Mo
from shallowspeed_tpu import schedules as S
from shallowspeed_tpu import trainer
from shallowspeed_tpu.optimizer import SGD, Adam, MomentumSGD
from shallowspeed_tpu.parallel import executor as E
from shallowspeed_tpu.observability.divergence import assert_models_equal
from shallowspeed_tpu.parallel import lower_schedule, make_mesh

SCHEDS = [S.NaiveParallelSchedule, S.GPipeSchedule, S.PipeDreamFlushSchedule]


def _random_case(seed):
    rng = np.random.RandomState(seed)
    dp, pp = [(1, 2), (2, 2), (1, 4), (2, 4), (4, 2), (4, 1)][seed % 6]
    # stage_size >= 2 keeps >= 1 Linear on the last stage (exact parity regime)
    n_sizes = pp * rng.randint(2, 4)
    n_sizes = max(n_sizes, 2)
    # monotone-decreasing widths ending in a class count no wider than any
    # hidden width (the documented passthrough constraint for uneven stages)
    widths = sorted(rng.randint(8, 48, size=n_sizes - 1).tolist(), reverse=True)
    sizes = tuple(widths) + (int(rng.randint(4, min(8, min(widths)) + 1)),)
    if len(sizes) % pp != 0:
        sizes = (sizes[0] + 2,) + sizes
        while len(sizes) % pp != 0:
            sizes = (sizes[0] + 2,) + sizes
    M = rng.choice([1, 2, 4])
    B = int(dp * M * rng.choice([4, 8]))
    sched = SCHEDS[seed % 3]
    return sizes, dp, pp, int(M), B, sched


@pytest.mark.parametrize("seed", range(12))
def test_random_layout_matches_sequential(seed):
    sizes, dp, pp, M, B, sched = _random_case(seed)
    spec_pp = Mo.make_model_spec(sizes, pp, B)
    if spec_pp.stages[-1].n_linears == 0:
        pytest.skip("zero-linear last stage differs architecturally (documented)")
    rng = np.random.RandomState(100 + seed)
    X = rng.randn(2, B, sizes[0]).astype(np.float32)
    Y = np.eye(sizes[-1], dtype=np.float32)[rng.randint(0, sizes[-1], (2, B))]

    # sequential
    spec1 = Mo.make_model_spec(sizes, 1, B)
    params = jax.tree.map(jnp.asarray, Mo.init_model(spec1))
    step1 = trainer.make_train_step(spec1, SGD(0.01))
    st = ()
    for i in range(2):
        params, st = step1(
            params,
            st,
            jnp.asarray(X[i].reshape(M, B // M, -1)),
            jnp.asarray(Y[i].reshape(M, B // M, -1)),
        )
    want = [l for stage in params for l in stage]

    # pipeline
    mesh = make_mesh(dp, pp)
    prog = lower_schedule(sched, M, pp)
    stacked, flags = E.init_stacked(spec_pp, mesh)
    step = E.make_pipeline_step(mesh, spec_pp, prog, B // dp // M, SGD(0.01))
    for i in range(2):
        stacked, _, _ = step(stacked, flags, (), jnp.asarray(X[i]), jnp.asarray(Y[i]))
    got = [l for stage in E.unstack_params(stacked, spec_pp) for l in stage]

    assert len(want) == len(got)
    for a, b in zip(want, got):
        np.testing.assert_allclose(
            np.asarray(a["W"]), b["W"], rtol=5e-4, atol=5e-6,
            err_msg=f"case: sizes={sizes} dp={dp} pp={pp} M={M} B={B} {sched.__name__}",
        )
        np.testing.assert_allclose(
            np.asarray(a["b"]).reshape(-1), b["b"].reshape(-1), rtol=5e-4, atol=5e-6
        )

    # inference path on the trained pipeline weights vs sequential predict
    eval_prog = lower_schedule(S.InferenceSchedule, M, pp, training=False)
    eval_step = E.make_pipeline_step(mesh, spec_pp, eval_prog, B // dp // M)
    preds = np.asarray(eval_step(stacked, flags, jnp.asarray(X[0])))
    seq_preds = np.asarray(trainer.make_predict(spec1)(params, jnp.asarray(X[0])))
    np.testing.assert_allclose(
        preds[:, : sizes[-1]], seq_preds, rtol=1e-3, atol=1e-5,
        err_msg=f"eval case: sizes={sizes} dp={dp} pp={pp} M={M}",
    )
    assert (preds[:, sizes[-1] :] == 0).all()


OPTS = [SGD(0.01), MomentumSGD(0.005, 0.9), Adam(0.003)]


def _random_case_r2(seed):
    """Round-2 feature fuzz: optimizer x zero1 x virtual stages, drawn from
    INDEPENDENT seed bits so every pairing (incl. zero1 + interleaved, and
    zero1 over a 4-way dp axis) occurs across the 12 seeds."""
    rng = np.random.RandomState(1000 + seed)
    V = [1, 2][seed % 2]
    zero1 = bool((seed // 2) % 2)
    dp, pp = [(2, 2), (1, 4), (4, 2)][(seed // 4) % 3]
    n_stages = pp * V
    # every stage gets >= 2 sizes (>= 1 Linear) -> exact-parity regime;
    # n_sizes is a multiple of n_stages by construction
    n_sizes = n_stages * int(rng.randint(2, 4))
    widths = sorted(rng.randint(8, 48, size=n_sizes - 1).tolist(), reverse=True)
    sizes = tuple(widths) + (int(rng.randint(4, min(8, min(widths)) + 1)),)
    M = int(pp * rng.choice([1, 2]))  # interleaved needs M % pp == 0
    B = int(dp * M * rng.choice([4, 8]))
    opt = OPTS[seed % 3]
    sched = S.InterleavedSchedule if V > 1 else SCHEDS[seed % 3]
    clip = [None, 0.05][(seed // 3) % 2]  # independent of the other bits
    # per-step loop vs fused whole-run program: offset the parity per mesh
    # block so every mesh (incl. the 2x2 square and 4-way dp) sees BOTH
    # execution modes across the 12 seeds
    fused = bool((seed + seed // 4) % 2)
    return sizes, dp, pp, V, M, B, opt, zero1, sched, clip, fused


def _assert_lattice_case_matches_sequential(
    sizes, dp, pp, V, M, B, opt, zero1, sched, clip, fused, data_seed,
    kb="xla", label_extra="", gbb=0, bsplit=False, tp=1, act="relu",
    recompute=False, zero=None,
):
    """The ONE sequential-vs-pipeline comparison harness behind the r2, r3
    and r4 lattice fuzz families: train two batches sequentially (the
    oracle) and through the mesh pipeline with the given feature
    combination, then compare every trained weight. ``tp > 1`` adds the
    Megatron model axis (same tolerance: its psums reassociate a split
    contraction, exactly like the dp sum). ``act`` picks the activation
    family (the model-zoo dimension); ``recompute`` drops the forward
    stash and re-runs the stage forward at the backward boundary — both
    must be invisible here. ``zero`` (superseding the ``zero1`` bool when
    set) picks the dp-axis ZeRO stage: 2-3 carry the cross-layout
    tolerance like tp (the per-tick scatter reassociates the microbatch
    sum), which is exactly what this oracle already prices."""
    spec_pp = Mo.make_model_spec(sizes, pp * V, B, act=act)
    assert spec_pp.stages[-1].n_linears > 0  # generator guarantees parity regime

    rng = np.random.RandomState(data_seed)
    X = rng.randn(2, B, sizes[0]).astype(np.float32)
    Y = np.eye(sizes[-1], dtype=np.float32)[rng.randint(0, sizes[-1], (2, B))]

    spec1 = Mo.make_model_spec(sizes, 1, B, act=act)
    params = jax.tree.map(jnp.asarray, Mo.init_model(spec1))
    step1 = trainer.make_train_step(spec1, opt, clip_norm=clip)
    st = opt.init(params)
    for i in range(2):
        params, st = step1(
            params,
            st,
            jnp.asarray(X[i].reshape(M, B // M, -1)),
            jnp.asarray(Y[i].reshape(M, B // M, -1)),
        )
    want = [l for stage in params for l in stage]

    mesh = make_mesh(dp, pp, tp=tp)
    order = E.interleave_order(pp * V, pp) if V > 1 else None
    prog = lower_schedule(
        sched, M, pp, virtual=V, backward_split=bsplit, recompute=recompute
    )
    stacked, flags = E.init_stacked(spec_pp, mesh, order=order)
    zstage = (1 if zero1 else 0) if zero is None else int(zero)
    if zstage >= 2:
        ost = E.zero_block_init_state(opt, spec_pp, mesh)
    elif zstage == 1:
        ost = E.zero1_init_state(opt, spec_pp, mesh)
    else:
        ost = opt.init(stacked)
    if zstage == 3:
        rows = E.zero_block_flatten_rows(
            jax.device_get(stacked), spec_pp, mesh)
        stacked = {"P": jax.device_put(rows, E.zero1_part_sharding(mesh))}
    if fused:
        # same two batches as one epoch inside the fused whole-run program
        run = E.make_pipeline_run(
            mesh, spec_pp, prog, B // dp // M, opt, zero=zstage,
            clip_norm=clip, kernel_backend=kb, grad_bucket_bytes=gbb,
        )
        stacked, ost, _ = run(stacked, flags, ost, jnp.asarray(X), jnp.asarray(Y), 1)
    else:
        step = E.make_pipeline_step(
            mesh, spec_pp, prog, B // dp // M, opt, zero=zstage,
            clip_norm=clip, kernel_backend=kb, grad_bucket_bytes=gbb,
        )
        for i in range(2):
            stacked, ost, _ = step(
                stacked, flags, ost, jnp.asarray(X[i]), jnp.asarray(Y[i])
            )
    if zstage == 3:
        stacked = E.zero_block_unflatten_rows(
            np.asarray(jax.device_get(stacked["P"])), spec_pp, mesh)
    got = [l for s in E.unstack_params(stacked, spec_pp, order=order) for l in s]
    assert len(want) == len(got)

    label = (
        f"sizes={sizes} dp={dp} pp={pp} tp={tp} V={V} M={M} B={B} "
        f"{type(opt).__name__} zero={zstage} clip={clip} fused={fused} "
        f"gbb={gbb} bsplit={bsplit} act={act} rec={recompute} "
        f"{sched.__name__}{label_extra}"
    )
    # Adam's early update direction is ~g/|g| per element: near-zero second
    # moments amplify ulp-level cross-layout reassociation of g, so its
    # tolerance is an order looser than the mul/add optimizers'
    rtol, atol = (5e-3, 5e-5) if isinstance(opt, Adam) else (5e-4, 5e-6)
    for a, b in zip(want, got):
        np.testing.assert_allclose(
            np.asarray(a["W"]), b["W"], rtol=rtol, atol=atol, err_msg=label
        )
        np.testing.assert_allclose(
            np.asarray(a["b"]).reshape(-1), b["b"].reshape(-1),
            rtol=rtol, atol=atol, err_msg=label,
        )


@pytest.mark.parametrize(
    "seed",
    # seed 5 deterministically draws the heaviest combo (~10s alone) —
    # it rides the slow tier (1-core wall budget), still in the full suite
    [pytest.param(s, marks=pytest.mark.slow) if s == 5 else s for s in range(12)],
)
def test_random_r2_feature_combo_matches_sequential(seed):
    """Random (optimizer, zero1, virtual-stage) combinations must still equal
    sequential training with the same optimizer — the round-2 features
    compose, not just work in isolation."""
    sizes, dp, pp, V, M, B, opt, zero1, sched, clip, fused = _random_case_r2(seed)
    _assert_lattice_case_matches_sequential(
        sizes, dp, pp, V, M, B, opt, zero1, sched, clip, fused,
        data_seed=2000 + seed,
    )


def _random_case_r3(seed):
    """Round-5 feature fuzz (round-4 verdict #3), round-10 extension: the
    full lattice — optimizer x zero1 x kernel_backend x virtual stages x
    epoch-vs-step x gradient-sync bucketing x backward splitting x TENSOR
    PARALLELISM — from independent seed bits, so pallas-backend
    interactions (e.g. zero1 x pallas x interleaved), bucketed-sync,
    split-backward and Megatron-tp interactions get randomized coverage,
    not just their dedicated tests. tp rides its own bit wherever it is
    supported (the xla backend; the pallas flag kernels compute whole
    slots), so it crosses dp/pp/zero1/bucketing/clip/fused-run and the
    split backward across the seeds."""
    rng = np.random.RandomState(3000 + seed)
    kb = ["xla", "pallas"][seed % 2]
    # bucketed gradient sync rides an independent bit + a random byte
    # budget, so bucketing meets every other feature across the seeds
    gbb = [0, int(rng.choice([256, 1024, 8192]))][(seed + seed // 5) % 2]
    V = [1, 2][(seed // 2) % 2]
    dp, pp = [(2, 2), (1, 4), (2, 1)][(seed // 4) % 3]
    opt = OPTS[(seed + seed // 2) % 3]
    zero1 = bool((seed // 3) % 2)
    clip = [None, 0.05][(seed // 6) % 2]
    fused = bool((seed + seed // 4) % 2)  # per-step loop vs whole-run program
    # split backward rides its own bit wherever it is supported (flat
    # schedules on the xla backend), so it meets zero1, clipping,
    # bucketing and the fused-run path across the seeds
    bsplit = bool((seed + seed // 3) % 2) and V == 1 and kb == "xla"
    # the tp axis: every (dp, pp) block here fits x2 on the 8 emulated
    # devices ((2,2)->8, (1,4)->8, (2,1)->4)
    tp = 2 if kb == "xla" and ((seed + seed // 6) % 2) else 1
    n_stages = pp * V
    n_sizes = n_stages * int(rng.randint(2, 4))
    n_sizes = max(n_sizes, 2)
    widths = sorted(rng.randint(8, 48, size=n_sizes - 1).tolist(), reverse=True)
    sizes = tuple(widths) + (int(rng.randint(4, min(8, min(widths)) + 1)),)
    M = int(pp * rng.choice([1, 2]))  # interleaved needs M % pp == 0
    B = int(dp * M * rng.choice([4, 8]))
    sched = S.InterleavedSchedule if V > 1 else SCHEDS[seed % 3]
    return (
        sizes, dp, pp, V, M, B, opt, zero1, kb, sched, clip, fused, gbb,
        bsplit, tp,
    )


@pytest.mark.parametrize(
    "seed",
    # seed 7 deterministically draws the heaviest combo (~7s alone) —
    # it rides the slow tier (1-core wall budget), still in the full suite
    [pytest.param(s, marks=pytest.mark.slow) if s == 7 else s for s in range(12)],
)
def test_random_r3_kernel_backend_combo_matches_sequential(seed):
    """Random (optimizer, zero1, kernel_backend, virtual, epoch-vs-step,
    grad-bucket-bytes, backward-split, tp) combinations must still equal
    sequential training — the pallas executor backend, the bucketed
    gradient sync, the split backward and Megatron tensor parallelism
    compose with every other feature, not just dp=pp=1."""
    (
        sizes, dp, pp, V, M, B, opt, zero1, kb, sched, clip, fused, gbb,
        bsplit, tp,
    ) = _random_case_r3(seed)
    _assert_lattice_case_matches_sequential(
        sizes, dp, pp, V, M, B, opt, zero1, sched, clip, fused,
        data_seed=4000 + seed, kb=kb, label_extra=f" kb={kb}", gbb=gbb,
        bsplit=bsplit, tp=tp,
    )


def _random_case_r4(seed):
    """Round-19 feature fuzz: the MODEL and RECOMPUTE dimensions —
    activation family (relu vs the transformer-style gelu+residual
    slots) and pipeline activation recompute — from independent seed
    bits, crossed with dp x pp x tp x zero1 x grad-bucketing x
    backward-split x epoch-vs-step, so recompute meets every shipped
    feature across the 12 seeds, not just its dedicated twins. Recompute
    needs a flat pipeline schedule (pp > 1, V == 1); gelu is excluded
    from the pallas backend only, which this family never draws."""
    rng = np.random.RandomState(7000 + seed)
    act = ["relu", "gelu"][seed % 2]
    recompute = bool((seed // 2) % 2)
    dp, pp = [(1, 4), (2, 2), (1, 2)][(seed // 4) % 3]
    opt = OPTS[(seed + seed // 3) % 3]
    zero1 = bool((seed // 3) % 2)
    clip = [None, 0.05][(seed + seed // 2) % 2]
    fused = bool((seed + seed // 4) % 2)
    gbb = [0, int(rng.choice([256, 8192]))][(seed // 5) % 2]
    bsplit = bool((seed + seed // 6) % 2)
    tp = 2 if (seed + seed // 5) % 2 and dp * pp <= 4 else 1
    per = int(rng.randint(2, 4))
    if act == "gelu":
        # gelu slot parity needs an even per-stage slice (model.py)
        per += per % 2
    n_sizes = pp * per
    widths = sorted(rng.randint(8, 48, size=n_sizes - 1).tolist(), reverse=True)
    sizes = tuple(widths) + (int(rng.randint(4, min(8, min(widths)) + 1)),)
    M = int(rng.choice([2, 4]))
    B = int(dp * M * rng.choice([4, 8]))
    sched = SCHEDS[seed % 3]
    return (
        sizes, dp, pp, M, B, opt, zero1, sched, clip, fused, gbb, bsplit,
        tp, act, recompute,
    )


@pytest.mark.parametrize(
    "seed",
    # seeds 2 and 3 (relu+recompute, gelu+recompute — the new lattice
    # dimension) keep tier-1 coverage; the rest ride the slow tier
    # (1-core wall budget)
    [s if s in (2, 3) else pytest.param(s, marks=pytest.mark.slow)
     for s in range(12)],
)
def test_random_r4_model_recompute_combo_matches_sequential(seed):
    """Random (activation family, recompute) combinations crossed with
    dp/pp/tp/zero1/bucketing/backward-split must still equal sequential
    training — the model zoo and the recompute tick are invisible to the
    math on every layout, not just the flagship relu-MLP."""
    (
        sizes, dp, pp, M, B, opt, zero1, sched, clip, fused, gbb, bsplit,
        tp, act, recompute,
    ) = _random_case_r4(seed)
    _assert_lattice_case_matches_sequential(
        sizes, dp, pp, 1, M, B, opt, zero1, sched, clip, fused,
        data_seed=8000 + seed, gbb=gbb, bsplit=bsplit, tp=tp, act=act,
        recompute=recompute,
    )


def _random_case_r5(seed):
    """Round-20 feature fuzz: the ZeRO STAGE dimension — ``zero`` in
    {0,1,2,3} cycling every 4 seeds so each stage meets three different
    feature draws — crossed with tp x grad-bucketing x backward-split x
    interleaved virtual stages x epoch-vs-step. Stage constraints mirror
    the executor's refusals: stage 3 syncs per tick (no bucket plan) and
    keeps params sharded at rest (the fused whole-run program's eval
    view is an API-level refusal, so the fused bit only rides stages
    0-2)."""
    rng = np.random.RandomState(9000 + seed)
    zero = seed % 4
    dp, pp = [(2, 2), (4, 2), (2, 1)][(seed // 4) % 3]
    V = 2 if (seed // 2) % 2 and pp > 1 else 1
    opt = OPTS[(seed + seed // 3) % 3]
    clip = [None, 0.05][(seed + seed // 2) % 2]
    gbb = (
        [0, int(rng.choice([256, 8192]))][(seed // 5) % 2]
        if zero != 3 else 0
    )
    bsplit = bool((seed + seed // 6) % 2) and V == 1 and pp > 1
    tp = 2 if (seed + seed // 5) % 2 and dp * pp <= 4 else 1
    fused = bool((seed + seed // 4) % 2) and zero != 3
    n_stages = pp * V
    n_sizes = n_stages * int(rng.randint(2, 4))
    widths = sorted(rng.randint(8, 48, size=n_sizes - 1).tolist(), reverse=True)
    sizes = tuple(widths) + (int(rng.randint(4, min(8, min(widths)) + 1)),)
    M = int(pp * rng.choice([1, 2]))  # interleaved needs M % pp == 0
    B = int(dp * M * rng.choice([4, 8]))
    sched = S.InterleavedSchedule if V > 1 else (
        S.PipeDreamFlushSchedule if bsplit else SCHEDS[seed % 3])
    return sizes, dp, pp, V, M, B, opt, zero, sched, clip, fused, gbb, bsplit, tp


@pytest.mark.parametrize(
    "seed",
    # seed 3 (zero=3 — the most exotic point of the new lattice
    # dimension) keeps tier-1 coverage; the rest ride the slow tier
    # (1-core wall budget; stage 2 has dedicated tier-1 legs in
    # test_zero23.py)
    [s if s == 3 else pytest.param(s, marks=pytest.mark.slow)
     for s in range(12)],
)
def test_random_r5_zero_stage_combo_matches_sequential(seed):
    """Random ZeRO-stage draws crossed with tp/bucketing/backward-split/
    interleaved must still equal sequential training — the dp-axis
    residency lattice is invisible to the math on every layout."""
    (
        sizes, dp, pp, V, M, B, opt, zero, sched, clip, fused, gbb, bsplit,
        tp,
    ) = _random_case_r5(seed)
    _assert_lattice_case_matches_sequential(
        sizes, dp, pp, V, M, B, opt, False, sched, clip, fused,
        data_seed=9500 + seed, gbb=gbb, bsplit=bsplit, tp=tp, zero=zero,
    )


BUCKET_LAYOUTS = {
    # layout -> (dp, pp, zero1, schedule)
    "dp2": (2, 1, False, S.GPipeSchedule),
    "zero1": (2, 2, True, S.GPipeSchedule),
    "gpipe-dp": (2, 2, False, S.GPipeSchedule),
}


@pytest.mark.parametrize("layout", sorted(BUCKET_LAYOUTS))
def test_bucketed_sync_bitwise_identical_to_anchor(layout):
    """The bucketing acceptance criterion: per-bucket gradient sync is
    BITWISE identical to the anchor collective — final weights, loss AND
    the pre-clip global grad norm (which must read post-sync buckets) —
    on dp-only, ZeRO-1 and pipeline+dp layouts, across bucket budgets,
    with global-norm clipping active the whole time."""
    dp, pp, zero1, sched = BUCKET_LAYOUTS[layout]
    sizes = (40, 36, 32, 28, 24, 20, 14, 10)
    M, B = 4, 32
    spec = Mo.make_model_spec(sizes, pp, B)
    mesh = make_mesh(dp, pp)
    prog = lower_schedule(sched, M, pp)
    rng = np.random.RandomState(7)
    X = rng.randn(2, B, sizes[0]).astype(np.float32)
    Y = np.eye(sizes[-1], dtype=np.float32)[rng.randint(0, sizes[-1], (2, B))]

    def train(gbb):
        opt = SGD(0.01)
        stacked, flags = E.init_stacked(spec, mesh)
        ost = E.zero1_init_state(opt, spec, mesh) if zero1 else opt.init(stacked)
        step = E.make_pipeline_step(
            mesh, spec, prog, B // dp // M, opt, zero1=zero1,
            clip_norm=0.05, with_grad_norm=True, grad_bucket_bytes=gbb,
        )
        for i in range(2):
            stacked, ost, loss, gnorm = step(
                stacked, flags, ost, jnp.asarray(X[i]), jnp.asarray(Y[i])
            )
        return jax.device_get(stacked), float(loss), float(gnorm)

    anchor_w, anchor_loss, anchor_gn = train(0)
    for gbb in (512, 8192):
        w, loss, gn = train(gbb)
        label = f"{layout} gbb={gbb}"
        assert loss == anchor_loss, label
        assert gn == anchor_gn, label  # the norm reads post-sync buckets
        for a, b in zip(jax.tree.leaves(anchor_w), jax.tree.leaves(w)):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b), err_msg=label
            )


BSPLIT_LAYOUTS = {
    # layout -> (dp, pp, zero1, schedule, clip, grad_bucket_bytes)
    "pp4-gpipe": (1, 4, False, S.GPipeSchedule, None, 0),
    "pp4-pipedream-clip": (1, 4, False, S.PipeDreamFlushSchedule, 0.05, 0),
    "dp2pp2-bucketed": (2, 2, False, S.GPipeSchedule, 0.05, 1024),
    "zero1": (2, 2, True, S.PipeDreamFlushSchedule, None, 0),
    "dp2-naive": (2, 1, False, S.NaiveParallelSchedule, None, 8192),
}


@pytest.mark.parametrize("layout", sorted(BSPLIT_LAYOUTS))
def test_backward_split_bitwise_identical_to_unsplit(layout):
    """The split-backward acceptance criterion: two-stage backward (B-input
    at the combined backward's tick, B-weight deferred into bubbles) is
    BITWISE identical to the unsplit schedule — final weights, loss AND
    the pre-clip global grad norm — across dp x pp x clip x grad-bucket
    combinations, GPipe and 1F1B (and naive) alike. The lowering enforces
    the weight-grad accumulation order this equality depends on."""
    dp, pp, zero1, sched, clip, gbb = BSPLIT_LAYOUTS[layout]
    sizes = (40, 36, 32, 28, 24, 20, 14, 10)
    M, B = 4, 32
    spec = Mo.make_model_spec(sizes, pp, B)
    mesh = make_mesh(dp, pp)
    rng = np.random.RandomState(11)
    X = rng.randn(2, B, sizes[0]).astype(np.float32)
    Y = np.eye(sizes[-1], dtype=np.float32)[rng.randint(0, sizes[-1], (2, B))]

    def train(bsplit):
        opt = SGD(0.01)
        prog = lower_schedule(sched, M, pp, backward_split=bsplit)
        stacked, flags = E.init_stacked(spec, mesh)
        ost = E.zero1_init_state(opt, spec, mesh) if zero1 else opt.init(stacked)
        step = E.make_pipeline_step(
            mesh, spec, prog, B // dp // M, opt, zero1=zero1,
            clip_norm=clip, with_grad_norm=True, grad_bucket_bytes=gbb,
        )
        for i in range(2):
            stacked, ost, loss, gnorm = step(
                stacked, flags, ost, jnp.asarray(X[i]), jnp.asarray(Y[i])
            )
        return jax.device_get(stacked), float(loss), float(gnorm)

    base_w, base_loss, base_gn = train(False)
    w, loss, gn = train(True)
    assert loss == base_loss, layout
    assert gn == base_gn, layout
    for a, b in zip(jax.tree.leaves(base_w), jax.tree.leaves(w)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=layout)


RECOMPUTE_LAYOUTS = {
    # layout -> (dp, pp, tp, zero1, schedule, bsplit, gbb, act)
    "pp4-gpipe": (1, 4, 1, False, S.GPipeSchedule, False, 0, "relu"),
    "pp4-pipedream-split": (
        1, 4, 1, False, S.PipeDreamFlushSchedule, True, 0, "relu",
    ),
    "dp2pp2-bucketed": (2, 2, 1, False, S.GPipeSchedule, False, 1024, "gelu"),
    "zero1": (2, 2, 1, True, S.PipeDreamFlushSchedule, False, 0, "relu"),
    "tp2-gelu": (1, 2, 2, False, S.GPipeSchedule, False, 0, "gelu"),
}


@pytest.mark.parametrize(
    "layout",
    # the two pp4 layouts (plain + split, the recompute-smoke pair) keep
    # tier-1 coverage; the dp/zero1/tp compositions ride the slow tier
    # (1-core wall budget), still in the full suite
    [lay if lay.startswith("pp4") else
     pytest.param(lay, marks=pytest.mark.slow)
     for lay in sorted(RECOMPUTE_LAYOUTS)],
)
def test_recompute_bitwise_identical_to_stashed(layout):
    """The recompute acceptance criterion (arXiv 2004.09910): dropping the
    forward activation stash and re-running the stage forward inside the
    backward tick is BITWISE identical to stashed training — final
    weights, loss AND the pre-clip global grad norm — across dp x pp x
    tp x zero1 x bucketing x split-backward and both activation
    families, with global-norm clipping active the whole time. The
    recompute forward re-executes character-identical slot expressions,
    so there is no tolerance to hide behind. The same pair of lowered
    programs must also PROVE the memory win: ``assert_recompute_peak_drop``
    replays both tick tables and refuses unless the recompute program's
    stash peak is strictly below its stashed twin's."""
    from shallowspeed_tpu.analysis.stash import assert_recompute_peak_drop

    dp, pp, tp, zero1, sched, bsplit, gbb, act = RECOMPUTE_LAYOUTS[layout]
    sizes = (40, 36, 32, 28, 24, 20, 14, 10)
    M, B = 4, 32
    spec = Mo.make_model_spec(sizes, pp, B, act=act)
    mesh = make_mesh(dp, pp, tp=tp)
    rng = np.random.RandomState(13)
    X = rng.randn(2, B, sizes[0]).astype(np.float32)
    Y = np.eye(sizes[-1], dtype=np.float32)[rng.randint(0, sizes[-1], (2, B))]
    progs = {
        rec: lower_schedule(sched, M, pp, backward_split=bsplit, recompute=rec)
        for rec in (False, True)
    }
    drop = assert_recompute_peak_drop(progs[False], progs[True])
    assert (
        drop["stash_peak_recompute"] < drop["stash_peak_stashed"]
        or drop["stash_peak_stashed"] == 1
    ), (layout, drop)

    def train(rec):
        opt = SGD(0.01)
        stacked, flags = E.init_stacked(spec, mesh)
        ost = E.zero1_init_state(opt, spec, mesh) if zero1 else opt.init(stacked)
        step = E.make_pipeline_step(
            mesh, spec, progs[rec], B // dp // M, opt, zero1=zero1,
            clip_norm=0.05, with_grad_norm=True, grad_bucket_bytes=gbb,
        )
        for i in range(2):
            stacked, ost, loss, gnorm = step(
                stacked, flags, ost, jnp.asarray(X[i]), jnp.asarray(Y[i])
            )
        return jax.device_get(stacked), float(loss), float(gnorm)

    base_w, base_loss, base_gn = train(False)
    w, loss, gn = train(True)
    assert loss == base_loss, layout
    assert gn == base_gn, layout
    for a, b in zip(jax.tree.leaves(base_w), jax.tree.leaves(w)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=layout)


KILL_RESUME_LAYOUTS = {
    # layout -> (killed-run session kwargs, resumed-run session kwargs) —
    # they differ only for the elastic case, which restores a dp=2 run's
    # snapshot onto a dp=4 mesh (same global batch, so the deterministic
    # data order — the bit-identity prerequisite — is unchanged)
    "dp2": (dict(dp=2), dict(dp=2)),
    "gpipe-pp4": (
        dict(pp=4, schedule="gpipe", mubatches=4),
        dict(pp=4, schedule="gpipe", mubatches=4),
    ),
    "zero1": (
        dict(dp=2, pp=2, schedule="gpipe", zero1=True, optimizer="momentum"),
        dict(dp=2, pp=2, schedule="gpipe", zero1=True, optimizer="momentum"),
    ),
    "bucketed": (
        dict(dp=2, grad_bucket_bytes=1024),
        dict(dp=2, grad_bucket_bytes=1024),
    ),
    "bsplit": (
        dict(pp=4, schedule="pipedream", backward_split=True, mubatches=4),
        dict(pp=4, schedule="pipedream", backward_split=True, mubatches=4),
    ),
    # activation recompute rides the same contract: the recompute tick is
    # program structure, not state — snapshots hold logical params only
    "recompute": (
        dict(pp=4, schedule="gpipe", recompute=True, mubatches=4),
        dict(pp=4, schedule="gpipe", recompute=True, mubatches=4),
    ),
    "elastic-dp2-to-dp4": (
        dict(dp=2, optimizer="momentum"),
        dict(dp=4, optimizer="momentum"),
    ),
    # tensor parallelism rides the same contract: a tp2 run's snapshot is
    # layout-free host data (the stacked tp shards reassemble to logical
    # params before saving), so kill-and-resume at tp2 is bitwise...
    "tp2": (dict(tp=2), dict(tp=2)),
    # ...and a dp2 snapshot restores onto a tp2 mesh exactly (the elastic
    # leg: exact at the restore point, cross-layout tolerance at the
    # finish line — the Megatron psums reassociate the split
    # contractions, like a dp-width change reassociates the all-reduce)
    "elastic-dp2-to-tp2": (
        dict(dp=2, optimizer="momentum"),
        dict(tp=2, optimizer="momentum"),
    ),
}


@pytest.fixture(scope="module")
def session_data_dir(tmp_path_factory):
    sizes = (24, 20, 18, 16, 14, 12, 11, 10)
    d = tmp_path_factory.mktemp("kill_resume_data")
    rng = np.random.RandomState(0)
    for suffix, n in (("train", 256), ("val", 96)):
        np.save(d / f"x_{suffix}.npy", rng.randn(n, sizes[0]).astype(np.float32))
        np.save(
            d / f"y_{suffix}.npy",
            np.eye(sizes[-1], dtype=np.float32)[rng.randint(0, sizes[-1], n)],
        )
    return d


@pytest.mark.parametrize(
    "layout",
    [
        # the elastic restores run two full sessions each and are the
        # slowest legs — exotic layouts ride the slow tier (1-core wall
        # budget); the same-layout legs keep tier-1 coverage. The
        # recompute leg rides slow too: checkpoints are recompute-
        # agnostic by construction and make recompute-smoke drives the
        # same parity end to end
        pytest.param(lay, marks=pytest.mark.slow)
        if lay.startswith("elastic") or lay == "recompute"
        else lay
        for lay in sorted(KILL_RESUME_LAYOUTS)
    ],
)
def test_kill_and_resume_bitwise_identical_to_uninterrupted(
    layout, session_data_dir, tmp_path
):
    """The kill-and-resume lattice dimension (docs/robustness.md): on every
    feature layout — dp, pipeline, ZeRO-1, bucketed grad sync, split
    backward — a run killed by an injected fault at a mid-epoch step and
    resumed from its last step snapshot finishes on exactly the bits of
    the uninterrupted twin. The ELASTIC dp=2 -> dp=4 restore is exact at
    the restore point (same logical snapshot, bit-identical load onto the
    wider mesh) and float-equivalent at the finish line — a different dp
    width reassociates the gradient all-reduce sum, so the cross-WIDTH
    comparison carries the repo's cross-layout tolerance, not bitwise."""
    from shallowspeed_tpu.api import TrainingSession
    from shallowspeed_tpu.faults import InjectedFault

    kw_killed, kw_resumed = KILL_RESUME_LAYOUTS[layout]
    # pp=4 needs 8 sizes (2 per stage); everything shallower runs a 3-layer
    # model — the recovery contract is about state capture, not depth, and
    # compile time is what this lattice mostly spends
    pp = kw_killed.get("pp", 1)
    common = dict(
        sizes=(24, 20, 18, 16, 14, 12, 11, 10) if pp == 4 else (24, 18, 14, 10),
        global_batch_size=64,  # 4 steps/epoch over the 256-sample shard
        lr=0.01,
        data_dir=session_data_dir,
    )
    twin = TrainingSession(**common, **kw_killed)
    for _ in range(2):
        twin.train_epoch()

    ck = tmp_path / "ck"
    run = TrainingSession(
        **common, **kw_killed, checkpoint_dir=ck, faults="die@step=5"
    )
    with pytest.raises(InjectedFault):
        while run.epoch < 2:
            run.train_steps(2)
            run.save_step_checkpoint()

    res = TrainingSession(
        **common, **kw_resumed, checkpoint_dir=ck, resume="auto"
    )
    assert res.resumed_from is not None and res.global_step == 5, layout
    elastic = kw_killed != kw_resumed
    if elastic:
        # the restore itself is exact across widths: at the restore point
        # the dp=4 session's layout-independent hash equals the snapshot's
        # logical params hash, bit for bit
        from shallowspeed_tpu import utils
        from shallowspeed_tpu.checkpoint import load_checkpoint

        snap_params, _, _ = load_checkpoint(res.resumed_from, 1)
        assert_models_equal(
            res.params(), snap_params, f"resumed[{layout}]", "snapshot"
        )
    while res.epoch < 2:
        res.train_steps(2)
    if not elastic:
        assert_models_equal(
            res.params(), twin.params(), f"resumed[{layout}]", "twin"
        )
    else:
        want = [l for st in twin.params() for l in st]
        got = [l for st in res.params() for l in st]
        for a, b in zip(want, got):
            np.testing.assert_allclose(
                np.asarray(a["W"]), np.asarray(b["W"]),
                rtol=3e-4, atol=3e-6, err_msg=layout,
            )


@pytest.mark.parametrize("seed", range(12))
def test_random_kernel_variant_fuzz(seed):
    """Sequential kernel-variant fuzz: random single-stage shapes, optimizer,
    clip and weight decay — the mega- and epoch-kernels must stay
    BIT-identical to the fused-XLA epoch, not just at the handcrafted
    shapes of their dedicated tests."""
    rng = np.random.RandomState(5000 + seed)
    L = int(rng.randint(2, 6))
    widths = sorted(rng.randint(8, 40, size=L).tolist(), reverse=True)
    sizes = tuple(widths) + (int(rng.randint(4, min(8, min(widths)) + 1)),)
    M = int(rng.choice([1, 2, 4]))
    B = int(M * rng.choice([4, 8]))
    nb = int(rng.randint(1, 4))
    opt = OPTS[seed % 3]
    clip = [None, 0.05][(seed // 3) % 2]

    X = jnp.asarray(rng.rand(nb, M, B // M, sizes[0]).astype(np.float32))
    Y = jnp.asarray(
        np.eye(sizes[-1], dtype=np.float32)[rng.randint(0, sizes[-1], (nb, M, B // M))]
    )
    spec = Mo.make_model_spec(sizes, 1, B)
    label = f"sizes={sizes} M={M} B={B} nb={nb} {type(opt).__name__} clip={clip}"
    out = {}
    for name, kw in {
        "xla": {},
        "mega": {"megakernel": True},
        "epoch": {"epoch_kernel": True},
    }.items():
        params = jax.tree.map(jnp.asarray, Mo.init_model(spec))
        st = opt.init(params)
        epoch = trainer.make_train_epoch(
            spec, opt, fuse_mubatches=True, clip_norm=clip, **kw
        )
        params, st, loss = epoch(params, st, X, Y)
        out[name] = (jax.device_get(params), jax.device_get(st), float(loss))
    # the whole-RUN kernel at n_epochs=1 must land on the same bits too
    params = jax.tree.map(jnp.asarray, Mo.init_model(spec))
    st = opt.init(params)
    run = trainer.make_train_run(
        spec, opt, fuse_mubatches=True, with_eval=False, run_kernel=True,
        clip_norm=clip,
    )
    p_r, st_r, losses_r = run(params, st, X, Y, 1)
    out["run"] = (
        jax.device_get(p_r), jax.device_get(st_r), float(losses_r[0])
    )
    for other in ("mega", "epoch", "run"):
        assert out["xla"][2] == out[other][2], label
        for tree_idx in (0, 1):
            for a, b in zip(
                jax.tree.leaves(out["xla"][tree_idx]),
                jax.tree.leaves(out[other][tree_idx]),
            ):
                np.testing.assert_array_equal(
                    np.asarray(a), np.asarray(b), err_msg=label
                )


# ---------------------------------------------------------------------------
# the async-save dimension of the kill-resume lattice (PR 12)
# ---------------------------------------------------------------------------

ASYNC_KILL_LAYOUTS = {
    "dp2": ["--dp", "2"],
    "gpipe-pp4": ["--pp", "4", "--schedule", "gpipe"],
    "tp2": ["--tp", "2"],
}


@pytest.fixture(scope="module")
def flagship_data_dir(tmp_path_factory):
    """784-dim synthetic data: the subprocess legs drive the real train.py,
    which trains the flagship model."""
    d = tmp_path_factory.mktemp("async_kill_data")
    rng = np.random.RandomState(0)
    for suffix, n in (("train", 256), ("val", 96)):
        np.save(d / f"x_{suffix}.npy", rng.rand(n, 784).astype(np.float32))
        np.save(
            d / f"y_{suffix}.npy",
            np.eye(10, dtype=np.float32)[rng.randint(0, 10, n)],
        )
    return d


@pytest.mark.parametrize(
    "layout",
    [
        "dp2",
        # the writer-window contract is layout-free host-side snapshot
        # logic, so one tier-1 subprocess leg suffices; the tp2/pp4 twins
        # ride the slow tier (1-core wall budget), still in the full suite
        pytest.param("gpipe-pp4", marks=pytest.mark.slow),
        pytest.param("tp2", marks=pytest.mark.slow),
    ],
)
def test_async_save_sigkill_in_writer_window_resumes_bitwise(
    layout, flagship_data_dir, tmp_path
):
    """The async-save dimension of the kill-resume lattice
    (docs/robustness.md "The async writer's crash windows"): a REAL
    train.py process checkpointing through the background writer is
    SIGKILLed at a fault-injected point INSIDE the writer's
    write/verify/rename window (die@save=N — after the temp file is
    durable, before the rename), across dp2 / gpipe-pp4 / tp2. The
    contract: `find_latest_good` never sees a torn or unverified file
    (only older fully-verifying snapshots are discoverable; the victim's
    temp is rename-invisible), and the resumed run finishes bitwise
    identical to the uninterrupted twin."""
    import os
    import re
    import subprocess
    import sys
    from pathlib import Path

    from shallowspeed_tpu.checkpoint import (
        find_latest_good,
        list_step_checkpoints,
    )

    root = Path(__file__).resolve().parent.parent
    lflags = ASYNC_KILL_LAYOUTS[layout]
    common = [
        "--data-dir", str(flagship_data_dir), "--epochs", "2",
        "--global-batch-size", "32", "--no-eval",
    ]

    def run(args, check=True, faults_spec=None):
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("SHALLOWSPEED_FAULTS", None)
        if faults_spec:
            env["SHALLOWSPEED_FAULTS"] = faults_spec
        r = subprocess.run(
            [sys.executable, str(root / "train.py"), *args],
            capture_output=True, text=True, timeout=540, cwd=root, env=env,
        )
        if check:
            assert r.returncode == 0, r.stderr[-2000:]
        return r

    twin = run(common + lflags)
    twin_hash = re.search(r"final model hash: ([0-9a-f]{40})", twin.stdout)
    assert twin_hash, twin.stdout

    ck = tmp_path / "ck"
    killed_args = common + lflags + [
        "--checkpoint-dir", str(ck), "--checkpoint-every-steps", "3",
        "--async-checkpoint",
    ]
    r = run(
        killed_args, check=False, faults_spec="die@save=2:mode=sigkill"
    )
    assert r.returncode == -9, (r.returncode, r.stderr[-1000:])
    # saves land at steps 3, 6, 9, ... — save seq 2 (step 9) was killed
    # INSIDE the window: its temp is durable but never renamed, so
    # discovery sees only the older fully-verifying snapshots
    steps = [gs for gs, _ in list_step_checkpoints(ck)]
    assert steps == [3, 6], (layout, steps)
    p, meta, skipped = find_latest_good(ck)
    assert p is not None and p.name == "step-00000006.npz", layout
    assert skipped == [], (layout, skipped)  # nothing torn is discoverable

    resumed = run(killed_args + ["--resume", "auto"])
    assert "resumed at epoch" in resumed.stdout, resumed.stdout
    res_hash = re.search(
        r"final model hash: ([0-9a-f]{40})", resumed.stdout
    )
    assert res_hash and res_hash.group(1) == twin_hash.group(1), layout
