"""Pallas kernel tests (interpreter mode on CPU, real kernels on TPU).

Verifies the fused linear+relu forward/backward kernels against the XLA path
and that the whole model trains identically with the Pallas backend enabled.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shallowspeed_tpu import model as Mo
from shallowspeed_tpu import ops, pallas_ops, trainer
from shallowspeed_tpu.optimizer import SGD, Adam, MomentumSGD

RNG = np.random.RandomState(0)


def r(*shape):
    return jnp.asarray(RNG.randn(*shape).astype(np.float32))


class TestKernels:
    def test_fwd_matches_xla(self):
        x, w, b = r(16, 24), r(20, 24), r(1, 20)
        y, mask = pallas_ops.linear_relu_fwd(x, w, b)
        y_ref = ops.relu(ops.linear(x, w, b))
        mask_ref = ops.linear(x, w, b) > 0
        np.testing.assert_allclose(y, y_ref, rtol=1e-5, atol=1e-6)
        np.testing.assert_array_equal(np.asarray(mask) > 0, np.asarray(mask_ref))

    def test_bwd_matches_xla(self):
        x, w = r(16, 24), r(20, 24)
        g = r(16, 20)
        mask = (r(16, 20) > 0).astype(jnp.float32)
        dx, dw, db = pallas_ops.linear_relu_bwd(g, mask, x, w)
        dx_r, dw_r, db_r = ops.linear_grad(g * mask, x, w)
        np.testing.assert_allclose(dx, dx_r, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(dw, dw_r, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(db).reshape(-1), db_r, rtol=1e-5, atol=1e-6)

    def test_bwd_matches_autograd(self):
        x, w, b = r(8, 12), r(10, 12), r(1, 10)

        def f_ref(x, w, b):
            return (ops.relu(ops.linear(x, w, b)) ** 2).sum()

        y, mask = pallas_ops.linear_relu_fwd(x, w, b)
        g = 2 * y
        dx, dw, db = pallas_ops.linear_relu_bwd(g, mask, x, w)
        gx, gw, gb = jax.grad(f_ref, argnums=(0, 1, 2))(x, w, b)
        np.testing.assert_allclose(dx, gx, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(dw, gw, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(db, gb, rtol=1e-4, atol=1e-5)


class TestTiledKernels:
    """Grid-tiled variants on ragged shapes: multi-tile grids in every
    dimension plus edge padding, checked against the XLA path."""

    MB, DIN, DOUT, TILE = 300, 260, 200, 128  # 3x3x2 tiles, all ragged

    def test_tiled_fwd_matches_xla(self):
        x, w, b = r(self.MB, self.DIN), r(self.DOUT, self.DIN), r(1, self.DOUT)
        y, mask = pallas_ops.linear_relu_fwd_tiled(x, w, b, tile=self.TILE)
        z = np.asarray(ops.linear(x, w, b))
        # contraction order differs between the tiled kernel and XLA, so z
        # values within float noise of 0 may legitimately flip relu sides
        np.testing.assert_allclose(y, np.maximum(z, 0), rtol=1e-5, atol=1e-4)
        stable = np.abs(z) > 1e-4
        np.testing.assert_array_equal(
            (np.asarray(mask) > 0)[stable], (z > 0)[stable]
        )

    def test_tiled_bwd_matches_xla(self):
        x, w = r(self.MB, self.DIN), r(self.DOUT, self.DIN)
        g = r(self.MB, self.DOUT)
        mask = (r(self.MB, self.DOUT) > 0).astype(jnp.float32)
        dx, dw, db = pallas_ops.linear_relu_bwd_tiled(g, mask, x, w, tile=self.TILE)
        dx_r, dw_r, db_r = ops.linear_grad(g * mask, x, w)
        np.testing.assert_allclose(dx, dx_r, rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(dw, dw_r, rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(
            np.asarray(db).reshape(-1), db_r, rtol=1e-4, atol=1e-4
        )

    def test_dispatch_picks_tiled_beyond_budget(self, monkeypatch):
        fits = pallas_ops._fwd_bytes(128, 784, 128) <= pallas_ops.SINGLE_BLOCK_BUDGET_BYTES
        assert fits  # flagship layers stay single-block
        assert pallas_ops._fwd_bytes(4096, 8192, 4096) > pallas_ops.SINGLE_BLOCK_BUDGET_BYTES
        assert pallas_ops._bwd_bytes(4096, 8192, 4096) > pallas_ops.SINGLE_BLOCK_BUDGET_BYTES

        # run the PUBLIC entry points down the tiled branch: budget forced to
        # 0 and unique shapes so jit can't serve a cached single-block trace
        monkeypatch.setattr(pallas_ops, "SINGLE_BLOCK_BUDGET_BYTES", 0)
        monkeypatch.setattr(pallas_ops, "TILE", 128)
        mb, din, dout = 37, 29, 23
        x, w, b = r(mb, din), r(dout, din), r(1, dout)
        y, mask = pallas_ops.linear_relu_fwd(x, w, b)
        z = np.asarray(ops.linear(x, w, b))
        np.testing.assert_allclose(y, np.maximum(z, 0), rtol=1e-5, atol=1e-4)
        g = r(mb, dout)
        dx, dw, db = pallas_ops.linear_relu_bwd(g, mask, x, w)
        dx_r, dw_r, db_r = ops.linear_grad(
            g * jnp.asarray(mask), x, w
        )
        np.testing.assert_allclose(dx, dx_r, rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(dw, dw_r, rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(
            np.asarray(db).reshape(-1), db_r, rtol=1e-4, atol=1e-4
        )


class TestTiledFlagKernels:
    """Grid-tiled flag-operand variants (the executor's oversize-slot path):
    ragged multi-tile grids in every dimension, BOTH flag values, against
    the XLA expression. Tolerances, not bit-equality: a multi-tile
    contraction reassociates the sum vs XLA's full dot."""

    MB, DIN, DOUT, TILE = 300, 260, 200, 128  # 3x3x2 tiles, all ragged

    @pytest.mark.parametrize("flag", [0, 1])
    def test_tiled_flag_fwd_matches_xla(self, flag):
        x, w, b = r(self.MB, self.DIN), r(self.DOUT, self.DIN), r(1, self.DOUT)
        y, mask = pallas_ops.linear_flag_fwd_tiled(
            x, w, b, jnp.int32(flag), tile=self.TILE
        )
        z = np.asarray(ops.linear(x, w, b))
        expect = np.maximum(z, 0) if flag else z
        np.testing.assert_allclose(y, expect, rtol=1e-5, atol=1e-4)
        stable = np.abs(z) > 1e-4  # float noise near 0 may flip the mask
        np.testing.assert_array_equal(
            (np.asarray(mask) > 0)[stable], (z > 0)[stable]
        )

    @pytest.mark.parametrize("flag", [0, 1])
    def test_tiled_flag_bwd_matches_xla(self, flag):
        x, w = r(self.MB, self.DIN), r(self.DOUT, self.DIN)
        g = r(self.MB, self.DOUT)
        mask = (r(self.MB, self.DOUT) > 0).astype(jnp.float32)
        dx, dw, db = pallas_ops.linear_flag_bwd_tiled(
            g, mask, x, w, jnp.int32(flag), tile=self.TILE
        )
        ge = g * mask if flag else g
        dx_r, dw_r, db_r = ops.linear_grad(ge, x, w)
        np.testing.assert_allclose(dx, dx_r, rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(dw, dw_r, rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(
            np.asarray(db).reshape(-1), db_r, rtol=1e-4, atol=1e-4
        )

    def test_flag_dispatch_picks_tiled_beyond_budget(self, monkeypatch):
        """The PUBLIC executor entry points route over-budget shapes to the
        tiled flag kernels (this was a build-time rejection until r04)."""
        monkeypatch.setattr(pallas_ops, "SINGLE_BLOCK_BUDGET_BYTES", 0)
        monkeypatch.setattr(pallas_ops, "TILE", 128)
        mb, din, dout = 37, 29, 23
        x, w, b = r(mb, din), r(dout, din), r(1, dout)
        for flag in (0, 1):
            y, mask = pallas_ops.linear_flag_fwd(x, w, b, jnp.int32(flag))
            z = np.asarray(ops.linear(x, w, b))
            expect = np.maximum(z, 0) if flag else z
            np.testing.assert_allclose(y, expect, rtol=1e-5, atol=1e-4)
            g = r(mb, dout)
            dx, dw, db = pallas_ops.linear_flag_bwd(
                g, jnp.asarray(mask), x, w, jnp.int32(flag)
            )
            ge = g * jnp.asarray(mask) if flag else g
            dx_r, dw_r, db_r = ops.linear_grad(ge, x, w)
            np.testing.assert_allclose(dx, dx_r, rtol=1e-4, atol=1e-4)
            np.testing.assert_allclose(dw, dw_r, rtol=1e-4, atol=1e-4)
            np.testing.assert_allclose(
                np.asarray(db).reshape(-1), db_r, rtol=1e-4, atol=1e-4
            )


class TestModelIntegration:
    def test_training_identical_with_pallas_backend(self):
        SIZES, B, M = (20, 16, 12, 10), 32, 4
        rng = np.random.RandomState(1)
        X = rng.randn(3, M, B // M, SIZES[0]).astype(np.float32)
        Y = np.eye(SIZES[-1], dtype=np.float32)[
            rng.randint(0, SIZES[-1], (3, M, B // M))
        ]
        results = []
        for use_pallas in (False, True):
            ops.set_pallas(use_pallas)
            try:
                spec = Mo.make_model_spec(SIZES, 1, B)
                params = jax.tree.map(jnp.asarray, Mo.init_model(spec))
                step = trainer.make_train_step(spec, SGD(0.01))
                st = ()
                for i in range(3):
                    params, st = step(params, st, jnp.asarray(X[i]), jnp.asarray(Y[i]))
                results.append([l for s in params for l in s])
            finally:
                ops.set_pallas(False)
        for a, b in zip(*results):
            np.testing.assert_allclose(
                np.asarray(a["W"]), np.asarray(b["W"]), rtol=1e-5, atol=1e-7
            )


class TestMegaKernel:
    """The whole-training-step kernel (fused_train_call, step mode): one op per
    batch — forward, grouped-softmax MSE head, backward, SGD update. The
    bar is BIT-identity with the fused XLA path at both precision classes
    (same dots, same grouped stability max, same update expression)."""

    def _epoch_pair(self, sizes, B, M, nb, precision, lr=0.01, wd=0.0):
        rng = np.random.RandomState(2)
        X = jnp.asarray(rng.rand(nb, M, B // M, sizes[0]).astype(np.float32))
        Y = jnp.asarray(
            np.eye(sizes[-1], dtype=np.float32)[
                rng.randint(0, sizes[-1], (nb, M, B // M))
            ]
        )
        spec = Mo.make_model_spec(sizes, 1, B)
        out = {}
        for mk in (False, True):
            params = jax.tree.map(jnp.asarray, Mo.init_model(spec))
            epoch = trainer.make_train_epoch(
                spec, SGD(lr, weight_decay=wd), precision=precision,
                fuse_mubatches=True, megakernel=mk,
            )
            params, _, loss = epoch(params, (), X, Y)
            out[mk] = (jax.device_get(params), float(loss))
        return out

    @pytest.mark.parametrize("precision", [None, jax.lax.Precision.HIGHEST])
    def test_epoch_bit_identical_to_fused_xla(self, precision):
        out = self._epoch_pair((20, 16, 12, 10), 32, 4, 3, precision)
        assert out[False][1] == out[True][1]
        for a, b in zip(out[False][0][0], out[True][0][0]):
            np.testing.assert_array_equal(np.asarray(a["W"]), np.asarray(b["W"]))
            np.testing.assert_array_equal(np.asarray(a["b"]), np.asarray(b["b"]))

    def test_flagship_shape_with_weight_decay(self):
        out = self._epoch_pair(
            (784, 128, 127, 126, 125, 124, 123, 10), 128, 4, 2,
            jax.lax.Precision.HIGHEST, wd=1e-4,
        )
        assert out[False][1] == out[True][1]
        for a, b in zip(out[False][0][0], out[True][0][0]):
            np.testing.assert_array_equal(np.asarray(a["W"]), np.asarray(b["W"]))

    def test_fused_run_megakernel_matches(self):
        """The whole-run program (epochs-outer scan + on-device eval) built
        over the mega-kernel batch body reproduces the XLA run exactly."""
        sizes, B, M = (20, 16, 12, 10), 32, 4
        rng = np.random.RandomState(3)
        X = jnp.asarray(rng.rand(2, M, B // M, sizes[0]).astype(np.float32))
        Y = jnp.asarray(
            np.eye(sizes[-1], dtype=np.float32)[rng.randint(0, sizes[-1], (2, M, B // M))]
        )
        vx = jnp.asarray(rng.rand(16, sizes[0]).astype(np.float32))
        vy = jnp.asarray(np.eye(sizes[-1], dtype=np.float32)[rng.randint(0, sizes[-1], 16)])
        spec = Mo.make_model_spec(sizes, 1, B)
        res = {}
        for mk in (False, True):
            params = jax.tree.map(jnp.asarray, Mo.init_model(spec))
            run = trainer.make_train_run(
                spec, SGD(0.01), fuse_mubatches=True, megakernel=mk
            )
            params, _, losses, accs = run(params, (), X, Y, vx, vy, 3)
            res[mk] = (np.asarray(losses), np.asarray(accs))
        np.testing.assert_array_equal(res[False][0], res[True][0])
        np.testing.assert_array_equal(res[False][1], res[True][1])

    def test_megakernel_guards(self):
        class NotAnOptimizer:
            pass

        spec = Mo.make_model_spec((20, 16, 12, 10), 1, 32)
        with pytest.raises(ValueError, match="fuse_mubatches"):
            trainer.make_train_epoch(spec, SGD(0.01), megakernel=True)
        with pytest.raises(ValueError, match="SGD, momentum and adam"):
            trainer.make_train_epoch(
                spec, NotAnOptimizer(), fuse_mubatches=True, megakernel=True
            )
        spec2 = Mo.make_model_spec((20, 16, 12, 10), 2, 32)
        with pytest.raises(ValueError, match="single-stage"):
            trainer.make_train_epoch(
                spec2, SGD(0.01), fuse_mubatches=True, megakernel=True
            )
        with pytest.raises(ValueError, match="VMEM"):
            huge = Mo.make_model_spec((4096, 4096, 10), 1, 2048)
            trainer.make_train_epoch(
                huge, SGD(0.01), fuse_mubatches=True, megakernel=True
            )


class TestEpochKernel:
    """The whole-EPOCH kernel (fused_train_call, epoch_mode): the batch axis is the
    Pallas grid, params ride the revisited output blocks — one device op per
    epoch. The bar is BIT-identity with the fused XLA epoch (and hence the
    per-batch mega-kernel) at both precision classes."""

    def _epoch_triple(self, sizes, B, M, nb, precision, lr=0.01, wd=0.0):
        rng = np.random.RandomState(2)
        X = jnp.asarray(rng.rand(nb, M, B // M, sizes[0]).astype(np.float32))
        Y = jnp.asarray(
            np.eye(sizes[-1], dtype=np.float32)[
                rng.randint(0, sizes[-1], (nb, M, B // M))
            ]
        )
        spec = Mo.make_model_spec(sizes, 1, B)
        out = {}
        for name, kw in {
            "xla": {},
            "mega": {"megakernel": True},
            "epoch": {"epoch_kernel": True},
        }.items():
            params = jax.tree.map(jnp.asarray, Mo.init_model(spec))
            epoch = trainer.make_train_epoch(
                spec, SGD(lr, weight_decay=wd), precision=precision,
                fuse_mubatches=True, **kw,
            )
            params, _, loss = epoch(params, (), X, Y)
            out[name] = (jax.device_get(params), float(loss))
        return out

    @pytest.mark.parametrize("precision", [None, jax.lax.Precision.HIGHEST])
    def test_epoch_kernel_bit_identical(self, precision):
        out = self._epoch_triple((20, 16, 12, 10), 32, 4, 3, precision)
        for other in ("mega", "epoch"):
            assert out["xla"][1] == out[other][1]
            for a, b in zip(out["xla"][0][0], out[other][0][0]):
                np.testing.assert_array_equal(np.asarray(a["W"]), np.asarray(b["W"]))
                np.testing.assert_array_equal(np.asarray(a["b"]), np.asarray(b["b"]))

    def test_flagship_shape_with_weight_decay(self):
        out = self._epoch_triple(
            (784, 128, 127, 126, 125, 124, 123, 10), 128, 4, 2,
            jax.lax.Precision.HIGHEST, wd=1e-4,
        )
        assert out["xla"][1] == out["epoch"][1]
        for a, b in zip(out["xla"][0][0], out["epoch"][0][0]):
            np.testing.assert_array_equal(np.asarray(a["W"]), np.asarray(b["W"]))

    def test_fused_run_epoch_kernel_matches(self):
        """The whole-run program (epochs-outer scan + on-device eval) built
        over the epoch-kernel core reproduces the XLA run exactly — 20
        epochs become ~20 device ops plus eval."""
        sizes, B, M = (20, 16, 12, 10), 32, 4
        rng = np.random.RandomState(3)
        X = jnp.asarray(rng.rand(2, M, B // M, sizes[0]).astype(np.float32))
        Y = jnp.asarray(
            np.eye(sizes[-1], dtype=np.float32)[rng.randint(0, sizes[-1], (2, M, B // M))]
        )
        vx = jnp.asarray(rng.rand(16, sizes[0]).astype(np.float32))
        vy = jnp.asarray(np.eye(sizes[-1], dtype=np.float32)[rng.randint(0, sizes[-1], 16)])
        spec = Mo.make_model_spec(sizes, 1, B)
        res = {}
        for ek in (False, True):
            params = jax.tree.map(jnp.asarray, Mo.init_model(spec))
            run = trainer.make_train_run(
                spec, SGD(0.01), fuse_mubatches=True, epoch_kernel=ek
            )
            params, _, losses, accs = run(params, (), X, Y, vx, vy, 3)
            res[ek] = (np.asarray(losses), np.asarray(accs))
        np.testing.assert_array_equal(res[False][0], res[True][0])
        np.testing.assert_array_equal(res[False][1], res[True][1])

    def test_epoch_kernel_guards(self):
        spec = Mo.make_model_spec((20, 16, 12, 10), 1, 32)
        with pytest.raises(ValueError, match="fuse_mubatches"):
            trainer.make_train_epoch(spec, SGD(0.01), epoch_kernel=True)
        with pytest.raises(ValueError, match="exclusive"):
            trainer.make_train_epoch(
                spec, SGD(0.01), fuse_mubatches=True, megakernel=True,
                epoch_kernel=True,
            )


class TestMomentumKernels:
    """Heavy-ball variants of the step and epoch kernels: same bar as SGD —
    BIT-identity (params, velocity state, loss) with the fused XLA path
    through optimizer.MomentumSGD."""

    def test_step_and_epoch_momentum_bit_identical(self):
        from shallowspeed_tpu.optimizer import MomentumSGD

        sizes, B, M, nb = (20, 16, 12, 10), 32, 4, 3
        rng = np.random.RandomState(5)
        X = jnp.asarray(rng.rand(nb, M, B // M, sizes[0]).astype(np.float32))
        Y = jnp.asarray(
            np.eye(sizes[-1], dtype=np.float32)[
                rng.randint(0, sizes[-1], (nb, M, B // M))
            ]
        )
        spec = Mo.make_model_spec(sizes, 1, B)
        opt = MomentumSGD(0.01, momentum=0.9, weight_decay=1e-4)
        out = {}
        for name, kw in {
            "xla": {},
            "mega": {"megakernel": True},
            "epoch": {"epoch_kernel": True},
        }.items():
            params = jax.tree.map(jnp.asarray, Mo.init_model(spec))
            st = opt.init(params)
            epoch = trainer.make_train_epoch(
                spec, opt, fuse_mubatches=True, **kw
            )
            # two epochs so a nonzero velocity feeds the second one
            params, st, _ = epoch(params, st, X, Y)
            params, st, loss = epoch(params, st, X, Y)
            out[name] = (jax.device_get(params), jax.device_get(st), float(loss))
        for other in ("mega", "epoch"):
            assert out["xla"][2] == out[other][2]
            for tree_idx in (0, 1):  # params, then velocity state
                for a, b in zip(
                    jax.tree.leaves(out["xla"][tree_idx]),
                    jax.tree.leaves(out[other][tree_idx]),
                ):
                    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_state_mirror_vmem_accounting(self):
        # exact accounting: each state mirror adds EXACTLY in+out copies
        # (2 x params floats) — an undercount would approve configs that
        # OOM VMEM at Mosaic compile time on chip
        sizes = (700, 700, 10)
        params = 700 * 700 + 700 + 700 * 10 + 10
        for n in (1, 2):  # momentum, adam
            assert pallas_ops._kernel_bytes(8, sizes, state_mirrors=n) == (
                pallas_ops._kernel_bytes(8, sizes) + n * 4 * 2 * params
            )
        # boundary: this config fits the SGD budget but NOT the momentum
        # (or adam) budget — the validator must catch the difference
        assert pallas_ops.train_step_kernel_fits(128, sizes)
        assert not pallas_ops.train_step_kernel_fits(128, sizes, state_mirrors=1)
        # the flagship class fits even adam's two mirrors
        assert pallas_ops.train_step_kernel_fits(
            128, (784, 128, 10), state_mirrors=2
        )


class TestAdamKernels:
    """Adam/AdamW variants of the step and epoch kernels: BIT-identity
    (params, both moment mirrors, the step counter, loss) with the fused
    XLA path through optimizer.Adam — the bias-correction powers b**t use
    the same traced-t expression as Adam.apply."""

    def test_step_and_epoch_adam_bit_identical(self):
        from shallowspeed_tpu.optimizer import Adam

        sizes, B, M, nb = (20, 16, 12, 10), 32, 4, 3
        rng = np.random.RandomState(7)
        X = jnp.asarray(rng.rand(nb, M, B // M, sizes[0]).astype(np.float32))
        Y = jnp.asarray(
            np.eye(sizes[-1], dtype=np.float32)[
                rng.randint(0, sizes[-1], (nb, M, B // M))
            ]
        )
        spec = Mo.make_model_spec(sizes, 1, B)
        opt = Adam(2e-4, weight_decay=1e-4)
        out = {}
        for name, kw in {
            "xla": {},
            "mega": {"megakernel": True},
            "epoch": {"epoch_kernel": True},
        }.items():
            params = jax.tree.map(jnp.asarray, Mo.init_model(spec))
            st = opt.init(params)
            epoch = trainer.make_train_epoch(
                spec, opt, fuse_mubatches=True, **kw
            )
            # two epochs so nonzero moments + a mid-range t feed epoch 2
            params, st, _ = epoch(params, st, X, Y)
            params, st, loss = epoch(params, st, X, Y)
            out[name] = (jax.device_get(params), jax.device_get(st), float(loss))
        for other in ("mega", "epoch"):
            assert out["xla"][2] == out[other][2]
            assert float(out["xla"][1]["t"]) == float(out[other][1]["t"]) == 2 * nb
            for tree_idx in (0, 1):  # params, then {m, v, t} state
                for a, b in zip(
                    jax.tree.leaves(out["xla"][tree_idx]),
                    jax.tree.leaves(out[other][tree_idx]),
                ):
                    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

class TestClipKernels:
    """Global-norm clipping INSIDE the mega/epoch kernels (round-4 verdict
    item #4): with a clip tight enough to bind on every batch, the kernel
    variants must stay BIT-identical (params, optimizer state, loss) to the
    fused XLA path, whose clip goes through optimizer.clip_tree. Also checks
    the clip actually changed training (vs the unclipped kernel run)."""

    def _run(self, opt, kw, clip, seed=9, epochs=2):
        sizes, B, M, nb = (20, 16, 12, 10), 32, 4, 3
        rng = np.random.RandomState(seed)
        X = jnp.asarray(rng.rand(nb, M, B // M, sizes[0]).astype(np.float32))
        Y = jnp.asarray(
            np.eye(sizes[-1], dtype=np.float32)[
                rng.randint(0, sizes[-1], (nb, M, B // M))
            ]
        )
        spec = Mo.make_model_spec(sizes, 1, B)
        params = jax.tree.map(jnp.asarray, Mo.init_model(spec))
        st = opt.init(params)
        epoch = trainer.make_train_epoch(
            spec, opt, fuse_mubatches=True, clip_norm=clip, **kw
        )
        loss = None
        for _ in range(epochs):
            params, st, loss = epoch(params, st, X, Y)
        return jax.device_get(params), jax.device_get(st), float(loss)

    @pytest.mark.parametrize(
        "opt",
        [
            SGD(0.01, weight_decay=1e-4),
            MomentumSGD(0.01, 0.9),
            Adam(2e-4),
        ],
        ids=["sgd", "momentum", "adam"],
    )
    def test_clip_bit_identical_across_variants(self, opt):
        CLIP = 0.05  # far below the natural grad norm: binds every batch
        outs = {
            name: self._run(opt, kw, CLIP)
            for name, kw in {
                "xla": {},
                "mega": {"megakernel": True},
                "epoch": {"epoch_kernel": True},
            }.items()
        }
        for other in ("mega", "epoch"):
            assert outs["xla"][2] == outs[other][2]
            for tree_idx in (0, 1):  # params, then optimizer state
                for a, b in zip(
                    jax.tree.leaves(outs["xla"][tree_idx]),
                    jax.tree.leaves(outs[other][tree_idx]),
                ):
                    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # the clip is live: the clipped epoch-kernel run differs from the
        # unclipped one
        unclipped = self._run(opt, {"epoch_kernel": True}, None)
        assert outs["epoch"][2] != unclipped[2]


class TestRunKernel:
    """The whole-RUN kernel (fused_train_call, n_epochs): the grid is
    (epochs, batches), params + optimizer state VMEM-resident for the whole
    run — ONE device op for the entire training run. The bar is BIT-identity
    (params, state, per-epoch losses) with looping the epoch kernel, and
    hence with fused XLA."""

    def _data(self, sizes, B, M, nb, seed=11):
        rng = np.random.RandomState(seed)
        X = jnp.asarray(rng.rand(nb, M, B // M, sizes[0]).astype(np.float32))
        Y = jnp.asarray(
            np.eye(sizes[-1], dtype=np.float32)[
                rng.randint(0, sizes[-1], (nb, M, B // M))
            ]
        )
        return X, Y

    @pytest.mark.parametrize(
        "opt,clip",
        [
            (SGD(0.01, weight_decay=1e-4), None),
            (MomentumSGD(0.01, 0.9), 0.05),
            (Adam(2e-4), None),
        ],
        ids=["sgd", "momentum+clip", "adam"],
    )
    def test_run_kernel_bit_identical_to_epoch_loop(self, opt, clip):
        sizes, B, M, nb, E = (20, 16, 12, 10), 32, 4, 3, 4
        X, Y = self._data(sizes, B, M, nb)
        spec = Mo.make_model_spec(sizes, 1, B)

        params = jax.tree.map(jnp.asarray, Mo.init_model(spec))
        st = opt.init(params)
        epoch = trainer.make_train_epoch(
            spec, opt, fuse_mubatches=True, epoch_kernel=True, clip_norm=clip
        )
        want_losses = []
        for _ in range(E):
            params, st, loss = epoch(params, st, X, Y)
            want_losses.append(float(loss))
        want = (jax.device_get(params), jax.device_get(st))

        params2 = jax.tree.map(jnp.asarray, Mo.init_model(spec))
        st2 = opt.init(params2)
        run = trainer.make_train_run(
            spec, opt, fuse_mubatches=True, run_kernel=True, with_eval=False,
            clip_norm=clip,
        )
        params2, st2, losses = run(params2, st2, X, Y, E)
        got = (jax.device_get(params2), jax.device_get(st2))

        np.testing.assert_array_equal(
            np.asarray(losses), np.asarray(want_losses, np.float32)
        )
        for tree_idx in (0, 1):
            for a, b in zip(
                jax.tree.leaves(want[tree_idx]), jax.tree.leaves(got[tree_idx])
            ):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_run_kernel_matches_fused_xla_run(self):
        """End of the ladder meets the start: the one-op run reproduces the
        fused-XLA whole-run program's losses exactly."""
        sizes, B, M, nb, E = (20, 16, 12, 10), 32, 4, 2, 3
        X, Y = self._data(sizes, B, M, nb, seed=13)
        spec = Mo.make_model_spec(sizes, 1, B)
        out = {}
        for name, kw in {
            "xla": {},
            "run": {"run_kernel": True},
        }.items():
            params = jax.tree.map(jnp.asarray, Mo.init_model(spec))
            run = trainer.make_train_run(
                spec, SGD(0.01), fuse_mubatches=True, with_eval=False, **kw
            )
            params, _, losses = run(params, (), X, Y, E)
            out[name] = (jax.device_get(params), np.asarray(losses))
        np.testing.assert_array_equal(out["xla"][1], out["run"][1])
        for a, b in zip(
            jax.tree.leaves(out["xla"][0]), jax.tree.leaves(out["run"][0])
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_run_kernel_guards(self):
        spec = Mo.make_model_spec((20, 16, 12, 10), 1, 32)
        with pytest.raises(ValueError, match="with_eval"):
            trainer.make_train_run(
                spec, SGD(0.01), fuse_mubatches=True, run_kernel=True
            )
        with pytest.raises(ValueError, match="subsumes"):
            trainer.make_train_run(
                spec, SGD(0.01), fuse_mubatches=True, run_kernel=True,
                epoch_kernel=True, with_eval=False,
            )
        with pytest.raises(ValueError, match="epoch_mode"):
            pallas_ops.fused_train_call(
                [{"W": jnp.zeros((4, 4)), "b": jnp.zeros(4)}],
                jnp.zeros((8, 4)), jnp.zeros((8, 4)),
                epoch_mode=False, relu_flags=(False,), group_rows=8,
                batch_size=8, lr=0.1, weight_decay=0.0, precision=None,
                n_epochs=2,
            )

    def test_run_kernel_rejects_zero_epochs(self):
        spec = Mo.make_model_spec((20, 16, 12, 10), 1, 32)
        X, Y = self._data((20, 16, 12, 10), 32, 4, 2)
        run = trainer.make_train_run(
            spec, SGD(0.01), fuse_mubatches=True, run_kernel=True,
            with_eval=False,
        )
        params = jax.tree.map(jnp.asarray, Mo.init_model(spec))
        with pytest.raises(ValueError, match="n_epochs >= 1"):
            run(params, (), X, Y, 0)
