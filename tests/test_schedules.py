"""Schedule-stream tests: structural properties + happens-before predicates.

Covers what the reference's tests/test_schedules.py covers, plus the
happens-before checks its header TODO wished for (reference
tests/test_schedules.py:4-10) — e.g. GPipe: last forward strictly before
first backward; PipeDream-Flush: at most min(M, depth-stage) forwards in
flight.
"""

import pytest

from shallowspeed_tpu import schedules as S


def flat(sched):
    return S.flat_commands(sched)


def types(cmds):
    return [type(c) for c in cmds]


ALL_TRAIN = [S.NaiveParallelSchedule, S.GPipeSchedule, S.PipeDreamFlushSchedule]


@pytest.mark.parametrize("cls", ALL_TRAIN)
@pytest.mark.parametrize("stages,stage", [(1, 0), (4, 0), (4, 2), (4, 3)])
def test_batch_bracketing(cls, stages, stage):
    cmds = flat(cls(num_micro_batches=4, num_stages=stages, stage_id=stage))
    assert isinstance(cmds[0], S.ZeroGrad)
    assert isinstance(cmds[-1], S.OptimizerStep)
    assert sum(isinstance(c, S.ZeroGrad) for c in cmds) == 1
    assert sum(isinstance(c, S.OptimizerStep) for c in cmds) == 1


@pytest.mark.parametrize("cls", ALL_TRAIN)
@pytest.mark.parametrize("stages,stage", [(1, 0), (4, 0), (4, 1), (4, 3)])
def test_every_mubatch_forward_and_backward_once(cls, stages, stage):
    M = 4
    cmds = flat(cls(num_micro_batches=M, num_stages=stages, stage_id=stage))
    fwd = [c.mubatch_id for c in cmds if isinstance(c, S.Forward)]
    bwd = [
        c.mubatch_id
        for c in cmds
        if isinstance(c, (S.BackwardGradAcc, S.BackwardGradAllReduce))
    ]
    assert sorted(fwd) == list(range(M))
    assert sorted(bwd) == list(range(M))


@pytest.mark.parametrize("cls", ALL_TRAIN)
def test_allreduce_exactly_once_and_on_final_backward(cls):
    """BackwardGradAllReduce marks the LAST executed backward of the batch —
    that is where the DP psum is anchored (reference pipe.py:108-122)."""
    cmds = flat(cls(num_micro_batches=4, num_stages=4, stage_id=1))
    ar = [i for i, c in enumerate(cmds) if isinstance(c, S.BackwardGradAllReduce)]
    bwd = [
        i
        for i, c in enumerate(cmds)
        if isinstance(c, (S.BackwardGradAcc, S.BackwardGradAllReduce))
    ]
    assert len(ar) == 1
    assert ar[0] == bwd[-1]


@pytest.mark.parametrize("cls", ALL_TRAIN)
@pytest.mark.parametrize("stage", [0, 1, 3])
def test_io_roles_by_stage(cls, stage):
    cmds = flat(cls(num_micro_batches=4, num_stages=4, stage_id=stage))
    has = lambda t: any(isinstance(c, t) for c in cmds)
    assert has(S.LoadMuBatchInput) == (stage == 0)
    assert has(S.RecvActivations) == (stage != 0)
    assert has(S.LoadMuBatchTarget) == (stage == 3)
    assert has(S.RecvOutputGrad) == (stage != 3)
    assert has(S.SendActivations) == (stage != 3)
    assert has(S.SendInputGrad) == (stage != 0)


def _pos(cmds, pred):
    return [i for i, c in enumerate(cmds) if pred(c)]


def test_gpipe_happens_before_all_fwd_before_any_bwd():
    for stage in range(4):
        cmds = flat(S.GPipeSchedule(num_micro_batches=4, num_stages=4, stage_id=stage))
        last_fwd = max(_pos(cmds, lambda c: isinstance(c, S.Forward)))
        first_bwd = min(
            _pos(cmds, lambda c: isinstance(c, (S.BackwardGradAcc, S.BackwardGradAllReduce)))
        )
        assert last_fwd < first_bwd


def test_gpipe_backward_order_reversed():
    cmds = flat(S.GPipeSchedule(num_micro_batches=4, num_stages=2, stage_id=1))
    bwd = [
        c.mubatch_id
        for c in cmds
        if isinstance(c, (S.BackwardGradAcc, S.BackwardGradAllReduce))
    ]
    assert bwd == [3, 2, 1, 0]


def test_naive_one_mubatch_fully_before_next():
    cmds = flat(S.NaiveParallelSchedule(num_micro_batches=3, num_stages=2, stage_id=0))
    events = [
        (c.mubatch_id, isinstance(c, S.Forward))
        for c in cmds
        if isinstance(c, (S.Forward, S.BackwardGradAcc, S.BackwardGradAllReduce))
    ]
    assert events == [(0, True), (0, False), (1, True), (1, False), (2, True), (2, False)]


class TestPipeDreamFlush:
    def test_backward_order_is_fifo(self):
        cmds = flat(S.PipeDreamFlushSchedule(num_micro_batches=4, num_stages=4, stage_id=1))
        bwd = [
            c.mubatch_id
            for c in cmds
            if isinstance(c, (S.BackwardGradAcc, S.BackwardGradAllReduce))
        ]
        assert bwd == [0, 1, 2, 3]

    @pytest.mark.parametrize("stage", range(4))
    def test_activation_memory_bound(self, stage):
        """In-flight forwards (fwd done, bwd not yet) never exceed
        min(M, depth - stage) — the 1F1B memory property."""
        M, depth = 8, 4
        cmds = flat(
            S.PipeDreamFlushSchedule(num_micro_batches=M, num_stages=depth, stage_id=stage)
        )
        in_flight = peak = 0
        for c in cmds:
            if isinstance(c, S.Forward):
                in_flight += 1
            elif isinstance(c, (S.BackwardGradAcc, S.BackwardGradAllReduce)):
                in_flight -= 1
            peak = max(peak, in_flight)
        assert peak <= min(M, depth - stage)

    def test_last_stage_strictly_alternates(self):
        cmds = flat(S.PipeDreamFlushSchedule(num_micro_batches=4, num_stages=4, stage_id=3))
        compute = [
            isinstance(c, S.Forward)
            for c in cmds
            if isinstance(c, (S.Forward, S.BackwardGradAcc, S.BackwardGradAllReduce))
        ]
        assert compute == [True, False] * 4


class TestBackwardSplitStreams:
    """Structural properties of the split-backward instruction streams
    (the lowering's own verifier re-checks these; here they are pinned as
    pure-data schedule properties, like everything else in this file)."""

    @pytest.mark.parametrize("cls", ALL_TRAIN)
    @pytest.mark.parametrize("stage", [0, 1, 3])
    def test_split_emits_one_pair_per_mubatch(self, cls, stage):
        M = 4
        cmds = flat(cls(num_micro_batches=M, num_stages=4, stage_id=stage,
                        backward_split=True))
        bins = [c.mubatch_id for c in cmds if isinstance(c, S.BackwardInputGradAcc)]
        bwws = [c.mubatch_id for c in cmds if isinstance(c, S.BackwardWeightGradAcc)]
        assert sorted(bins) == list(range(M))
        assert sorted(bwws) == list(range(M))
        # no combined backwards anywhere in a split stream
        assert not any(
            isinstance(c, (S.BackwardGradAcc, S.BackwardGradAllReduce)) for c in cmds
        )

    @pytest.mark.parametrize("cls", ALL_TRAIN)
    def test_split_bweight_order_matches_binput_order(self, cls):
        """The weight-grad accumulation-order contract, at the stream level."""
        cmds = flat(cls(num_micro_batches=4, num_stages=4, stage_id=1,
                        backward_split=True))
        bins = [c.mubatch_id for c in cmds if isinstance(c, S.BackwardInputGradAcc)]
        bwws = [c.mubatch_id for c in cmds if isinstance(c, S.BackwardWeightGradAcc)]
        assert bwws == bins

    @pytest.mark.parametrize("cls", ALL_TRAIN)
    def test_split_sends_ride_the_binput(self, cls):
        """SendInputGrad directly follows a B-input (the dx producer),
        never a B-weight — the relay stays on the combined backward's
        critical path."""
        cmds = flat(cls(num_micro_batches=4, num_stages=4, stage_id=2,
                        backward_split=True))
        for i, c in enumerate(cmds):
            if isinstance(c, S.SendInputGrad):
                assert isinstance(cmds[i - 1], S.BackwardInputGradAcc)

    @pytest.mark.parametrize("cls", ALL_TRAIN)
    def test_split_anchor_is_final_bweight(self, cls):
        cmds = flat(cls(num_micro_batches=4, num_stages=4, stage_id=1,
                        backward_split=True))
        ar = [i for i, c in enumerate(cmds)
              if isinstance(c, S.BackwardWeightGradAllReduce)]
        bww = [i for i, c in enumerate(cmds)
               if isinstance(c, S.BackwardWeightGradAcc)]
        assert len(ar) == 1
        assert ar[0] == bww[-1]


def test_inference_forward_only():
    for stage in range(3):
        cmds = flat(S.InferenceSchedule(num_micro_batches=2, num_stages=3, stage_id=stage))
        assert not any(
            isinstance(c, (S.BackwardGradAcc, S.BackwardGradAllReduce, S.ZeroGrad, S.OptimizerStep))
            for c in cmds
        )
        assert sum(isinstance(c, S.Forward) for c in cmds) == 2
