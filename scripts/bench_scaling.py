"""Scaling benchmark: the five BASELINE.md configs, samples/sec + efficiency.

Measures MNIST-MLP training throughput for:
    seq          sequential (1 device)
    dp4          DP=4
    pp4-naive    PP=4, naive schedule
    pp4-gpipe    PP=4, GPipe
    dp2pp4-gpipe DP=2 x PP=4 (8 devices)

and reports samples/sec plus scaling efficiency vs the sequential run
(efficiency = throughput / (n_devices * seq_throughput)). Emits one JSON line
per config. Configs needing more devices than available are skipped with a
note (a single-chip host runs only `seq`; use the 8-virtual-device CPU mesh
to exercise the rest:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 ...).

NOTE on interpretation: pipeline parallelism on this tiny MLP exists to
demonstrate/validate the machinery (the reference is an educational
framework); per-device efficiency is expected to be <1 because the model is
far too small to fill a pipeline — the numbers quantify schedule overhead
(naive vs GPipe vs 1F1B bubbles), which is exactly what the reference's
pebble diagrams illustrate.
"""

import argparse
import json
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from shallowspeed_tpu.api import (  # the reference's canonical config
    FLAGSHIP_BATCH as B,
    FLAGSHIP_LR as LR,
    FLAGSHIP_MUBATCHES as M,
    FLAGSHIP_SIZES as SIZES,
)


def _data(nb, rng):
    X = rng.rand(nb, B, SIZES[0]).astype(np.float32)
    Y = np.eye(SIZES[-1], dtype=np.float32)[rng.randint(0, SIZES[-1], (nb, B))]
    return X, Y


def bench_sequential(nb, reps):
    import jax
    import jax.numpy as jnp

    from shallowspeed_tpu import model as Mo
    from shallowspeed_tpu import trainer
    from shallowspeed_tpu.optimizer import SGD

    spec = Mo.make_model_spec(SIZES, 1, B)
    params = jax.tree.map(jnp.asarray, Mo.init_model(spec))
    epoch = trainer.make_train_epoch(spec, SGD(LR))
    X, Y = _data(nb, np.random.RandomState(0))
    Xe = jnp.asarray(X.reshape(nb, M, B // M, -1))
    Ye = jnp.asarray(Y.reshape(nb, M, B // M, -1))
    st = ()
    params, st, _ = epoch(params, st, Xe, Ye)
    jax.block_until_ready(params)
    t0 = time.perf_counter()
    for _ in range(reps):
        params, st, _ = epoch(params, st, Xe, Ye)
    jax.block_until_ready(params)
    return reps * nb * B / (time.perf_counter() - t0)


def bench_pipeline(dp, pp, sched_name, nb, reps, virtual=1):
    import jax
    import jax.numpy as jnp

    from shallowspeed_tpu import model as Mo
    from shallowspeed_tpu import schedules as S
    from shallowspeed_tpu.optimizer import SGD
    from shallowspeed_tpu.parallel import executor as E
    from shallowspeed_tpu.parallel import lower_schedule, make_mesh

    mesh = make_mesh(dp, pp)
    spec = Mo.make_model_spec(SIZES, pp * virtual, B)
    order = E.interleave_order(pp * virtual, pp) if virtual > 1 else None
    prog = lower_schedule(S.SCHEDULES[sched_name], M, pp, virtual=virtual)
    stacked, flags = E.init_stacked(spec, mesh, order=order)
    epoch = E.make_pipeline_epoch(mesh, spec, prog, B // dp // M, SGD(LR))
    X, Y = _data(nb, np.random.RandomState(0))
    Xj, Yj = jnp.asarray(X), jnp.asarray(Y)
    stacked, st, _ = epoch(stacked, flags, (), Xj, Yj)
    jax.block_until_ready(stacked["W"])
    t0 = time.perf_counter()
    for _ in range(reps):
        stacked, st, _ = epoch(stacked, flags, st, Xj, Yj)
    jax.block_until_ready(stacked["W"])
    return reps * nb * B / (time.perf_counter() - t0)


CONFIGS = [
    # the five BASELINE.md configs...  (name, dp, pp, schedule, virtual)
    ("seq", 1, 1, None, 1),
    ("dp4", 4, 1, "gpipe", 1),
    ("pp4-naive", 1, 4, "naive", 1),
    ("pp4-gpipe", 1, 4, "gpipe", 1),
    ("dp2pp4-gpipe", 2, 4, "gpipe", 1),
    # ...plus the schedules the reference never implemented
    ("pp4-pipedream", 1, 4, "pipedream", 1),
    ("pp4v2-interleaved", 1, 4, "interleaved", 2),
]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batches", type=int, default=64, help="batches per rep")
    ap.add_argument("--reps", type=int, default=3)
    args = ap.parse_args()

    import jax

    n_dev = len(jax.devices())
    results = {}
    for name, dp, pp, sched, virtual in CONFIGS:
        need = dp * pp
        if need > n_dev:
            print(json.dumps({"config": name, "skipped": f"needs {need} devices, have {n_dev}"}))
            continue
        if name == "seq":
            sps = bench_sequential(args.batches, args.reps)
        else:
            sps = bench_pipeline(dp, pp, sched, args.batches, args.reps, virtual)
        results[name] = sps
        eff = (
            sps / (need * results["seq"])
            if "seq" in results and name != "seq"
            else 1.0
        )
        print(
            json.dumps(
                {
                    "config": name,
                    "devices": need,
                    "samples_per_sec": round(sps, 1),
                    "efficiency_vs_seq": round(eff, 4),
                }
            )
        )


if __name__ == "__main__":
    main()
