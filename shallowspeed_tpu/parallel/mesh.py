"""Device-mesh construction: the TPU replacement for the reference's two MPI
communicators (train.py:87-94 — dp_comm = Split(rank % PP), pp_comm =
Split(rank // PP)).

A 2-D ``jax.sharding.Mesh`` with axes ``('dp', 'pp')`` expresses the same
grid: rows are model replicas (the pp_comm groups), columns are same-stage
ranks across replicas (the dp_comm groups). Collectives over axis 'dp' =
Iallreduce over dp_comm; ppermute over axis 'pp' = the stage-relay Send/Recv
pairs. On a real slice the mesh rides ICI; on CPU tests it rides the
host-emulated devices from --xla_force_host_platform_device_count.
"""

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(dp: int, pp: int, devices=None) -> Mesh:
    if devices is None:
        devices = jax.devices()
    if dp * pp > len(devices):
        raise ValueError(
            f"need {dp * pp} devices for DP={dp} x PP={pp}, have {len(devices)}"
        )
    grid = np.asarray(devices[: dp * pp]).reshape(dp, pp)
    return Mesh(grid, ("dp", "pp"))
