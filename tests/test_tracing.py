"""Distributed request tracing tests: the span Tracer, the chain reader
(assembly, clock alignment, completeness refusal), phase attribution and
waterfalls, the dispatch-overhead probe — and the fleet-level legs:
handshake-aligned joins against an artificially skewed worker clock, and
the SIGKILL failover chain (docs/observability.md § Tracing).

Multi-process tests carry the ``fleet`` marker and skip-with-reason when
the platform cannot spawn worker processes (the test_fleet convention).
"""

import gzip
import json
import os
import time

import numpy as np
import pytest

from shallowspeed_tpu.observability import JsonlMetrics, read_jsonl, tracing
from shallowspeed_tpu.observability.metrics import SCHEMA_VERSION
from shallowspeed_tpu.serving import fleet as fleet_mod
from shallowspeed_tpu.serving import loadgen
from shallowspeed_tpu.serving.fleet import ServingFleet

SIZES = (24, 20, 18, 16, 14, 12, 11, 10)
GBS = 64


@pytest.fixture()
def data_dir(tmp_path):
    rng = np.random.RandomState(0)
    for suffix, n in (("train", 256), ("val", 96)):
        x = rng.randn(n, SIZES[0]).astype(np.float32)
        y = np.eye(SIZES[-1], dtype=np.float32)[rng.randint(0, SIZES[-1], n)]
        np.save(tmp_path / f"x_{suffix}.npy", x)
        np.save(tmp_path / f"y_{suffix}.npy", y)
    return tmp_path


# ---------------------------------------------------------------------------
# Tracer + reader units (no processes, no jax programs)
# ---------------------------------------------------------------------------


def test_tracer_emits_linked_closed_spans(tmp_path):
    path = tmp_path / "t.jsonl"
    with JsonlMetrics(path) as m:
        tr = tracing.Tracer(m, process="f")
        tid = tr.new_trace(7)
        assert tid == "f-7"
        root = tr.span("fleet.queue", tid, 1.0, 1.2, parent=None)
        route = tr.span("route", tid, 1.2, 1.21, parent=root, to_replica=0)
        ack = tr.span(
            "ack", tid, 1.5, 1.5, parent=route, terminal=True, verdict="ok"
        )
        assert root and route and ack and len({root, route, ack}) == 3
    recs = read_jsonl(path)
    spans = [r for r in recs if r["kind"] == "trace"]
    assert [s["name"] for s in spans] == ["fleet.queue", "route", "ack"]
    assert spans[1]["parent_id"] == root and spans[2]["terminal"] is True
    assert all(r["v"] == SCHEMA_VERSION for r in spans)


def test_tracer_disabled_costs_nothing():
    from shallowspeed_tpu.observability import NullMetrics

    tr = tracing.Tracer(NullMetrics(), process="e")
    assert tr.enabled is False
    assert tr.span("dispatch", "e-1", 0.0, 1.0) is None
    tr.clock_offset(0, 1.0, 0.001, 0.0005)  # no-op, no raise


def _span(name, trace_id, span_id, t0, t1, parent=None, clock="parent",
          replica_id=None, terminal=False, **fields):
    return {
        "v": SCHEMA_VERSION, "ts": 0.0, "kind": "trace", "name": name,
        "trace_id": trace_id, "span_id": span_id, "parent_id": parent,
        "t0": t0, "t1": t1, "clock": clock, "replica_id": replica_id,
        "terminal": terminal, **fields,
    }


def _offset(replica_id, offset_s, uncertainty_s=0.0001):
    return {
        "v": SCHEMA_VERSION, "ts": 0.0, "kind": "trace",
        "name": "clock_offset", "trace_id": None, "span_id": None,
        "parent_id": None, "t0": None, "t1": None, "clock": "parent",
        "replica_id": replica_id, "terminal": False,
        "offset_s": offset_s, "rtt_s": 2 * uncertainty_s,
        "uncertainty_s": uncertainty_s,
    }


def _request(trace_id, verdict="ok"):
    return {
        "v": SCHEMA_VERSION, "ts": 0.0, "kind": "request", "name": verdict,
        "id": 0, "trace_id": trace_id,
    }


def test_reader_aligns_worker_clock_exactly():
    """Worker spans shifted by a known offset land back on the parent
    timeline once the clock_offset record is applied — cross-process
    durations (including the pipe-hop gaps) reconstruct exactly."""
    off = 5.0  # worker clock runs 5 s ahead of the parent's
    recs = [
        _offset(0, off),
        _span("fleet.queue", "f-0", "f.1", 10.00, 10.01),
        _span("route", "f-0", "f.2", 10.01, 10.012, parent="f.1"),
        _span("worker.queue", "f-0", "r0.1", 10.02 + off, 10.05 + off,
              parent="f.2", clock="worker", replica_id=0),
        _span("dispatch", "f-0", "r0.2", 10.05 + off, 10.09 + off,
              parent="r0.1", clock="worker", replica_id=0),
        _span("ack", "f-0", "f.3", 10.10, 10.10, parent="r0.2",
              terminal=True, verdict="ok"),
        _request("f-0"),
    ]
    chains = tracing.assemble_chains(recs)
    chain = chains["f-0"]
    assert chain.alignment == "aligned"
    assert tracing.verify_terminal_chains(recs, chains) == []
    wq = next(s for s in chain.spans if s["name"] == "worker.queue")
    assert wq["t0_aligned"] == pytest.approx(10.02)
    phases = tracing.chain_phases(chain)
    assert phases["worker.queue"] == pytest.approx(0.03)
    assert phases["dispatch"] == pytest.approx(0.04)
    # the forward pipe hop (route end -> worker admission) charges to
    # route; the return hop (dispatch end -> ack) charges to ack
    assert phases["route"] == pytest.approx(0.002 + 0.008)
    assert phases["ack"] == pytest.approx(0.01)
    # phases cover the whole latency, exactly
    assert sum(phases.values()) == pytest.approx(chain.latency_s)
    assert chain.latency_s == pytest.approx(0.10)


def test_reader_flags_missing_alignment_as_degraded():
    """Worker spans with NO recorded offset are never silently joined:
    the chain is flagged, and completeness still holds (alignment
    quality and causal completeness are separate verdicts)."""
    recs = [
        _span("fleet.queue", "f-1", "f.1", 0.0, 0.1),
        _span("worker.queue", "f-1", "r3.1", 100.0, 100.2, parent="f.1",
              clock="worker", replica_id=3),
        _span("ack", "f-1", "f.2", 0.3, 0.3, parent="r3.1", terminal=True,
              verdict="ok"),
        _request("f-1"),
    ]
    chains = tracing.assemble_chains(recs)
    assert chains["f-1"].alignment == "missing"
    assert tracing.verify_terminal_chains(recs, chains) == []


def test_reader_refuses_orphan_and_unclosed_chains():
    """The completeness gate: a terminal request whose chain has an
    orphan span (parent id absent), an unclosed span, or no chain at all
    is REFUSED with the trace named — strict mode raises TraceError."""
    recs = [
        # orphan: parent f.99 never emitted
        _span("route", "t-a", "f.1", 0.0, 0.1, parent="f.99"),
        _span("ack", "t-a", "f.2", 0.2, 0.2, parent="f.1", terminal=True),
        _request("t-a"),
        # unclosed: t1 missing
        _span("dispatch", "t-b", "f.3", 0.0, None),
        _span("ack", "t-b", "f.4", 0.2, 0.2, parent="f.3", terminal=True),
        _request("t-b"),
        # no terminal span
        _span("fleet.queue", "t-c", "f.5", 0.0, 0.1),
        _request("t-c"),
        # no chain at all
        _request("t-d"),
        # and one healthy chain
        _span("ack", "t-e", "f.6", 0.0, 0.0, terminal=True, verdict="ok"),
        _request("t-e"),
    ]
    problems = tracing.verify_terminal_chains(recs)
    text = "\n".join(problems)
    assert "t-a" in text and "orphan" in text
    assert "t-b" in text and "unclosed" in text
    assert "t-c" in text and "no terminal" in text
    assert "t-d" in text and "no span chain" in text
    assert "t-e" not in text
    with pytest.raises(tracing.TraceError, match="t-a"):
        tracing.verify_terminal_chains(recs, strict=True)


def test_attribution_p99_conditional_and_slo_burn():
    """The makespan-quantization scoreboard: many fast queue-dominated
    chains plus one slow dispatch-dominated outlier — the MEAN
    attribution and the P99-CONDITIONAL attribution must disagree, the
    tail naming dispatch as dominant. SLO burn scores phase seconds
    against the deadline budget."""
    recs = []
    for i in range(50):
        t0 = float(i)
        recs += [
            _span("worker.queue", f"e-{i}", f"e.{3 * i + 1}", t0, t0 + 0.008),
            _span("dispatch", f"e-{i}", f"e.{3 * i + 2}", t0 + 0.008,
                  t0 + 0.010, parent=f"e.{3 * i + 1}"),
            _span("ack", f"e-{i}", f"e.{3 * i + 3}", t0 + 0.010, t0 + 0.010,
                  parent=f"e.{3 * i + 2}", terminal=True, verdict="ok",
                  deadline_ms=100.0),
            _request(f"e-{i}"),
        ]
    # the outlier: 1 s of dispatch
    recs += [
        _span("worker.queue", "e-x", "e.900", 90.0, 90.01),
        _span("dispatch", "e-x", "e.901", 90.01, 91.01, parent="e.900"),
        _span("ack", "e-x", "e.902", 91.01, 91.01, parent="e.901",
              terminal=True, verdict="ok", deadline_ms=100.0),
        _request("e-x"),
    ]
    chains = tracing.assemble_chains(recs)
    att = tracing.attribution(chains, worst_k=2)
    assert att["chains"] == 51
    # mean is time-weighted; the tail is dispatch
    assert att["p99_dominant_phase"] == "dispatch"
    assert att["phases_p99"]["dispatch"] > 0.95
    # queue dominates the typical request but not the tail
    assert att["phases_mean"]["worker.queue"] < 0.5
    assert att["slo_chains"] == 51
    assert att["slo_burn"]["dispatch"] > 0.0
    # worst-k is the outlier first; its waterfall renders bars + times
    worst = att["worst"]
    assert worst[0].trace_id == "e-x"
    lines = tracing.waterfall(worst[0])
    assert "e-x" in lines[0] and "ok" in lines[0]
    assert any("dispatch" in ln and "█" in ln for ln in lines[1:])


def test_engine_chains_complete_for_every_terminal_verdict(data_dir, tmp_path):
    """Standalone engine end to end: ok, expired and dropped requests all
    leave complete chains (trace_id stamped on their request records),
    attribution phases sum exactly to each chain's latency, and the
    report CLI renders the Tracing section from the same file."""
    from shallowspeed_tpu.api import TrainingSession
    from shallowspeed_tpu.observability.report import build_report, render
    from shallowspeed_tpu.serving.engine import ServingEngine

    path = tmp_path / "serve.jsonl"
    m = JsonlMetrics(path)
    session = TrainingSession(
        sizes=SIZES, global_batch_size=GBS, lr=0.01, data_dir=data_dir,
        metrics=m, predict_slot_ladder=(1, 2),
    )
    engine = ServingEngine(session, metrics=m, slo_ms=5000, max_queue=4)
    engine.warm_ladder()
    rng = np.random.RandomState(1)
    for _ in range(4):
        engine.submit(rng.randn(2, SIZES[0]).astype(np.float32))
    # bounded admission: the 5th is dropped (terminal at submit)
    dropped = engine.submit(rng.randn(1, SIZES[0]).astype(np.float32))
    assert dropped.verdict == "dropped"
    engine.drain()
    # an already-expired deadline is shed at pack time
    engine.submit(
        rng.randn(1, SIZES[0]).astype(np.float32), deadline_ms=0.0001
    )
    time.sleep(0.005)
    engine.drain()
    m.close()
    recs = read_jsonl(path)
    chains = tracing.assemble_chains(recs)
    assert tracing.verify_terminal_chains(recs, chains) == []
    verdicts = {c.verdict for c in chains.values()}
    assert verdicts == {"ok", "dropped", "expired"}
    for c in chains.values():
        phases = tracing.chain_phases(c)
        assert sum(phases.values()) == pytest.approx(c.latency_s)
    # every terminal request record carries the join key
    reqs = [r for r in recs if r["kind"] == "request"]
    assert reqs and all(r.get("trace_id") in chains for r in reqs)
    report = build_report(recs, source="serve.jsonl", slo_ms=5000)
    assert report["tracing"]["problems"] == []
    text = render(report, "md")
    assert "## Tracing" in text
    assert "phase attribution (mean)" in text
    assert "slowest requests:" in text


def test_engine_failed_dispatch_exhaustion_chain(data_dir, tmp_path):
    """A permanently-failing dispatch: the retry budget exhausts, the
    request terminates as "error", and its chain is still complete —
    nothing ever vanishes from the trace either."""
    from shallowspeed_tpu.api import TrainingSession
    from shallowspeed_tpu.serving.engine import ServingEngine

    path = tmp_path / "err.jsonl"
    m = JsonlMetrics(path)
    session = TrainingSession(
        sizes=SIZES, global_batch_size=GBS, lr=0.01, data_dir=data_dir,
        metrics=m, predict_slot_ladder=(1, 2),
    )
    engine = ServingEngine(session, metrics=m, retry=2)
    engine.warm_ladder()

    def boom(x):
        raise RuntimeError("injected dispatch failure")

    session.predict = boom
    engine.submit(np.zeros((1, SIZES[0]), np.float32))
    done = engine.drain()
    assert [r.verdict for r in done] == ["error"]
    m.close()
    recs = read_jsonl(path)
    chains = tracing.assemble_chains(recs)
    assert tracing.verify_terminal_chains(recs, chains) == []
    (chain,) = chains.values()
    assert chain.verdict == "error"
    assert [s["name"] for s in chain.spans] == ["worker.queue", "ack"]


# ---------------------------------------------------------------------------
# dispatch-overhead probe (trace_stats + session)
# ---------------------------------------------------------------------------


def _write_trace(path, events):
    with gzip.open(path, "wt") as f:
        json.dump({"traceEvents": events}, f)


def test_dispatch_busy_host_executor_fallback(tmp_path):
    """The CPU backend emits no /device: pid — dispatch_busy falls back
    to the HLO thunk events on the tf_XLA* executor threads, takes the
    interval UNION (parallel workers must not exceed wall), and excludes
    runtime plumbing (C++ ``::`` internals incl. the ThunkExecutor WAIT,
    python ``$`` frames, ParseArguments)."""
    p = tmp_path / "cpu.trace.json.gz"
    _write_trace(p, [
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "/host:CPU"}},
        {"ph": "M", "pid": 1, "tid": 2, "name": "thread_name",
         "args": {"name": "tf_XLAEigen/12345"}},
        {"ph": "M", "pid": 1, "tid": 3, "name": "thread_name",
         "args": {"name": "tf_XLATfrtCpuClient/999"}},
        {"ph": "M", "pid": 1, "tid": 4, "name": "thread_name",
         "args": {"name": "python-main"}},
        # two overlapping thunks on parallel workers: union is 15us
        {"ph": "X", "pid": 1, "tid": 2, "name": "dot.14", "ts": 0, "dur": 10},
        {"ph": "X", "pid": 1, "tid": 3, "name": "fusion.1.clone", "ts": 5,
         "dur": 10},
        # a comm thunk, disjoint: union grows to 20us, comm 5us
        {"ph": "X", "pid": 1, "tid": 3, "name": "all-reduce.2", "ts": 30,
         "dur": 5},
        # excluded plumbing
        {"ph": "X", "pid": 1, "tid": 3,
         "name": "ThunkExecutor::Execute (wait for completion)", "ts": 0,
         "dur": 1000},
        {"ph": "X", "pid": 1, "tid": 3, "name": "ParseArguments", "ts": 0,
         "dur": 50},
        {"ph": "X", "pid": 1, "tid": 2,
         "name": "ThreadpoolListener::Record", "ts": 0, "dur": 40},
        {"ph": "X", "pid": 1, "tid": 4, "name": "$profiler.py:226 trace",
         "ts": 0, "dur": 99999},
    ])
    from shallowspeed_tpu.observability import trace_stats

    busy = trace_stats.dispatch_busy(p)
    assert busy["source"] == "host-executor"
    assert busy["op_events"] == 3
    assert busy["busy_union_s"] == pytest.approx(20e-6)
    assert busy["comm_union_s"] == pytest.approx(5e-6)
    assert busy["compute_union_s"] == pytest.approx(15e-6)
    # the share: 20us busy of 100us wall -> 80% dispatch overhead
    share = trace_stats.dispatch_overhead_share(busy["busy_union_s"], 100e-6)
    assert share == pytest.approx(0.8)
    # unmeasurable sides stay None, never a fabricated perfect 0
    assert trace_stats.dispatch_overhead_share(None, 1.0) is None
    assert trace_stats.dispatch_overhead_share(1.0, None) is None
    # clamped: op union exceeding wall (timer jitter) reads as 0, not < 0
    assert trace_stats.dispatch_overhead_share(2.0, 1.0) == 0.0


def test_dispatch_busy_prefers_device_pids(tmp_path):
    """With a real device timeline present, dispatch_busy uses it (and
    ignores host executor threads)."""
    p = tmp_path / "dev.trace.json.gz"
    _write_trace(p, [
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": 2, "name": "process_name",
         "args": {"name": "/host:CPU"}},
        {"ph": "M", "pid": 2, "tid": 9, "name": "thread_name",
         "args": {"name": "tf_XLAEigen/1"}},
        {"ph": "X", "pid": 1, "tid": 1, "name": "fusion.7", "ts": 0,
         "dur": 30},
        {"ph": "X", "pid": 2, "tid": 9, "name": "dot.1", "ts": 0, "dur": 500},
    ])
    from shallowspeed_tpu.observability import trace_stats

    busy = trace_stats.dispatch_busy(p)
    assert busy["source"] == "device"
    assert busy["op_events"] == 1
    assert busy["busy_union_s"] == pytest.approx(30e-6)


def test_session_dispatch_overhead_probe(data_dir, tmp_path):
    """The measured op-issue roofline end to end on the CPU backend: the
    probe dispatches real epochs under the profiler, attributes op busy
    time via the executor-thread union, and emits the evidence event.
    The share is a genuine measurement: in (0, 1], with op events
    attributed and the provenance stamped."""
    from shallowspeed_tpu.api import TrainingSession

    path = tmp_path / "probe.jsonl"
    m = JsonlMetrics(path)
    session = TrainingSession(
        sizes=SIZES, global_batch_size=GBS, lr=0.01, data_dir=data_dir,
        metrics=m,
    )
    rec = session.measure_dispatch_overhead(repeats=1)
    m.close()
    assert rec["program"] == "epoch_program" and rec["repeats"] == 1
    assert rec["op_events"] > 0 and rec["op_source"] == "host-executor"
    assert rec["device_busy_s"] is not None
    assert 0.0 < rec["host_wall_s"]
    assert rec["dispatch_overhead"] is not None
    assert 0.0 <= rec["dispatch_overhead"] < 1.0
    assert "jax.profiler" in rec["provenance"]
    events = [
        r for r in read_jsonl(path)
        if r["kind"] == "event" and r["name"] == "dispatch_overhead"
    ]
    assert len(events) == 1
    assert events[0]["dispatch_overhead"] == rec["dispatch_overhead"]
    with pytest.raises(ValueError, match="repeats"):
        session.measure_dispatch_overhead(repeats=0)
    with pytest.raises(ValueError, match="program"):
        session.measure_dispatch_overhead(program="nope")


# ---------------------------------------------------------------------------
# the fleet legs: skewed-clock alignment + SIGKILL failover chains
# ---------------------------------------------------------------------------


def _require_workers():
    if not fleet_mod.fleet_workers_supported():
        pytest.skip(
            "this platform cannot spawn fleet worker processes "
            "(multiprocessing spawn context unavailable or broken)"
        )


def _worker_config(data_dir, clock_offset_s=None):
    cfg = {
        "session": dict(
            sizes=SIZES,
            global_batch_size=GBS,
            lr=0.01,
            data_dir=os.fspath(data_dir),
            predict_slot_ladder=(1, 2),
        ),
        "engine": dict(retry=2, breaker_threshold=3),
        "verify": True,
    }
    if clock_offset_s is not None:
        cfg["clock_offset_s"] = clock_offset_s
    return cfg


def _drive_fleet(fleet, n_requests, rate=300.0, kill_after=None):
    """Seeded open-loop drive; optionally SIGKILL the busiest ready
    replica once ``kill_after`` requests completed. Returns (submitted,
    done, killed_replica_id)."""
    payloads = loadgen.request_payloads(n_requests, SIZES[0], seed=0)
    arrivals = loadgen.poisson_arrivals(rate, n_requests, seed=0)
    t0 = fleet.clock()
    i, killed = 0, None
    submitted, done = [], []
    while i < n_requests or fleet.queue_depth:
        now = fleet.clock() - t0
        while i < n_requests and arrivals[i] <= now:
            submitted.append(
                fleet.submit(payloads[i], arrival_t=t0 + arrivals[i])
            )
            i += 1
        done.extend(fleet.step())
        if kill_after is not None and killed is None and len(done) >= kill_after:
            ready = [
                r for r in fleet.replicas.values() if r.state == "ready"
            ]
            victim = max(ready, key=lambda r: (r.inflight, -r.replica_id))
            fleet.sigkill_replica(victim.replica_id)
            killed = victim.replica_id
        if not fleet.queue_depth and i < n_requests:
            time.sleep(max(0.0, arrivals[i] - (fleet.clock() - t0)))
    return submitted, done, killed


@pytest.mark.fleet
def test_skewed_worker_clock_alignment_reconstructs_durations(
    data_dir, tmp_path
):
    """Satellite: inject a +3 s artificial worker clock offset (the
    worker-config test hook) and prove the handshake-aligned join
    reconstructs correct span durations — the recovered offset matches
    the injection within its own recorded uncertainty bound, worker
    spans land INSIDE their request's parent-side window, and per-chain
    phases sum to the parent-measured latency. Also: the same stream
    with the offset records STRIPPED reads as alignment-degraded, with
    the report naming the unmapped replicas instead of joining raw
    clocks."""
    _require_workers()
    from shallowspeed_tpu.observability.report import build_report, render

    SKEW = 3.0
    path = tmp_path / "skew.jsonl"
    m = JsonlMetrics(path)
    with ServingFleet(
        _worker_config(data_dir, clock_offset_s=SKEW),
        n_replicas=2, slo_ms=5000, retry=2, metrics=m, seed=0,
    ) as fleet:
        fleet.start()
        submitted, _done, _ = _drive_fleet(fleet, 16)
        fleet.record_summary()
    m.close()
    assert all(r.verdict == "ok" for r in submitted)
    recs = read_jsonl(str(path) + "*")
    offsets = tracing.clock_offsets(recs)
    assert set(offsets) == {0, 1}
    for rid, off in offsets.items():
        # the NTP-style bound is a guarantee, not a heuristic: the
        # injected skew lies within offset ± uncertainty
        assert abs(off["offset_s"] - SKEW) <= off["uncertainty_s"], (
            rid, off,
        )
        assert off["uncertainty_s"] < 0.05
    chains = tracing.assemble_chains(recs)
    assert tracing.verify_terminal_chains(recs, chains) == []
    for c in chains.values():
        assert c.alignment == "aligned"
        # worker spans, aligned, sit inside the parent-side window
        # (slack = the recorded uncertainty, not the 3 s skew)
        slack = c.uncertainty_s + 1e-4
        for s in c.spans:
            if s.get("clock") == "worker":
                assert s["t0_aligned"] >= c.t0 - slack
                assert s["t1_aligned"] <= c.t_end + slack
        phases = tracing.chain_phases(c)
        assert sum(phases.values()) == pytest.approx(
            c.latency_s, abs=4 * c.uncertainty_s + 1e-6
        )
    # strip the offsets: the join must DEGRADE loudly, not guess
    stripped = [
        r for r in recs
        if not (r.get("kind") == "trace" and r.get("name") == "clock_offset")
    ]
    degraded = tracing.assemble_chains(stripped)
    assert all(c.alignment == "missing" for c in degraded.values())
    report = build_report(stripped, source="stripped")
    assert report["tracing"]["alignment_missing_replicas"] == [0, 1]
    assert "ALIGNMENT DEGRADED" in render(report, "md")


@pytest.mark.fleet
def test_sigkill_failover_chain_links_dead_replica_to_completion(
    data_dir, tmp_path
):
    """Satellite: SIGKILL a replica mid-soak (the fleet-smoke anchor) and
    assert the re-queued requests' chains carry a failover.requeue span
    linking the dead replica's partial chain to the surviving replica's
    completion — and NO terminal request is left with an orphan or
    unclosed chain, kill or no kill."""
    _require_workers()
    path = tmp_path / "kill.jsonl"
    m = JsonlMetrics(path)
    with ServingFleet(
        _worker_config(data_dir),
        n_replicas=3, slo_ms=5000, retry=3, metrics=m, seed=0,
    ) as fleet:
        fleet.start()
        submitted, _done, killed = _drive_fleet(fleet, 40, kill_after=5)
        stats = fleet.stats()
        fleet.record_summary()
    m.close()
    assert killed is not None
    assert all(r.verdict != "queued" for r in submitted)
    recs = read_jsonl(str(path) + "*")
    chains = tracing.assemble_chains(recs)
    # the hard gate: zero orphan/unclosed chains across the kill
    assert tracing.verify_terminal_chains(recs, chains) == []
    if stats["failover_requeued"]:
        failover = [
            c for c in chains.values()
            if any(s["name"] == "failover.requeue" for s in c.spans)
        ]
        assert failover, "failover ran but no chain carries its span"
        for c in failover:
            fo = next(s for s in c.spans if s["name"] == "failover.requeue")
            assert fo["from_replica"] == killed
            # the span's parent is the dead replica's partial chain (its
            # route span, or the worker's last shipped span) ...
            ids = {s["span_id"]: s for s in c.spans}
            assert fo["parent_id"] in ids
            # ... and the request still reached a terminal verdict with
            # the surviving replicas
            assert c.verdict in ("ok", "error")
            if c.verdict == "ok":
                served = next(
                    s for s in c.spans if s.get("terminal")
                )["replica_id_served"]
                assert served != killed


@pytest.mark.fleet
def test_fleet_chaos_record_carries_trace_verdict(data_dir, tmp_path):
    """The bench-level gate: fleet_chaos_soak's record carries the
    span-chain completeness verdict (trace_chains / trace_problems) that
    make trace-smoke asserts on."""
    _require_workers()
    from shallowspeed_tpu.serving.bench_serving import fleet_chaos_soak

    path = tmp_path / "soak.jsonl"
    m = JsonlMetrics(path)
    record = fleet_chaos_soak(
        _worker_config(data_dir),
        in_dim=SIZES[0],
        n_replicas=2,
        kill_after=4,
        n_requests=30,
        rate=300.0,
        seed=0,
        slo_ms=5000,
        metrics=m,
        retry=3,
    )
    m.close()
    assert record["silently_lost"] == []
    assert record["trace_chains"] is not None and record["trace_chains"] > 0
    assert record["trace_problems"] == []
