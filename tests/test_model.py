"""Model-layer tests: partitioning, layout-independent init, VJP correctness.

Mirrors the reference's tests/test_layers.py (param counts, stage
partitioning, end-to-end fwd+bwd) with jax.grad as the gradient oracle.
"""

import jax
import jax.numpy as jnp
import numpy as np

from shallowspeed_tpu import model as M
from shallowspeed_tpu import ops

SIZES = (784, 128, 127, 126, 125, 124, 123, 10)  # flagship model (train.py:98)


def device(params_list):
    return jax.tree.map(jnp.asarray, params_list)


class TestPartitioning:
    def test_stage_slices_overlap_boundary(self):
        # same semantics the reference asserts in tests/test_layers.py:52-70
        parts = M.partition_sizes(list(range(9)), 3)
        assert parts == [(0, 1, 2, 3), (3, 4, 5, 6), (6, 7, 8)]

    def test_uneven_flagship_stages(self):
        spec = M.make_model_spec(SIZES, 4, 128)
        n_lin = [s.n_linears for s in spec.stages]
        assert n_lin == [2, 2, 2, 1]  # stages are deliberately unequal
        assert spec.stages[-1].has_head
        # last Linear of last stage has no fused relu; all others do
        assert spec.stages[-1].relu_flags == (False,)
        assert all(all(s.relu_flags) for s in spec.stages[:-1])

    def test_zero_linear_trailing_stage(self):
        spec = M.make_model_spec(SIZES, 8, 128)
        assert spec.stages[-1].n_linears == 0
        assert spec.stages[-1].has_head

    def test_in_out_dims(self):
        spec = M.make_model_spec(SIZES, 4, 128)
        assert [s.in_dim for s in spec.stages] == [784, 127, 125, 123]
        assert [s.out_dim for s in spec.stages] == [127, 125, 123, 10]


class TestInit:
    def test_layout_independent(self):
        """Partitioning must not change the initial weights (layers.py:103-106)."""
        seq = M.init_model(M.make_model_spec(SIZES, 1, 128))
        pp4 = M.init_model(M.make_model_spec(SIZES, 4, 128))
        flat_seq = [l for s in seq for l in s]
        flat_pp4 = [l for s in pp4 for l in s]
        assert len(flat_seq) == len(flat_pp4) == 7
        for a, b in zip(flat_seq, flat_pp4):
            np.testing.assert_array_equal(a["W"], b["W"])
            np.testing.assert_array_equal(a["b"], b["b"])

    def test_deterministic(self):
        a = M.init_model(M.make_model_spec(SIZES, 2, 128))
        b = M.init_model(M.make_model_spec(SIZES, 2, 128))
        for sa, sb in zip(a, b):
            for la, lb in zip(sa, sb):
                np.testing.assert_array_equal(la["W"], lb["W"])

    def test_scale(self):
        spec = M.make_model_spec((784, 128), 1, 128)
        w = M.init_model(spec)[0][0]["W"]
        assert w.shape == (128, 784)
        assert abs(float(np.std(w)) - 1 / np.sqrt(784)) < 0.005


class TestForwardBackward:
    def test_forward_is_softmax_distribution(self):
        spec = M.make_model_spec((20, 16, 10), 1, 32)
        params = device(M.init_model(spec))
        x = jnp.asarray(np.random.RandomState(0).randn(8, 20), jnp.float32)
        out, _ = M.model_forward(params, spec, x)
        np.testing.assert_allclose(np.asarray(out).sum(1), 1.0, atol=1e-4)

    def test_backward_matches_jax_grad(self):
        spec = M.make_model_spec((12, 16, 14, 10), 1, 32)
        params = device(M.init_model(spec))
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(8, 12), jnp.float32)
        t = jnp.asarray(np.eye(10, dtype=np.float32)[rng.randint(0, 10, 8)])

        def loss(params):
            out, _ = M.model_forward(params, spec, x)
            return ops.mse_loss(out, t, 32)

        want = jax.grad(loss)(params)
        _, res = M.model_forward(params, spec, x)
        _, got = M.model_backward(params, spec, res, t)
        jax.tree.map(
            lambda g, w: np.testing.assert_allclose(g, w, rtol=1e-4, atol=1e-6),
            got,
            want,
        )

    def test_staged_equals_sequential(self):
        """Chaining PP=4 stages == one-stage full model, float-for-float."""
        rng = np.random.RandomState(2)
        x = jnp.asarray(rng.randn(8, 784), jnp.float32)
        t = jnp.asarray(np.eye(10, dtype=np.float32)[rng.randint(0, 10, 8)])
        outs, grads = [], []
        for n_stages in (1, 4):
            spec = M.make_model_spec(SIZES, n_stages, 128)
            params = device(M.init_model(spec))
            out, res = M.model_forward(params, spec, x)
            _, g = M.model_backward(params, spec, res, t)
            outs.append(np.asarray(out))
            grads.append([l for s in g for l in s])
        np.testing.assert_array_equal(outs[0], outs[1])
        for a, b in zip(*grads):
            np.testing.assert_array_equal(a["W"], b["W"])
            np.testing.assert_array_equal(a["b"], b["b"])

    def test_backward_input_grad_matches_jax(self):
        spec = M.make_model_spec((12, 10), 1, 16)
        params = device(M.init_model(spec))
        rng = np.random.RandomState(3)
        x = jnp.asarray(rng.randn(4, 12), jnp.float32)
        t = jnp.asarray(np.eye(10, dtype=np.float32)[rng.randint(0, 10, 4)])

        def loss(x):
            out, _ = M.model_forward(params, spec, x)
            return ops.mse_loss(out, t, 16)

        want = jax.grad(loss)(x)
        _, res = M.model_forward(params, spec, x)
        dx, _ = M.model_backward(params, spec, res, t)
        np.testing.assert_allclose(dx, want, rtol=1e-4, atol=1e-6)
