"""SSP003 good twin: the durable write routed through atomic_write
(reads stay unrestricted)."""

import json

from shallowspeed_tpu.checkpoint import atomic_write


def save_entry(path, record):
    payload = json.dumps(record, allow_nan=False).encode()
    atomic_write(path, lambda f: f.write(payload), suffix=".json.tmp")


def load_entry(path):
    with open(path, encoding="utf-8") as f:
        return json.load(f)
