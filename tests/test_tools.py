"""Smoke tests for the developer tools (pebble renderer, scaling bench CLI)."""

import subprocess
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent


def test_show_schedule_renders_all(capsys):
    scripts_dir = str(ROOT / "scripts")
    sys.path.insert(0, scripts_dir)
    try:
        import show_schedule
    finally:
        sys.path.remove(scripts_dir)
    for name in ("gpipe", "naive", "pipedream", "inference"):
        show_schedule.render(name, 4, 4)
    out = capsys.readouterr().out
    assert "utilization" in out
    assert "F0" in out and "B0" in out
    # GPipe's lowered latency shows up in the header
    assert "gpipe  M=4 S=4: 14 ticks" in out


def test_show_schedule_renders_split_cells(capsys):
    """--backward-split diagrams: b<m> B-input cells at the combined
    backward's ticks, W<m> B-weight cells in the bubbles, and BOTH
    utilization figures in the header."""
    scripts_dir = str(ROOT / "scripts")
    sys.path.insert(0, scripts_dir)
    try:
        import show_schedule
    finally:
        sys.path.remove(scripts_dir)
    show_schedule.render("pipedream", 4, 4, backward_split=True)
    out = capsys.readouterr().out
    assert "b0" in out and "W0" in out and "B0" not in out
    assert "split-bwd" in out
    assert "weighted" in out
    # the README's quoted split diagram header
    assert "15 ticks" in out


def test_weighted_utilization_matches_documented_figures():
    """docs/lowering.md's weighted-bubble table (1F1B M=8: 40% -> 11%
    split; GPipe M=4: 43% -> 33%) must be computable from the lowered
    tick tables — and the weights come from the cost model's single
    source (fwd 1, combined bwd 2, split halves 1)."""
    from shallowspeed_tpu import schedules as S
    from shallowspeed_tpu.observability.costmodel import PIPELINE_OP_COSTS
    from shallowspeed_tpu.parallel.lowering import (
        lower_schedule,
        weighted_makespan,
        weighted_utilization,
    )

    assert PIPELINE_OP_COSTS == {
        "fwd": 1.0, "bwd": 2.0, "bwd_in": 1.0, "bwd_w": 1.0, "recompute": 1.0,
    }
    pd8 = lower_schedule(S.PipeDreamFlushSchedule, 8, 4)
    pd8s = lower_schedule(S.PipeDreamFlushSchedule, 8, 4, backward_split=True)
    assert round((1 - weighted_utilization(pd8)) * 100) == 40
    assert round((1 - weighted_utilization(pd8s)) * 100) == 11
    g4 = lower_schedule(S.GPipeSchedule, 4, 4)
    g4s = lower_schedule(S.GPipeSchedule, 4, 4, backward_split=True)
    assert round((1 - weighted_utilization(g4)) * 100) == 43
    assert round((1 - weighted_utilization(g4s)) * 100) == 33
    # the lockstep tick model: GPipe M=4 P=4 = 7 fwd-phase ticks (max
    # weight 1) + 7 bwd-phase ticks (max weight 2) = 21 forward-units
    assert weighted_makespan(g4) == 21.0
    assert weighted_makespan(g4s) == float(g4s.num_ticks)  # all ticks weight 1


def test_utilization_matches_documented_bubble_figures():
    """The docs' bubble-shrink claims (docs/lowering.md: flat 1F1B 57% vs
    interleaved V=2 73% at P=4, M=4; GPipe M/(M+S-1) per phase) must be
    computable from the lowered tick tables, not hand-written prose."""
    from shallowspeed_tpu import schedules as S
    from shallowspeed_tpu.parallel.lowering import lower_schedule, utilization

    flat = lower_schedule(S.PipeDreamFlushSchedule, 4, 4)
    inter = lower_schedule(S.InterleavedSchedule, 4, 4, virtual=2)
    gpipe = lower_schedule(S.GPipeSchedule, 4, 4)
    # exact active-cell counts: every device computes V*M forwards + V*M
    # backwards, so active = P * 2*V*M cells out of num_ticks * P
    assert utilization(flat) == (2 * 4 * 4) / (flat.num_ticks * 4)
    assert utilization(inter) == (2 * 2 * 4 * 4) / (inter.num_ticks * 4)
    # the documented headline figures
    assert round(utilization(flat) * 100) == 57
    assert round(utilization(gpipe) * 100) == 57
    assert round(utilization(inter) * 100) == 73
    assert utilization(inter) > utilization(flat)  # the V-fold fill shrink
    # inference relay: M/(M+S-1) utilization exactly
    inf = lower_schedule(S.InferenceSchedule, 4, 4)
    assert abs(utilization(inf) - 4 / (4 + 4 - 1)) < 1e-12


def test_trace_stats_reproduces_roofline_numbers():
    """docs/performance.md's latency-roofline evidence (63,238 device ops in
    ~15 ms = ~238 ns/op issued, ~2.9x unit overlap) must be recomputable
    from the committed chip trace by scripts/trace_stats.py."""
    scripts_dir = str(ROOT / "scripts")
    sys.path.insert(0, scripts_dir)
    try:
        import trace_stats
    finally:
        sys.path.remove(scripts_dir)
    # pinned to the specific committed round-2 trace file (not a directory
    # glob): new captures write per-round dirs (artifacts/tpu_trace_r<N>),
    # so future chip runs can never silently re-target this assertion
    frozen = (
        ROOT / "artifacts" / "tpu_trace" / "plugins" / "profile"
        / "2026_07_29_10_39_10" / "runsc.trace.json.gz"
    )
    assert frozen.is_file(), "committed chip trace missing"
    s = trace_stats.summarize(frozen)
    assert s["device_ops"] == 63238
    assert 230 <= s["ns_per_op_issued"] <= 250
    assert 2.5 <= s["unit_overlap"] <= 3.5
    # matmuls present and dominated in count by small fusions — the
    # op-stream (not FLOPs) picture the roofline section describes
    assert s["top_ops"].get("convolution_add_fusion", 0) > 10000
    # a sequential chip trace has no collectives: the overlap split must
    # say so (no comm -> no efficiency claim), not fabricate a number
    assert s["comm_ops"] == 0 and s["comm_ms"] == 0.0
    assert s["exposed_comm_ms"] == 0.0
    assert s["overlap_efficiency"] is None


def test_train_cli_help():
    r = subprocess.run(
        [sys.executable, str(ROOT / "train.py"), "--help"],
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert r.returncode == 0
    for flag in (
        "--dp", "--pp", "--schedule", "--checkpoint", "--resume",
        "--precision", "--grad-bucket-bytes",
    ):
        assert flag in r.stdout


def _import_bench():
    sys.path.insert(0, str(ROOT))
    try:
        import bench
    finally:
        sys.path.remove(str(ROOT))
    return bench


def test_slope_timing_per_leg_minima(monkeypatch):
    """The slope estimator must take per-leg minima BEFORE differencing, so a
    contended leg in one trial cannot corrupt the estimate (TPU_STATUS_r02.md
    finding 5: chip-pool contention varies 40x across claim windows)."""
    bench = _import_bench()
    fake = {"t": 0.0}
    monkeypatch.setattr(bench.time, "perf_counter", lambda: fake["t"])
    calls = {"n": 0}

    def run_k(k):
        contention = 0.5 if calls["n"] == 0 else 0.0  # first k1 leg contended
        calls["n"] += 1
        fake["t"] += 0.1 + 0.01 * k + contention  # constant + per-epoch cost

    est = bench.slope_epoch_seconds(run_k, k1=2, k2=8, trials=3)
    assert abs(est - 0.01) < 1e-12  # constants and the contended leg cancel out


def test_slope_timing_rejects_non_positive_slope(monkeypatch):
    """If more epochs never cost more time, the device isn't executing the
    work (the async-dispatch failure mode) — the protocol must refuse."""
    import pytest

    bench = _import_bench()
    fake = {"t": 0.0}
    monkeypatch.setattr(bench.time, "perf_counter", lambda: fake["t"])

    def run_k(k):
        fake["t"] += 0.1  # pure constant: dispatch-only, no real execution

    with pytest.raises(RuntimeError, match="slope timing failed"):
        bench.slope_epoch_seconds(run_k, trials=2)


def test_measured_epoch_sps_protocol(monkeypatch):
    """measured_epoch_sps = samples_per_epoch / honest-slope, warmup excluded."""
    import numpy as np

    bench = _import_bench()
    fake = {"t": 0.0}
    monkeypatch.setattr(bench.time, "perf_counter", lambda: fake["t"])
    monkeypatch.setattr(bench, "sync_readback", lambda tree: None)

    def epoch_fn(p, s, X, Y):
        fake["t"] += 0.02  # 20 ms per epoch of "device" time
        return p, s, 0.0

    X = np.zeros((4, 2, 8, 3), np.float32)  # 4 batches x 2 mubatches x 8 rows
    sps = bench.measured_epoch_sps(epoch_fn, {"w": np.zeros(2)}, (), X, None)
    assert abs(sps - (4 * 2 * 8) / 0.02) < 1e-6


def test_bench_watchdog_salvage_and_error_protocol(monkeypatch, tmp_path):
    """_run_measurements must salvage per-cell results from a child that
    fails one cell, and report the failed cell's error instead of silently
    misdiagnosing it as a tunnel wedge (the fallback tag depends on it)."""
    bench = _import_bench()

    # a stand-in "bench.py" child that succeeds for 'default', errors for
    # 'highest', using the real per-line flushed protocol
    child = tmp_path / "fake_bench.py"
    child.write_text(
        "import json, sys\n"
        "for p in sys.argv[2].split(','):\n"
        "    if p == 'default':\n"
        "        print(json.dumps({'precision': p, 'sps': 123.0}), flush=True)\n"
        "    else:\n"
        "        print(json.dumps({'precision': p, 'error': 'boom'}), flush=True)\n"
        "sys.exit(4)\n"
    )
    monkeypatch.setattr(bench, "__file__", str(child))
    results, saw_timeout, errors, meta = bench._run_measurements(
        ("default", "highest"), timeout_s=30, attempts=2
    )
    assert results == {"default": 123.0}
    assert not saw_timeout  # a crash is NOT a wedge
    assert "boom" in errors.get("highest", "")
    # provenance: no tunnel env in tests -> backend recorded as cpu; a line
    # without an explicit interleaved field defaults to True (legacy lines)
    assert meta["default"] == {"interleaved": True, "backend": "cpu"}


def test_bench_watchdog_timeout_is_flagged(monkeypatch, tmp_path):
    """A child that hangs must be killed at the timeout and reported as a
    wedge (saw_timeout=True), with any flushed results still salvaged."""
    bench = _import_bench()

    child = tmp_path / "hang_bench.py"
    child.write_text(
        "import json, sys, time\n"
        "print(json.dumps({'precision': 'default', 'sps': 7.0}), flush=True)\n"
        "time.sleep(60)\n"
    )
    monkeypatch.setattr(bench, "__file__", str(child))
    results, saw_timeout, errors, meta = bench._run_measurements(
        ("default", "highest"), timeout_s=3, attempts=1
    )
    assert results == {"default": 7.0}  # flushed before the hang — salvaged
    assert saw_timeout


def test_build_record_honesty_rules():
    """Every labeling rule of the published bench record, unit-level:
    observed-cpu tagging, plausibility + cross-check SUSPECT tags, and the
    same_window pairing conditions."""
    bench = _import_bench()
    tpu = {"interleaved": True, "backend": "tpu"}
    cpu = {"interleaved": True, "backend": "cpu"}
    lone = {"interleaved": False, "backend": "tpu"}

    def rec(results, meta, tag="", env_active=True, **kw):
        return bench.build_record(results, meta, 1000.0, tag, env_active, **kw)

    # clean chip pair -> untagged metric, same_window
    r, w = rec({"default": 5e6, "highest": 3e6}, {"default": tpu, "highest": tpu})
    assert r["metric"] == "mnist_mlp_train_samples_per_sec_per_chip" and not w
    assert r["same_window"] and r["value_backend"] == "tpu"
    assert r["vs_baseline"] == 5000.0

    # child silently degraded to CPU while the tunnel env was active
    r, w = rec({"default": 5e4}, {"default": cpu})
    assert r["metric"].endswith("_CPU_FALLBACK_CHILD_BACKEND_DEGRADED") and w

    # ... but with no tunnel env (plain CPU host) that's not a degradation
    r, _ = rec({"default": 5e4}, {"default": cpu}, env_active=False)
    assert r["metric"] == "mnist_mlp_train_samples_per_sec_per_chip"

    # an existing fallback tag is preserved, not double-tagged
    r, _ = rec({"default": 5e4}, {"default": cpu}, tag="_CPU_FALLBACK_X")
    assert r["metric"].endswith("_CPU_FALLBACK_X")

    # implausible FLOP rate -> SUSPECT_TIMING (default ceiling 200 TFLOP/s)
    too_fast = 300e12 / bench.flops_per_sample()
    r, w = rec({"default": too_fast}, {"default": tpu})
    assert r["metric"].endswith("_SUSPECT_TIMING") and "ceiling" in w[0]

    # headline > 2x the whole-run cross-check -> SUSPECT_TIMING (once)
    r, w = rec(
        {"default": 5e6, "_crosscheck": 2e6}, {"default": tpu}
    )
    assert r["metric"].count("_SUSPECT_TIMING") == 1 and "cross-check" in w[0]

    # a retry-measured lone cell breaks the same-window pairing
    r, _ = rec({"default": 5e6, "highest": 3e6}, {"default": tpu, "highest": lone})
    assert not r["same_window"]
    # cross-backend pair too
    r, _ = rec({"default": 5e6, "highest": 3e6}, {"default": tpu, "highest": cpu})
    assert not r["same_window"]

    # nothing measured
    r, w = rec({}, {})
    assert r is None and "no measurement" in w[0]

    # tunnel diagnostics ride in the record itself (round-3 lesson: an
    # empty-chip round must be self-describing); preliminary marks the
    # phase-1 record printed before the tunnel was probed
    diag = {"probes": [{"outcome": "timeout", "seconds": 180.0}],
            "patience_s": 600.0, "failure": "unresponsive"}
    r, _ = rec({"default": 5e4}, {"default": cpu},
               tag="_CPU_FALLBACK_TUNNEL_UNRESPONSIVE", tunnel=diag,
               preliminary=True)
    assert r["tunnel"] == diag and r["preliminary"] is True
    # a non-preliminary record carries no preliminary key at all
    r, _ = rec({"default": 5e6}, {"default": tpu}, tunnel={"probes": []})
    assert "preliminary" not in r and r["tunnel"] == {"probes": []}
    # and with no diagnostics passed, no tunnel key
    r, _ = rec({"default": 5e6}, {"default": tpu})
    assert "tunnel" not in r


def test_build_record_mfu_companions():
    """The record lines carry MFU alongside samples/s: each cell against
    its OWN backend's per-chip peak, with the peak + source recorded so a
    nominal-CPU MFU is self-describing."""
    bench = _import_bench()
    tpu = {"interleaved": True, "backend": "tpu"}
    cpu = {"interleaved": True, "backend": "cpu"}
    fps = bench.flops_per_sample()
    r, _ = bench.build_record(
        {"default": 5e6, "highest": 3e6}, {"default": tpu, "highest": tpu},
        1000.0, "", True,
    )
    assert abs(r["mfu"] - 5e6 * fps / 200e12) < 1e-6  # rounded to 6 places
    assert abs(r["mfu_fp32_highest"] - 3e6 * fps / 100e12) < 1e-6
    assert r["mfu_peak_flops"] == 200e12
    assert r["mfu_peak_source"] == "datasheet-v5e"
    # cpu cells get the clearly-tagged nominal peak
    r, _ = bench.build_record({"default": 5e4}, {"default": cpu}, 1000.0, "", False)
    assert r["mfu_peak_source"] == "nominal-cpu-default" and r["mfu"] > 0
    # the phase-0 stub stays null-valued but record-shaped
    r, _ = bench.build_record(
        {}, {}, 1000.0, "_STUB_NOT_MEASURED", True, stub=True
    )
    assert r["mfu"] is None and r["mfu_peak_flops"] is None
    # peak_hbm_bytes rides the record when the child's memory audit (the
    # shared program_audit.memory_stats path) reported one — null otherwise
    assert r["peak_hbm_bytes"] is None
    r, _ = bench.build_record(
        {"default": 5e4, "_peak_hbm_bytes": 123456}, {"default": cpu},
        1000.0, "", False,
    )
    assert r["peak_hbm_bytes"] == 123456


def test_bench_publishes_before_spending_tunnel_patience(monkeypatch, capsys):
    """The round-3 regression, bounded out: with the tunnel env active,
    bench.main must print a complete preliminary record BEFORE the first
    probe is launched (so a driver kill during probe patience can never
    again produce an empty BENCH record), and the final line must carry the
    accurate failure tag plus the probe diagnostics."""
    import json as _json

    import pytest

    bench = _import_bench()
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "10.0.0.1")
    monkeypatch.setattr(bench, "numpy_baseline_sps", lambda: 1000.0)
    calls = []

    def fake_measure(precisions, timeout_s, attempts=2, force_cpu=False):
        calls.append("measure_cpu" if force_cpu else "measure_tunnel")
        return (
            {p: 5e4 for p in precisions},
            False,
            {},
            {p: {"interleaved": True, "backend": "cpu"} for p in precisions},
        )

    monkeypatch.setattr(bench, "_run_measurements", fake_measure)
    stdout_at_probe = {}

    def fake_probe(*a, **k):
        calls.append("probe")
        stdout_at_probe["out"] = capsys.readouterr().out  # consumed, re-joined below
        return "_CPU_FALLBACK_TUNNEL_UNRESPONSIVE", {
            "probes": [{"outcome": "timeout", "seconds": 180.0}],
            "patience_s": 600.0,
            "failure": "unresponsive",
        }

    monkeypatch.setattr(bench, "_ensure_responsive_backend", fake_probe)
    with pytest.raises(SystemExit) as exc:
        bench.main()
    assert exc.value.code == 0
    # the CPU measurement ran before the probe, and no tunnel measurement ran
    assert calls == ["measure_cpu", "probe"]
    # a complete preliminary record was already on stdout when probing began
    pre_lines = [
        _json.loads(ln)
        for ln in stdout_at_probe["out"].splitlines()
        if ln.startswith("{")
    ]
    # two records precede the probe: the null-value stub printed before ANY
    # measurement (ADVICE r04 — a driver kill during the phase-1 CPU cells
    # still leaves a parseable line) and the complete CPU preliminary
    assert len(pre_lines) == 2
    assert pre_lines[0]["preliminary"] is True and pre_lines[0]["value"] is None
    assert "stub" in pre_lines[0]["tunnel"]["state"]
    # ADVICE r05: the stub is machine-readably a stub — a neutral
    # _STUB_NOT_MEASURED tag (the tunnel was never probed at that point,
    # so no _CPU_FALLBACK_TUNNEL_UNRESPONSIVE claim) plus "stub": true
    assert pre_lines[0]["stub"] is True
    assert pre_lines[0]["metric"].endswith("_STUB_NOT_MEASURED")
    assert "UNRESPONSIVE" not in pre_lines[0]["metric"]
    assert pre_lines[1]["preliminary"] is True and pre_lines[1]["value"] == 5e4
    # the final (last) line is the authoritative record with diagnostics
    post_lines = [
        _json.loads(ln)
        for ln in capsys.readouterr().out.splitlines()
        if ln.startswith("{")
    ]
    final = post_lines[-1]
    assert "preliminary" not in final
    assert final["metric"].endswith("_CPU_FALLBACK_TUNNEL_UNRESPONSIVE")
    assert final["tunnel"]["probes"][0]["outcome"] == "timeout"


def test_bench_healthy_probe_upgrades_to_chip_record(monkeypatch, capsys):
    """Healthy-tunnel path: phase 1 publishes the CPU preliminary, a healthy
    probe re-emits an interim (accurately tagged for the kill-during-
    measurement window), and the LAST line is the untagged chip record."""
    import json as _json

    import pytest

    bench = _import_bench()
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "10.0.0.1")
    monkeypatch.setattr(bench, "numpy_baseline_sps", lambda: 1000.0)

    def fake_measure(precisions, timeout_s, attempts=2, force_cpu=False):
        backend, sps = ("cpu", 5e4) if force_cpu else ("tpu", 5e6)
        return (
            {p: sps for p in precisions},
            False,
            {},
            {p: {"interleaved": True, "backend": backend} for p in precisions},
        )

    monkeypatch.setattr(bench, "_run_measurements", fake_measure)
    monkeypatch.setattr(
        bench,
        "_ensure_responsive_backend",
        lambda *a, **k: ("", {"probes": [{"outcome": "ok", "seconds": 21.0}],
                              "patience_s": 600.0}),
    )
    with pytest.raises(SystemExit) as exc:
        bench.main()
    assert exc.value.code == 0
    lines = [
        _json.loads(ln)
        for ln in capsys.readouterr().out.splitlines()
        if ln.startswith("{")
    ]
    assert len(lines) == 4  # stub, preliminary, interim, final
    assert lines[0]["preliminary"] and lines[0]["value"] is None
    assert lines[0]["stub"] is True and "_STUB_NOT_MEASURED" in lines[0]["metric"]
    assert lines[1]["preliminary"] and "UNRESPONSIVE" in lines[1]["metric"]
    assert "stub" not in lines[1]  # only the phase-0 line is a stub
    assert lines[2]["preliminary"] and "WEDGED_MIDRUN" in lines[2]["metric"]
    assert lines[2]["tunnel"]["probes"][0]["outcome"] == "ok"
    final = lines[-1]
    assert final["metric"] == "mnist_mlp_train_samples_per_sec_per_chip"
    assert final["value"] == 5e6 and final["value_backend"] == "tpu"
    assert "preliminary" not in final


def test_probe_patience_respects_budget(monkeypatch):
    """The probe retry loop must never overshoot its patience budget by a
    whole probe (ADVICE r03): a retry is launched only when a full
    probe_timeout_s still fits before the deadline. Round 3's regression —
    probe patience outliving the driver window — is bounded out here."""
    import subprocess as sp

    bench = _import_bench()
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "10.0.0.1")
    fake = {"t": 0.0}
    monkeypatch.setattr(bench.time, "monotonic", lambda: fake["t"])
    monkeypatch.setattr(
        bench.time, "sleep", lambda s: fake.__setitem__("t", fake["t"] + s)
    )

    class WedgedProc:  # every probe hangs until its timeout
        pid = 99999

        def wait(self, timeout=None):
            if timeout is not None:
                fake["t"] += timeout
                raise sp.TimeoutExpired("probe", timeout)
            return -9  # post-kill reap

    monkeypatch.setattr(bench.subprocess, "Popen", lambda *a, **k: WedgedProc())
    monkeypatch.setattr(bench.os, "killpg", lambda *a, **k: None)
    tag, diag = bench._ensure_responsive_backend(probe_timeout_s=180, patience_s=600)
    assert tag == "_CPU_FALLBACK_TUNNEL_UNRESPONSIVE"
    assert fake["t"] <= 600  # never overshoots the documented budget
    # between-probe sleeps now follow the shared bounded backoff (short
    # early delays, clamped so the last probe still fits the budget): the
    # 600 s window fits exactly three 180 s probes at every jitter draw
    assert [p["outcome"] for p in diag["probes"]] == ["timeout"] * 3
    assert diag["patience_s"] == 600 and "unresponsive" in diag["failure"]


def test_slope_timing_interleaved_same_window(monkeypatch):
    """slope_epoch_seconds_many must interleave configs WITHIN each trial
    (so a contention window hits all configs equally) and estimate each
    config's slope with the same per-leg-minimum discipline."""
    bench = _import_bench()
    fake = {"t": 0.0}
    monkeypatch.setattr(bench.time, "perf_counter", lambda: fake["t"])
    order = []

    def make_run_k(name, per_epoch):
        def run_k(k):
            order.append(name)
            # trial 2 of 3 is globally contended: both configs see it, so
            # per-leg minima drop it for both and the ratio stays truthful
            contended = 0.7 if len(order) // 4 == 1 else 0.0
            fake["t"] += 0.05 + per_epoch * k + contended
        return run_k

    slopes = bench.slope_epoch_seconds_many(
        {"a": make_run_k("a", 0.01), "b": make_run_k("b", 0.02)},
        trials=3,
        min_delta_s=0,  # fixed legs: this test pins the interleaving order
    )
    assert abs(slopes["a"] - 0.01) < 1e-12
    assert abs(slopes["b"] - 0.02) < 1e-12
    # interleaving: each trial visits a then b before the next trial
    assert order[:4] == ["a", "a", "b", "b"]


def test_slope_timing_adapts_legs_past_rtt_hiding(monkeypatch):
    """On a high-RTT tunnel, a whole leg's device work can hide inside the
    dispatch+readback constants (wall = max(RTT, device_time)), making the
    naive fixed-leg delta pure noise (observed: 1.65e9 'samples/s' matrix
    cells). The estimator must measure the zero-epoch constants, grow the
    small leg until device time is resolvable ABOVE them, and then recover
    the true per-epoch cost exactly (both legs unhidden => constants
    cancel)."""
    bench = _import_bench()
    fake = {"t": 0.0}
    monkeypatch.setattr(bench.time, "perf_counter", lambda: fake["t"])
    RTT, PER_EPOCH = 0.08, 0.001

    def run_k(k):
        fake["t"] += max(RTT, PER_EPOCH * k)  # k epochs fully overlap the RTT

    slopes = bench.slope_epoch_seconds_many({"cell": run_k}, trials=3)
    assert abs(slopes["cell"] - PER_EPOCH) < 1e-12


def test_slope_timing_failures_dict_salvages_good_configs(monkeypatch):
    """With a `failures` dict, one unresolvable config must not discard the
    other configs' completed measurements (a whole chip claim's worth on the
    real tunnel)."""
    bench = _import_bench()
    fake = {"t": 0.0}
    monkeypatch.setattr(bench.time, "perf_counter", lambda: fake["t"])

    def good(k):
        fake["t"] += 0.1 + 0.01 * k

    def stuck(k):
        fake["t"] += 0.1  # pure constant: never resolves

    failures = {}
    slopes = bench.slope_epoch_seconds_many(
        {"good": good, "stuck": stuck}, trials=2, failures=failures
    )
    assert abs(slopes["good"] - 0.01) < 1e-12
    assert "good" not in failures
    assert "stuck" in failures and "stuck" not in slopes
