"""Optimizer-state plumbing + real activation-memory bounds.

Two properties added after review:
1. a STATEFUL optimizer (momentum) must keep distributed == sequential —
   i.e. the pipeline executor threads optimizer state exactly like the
   sequential trainer (it used to silently drop it);
2. the lowering allocates activation-stash slots, so PipeDream-Flush's 1F1B
   memory bound is physical buffer depth, not just a diagram property.
"""

import jax
import jax.numpy as jnp
import numpy as np

from shallowspeed_tpu import model as Mo
from shallowspeed_tpu import schedules as S
from shallowspeed_tpu import trainer
from shallowspeed_tpu.optimizer import SGD, MomentumSGD
from shallowspeed_tpu.parallel import executor as E
from shallowspeed_tpu.parallel import lower_schedule, make_mesh

SIZES = (24, 20, 18, 16, 14, 12, 11, 10)
B, M = 32, 4


def test_momentum_pipeline_equals_sequential():
    opt = MomentumSGD(lr=0.01, momentum=0.9)
    rng = np.random.RandomState(0)
    NB = 4  # several batches so stale/dropped velocity would visibly diverge
    X = rng.randn(NB, B, SIZES[0]).astype(np.float32)
    Y = np.eye(SIZES[-1], dtype=np.float32)[rng.randint(0, 10, (NB, B))]

    spec1 = Mo.make_model_spec(SIZES, 1, B)
    params = jax.tree.map(jnp.asarray, Mo.init_model(spec1))
    step1 = trainer.make_train_step(spec1, opt)
    st = opt.init(params)
    for i in range(NB):
        params, st = step1(
            params,
            st,
            jnp.asarray(X[i].reshape(M, B // M, -1)),
            jnp.asarray(Y[i].reshape(M, B // M, -1)),
        )
    want = [l for stage in params for l in stage]

    mesh = make_mesh(2, 4)
    spec4 = Mo.make_model_spec(SIZES, 4, B)
    prog = lower_schedule(S.GPipeSchedule, M, 4)
    stacked, flags = E.init_stacked(spec4, mesh)
    opt_state = opt.init(stacked)
    step = E.make_pipeline_step(mesh, spec4, prog, B // 2 // M, opt)
    for i in range(NB):
        stacked, opt_state, _ = step(
            stacked, flags, opt_state, jnp.asarray(X[i]), jnp.asarray(Y[i])
        )
    got = [l for stage in E.unstack_params(stacked, spec4) for l in stage]

    for a, b in zip(want, got):
        np.testing.assert_allclose(np.asarray(a["W"]), b["W"], rtol=5e-4, atol=5e-6)
        np.testing.assert_allclose(
            np.asarray(a["b"]).reshape(-1), b["b"].reshape(-1), rtol=5e-4, atol=5e-6
        )
    # the velocity state itself must be live (non-zero) after training
    v_norm = sum(float(jnp.abs(v).sum()) for v in jax.tree.leaves(opt_state))
    assert v_norm > 0


class TestStashDepth:
    """Activation-stash slots = the schedule's true peak activation memory."""

    def test_gpipe_allocates_m_slots(self):
        assert lower_schedule(S.GPipeSchedule, 8, 4).n_stash_slots == 8

    def test_pipedream_allocates_min_m_depth(self):
        # 1F1B: stage 0 holds at most `depth` live microbatches
        assert lower_schedule(S.PipeDreamFlushSchedule, 8, 4).n_stash_slots == 4
        assert lower_schedule(S.PipeDreamFlushSchedule, 2, 4).n_stash_slots == 2

    def test_naive_allocates_one_slot(self):
        assert lower_schedule(S.NaiveParallelSchedule, 8, 4).n_stash_slots == 1

    def test_inference_allocates_none(self):
        p = lower_schedule(S.InferenceSchedule, 4, 4, training=False)
        assert p.n_stash_slots == 1  # minimum placeholder; never written
        assert (np.asarray(p.stash_write) == p.n_stash_slots).all()
