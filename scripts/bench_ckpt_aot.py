"""The two production-path-stall scoreboards (ROADMAP item 5, PR 12):

1. **Checkpoint overhead fraction, sync vs async** — the same dp2
   training run checkpointing every step, measured as checkpoint wall /
   (checkpoint + train-dispatch wall): the report Reliability section's
   exact formula, with the async leg charging only the ON-PATH cost
   (device->host snapshot + bounded-queue enqueue). Trials interleave
   sync/async so the pair is same-window (the BENCH_r0x protocol), and
   the async leg drains its writer before the clock stops — nothing
   off-path is hidden outside the window.

2. **Fleet `scale_up_s`, cold vs cache-warm** — a real 1-replica
   ``ServingFleet`` (spawned worker process, own JAX runtime, ladder
   warmed before ready). The no-cache fleet's replacement recompiles the
   ladder (cold); the aot-cache fleet's replacement deserializes what
   the first replica compiled (warm). Both walls are the fleet's own
   spawn-to-ready measurement — the same number `make fleet-smoke`
   records.

Writes the versioned record beside bench_scaling's (CKPT_AOT_r01.json
at the repo root by default). CPU-fallback caveat applies as everywhere:
on emulated devices these validate machinery and RELATIVE ratios, not
chip performance.
"""

import argparse
import json
import shutil
import sys
import tempfile
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

BENCH_VERSION = 1
LADDER = (1, 2, 4, 8)


def _make_data(d):
    d.mkdir(parents=True, exist_ok=True)
    rng = np.random.RandomState(0)
    for suffix, n in (("train", 512), ("val", 96)):
        np.save(d / f"x_{suffix}.npy", rng.rand(n, 784).astype(np.float32))
        np.save(
            d / f"y_{suffix}.npy",
            np.eye(10, dtype=np.float32)[rng.randint(0, 10, n)],
        )


CKPT_SIZES = (784, 512, 512, 512, 256, 10)  # ~1M params: the regime where
# verification (sha256 over every byte) + the zip write dominate a save —
# the flagship MLP is so small that the device->host snapshot (which MUST
# stay on-path for consistency) hides the off-path win


def _ckpt_leg(data_dir, work, async_, steps, trial):
    """One checkpoint-overhead leg: train `steps` steps checkpointing
    every step; returns (ckpt_on_path_wall, train_wall)."""
    from shallowspeed_tpu.api import TrainingSession

    ck = work / f"ck_{'async' if async_ else 'sync'}_{trial}"
    run = TrainingSession(
        sizes=CKPT_SIZES, dp=2, global_batch_size=32, mubatches=2,
        data_dir=data_dir, checkpoint_dir=ck, async_checkpoint=async_,
        optimizer="momentum",  # optimizer state doubles the saved bytes
    )
    run.train_steps(1)  # compile outside the measured window
    ckpt_wall = 0.0
    train_wall = 0.0
    for _ in range(steps):
        t0 = time.perf_counter()
        run.train_steps(1)
        train_wall += time.perf_counter() - t0
        t0 = time.perf_counter()
        run.save_step_checkpoint()
        ckpt_wall += time.perf_counter() - t0
    # drain INSIDE the async leg's accounting window: the off-path work
    # must finish before the leg's clock stops, or the comparison would
    # credit async with work it merely deferred past the measurement
    t0 = time.perf_counter()
    run.close()
    drain_wall = time.perf_counter() - t0
    shutil.rmtree(ck, ignore_errors=True)
    return ckpt_wall, train_wall, drain_wall


def bench_checkpoint_overhead(data_dir, work, steps=16, trials=3):
    legs = {"sync": [], "async": []}
    # interleave the pair per trial: same-window ratios
    for trial in range(trials):
        for name, async_ in (("sync", False), ("async", True)):
            legs[name].append(_ckpt_leg(data_dir, work, async_, steps, trial))
    out = {}
    for name, rows in legs.items():
        ck = min(r[0] for r in rows)  # per-leg minima, like the bench
        tr = min(r[1] for r in rows)
        out[name] = {
            "checkpoint_wall_s": ck,
            "train_wall_s": tr,
            "drain_wall_s": min(r[2] for r in rows),
            "overhead_fraction": ck / (ck + tr) if (ck + tr) > 0 else None,
            "per_save_ms": 1e3 * ck / steps,
            "trials": [
                {"checkpoint_wall_s": a, "train_wall_s": b, "drain_wall_s": c}
                for a, b, c in rows
            ],
        }
    sync_f, async_f = (
        out["sync"]["overhead_fraction"], out["async"]["overhead_fraction"]
    )
    out["steps"] = steps
    out["overhead_ratio_async_vs_sync"] = (
        async_f / sync_f if sync_f else None
    )
    return out


def bench_fleet_scale_up(data_dir, work):
    """Cold vs cache-warm replacement: two 1-replica fleets, each scaled
    up once; the replacement's spawn-to-ready wall is the scoreboard."""
    from shallowspeed_tpu.serving.fleet import (
        ServingFleet,
        fleet_workers_supported,
    )

    if not fleet_workers_supported():
        return {"skipped": "platform cannot spawn fleet worker processes"}
    out = {}
    for name, cache in (("cold", None), ("aot_warm", work / "aot")):
        # pp2 rung programs: pipeline-step compiles are the expensive
        # ladder (seconds each on CPU XLA) — the shape where a serving
        # replica's cold start is genuinely seconds-of-XLA
        session = dict(
            pp=2, schedule="gpipe", global_batch_size=32, mubatches=2,
            data_dir=str(data_dir),
            predict_slot_ladder=LADDER,
        )
        if cache is not None:
            session["aot_cache_dir"] = str(cache)
        fleet = ServingFleet({"session": session}, n_replicas=1)
        try:
            t0 = time.perf_counter()
            fleet.start()  # first replica: compiles (and writes the cache)
            first_ready = time.perf_counter() - t0
            fleet.scale_up(wait_ready=True)
            stats = fleet.stats()
            walls = [
                r.get("ready_wall_s")
                for r in stats["per_replica"].values()
                if r.get("ready_wall_s") is not None
            ]
            out[name] = {
                "first_replica_ready_s": first_ready,
                "scale_up_s": stats["scale_up_s"],
                "ready_walls_s": walls,
            }
        finally:
            fleet.stop()
    if "cold" in out and "aot_warm" in out:
        cold, warm = out["cold"]["scale_up_s"], out["aot_warm"]["scale_up_s"]
        out["scale_up_speedup"] = (
            cold / warm if cold is not None and warm else None
        )
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=None,
                    help="record path (default: CKPT_AOT_r01.json at the "
                    "repo root)")
    ap.add_argument("--steps", type=int, default=16)
    ap.add_argument("--trials", type=int, default=3)
    ap.add_argument("--skip-fleet", action="store_true")
    ap.add_argument(
        "--archive-previous", action="store_true",
        help="snapshot the existing checkpoint_overhead section as a new "
        "checkpoint_overhead_r<N> round before writing (use when a code "
        "change makes the superseded numbers a different regime)",
    )
    args = ap.parse_args(argv)

    import jax

    work = Path(tempfile.mkdtemp(prefix="bench_ckpt_aot_"))
    data_dir = work / "data"
    _make_data(data_dir)
    record = {
        "bench": "ckpt_aot",
        "bench_version": BENCH_VERSION,
        "created": time.strftime("%Y-%m-%d %H:%M:%S"),
        "platform": jax.devices()[0].platform,
        "n_devices": len(jax.devices()),
        "cpu_fallback_caveat": (
            "emulated CPU devices: machinery + relative ratios, not chip "
            "performance"
        ),
        "protocol": (
            "same-window: sync/async legs interleaved per trial, per-leg "
            "minima; async leg drains its writer inside the window; fleet "
            "walls are the fleet's own spawn-to-ready measurement"
        ),
        "checkpoint_overhead": bench_checkpoint_overhead(
            data_dir, work, steps=args.steps, trials=args.trials
        ),
    }
    if not args.skip_fleet:
        record["fleet_scale_up"] = bench_fleet_scale_up(data_dir, work)
    out = Path(
        args.out
        if args.out
        else Path(__file__).resolve().parent.parent / "CKPT_AOT_r01.json"
    )
    if out.exists():
        # preserve prior rounds instead of clobbering them: archived
        # checkpoint_overhead_r<N> sections (and a skipped fleet leg's
        # last measurement) carry forward, so the scoreboard the docs
        # cite stays reproducible BY THIS SCRIPT; --archive-previous
        # additionally snapshots the current section as a new round
        # (used when a code change makes the old numbers a different
        # REGIME, not just a rerun — unconditional archiving would grow
        # one near-duplicate section per invocation)
        try:
            old = json.loads(out.read_text())
        except (OSError, json.JSONDecodeError):
            old = {}
        for k, v in old.items():
            if k.startswith("checkpoint_overhead_r"):
                record[k] = v
        if args.archive_previous and "checkpoint_overhead" in old:
            n = 1
            while f"checkpoint_overhead_r{n}" in record:
                n += 1
            record[f"checkpoint_overhead_r{n}"] = old["checkpoint_overhead"]
        if "fleet_scale_up" not in record and "fleet_scale_up" in old:
            record["fleet_scale_up"] = old["fleet_scale_up"]
    out.write_text(json.dumps(record, indent=2) + "\n")
    co = record["checkpoint_overhead"]
    print(f"record written: {out}")
    print(
        "checkpoint overhead: sync "
        f"{co['sync']['overhead_fraction'] * 100:.1f}% -> async "
        f"{co['async']['overhead_fraction'] * 100:.1f}% "
        f"({co['sync']['per_save_ms']:.1f} -> "
        f"{co['async']['per_save_ms']:.1f} ms/save on-path)"
    )
    fs = record.get("fleet_scale_up", {})
    if fs.get("scale_up_speedup") is not None:
        print(
            f"fleet scale_up_s: cold {fs['cold']['scale_up_s']:.2f}s -> "
            f"cache-warm {fs['aot_warm']['scale_up_s']:.2f}s "
            f"({fs['scale_up_speedup']:.1f}x)"
        )
    shutil.rmtree(work, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
