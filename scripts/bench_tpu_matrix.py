"""Single-chip tuning matrix: precision x microbatch-fusion x kernel backend.

For a healthy accelerator, sweeps the sequential-trainer configurations that
matter on the MXU and prints one JSON line per cell:

    {"config": "fused+default+pallas", "samples_per_sec": ..., "speedup_vs_ref_cfg": ...}

Reference cell: scanned microbatches + HIGHEST precision + XLA kernels (the
NumPy-parity configuration). Runs anywhere (CPU included) — on CPU it mostly
measures XLA CPU codegen, which is still useful for regression tracking.

    python scripts/bench_tpu_matrix.py --batches 116 --trials 3
"""

import argparse
import itertools
import json
import sys
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from shallowspeed_tpu.api import (  # the reference's canonical config
    FLAGSHIP_BATCH as B,
    FLAGSHIP_LR as LR,
    FLAGSHIP_MUBATCHES as M,
    FLAGSHIP_SIZES as SIZES,
)


def measure(fused, precision_name, pallas, nb, trials):
    import jax
    import jax.numpy as jnp
    from jax import lax

    from shallowspeed_tpu import model as Mo
    from shallowspeed_tpu import ops, trainer
    from shallowspeed_tpu.optimizer import SGD

    ops.set_pallas(pallas)
    try:
        precision = (
            lax.Precision.HIGHEST if precision_name == "highest" else lax.Precision.DEFAULT
        )
        spec = Mo.make_model_spec(SIZES, 1, B)
        params = jax.tree.map(jnp.asarray, Mo.init_model(spec))
        epoch = trainer.make_train_epoch(
            spec, SGD(LR), precision=precision, fuse_mubatches=fused
        )
        rng = np.random.RandomState(0)
        X = jnp.asarray(rng.rand(nb, M, B // M, SIZES[0]).astype(np.float32))
        Y = jnp.asarray(
            np.eye(SIZES[-1], dtype=np.float32)[
                rng.randint(0, SIZES[-1], (nb, M, B // M))
            ]
        )
        import bench

        return bench.measured_epoch_sps(epoch, params, (), X, Y, trials=trials)
    finally:
        ops.set_pallas(False)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batches", type=int, default=116)
    ap.add_argument(
        "--trials",
        type=int,
        default=3,
        help="slope-timing trials per cell; each trial times 2+8 epochs "
        "(see bench.slope_epoch_seconds)",
    )
    ap.add_argument("--skip-pallas", action="store_true")
    args = ap.parse_args()

    ref_key = ("scanned", "highest", "xla")
    results = {}
    for fused, prec, pallas in itertools.product(
        (False, True), ("highest", "default"), (False, True)
    ):
        if pallas and args.skip_pallas:
            continue
        key = (
            "fused" if fused else "scanned",
            prec,
            "pallas" if pallas else "xla",
        )
        sps = measure(fused, prec, pallas, args.batches, args.trials)
        results[key] = sps
        print(
            json.dumps(
                {
                    "config": "+".join(key),
                    "samples_per_sec": round(sps, 1),
                    "speedup_vs_ref_cfg": round(sps / results[ref_key], 3)
                    if ref_key in results
                    else None,
                }
            ),
            flush=True,
        )


if __name__ == "__main__":
    main()
