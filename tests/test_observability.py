"""Observability subsystem tests: recorders, spans, JSONL schema, trace
stats, and the TrainingSession telemetry wiring (sequential + mesh layouts).
"""

import gzip
import json
import sys

import numpy as np
import pytest

from shallowspeed_tpu.observability import (
    SCHEMA_VERSION,
    JsonlMetrics,
    MetricsRecorder,
    NullMetrics,
    read_jsonl,
    span,
    trace_stats,
)

SIZES = (24, 20, 18, 16, 14, 12, 11, 10)
N, GBS = 256, 64


@pytest.fixture()
def data_dir(tmp_path):
    rng = np.random.RandomState(0)
    for suffix, n in (("train", N), ("val", 96)):
        x = rng.randn(n, SIZES[0]).astype(np.float32)
        y = np.eye(SIZES[-1], dtype=np.float32)[rng.randint(0, SIZES[-1], n)]
        np.save(tmp_path / f"x_{suffix}.npy", x)
        np.save(tmp_path / f"y_{suffix}.npy", y)
    return tmp_path


# ---------------------------------------------------------------------------
# recorders
# ---------------------------------------------------------------------------


def test_counter_gauge_histogram_math():
    m = MetricsRecorder()
    m.counter("steps")
    m.counter("steps")
    m.counter("samples", 128)
    m.counter("samples", 64)
    m.gauge("lr", 0.1)
    m.gauge("lr", 0.05)  # last value wins
    for v in (1.0, 3.0, 2.0):
        m.observe("loss", v)
    s = m.summary()
    assert s["counters"] == {"steps": 2.0, "samples": 192.0}
    assert s["gauges"] == {"lr": 0.05}
    h = s["histograms"]["loss"]
    assert h["count"] == 3 and h["min"] == 1.0 and h["max"] == 3.0
    assert abs(h["mean"] - 2.0) < 1e-12


def test_timer_records_duration():
    m = MetricsRecorder()
    with m.timer("work") as t:
        sum(range(1000))
    assert t.seconds is not None and t.seconds >= 0
    h = m.summary()["histograms"]["work.seconds"]
    assert h["count"] == 1 and h["min"] == t.seconds


def test_span_nesting_paths_and_depths():
    m = MetricsRecorder()
    with m.span("outer"):
        with m.span("inner"):
            with m.span("leaf"):
                pass
        with m.span("inner2"):
            pass
    paths = [p for p, _ in m.spans]
    # spans record on EXIT, innermost first
    assert paths == [
        "outer/inner/leaf", "outer/inner", "outer/inner2", "outer",
    ]
    # standalone spans (no recorder) still time and nest
    with span("a") as sa:
        with span("b") as sb:
            pass
    assert sa.path == "a" and sb.path == "a/b" and sb.depth == 1
    assert sa.seconds >= sb.seconds >= 0


def test_null_metrics_hot_path_zero_net_allocation():
    """The disabled recorder must cost nothing measurable: after warmup, a
    large burst of hot-path calls leaves the interpreter's allocated-block
    count unchanged (no per-call objects survive, no hidden aggregation)."""
    m = NullMetrics()

    def burst(n):
        for _ in range(n):
            m.counter("x")
            m.counter("x", 2.0)
            m.gauge("g", 1.0)
            m.observe("h", 0.5)
            with m.timer("t"):
                pass
            with m.span("s"):
                pass
            m.audit("a")  # the v3 audit hook keeps the guarantee too
            m.checkpoint("c")  # ... and the v4 fault-tolerance hooks
            m.recovery("r")
            m.request("q")  # ... and the v5 serving hooks
            m.serving("s")
            m.serving_health("b")  # ... and the v6 degradation hooks
            m.reload("r")
            m.trace("t")  # ... and the v10 tracing hook
            m.rollup("w")  # ... and the v11 live-telemetry hooks
            m.alert("a")
            m.digest("d")  # ... and the v12 numerics-provenance hook
            m.autoscale("a")  # ... and the v13 capacity hook

    burst(100)  # warm up caches (method cache, code objects)
    # background threads (XLA's pools) can allocate a handful of blocks at
    # any moment, so take the min over a few trials: a REAL per-call leak
    # (one surviving object per call) would show up as >= 30000 blocks in
    # EVERY trial, while an idle interpreter shows ~0 in at least one
    deltas = []
    for _ in range(5):
        before = sys.getallocatedblocks()
        burst(5000)
        deltas.append(abs(sys.getallocatedblocks() - before))
    assert min(deltas) <= 16, (
        f"null backend leaked {min(deltas)} blocks per 5000-call burst"
    )
    assert m.enabled is False


def test_jsonl_schema_round_trip(tmp_path):
    path = tmp_path / "m.jsonl"
    with JsonlMetrics(path) as m:
        m.counter("epochs")
        m.gauge("lr", 0.006)
        m.observe("loss", 0.5)
        with m.timer("compile"):
            pass
        with m.span("epoch"):
            pass
        m.event("epoch", epoch=0, loss=0.5, samples_per_sec=1234.5)
    # raw file: every line is valid JSON and carries the schema version
    lines = [json.loads(l) for l in path.read_text().splitlines()]
    assert all(rec["v"] == SCHEMA_VERSION for rec in lines)
    assert lines[0]["kind"] == "meta" and "schema" in lines[0]
    # reader round-trip preserves kinds and fields
    recs = read_jsonl(path)
    kinds = [r["kind"] for r in recs]
    assert kinds == ["meta", "counter", "gauge", "histogram", "timer", "span",
                     "event"]
    ev = recs[-1]
    assert ev["name"] == "epoch" and ev["loss"] == 0.5
    assert ev["samples_per_sec"] == 1234.5
    assert all("ts" in r for r in recs)


def test_read_jsonl_rejects_newer_schema(tmp_path):
    path = tmp_path / "future.jsonl"
    path.write_text(json.dumps({"v": SCHEMA_VERSION + 1, "kind": "event"}) + "\n")
    with pytest.raises(ValueError, match="newer"):
        read_jsonl(path)
    assert read_jsonl(path, strict=False)[0]["v"] == SCHEMA_VERSION + 1


def test_jsonl_survives_abandonment(tmp_path):
    """Per-record flushing: everything recorded before a kill is on disk."""
    path = tmp_path / "m.jsonl"
    m = JsonlMetrics(path)
    m.counter("a")
    # no close() — simulate the process dying here
    recs = read_jsonl(path)
    assert [r["kind"] for r in recs] == ["meta", "counter"]
    m.close()
    with pytest.raises(ValueError, match="closed"):
        m.counter("b")


# ---------------------------------------------------------------------------
# trace_stats (importable module + synthetic fixture)
# ---------------------------------------------------------------------------


def _write_synthetic_trace(path):
    """Two device ops (10us + 30us, 20us gap) + host noise + module envelope."""
    events = [
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": 2, "name": "process_name",
         "args": {"name": "python host"}},
        {"ph": "M", "pid": 1, "tid": 9, "name": "thread_name",
         "args": {"name": "XLA Modules"}},
        # the whole-module envelope: must be EXCLUDED from op stats
        {"ph": "X", "pid": 1, "tid": 9, "name": "jit_step", "ts": 0, "dur": 60},
        {"ph": "X", "pid": 1, "tid": 1, "name": "fusion.1", "ts": 0, "dur": 10},
        {"ph": "X", "pid": 1, "tid": 1, "name": "convolution.2", "ts": 30,
         "dur": 30},
        # host-side op: wrong pid, excluded
        {"ph": "X", "pid": 2, "tid": 1, "name": "hostop", "ts": 0, "dur": 999},
    ]
    with gzip.open(path, "wt") as f:
        json.dump({"traceEvents": events}, f)


def test_trace_stats_summarize_synthetic(tmp_path):
    trace = tmp_path / "x.trace.json.gz"
    _write_synthetic_trace(trace)
    s = trace_stats.summarize(trace)
    assert s["device_ops"] == 2
    assert s["span_ms"] == 0.06  # 0..60us
    assert s["busy_ms"] == 0.04  # 10 + 30
    assert s["ns_per_op_issued"] == 30000.0  # 60us / 2 ops
    assert abs(s["unit_overlap"] - 0.67) < 1e-9
    assert s["top_ops"] == {"fusion": 1, "convolution": 1}
    # an all-compute trace: the comm split exists and is zero
    assert s["comm_ops"] == 0 and s["comm_ms"] == 0.0
    assert s["compute_ms"] == 0.04 and s["comm_fraction"] == 0.0


def test_trace_stats_comm_compute_split(tmp_path):
    """Device ops split into comm vs compute by HLO-name prefix — the
    measured comm share the analytical comms model's bound verdict is
    compared against (docs/observability.md)."""
    trace = tmp_path / "comm.trace.json.gz"
    events = [
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "X", "pid": 1, "tid": 1, "name": "fusion.1", "ts": 0, "dur": 30},
        {"ph": "X", "pid": 1, "tid": 1, "name": "all-reduce.3", "ts": 30,
         "dur": 10},
        {"ph": "X", "pid": 1, "tid": 2, "name": "collective-permute-start.1",
         "ts": 40, "dur": 15},
        {"ph": "X", "pid": 1, "tid": 1, "name": "reduce-scatter.2", "ts": 55,
         "dur": 5},
    ]
    with gzip.open(trace, "wt") as f:
        json.dump({"traceEvents": events}, f)
    s = trace_stats.summarize(trace)
    assert s["comm_ops"] == 3
    assert s["comm_ms"] == 0.03  # 10 + 15 + 5 us
    assert s["compute_ms"] == 0.03
    assert s["comm_fraction"] == 0.5
    assert trace_stats.is_comm_op("all-gather-done.7")
    assert not trace_stats.is_comm_op("fusion.all")


def test_trace_stats_find_traces_and_empty(tmp_path):
    (tmp_path / "sub").mkdir()
    trace = tmp_path / "sub" / "y.trace.json.gz"
    _write_synthetic_trace(trace)
    found = trace_stats.find_traces(tmp_path)
    assert found == [trace]
    empty = tmp_path / "empty.trace.json.gz"
    with gzip.open(empty, "wt") as f:
        json.dump({"traceEvents": []}, f)
    assert trace_stats.summarize(empty) == {"trace": str(empty), "device_ops": 0}


def test_trace_stats_script_shim_reexports():
    """scripts/trace_stats.py stays a working import surface (and the
    package module is importable exactly as the acceptance criterion asks)."""
    from pathlib import Path

    scripts_dir = str(Path(__file__).resolve().parent.parent / "scripts")
    sys.path.insert(0, scripts_dir)
    try:
        import trace_stats as shim
    finally:
        sys.path.remove(scripts_dir)
    assert shim.summarize is trace_stats.summarize
    assert shim.find_traces is trace_stats.find_traces


# ---------------------------------------------------------------------------
# program stats (lowering-time pipeline telemetry)
# ---------------------------------------------------------------------------


def test_program_stats_match_lowered_tables():
    from shallowspeed_tpu import schedules as S
    from shallowspeed_tpu.parallel.lowering import (
        lower_schedule,
        program_stats,
        utilization,
    )

    prog = lower_schedule(S.GPipeSchedule, 4, 4)
    stats = program_stats(prog)
    assert stats["num_ticks"] == prog.num_ticks
    assert stats["num_stages"] == 4 and stats["num_micro_batches"] == 4
    assert stats["is_training"] is True
    # every device runs M forwards + M backwards
    assert stats["active_cells"] == 4 * 2 * 4
    assert abs(stats["utilization"] - utilization(prog)) < 1e-12
    assert abs(stats["bubble_fraction"] - (1 - utilization(prog))) < 1e-12
    # sends: stages 0..P-2 send M activations fwd, stages 1..P-1 M grads bwd
    assert stats["sends_fwd"] == 3 * 4 and stats["sends_bwd"] == 3 * 4
    assert len(stats["stage_occupancy"]) == 4
    assert all(0 < o <= 1 for o in stats["stage_occupancy"])
    # JSON-serializable as-is (the JSONL sink emits it verbatim)
    json.dumps(stats)


# ---------------------------------------------------------------------------
# trainer/executor grad-norm aux
# ---------------------------------------------------------------------------


def test_trainer_grad_norm_aux_matches_plain_epoch():
    """with_grad_norm changes ONLY the arity: params/loss stay bitwise
    identical, and the aux norm is finite and positive."""
    import jax
    import jax.numpy as jnp

    from shallowspeed_tpu import model as Mo
    from shallowspeed_tpu import trainer
    from shallowspeed_tpu.optimizer import SGD

    B, M = 32, 4
    spec = Mo.make_model_spec(SIZES, 1, B)
    rng = np.random.RandomState(3)
    X = jnp.asarray(rng.rand(2, M, B // M, SIZES[0]).astype(np.float32))
    Y = jnp.asarray(
        np.eye(SIZES[-1], dtype=np.float32)[rng.randint(0, SIZES[-1], (2, M, B // M))]
    )
    p0 = jax.tree.map(jnp.asarray, Mo.init_model(spec))
    plain = trainer.make_train_epoch(spec, SGD(0.01), clip_norm=1.0)
    aux_fn = trainer.make_train_epoch(
        spec, SGD(0.01), clip_norm=1.0, with_grad_norm=True
    )
    p1, _, loss1 = plain(jax.tree.map(jnp.copy, p0), (), X, Y)
    p2, _, loss2, aux = aux_fn(jax.tree.map(jnp.copy, p0), (), X, Y)
    assert float(loss1) == float(loss2)
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    gn = float(aux["grad_norm"])
    assert np.isfinite(gn) and gn > 0


def test_trainer_grad_norm_rejects_kernel_paths():
    from shallowspeed_tpu import model as Mo
    from shallowspeed_tpu import trainer
    from shallowspeed_tpu.optimizer import SGD

    spec = Mo.make_model_spec(SIZES, 1, 32)
    with pytest.raises(ValueError, match="VMEM"):
        trainer.make_train_epoch(
            spec, SGD(0.01), fuse_mubatches=True, megakernel=True,
            with_grad_norm=True,
        )
    with pytest.raises(ValueError, match="VMEM"):
        trainer.make_train_run(
            spec, SGD(0.01), fuse_mubatches=True, epoch_kernel=True,
            with_grad_norm=True,
        )


def test_executor_grad_norm_matches_sequential():
    """The mesh aux norm equals the sequential aux norm for the same model
    and data (same ledger, reduced over the mesh axes)."""
    import jax
    import jax.numpy as jnp

    from shallowspeed_tpu import model as Mo
    from shallowspeed_tpu import schedules as S
    from shallowspeed_tpu import trainer
    from shallowspeed_tpu.optimizer import SGD
    from shallowspeed_tpu.parallel import executor as E
    from shallowspeed_tpu.parallel import lower_schedule, make_mesh

    B, M = 32, 4
    rng = np.random.RandomState(5)
    Xb = rng.randn(B, SIZES[0]).astype(np.float32)
    Yb = np.eye(SIZES[-1], dtype=np.float32)[rng.randint(0, SIZES[-1], B)]

    spec1 = Mo.make_model_spec(SIZES, 1, B)
    p0 = jax.tree.map(jnp.asarray, Mo.init_model(spec1))
    seq = trainer.make_train_epoch(spec1, SGD(0.01), with_grad_norm=True)
    _, _, _, aux_seq = seq(
        p0, (),
        jnp.asarray(Xb.reshape(1, M, B // M, -1)),
        jnp.asarray(Yb.reshape(1, M, B // M, -1)),
    )

    mesh = make_mesh(2, 2)
    spec = Mo.make_model_spec(SIZES, 2, B)
    prog = lower_schedule(S.GPipeSchedule, M, 2)
    stacked, flags = E.init_stacked(spec, mesh)
    step = E.make_pipeline_step(
        mesh, spec, prog, B // 2 // M, SGD(0.01), with_grad_norm=True
    )
    _, _, loss, gnorm = step(
        stacked, flags, (), jnp.asarray(Xb), jnp.asarray(Yb)
    )
    np.testing.assert_allclose(
        float(gnorm), float(aux_seq["grad_norm"]), rtol=2e-4
    )

    # zero1 path computes the same norm from the scattered chunks
    from shallowspeed_tpu.optimizer import MomentumSGD

    opt_z = MomentumSGD(0.01, 0.9)
    st_z, fl_z = E.init_stacked(spec, mesh)
    oz = E.zero1_init_state(opt_z, spec, mesh)
    step_z = E.make_pipeline_step(
        mesh, spec, prog, B // 2 // M, opt_z, zero1=True, clip_norm=1.0,
        with_grad_norm=True,
    )
    _, _, _, gnorm_z = step_z(st_z, fl_z, oz, jnp.asarray(Xb), jnp.asarray(Yb))
    np.testing.assert_allclose(float(gnorm_z), float(gnorm), rtol=2e-4)


# ---------------------------------------------------------------------------
# TrainingSession end-to-end telemetry
# ---------------------------------------------------------------------------


def _epoch_events(recs):
    return [r for r in recs if r.get("kind") == "event" and r.get("name") == "epoch"]


@pytest.mark.parametrize(
    "kw", [dict(), dict(dp=2, pp=2, schedule="gpipe")], ids=["seq", "dp2pp2"]
)
def test_session_emits_per_epoch_records(data_dir, tmp_path, kw):
    """The acceptance contract: >= 1 record per epoch with epoch/loss/
    samples_per_sec plus a compile-time span record, on the single-device
    AND the dp=2,pp=2 CPU-mesh layouts."""
    from shallowspeed_tpu.api import TrainingSession

    path = tmp_path / "metrics.jsonl"
    with JsonlMetrics(path) as m:
        run = TrainingSession(
            sizes=SIZES, global_batch_size=GBS, lr=0.01, data_dir=data_dir,
            metrics=m, **kw,
        )
        for _ in range(2):
            run.train_epoch()
    recs = read_jsonl(path)
    epochs = _epoch_events(recs)
    assert len(epochs) == 2
    for i, r in enumerate(epochs):
        assert r["epoch"] == i
        assert np.isfinite(r["loss"])
        assert r["samples_per_sec"] > 0
    # the first dispatch compiles (the AOT probe can't warm the jit call
    # cache), so its record is honestly flagged and later ones are not
    assert epochs[0]["includes_compile"] is True
    assert "includes_compile" not in epochs[1]
    spans = [r for r in recs if r.get("kind") == "span"]
    assert any(s["name"] == "jit_compile" for s in spans)
    assert any(s["name"] == "train_epoch" for s in spans)
    assert any(s["name"] == "device_put" for s in spans)
    if kw:  # mesh layout: lowering span + the static program stats event
        assert any(s["name"] == "schedule_lower" for s in spans)
        progs = [r for r in recs if r.get("name") == "pipeline_program"]
        assert len(progs) == 1
        assert progs[0]["schedule"] == "gpipe" and progs[0]["num_stages"] == 2
        assert 0.0 < progs[0]["bubble_fraction"] < 1.0


def test_session_records_grad_norm_when_clipping(data_dir, tmp_path):
    from shallowspeed_tpu.api import TrainingSession

    for kw in (dict(), dict(dp=2, pp=2, schedule="gpipe")):
        path = tmp_path / "gn.jsonl"
        with JsonlMetrics(path) as m:
            run = TrainingSession(
                sizes=SIZES, global_batch_size=GBS, lr=0.01, clip_norm=1.0,
                data_dir=data_dir, metrics=m, **kw,
            )
            run.train_epoch()
        (rec,) = _epoch_events(read_jsonl(path))
        assert np.isfinite(rec["grad_norm"]) and rec["grad_norm"] > 0


def test_session_fused_run_emits_per_epoch_records(data_dir, tmp_path):
    from shallowspeed_tpu.api import TrainingSession

    path = tmp_path / "run.jsonl"
    with JsonlMetrics(path) as m:
        run = TrainingSession(
            sizes=SIZES, global_batch_size=GBS, lr=0.01, clip_norm=1.0,
            data_dir=data_dir, metrics=m,
        )
        losses, accs = run.train_run(3)
    recs = read_jsonl(path)
    epochs = _epoch_events(recs)
    assert len(epochs) == 3
    for e, r in enumerate(epochs):
        assert r["epoch"] == e and r["fused_run"] is True
        assert r["loss"] == losses[e] and r["accuracy"] == accs[e]
        assert np.isfinite(r["grad_norm"]) and r["samples_per_sec"] > 0
    assert any(
        r.get("kind") == "span" and r["name"] == "jit_compile" for r in recs
    )


def test_jsonl_stays_strict_json_under_non_finite_values(tmp_path):
    """The blow-up evidence must stay parseable: non-finite floats are
    sanitized to "NaN"/"Infinity"/"-Infinity" strings so every line is
    STRICT JSON (json.dumps's default would write bare NaN tokens exactly
    on the records the health feature exists to produce)."""
    path = tmp_path / "nan.jsonl"
    with JsonlMetrics(path) as m:
        m.step("train", step=0, epoch=0, loss=float("nan"),
               grad_norm=float("inf"), param_norm=-float("inf"))
        m.health("non_finite", epoch=0, step=0, value=float("nan"),
                 action="halt", detail="loss is nan")
        m.event("weird", nested={"a": [1.0, float("nan")]})

    def no_constants(name):  # bare NaN/Infinity tokens are a parse error
        raise ValueError(f"non-strict JSON token {name!r}")

    lines = path.read_text().splitlines()
    recs = [json.loads(l, parse_constant=no_constants) for l in lines]
    step = recs[1]
    assert step["loss"] == "NaN" and step["grad_norm"] == "Infinity"
    assert step["param_norm"] == "-Infinity"
    assert recs[2]["value"] == "NaN"
    assert recs[3]["nested"]["a"] == [1.0, "NaN"]


def test_schema_v2_and_v3_kinds(tmp_path):
    """Schema v2/v3: the step/health/xla_audit record kinds round-trip with
    the version stamp, and NullMetrics no-ops them."""
    path = tmp_path / "v3.jsonl"
    with JsonlMetrics(path) as m:
        m.step("train", step=0, epoch=0, loss=0.5, grad_norm=0.1, param_norm=9.0)
        m.health("non_finite", epoch=0, step=3, action="warn", detail="x")
        m.audit(
            "epoch_program",
            census={"all_reduce": {"count": 14, "bytes": 4096}},
            census_ok=True,
        )
    recs = read_jsonl(path)
    assert [r["kind"] for r in recs] == ["meta", "step", "health", "xla_audit"]
    assert all(r["v"] == SCHEMA_VERSION for r in recs)
    assert recs[1]["step"] == 0 and recs[1]["param_norm"] == 9.0
    assert recs[2]["name"] == "non_finite" and recs[2]["action"] == "warn"
    assert recs[3]["name"] == "epoch_program" and recs[3]["census_ok"] is True
    assert recs[3]["census"]["all_reduce"]["count"] == 14
    n = NullMetrics()
    n.step("train", loss=0.5)
    n.health("non_finite", step=1)
    n.audit("epoch_program", census_ok=True)


def test_schema_v3_reader_accepts_v1_and_v2_unchanged(tmp_path):
    """The compat contract (docs/observability.md): v3 is additive, so the
    v3 reader accepts v1 AND v2 files unchanged, the strict refusal stays
    one-directional (only records NEWER than the reader), and the new
    xla_audit kind round-trips through the non-finite-float sanitizer."""
    # v1 and v2 files, as their writers produced them
    v1 = tmp_path / "v1.jsonl"
    v1.write_text(
        json.dumps({"v": 1, "ts": 0.0, "kind": "event", "name": "epoch",
                    "epoch": 0, "loss": 0.5}) + "\n"
    )
    v2 = tmp_path / "v2.jsonl"
    v2.write_text(
        json.dumps({"v": 2, "ts": 0.0, "kind": "step", "name": "train",
                    "step": 0, "loss": 0.5}) + "\n"
        + json.dumps({"v": 2, "ts": 0.0, "kind": "health",
                      "name": "non_finite", "action": "warn"}) + "\n"
    )
    assert read_jsonl(v1)[0]["loss"] == 0.5
    assert [r["kind"] for r in read_jsonl(v2)] == ["step", "health"]
    # one-directional: only NEWER records are refused
    v4 = tmp_path / "v4.jsonl"
    v4.write_text(json.dumps({"v": SCHEMA_VERSION + 1, "kind": "event"}) + "\n")
    with pytest.raises(ValueError, match="newer"):
        read_jsonl(v4)
    # xla_audit through the sanitizer: a non-finite nested field (e.g. an
    # unknown-peak division) stays STRICT JSON
    path = tmp_path / "audit.jsonl"
    with JsonlMetrics(path) as m:
        m.audit(
            "epoch_program",
            expected={"comms_time_per_step_s": float("inf"),
                      "bytes": [1.0, float("nan")]},
            census_ok=True,
        )
    raw = [json.loads(l, parse_constant=lambda s: (_ for _ in ()).throw(
        ValueError(s))) for l in path.read_text().splitlines()]
    assert raw[1]["expected"]["comms_time_per_step_s"] == "Infinity"
    assert raw[1]["expected"]["bytes"] == [1.0, "NaN"]
    assert read_jsonl(path)[1]["census_ok"] is True


def test_schema_v4_checkpoint_and_recovery_kinds(tmp_path):
    """Schema v4 (additive): the checkpoint/recovery record kinds round-trip
    with the version stamp, the v4 reader accepts v1-v3 files unchanged
    (the refusal stays one-directional), and NullMetrics no-ops the new
    hooks."""
    path = tmp_path / "v4.jsonl"
    with JsonlMetrics(path) as m:
        m.checkpoint(
            "step", path="/tmp/ck/step-00000008.npz", epoch=1,
            step_in_epoch=0, global_step=8, bytes=4096, wall_s=0.01,
        )
        m.recovery(
            "resumed", resumed_from="/tmp/ck/step-00000008.npz", epoch=1,
            step_in_epoch=0, global_step=8,
            skipped=[{"path": "/tmp/ck/step-00000012.npz",
                      "cause": "content checksum mismatch"}],
        )
    recs = read_jsonl(path)
    assert [r["kind"] for r in recs] == ["meta", "checkpoint", "recovery"]
    assert all(r["v"] == SCHEMA_VERSION for r in recs)
    assert recs[1]["name"] == "step" and recs[1]["global_step"] == 8
    assert recs[2]["name"] == "resumed"
    assert recs[2]["skipped"][0]["cause"] == "content checksum mismatch"
    # v1-v3 files load unchanged under the v4 reader
    for v, rec in (
        (1, {"kind": "event", "name": "epoch", "epoch": 0, "loss": 0.5}),
        (2, {"kind": "step", "name": "train", "step": 0, "loss": 0.5}),
        (3, {"kind": "xla_audit", "name": "epoch_program", "census_ok": True}),
    ):
        p = tmp_path / f"old-v{v}.jsonl"
        p.write_text(json.dumps({"v": v, "ts": 0.0, **rec}) + "\n")
        assert read_jsonl(p)[0]["kind"] == rec["kind"]
    v5 = tmp_path / "v5.jsonl"
    v5.write_text(json.dumps({"v": SCHEMA_VERSION + 1, "kind": "event"}) + "\n")
    with pytest.raises(ValueError, match="newer"):
        read_jsonl(v5)
    n = NullMetrics()
    n.checkpoint("step", global_step=8)
    n.recovery("resumed", global_step=8)


def test_schema_v5_request_and_serving_kinds(tmp_path):
    """Schema v5 (additive): the request/serving record kinds round-trip
    with the version stamp AND the non-finite sanitizer, the v5 reader
    accepts v1-v4 files unchanged, a newer file is refused (the strict
    check stays one-directional), and NullMetrics no-ops the new hooks."""
    path = tmp_path / "v5.jsonl"
    with JsonlMetrics(path) as m:
        m.request(
            "ok", id=3, rows=5, slots=1, enqueue_ts=1.0, dispatch_ts=1.5,
            complete_ts=2.0, latency_s=1.0, queue_s=0.5, deadline_ms=None,
            slo_ok=True,
        )
        m.request(
            "dropped", id=4, rows=2, slots=1, enqueue_ts=2.0,
            dispatch_ts=None, complete_ts=None,
            latency_s=float("nan"),  # through the sanitizer
            queue_s=None, deadline_ms=10.0, slo_ok=False,
        )
        m.serving(
            "summary", completed=7, dropped=1, offered_rps=100.0,
            p50_latency_s=0.01, p99_latency_s=float("inf"),
            goodput_rps=88.0, padding_waste=0.25, queue_depth_max=3,
        )
    recs = read_jsonl(path)
    assert [r["kind"] for r in recs] == ["meta", "request", "request", "serving"]
    assert all(r["v"] == SCHEMA_VERSION for r in recs)
    assert recs[1]["name"] == "ok" and recs[1]["slo_ok"] is True
    assert recs[2]["name"] == "dropped" and recs[2]["latency_s"] == "NaN"
    assert recs[3]["p99_latency_s"] == "Infinity"
    assert recs[3]["goodput_rps"] == 88.0
    # every line stays STRICT JSON (no bare NaN/Infinity tokens)
    raw = [json.loads(l, parse_constant=lambda s: (_ for _ in ()).throw(
        ValueError(s))) for l in path.read_text().splitlines()]
    assert len(raw) == 4
    # v1-v4 files load unchanged under the v5 reader
    for v, rec in (
        (1, {"kind": "event", "name": "epoch", "epoch": 0, "loss": 0.5}),
        (2, {"kind": "step", "name": "train", "step": 0, "loss": 0.5}),
        (3, {"kind": "xla_audit", "name": "epoch_program", "census_ok": True}),
        (4, {"kind": "checkpoint", "name": "step", "global_step": 8}),
    ):
        p = tmp_path / f"old-v{v}.jsonl"
        p.write_text(json.dumps({"v": v, "ts": 0.0, **rec}) + "\n")
        assert read_jsonl(p)[0]["kind"] == rec["kind"]
    # one-directional refusal: a newer file fails loudly
    v6 = tmp_path / "newer.jsonl"
    v6.write_text(json.dumps({"v": SCHEMA_VERSION + 1, "kind": "event"}) + "\n")
    with pytest.raises(ValueError, match="newer"):
        read_jsonl(v6)
    n = NullMetrics()
    n.request("ok", id=0, rows=1)
    n.serving("summary", completed=1)


def test_schema_v6_serving_health_and_reload_kinds(tmp_path):
    """Schema v6 (additive): the serving_health/reload record kinds — the
    serving degradation evidence stream — round-trip with the version
    stamp AND the non-finite sanitizer, the reader accepts v1-v5 files
    unchanged, a future-versioned file is refused (the strict check stays
    one-directional), and NullMetrics no-ops the new hooks."""
    path = tmp_path / "v6.jsonl"
    with JsonlMetrics(path) as m:
        m.serving_health(
            "breaker_open", dispatch=7, consecutive_failures=3,
        )
        m.serving_health(
            "unhealthy_dispatch", dispatch=6,
            worst_value=float("nan"),  # through the sanitizer
        )
        m.reload(
            "ok", path="/tmp/ck/step-00000008.npz", step=8, reason="breaker",
            wall_s=0.01, programs_cached=3,
        )
        m.reload(
            "failed", path="/tmp/ck", reason="watch",
            error="checksum mismatch", wall_s=float("inf"),
        )
        # the v6-extended request verdicts ride the existing kind
        m.request("expired", id=1, rows=2, slots=1, attempts=0,
                  reason="deadline")
        m.request("error", id=2, rows=1, slots=1, attempts=2,
                  reason="InjectedFault: injected")
        m.request("unhealthy", id=3, rows=1, slots=1, attempts=0)
    recs = read_jsonl(path)
    assert [r["kind"] for r in recs] == [
        "meta", "serving_health", "serving_health", "reload", "reload",
        "request", "request", "request",
    ]
    assert all(r["v"] == SCHEMA_VERSION for r in recs)
    assert recs[1]["name"] == "breaker_open" and recs[1]["dispatch"] == 7
    assert recs[2]["worst_value"] == "NaN"
    assert recs[3]["name"] == "ok" and recs[3]["step"] == 8
    assert recs[4]["wall_s"] == "Infinity"
    assert [r["name"] for r in recs[5:]] == ["expired", "error", "unhealthy"]
    assert recs[6]["attempts"] == 2
    # every line stays STRICT JSON (no bare NaN/Infinity tokens)
    raw = [json.loads(l, parse_constant=lambda s: (_ for _ in ()).throw(
        ValueError(s))) for l in path.read_text().splitlines()]
    assert len(raw) == 8
    # v1-v5 files load unchanged under the v6 reader
    for v, rec in (
        (1, {"kind": "event", "name": "epoch", "epoch": 0, "loss": 0.5}),
        (2, {"kind": "step", "name": "train", "step": 0, "loss": 0.5}),
        (3, {"kind": "xla_audit", "name": "epoch_program", "census_ok": True}),
        (4, {"kind": "checkpoint", "name": "step", "global_step": 8}),
        (5, {"kind": "serving", "name": "summary", "completed": 7}),
    ):
        p = tmp_path / f"old-v{v}.jsonl"
        p.write_text(json.dumps({"v": v, "ts": 0.0, **rec}) + "\n")
        assert read_jsonl(p)[0]["kind"] == rec["kind"]
    # one-directional refusal: a future-versioned file fails loudly
    v_next = tmp_path / "vnext.jsonl"
    v_next.write_text(
        json.dumps({"v": SCHEMA_VERSION + 1, "kind": "event"}) + "\n"
    )
    with pytest.raises(ValueError, match="newer"):
        read_jsonl(v_next)
    n = NullMetrics()
    n.serving_health("breaker_open", dispatch=1)
    n.reload("ok", path="x")


def test_schema_v7_fleet_kinds(tmp_path):
    """Schema v7 (additive): the fleet/fleet_health record kinds — the
    serving fleet's evidence stream, every event tagged replica_id —
    round-trip with the version stamp, and the reader accepts v1-v6
    files unchanged. (The version pin and the one-ahead refusal live
    with the NEWEST schema's test — test_schema_v9_static_analysis —
    so a bump edits exactly one test.)"""
    path = tmp_path / "v7.jsonl"
    with JsonlMetrics(path) as m:
        m.fleet_health("replica_spawned", replica_id=0, checkpoint=None)
        m.fleet_health("replica_ready", replica_id=0, wall_s=1.5)
        m.fleet_health("replica_dead", replica_id=0, inflight=3, error=None)
        m.fleet_health("failover", replica_id=0, requeued=3, exhausted=0)
        m.fleet(
            "summary",
            completed=40, dropped=0, failovers=1, reroutes=2,
            routing={0: 21, 1: 19}, routing_skew=1.05,
            per_replica={0: {"routed": 21, "verdicts": {"ok": 21}}},
            recovery_s=0.004,
        )
    recs = read_jsonl(path)
    assert [r["kind"] for r in recs] == [
        "meta", "fleet_health", "fleet_health", "fleet_health",
        "fleet_health", "fleet",
    ]
    assert all(r["v"] == SCHEMA_VERSION for r in recs)
    assert all(
        "replica_id" in r for r in recs if r["kind"] == "fleet_health"
    )
    assert recs[4]["name"] == "failover" and recs[4]["requeued"] == 3
    assert recs[5]["routing"] == {"0": 21, "1": 19}  # JSON stringifies keys
    # v1-v6 files load unchanged under the v7 reader
    for v, rec in (
        (1, {"kind": "event", "name": "epoch", "epoch": 0, "loss": 0.5}),
        (5, {"kind": "serving", "name": "summary", "completed": 7}),
        (6, {"kind": "serving_health", "name": "breaker_open", "dispatch": 3}),
    ):
        p = tmp_path / f"old-v{v}.jsonl"
        p.write_text(json.dumps({"v": v, "ts": 0.0, **rec}) + "\n")
        assert read_jsonl(p)[0]["kind"] == rec["kind"]
    n = NullMetrics()
    n.fleet("summary", completed=1)
    n.fleet_health("replica_dead", replica_id=0)


def test_schema_v8_async_ckpt_and_aot(tmp_path):
    """Schema v8 (additive): the aot_cache kind plus the async-writer
    fields on checkpoint and verify_s on reload — round-trip with the
    version stamp, the v8 reader accepts v1-v7 files unchanged, and
    NullMetrics no-ops the new hook. (Version pin + one-ahead refusal
    live with the newest schema's test, per the bump convention.)"""
    path = tmp_path / "v8.jsonl"
    with JsonlMetrics(path) as m:
        m.aot_cache("miss", program="inference_r4", key="ab12")
        m.aot_cache(
            "store", program="inference_r4", key="ab12", wall_s=0.01,
            bytes=2048,
        )
        m.aot_cache("hit", program="inference_r4", key="ab12", wall_s=0.002)
        m.aot_cache(
            "corrupt", program="inference_r4", key="ab12",
            reason="payload sha256 mismatch — torn or bit-rotted",
        )
        m.checkpoint(
            "step", path="ck/step-00000004.npz", global_step=4, bytes=100,
            wall_s=0.001, **{"async": True}, queue_depth=1,
            verify_s=0.0005, write_s=0.002, queued_s=0.0001,
        )
        m.reload("ok", path="ck/step-00000008.npz", step=8, reason="watch",
                 wall_s=0.01, verify_s=0.004)
    recs = read_jsonl(path)
    kinds = [r["kind"] for r in recs]
    assert kinds == [
        "meta", "aot_cache", "aot_cache", "aot_cache", "aot_cache",
        "checkpoint", "reload",
    ]
    assert all(r["v"] == SCHEMA_VERSION for r in recs)
    assert [r["name"] for r in recs if r["kind"] == "aot_cache"] == [
        "miss", "store", "hit", "corrupt",
    ]
    ck = recs[5]
    assert ck["async"] is True and ck["queue_depth"] == 1
    assert ck["verify_s"] == 0.0005 and ck["write_s"] == 0.002
    assert recs[6]["verify_s"] == 0.004
    # v1-v7 files load unchanged under the v8 reader
    for v, rec in (
        (4, {"kind": "checkpoint", "name": "step", "global_step": 2}),
        (6, {"kind": "reload", "name": "ok", "path": "x"}),
        (7, {"kind": "fleet", "name": "summary", "completed": 3}),
    ):
        p = tmp_path / f"old-v{v}.jsonl"
        p.write_text(json.dumps({"v": v, "ts": 0.0, **rec}) + "\n")
        assert read_jsonl(p)[0]["kind"] == rec["kind"]
    NullMetrics().aot_cache("hit", program="x")


def test_schema_v9_static_analysis(tmp_path):
    """Schema v9 (additive): the static_analysis kind (one verdict per
    analyzed program: pass list, per-pass stats, finding count) plus the
    SCHEMA_KINDS registry — round-trip with the version stamp, the v9
    reader accepts v1-v8 files unchanged, and NullMetrics no-ops the
    hook. (Version pin + one-ahead refusal live with the newest schema's
    test — test_schema_v10_trace — per convention.)"""
    from shallowspeed_tpu.observability.metrics import SCHEMA_KINDS

    assert SCHEMA_KINDS["static_analysis"] == 9
    path = tmp_path / "v9.jsonl"
    with JsonlMetrics(path) as m:
        m.static_analysis(
            "epoch_program",
            passes=["send_recv", "deadlock", "stash"],
            findings=0,
            send_recv={"sends_fwd": 12, "sends_bwd": 12},
            stash={"stash": {"peak": 4}},
        )
        m.static_analysis(
            "inference_r2",
            passes=["send_recv", "deadlock", "stash"],
            findings=1,
            finding="tick 3 stage 1: reads fwd mailbox slot 0 which holds"
                    " no message",
        )
        m.static_analysis("lint", passes=["BLE001"], findings=0)
    recs = read_jsonl(path)
    assert [r["kind"] for r in recs] == [
        "meta", "static_analysis", "static_analysis", "static_analysis",
    ]
    assert all(r["v"] == SCHEMA_VERSION for r in recs)
    assert recs[1]["findings"] == 0 and recs[1]["send_recv"]["sends_fwd"] == 12
    assert "tick 3" in recs[2]["finding"]
    # v1-v8 files load unchanged under the current reader
    for v, rec in (
        (1, {"kind": "event", "name": "epoch", "epoch": 0, "loss": 0.5}),
        (3, {"kind": "xla_audit", "name": "epoch_program", "census": {}}),
        (8, {"kind": "aot_cache", "name": "hit", "program": "x"}),
    ):
        p = tmp_path / f"old-v{v}.jsonl"
        p.write_text(json.dumps({"v": v, "ts": 0.0, **rec}) + "\n")
        assert read_jsonl(p)[0]["kind"] == rec["kind"]
    NullMetrics().static_analysis("epoch_program", findings=0)


def test_schema_v10_trace(tmp_path):
    """Schema v10 (additive): the ``trace`` kind — one closed span per
    record with trace/span/parent ids, raw clock-domain endpoints and the
    terminal flag, plus the ``clock_offset`` alignment records — round
    trips with the version stamp (non-finite endpoint values survive the
    strict-JSON sanitizer as strings), the v10+ reader accepts v1-v9
    files unchanged, and NullMetrics no-ops the hook. (The version pin
    and one-ahead refusal moved to the v11 test — the newest-schema
    convention.)"""
    from shallowspeed_tpu.observability.metrics import SCHEMA_KINDS

    assert SCHEMA_KINDS["trace"] == 10
    path = tmp_path / "v10.jsonl"
    with JsonlMetrics(path) as m:
        m.trace(
            "worker.queue", trace_id="f-3", span_id="r0.1", parent_id="f.2",
            t0=10.5, t1=10.9, clock="worker", replica_id=0, terminal=False,
        )
        m.trace(
            "ack", trace_id="f-3", span_id="f.9", parent_id="r0.4",
            t0=11.0, t1=11.0, clock="parent", replica_id=None,
            terminal=True, verdict="ok",
        )
        m.trace(
            "clock_offset", trace_id=None, span_id=None, parent_id=None,
            t0=None, t1=None, clock="parent", replica_id=0,
            offset_s=3.0001, rtt_s=0.0004, uncertainty_s=0.0002,
        )
        # a blown-up duration must survive as STRICT JSON (the sanitizer
        # contract every schema bump re-proves on its new kind)
        m.trace(
            "dispatch", trace_id="f-4", span_id="r0.2", parent_id=None,
            t0=1.0, t1=float("nan"), clock="worker", replica_id=0,
            terminal=False,
        )
    recs = read_jsonl(path)
    assert [r["kind"] for r in recs] == ["meta"] + ["trace"] * 4
    assert all(r["v"] == SCHEMA_VERSION for r in recs)
    assert recs[1]["trace_id"] == "f-3" and recs[1]["parent_id"] == "f.2"
    assert recs[2]["terminal"] is True and recs[2]["verdict"] == "ok"
    assert recs[3]["name"] == "clock_offset" and recs[3]["offset_s"] == 3.0001
    assert recs[4]["t1"] == "NaN"  # sanitized, line stayed parseable
    # v1-v9 files load unchanged under the v10 reader
    for v, rec in (
        (1, {"kind": "event", "name": "epoch", "epoch": 0, "loss": 0.5}),
        (5, {"kind": "request", "name": "ok", "id": 1}),
        (9, {"kind": "static_analysis", "name": "lint", "findings": 0}),
    ):
        p = tmp_path / f"trace-old-v{v}.jsonl"
        p.write_text(json.dumps({"v": v, "ts": 0.0, **rec}) + "\n")
        assert read_jsonl(p)[0]["kind"] == rec["kind"]
    NullMetrics().trace("worker.queue", trace_id="x")


def test_schema_v11_rollup_alert(tmp_path):
    """Schema v11 (additive): the ``rollup`` (closed tumbling-window
    summary) and ``alert`` (firing/resolved transition) kinds round trip
    with the version stamp, the v11+ reader accepts v1-v10 files
    unchanged, and NullMetrics no-ops both new hooks. (The version pin
    and one-ahead refusal moved to the v12 test — the newest-schema
    convention.)"""
    from shallowspeed_tpu.observability.metrics import SCHEMA_KINDS

    assert SCHEMA_KINDS["rollup"] == 11
    assert SCHEMA_KINDS["alert"] == 11
    path = tmp_path / "v11.jsonl"
    with JsonlMetrics(path) as m:
        m.rollup(
            "serving", window_start=12.0, window_end=13.0, window_s=1.0,
            seq=0, counters={"ok": 41, "terminal": 42}, late=0,
            rates={"terminal": {"rate": 42.0, "ewma": 40.1}},
            gauges={"queue_depth": {"last": 3, "min": 0, "max": 7}},
            quantiles={"latency_s": {"p50": 0.004, "p99": 0.02}},
            replica_id=None,
        )
        m.alert(
            "breaker_open", rule="breaker_open", state="firing",
            severity="page", t=12.75, value="breaker_open",
            threshold=None, burn_fast=None, burn_slow=None,
            reason="health event 'breaker_open'", replica_id=0,
        )
    recs = read_jsonl(path)
    assert [r["kind"] for r in recs] == ["meta", "rollup", "alert"]
    assert all(r["v"] == SCHEMA_VERSION for r in recs)
    assert recs[1]["counters"]["terminal"] == 42
    assert recs[1]["quantiles"]["latency_s"]["p99"] == 0.02
    assert recs[2]["state"] == "firing" and recs[2]["replica_id"] == 0
    # v1-v10 files load unchanged under the v11 reader
    for v, rec in (
        (1, {"kind": "event", "name": "epoch", "epoch": 0, "loss": 0.5}),
        (5, {"kind": "request", "name": "ok", "id": 1}),
        (10, {"kind": "trace", "name": "ack", "trace_id": "f-1"}),
    ):
        p = tmp_path / f"rollup-old-v{v}.jsonl"
        p.write_text(json.dumps({"v": v, "ts": 0.0, **rec}) + "\n")
        assert read_jsonl(p)[0]["kind"] == rec["kind"]
    NullMetrics().rollup("serving", counters={})
    NullMetrics().alert("breaker_open", state="firing")


def test_schema_v12_digest(tmp_path):
    """Schema v12 (additive): the ``digest`` kind — one numerics-provenance
    row per optimizer step, with per-global-layer crc/norm lists — round
    trips with the version stamp AND the non-finite sanitizer, the v12
    reader accepts v1-v11 files unchanged, and NullMetrics no-ops the
    hook. (The version pin and one-ahead refusal moved to the v13 test —
    the newest-schema convention.)"""
    from shallowspeed_tpu.observability.metrics import SCHEMA_KINDS

    assert SCHEMA_KINDS["digest"] == 12
    path = tmp_path / "v12.jsonl"
    with JsonlMetrics(path) as m:
        m.digest(
            "train", step=7, epoch=1, layers=2,
            crc_w=[0x89BB9AF3, 1], crc_b=[0, 0xFFFFFFFF],
            pnorm_w=[3.25, 0.5], pnorm_b=[0.125, 0.0625],
            # a blown-up run's norms must survive as STRICT JSON (the
            # sanitizer contract every schema bump re-proves)
            gnorm_w=[float("nan"), 1.0], gnorm_b=[0.5, float("inf")],
        )
    recs = read_jsonl(path)
    assert [r["kind"] for r in recs] == ["meta", "digest"]
    assert all(r["v"] == SCHEMA_VERSION for r in recs)
    d = recs[1]
    assert d["step"] == 7 and d["layers"] == 2
    assert d["crc_w"] == [0x89BB9AF3, 1] and d["crc_b"][1] == 0xFFFFFFFF
    assert d["gnorm_w"][0] == "NaN" and d["gnorm_b"][1] == "Infinity"
    # v1-v11 files load unchanged under the v12 reader
    for v, rec in (
        (1, {"kind": "event", "name": "epoch", "epoch": 0, "loss": 0.5}),
        (5, {"kind": "request", "name": "ok", "id": 1}),
        (11, {"kind": "alert", "name": "breaker_open", "state": "firing"}),
    ):
        p = tmp_path / f"digest-old-v{v}.jsonl"
        p.write_text(json.dumps({"v": v, "ts": 0.0, **rec}) + "\n")
        assert read_jsonl(p)[0]["kind"] == rec["kind"]
    NullMetrics().digest("train", step=0, crc_w=[])


def test_schema_v13_autoscale(tmp_path):
    """Schema v13 (additive): the ``autoscale`` kind — one capacity
    decision with its evidence (rule, direction, fleet size before/
    after, rollup window, flap flag) — round trips with the version
    stamp, the v13 reader accepts v1-v12 files unchanged, a v14 file is
    refused, and NullMetrics no-ops the hook. Carries the version pin
    and the one-ahead refusal (the newest-schema convention)."""
    from shallowspeed_tpu.observability.metrics import SCHEMA_KINDS

    assert SCHEMA_VERSION == 13
    # the registry IS the docstring's kind list: every recorder hook has
    # a registered kind, and the newest kinds carry the newest version
    assert SCHEMA_KINDS["autoscale"] == 13
    assert max(SCHEMA_KINDS.values()) == SCHEMA_VERSION
    path = tmp_path / "v13.jsonl"
    with JsonlMetrics(path) as m:
        m.autoscale(
            "scale_out", direction="out", rule="knee_proximity", t=12.5,
            replicas_before=1, replicas_after=2, replicas_ready=1,
            queue_depth=4, window_end=12.0, value=43.7, threshold=40.5,
            flap=False, reason="admitted rate within 10% of the knee",
            leg="autoscaled",
        )
    recs = read_jsonl(path)
    assert [r["kind"] for r in recs] == ["meta", "autoscale"]
    assert all(r["v"] == SCHEMA_VERSION for r in recs)
    d = recs[1]
    assert d["name"] == "scale_out" and d["direction"] == "out"
    assert d["replicas_before"] == 1 and d["replicas_after"] == 2
    assert d["rule"] == "knee_proximity" and d["flap"] is False
    # v1-v12 files load unchanged under the v13 reader
    for v, rec in (
        (1, {"kind": "event", "name": "epoch", "epoch": 0, "loss": 0.5}),
        (5, {"kind": "request", "name": "ok", "id": 1}),
        (11, {"kind": "alert", "name": "breaker_open", "state": "firing"}),
        (12, {"kind": "digest", "name": "train", "step": 0, "crc_w": [1]}),
    ):
        p = tmp_path / f"autoscale-old-v{v}.jsonl"
        p.write_text(json.dumps({"v": v, "ts": 0.0, **rec}) + "\n")
        assert read_jsonl(p)[0]["kind"] == rec["kind"]
    # one-directional refusal: a v14 file fails loudly
    v14 = tmp_path / "v14.jsonl"
    v14.write_text(json.dumps({"v": 14, "kind": "event"}) + "\n")
    with pytest.raises(ValueError, match="newer"):
        read_jsonl(v14)
    NullMetrics().autoscale("scale_out", direction="out")


def test_replica_shard_suffix_and_fallback_read(tmp_path):
    """Fleet workers reuse the multihost shard convention as .r{id}:
    replica_shard_path names each worker's own JSONL shard, an explicit
    glob merges parent + shards, and the bare-path fallback resolves a
    missing base to its .r shards (never to look-alike neighbors)."""
    from shallowspeed_tpu.observability.metrics import replica_shard_path

    base = tmp_path / "fleet.jsonl"
    assert replica_shard_path(base, 2) == str(base) + ".r2"
    for rid in (0, 1):
        with JsonlMetrics(replica_shard_path(base, rid)) as m:
            m.request("ok", id=rid, rows=1, slots=1)
    # a look-alike neighbor must never be merged by the BARE-PATH
    # fallback (an explicit glob is the caller's own choice)
    decoy = tmp_path / "fleet.jsonl.rpartial"
    decoy.write_text("not json\n")
    # bare-path fallback: base missing -> its .r shards, sorted
    recs = read_jsonl(base)
    assert [r["id"] for r in recs if r["kind"] == "request"] == [0, 1]
    decoy.unlink()
    # parent + shards via explicit glob once the base exists too
    with JsonlMetrics(base) as m:
        m.fleet("summary", completed=2)
    recs2 = read_jsonl(str(base) + "*")
    kinds = [r["kind"] for r in recs2 if r["kind"] != "meta"]
    assert kinds.count("request") == 2 and kinds.count("fleet") == 1


def test_percentile_single_shared_definition():
    """Satellite: the ONE percentile helper equals np.percentile exactly
    (not approximately) on arbitrary data, ignores None samples, and
    returns None — never 0.0 — when nothing was measured. The engine
    summary, fleet summary and report fallback all call it, so p99 can
    no longer disagree with itself across consumers."""
    from shallowspeed_tpu.observability import percentile

    rng = np.random.RandomState(7)
    for n in (1, 2, 3, 10, 100, 101):
        vals = list(rng.exponential(0.01, size=n))
        for q in (0, 50, 90, 99, 100):
            assert percentile(vals, q) == float(
                np.percentile(np.asarray(vals, np.float64), q)
            )
    assert percentile([None, 3.0, None, 1.0], 50) == 2.0
    assert percentile([], 99) is None
    assert percentile([None, None], 99) is None


def test_throughput_window_single_shared_definition():
    """Satellite: the ONE first-enqueue -> last-complete window helper
    (the engine's and fleet's previously copy-pasted
    _first_enqueue_t/_last_complete_t bookkeeping). Min-enqueue /
    max-complete whatever the call order, None until BOTH ends exist —
    an unmeasured window must not read as an instant one — and reset
    clears it for the bench sweep's per-rate boundary."""
    from shallowspeed_tpu.observability import ThroughputWindow

    w = ThroughputWindow()
    assert w.window_s is None
    w.note_enqueue(10.0)
    assert w.window_s is None  # half a window is no window
    w.note_complete(11.5)
    assert w.window_s == 1.5
    # out-of-order notes keep the extremes (completions finish out of
    # enqueue order under continuous batching)
    w.note_enqueue(9.0)
    w.note_complete(11.0)
    assert w.window_s == 2.5
    w.reset()
    assert w.window_s is None and w.first_enqueue_t is None


def test_jsonl_multihost_shard_suffix_and_glob_read(tmp_path, monkeypatch):
    """Multihost JSONL safety: under process_count > 1 every host writes
    its own .p{index} shard (no interleaved writes into one file), and
    read_jsonl accepts a glob of shards — plus the bare-path auto-fallback
    the report CLI rides."""
    import jax

    from shallowspeed_tpu.parallel import multihost

    base = tmp_path / "multi.jsonl"
    # a live 2-process distributed runtime, as the probe sees it (the
    # compat gate first — it keeps the probe from initializing the
    # backend in single-process runs — then the public process surface)
    monkeypatch.setattr(multihost, "_distributed_is_initialized", lambda: True)
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    with JsonlMetrics(base) as m:
        assert m.path == str(base) + ".p1"
        m.event("epoch", epoch=0, loss=0.5)
    assert not base.exists()
    shard1 = tmp_path / "multi.jsonl.p1"
    assert shard1.exists()
    # a second host's shard, written independently
    shard0 = tmp_path / "multi.jsonl.p0"
    shard0.write_text(
        json.dumps({"v": SCHEMA_VERSION, "ts": 0.0, "kind": "event",
                    "name": "epoch", "epoch": 0, "loss": 0.25}) + "\n"
    )
    # explicit glob: sorted shard order, concatenated
    recs = read_jsonl(str(base) + ".p*")
    assert [r["loss"] for r in recs if r["kind"] == "event"] == [0.25, 0.5]
    # bare-path fallback: the unsharded name resolves to its shards
    recs2 = read_jsonl(base)
    assert len(recs2) == len(recs)
    # a missing glob refuses loudly
    with pytest.raises(FileNotFoundError):
        read_jsonl(str(tmp_path / "nope-*.jsonl"))


def test_shard_path_single_process_is_identity(tmp_path):
    """With one jax process (the normal case) the path is untouched."""
    from shallowspeed_tpu.observability.metrics import _shard_path

    assert _shard_path(tmp_path / "x.jsonl") == str(tmp_path / "x.jsonl")


@pytest.mark.parametrize(
    "kw", [dict(), dict(dp=2, pp=2, schedule="gpipe")], ids=["seq", "dp2pp2"]
)
def test_session_emits_step_records(data_dir, tmp_path, kw):
    """The flight recorder: one schema-v2 step record per optimizer step on
    BOTH layouts, globally numbered, with finite loss/grad/param norms, and
    the ring buffer holding the same samples."""
    from shallowspeed_tpu.api import TrainingSession

    path = tmp_path / "steps.jsonl"
    with JsonlMetrics(path) as m:
        run = TrainingSession(
            sizes=SIZES, global_batch_size=GBS, lr=0.01, data_dir=data_dir,
            metrics=m, **kw,
        )
        for _ in range(2):
            run.train_epoch()
    recs = read_jsonl(path)
    steps = [r for r in recs if r["kind"] == "step"]
    nb = run.batches_per_epoch
    assert len(steps) == 2 * nb
    assert [s["step"] for s in steps] == list(range(2 * nb))
    assert steps[nb]["epoch"] == 1
    for s in steps:
        assert np.isfinite(s["loss"])
        assert np.isfinite(s["grad_norm"]) and s["grad_norm"] > 0
        assert np.isfinite(s["param_norm"]) and s["param_norm"] > 0
    assert len(run.flight) == 2 * nb and run.flight.total_steps == 2 * nb
    assert run.flight.last(1)[0]["step"] == 2 * nb - 1


def test_record_steps_false_opts_out_of_flight_aux(data_dir, tmp_path):
    """record_steps=False keeps a metrics session at the PR1 cost profile:
    epoch events only, no step records, no per-step aux in the program."""
    from shallowspeed_tpu.api import TrainingSession

    path = tmp_path / "optout.jsonl"
    with JsonlMetrics(path) as m:
        run = TrainingSession(
            sizes=SIZES, global_batch_size=GBS, lr=0.01, data_dir=data_dir,
            metrics=m, record_steps=False,
        )
        run.train_epoch()
    assert run._step_aux is False and run.flight is None
    recs = read_jsonl(path)
    assert [r for r in recs if r["kind"] == "step"] == []
    assert len(_epoch_events(recs)) == 1
    # record_steps=True forces the flight ring on even without a recorder
    run2 = TrainingSession(
        sizes=SIZES, global_batch_size=GBS, lr=0.01, data_dir=data_dir,
        record_steps=True,
    )
    run2.train_epoch()
    assert len(run2.flight) == run2.batches_per_epoch


def test_warm_run_first_session_still_records_xla_crosscheck(data_dir, tmp_path):
    """A train_run-before-train_epoch session must not lose the XLA
    cost_analysis leg: the early (analytical-only) cost_model event is
    upgraded once the epoch program compiles (last event wins)."""
    from shallowspeed_tpu.api import TrainingSession
    from shallowspeed_tpu.observability.costmodel import compiled_flops

    path = tmp_path / "runfirst.jsonl"
    with JsonlMetrics(path) as m:
        run = TrainingSession(
            sizes=SIZES, global_batch_size=GBS, lr=0.01, data_dir=data_dir,
            metrics=m,
        )
        run.train_run(1, with_eval=False)
        run.train_epoch()
    events = [r for r in read_jsonl(path) if r.get("name") == "cost_model"]
    if compiled_flops(run._epoch_fn.lower(*run._epoch_args()).compile())[0] is None:
        pytest.skip("backend exposes no cost_analysis flops")
    assert events[0]["xla_flops_per_epoch"] is None  # pre-compile record
    assert events[-1]["xla_flops_per_epoch"] > 0  # upgraded record
    assert events[-1]["flops_ratio"] > 0


def test_step_aux_matches_epoch_mean(data_dir, tmp_path):
    """The per-step vectors are the same numbers the epoch aggregates: the
    mean of step losses IS the epoch loss record."""
    from shallowspeed_tpu.api import TrainingSession

    path = tmp_path / "agg.jsonl"
    with JsonlMetrics(path) as m:
        run = TrainingSession(
            sizes=SIZES, global_batch_size=GBS, lr=0.01, data_dir=data_dir,
            metrics=m,
        )
        loss = run.train_epoch()
    steps = [r for r in read_jsonl(path) if r["kind"] == "step"]
    np.testing.assert_allclose(
        np.mean([s["loss"] for s in steps]), loss, rtol=1e-6
    )


def test_session_emits_cost_model_and_mfu(data_dir, tmp_path):
    """MFU accounting: the cost_model event (analytical + XLA cross-check
    legs, peak provenance) and per-epoch mfu/achieved_flops gauges."""
    from shallowspeed_tpu.api import TrainingSession
    from shallowspeed_tpu.observability.costmodel import (
        mlp_train_flops_per_sample,
    )

    path = tmp_path / "mfu.jsonl"
    with JsonlMetrics(path) as m:
        run = TrainingSession(
            sizes=SIZES, global_batch_size=GBS, lr=0.01, data_dir=data_dir,
            metrics=m, dp=2, pp=2, schedule="gpipe",
        )
        run.train_epoch()
    recs = read_jsonl(path)
    (cost,) = [r for r in recs if r.get("name") == "cost_model"]
    fps = mlp_train_flops_per_sample(SIZES)
    assert cost["flops_per_sample"] == fps
    assert cost["flops_per_epoch"] == fps * GBS * run.batches_per_epoch
    assert cost["n_devices"] == 4 and cost["peak_flops_per_chip"] > 0
    assert "peak_source" in cost
    # padded pipeline FLOPs from the actual tick tables: >= logical
    assert cost["padded_ratio"] >= 1.0
    gauges = {r["name"]: r["value"] for r in recs if r["kind"] == "gauge"}
    assert gauges["model_flops"] == cost["flops_per_epoch"]
    assert gauges["achieved_flops_per_sec"] > 0
    assert 0 < gauges["mfu"] < 1.5  # a utilization, not a raw FLOP count
    (ep,) = _epoch_events(recs)
    assert np.isfinite(ep["mfu"])


def test_mesh_fused_run_reports_grad_norm(data_dir, tmp_path):
    """The satellite contract: make_pipeline_run now threads the grad-norm
    aux, so MESH fused-run epoch records carry grad_norm too (this was the
    documented gap in docs/observability.md)."""
    from shallowspeed_tpu.api import TrainingSession

    path = tmp_path / "meshrun.jsonl"
    with JsonlMetrics(path) as m:
        run = TrainingSession(
            sizes=SIZES, global_batch_size=GBS, lr=0.01, clip_norm=1.0,
            data_dir=data_dir, metrics=m, dp=2, pp=2, schedule="gpipe",
        )
        losses, accs = run.train_run(2)
    epochs = _epoch_events(read_jsonl(path))
    assert len(epochs) == 2
    for e, r in enumerate(epochs):
        assert r["fused_run"] is True and r["loss"] == losses[e]
        assert np.isfinite(r["grad_norm"]) and r["grad_norm"] > 0


def test_executor_step_stats_param_norm_matches_unstacked():
    """The mesh per-step param norm is the LOGICAL norm: padded entries are
    exactly zero, so the stacked pp-psum'd norm equals the norm of the
    unstacked parameters."""
    import jax
    import jax.numpy as jnp

    from shallowspeed_tpu import model as Mo
    from shallowspeed_tpu import schedules as S
    from shallowspeed_tpu.optimizer import SGD, global_norm
    from shallowspeed_tpu.parallel import executor as E
    from shallowspeed_tpu.parallel import lower_schedule, make_mesh

    B, M = 32, 4
    rng = np.random.RandomState(7)
    Xb = rng.randn(B, SIZES[0]).astype(np.float32)
    Yb = np.eye(SIZES[-1], dtype=np.float32)[rng.randint(0, SIZES[-1], B)]
    mesh = make_mesh(2, 2)
    spec = Mo.make_model_spec(SIZES, 2, B)
    prog = lower_schedule(S.GPipeSchedule, M, 2)
    stacked, flags = E.init_stacked(spec, mesh)
    step = E.make_pipeline_step(
        mesh, spec, prog, B // 2 // M, SGD(0.01), with_step_stats=True
    )
    new_stacked, _, loss, gnorm, pnorm = step(
        stacked, flags, (), jnp.asarray(Xb), jnp.asarray(Yb)
    )
    logical = E.unstack_params(new_stacked, spec)
    expect = float(global_norm(jax.tree.map(jnp.asarray, logical)))
    np.testing.assert_allclose(float(pnorm), expect, rtol=2e-5)
    assert np.isfinite(float(gnorm)) and float(gnorm) > 0


def test_session_metrics_do_not_change_training(data_dir, tmp_path):
    """Telemetry is observation only: the recorded run trains to the exact
    same weights as the unrecorded one."""
    from shallowspeed_tpu.api import TrainingSession

    plain = TrainingSession(
        sizes=SIZES, global_batch_size=GBS, lr=0.01, data_dir=data_dir
    )
    with JsonlMetrics(tmp_path / "p.jsonl") as m:
        recorded = TrainingSession(
            sizes=SIZES, global_batch_size=GBS, lr=0.01, data_dir=data_dir,
            metrics=m,
        )
        l1 = [plain.train_epoch() for _ in range(2)]
        l2 = [recorded.train_epoch() for _ in range(2)]
    assert l1 == l2
    assert plain.model_hash() == recorded.model_hash()
