"""Analytical cost model + XLA cost_analysis cross-check + MFU accounting.

"As fast as the hardware allows" is a ratio, and this module owns both of
its legs:

- the NUMERATOR is model FLOPs — the analytical count of useful training
  arithmetic (fwd ``2P`` + bwd ``4P`` per sample for ``P = sum(in*out)``,
  the standard MLP ledger; ``bench.flops_per_sample`` delegates here so the
  benchmark and the telemetry can never disagree on it). For pipeline
  layouts the PADDED hardware FLOPs (what the stacked-slot executor
  actually multiplies, computed from the lowered tick tables) are tracked
  alongside, so the padding tax is a recorded number, not folklore;
- the XLA leg: ``Compiled.cost_analysis()`` FLOPs/bytes pulled from the
  jit-compiled epoch program. The analytical count is CROSS-CHECKED against
  it (``flops_ratio``) — if the two diverge wildly, either the analytical
  model or the lowering regressed, and a consumer can see which epoch
  program to distrust;
- the DENOMINATOR is peak FLOP/s: per-chip datasheet numbers for the TPU
  precision classes (matching bench.py's physical-plausibility ceilings), a
  clearly-labeled NOMINAL figure for host CPU (there is no single honest
  CPU peak; the source tag says so), or the ``SHALLOWSPEED_PEAK_FLOPS`` env
  override for any other hardware. Every MFU record carries the peak AND
  its source, so a number computed against the nominal CPU default cannot
  be misread as a datasheet MFU.

``MFU = samples_per_sec * model_flops_per_sample / (peak_per_chip * chips)``
— model FLOPs in the numerator (the Chowdhery et al. PaLM convention), so
padding and recomputation make MFU WORSE, never better.
"""

import os

# Per-chip peak model FLOP/s by (platform, matmul-precision class). The TPU
# rows are the same v5e-class ceilings bench.py's plausibility guard uses
# (fp32-accumulate fp32-input ~100 TF/s, bf16-input MXU passes ~200 TF/s).
# The CPU row is a NOMINAL single-socket figure (order 100 GFLOP/s fp32) —
# labeled as such in the source tag; override with SHALLOWSPEED_PEAK_FLOPS.
PEAK_FLOPS_PER_CHIP = {
    ("tpu", "highest"): 100e12,
    ("tpu", "default"): 200e12,
    ("cpu", "highest"): 2e11,
    ("cpu", "default"): 2e11,
}

ENV_PEAK = "SHALLOWSPEED_PEAK_FLOPS"

# Relative per-tick FLOP weights of the pipeline executor's compute ops, in
# units of one forward's matmul work (2P per microbatch, P = the padded
# per-slot weight count — every op runs the same slot stack, so the RATIOS
# are exact regardless of stage): a combined backward is dgrad 2P + wgrad 2P
# = 2 forwards; the split halves are one forward each. This is the single
# source for ``lowering.weighted_utilization`` / ``weighted_makespan`` —
# the metric that can see the split-backward win (equal-weight utilization
# counts a 4P backward cell and a 2P forward cell the same, so it scores a
# schedule that splits backwards WORSE while the lockstep step time drops).
PIPELINE_OP_COSTS = {
    "fwd": 1.0, "bwd": 2.0, "bwd_in": 1.0, "bwd_w": 1.0,
    # an OP_RECOMPUTE cell re-runs a full stage forward (torchgpipe
    # trade): same 2P matmul work as a forward tick
    "recompute": 1.0,
}


def mlp_train_flops_per_sample(sizes):
    """Analytical training FLOPs per sample: fwd 2P + bwd 4P (dgrad 2P +
    wgrad 2P) for P = sum(in*out) — bias adds, relu and the softmax head
    are O(width) noise against the O(width^2) matmuls and are not counted.
    The single source of truth (bench.flops_per_sample delegates here)."""
    sizes = tuple(sizes)
    return 6 * sum(sizes[i] * sizes[i + 1] for i in range(len(sizes) - 1))


def peak_flops_per_chip(platform, precision="highest"):
    """-> ``(peak_flops, source)`` for one chip; ``(None, source)`` when the
    platform is unknown. ``platform`` accepts jax device platform strings
    ('tpu', 'axon' — the tunnel's TPU — or 'cpu')."""
    env = os.environ.get(ENV_PEAK)
    if env:
        return float(env), f"env:{ENV_PEAK}"
    plat = "tpu" if platform in ("tpu", "axon") else platform
    key = (plat, precision)
    if key not in PEAK_FLOPS_PER_CHIP:
        return None, f"unknown-platform:{platform}"
    source = "datasheet-v5e" if plat == "tpu" else "nominal-cpu-default"
    return PEAK_FLOPS_PER_CHIP[key], source


def serving_latency_bound(
    prog, spec, slot_rows, dp=1, platform="cpu", precision="highest", tp=1
):
    """Analytical latency floor for ONE request slot through the layout's
    inference program — the model-side number the serving bench and report
    quote next to the MEASURED p50/p99 (docs/serving.md).

    Mesh layouts (``prog`` = the single-slot lowered inference program):
    under the executor's lockstep tick model a dispatch takes
    ``weighted_makespan(prog)`` forward-units of work (for a forward-only
    program that is exactly its tick count x ``PIPELINE_OP_COSTS['fwd']``),
    and one forward-unit is ``2 * (slot_rows/dp) * padded_P`` FLOPs over
    the PADDED slot stack (``lowering.program_flops``'s per-cell ledger).
    Sequential (``prog=None``): one slot's logical forward,
    ``2 * P * slot_rows`` FLOPs. Divided by the platform's peak
    (``peak_flops_per_chip``) — a lower bound: dispatch overhead, relay
    bandwidth and queueing all sit on top of it, which is the point of
    printing it under the measured percentiles.

    Returns ``{"ticks", "weighted_ticks", "flops", "seconds",
    "peak_flops_per_chip", "peak_source"}`` (``seconds`` None when the
    platform peak is unknown; ``ticks`` None on the sequential path).
    """
    peak, source = peak_flops_per_chip(platform, precision)
    if prog is None:
        flops = 2 * sum(
            spec.sizes[i] * spec.sizes[i + 1] for i in range(len(spec.sizes) - 1)
        ) * slot_rows
        ticks = weighted = None
    else:
        from shallowspeed_tpu.parallel.executor import slot_shapes
        from shallowspeed_tpu.parallel.lowering import weighted_makespan

        # per-DEVICE floor: the Megatron shards split every slot matmul,
        # so a tp rank executes 1/tp of the (tp-rounded) padded stack
        padded_p = sum(o * i for o, i in slot_shapes(spec, tp)) // max(tp, 1)
        weighted = weighted_makespan(prog)  # forward-units (fwd weight 1.0)
        ticks = int(prog.num_ticks)
        flops = weighted * 2 * (slot_rows // dp) * padded_p
    return {
        "ticks": ticks,
        "weighted_ticks": None if prog is None else float(weighted),
        "flops": float(flops),
        "seconds": (flops / peak) if peak else None,
        "peak_flops_per_chip": peak,
        "peak_source": source,
    }


def compiled_flops(compiled):
    """Pull ``(flops, bytes_accessed)`` from a jax ``Compiled``'s
    ``cost_analysis()`` across jax versions (dict in newer jax, a one-dict
    list in 0.4.x; either field may be absent — e.g. some backends report
    no bytes). Returns ``(None, None)`` when the backend offers nothing:
    cost analysis is a cross-check, never a hard dependency."""
    try:
        ca = compiled.cost_analysis()
    except Exception:  # noqa: BLE001 — backend-optional surface
        return None, None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None, None

    def _get(key):
        v = ca.get(key)
        try:
            v = float(v)
        except (TypeError, ValueError):
            return None
        return v if v > 0 else None

    return _get("flops"), _get("bytes accessed")


class CostModel:
    """One session's FLOP ledger: analytical model FLOPs, optional padded
    pipeline FLOPs, the XLA-compiled cross-check, and the MFU peak."""

    def __init__(
        self,
        sizes,
        global_batch,
        batches_per_epoch,
        n_devices=1,
        platform="cpu",
        precision="highest",
        padded_flops_per_batch=None,
    ):
        self.sizes = tuple(sizes)
        self.global_batch = int(global_batch)
        self.batches_per_epoch = int(batches_per_epoch)
        self.n_devices = int(n_devices)
        self.platform = platform
        self.precision = precision
        self.flops_per_sample = mlp_train_flops_per_sample(sizes)
        self.flops_per_batch = self.flops_per_sample * self.global_batch
        self.flops_per_epoch = self.flops_per_batch * self.batches_per_epoch
        # hardware work actually dispatched per batch on padded-stack
        # layouts (lowering.program_flops x dp); None on the sequential path
        # where logical == padded
        self.padded_flops_per_batch = (
            None if padded_flops_per_batch is None else float(padded_flops_per_batch)
        )
        self.peak_flops_per_chip, self.peak_source = peak_flops_per_chip(
            platform, precision
        )
        self.xla_flops_per_epoch = None
        self.xla_bytes_per_epoch = None

    def attach_compiled(self, compiled):
        """Record the compiled epoch program's cost_analysis numbers;
        returns True when the backend reported FLOPs."""
        flops, nbytes = compiled_flops(compiled)
        if flops is not None:
            self.xla_flops_per_epoch = flops
        if nbytes is not None:
            self.xla_bytes_per_epoch = nbytes
        return flops is not None

    @property
    def flops_ratio(self):
        """XLA-reported / analytical epoch FLOPs (the cross-check); None
        until a compiled program has been attached. This is a STRUCTURAL
        cross-check, not an equality: XLA's cost analysis counts each
        ``lax.scan`` body once regardless of trip count (observed on the
        CPU and TPU backends), so a whole-epoch program's ratio lands
        around ``1 / (batches x microbatches)``, padded pipeline layouts
        land higher by the padding tax, and a sudden order-of-magnitude
        MOVE of the ratio for the same layout is what flags a lowering or
        analytical-model regression. Recorded, never asserted blindly."""
        if self.xla_flops_per_epoch is None or self.flops_per_epoch <= 0:
            return None
        return self.xla_flops_per_epoch / self.flops_per_epoch

    @property
    def padded_ratio(self):
        """Padded / logical FLOPs per batch (the pipeline padding tax)."""
        if self.padded_flops_per_batch is None or self.flops_per_batch <= 0:
            return None
        return self.padded_flops_per_batch / self.flops_per_batch

    def achieved_flops_per_sec(self, samples_per_sec):
        """Model-FLOP throughput at an observed samples/s."""
        return samples_per_sec * self.flops_per_sample

    def mfu(self, samples_per_sec):
        """Model FLOP utilization against the layout's total peak (peak per
        chip x participating devices); None when no peak is known."""
        if not self.peak_flops_per_chip or samples_per_sec is None:
            return None
        total_peak = self.peak_flops_per_chip * max(1, self.n_devices)
        return self.achieved_flops_per_sec(samples_per_sec) / total_peak

    def as_record(self):
        """JSON-able snapshot — the ``cost_model`` event's field set."""
        rec = {
            "flops_per_sample": self.flops_per_sample,
            "flops_per_batch": self.flops_per_batch,
            "flops_per_epoch": self.flops_per_epoch,
            "batches_per_epoch": self.batches_per_epoch,
            "global_batch": self.global_batch,
            "n_devices": self.n_devices,
            "platform": self.platform,
            "precision": self.precision,
            "peak_flops_per_chip": self.peak_flops_per_chip,
            "peak_source": self.peak_source,
            "xla_flops_per_epoch": self.xla_flops_per_epoch,
            "xla_bytes_per_epoch": self.xla_bytes_per_epoch,
            "flops_ratio": self.flops_ratio,
        }
        if self.padded_flops_per_batch is not None:
            rec["padded_flops_per_batch"] = self.padded_flops_per_batch
            rec["padded_ratio"] = self.padded_ratio
        return rec
